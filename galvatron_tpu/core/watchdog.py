"""Hang watchdog: a heartbeat deadline around each training step.

The preemption signature `core/signals.py` documents — a device vanishing
mid-collective — does not crash the survivors: their next collective simply
never completes, and the job burns pod-hours in silence (MegaScale NSDI '24
§5 reports stalled collectives as the dominant *undetected* failure mode).
This module converts that silence into a supervised restart:

- :class:`HangWatchdog` — a daemon thread armed around each train step
  (``arm(step)`` / ``disarm()``) with a ``--step_timeout_s`` deadline. A
  step that outlives its deadline fires ``on_hang(step)`` exactly once
  (all-thread stack dump + flight-recorder dump + best-effort emergency
  save, wired by the trainer) and then hard-exits with :data:`EXIT_HANG`,
  so the supervisor (`core/elastic.py`) restarts instead of waiting forever.
- :class:`StateHolder` — the last *bound* train state (post-rebind, pre-
  donation). The train step donates its input buffers, so an emergency save
  from the watchdog thread is only legal while the holder is marked valid;
  the trainer invalidates it across each donating dispatch. On a real
  stalled collective the held buffers may be unreachable anyway — the save
  is best-effort by contract, and the last committed interval checkpoint
  remains the floor.
- :func:`dump_all_stacks` — every thread's Python stack, for the flight
  dump and stderr (the "where was everyone when the collective stalled"
  forensic the operator otherwise reconstructs by hand).

The first armed step of a process gets its deadline scaled by
``warmup_scale`` (default 10x): it carries XLA compilation, and declaring a
compile a hang would turn every cold start into a crash loop.

:class:`HeartbeatMonitor` is the *supervisor-side* complement: the child
touches a heartbeat file every step (``GALVATRON_HEARTBEAT_FILE``), and the
elastic supervisor's spawn loop polls its mtime. A child so wedged that its
own in-process watchdog cannot run (interpreter deadlock, a stuck runtime
call before the watchdog arms, ``--step_timeout_s`` unset) stops
heartbeating, and the supervisor kills + restarts it — the last line of
defense against "a wedged child hangs the run forever".
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, Optional

#: child exit code the supervisor maps to "watchdog-declared hang"
#: (the full contract lives in core/elastic.py)
EXIT_HANG = 77

#: child-side env var naming the heartbeat file the supervisor watches
#: (set by core/elastic.py under --heartbeat_timeout_s; the trainer beats
#: it once per step — see beat_heartbeat)
HEARTBEAT_ENV = "GALVATRON_HEARTBEAT_FILE"


def beat_heartbeat(path: str, step: int) -> None:
    """One heartbeat: rewrite ``path`` with the current step (atomic
    replace — the monitor reads mtime, a reader of the content never sees
    a torn write). Best-effort: a heartbeat I/O error must never take down
    the step that was proving its liveness."""
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"{int(step)} {time.time()}\n")
        os.replace(tmp, path)
    except OSError:
        pass


class HeartbeatMonitor:
    """Supervisor-side staleness check over a child's heartbeat file.

    ``fresh_within(timeout_s)`` answers "has the child beaten within the
    last ``timeout_s`` seconds?". Before the FIRST beat ever lands the
    child is compiling/bootstrapping, so staleness is measured against
    ``started_at`` with ``first_beat_grace_s`` (compile-length) instead of
    ``timeout_s`` — the same blind-first-step reasoning as
    :class:`HangWatchdog`'s ``warmup_scale``, at the process level."""

    def __init__(self, path: str, first_beat_grace_s: float):
        self.path = path
        self.first_beat_grace_s = float(first_beat_grace_s)
        self.started_at = time.monotonic()

    def last_beat_age_s(self) -> Optional[float]:
        """Seconds since the last beat, or None when no beat exists yet."""
        try:
            mtime = os.stat(self.path).st_mtime
        except OSError:
            return None
        return max(0.0, time.time() - mtime)

    def stale(self, timeout_s: float) -> bool:
        """True when the child must be presumed wedged: no beat for
        ``timeout_s`` seconds (or no first beat within the grace)."""
        age = self.last_beat_age_s()
        if age is None:
            return time.monotonic() - self.started_at > self.first_beat_grace_s
        return age > float(timeout_s)


def dump_all_stacks() -> str:
    """Format the Python stack of every live thread (watchdog thread
    included — its own frames are the cheapest proof the dump worked)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    parts = []
    for tid, frame in sys._current_frames().items():
        parts.append(
            f"--- thread {names.get(tid, '?')} (ident {tid}) ---\n"
            + "".join(traceback.format_stack(frame))
        )
    try:
        # with GALVATRON_LOCK_CHECK=1 armed, say which thread holds which
        # named lock — the stacks show WHERE threads are blocked, this shows
        # WHY (the other half of every deadlock forensic)
        from galvatron_tpu.analysis.locks import held_snapshot, lock_check_armed

        if lock_check_armed():
            held = held_snapshot()
            if held:
                parts.append("--- held locks ---\n" + "\n".join(
                    f"{tname}: {', '.join(locks)}"
                    for tname, locks in sorted(held.items())
                ))
    except Exception:
        pass
    return "\n".join(parts)


class StateHolder:
    """Thread-safe holder of the last bound (non-donated) train state.

    The trainer calls ``set`` after each completed iteration's rebind and
    ``invalidate`` immediately before the next donating ``train_step``
    dispatch; the watchdog's emergency save reads ``snapshot`` and gets
    ``None`` whenever saving would touch donated buffers."""

    def __init__(self):
        self._lock = threading.Lock()
        self._state: Any = None
        self._meta: Dict[str, Any] = {}
        self._valid = False

    def set(self, state: Any, **meta: Any) -> None:
        with self._lock:
            self._state = state
            self._meta = dict(meta)
            self._valid = True

    def invalidate(self) -> None:
        with self._lock:
            self._valid = False

    def snapshot(self) -> Optional[Dict[str, Any]]:
        """``{"state": ..., **meta}`` while valid, else None."""
        with self._lock:
            if not self._valid or self._state is None:
                return None
            return {"state": self._state, **self._meta}


class HangWatchdog:
    """Deadline thread: ``arm(step)`` starts a countdown, ``disarm()``
    cancels it; an expired countdown fires ``on_hang(step)`` once and then
    ``os._exit(exit_code)`` (``exit_code=None`` skips the exit — unit
    tests observe the firing without killing the interpreter).

    ``on_hang`` failures are printed, never raised, and never prevent the
    exit: a broken forensics path must not leave the process hanging —
    that is the exact failure this class exists to end."""

    def __init__(
        self,
        timeout_s: float,
        on_hang: Callable[[int], None],
        exit_code: Optional[int] = EXIT_HANG,
        warmup_scale: float = 10.0,
        first_step_scale: Optional[float] = None,
        poll_s: Optional[float] = None,
    ):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.on_hang = on_hang
        self.exit_code = exit_code
        self.warmup_scale = max(1.0, float(warmup_scale))
        # the first-step grace exists purely for XLA compilation; a warm
        # compile cache (the trainer's AOT warmup reported a hit, or the
        # elastic re-plan prewarmed the new plan's programs) means the first
        # step pays a cache deserialize, not a compile — pass
        # ``first_step_scale=1.0`` so a REAL first-step hang after a
        # prewarmed restart is detected in seconds, not 10x step-timeout.
        # None keeps the blind compile-length default.
        self.first_step_scale = (
            self.warmup_scale if first_step_scale is None
            else max(1.0, float(first_step_scale))
        )
        self.fired = False
        self._armed_before = False
        self._lock = threading.Lock()
        self._deadline: Optional[float] = None
        self._step: int = -1
        self._stop = threading.Event()
        self._poll_s = poll_s if poll_s else max(0.02, min(0.5, timeout_s / 4))
        self._thread = threading.Thread(
            target=self._run, name="hang-watchdog", daemon=True
        )
        self._thread.start()

    def arm(self, step: int, warmup: bool = False) -> None:
        """Start the countdown for ``step``. ``warmup=True`` applies the
        compile-length deadline to THIS step too — the trainer passes it on
        any step it knows will recompile (a rampup batch-size transition),
        not just the process's first step; a 1x deadline there would
        declare a healthy recompile a hang."""
        if warmup:
            # a step the trainer KNOWS will recompile (rampup transition)
            # always gets the compile-length deadline — the warm-cache hint
            # only covers the programs the startup warmup proved warm
            scale = self.warmup_scale
        elif not self._armed_before:
            scale = self.first_step_scale
        else:
            scale = 1.0
        self._armed_before = True
        with self._lock:
            self._step = int(step)
            self._deadline = time.monotonic() + self.timeout_s * scale

    def disarm(self) -> None:
        with self._lock:
            self._deadline = None

    @property
    def armed(self) -> bool:
        with self._lock:
            return self._deadline is not None

    def close(self) -> None:
        """Stop the thread (trainer teardown — also disarms, so a slow exit
        checkpoint cannot be declared a hang)."""
        self.disarm()
        self._stop.set()
        self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self._poll_s):
            with self._lock:
                deadline, step = self._deadline, self._step
            if deadline is None or time.monotonic() < deadline:
                continue
            self.fired = True
            try:
                self.on_hang(step)
            except Exception as e:  # noqa: BLE001 — forensics must not block the exit
                print(f"watchdog on_hang failed: {e!r}", file=sys.stderr, flush=True)
            if self.exit_code is not None:
                os._exit(self.exit_code)
            return  # exit_code None (tests): fire once, then stand down
