"""Fault-injection harness for the resilience layer.

Production TPU training is dominated by preemptions and transient storage
faults (Varuna/Bamboo treat recovery as a first-class subsystem); this module
makes those failure modes *reproducible* so tests can prove the recovery
paths end-to-end instead of trusting them. All hooks are no-ops unless armed,
either programmatically (``configure(...)`` — what the tests use) or via the
``GALVATRON_FAULTS`` environment variable (what a chaos job on a real pod
uses), e.g.::

    GALVATRON_FAULTS="kill_mid_save=1,fail_io=3,nan_at_step=5,nan_count=2"

Supported faults:

- ``kill_mid_save=N``    — the next N checkpoint saves crash after the data
                           write but before the manifest/commit rename, so
                           the staging dir is left uncommitted (the
                           preemption-mid-save scenario).
- ``corrupt_leaf=N``     — after the next N saves commit, flip bytes in the
                           middle of the largest array file of the committed
                           step (the transient-storage-corruption scenario).
- ``fail_io=N``          — the next N retry-protected I/O operations raise
                           ``OSError`` (consumed per *attempt*, so a retry
                           loop with enough budget rides through).
- ``nan_at_step=K`` (+ ``nan_count=N``, default 1) — the observed loss at
  training steps K..K+N-1 is forced to NaN (the silent-divergence scenario).
- ``preempt_at_step=N``  — SIGTERM is delivered to the process itself
  mid-step at global batch index N (the maintenance-event/preemption
  scenario: the graceful handler latches it, the trainer checkpoints and
  exits, and the elastic supervisor sees ``EXIT_PREEMPTED``).
- ``hang_at_step=N`` (+ ``hang_s=S``, default 300) — the step at global
  batch index N sleeps S seconds before dispatch (the stalled-collective
  scenario ``core/signals.py`` documents), tripping ``--step_timeout_s``'s
  hang watchdog.
- ``kill_host_mid_step=N`` — SIGKILL to the process itself mid-step at
  global batch index N (once): the host-loss scenario. Nothing runs after
  it — no emergency save, no graceful exit — so recovery must come from a
  committed checkpoint or the in-memory peer replica
  (``core/peer_store.py``).
- ``preempt_with_grace=N`` — at global batch index N, write the
  preemption *notice file* (``GALVATRON_PREEMPT_NOTICE`` /
  ``--preempt_notice_file``) instead of a signal — the metadata-server
  eviction-notice scenario; the trainer's PreemptionListener must drain
  (expedited replicated save) within ``--preempt_grace_s`` and exit
  preempted.
- ``storage_outage=N`` — the next N checkpoint *save operations* fail
  wholesale with ``OSError`` (consumed per save, not per attempt — the
  outage outlasts any retry budget). With peer replication armed the
  trainer degrades to the RAM replica and keeps training; without it the
  save failure surfaces.

Serving faults (the serving chaos harness — injected at the engine's
iteration seam, so recovery exercises exactly the crash-supervision /
cancellation machinery a real fault would):

- ``engine_crash_at_iter=N`` — the engine's decode iteration N raises
  (once); the in-process ``EngineSupervisor`` must fail in-flight requests
  fast, reset the KV cache, warm-rebuild, and keep serving.
- ``prefill_fail_at=N``      — prefill chunk N raises (once); only that
  one request fails, its slot frees.
- ``slow_decode_ms=K``       — every decode iteration sleeps K ms (the
  degraded-chip scenario: TTL expiry and drain deadlines under load).
- ``client_stall=N``         — the server's disconnect poll treats the next
  N connections as vanished clients (the dead-client slot-leak scenario:
  cancellation must free the slot mid-decode).
- ``kill_replica_at_dispatch=N`` — consumed by the fleet router
  (`serving/fleet.py`): the replica chosen for dispatch N is SIGKILLed
  shortly after the request is forwarded (once) — the
  replica-dies-mid-flight scenario the failover path must absorb.

The hooks are called from the real code paths (checkpoint save/commit, the
retry wrapper, the trainer's loss observation and step loop), so an
injected fault exercises exactly the machinery a real one would.

Topology simulation: the separate ``GALVATRON_FAULTS_WORLD`` env var (a
comma list of device counts, e.g. ``"8,4"``) is read by the elastic
supervisor (`core/elastic.py`), which gives its k-th child a virtual CPU
platform of that width — a preemption that shrinks the world from 8 to 4
devices across a restart becomes reproducible on any host.
"""

from __future__ import annotations

import os
import signal as _signal
import time as _time
from typing import Dict, List, Optional

ENV_VAR = "GALVATRON_FAULTS"
WORLD_ENV_VAR = "GALVATRON_FAULTS_WORLD"

_active: Dict[str, int] = {}


class FaultInjected(RuntimeError):
    """Raised by an armed crash hook (simulated preemption/kill)."""


def configure(**faults: int) -> None:
    """Arm faults programmatically (merges into the active set)."""
    for k, v in faults.items():
        _active[k] = int(v)


def reset() -> None:
    _active.clear()


def active() -> Dict[str, int]:
    return dict(_active)


def init_from_env(env: Optional[str] = None) -> None:
    """Parse ``GALVATRON_FAULTS`` (comma-separated key=int pairs)."""
    spec = env if env is not None else os.environ.get(ENV_VAR, "")
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        try:
            _active[key.strip()] = int(val) if val else 1
        except ValueError:
            raise ValueError(
                f"{ENV_VAR}: expected key=int pairs, got {part!r}"
            ) from None


def _consume(key: str) -> bool:
    n = _active.get(key, 0)
    if n > 0:
        _active[key] = n - 1
        return True
    return False


def crash(point: str) -> None:
    """Simulated kill at a named crash point (e.g. ``mid_save``)."""
    if _consume(f"kill_{point}"):
        raise FaultInjected(f"injected crash at {point}")


def maybe_fail_io(site: str = "") -> None:
    """Injected transient I/O failure (consumed by retry loops)."""
    if _consume("fail_io"):
        raise OSError(f"injected transient I/O failure ({site or 'io'})")


def force_nan(step: int) -> bool:
    """True when the observed loss at ``step`` should be forced to NaN."""
    k = _active.get("nan_at_step")
    if k is None:
        return False
    return k <= step < k + _active.get("nan_count", 1)


def maybe_preempt(step: int) -> None:
    """Armed ``preempt_at_step=N``: deliver SIGTERM to this process at batch
    index N — once. Sent mid-step (after the batch fetch, before the
    update), exactly the window a real maintenance event lands in; the
    trainer's :class:`~galvatron_tpu.core.signals.GracefulExitHandler`
    latches it and the loop checkpoints-then-exits at the next boundary."""
    k = _active.get("preempt_at_step")
    if k is not None and step == int(k):
        del _active["preempt_at_step"]
        os.kill(os.getpid(), _signal.SIGTERM)


def maybe_kill_host(step: int) -> None:
    """Armed ``kill_host_mid_step=N``: SIGKILL this process at batch index
    N — once. Unlike :func:`maybe_preempt` nothing downstream runs: the
    kernel reaps the process before any handler, exactly what a host loss
    looks like to the survivors. Delivered mid-step (after the batch
    fetch, before the update), the worst window: the batch is fetched but
    its work is lost."""
    k = _active.get("kill_host_mid_step")
    if k is not None and step == int(k):
        del _active["kill_host_mid_step"]
        os.kill(os.getpid(), _signal.SIGKILL)


def maybe_preempt_notice(step: int, notice_file: Optional[str] = None) -> None:
    """Armed ``preempt_with_grace=N``: at batch index N, create the
    preemption notice file — once. The path comes from the argument or
    ``GALVATRON_PREEMPT_NOTICE``; unarmed or pathless, a no-op."""
    k = _active.get("preempt_with_grace")
    if k is None or step != int(k):
        return
    path = notice_file or os.environ.get("GALVATRON_PREEMPT_NOTICE")
    if not path:
        return
    del _active["preempt_with_grace"]
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(f"preempt notice injected at step {step}\n")
    os.replace(tmp, path)


def storage_outage_gate() -> None:
    """Armed ``storage_outage=N``: the next N checkpoint saves fail with
    ``OSError`` at the top of the save path — one consume per SAVE (not
    per retry attempt, unlike ``fail_io``), so the outage outlasts the
    retry budget and the caller's degraded path is what gets proven."""
    if _consume("storage_outage"):
        raise OSError("injected storage outage (checkpoint save)")


def maybe_hang(step: int) -> None:
    """Armed ``hang_at_step=N``: sleep ``hang_s`` seconds inside the step at
    batch index N — once. Simulates the stalled collective of a half-dead
    pod; the hang watchdog (``--step_timeout_s``) must convert it into a
    flight dump + emergency save + hang-coded exit."""
    k = _active.get("hang_at_step")
    if k is not None and step == int(k):
        del _active["hang_at_step"]
        _time.sleep(_active.get("hang_s", 300))


def engine_iteration(step: int) -> None:
    """Serving-engine iteration seam. ``engine_crash_at_iter=N``: decode
    iteration N raises :class:`FaultInjected` — once, so the supervised
    restart proves recovery, not a crash loop. ``slow_decode_ms=K``: every
    iteration sleeps K ms (degraded-chip simulation)."""
    k = _active.get("engine_crash_at_iter")
    if k is not None and step == int(k):
        del _active["engine_crash_at_iter"]
        raise FaultInjected(f"injected engine crash at decode iteration {step}")
    ms = _active.get("slow_decode_ms", 0)
    if ms:
        _time.sleep(ms / 1000.0)


def prefill_chunk(idx: int) -> None:
    """Armed ``prefill_fail_at=N``: the engine's N-th prefill chunk raises
    (once) — one request fails, the engine and its other slots live on."""
    k = _active.get("prefill_fail_at")
    if k is not None and idx == int(k):
        del _active["prefill_fail_at"]
        raise FaultInjected(f"injected prefill failure at chunk {idx}")


def kill_replica(dispatch_idx: int) -> bool:
    """Armed ``kill_replica_at_dispatch=N``: the fleet router SIGKILLs the
    replica serving dispatch N shortly after forwarding the request — once,
    so the supervised respawn proves recovery, not a kill loop. The router
    is the consumer (the replica process cannot kill itself mid-accept
    without also racing its own HTTP reply)."""
    k = _active.get("kill_replica_at_dispatch")
    if k is not None and dispatch_idx == int(k):
        # pop, not del: concurrent dispatch threads may race the match, and
        # only ONE caller gets the kill (the other sees the key gone)
        return _active.pop("kill_replica_at_dispatch", None) is not None
    return False


def maybe_client_stall() -> bool:
    """Armed ``client_stall=N``: the server's disconnect poll reports the
    next N polled connections as dead clients (consumed per connection),
    driving the cancellation path without a real socket reset."""
    return _consume("client_stall")


def world_schedule(env: Optional[str] = None) -> List[int]:
    """Parse ``GALVATRON_FAULTS_WORLD`` (comma list of device counts). The
    elastic supervisor runs its k-th child on entry ``min(k, len-1)`` — a
    one-entry list pins a constant simulated world, ``"8,4"`` simulates a
    shrink at the first restart. Empty/unset → no simulation (children see
    the real backend)."""
    spec = env if env is not None else os.environ.get(WORLD_ENV_VAR, "")
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            n = int(part)
        except ValueError:
            raise ValueError(
                f"{WORLD_ENV_VAR}: expected comma-separated device counts, "
                f"got {part!r}"
            ) from None
        if n < 1:
            raise ValueError(f"{WORLD_ENV_VAR}: device counts must be >= 1, got {n}")
        out.append(n)
    return out


def after_commit(step_dir: str) -> None:
    """Post-commit hook: corrupt the just-committed checkpoint if armed."""
    if _consume("corrupt_leaf"):
        corrupt_checkpoint_leaf(step_dir)


def corrupt_checkpoint_leaf(step_dir: str) -> str:
    """Flip bytes in the middle of the largest array file under a committed
    step directory (manifest excluded) — storage corruption that name-based
    selection cannot see and only content verification catches."""
    largest, size = None, -1
    for root, _, files in os.walk(step_dir):
        for fn in files:
            if fn == "manifest.json":
                continue
            full = os.path.join(root, fn)
            s = os.path.getsize(full)
            if s > size:
                largest, size = full, s
    if largest is None or size <= 0:
        raise FileNotFoundError(f"no array files to corrupt under {step_dir}")
    with open(largest, "r+b") as f:
        f.seek(size // 2)
        chunk = f.read(min(64, size - size // 2))
        f.seek(size // 2)
        f.write(bytes(b ^ 0xFF for b in chunk))
    return largest
