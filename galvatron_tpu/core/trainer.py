"""Training loop driver shared by all model-family entries.

The train_dist.py body of the reference (reference:
models/llama_hf/train_dist.py:16-90): resolve model config → hybrid strategy
→ construct hybrid model → dataloader → Adam → iterate forward_backward with
profiler hooks. Plus what the reference lacks: checkpoint save/resume.
"""

from __future__ import annotations

import argparse
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from galvatron_tpu.core.arguments import hybrid_config_from_args, model_config_from_args
from galvatron_tpu.core.checkpoint import (
    abstract_state_of,
    latest_step,
    restore_checkpoint,
    save_checkpoint,
)
from galvatron_tpu.core.dataloader import build_dataloader
from galvatron_tpu.core.optim import AdamConfig
from galvatron_tpu.parallel.hybrid import build_runtime
from galvatron_tpu.profiling.runtime import RuntimeProfiler


def train(ns: argparse.Namespace, verbose: bool = True) -> dict:
    cfg = model_config_from_args(ns)
    if ns.attn_impl != "auto":
        cfg = cfg.replace(attn_impl=ns.attn_impl)
    elif jax.default_backend() != "cpu":
        cfg = cfg.replace(attn_impl="flash")
    world = len(jax.devices())
    hp = hybrid_config_from_args(ns, cfg.num_layers, world)
    adam = AdamConfig(lr=ns.lr, weight_decay=ns.weight_decay, grad_clip=ns.grad_clip)
    seq = cfg.max_seq_len
    rt = build_runtime(
        cfg, hp, adam=adam, global_batch_size=ns.global_train_batch_size, seq_len=seq
    )

    start_step = 0
    if ns.load and latest_step(ns.load) is not None:
        state = restore_checkpoint(ns.load, abstract_state_of(rt))
        start_step = int(np.asarray(state["step"]))
        if verbose:
            print(f"resumed from {ns.load} at step {start_step}")
    else:
        state = rt.init_state(jax.random.key(ns.seed))

    # start_batch fast-forwards by index arithmetic so resume sees the batches
    # an uninterrupted run would (reference has no resume at all)
    loader = build_dataloader(
        cfg, ns.global_train_batch_size, seq, seed=ns.seed, start_batch=start_step
    )
    prof = RuntimeProfiler(warmup_iters=1)
    losses = []
    for it in range(start_step, ns.train_iters):
        batch = jnp.asarray(next(loader))
        prof.begin_iter()
        state, loss = rt.train_step(state, batch)
        prof.end_iter(loss if (ns.profile or ns.check_loss) else None)
        if ns.check_loss or ns.profile:
            losses.append(float(loss))
            if verbose:
                print(f"iter {it}: loss {float(loss):.4f}")
        if ns.save and ns.save_interval and (it + 1) % ns.save_interval == 0:
            save_checkpoint(ns.save, state, it + 1)
            if verbose:
                print(f"saved step {it + 1} → {ns.save}")
    if ns.save:
        final_step = int(np.asarray(state["step"]))
        if latest_step(ns.save) != final_step:
            save_checkpoint(ns.save, state, final_step)
    report = prof.report(ns.global_train_batch_size, seq) if prof.iter_times_ms else ""
    if verbose and report:
        print(report)
    return {
        "losses": losses,
        "iter_ms": prof.avg_iter_ms if prof.iter_times_ms else None,
        "state": state,
    }
