"""Training loop driver shared by all model-family entries.

The train_dist.py body of the reference (reference:
models/llama_hf/train_dist.py:16-90): resolve model config → hybrid strategy
→ construct hybrid model → dataloader → Adam → iterate forward_backward with
profiler hooks. Plus what the reference lacks: checkpoint save/resume.
"""

from __future__ import annotations

import argparse
import jax
import numpy as np

from galvatron_tpu.core.arguments import hybrid_config_from_args, model_config_from_args
from galvatron_tpu.core.checkpoint import (
    latest_step,
    restore_checkpoint_portable,
    save_checkpoint_portable,
)
from galvatron_tpu.core.dataloader import build_dataloader
from galvatron_tpu.core.optim import AdamConfig
from galvatron_tpu.parallel.hybrid import build_runtime
from galvatron_tpu.profiling.runtime import RuntimeProfiler


def train(ns: argparse.Namespace, verbose: bool = True) -> dict:
    if getattr(ns, "multihost", 0):
        # join the multi-host job (TPU pods: coordinator/process id are
        # auto-detected from the TPU metadata; DCN carries the collectives) —
        # the reference's torch.distributed.init_process_group role
        # (site_package/megatron/initialize.py _initialize_distributed)
        jax.distributed.initialize()
    hf_params = None
    if getattr(ns, "load_hf", None):
        # pretrained HF weights: the model shape comes from the HF config
        # (the reference builds its model FROM the HF checkpoint the same
        # way — models/llama_hf/train_dist.py)
        from galvatron_tpu.models.convert import load_hf_llama

        hf_params, cfg = load_hf_llama(ns.load_hf)
        # weight-bearing dims come from the HF config; the training sequence
        # length is still the user's call (shorter contexts train fine). The
        # learned-pos table must follow the override, or the imported state
        # would disagree with the runtime's nominal shapes and break resume.
        if getattr(ns, "seq_length", None) and ns.seq_length != cfg.max_seq_len:
            if "pos" in hf_params.get("embed", {}):
                if ns.seq_length > cfg.max_seq_len:
                    raise ValueError(
                        f"--seq_length {ns.seq_length} exceeds the checkpoint's "
                        f"learned-position table ({cfg.max_seq_len})"
                    )
                hf_params["embed"]["pos"] = hf_params["embed"]["pos"][: ns.seq_length]
            cfg = cfg.replace(max_seq_len=ns.seq_length)
    else:
        cfg = model_config_from_args(ns)
    from galvatron_tpu.core.arguments import resolve_attn_impl

    cfg = resolve_attn_impl(cfg, ns)
    world = len(jax.devices())
    hp = hybrid_config_from_args(ns, cfg.total_layers, world)
    lr_schedule = None
    if getattr(ns, "lr_warmup_iters", 0) or getattr(ns, "lr_decay_iters", 0):
        from galvatron_tpu.core.schedules import LRSchedule

        lr_schedule = LRSchedule(
            lr=ns.lr, min_lr=ns.min_lr, warmup_iters=ns.lr_warmup_iters,
            decay_iters=ns.lr_decay_iters, decay_style=ns.lr_decay_style,
        )
    adam = AdamConfig(
        lr=ns.lr, weight_decay=ns.weight_decay, grad_clip=ns.grad_clip,
        lr_schedule=lr_schedule,
    )
    rampup = None
    if getattr(ns, "rampup_batch_size", None):
        from galvatron_tpu.core.schedules import BatchSizeRampup

        if hp.pp > 1:
            raise ValueError("--rampup_batch_size requires pp=1 (static pipeline shapes)")
        start, inc, samples = ns.rampup_batch_size
        rampup = BatchSizeRampup(
            start=start, increment=inc, rampup_samples=samples,
            target=ns.global_train_batch_size,
        )
        for bs in rampup.sizes():
            if bs % world != 0:
                raise ValueError(
                    f"rampup batch size {bs} must be divisible by the device "
                    f"count {world} (global batches shard over all data axes)"
                )
            if bs % max(1, hp.chunks) != 0:
                raise ValueError(
                    f"rampup batch size {bs} must be divisible by chunks "
                    f"{hp.chunks} (micro-batch gradient accumulation)"
                )
    seq = cfg.sample_len
    mesh = axes = None
    if getattr(ns, "num_slices", 0) and ns.num_slices > 1:
        # multislice: slice-major device order puts pp + the major data axes
        # across the DCN boundary (parallel/mesh.build_mesh)
        from galvatron_tpu.parallel.mesh import build_mesh

        mesh, axes = build_mesh(pp=hp.pp, num_slices=ns.num_slices)
    rt = build_runtime(
        cfg, hp, mesh=mesh, axes=axes, adam=adam,
        global_batch_size=ns.global_train_batch_size, seq_len=seq,
    )

    start_step = 0
    if ns.load and latest_step(ns.load) is not None:
        state = restore_checkpoint_portable(ns.load, rt)
        start_step = int(np.asarray(state["step"]))
        if verbose:
            print(f"resumed from {ns.load} at step {start_step}")
    elif hf_params is not None:
        state = rt.init_state_from(hf_params)
        if verbose:
            print(f"initialized from HF checkpoint {ns.load_hf}")
    else:
        state = rt.init_state(jax.random.key(ns.seed))

    # start_batch fast-forwards by index arithmetic so resume sees the batches
    # an uninterrupted run would (reference has no resume at all)
    loader = build_dataloader(
        cfg, ns.global_train_batch_size, seq, seed=ns.seed, start_batch=start_step,
        data_path=getattr(ns, "data_path", None),
    )
    from galvatron_tpu.core.signals import GracefulExitHandler
    from galvatron_tpu.utils.metrics import MetricsLogger

    # per-iter host syncs (float(loss) every step) serialize dispatch with
    # device compute; only sync each iteration when the user asked for
    # per-iter observables (loss curves, per-iter metrics). Otherwise let
    # dispatch run free and time a window (TPU-idiomatic async training).
    sync_each = bool(ns.check_loss or getattr(ns, "metrics_path", None))
    prof = RuntimeProfiler(warmup_iters=1, windowed=not sync_each)
    # jax.profiler trace of the training loop (op/kernel timeline viewable in
    # TensorBoard/Perfetto) — the tracing counterpart of the reference's
    # torch.profiler + CUDA-event instrumentation (SURVEY §5). Started after
    # the warmup iteration so compile/warmup spans don't drown the timeline.
    trace_dir = getattr(ns, "trace_dir", None)
    trace_started = False
    losses = []
    # consumed-samples bookkeeping: under rampup, replay the schedule from
    # step 0 so a resumed run sees exactly the sizes (and per-size stream
    # positions) an uninterrupted run would
    consumed = 0
    batches_at_size: dict = {}
    if rampup is not None:
        for _ in range(start_step):
            b = rampup(consumed)
            batches_at_size[b] = batches_at_size.get(b, 0) + 1
            consumed += b
    else:
        consumed = start_step * ns.global_train_batch_size
    consumed_at_start = consumed
    cur_bs = ns.global_train_batch_size
    metrics = MetricsLogger(getattr(ns, "metrics_path", None))
    iters_run = 0
    try:
        with GracefulExitHandler() as exit_handler:
            for it in range(start_step, ns.train_iters):
                if exit_handler.signaled is not None:
                    if verbose:
                        print(f"signal {exit_handler.signaled} received; stopping at iter {it}")
                    break
                # start after the warmup/compile iteration so the timeline
                # shows steady-state steps, not one giant compile span
                if trace_dir and not trace_started and iters_run >= 1:
                    jax.profiler.start_trace(trace_dir)
                    trace_started = True
                if rampup is not None:
                    bs = rampup(consumed)
                    if bs != cur_bs or it == start_step:
                        cur_bs = bs
                        loader = build_dataloader(
                            cfg, bs, seq, seed=ns.seed + bs,
                            start_batch=batches_at_size.get(bs, 0),
                            data_path=getattr(ns, "data_path", None),
                        )
                    batches_at_size[bs] = batches_at_size.get(bs, 0) + 1
                    consumed += bs
                else:
                    consumed += cur_bs
                iters_run += 1
                batch = rt.shard_batch(next(loader))
                prof.begin_iter()
                state, loss = rt.train_step(state, batch)
                # always hand end_iter the loss: per-iter mode syncs each
                # step (sync_each implies that's wanted); windowed mode syncs
                # ONCE, to close the warmup — without it the window would
                # open while warmup compute is still in flight and overstate
                # avg iter time
                prof.end_iter(loss)
                if sync_each:
                    losses.append(float(loss))
                    if verbose:
                        print(f"iter {it}: loss {float(loss):.4f}")
                if metrics.path:
                    metrics.log(
                        "train_iter", step=it, loss=float(loss), batch_size=cur_bs,
                        iter_ms=(prof.iter_times_ms[-1] if prof.iter_times_ms else None),
                    )
                if ns.save and ns.save_interval and (it + 1) % ns.save_interval == 0:
                    save_checkpoint_portable(ns.save, state, it + 1, rt)
                    if verbose:
                        print(f"saved step {it + 1} → {ns.save}")
        prof.finish(loss if iters_run else None)
    finally:
        # always close the trace — an exception mid-loop must not lose the
        # captured data or wedge the process-wide profiler state
        if trace_started:
            jax.profiler.stop_trace()
            if verbose:
                print(f"jax.profiler trace → {trace_dir}")
    # checkpoint on exit — normal completion or signal (the reference's
    # dist_signal_handler checkpoint-then-exit pattern, there unused)
    if ns.save:
        final_step = int(np.asarray(state["step"]))
        if latest_step(ns.save) != final_step:
            save_checkpoint_portable(ns.save, state, final_step, rt)
    metrics.close()
    # throughput from actual samples processed (rampup runs at smaller sizes)
    avg_bs = (consumed - consumed_at_start) / iters_run if iters_run else 0
    # cost-model fidelity: predicted-vs-measured iteration time when training
    # the searched strategy at its searched batch size (the benchmark the
    # reference itself optimizes, SURVEY §6; search_cost_ms is written by
    # SearchEngine.save_result)
    predicted_ms = None
    if ns.galvatron_config_path:
        import json as _json

        try:
            with open(ns.galvatron_config_path) as f:
                d = _json.load(f)
            if d.get("global_bsz") == ns.global_train_batch_size:
                predicted_ms = d.get("search_cost_ms")
        except (OSError, ValueError):
            pass
    report = (
        prof.report(avg_bs, seq, predicted_ms=predicted_ms)
        if prof.iter_times_ms
        else ""
    )
    if verbose and report:
        print(report)
    return {
        "losses": losses,
        "iter_ms": prof.avg_iter_ms if prof.iter_times_ms else None,
        "state": state,
    }
