"""Training loop driver shared by all model-family entries.

The train_dist.py body of the reference (reference:
models/llama_hf/train_dist.py:16-90): resolve model config → hybrid strategy
→ construct hybrid model → dataloader → Adam → iterate forward_backward with
profiler hooks. Plus what the reference lacks: checkpoint save/resume, and
the resilience layer around it — every exit mode (normal completion, SIGTERM,
unhandled exception, anomaly abort) lands a committed, resumable checkpoint,
and a non-finite loss is skipped/aborted by policy (core/resilience.py)
instead of silently poisoning the optimizer state.
"""

from __future__ import annotations

import argparse
import math
import os
import time

import jax
import numpy as np

from galvatron_tpu.core import faults
from galvatron_tpu.core import peer_store as peer_store_mod
from galvatron_tpu.core.arguments import hybrid_config_from_args, model_config_from_args
from galvatron_tpu.core.checkpoint import (
    CheckpointCorruptError,
    latest_step,
    portable_flat_state,
    read_manifest,
    restore_checkpoint_portable,
    restore_from_flat_leaves,
    save_checkpoint_portable,
    step_path,
    uncommitted_steps,
)
from galvatron_tpu.core.preemption import PreemptionListener
from galvatron_tpu.core.dataloader import build_dataloader
from galvatron_tpu.core.resilience import AnomalyAbort, AnomalySentinel
from galvatron_tpu.parallel.hybrid import build_runtime
from galvatron_tpu.profiling.runtime import RuntimeProfiler


def train(ns: argparse.Namespace, verbose: bool = True) -> dict:
    from galvatron_tpu.obs import tracing as obs_tracing

    # --xla_overlap: the curated latency-hiding flag set must land in
    # XLA_FLAGS before _train_impl's first backend touch (distributed init,
    # mesh build) — a later append would be silently ignored by the already-
    # initialized runtime. The applied set rides the manifest fingerprint.
    from galvatron_tpu.parallel.mesh import apply_xla_overlap

    ns.xla_overlap_applied = apply_xla_overlap(getattr(ns, "xla_overlap", "off"))

    # span tracer lifecycle wrapper: enable happens out here so that a
    # setup failure ANYWHERE in _train_impl (corrupt restore, loader build,
    # sidecar bind, ...) cannot leak the enabled process-wide singleton into
    # a later run — which would silently force per-iter syncs and record
    # spans nobody exports. --flight_dir arms tracing too: a flight
    # recorder with no span ring would be a silent no-op exactly when the
    # operator asked for crash forensics — and so does --step_timeout_s:
    # the hang watchdog's whole output IS the flight dump it takes on fire.
    tracer = obs_tracing.tracer
    tracer_owned = False
    if (
        getattr(ns, "trace_spans", None)
        or getattr(ns, "flight_dir", None)
        or getattr(ns, "step_timeout_s", 0)
    ):
        tracer.enable(capacity=getattr(ns, "trace_ring", 4096))
        tracer_owned = True
    try:
        return _train_impl(ns, verbose, tracer, tracer_owned)
    except BaseException as e:
        # _train_impl's own finally exports + dumps on every path that
        # reached the training loop; the tracer still being enabled here
        # means SETUP crashed before that try was entered — the forensics
        # the flags promise (a corrupt-restore fallback trail, most
        # commonly) must still land before the ring is dropped
        if tracer_owned and tracer.enabled:
            _export_obs_artifacts(
                ns, tracer, e, extra={"phase": "setup"}, verbose=verbose
            )
        raise
    finally:
        if tracer_owned and tracer.enabled:
            tracer.disable()
            tracer.clear()


def _export_obs_artifacts(ns, tracer, exc, extra=None, verbose=True) -> None:
    """Flight-recorder dump (exceptional exits only) + span-trace export.
    Best-effort by contract: callers sit in crash/teardown paths where an
    observability failure must never mask the original exception."""
    try:
        if exc is not None:
            fdir = getattr(ns, "flight_dir", None)
            if not fdir and getattr(ns, "trace_spans", None):
                fdir = os.path.dirname(os.path.abspath(ns.trace_spans))
            if fdir:
                from galvatron_tpu.obs.flight import dump_flight

                fpath = dump_flight(
                    fdir, tracer,
                    reason=f"{type(exc).__name__}: {str(exc)[:200]}",
                    extra=extra,
                )
                if fpath:
                    print(f"flight recorder → {fpath}")
        if getattr(ns, "trace_spans", None) and jax.process_index() == 0:
            out = tracer.export_chrome_trace(ns.trace_spans)
            if verbose:
                print(f"span trace → {out}")
    except Exception as obs_err:  # noqa: BLE001 — observability is best-effort
        print(f"observability export failed: {obs_err!r}")


def _train_impl(ns: argparse.Namespace, verbose: bool, tracer,
                tracer_owned: bool) -> dict:
    faults.init_from_env()  # chaos hooks: no-ops unless GALVATRON_FAULTS is set
    if getattr(ns, "multihost", 0):
        # join the multi-host job (TPU pods: coordinator/process id are
        # auto-detected from the TPU metadata; DCN carries the collectives) —
        # the reference's torch.distributed.init_process_group role
        # (site_package/megatron/initialize.py _initialize_distributed)
        jax.distributed.initialize()
    hf_params = None
    if getattr(ns, "load_hf", None):
        # pretrained HF weights: the model shape comes from the HF config
        # (the reference builds its model FROM the HF checkpoint the same
        # way — models/llama_hf/train_dist.py)
        from galvatron_tpu.models.convert import load_hf_llama

        hf_params, cfg = load_hf_llama(ns.load_hf)
        # weight-bearing dims come from the HF config; the training sequence
        # length is still the user's call (shorter contexts train fine). The
        # learned-pos table must follow the override, or the imported state
        # would disagree with the runtime's nominal shapes and break resume.
        if getattr(ns, "seq_length", None) and ns.seq_length != cfg.max_seq_len:
            if "pos" in hf_params.get("embed", {}):
                if ns.seq_length > cfg.max_seq_len:
                    raise ValueError(
                        f"--seq_length {ns.seq_length} exceeds the checkpoint's "
                        f"learned-position table ({cfg.max_seq_len})"
                    )
                hf_params["embed"]["pos"] = hf_params["embed"]["pos"][: ns.seq_length]
            cfg = cfg.replace(max_seq_len=ns.seq_length)
    else:
        cfg = model_config_from_args(ns)
    from galvatron_tpu.core.arguments import resolve_attn_impl

    # data-pipeline flags (galvatron_tpu/data/): packing rides the model
    # config (split_batch / attention masking / position reset key off it),
    # and must be set BEFORE attn resolution so 'auto' lands on the
    # segment-maskable xla path instead of flash
    if getattr(ns, "pack_sequences", 0):
        cfg = cfg.replace(pack_sequences=True)
    use_data_pipe = bool(
        getattr(ns, "data_mixture", None)
        or cfg.pack_sequences
        or getattr(ns, "prefetch_depth", 0)
    )
    if use_data_pipe:
        if not (getattr(ns, "data_mixture", None) or getattr(ns, "data_path", None)):
            raise ValueError(
                "--pack_sequences/--prefetch_depth/--data_mixture need a real "
                "corpus: pass --data_path or --data_mixture"
            )
        if getattr(ns, "rampup_batch_size", None):
            raise ValueError(
                "--rampup_batch_size is incompatible with the data pipeline "
                "(mixture/packing/prefetch): the sample-domain cursor is "
                "defined at one global batch size"
            )
    cfg = resolve_attn_impl(cfg, ns)
    world = len(jax.devices())
    from galvatron_tpu.analysis import plan_check

    if ns.galvatron_config_path:
        # fail-fast BEFORE any mesh is built: a bad plan surfaces as
        # structured GTA… diagnostics in milliseconds instead of a cryptic
        # compiler abort (or a silent memory blowout) minutes into startup.
        # The file is checked directly so even plans that fail to decode
        # report field provenance rather than a bare codec ValueError.
        plan_check.ensure_valid(
            ns.galvatron_config_path, model_config=cfg, world_size=world,
            global_bsz=ns.global_train_batch_size,
            context=f"refusing to start: {ns.galvatron_config_path}",
            verbose=verbose,
        )
    hp = hybrid_config_from_args(ns, cfg.total_layers, world)
    if not ns.galvatron_config_path:
        plan_check.ensure_valid(
            hp, model_config=cfg, world_size=world,
            global_bsz=ns.global_train_batch_size,
            context="refusing to start: invalid hybrid-parallel flags",
            verbose=verbose,
        )
    from galvatron_tpu.core.arguments import adam_config_from_args

    # shared with the elastic prewarm: the optimizer terms are constants in
    # the compiled train_step, so they are part of the program's identity
    adam = adam_config_from_args(ns)
    rampup = None
    if getattr(ns, "rampup_batch_size", None):
        from galvatron_tpu.core.schedules import BatchSizeRampup

        if hp.pp > 1:
            raise ValueError("--rampup_batch_size requires pp=1 (static pipeline shapes)")
        start, inc, samples = ns.rampup_batch_size
        rampup = BatchSizeRampup(
            start=start, increment=inc, rampup_samples=samples,
            target=ns.global_train_batch_size,
        )
        for bs in rampup.sizes():
            if bs % world != 0:
                raise ValueError(
                    f"rampup batch size {bs} must be divisible by the device "
                    f"count {world} (global batches shard over all data axes)"
                )
            if bs % max(1, hp.chunks) != 0:
                raise ValueError(
                    f"rampup batch size {bs} must be divisible by chunks "
                    f"{hp.chunks} (micro-batch gradient accumulation)"
                )
    seq = cfg.sample_len
    mesh = axes = None
    if getattr(ns, "num_slices", 0) and ns.num_slices > 1:
        # multislice: slice-major device order puts pp + the major data axes
        # across the DCN boundary (parallel/mesh.build_mesh)
        from galvatron_tpu.parallel.mesh import build_mesh

        mesh, axes = build_mesh(pp=hp.pp, num_slices=ns.num_slices)
    rt = build_runtime(
        cfg, hp, mesh=mesh, axes=axes, adam=adam,
        global_batch_size=ns.global_train_batch_size, seq_len=seq,
    )

    from galvatron_tpu.obs import tracing as obs_tracing
    from galvatron_tpu.utils.metrics import SCHEMA_VERSION, MetricsLogger

    # opened before restore so a corrupt-latest fallback (ckpt_fallback) is
    # visible in the same JSONL stream as the training events. Multihost:
    # O_APPEND does not serialize cross-process writers on network
    # filesystems, so the JSONL sink is process-0-only (the other hosts get
    # a no-op logger; see MetricsLogger's docstring).
    metrics_path = getattr(ns, "metrics_path", None)
    if metrics_path and jax.process_index() != 0:
        metrics_path = None
    metrics = MetricsLogger(metrics_path)
    # in-memory peer replication client (core/peer_store.py): armed by the
    # elastic supervisor under --peer_replicate (env carries the store
    # addresses + this peer's ring rank). None = the RAM tier is off and
    # every recovery path below degrades to disk-only exactly as before.
    peer_client = peer_store_mod.client_from_env()
    # topology + plan fingerprint: rides every manifest so a restart can
    # tell "same world, same plan" from "the pod shrank under me" (GTA017)
    # and from a legal cross-plan resume. mesh_shape/axes are forensic;
    # world_size is the gate (plan_check.check_topology_fingerprint).
    from galvatron_tpu.core.strategy import plan_hash

    fingerprint = {
        "world_size": world,
        "mesh_shape": [int(x) for x in rt.mesh.devices.shape],
        "mesh_axes": [str(a) for a in rt.mesh.axis_names],
        "plan_hash": plan_hash(hp),
        "global_bsz": int(ns.global_train_batch_size),
        # scheduler provenance (--xla_overlap): mode + the flags actually
        # appended, so a perf delta across manifests is attributable
        "xla_overlap": getattr(ns, "xla_overlap", "off"),
        "xla_overlap_flags": list(getattr(ns, "xla_overlap_applied", []) or []),
    }
    # AOT compile subsystem (galvatron_tpu/aot; DESIGN.md § AOT compile
    # subsystem): an explicit --compile_cache_dir arms the startup consult —
    # enable the shared persistent cache, AOT-compile the programs THIS run
    # will dispatch (always train_step; init_state only when a fresh init is
    # coming — a resume never calls it, and eval_loss belongs to `cli
    # warmup`, not a train run), and account plan-keyed hit/miss in the
    # artifact manifest. Running BEFORE restore/init means the init compile
    # below is already a cache deserialize, the loop's first step pays no
    # XLA compile, and a proven-warm start shrinks the watchdog's
    # first-step compile grace to the normal deadline. Without the flag the
    # subsystem stays out of the way entirely (an already-configured jax
    # cache keeps working; no manifest, no extra lowering).
    aot_warm_hint = False
    aot_summ = None
    if getattr(ns, "compile_cache_dir", None):
        from galvatron_tpu.aot.cache import (
            ArtifactStore,
            enable_persistent_cache,
            resolve_compile_cache_dir,
        )

        aot_dir = resolve_compile_cache_dir(ns)
        # best-effort by contract, like the elastic prewarm: a cache-
        # infrastructure failure (read-only mount, torn store) costs only
        # warmth — the run must still train cold
        try:
            if aot_dir:
                from galvatron_tpu.aot import warmup as aot_warmup

                will_restore = bool(ns.load and latest_step(ns.load) is not None)
                include = ["train_step"]
                if not will_restore and hf_params is None:
                    include.append("init_state")
                store = ArtifactStore(
                    enable_persistent_cache(aot_dir, override=True)
                )
                t0_warm = time.perf_counter()
                aot_reports = aot_warmup.warmup_runtime(
                    rt, ns.global_train_batch_size, seq, store=store,
                    plan=hp, model_cfg=cfg, include=include, verbose=verbose,
                )
                startup_ms = round((time.perf_counter() - t0_warm) * 1000.0, 1)
                for r in aot_reports:
                    metrics.log(
                        "compile_cache", program=r["program"], key=r["key"],
                        status=r["status"], hit=bool(r.get("cache_hit")),
                        compile_ms=r["compile_ms"],
                    )
                aot_summ = aot_warmup.summarize(aot_reports)
                aot_summ["startup_compile_ms"] = startup_ms
                ts_rep = next(
                    (r for r in aot_reports if r["program"] == "train_step"),
                    None,
                )
                # warm ONLY when the step program itself was served from the
                # manifest-known cache: hits on secondary programs must not
                # shave the grace the first step's real compile still needs
                aot_warm_hint = bool(
                    ts_rep
                    and ts_rep["status"] == "compiled"
                    and ts_rep["cache_hit"]
                )
                metrics.log(
                    "aot_warmup", warm_hint=aot_warm_hint, cache_dir=store.dir,
                    **aot_summ,
                )
                if verbose:
                    print(
                        f"aot warmup: {aot_summ['hits']} hits / "
                        f"{aot_summ['misses']} misses, {startup_ms:.0f} ms "
                        f"startup compile "
                        f"({'warm' if aot_warm_hint else 'cold'} start)"
                    )
        except Exception as e:  # noqa: BLE001 — warmth only, never the run
            aot_warm_hint = False
            aot_summ = None
            metrics.log(
                "aot_warmup", warm_hint=False, cache_dir=aot_dir,
                error=f"{type(e).__name__}: {e}"[:200],
            )
            print(f"warning: aot warmup failed ({type(e).__name__}: {e}); "
                  "starting cold")
    start_step = 0
    batch_offset = 0
    saved_data_state = None  # checkpoint's data-pipeline cursor (if any)
    # two-tier restore: the in-memory peer replica (core/peer_store.py) is
    # consulted FIRST and used when it is NEWER than the newest committed
    # disk step — host-loss recovery must not round-trip through storage,
    # and after a storage outage the replica may be the only record of the
    # last interval. A replica that fails its digest/structure check falls
    # back to the disk tier with a ckpt_fallback event, exactly as a
    # corrupt disk step falls back to an older one.
    restored_from = None
    meta: dict = {}
    disk_latest = latest_step(ns.load) if ns.load else None
    if peer_client is not None:
        rec = None
        try:
            rec = peer_client.get_newest()
        except Exception as peer_err:  # noqa: BLE001 — the RAM tier is optional
            print(f"peer store unreachable, using disk tier: {peer_err!r}")
        if rec is not None and (
            disk_latest is None or int(rec[0].get("step", -1)) > disk_latest
        ):
            h, payload = rec
            try:
                leaves = peer_store_mod.deserialize_state(payload, h)
                state = restore_from_flat_leaves(rt, leaves)
                start_step = int(h.get("step", 0))
                meta = dict(h.get("meta") or {})
                restored_from = "peer"
                if verbose:
                    print(f"restored step {start_step} from the in-memory "
                          f"peer replica ({int(h.get('nbytes', 0))} bytes)")
            except (peer_store_mod.ReplicaCorruptError,
                    CheckpointCorruptError) as e:
                metrics.log("ckpt_fallback", step=int(h.get("step", -1)),
                            error=str(e)[:300], source="peer")
                tracer.instant("ckpt_fallback", step=int(h.get("step", -1)),
                               source="peer")
                print(f"peer replica corrupt, falling back to disk: "
                      f"{str(e)[:200]}")
                meta = {}
    if restored_from is None and ns.load and disk_latest is not None:
        state = restore_checkpoint_portable(ns.load, rt, metrics=metrics)
        start_step = int(np.asarray(state["step"]))
        m = read_manifest(step_path(ns.load, start_step))
        meta = m.get("meta") if m and isinstance(m.get("meta"), dict) else {}
        restored_from = "disk"
    if restored_from is not None:
        # stream position ≠ optimizer step once anomaly skips happened: a
        # skipped batch was consumed but produced no update. Both tiers
        # record batches-consumed in their meta (manifest / replica header).
        batch_offset = start_step
        if meta:
            batch_offset = int(meta.get("batches_consumed", start_step))
        if isinstance(meta.get("data_state"), dict):
            saved_data_state = meta["data_state"]
        saved_fp = meta.get("fingerprint")
        if isinstance(saved_fp, dict):
            from galvatron_tpu.analysis.plan_check import (
                PlanError,
                check_topology_fingerprint,
            )

            diags = check_topology_fingerprint(saved_fp, world, source=ns.load)
            if diags and not getattr(ns, "allow_topology_change", False):
                # the plan this run would train was never searched for the
                # live mesh — refuse, pointing at the supervised path that
                # re-plans automatically (run-elastic sets the allow flag
                # after installing a validated plan for THIS topology)
                raise PlanError(
                    diags,
                    context=f"refusing to resume {ns.load} on a changed topology",
                )
            if diags:
                metrics.log(
                    "topology_resume", step=start_step,
                    old_world=saved_fp.get("world_size"), new_world=world,
                    old_plan=saved_fp.get("plan_hash"),
                    new_plan=fingerprint["plan_hash"],
                )
                tracer.instant(
                    "topology_resume", step=start_step,
                    old_world=saved_fp.get("world_size"), new_world=world,
                )
                if verbose:
                    print(
                        f"topology-change resume: {saved_fp.get('world_size')} "
                        f"→ {world} devices (checkpoint resharded portably)"
                    )
            elif saved_fp.get("plan_hash") not in (None, fingerprint["plan_hash"]):
                # cross-plan resume on the SAME topology is the portable
                # checkpoint working as designed — an event, not an error
                metrics.log(
                    "plan_change", step=start_step,
                    old_plan=saved_fp.get("plan_hash"),
                    new_plan=fingerprint["plan_hash"],
                )
                tracer.instant("plan_change", step=start_step)
        # sample-domain resume: the batch domain is only meaningful at the
        # batch size that consumed it — after a re-plan (or an operator
        # decision) changed the global batch, the cursor converts through
        # samples so no example is skipped or replayed
        rec_bsz = meta.get("global_bsz")
        if not rec_bsz and isinstance(saved_fp, dict):
            rec_bsz = saved_fp.get("global_bsz")
        samples_rec = meta.get("samples_consumed")
        if (
            samples_rec is not None
            and rec_bsz
            and int(rec_bsz) != ns.global_train_batch_size
        ):
            if getattr(ns, "rampup_batch_size", None):
                raise ValueError(
                    "cannot combine --rampup_batch_size with a changed "
                    f"--global_train_batch_size on resume (checkpoint "
                    f"records bsz {rec_bsz}): the rampup schedule replays "
                    "in the batch domain"
                )
            if int(samples_rec) % ns.global_train_batch_size:
                raise ValueError(
                    f"cannot resume at --global_train_batch_size "
                    f"{ns.global_train_batch_size}: the checkpoint consumed "
                    f"{samples_rec} samples (at bsz {rec_bsz}), which is not "
                    f"divisible — a partial batch would be skipped or "
                    f"replayed. Pick a batch size dividing {samples_rec}."
                )
            batch_offset = int(samples_rec) // ns.global_train_batch_size
            if verbose:
                print(
                    f"sample-domain resume: {samples_rec} samples consumed "
                    f"at bsz {rec_bsz} → batch cursor {batch_offset} at "
                    f"bsz {ns.global_train_batch_size}"
                )
        # recovery provenance: which tier restored and where the cursor
        # landed. The chaos harness derives MTTR (supervisor child_exit ts →
        # this record's ts) and steps-lost (fault step − resume_batches)
        # from it, so it fires on every resume, not only post-failure ones.
        metrics.log("recovery", step=start_step, source=restored_from,
                    resume_batches=batch_offset,
                    resume_samples=meta.get("samples_consumed"))
        if verbose:
            src = ns.load if restored_from == "disk" else "peer replica"
            print(f"resumed from {src} at step {start_step}")
    elif ns.load and uncommitted_steps(ns.load):
        # pre-manifest legacy dirs must not silently restart from scratch
        raise FileNotFoundError(
            f"--load {ns.load}: steps {uncommitted_steps(ns.load)} exist but "
            "none carry a manifest (pre-commit-protocol saves, or partial "
            "writes). Refusing to silently start from step 0 — restore one "
            "explicitly (checkpoint.restore_checkpoint_portable(..., step=N)) "
            "and re-save to commit it, or point --load elsewhere."
        )
    elif hf_params is not None:
        state = rt.init_state_from(hf_params)
        if verbose:
            print(f"initialized from HF checkpoint {ns.load_hf}")
    else:
        state = rt.init_state(jax.random.key(ns.seed))

    # start_batch fast-forwards by index arithmetic so resume sees the batches
    # an uninterrupted run would (reference has no resume at all); the offset
    # is batches CONSUMED, not optimizer steps — they diverge after skips
    data_pipe = None
    if saved_data_state is not None and not use_data_pipe:
        # the checkpoint was trained through the data pipeline; resuming
        # without its flags would silently continue a real-corpus run on
        # synthetic tokens (or unpacked windows), bypassing the per-source
        # verification the subsystem promises
        raise ValueError(
            f"--load {ns.load}: the checkpoint records a data-pipeline cursor "
            f"(sources {sorted(saved_data_state.get('per_source_consumed', {}))}"
            f"{', packed' if saved_data_state.get('packed') else ''}) but this "
            "run passes none of --data_mixture/--pack_sequences/"
            "--prefetch_depth. Resume with the original data flags, or point "
            "--load elsewhere."
        )
    if use_data_pipe:
        # production input path (galvatron_tpu/data/): sharded corpora,
        # deterministic mixture, sequence packing, async device prefetch.
        # The pipeline applies rt.shard_batch itself (on the prefetch thread
        # when armed), so the loop's data span is a dequeue. A restored
        # checkpoint's per-source cursor is verified against the rebuilt
        # schedule — a changed mixture fails loudly instead of silently
        # replaying or skipping samples.
        from galvatron_tpu.data import build_data_pipeline

        data_pipe = build_data_pipeline(
            cfg, ns.global_train_batch_size, seq, seed=ns.seed,
            start_batch=batch_offset,
            data_path=getattr(ns, "data_path", None),
            mixture=getattr(ns, "data_mixture", None),
            pack=cfg.pack_sequences,
            prefetch_depth=getattr(ns, "prefetch_depth", 0),
            put_fn=rt.shard_batch,
            resume_state=saved_data_state,
        )
        loader = iter(data_pipe)
    else:
        loader = build_dataloader(
            cfg, ns.global_train_batch_size, seq, seed=ns.seed, start_batch=batch_offset,
            data_path=getattr(ns, "data_path", None),
        )
    from galvatron_tpu.core.signals import GracefulExitHandler

    # per-iter host syncs (float(loss) every step) serialize dispatch with
    # device compute; only sync each iteration when the user asked for
    # per-iter observables (loss curves, per-iter metrics) or armed the
    # anomaly sentinel (which must classify the realized loss). Otherwise let
    # dispatch run free and time a window (TPU-idiomatic async training).
    sentinel = AnomalySentinel(getattr(ns, "anomaly_max_skips", 0))
    # span tracing syncs each iteration too: spans measure realized step
    # time, and an async span would just time dispatch (documented
    # observational overhead of tracing ON)
    # the sidecar is a per-iteration observable too: without the sync its
    # loss/iter_ms/mfu gauges would stay None (windowed profiling measures
    # nothing until the end of the run) — an operator who opened a metrics
    # port asked for live numbers. Process-0-gated like the server itself.
    obs_on = bool(getattr(ns, "obs_port", 0)) and jax.process_index() == 0
    # the hang watchdog bounds REALIZED step time: without a per-iter sync
    # the loop would run ahead of a stalled collective by the dispatch
    # depth and the deadline would measure dispatch, not the hang
    watchdog_on = bool(getattr(ns, "step_timeout_s", 0.0))
    # cost-model fidelity anchor: the plan's predicted step time
    # (search_cost_ms, written by SearchEngine.save_result) — read ONCE
    # here so the per-iter drift gauge and the end-of-run report share it.
    # The prediction only applies when training the searched batch size.
    predicted_ms = None
    if ns.galvatron_config_path:
        import json as _json

        try:
            with open(ns.galvatron_config_path) as f:
                _plan_doc = _json.load(f)
            if _plan_doc.get("global_bsz") == ns.global_train_batch_size:
                predicted_ms = _plan_doc.get("search_cost_ms")
        except (OSError, ValueError):
            pass
    # step-time-drift SLO (obs/slo.py): sustained (iter_ms - predicted)/
    # predicted past the flag's threshold raises a burn-rate breach — the
    # drift gauge is ROADMAP item 2's online re-plan signal. Drift needs
    # the realized per-iter time, so arming it joins sync_each below.
    train_slo = None
    slo_drift_on = (
        bool(getattr(ns, "slo_step_time_drift", 0.0))
        and jax.process_index() == 0
    )
    if slo_drift_on:
        from galvatron_tpu.obs.slo import SLOEngine, build_training_rules

        _slo_dir = ns.save or (
            os.path.dirname(metrics.path) or "." if metrics.path else None
        )
        train_slo = SLOEngine(
            rules=build_training_rules(ns),
            events_path=(os.path.join(_slo_dir, "slo_events.jsonl")
                         if _slo_dir else None),
            source="trainer",
        )
    # metrics.path, not ns.metrics_path: on a pod only process 0 owns the
    # JSONL sink — the other hosts must not pay a per-iter sync for a no-op
    # logger (their sentinel/tracing terms still apply to all hosts alike)
    sync_each = bool(
        ns.check_loss or metrics.path or sentinel.armed or tracer.enabled
        or obs_on or watchdog_on or slo_drift_on
    )
    prof = RuntimeProfiler(warmup_iters=1, windowed=not sync_each)
    # step accounting (obs/stepstats.py): tokens/s + achieved TFLOP/s + MFU
    # per train_iter record and for the sidecar/summary — derived, no
    # extra measurement
    from galvatron_tpu.obs.stepstats import StepStats

    stepstats = StepStats(
        cfg, ns.global_train_batch_size, seq, hp=hp,
        peak_tflops_override=getattr(ns, "peak_tflops", 0.0),
    )
    # jax.profiler trace of the training loop (op/kernel timeline viewable in
    # TensorBoard/Perfetto) — the tracing counterpart of the reference's
    # torch.profiler + CUDA-event instrumentation (SURVEY §5). Started after
    # the warmup iteration so compile/warmup spans don't drown the timeline.
    trace_dir = getattr(ns, "trace_dir", None)
    trace_started = False
    # step-bounded profiler window (--profile_steps A:B) — the precise
    # alternative to the whole-run --trace_dir capture; when both are given
    # the window wins (profiler traces cannot nest)
    pw = None
    if getattr(ns, "profile_steps", None):
        import tempfile

        from galvatron_tpu.obs.flight import ProfilerWindow, parse_profile_steps

        a, b = parse_profile_steps(ns.profile_steps)
        pw = ProfilerWindow(
            trace_dir or tempfile.mkdtemp(prefix="galvatron_profile_"), a, b
        )
    # pipeline schedules run inside ONE jitted scan — per-stage activity is
    # rendered from the schedule's structural clock model instead
    # (obs/tracing.emit_tick_spans; spans are labeled synthetic)
    sched_ticks = None
    if tracer.enabled and hp.pp > 1 and hp.vpp == 1:
        if hp.pipeline_type == "pipedream_flush":
            from galvatron_tpu.parallel.pipeline_1f1b import (
                pipedream_schedule_ticks as _schedule_ticks,
            )
        else:
            from galvatron_tpu.parallel.pipeline import (
                gpipe_schedule_ticks as _schedule_ticks,
            )
        sched_ticks = _schedule_ticks(hp.pp, max(1, hp.chunks))
    obs_server = train_obs = None
    if obs_on:
        # headless-run scrape endpoint: GET /metrics + /healthz on a sidecar
        # thread (process 0 only on a pod — one scrape target per job).
        # Started LAST in setup: everything after this point down to the
        # main try is pure arithmetic, so a setup failure cannot strand the
        # listener thread on its port
        from galvatron_tpu.obs.prom import ObsServer, TrainStats

        train_obs = TrainStats()
        obs_server = ObsServer(train_obs.render, port=ns.obs_port)
        if verbose:
            print(f"obs sidecar: http://127.0.0.1:{obs_server.port}/metrics")
    if train_obs is not None and aot_summ is not None:
        train_obs.compile_cache_hits = aot_summ["hits"]
        train_obs.compile_cache_misses = aot_summ["misses"]
        train_obs.startup_compile_ms = aot_summ["startup_compile_ms"]
    losses = []
    # consumed-samples bookkeeping: under rampup, replay the schedule from
    # step 0 so a resumed run sees exactly the sizes (and per-size stream
    # positions) an uninterrupted run would
    consumed = 0
    batches_at_size: dict = {}
    if rampup is not None:
        for _ in range(batch_offset):
            b = rampup(consumed)
            batches_at_size[b] = batches_at_size.get(b, 0) + 1
            consumed += b
    else:
        consumed = batch_offset * ns.global_train_batch_size
    consumed_at_start = consumed
    # samples actually COUNTED into manifests (increments with iters_run,
    # one fetched batch at a time — `consumed` runs one batch ahead inside
    # an iteration, and a crash between the two must not claim a sample
    # the stream never delivered)
    samples_done = consumed
    cur_bs = ns.global_train_batch_size
    keep_n = getattr(ns, "keep_last_n", 0)
    # due-based save schedule instead of a bare modulus: an anomaly-skipped
    # iteration `continue`s past the save point, and a modulus would then
    # silently double the checkpoint cadence exactly when the run is unstable
    next_save_at = (
        (batch_offset // ns.save_interval + 1) * ns.save_interval
        if ns.save and ns.save_interval else None
    )
    # `it` counts BATCHES globally (train_iters bounds batches consumed, so
    # a crash+resume run trains exactly the batches an uninterrupted run
    # would); the optimizer step lags by every anomaly skip, pre-crash skips
    # included — resuming at start_step instead would silently re-grant the
    # skipped iterations and re-log train_iter steps the first run already
    # emitted for different batches
    prior_skips = batch_offset - start_step
    iters_run = 0

    def _save_meta(batches=None, samples=None):
        # one schema for every save path (interval, exit, watchdog): the
        # stream cursor in BOTH domains plus the topology fingerprint. The
        # watchdog passes its snapshot's cursors; everyone else defaults to
        # the live ones.
        batches = batch_offset + iters_run if batches is None else batches
        samples = samples_done if samples is None else samples
        meta = {
            "batches_consumed": batches,
            "samples_consumed": samples,
            "global_bsz": int(ns.global_train_batch_size),
            "fingerprint": fingerprint,
        }
        if data_pipe is not None:
            # per-source mixture cursor: derived from the sample position, so
            # a resumed run can VERIFY it replays/skips nothing per source
            # (state() is pure in the position — watchdog-thread safe)
            meta["data_state"] = data_pipe.state(samples)
        return meta

    def _push_replica(st, step) -> bool:
        # RAM tier of the two-tier checkpoint: serialize the SAME portable
        # flat state the disk checkpoint would hold and hand it to a peer
        # host's in-memory store (ring neighbor). Best-effort by contract —
        # a dead peer degrades to disk-only, never fails the step.
        if peer_client is None:
            return False
        try:
            flat = portable_flat_state(st, rt)
            payload, header = peer_store_mod.serialize_state(
                flat, step, meta=_save_meta()
            )
            peer_client.put(payload, header)
            metrics.log("peer_replicate", step=step, nbytes=header["nbytes"])
            return True
        except Exception as e:  # noqa: BLE001 — replication is best-effort
            metrics.log(
                "peer_replicate_failed", step=step, error=str(e)[:300]
            )
            if verbose:
                print(f"peer replication failed at step {step}: {e!r}")
            return False

    # hang watchdog (--step_timeout_s; core/watchdog.py): armed around each
    # step, fires on a stalled collective — stacks + flight dump + a
    # best-effort emergency save of the last BOUND state (the holder is
    # invalidated across each donating dispatch), then exit EXIT_HANG so
    # the elastic supervisor restarts instead of burning the pod silently
    wd = holder = None
    if watchdog_on:
        import contextlib
        import sys as _sys

        from galvatron_tpu.core import watchdog as wdmod

        holder = wdmod.StateHolder()
        holder.set(state, step=start_step, batches=batch_offset, samples=consumed)

        def _on_hang(step_it):
            stacks = wdmod.dump_all_stacks()
            print(
                f"watchdog: step {step_it} exceeded --step_timeout_s "
                f"{ns.step_timeout_s}s; all-thread stacks:\n{stacks}",
                file=_sys.stderr, flush=True,
            )
            tracer.instant("watchdog_hang", step=step_it)
            snap_h = holder.snapshot()
            try:
                metrics.log(
                    "watchdog_hang", step=step_it,
                    save_possible=snap_h is not None,
                )
            except Exception:
                pass  # the JSONL sink must not block the forensics below
            fdir = getattr(ns, "flight_dir", None)
            if not fdir and getattr(ns, "trace_spans", None):
                fdir = os.path.dirname(os.path.abspath(ns.trace_spans))
            if not fdir:
                fdir = ns.save
            if fdir:
                from galvatron_tpu.obs.flight import dump_flight

                fpath = dump_flight(
                    fdir, tracer,
                    reason=f"watchdog hang at step {step_it} "
                           f"(deadline {ns.step_timeout_s}s)",
                    extra={"step": step_it, "stacks": stacks[-20000:]},
                )
                if fpath:
                    print(f"flight recorder → {fpath}", file=_sys.stderr, flush=True)
            if ns.save and snap_h is not None:
                # on a REAL stalled collective the held buffers may be
                # unreachable and this save may fail or block — best-effort
                # by contract; the last committed interval save is the floor
                try:
                    save_checkpoint_portable(
                        ns.save, snap_h["state"], snap_h["step"], rt,
                        keep_last_n=keep_n,
                        meta=_save_meta(
                            batches=snap_h["batches"], samples=snap_h["samples"]
                        ),
                    )
                    print(
                        f"watchdog emergency checkpoint step {snap_h['step']} "
                        f"→ {ns.save}", file=_sys.stderr, flush=True,
                    )
                except Exception as save_err:  # noqa: BLE001
                    print(f"watchdog emergency save failed: {save_err!r}",
                          file=_sys.stderr, flush=True)
            # HangWatchdog os._exits with EXIT_HANG when this returns

        wd = wdmod.HangWatchdog(
            ns.step_timeout_s, _on_hang,
            # proven-warm compile cache (startup AOT warmup hit, e.g. after
            # an elastic re-plan prewarm): the first step carries no XLA
            # compile, so it gets the NORMAL deadline — a real first-step
            # hang on a restarted child is detected in seconds, not 10x
            first_step_scale=1.0 if aot_warm_hint else None,
        )

        @contextlib.contextmanager
        def _watchdog_step(it):
            # a rampup batch-size transition recompiles the step: give it
            # the compile-length (warmup) deadline, or the transition of a
            # healthy run would be declared a hang
            wd.arm(
                it,
                warmup=rampup is not None and rampup(consumed) != cur_bs,
            )
            try:
                yield
            finally:
                wd.disarm()
            # normal exits only (incl. the anomaly-skip `continue`): rebind
            # the holder to the now-valid state. On an exception `state`
            # may still name donated buffers — the holder stays invalid and
            # the crash path's own exit save (bound post-rebind) takes over.
            holder.set(
                state,
                step=it + 1 - prior_skips - sentinel.total_skips,
                batches=batch_offset + iters_run,
                samples=samples_done,
            )
    else:
        import contextlib

        def _watchdog_step(it):  # noqa: ARG001 — uniform call site
            return contextlib.nullcontext()

    train_exc = None
    # preemption notice listener (core/preemption.py): the notice FILE
    # stands in for the cloud metadata server's eviction flag; SIGTERM
    # keeps riding the GracefulExitHandler branch below. Either way the
    # loop drains at the next step boundary and the exit path's replicated
    # save is the grace window's "expedited save".
    preempt_listener = PreemptionListener(
        None,
        notice_file=getattr(ns, "preempt_notice_file", None),
        grace_s=getattr(ns, "preempt_grace_s", 30.0) or 30.0,
        # poll every step: one os.path.exists is noise next to a dispatch,
        # and any throttle longer than a step can miss the notice entirely
        # on a fast (or simulated) mesh
        poll_interval_s=0.0,
    )
    # supervisor-side heartbeat (core/watchdog.py): one beat per step so a
    # child too wedged for its own in-process watchdog is still detectable
    from galvatron_tpu.core.watchdog import HEARTBEAT_ENV, beat_heartbeat

    hb_file = os.environ.get(HEARTBEAT_ENV)
    try:
        with GracefulExitHandler() as exit_handler:
            for it in range(batch_offset, ns.train_iters):
                if hb_file:
                    beat_heartbeat(hb_file, it)
                if exit_handler.signaled is not None:
                    if verbose:
                        print(f"signal {exit_handler.signaled} received; stopping at iter {it}")
                    break
                notice = preempt_listener.check()
                if notice is not None:
                    if verbose:
                        print(
                            f"preemption notice ({notice}) received; draining "
                            f"at iter {it} (grace "
                            f"{preempt_listener.grace_s:.0f}s)"
                        )
                    metrics.log("preempt_notice", step=it, reason=notice,
                                grace_s=float(preempt_listener.grace_s))
                    tracer.instant("preempt_notice", step=it, reason=notice)
                    break
                # start after the warmup/compile iteration so the timeline
                # shows steady-state steps, not one giant compile span (a
                # --profile_steps window supersedes the whole-run capture:
                # profiler traces cannot nest)
                if trace_dir and pw is None and not trace_started and iters_run >= 1:
                    jax.profiler.start_trace(trace_dir)
                    trace_started = True
                if pw is not None:
                    # stop is checked at the loop TOP (previous iteration's
                    # index) so an anomaly-skip `continue` cannot carry the
                    # window past its STOP boundary; the run-end close lives
                    # in the finally below
                    pw.maybe_stop(it - 1, verbose=verbose)
                    pw.maybe_start(it)
                step_sp = tracer.span("step", step=it)
                with _watchdog_step(it), step_sp:
                    if rampup is not None:
                        bs = rampup(consumed)
                        if bs != cur_bs or it == batch_offset:
                            cur_bs = bs
                            loader = build_dataloader(
                                cfg, bs, seq, seed=ns.seed + bs,
                                start_batch=batches_at_size.get(bs, 0),
                                data_path=getattr(ns, "data_path", None),
                            )
                        batches_at_size[bs] = batches_at_size.get(bs, 0) + 1
                        consumed += bs
                    else:
                        consumed += cur_bs
                    with tracer.span("data", step=it):
                        # the data pipeline already device-put the batch (on
                        # its prefetch thread when armed) — the span measures
                        # a dequeue, which is the point of the prefetcher
                        batch = (
                            next(loader)
                            if data_pipe is not None
                            else rt.shard_batch(next(loader))
                        )
                    pipe_meta = data_pipe.last_meta if data_pipe is not None else {}
                    # counted only once the batch is actually consumed: iters_run
                    # feeds the batches_consumed manifest record, and a crash in
                    # the fetch itself must not make resume skip a real batch
                    iters_run += 1
                    samples_done += cur_bs
                    # chaos hooks (core/faults.py): a simulated preemption
                    # SIGTERM mid-step, and a simulated stalled collective
                    # (sleep) that the armed watchdog must convert into a
                    # flight dump + emergency save + hang-coded exit. Both
                    # sit BEFORE the donating dispatch: the fetched batch is
                    # counted but untrained, exactly a real preemption's
                    # window, and the watchdog's holder is still valid.
                    faults.maybe_preempt(it)
                    # harsher chaos tiers: kill_host_mid_step SIGKILLs this
                    # process with no grace at all (recovery must come from
                    # the peer replica or the last committed checkpoint);
                    # preempt_with_grace writes the NOTICE file a real cloud
                    # eviction would, exercising the drain path above
                    faults.maybe_kill_host(it)
                    faults.maybe_preempt_notice(
                        it, getattr(ns, "preempt_notice_file", None)
                    )
                    faults.maybe_hang(it)
                    # rollback copy — the train step donates its input buffers,
                    # so a discarded update is unrecoverable without it (None
                    # when the sentinel is disarmed: no memory cost)
                    snap = sentinel.snapshot(state)
                    prof.begin_iter()
                    t_step0 = time.perf_counter() if sched_ticks is not None else None
                    if holder is not None:
                        # the dispatch below donates `state`: an emergency
                        # save between here and the post-step rebind would
                        # read freed buffers
                        holder.invalidate()
                    with tracer.span("fwd_bwd", step=it):
                        new_state, loss = rt.train_step(state, batch)
                    # rebind NOW: the old buffers were donated into train_step,
                    # so `state` must never name them again — an XLA error
                    # surfacing at float(loss) below would otherwise hand the
                    # emergency-save path deleted arrays
                    state = new_state
                    with tracer.span("sync", step=it) as sync_sp:
                        # always hand end_iter the loss: per-iter mode syncs each
                        # step (sync_each implies that's wanted); windowed mode syncs
                        # ONCE, to close the warmup — without it the window would
                        # open while warmup compute is still in flight and overstate
                        # avg iter time
                        prof.end_iter(loss)
                        loss_val = float(loss) if sync_each else None  # gta: disable=GTL101 — deliberate sync, gated by sync_each (off unless per-iter observables, span tracing, or the anomaly sentinel need the realized loss)
                        sync_sp.sync(loss)
                    if sched_ticks is not None:
                        # the fwd_bwd+sync window is the realized step; render
                        # the schedule's per-stage tick grid onto it so 1F1B
                        # bubbles are visible on the timeline
                        obs_tracing.emit_tick_spans(
                            tracer, sched_ticks[0], sched_ticks[1],
                            tracer.pc_to_us(t_step0),
                            (time.perf_counter() - t_step0) * 1e6, step=it,
                        )
                    # injection sits OUTSIDE the armed gate: chaos jobs force a
                    # NaN observation with or without the sentinel (a disarmed
                    # run must drive the stringified-JSONL divergence path too)
                    if loss_val is not None and faults.force_nan(it):
                        loss_val = float("nan")
                    if sentinel.armed:
                        verdict = sentinel.observe(loss_val, it)
                        if verdict != "ok":
                            # discard the poisoned update: drop the batch, roll
                            # the state back to the pre-step snapshot
                            state = snap
                            if verdict == "abort":
                                raise AnomalyAbort(
                                    it, sentinel.consecutive, sentinel.max_skips
                                )
                            # loss serialized as a string: bare NaN/Infinity is
                            # not valid JSON and would break strict JSONL readers
                            metrics.log(
                                "anomaly_skip", step=it, loss=str(loss_val),
                                consecutive=sentinel.consecutive,
                            )
                            tracer.instant(
                                "anomaly_skip", step=it, loss=str(loss_val),
                                consecutive=sentinel.consecutive,
                            )
                            if train_obs is not None:
                                train_obs.anomaly_skips = sentinel.total_skips
                            if verbose:
                                print(
                                    f"iter {it}: non-finite loss; update skipped "
                                    f"({sentinel.consecutive}/{sentinel.max_skips})"
                                )
                            continue
                    if sync_each:
                        losses.append(loss_val)
                        if verbose:
                            print(f"iter {it}: loss {loss_val:.4f}")
                    iter_ms = prof.iter_times_ms[-1] if prof.iter_times_ms else None
                    stat = (
                        stepstats.per_iter(
                            iter_ms, cur_bs,
                            nonpad_tokens=pipe_meta.get("nonpad_tokens"),
                        )
                        if metrics.path or train_obs is not None
                        else {}
                    )
                    if stat.get("comm_wait_ms") is not None:
                        step_sp.set(
                            comm_wait_ms=stat["comm_wait_ms"],
                            bubble_fraction=stat["bubble_fraction"],
                        )
                    # step-time drift vs the plan's prediction: the signed
                    # ratio the re-planner (ROADMAP item 2) and the drift
                    # SLO both consume
                    drift = (
                        (iter_ms - predicted_ms) / predicted_ms
                        if predicted_ms and iter_ms is not None
                        else None
                    )
                    if metrics.path:
                        metrics.log(
                            "train_iter", schema=SCHEMA_VERSION, step=it,
                            # a disarmed run can still diverge: bare NaN/Infinity
                            # is not valid JSON (same reason anomaly_skip
                            # stringifies), so non-finite losses log as strings
                            loss=(
                                loss_val
                                if loss_val is None or math.isfinite(loss_val)
                                else str(loss_val)
                            ),
                            batch_size=cur_bs,
                            iter_ms=iter_ms,
                            **stat,
                            **({"step_time_drift": round(drift, 4)}
                               if drift is not None else {}),
                        )
                    if train_slo is not None and drift is not None:
                        train_slo.observe_drift("step_time_drift", drift,
                                                step=it)
                    if train_obs is not None:
                        train_obs.iterations += 1
                        if loss_val is not None:
                            train_obs.last_loss = loss_val
                        if iter_ms is not None:
                            train_obs.last_iter_ms = iter_ms
                            train_obs.predicted_iter_ms = predicted_ms
                            train_obs.step_time_drift = drift
                            train_obs.tokens_per_s = stat.get("tokens_per_s")
                            train_obs.tflops_per_device = stat.get("tflops_per_device")
                            train_obs.mfu = stat.get("mfu")
                            train_obs.hfu = stat.get("hfu")
                            train_obs.comm_wait_ms = stat.get("comm_wait_ms")
                            train_obs.bubble_fraction = stat.get(
                                "bubble_fraction"
                            )
                            train_obs.packing_efficiency = stat.get(
                                "packing_efficiency"
                            )
                    if next_save_at is not None and (it + 1) >= next_save_at:
                        # dir name = the state's actual optimizer step: skipped
                        # iterations (this run's AND pre-crash ones) advanced
                        # `it` but not the state, and the exit-save dedup
                        # compares latest_step against it
                        actual_step = it + 1 - prior_skips - sentinel.total_skips
                        if wd is not None:
                            # the save legitimately outlasts a step deadline
                            # (large state, slow GCS); killed mid-commit it
                            # would deterministically repeat at this step
                            # until the restart budget ran out — same
                            # stand-down the exit save gets
                            wd.disarm()
                        # RAM tier first: the replica must exist BEFORE the
                        # disk commit so a storage outage (or a kill during
                        # the commit) still leaves this step recoverable
                        replicated = _push_replica(state, actual_step)
                        try:
                            save_checkpoint_portable(
                                ns.save, state, actual_step, rt,
                                keep_last_n=keep_n,
                                meta=_save_meta(),
                            )
                        except OSError as save_err:
                            if not replicated:
                                raise
                            # storage down but the peer replica landed: the
                            # run keeps training on the RAM tier alone and
                            # retries disk at the next due save / exit save
                            metrics.log(
                                "save_degraded_to_peer", step=actual_step,
                                error=str(save_err)[:300],
                            )
                            print(
                                f"warning: disk save at step {actual_step} "
                                f"failed ({save_err}); continuing on peer "
                                f"replica", flush=True,
                            )
                        else:
                            if train_obs is not None:
                                train_obs.checkpoints_saved += 1
                            if verbose:
                                print(f"saved step {actual_step} → {ns.save}")
                        next_save_at = (
                            (it + 1) // ns.save_interval + 1
                        ) * ns.save_interval
        prof.finish(loss if iters_run else None)
    except BaseException as e:
        train_exc = e
        raise
    finally:
        # the watchdog stands down FIRST: the exit checkpoint below can
        # legitimately outlast --step_timeout_s, and an armed deadline
        # firing mid-commit would turn a clean exit into a hang-coded kill
        if wd is not None:
            wd.close()
        # the prefetch thread stands down SECOND, on every exit path — a
        # producer blocked on its bounded queue must not sit on buffers (or
        # keep touching the corpus) while the exit checkpoint commits; the
        # end-of-run mixture/packing summary lands in the JSONL first
        if data_pipe is not None:
            try:
                metrics.log("data_pipeline", **data_pipe.summary(samples_done))
            except Exception:
                pass  # observability must not block the shutdown chain
            data_pipe.close()
        # always close the trace — an exception mid-loop must not lose the
        # captured data or wedge the process-wide profiler state. Guarded:
        # a stop_trace failure (e.g. flushing to broken storage) must not
        # rob the crash path of its emergency checkpoint below, nor mask
        # the original training exception
        if pw is not None:
            pw.close(verbose=verbose)
        if trace_started:
            try:
                jax.profiler.stop_trace()
                if verbose:
                    print(f"jax.profiler trace → {trace_dir}")
            except Exception as trace_err:
                print(f"failed to close jax.profiler trace: {trace_err!r}")
        # checkpoint on exit — normal completion, signal-stop (the
        # reference's dist_signal_handler checkpoint-then-exit pattern,
        # there unused), unhandled exception, or anomaly abort: every exit
        # mode lands a committed, resumable checkpoint
        try:
            # the save itself is collective on a multi-controller pod
            # (orbax write + commit barrier), so it is only safe when every
            # process reaches this path with the same verdict: normal
            # completion, signal-stop (preemption signals all hosts), and
            # AnomalyAbort (decided on the globally-reduced loss) are
            # replicated; an arbitrary exception may be host-local (one
            # host's dataloader shard failing), and entering the collective
            # save alone would hang inside this finally with the traceback
            # never printed. There the exception surfaces instead.
            replicated_exit = (
                train_exc is None
                or isinstance(train_exc, AnomalyAbort)
                or jax.process_count() == 1
            )
            if ns.save and not replicated_exit:
                print(
                    "skipping exit checkpoint: exception on a multi-host run "
                    "may be host-local and the save is collective"
                )
            if ns.save and replicated_exit:
                final_step = int(np.asarray(state["step"]))
                batches_now = batch_offset + iters_run
                # dedup on step AND stream position: trailing anomaly-skipped
                # batches advance batches_consumed without advancing the
                # optimizer step, and skipping the re-save would leave the
                # committed meta stale — resume would then replay the skipped
                # batches (deterministically poisoned data could loop the
                # skip budget on every restart instead of progressing)
                already_committed = latest_step(ns.save) == final_step
                if already_committed:
                    m = read_manifest(step_path(ns.save, final_step))
                    meta = m.get("meta") if m else None
                    already_committed = isinstance(meta, dict) and int(
                        meta.get("batches_consumed", -1)
                    ) == batches_now
                if not already_committed:
                    # push the RAM tier before the disk commit: if the disk
                    # exit save raises (storage still out during a drain),
                    # the peer replica carries the final step into the next
                    # incarnation
                    _push_replica(state, final_step)
                    save_checkpoint_portable(
                        ns.save, state, final_step, rt, keep_last_n=keep_n,
                        meta=_save_meta(),
                    )
                if train_exc is not None:
                    # the event fires even when the write was skipped (e.g.
                    # an anomaly abort whose last-good state an interval
                    # save already committed) — the operator signal is the
                    # exceptional exit, not the redundant write
                    metrics.log(
                        "emergency_save", step=final_step,
                        already_committed=already_committed,
                        reason=f"{type(train_exc).__name__}: "
                               f"{str(train_exc)[:200]}",
                    )
                    print(f"emergency checkpoint step {final_step} → {ns.save}")
                elif verbose and not already_committed:
                    print(f"saved step {final_step} → {ns.save}")
        except Exception as save_err:
            # best-effort: a failed exit save must not mask the original error
            print(f"exit checkpoint failed: {save_err!r}")
        finally:
            # crash runs flush their JSONL tail too
            metrics.close()
        # observability teardown: flight dump on exceptional exits, span
        # export, sidecar shutdown — all best-effort, never masking the
        # original exception (the emergency checkpoint above already ran)
        try:
            _export_obs_artifacts(
                ns, tracer, train_exc,
                extra={"iter": batch_offset + iters_run}, verbose=verbose,
            )
        finally:
            if obs_server is not None:
                obs_server.close()
            if train_slo is not None:
                train_slo.close()
            if tracer_owned:
                # this run turned tracing on; turn it off (and drop the
                # ring) so spans cannot leak into a later run in-process
                tracer.disable()
                tracer.clear()
    # throughput from actual samples processed (rampup runs at smaller sizes)
    avg_bs = (consumed - consumed_at_start) / iters_run if iters_run else 0
    # cost-model fidelity: predicted-vs-measured iteration time when training
    # the searched strategy at its searched batch size (SURVEY §6);
    # predicted_ms was resolved once before the loop — the per-iter drift
    # gauge/SLO and this report read the same anchor
    report = (
        prof.report(avg_bs, seq, predicted_ms=predicted_ms, step_stats=stepstats)
        if prof.iter_times_ms
        else ""
    )
    if verbose and report:
        print(report)
    return {
        "losses": losses,
        "iter_ms": prof.avg_iter_ms if prof.iter_times_ms else None,
        "state": state,
        # the elastic child maps this to EXIT_PREEMPTED: a signal-stop run
        # completed nothing abnormal, but the supervisor must restart it.
        # A notice-file drain (no signal delivered) reports its reason in
        # the same slot — the supervisor treats both as a preemption.
        "signaled": (
            exit_handler.signaled
            if exit_handler.signaled is not None
            else preempt_listener.reason
        ),
    }
