"""Preemption notices and degraded-mesh continuation arithmetic.

A spot/preemptible TPU slice does not just die — the platform delivers an
eviction *notice* with a grace window (SIGTERM plus a metadata-server flag
on GCE; here a pollable notice file stands in for the metadata server so
the sim world and tests can drive it). The trainer's job inside that
window is an *expedited replicated save* and a coordinated drain: finish
the in-flight step, push the replica to the peer store, commit to disk if
storage allows, and exit with the preempted code — the elastic supervisor
then restarts onto whatever capacity remains.

When the remaining capacity is SMALLER (a peer host was the thing
preempted), the run continues at reduced DP width through the existing
GTA017 re-plan + exact-cursor resume path instead of aborting. The one
invariant that must survive the shrink is the *global batch size* — the
optimizer trajectory is calibrated to it — so the lost data parallelism is
paid back in gradient accumulation: :func:`degraded_continuation` computes
the chunk (micro-batch) adjustment and enforces the ``--degraded_min_dp``
floor below which continuing is worse than waiting for capacity.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Optional

#: child-side env var: the supervisor's notice-file path (the trainer also
#: honors --preempt_notice_file; env lets the chaos harness arm it without
#: touching argv)
NOTICE_ENV = "GALVATRON_PREEMPT_NOTICE"


class PreemptionListener:
    """Latches a preemption notice from either delivery channel.

    - **SIGTERM** — observed through the trainer's existing
      :class:`~galvatron_tpu.core.signals.GracefulExitHandler` (passed in
      as ``exit_handler``), so signal disposition stays owned by one
      object.
    - **notice file** — a pollable path (``--preempt_notice_file`` /
      ``GALVATRON_PREEMPT_NOTICE``) standing in for the cloud metadata
      server; its *existence* is the notice. Polled at most once per
      ``poll_interval_s`` so the per-step cost is an ``os.path.exists``
      amortized to ~zero.

    Once noticed, ``deadline`` is ``notice_ts + grace_s``: the drain must
    finish the current step, replicate, save, and exit before it."""

    def __init__(self, exit_handler=None, notice_file: Optional[str] = None,
                 grace_s: float = 30.0, poll_interval_s: float = 0.25):
        self.exit_handler = exit_handler
        self.notice_file = notice_file or os.environ.get(NOTICE_ENV) or None
        self.grace_s = float(grace_s)
        self.poll_interval_s = float(poll_interval_s)
        self.notice_ts: Optional[float] = None
        self.reason: Optional[str] = None
        self._last_poll = 0.0

    @property
    def noticed(self) -> bool:
        return self.notice_ts is not None

    @property
    def deadline(self) -> Optional[float]:
        return None if self.notice_ts is None else self.notice_ts + self.grace_s

    def remaining_s(self) -> Optional[float]:
        d = self.deadline
        return None if d is None else max(0.0, d - time.monotonic())

    def check(self) -> Optional[str]:
        """Poll both channels; returns the latched reason (``"sigterm"`` |
        ``"notice"``) once a notice exists, else None. Idempotent after the
        first latch — the drain is triggered once."""
        if self.notice_ts is not None:
            return self.reason
        if self.exit_handler is not None and getattr(
            self.exit_handler, "signaled", None
        ) is not None:
            self.notice_ts = time.monotonic()
            self.reason = "sigterm"
            return self.reason
        if self.notice_file:
            now = time.monotonic()
            if now - self._last_poll >= self.poll_interval_s:
                self._last_poll = now
                if os.path.exists(self.notice_file):
                    self.notice_ts = now
                    self.reason = "notice"
                    return self.reason
        return None


@dataclasses.dataclass(frozen=True)
class DegradedPlan:
    """Outcome of the shrink arithmetic. ``feasible`` False carries the
    human-readable ``reason`` the supervisor's give-up message surfaces."""

    feasible: bool
    reason: str
    old_dp: int
    new_dp: int
    global_bsz: int
    #: per-step samples each surviving replica now owns
    per_replica_bsz: int = 0
    #: gradient-accumulation chunks after the adjustment
    new_chunks: int = 0
    #: micro-batch each chunk processes (per replica)
    micro_bsz: int = 0

    @property
    def accum_scale(self) -> float:
        """How much more sequential work each survivor does per step."""
        return self.old_dp / self.new_dp if self.new_dp else float("inf")


def degraded_continuation(old_dp: int, new_dp: int, global_bsz: int,
                          chunks: int = 1, min_dp: int = 1) -> DegradedPlan:
    """Shrink DP width ``old_dp → new_dp`` while PRESERVING the global
    batch (the optimizer trajectory's calibration) via gradient
    accumulation.

    Each surviving replica's per-step share grows from
    ``global_bsz/old_dp`` to ``global_bsz/new_dp``; the extra samples are
    taken as additional accumulation chunks, starting from the smallest
    chunk count ≥ the proportional scale-up that divides the new
    per-replica batch evenly (micro-batches must stay integral — XLA
    programs are shape-specialized). Infeasible when ``new_dp`` is below
    the ``min_dp`` floor (``--degraded_min_dp``: the operator's judgment
    that below this width waiting beats limping) or when ``global_bsz``
    does not divide over the survivors."""
    old_dp, new_dp = int(old_dp), int(new_dp)
    global_bsz, chunks, min_dp = int(global_bsz), max(1, int(chunks)), int(min_dp)
    if new_dp < 1:
        return DegradedPlan(False, "no surviving data-parallel replicas",
                            old_dp, new_dp, global_bsz)
    if new_dp < min_dp:
        return DegradedPlan(
            False,
            f"degraded DP width {new_dp} below --degraded_min_dp {min_dp}",
            old_dp, new_dp, global_bsz,
        )
    if global_bsz % new_dp:
        return DegradedPlan(
            False,
            f"global batch {global_bsz} not divisible by degraded DP width "
            f"{new_dp}",
            old_dp, new_dp, global_bsz,
        )
    per_replica = global_bsz // new_dp
    # proportional accumulation scale-up, then walk up to the next chunk
    # count that divides the per-replica batch evenly
    want = max(1, -(-chunks * old_dp // new_dp))  # ceil
    new_chunks = None
    for c in range(min(want, per_replica), per_replica + 1):
        if per_replica % c == 0:
            new_chunks = c
            break
    if new_chunks is None:  # per_replica >= 1 ⇒ c == per_replica always divides
        new_chunks = per_replica
    return DegradedPlan(
        True, "", old_dp, new_dp, global_bsz,
        per_replica_bsz=per_replica,
        new_chunks=new_chunks,
        micro_bsz=per_replica // new_chunks,
    )
