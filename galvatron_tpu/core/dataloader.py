"""Synthetic LM dataloader with data-parallel sharding.

The reference trains on synthetic random-token datasets per model family
(reference: models/llama_hf/dataloader.py:5-30 — random vocab tokens;
utils/training_utils.py:14-23 — DistributedSampler split over the dp group).
Here the dataloader yields global (B, S+1) int32 batches; sharding over the
mesh's data axes is applied by the runtime's batch sharding, so the loader
itself stays host-side and device-layout-agnostic.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np


class RandomTokenDataset:
    def __init__(self, vocab_size: int, seq_len: int, size: int = 1024, seed: int = 1234):
        self.vocab_size = vocab_size
        self.seq_len = seq_len
        self.size = size
        self.seed = seed

    def __len__(self) -> int:
        return self.size

    def batches_per_epoch(self, global_batch_size: int) -> int:
        return max(0, (self.size - global_batch_size) // global_batch_size + 1)

    def batch_iterator(
        self, global_batch_size: int, epochs: Optional[int] = None, start_batch: int = 0
    ) -> Iterator[np.ndarray]:
        """Yields (B, S+1) int32 token batches (inputs ‖ next-token labels).

        ``start_batch`` resumes mid-stream without materializing the skipped
        batches: batch contents depend only on (seed, epoch, position), so the
        offset is pure index arithmetic."""
        per_epoch = self.batches_per_epoch(global_batch_size)
        if per_epoch == 0:
            raise ValueError(
                f"global_batch_size {global_batch_size} exceeds dataset size "
                f"{self.size}; no full batch can be formed"
            )
        epoch, skip = divmod(start_batch, per_epoch)
        while epochs is None or epoch < epochs:
            rng = np.random.RandomState(self.seed + epoch)
            order = rng.permutation(self.size)
            start_i = skip * global_batch_size
            skip = 0
            for i in range(start_i, self.size - global_batch_size + 1, global_batch_size):
                idx = order[i : i + global_batch_size]
                batch_rng = np.random.RandomState(self.seed * 1000003 + int(idx[0]))
                yield batch_rng.randint(
                    0, self.vocab_size, (global_batch_size, self.seq_len + 1), np.int32
                )
            epoch += 1


def build_dataloader(cfg, global_batch_size: int, seq_len: Optional[int] = None,
                     size: int = 1024, seed: int = 1234, start_batch: int = 0,
                     data_path: Optional[str] = None):
    """``data_path`` selects the real-corpus path: a ``write_indexed_dataset``
    prefix is loaded memory-mapped and sampled GPT-window style
    (galvatron_tpu.core.data); otherwise the synthetic random-token stream."""
    seq_len = seq_len or cfg.max_seq_len
    if data_path:
        from galvatron_tpu.core.data import GPTWindowDataset, IndexedTokenDataset

        indexed = IndexedTokenDataset(data_path)
        if indexed.meta["vocab_size"] > cfg.vocab_size:
            raise ValueError(
                f"corpus vocab {indexed.meta['vocab_size']} exceeds the model "
                f"vocab {cfg.vocab_size}"
            )
        ds = GPTWindowDataset(indexed, seq_len, seed)
        return ds.batch_iterator(global_batch_size, start_batch=start_batch)
    ds = RandomTokenDataset(cfg.vocab_size, seq_len, size, seed)
    return ds.batch_iterator(global_batch_size, start_batch=start_batch)
