"""Synthetic LM dataloader with data-parallel sharding.

The reference trains on synthetic random-token datasets per model family
(reference: models/llama_hf/dataloader.py:5-30 — random vocab tokens;
utils/training_utils.py:14-23 — DistributedSampler split over the dp group).
Here the dataloader yields global (B, S+1) int32 batches; sharding over the
mesh's data axes is applied by the runtime's batch sharding, so the loader
itself stays host-side and device-layout-agnostic.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np


class _RandomStreamDataset:
    """Shared epoch/permutation/resume machinery for the synthetic streams.

    ``start_batch`` resumes mid-stream without materializing the skipped
    batches: batch contents depend only on (seed, epoch, position), so the
    offset is pure index arithmetic. Subclasses implement ``_sample_rows(ids)``
    → one (len(ids), sample_len+1) int32 batch keyed by SAMPLE identity.

    Two determinism fixes over the original implementation:

    - the per-epoch permutation is seeded from the MIXED ``(seed, epoch)``
      pair (``data_native.mix_seed``), not ``seed + epoch`` — the additive
      scheme aliased adjacent streams (``(seed=s, epoch=1)`` replayed
      ``(seed=s+1, epoch=0)``'s order exactly);
    - row contents are keyed by each row's sample index, not by the FIRST
      index of its batch — the old scheme generated the whole batch from
      ``idx[0]``, so the epoch permutation never actually permuted samples
      (every epoch trained epoch-0's multiset in a thin disguise) and the
      sample-domain cursor had no per-sample identity to be exact over.
      Epochs now reshuffle real per-sample rows."""

    def __init__(self, size: int = 1024, seed: int = 1234):
        self.size = size
        self.seed = seed

    def __len__(self) -> int:
        return self.size

    def batches_per_epoch(self, global_batch_size: int) -> int:
        return max(0, (self.size - global_batch_size) // global_batch_size + 1)

    def _sample_rows(self, ids: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def _row_hash(self, ids: np.ndarray, n_cols: int) -> np.ndarray:
        """(len(ids), n_cols) uint64 lattice of splitmix64(seed ⊕ cell id) —
        the vectorized per-sample content generator."""
        from galvatron_tpu.core.data_native import _splitmix64_np, mix_seed

        base = np.uint64(mix_seed(self.seed, 0xDA7A))
        with np.errstate(over="ignore"):
            cell = (
                np.asarray(ids, np.uint64)[:, None] * np.uint64(n_cols)
                + np.arange(n_cols, dtype=np.uint64)[None]
            )
            return _splitmix64_np(base ^ cell)

    def batch_iterator(
        self, global_batch_size: int, epochs: Optional[int] = None, start_batch: int = 0
    ) -> Iterator[np.ndarray]:
        from galvatron_tpu.core.data_native import mix_seed, shuffle_index

        per_epoch = self.batches_per_epoch(global_batch_size)
        if per_epoch == 0:
            raise ValueError(
                f"global_batch_size {global_batch_size} exceeds dataset size "
                f"{self.size}; no full batch can be formed"
            )
        epoch, skip = divmod(start_batch, per_epoch)
        while epochs is None or epoch < epochs:
            order = shuffle_index(self.size, mix_seed(self.seed, epoch))
            start_i = skip * global_batch_size
            skip = 0
            for i in range(start_i, self.size - global_batch_size + 1, global_batch_size):
                yield self._sample_rows(order[i : i + global_batch_size])
            epoch += 1


class RandomTokenDataset(_RandomStreamDataset):
    """(B, S+1) int32 token batches (inputs ‖ next-token labels)."""

    def __init__(self, vocab_size: int, seq_len: int, size: int = 1024, seed: int = 1234):
        super().__init__(size, seed)
        self.vocab_size = vocab_size
        self.seq_len = seq_len

    def _sample_rows(self, ids):
        h = self._row_hash(ids, self.seq_len + 1)
        return (h % np.uint64(self.vocab_size)).astype(np.int32)


class RandomImageDataset(_RandomStreamDataset):
    """Synthetic image-classification stream for the vision families: each
    row is (image_size²·channels) uint8 pixel values stored as int32 ‖ one
    class label — the same (B, sample_len+1) int32 contract the token loaders
    use, so batching/sharding/resume machinery is shared unchanged."""

    def __init__(self, n_pixels: int, num_classes: int, size: int = 1024, seed: int = 1234):
        super().__init__(size, seed)
        self.n_pixels = n_pixels
        self.num_classes = num_classes

    def _sample_rows(self, ids):
        h = self._row_hash(ids, self.n_pixels + 1)
        pixels = (h[:, : self.n_pixels] % np.uint64(256)).astype(np.int32)
        labels = (h[:, self.n_pixels :] % np.uint64(self.num_classes)).astype(np.int32)
        return np.concatenate([pixels, labels], axis=1)


def build_dataloader(cfg, global_batch_size: int, seq_len: Optional[int] = None,
                     size: int = 1024, seed: int = 1234, start_batch: int = 0,
                     data_path: Optional[str] = None):
    """``data_path`` selects the real-corpus path: a ``write_indexed_dataset``
    prefix is loaded memory-mapped and sampled GPT-window style
    (galvatron_tpu.core.data); otherwise the synthetic random-token stream."""
    if getattr(cfg, "image_size", 0):
        if data_path:
            raise ValueError("indexed token corpora do not apply to vision models")
        ds = RandomImageDataset(cfg.sample_len, cfg.num_classes, size, seed)
        return ds.batch_iterator(global_batch_size, start_batch=start_batch)
    seq_len = seq_len or cfg.max_seq_len
    if data_path:
        from galvatron_tpu.core.data import GPTWindowDataset, IndexedTokenDataset

        indexed = IndexedTokenDataset(data_path)
        if indexed.meta["vocab_size"] > cfg.vocab_size:
            raise ValueError(
                f"corpus vocab {indexed.meta['vocab_size']} exceeds the model "
                f"vocab {cfg.vocab_size}"
            )
        ds = GPTWindowDataset(indexed, seq_len, seed)
        return ds.batch_iterator(global_batch_size, start_batch=start_batch)
    ds = RandomTokenDataset(cfg.vocab_size, seq_len, size, seed)
    return ds.batch_iterator(global_batch_size, start_batch=start_batch)
