"""Synthetic LM dataloader with data-parallel sharding.

The reference trains on synthetic random-token datasets per model family
(reference: models/llama_hf/dataloader.py:5-30 — random vocab tokens;
utils/training_utils.py:14-23 — DistributedSampler split over the dp group).
Here the dataloader yields global (B, S+1) int32 batches; sharding over the
mesh's data axes is applied by the runtime's batch sharding, so the loader
itself stays host-side and device-layout-agnostic.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np


class _RandomStreamDataset:
    """Shared epoch/permutation/resume machinery for the synthetic streams.

    ``start_batch`` resumes mid-stream without materializing the skipped
    batches: batch contents depend only on (seed, epoch, position), so the
    offset is pure index arithmetic. Subclasses implement ``_sample(rng, B)``
    → one (B, sample_len+1) int32 batch."""

    def __init__(self, size: int = 1024, seed: int = 1234):
        self.size = size
        self.seed = seed

    def __len__(self) -> int:
        return self.size

    def batches_per_epoch(self, global_batch_size: int) -> int:
        return max(0, (self.size - global_batch_size) // global_batch_size + 1)

    def _sample(self, rng: np.random.RandomState, global_batch_size: int) -> np.ndarray:
        raise NotImplementedError

    def batch_iterator(
        self, global_batch_size: int, epochs: Optional[int] = None, start_batch: int = 0
    ) -> Iterator[np.ndarray]:
        per_epoch = self.batches_per_epoch(global_batch_size)
        if per_epoch == 0:
            raise ValueError(
                f"global_batch_size {global_batch_size} exceeds dataset size "
                f"{self.size}; no full batch can be formed"
            )
        epoch, skip = divmod(start_batch, per_epoch)
        while epochs is None or epoch < epochs:
            rng = np.random.RandomState(self.seed + epoch)
            order = rng.permutation(self.size)
            start_i = skip * global_batch_size
            skip = 0
            for i in range(start_i, self.size - global_batch_size + 1, global_batch_size):
                idx = order[i : i + global_batch_size]
                batch_rng = np.random.RandomState(self.seed * 1000003 + int(idx[0]))
                yield self._sample(batch_rng, global_batch_size)
            epoch += 1


class RandomTokenDataset(_RandomStreamDataset):
    """(B, S+1) int32 token batches (inputs ‖ next-token labels)."""

    def __init__(self, vocab_size: int, seq_len: int, size: int = 1024, seed: int = 1234):
        super().__init__(size, seed)
        self.vocab_size = vocab_size
        self.seq_len = seq_len

    def _sample(self, rng, global_batch_size):
        return rng.randint(
            0, self.vocab_size, (global_batch_size, self.seq_len + 1), np.int32
        )


class RandomImageDataset(_RandomStreamDataset):
    """Synthetic image-classification stream for the vision families: each
    row is (image_size²·channels) uint8 pixel values stored as int32 ‖ one
    class label — the same (B, sample_len+1) int32 contract the token loaders
    use, so batching/sharding/resume machinery is shared unchanged."""

    def __init__(self, n_pixels: int, num_classes: int, size: int = 1024, seed: int = 1234):
        super().__init__(size, seed)
        self.n_pixels = n_pixels
        self.num_classes = num_classes

    def _sample(self, rng, global_batch_size):
        pixels = rng.randint(0, 256, (global_batch_size, self.n_pixels), np.int32)
        labels = rng.randint(0, self.num_classes, (global_batch_size, 1), np.int32)
        return np.concatenate([pixels, labels], axis=1)


def build_dataloader(cfg, global_batch_size: int, seq_len: Optional[int] = None,
                     size: int = 1024, seed: int = 1234, start_batch: int = 0,
                     data_path: Optional[str] = None):
    """``data_path`` selects the real-corpus path: a ``write_indexed_dataset``
    prefix is loaded memory-mapped and sampled GPT-window style
    (galvatron_tpu.core.data); otherwise the synthetic random-token stream."""
    if getattr(cfg, "image_size", 0):
        if data_path:
            raise ValueError("indexed token corpora do not apply to vision models")
        ds = RandomImageDataset(cfg.sample_len, cfg.num_classes, size, seed)
        return ds.batch_iterator(global_batch_size, start_batch=start_batch)
    seq_len = seq_len or cfg.max_seq_len
    if data_path:
        from galvatron_tpu.core.data import GPTWindowDataset, IndexedTokenDataset

        indexed = IndexedTokenDataset(data_path)
        if indexed.meta["vocab_size"] > cfg.vocab_size:
            raise ValueError(
                f"corpus vocab {indexed.meta['vocab_size']} exceeds the model "
                f"vocab {cfg.vocab_size}"
            )
        ds = GPTWindowDataset(indexed, seq_len, seed)
        return ds.batch_iterator(global_batch_size, start_batch=start_batch)
    ds = RandomTokenDataset(cfg.vocab_size, seq_len, size, seed)
    return ds.batch_iterator(global_batch_size, start_batch=start_batch)
