"""Graceful-exit signal handling (failure detection).

Counterpart of the vendored Megatron ``dist_signal_handler.py`` (reference:
site_package/megatron/dist_signal_handler.py:1-81 — SIGTERM caught on every
rank, all-gathered so all ranks agree, then checkpoint + exit; carried but
unused by the reference's own trainer, SURVEY §5 "failure detection: none").

Here the trainer polls ``handler.signaled`` at iteration boundaries and
checkpoints before exiting. Under multi-controller JAX each host process
installs its own handler; the decision is host-local (a SIGTERM'd host stops
fetching work, which stalls collectives — preemption on TPU pods delivers the
signal to every host simultaneously, so in practice all hosts agree).
"""

from __future__ import annotations

import signal
from types import FrameType
from typing import List, Optional


class GracefulExitHandler:
    """Context manager latching SIGTERM/SIGINT; restores prior handlers on
    exit. Second SIGINT falls through to the default handler (hard Ctrl-C)."""

    def __init__(self, signals: Optional[List[int]] = None):
        self.signals = signals or [signal.SIGTERM, signal.SIGINT]
        self.signaled: Optional[int] = None
        self._prev = {}

    def _handle(self, signum: int, frame: Optional[FrameType]):
        if self.signaled is not None and signum == signal.SIGINT:
            # second Ctrl-C: restore and re-raise for an immediate stop
            signal.signal(signum, self._prev.get(signum, signal.SIG_DFL))
            raise KeyboardInterrupt
        self.signaled = signum

    def __enter__(self) -> "GracefulExitHandler":
        for s in self.signals:
            try:
                self._prev[s] = signal.signal(s, self._handle)
            except (ValueError, OSError):
                # non-main thread or unsupported signal: degrade to no-op
                pass
        return self

    def __exit__(self, *exc):
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, OSError):
                pass
        return False
