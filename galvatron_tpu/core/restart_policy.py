"""Shared restart/backoff/give-up decision policy for every supervisor.

Three supervisors make the same decision after a failure — the elastic
training supervisor (`core/elastic.py`, child process exits), the in-process
serving engine supervisor (`serving/resilience.py`, decode-loop crashes),
and the fleet router's per-replica supervision (`serving/fleet.py`, replica
process deaths). The decision table is identical in all three::

    ==================================    =====================================
    condition                             decision
    ==================================    =====================================
    failure, progress since last one      restart (budget resets — progress)
    failure, no progress, budget left     restart after full-jitter backoff
    failure, no progress, budget spent    give up
    ==================================    =====================================

"Progress" is supervisor-defined (a newer committed checkpoint step, a
completed request, a completed dispatch); what is shared is the *budget
arithmetic*: the give-up bound counts CONSECUTIVE failures without
progress — a progressed failure resets the streak to 1, never to 0 (the
failure itself still counts), so ``max_restarts`` no-progress failures in
a row exhaust the budget regardless of how long the run has been healthy.
Backoff rides `core/retry.py`'s full-jitter schedule (uniform in
``[0, base·2^n]`` capped), indexed by the no-progress streak; callers with
a reason to skip the wait (elastic's preempted-save children checkpointed
and *expect* to be rerun) pass ``immediate=True`` — the failure still
counts against the budget, only the sleep is skipped.

This module is pure decision arithmetic: no sleeping, no process control,
no engine surgery — callers act on the returned :class:`RestartDecision`.
"""

from __future__ import annotations

import dataclasses

from galvatron_tpu.core.retry import RetryPolicy


@dataclasses.dataclass(frozen=True)
class RestartDecision:
    """One supervisor decision: restart (after ``backoff_s``) or give up."""

    give_up: bool
    consecutive: int  # no-progress failure streak INCLUDING this failure
    backoff_s: float  # sleep before the restart (0.0 on give-up/immediate)

    @property
    def restart(self) -> bool:
        return not self.give_up


class RestartPolicy:
    """The shared decision table, stateful over one supervised lifetime.

    ``max_restarts`` bounds consecutive no-progress restarts: the
    ``max_restarts + 1``-th no-progress failure in a row is a give-up.
    ``max_restarts=0`` gives up on the first failure regardless of progress
    (the streak resets to 1, never 0 — a zero budget supervises nothing).
    """

    def __init__(self, max_restarts: int = 3, backoff_s: float = 1.0,
                 backoff_cap_s: float = 60.0, jitter: str = "full"):
        self.max_restarts = max(0, int(max_restarts))
        self.retry = RetryPolicy(
            attempts=self.max_restarts + 1,
            base_delay_s=float(backoff_s),
            max_delay_s=float(backoff_cap_s),
            jitter=jitter,
        )
        self.consecutive = 0  # failures since the last progressed failure

    def on_failure(self, progressed: bool, immediate: bool = False,
                   free: bool = False) -> RestartDecision:
        """Record one failure and decide. ``progressed`` = supervisor-level
        progress happened since the previous failure (resets the streak to
        1); ``immediate`` skips the backoff sleep but still counts the
        failure against the budget.

        ``free`` (preemption-aware supervisors): a failure that is the
        platform's EXPECTED lifecycle — a graceful preemption whose child
        checkpointed and made progress — does not consume budget at all
        (streak resets to 0, no backoff). A fleet living on spot capacity
        can be preempted more than ``max_restarts`` times in a healthy
        week; only preemptions WITHOUT progress keep counting, so a
        preempt-loop that never advances still exhausts the budget."""
        if free and progressed:
            self.consecutive = 0
            return RestartDecision(False, 0, 0.0)
        self.consecutive = 1 if progressed else self.consecutive + 1
        if self.consecutive > self.max_restarts:
            return RestartDecision(True, self.consecutive, 0.0)
        delay = 0.0 if immediate else self.retry.delay(
            min(self.consecutive - 1, self.retry.attempts - 1)
        )
        return RestartDecision(False, self.consecutive, delay)

    def reset(self) -> None:
        """Forget the streak (supervised entity replaced wholesale)."""
        self.consecutive = 0
