"""Minimal AdamW with explicit, shardable state.

The reference trains with ``torch.optim.Adam`` over FSDP-flattened params
(models/llama_hf/train_dist.py:53); ZeRO-2 shards optimizer state via FSDP
SHARD_GRAD_OP. Here the optimizer state is a plain pytree ``{mu, nu, count}``
mirroring the param tree, so ZeRO-style sharding is just a sharding spec on
the moment trees (galvatron_tpu.parallel.sharding.param_spec with
``for_opt_state=True``) — GSPMD then emits the reduce-scatter(grad) /
sharded-update / all-gather(param) pattern ZeRO hand-implements.

A hand-rolled optimizer (rather than optax) keeps the state structure
transparent for per-leaf sharding and for the search engine's memory cost
model (4×param model states, reference: galvatron/core/cost_model.py:31).

With ``HybridParallelConfig.grad_overlap`` on, ZeRO-2/3 gradients arrive
here already reduce-scattered per layer: sharding.overlap_grad_sync pins
each layer's gradient cotangent to the opt-state spec during backward, so
XLA issues the reduce-scatter as soon as that layer's backward finishes
instead of in one trailing block. Nothing in this module changes — the
update math is elementwise and sharding-agnostic; only the timing of the
collectives moves.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp


class AdamConfig(NamedTuple):
    lr: float = 1e-4
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 0.0
    grad_clip: Optional[float] = 1.0
    # optional LRSchedule (galvatron_tpu.core.schedules); when set, the
    # effective lr is lr_schedule(step) — evaluated inside the jitted update
    # from the optimizer step count, so one compiled train_step serves the
    # whole schedule (reference: megatron lr-decay flags, SURVEY §2.6)
    lr_schedule: Optional[Any] = None


def init_opt_state(params) -> Dict[str, Any]:
    zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
    return {
        "mu": jax.tree.map(zeros, params),
        "nu": jax.tree.map(zeros, params),
        "count": jnp.zeros((), jnp.int32),
    }


def global_norm(tree) -> jax.Array:
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in jax.tree.leaves(tree))
    )


def apply_update_with_scaler(state, loss, grads, adam: "AdamConfig", scaler_cfg):
    """fp16 train-state transition: AdamW update skipped atomically on
    gradient overflow, dynamic loss scale advanced (reference:
    site_package/megatron/optimizer/grad_scaler.py DynamicGradScaler +
    the skipped-iteration handling in megatron optimizer step).

    ``grads`` must already be unscaled. ``state`` carries a ``scaler`` entry
    from ``galvatron_tpu.core.schedules.init_scaler_state``.
    """
    import jax.numpy as jnp  # noqa: F811 — keep local for clarity

    from galvatron_tpu.core.schedules import all_finite, scaler_update

    finite = all_finite(grads) & jnp.isfinite(loss)
    new_params, new_opt = adamw_update(state["params"], grads, state["opt"], adam)
    select = lambda new, old: jax.tree.map(lambda a, b: jnp.where(finite, a, b), new, old)
    return {
        "params": select(new_params, state["params"]),
        "opt": select(new_opt, state["opt"]),  # count advances only on clean steps
        "step": state["step"] + 1,
        "scaler": scaler_update(state["scaler"], finite, scaler_cfg),
    }, loss


def adamw_update(params, grads, opt_state, cfg: AdamConfig, lr_scale=1.0):
    """One AdamW step in fp32 master precision; returns (params, opt_state)."""
    count = opt_state["count"] + 1
    if cfg.grad_clip is not None:
        gn = global_norm(grads)
        scale = jnp.minimum(1.0, cfg.grad_clip / jnp.maximum(gn, 1e-12))
        grads = jax.tree.map(lambda g: g.astype(jnp.float32) * scale, grads)
    else:
        grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
    b1c = 1.0 - cfg.b1 ** count.astype(jnp.float32)
    b2c = 1.0 - cfg.b2 ** count.astype(jnp.float32)
    mu = jax.tree.map(lambda m, g: cfg.b1 * m + (1 - cfg.b1) * g, opt_state["mu"], grads)
    nu = jax.tree.map(lambda v, g: cfg.b2 * v + (1 - cfg.b2) * g * g, opt_state["nu"], grads)
    if cfg.lr_schedule is not None:
        # 0-based step index = count before this update's increment
        lr = cfg.lr_schedule(count.astype(jnp.float32) - 1.0) * lr_scale
    else:
        lr = cfg.lr * lr_scale

    def upd(p, m, v):
        step = lr * (m / b1c) / (jnp.sqrt(v / b2c) + cfg.eps)
        if cfg.weight_decay:
            step = step + lr * cfg.weight_decay * p.astype(jnp.float32)
        return (p.astype(jnp.float32) - step).astype(p.dtype)

    new_params = jax.tree.map(upd, params, mu, nu)
    return new_params, {"mu": mu, "nu": nu, "count": count}
