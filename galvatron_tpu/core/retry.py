"""Retry with exponential backoff for transient I/O.

Shared by checkpoint save/restore, the indexed-corpus reads and HF weight
loading: on TPU pods the checkpoint/corpus filesystem is network-attached
(GCS fuse, NFS), where transient ``OSError``s are routine and a single
failed read should not kill a multi-hour run. Deliberately I/O-scoped:
only exceptions in ``policy.retryable`` (default ``OSError``) are retried;
everything else — including corruption, structure mismatches, and the
deterministic ``OSError`` subclasses in ``policy.non_retryable``
(missing path, permission denied), which retrying cannot fix —
propagates immediately.

Backoff delays carry **full jitter** (AWS architecture-blog sense: each
delay is uniform in ``[0, base·backoff^n]``, capped). A deterministic
schedule synchronizes every host in a pod: after a shared storage blip all
N hosts retry at exactly base, then exactly 2·base, ... — a thundering
herd that re-creates the overload it is backing off from on NFS/GCS.
``jitter="none"`` restores the deterministic schedule for callers that
need reproducible timing.

Every attempt first passes through :func:`faults.maybe_fail_io`, so any
retry-protected site is automatically a fault-injection point for the
``fail_io=N`` fault (tests/test_resilience.py proves the ride-through and
pins the jitter bounds).
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Any, Callable, Optional, Tuple, Type

from galvatron_tpu.core import faults


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    # defaults sized for the stated purpose — riding out routine
    # network-filesystem stalls on multi-hour pod runs: 5 attempts with
    # 0.2/0.4/0.8/1.6s backoff ≈ 3s of ride-through (a 3-attempt/0.15s
    # window would lose the run to any sub-second GCS-fuse/NFS blip)
    attempts: int = 5
    base_delay_s: float = 0.2
    max_delay_s: float = 10.0
    backoff: float = 2.0
    retryable: Tuple[Type[BaseException], ...] = (OSError,)
    # deterministic OSError subclasses retrying can never fix: a typo'd path
    # or a permission problem must surface as itself on the first attempt,
    # not as a "failed after 3 attempts" transient-I/O exhaustion
    non_retryable: Tuple[Type[BaseException], ...] = (
        FileNotFoundError,
        PermissionError,
        IsADirectoryError,
        NotADirectoryError,
    )
    # "full" (default): uniform in [0, capped exponential] — decorrelates
    # the hosts of a pod retrying the same shared-storage fault; "none":
    # the old deterministic schedule (reproducible-timing callers only)
    jitter: str = "full"

    def __post_init__(self):
        if self.jitter not in ("full", "none"):
            raise ValueError(f"jitter must be 'full' or 'none', got {self.jitter!r}")

    def max_delay(self, attempt: int) -> float:
        """Deterministic ceiling for retry ``attempt`` (0-based):
        min(max_delay_s, base * backoff^n) — the jitter's upper bound."""
        return min(self.max_delay_s, self.base_delay_s * self.backoff ** attempt)

    def delay(self, attempt: int, rng=random) -> float:
        """Backoff before retry ``attempt``: full jitter draws uniformly
        from [0, :meth:`max_delay`]; ``jitter='none'`` returns the ceiling
        itself. ``rng`` (anything with ``.uniform``) is injectable so tests
        can pin the distribution."""
        cap = self.max_delay(attempt)
        if self.jitter == "none" or cap <= 0:
            return cap
        return rng.uniform(0.0, cap)


def with_retries(
    fn: Callable[[], Any],
    policy: Optional[RetryPolicy] = None,
    describe: str = "",
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
) -> Any:
    """Run ``fn()`` with up to ``policy.attempts`` tries; exponential backoff
    between tries; the final failure propagates with the attempt count noted
    via exception note (non-retryable exceptions propagate immediately)."""
    policy = policy or RetryPolicy()
    last: Optional[BaseException] = None
    for attempt in range(policy.attempts):
        try:
            faults.maybe_fail_io(describe)
            return fn()
        except policy.retryable as e:
            if isinstance(e, policy.non_retryable):
                raise
            last = e
            if attempt + 1 >= policy.attempts:
                break
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(policy.delay(attempt))
    assert last is not None
    if hasattr(last, "add_note"):  # 3.11+
        last.add_note(
            f"({describe or 'operation'} failed after {policy.attempts} attempts)"
        )
    raise last
