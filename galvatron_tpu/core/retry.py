"""Retry with exponential backoff for transient I/O.

Shared by checkpoint save/restore, the indexed-corpus reads and HF weight
loading: on TPU pods the checkpoint/corpus filesystem is network-attached
(GCS fuse, NFS), where transient ``OSError``s are routine and a single
failed read should not kill a multi-hour run. Deliberately I/O-scoped:
only exceptions in ``policy.retryable`` (default ``OSError``) are retried;
everything else — including corruption, structure mismatches, and the
deterministic ``OSError`` subclasses in ``policy.non_retryable``
(missing path, permission denied), which retrying cannot fix —
propagates immediately.

Backoff delays carry **full jitter** (AWS architecture-blog sense: each
delay is uniform in ``[0, base·backoff^n]``, capped). A deterministic
schedule synchronizes every host in a pod: after a shared storage blip all
N hosts retry at exactly base, then exactly 2·base, ... — a thundering
herd that re-creates the overload it is backing off from on NFS/GCS.
``jitter="none"`` restores the deterministic schedule for callers that
need reproducible timing.

Every attempt first passes through :func:`faults.maybe_fail_io`, so any
retry-protected site is automatically a fault-injection point for the
``fail_io=N`` fault (tests/test_resilience.py proves the ride-through and
pins the jitter bounds).

Two observability/bounding layers ride every call:

- **retry budget** — ``max_elapsed_s`` caps the *wall-clock* a single call
  may spend retrying (attempt count alone is a poor bound once backoff
  grows: 5 attempts at a 10s cap can hold a preemption drain hostage for
  40s). When the budget cannot cover the next backoff, the call gives up
  early with the elapsed time noted.
- **counters** — module-level :data:`RETRY_COUNTERS` (utils/metrics.py
  ``Counters``) accumulate ``io_retry`` (every retried attempt) and
  ``io_give_up`` (every exhausted call) process-wide; the /metrics
  endpoint (obs/prom.py) exports them, so storage flakiness is visible as
  a rising retry rate *before* it becomes an outage.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Any, Callable, Optional, Tuple, Type

from galvatron_tpu.core import faults
from galvatron_tpu.utils.metrics import Counters

#: process-wide transient-I/O retry telemetry, exported on /metrics
RETRY_COUNTERS = Counters("io_retry", "io_give_up")


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    # defaults sized for the stated purpose — riding out routine
    # network-filesystem stalls on multi-hour pod runs: 5 attempts with
    # 0.2/0.4/0.8/1.6s backoff ≈ 3s of ride-through (a 3-attempt/0.15s
    # window would lose the run to any sub-second GCS-fuse/NFS blip)
    attempts: int = 5
    base_delay_s: float = 0.2
    max_delay_s: float = 10.0
    backoff: float = 2.0
    retryable: Tuple[Type[BaseException], ...] = (OSError,)
    # deterministic OSError subclasses retrying can never fix: a typo'd path
    # or a permission problem must surface as itself on the first attempt,
    # not as a "failed after 3 attempts" transient-I/O exhaustion
    non_retryable: Tuple[Type[BaseException], ...] = (
        FileNotFoundError,
        PermissionError,
        IsADirectoryError,
        NotADirectoryError,
    )
    # "full" (default): uniform in [0, capped exponential] — decorrelates
    # the hosts of a pod retrying the same shared-storage fault; "none":
    # the old deterministic schedule (reproducible-timing callers only)
    jitter: str = "full"
    # per-call wall-clock retry budget (seconds); None = bounded by attempt
    # count only. A preemption drain with 30s of grace cannot afford a
    # retry loop whose backoff alone can exceed it.
    max_elapsed_s: Optional[float] = None

    def __post_init__(self):
        if self.jitter not in ("full", "none"):
            raise ValueError(f"jitter must be 'full' or 'none', got {self.jitter!r}")

    def max_delay(self, attempt: int) -> float:
        """Deterministic ceiling for retry ``attempt`` (0-based):
        min(max_delay_s, base * backoff^n) — the jitter's upper bound."""
        return min(self.max_delay_s, self.base_delay_s * self.backoff ** attempt)

    def delay(self, attempt: int, rng=random) -> float:
        """Backoff before retry ``attempt``: full jitter draws uniformly
        from [0, :meth:`max_delay`]; ``jitter='none'`` returns the ceiling
        itself. ``rng`` (anything with ``.uniform``) is injectable so tests
        can pin the distribution."""
        cap = self.max_delay(attempt)
        if self.jitter == "none" or cap <= 0:
            return cap
        return rng.uniform(0.0, cap)


def with_retries(
    fn: Callable[[], Any],
    policy: Optional[RetryPolicy] = None,
    describe: str = "",
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
) -> Any:
    """Run ``fn()`` with up to ``policy.attempts`` tries; exponential backoff
    between tries; the final failure propagates with the attempt count noted
    via exception note (non-retryable exceptions propagate immediately)."""
    policy = policy or RetryPolicy()
    last: Optional[BaseException] = None
    start = time.monotonic()
    attempts_made = 0
    for attempt in range(policy.attempts):
        try:
            faults.maybe_fail_io(describe)
            return fn()
        except policy.retryable as e:
            if isinstance(e, policy.non_retryable):
                raise
            last = e
            attempts_made = attempt + 1
            if attempts_made >= policy.attempts:
                break
            delay = policy.delay(attempt)
            if policy.max_elapsed_s is not None and (
                time.monotonic() - start + delay > policy.max_elapsed_s
            ):
                # the budget cannot cover the next backoff: give up now
                # rather than blow the caller's deadline sleeping
                break
            RETRY_COUNTERS.inc("io_retry")
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(delay)
    assert last is not None
    RETRY_COUNTERS.inc("io_give_up")
    if hasattr(last, "add_note"):  # 3.11+
        last.add_note(
            f"({describe or 'operation'} failed after {attempts_made} "
            f"attempt(s) in {time.monotonic() - start:.2f}s"
            + (f", retry budget {policy.max_elapsed_s}s" if policy.max_elapsed_s is not None else "")
            + ")"
        )
    raise last
