"""Retry with exponential backoff for transient I/O.

Shared by checkpoint save/restore, the indexed-corpus reads and HF weight
loading: on TPU pods the checkpoint/corpus filesystem is network-attached
(GCS fuse, NFS), where transient ``OSError``s are routine and a single
failed read should not kill a multi-hour run. Deliberately I/O-scoped:
only exceptions in ``policy.retryable`` (default ``OSError``) are retried;
everything else — including corruption, structure mismatches, and the
deterministic ``OSError`` subclasses in ``policy.non_retryable``
(missing path, permission denied), which retrying cannot fix —
propagates immediately.

Every attempt first passes through :func:`faults.maybe_fail_io`, so any
retry-protected site is automatically a fault-injection point for the
``fail_io=N`` fault (tests/test_resilience.py proves the ride-through).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Optional, Tuple, Type

from galvatron_tpu.core import faults


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    # defaults sized for the stated purpose — riding out routine
    # network-filesystem stalls on multi-hour pod runs: 5 attempts with
    # 0.2/0.4/0.8/1.6s backoff ≈ 3s of ride-through (a 3-attempt/0.15s
    # window would lose the run to any sub-second GCS-fuse/NFS blip)
    attempts: int = 5
    base_delay_s: float = 0.2
    max_delay_s: float = 10.0
    backoff: float = 2.0
    retryable: Tuple[Type[BaseException], ...] = (OSError,)
    # deterministic OSError subclasses retrying can never fix: a typo'd path
    # or a permission problem must surface as itself on the first attempt,
    # not as a "failed after 3 attempts" transient-I/O exhaustion
    non_retryable: Tuple[Type[BaseException], ...] = (
        FileNotFoundError,
        PermissionError,
        IsADirectoryError,
        NotADirectoryError,
    )

    def delay(self, attempt: int) -> float:
        """Backoff before retry ``attempt`` (0-based): base * backoff^n."""
        return min(self.max_delay_s, self.base_delay_s * self.backoff ** attempt)


def with_retries(
    fn: Callable[[], Any],
    policy: Optional[RetryPolicy] = None,
    describe: str = "",
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
) -> Any:
    """Run ``fn()`` with up to ``policy.attempts`` tries; exponential backoff
    between tries; the final failure propagates with the attempt count noted
    via exception note (non-retryable exceptions propagate immediately)."""
    policy = policy or RetryPolicy()
    last: Optional[BaseException] = None
    for attempt in range(policy.attempts):
        try:
            faults.maybe_fail_io(describe)
            return fn()
        except policy.retryable as e:
            if isinstance(e, policy.non_retryable):
                raise
            last = e
            if attempt + 1 >= policy.attempts:
                break
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(policy.delay(attempt))
    assert last is not None
    if hasattr(last, "add_note"):  # 3.11+
        last.add_note(
            f"({describe or 'operation'} failed after {policy.attempts} attempts)"
        )
    raise last
