"""Indexed memory-mapped token datasets — real-data training pipeline.

TPU-native counterpart of the vendored Megatron ``data/`` subsystem the
reference carries but never wires into Galvatron's trainer (SURVEY §2.6: its
live dataloaders are synthetic random tokens, models/llama_hf/dataloader.py:
5-30; megatron ships indexed_dataset/gpt_dataset for real corpora). Design:

- On-disk format: ``<prefix>.bin`` — the flat token stream (little-endian,
  uint16 when the vocab fits, else int32); ``<prefix>.idx.json`` — dtype,
  document offsets, token count. The ``.bin`` is memory-mapped; no tokens are
  resident until touched, so corpus size is bounded by disk, not host RAM
  (megatron's indexed_dataset contract).
- ``GPTWindowDataset`` — GPT-style LM sampling: documents concatenated into
  one stream, fixed (seq_len+1)-token windows (stride seq_len so each label
  is trained exactly once), per-epoch shuffle of window order, and O(1)
  deterministic resume by batch index (same contract as the synthetic
  RandomTokenDataset, so trainer resume logic is loader-agnostic).
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Iterator, List, Optional, Sequence

import numpy as np


def write_indexed_dataset(
    prefix: str, docs: Iterable[Sequence[int]], vocab_size: int
) -> dict:
    """Build ``<prefix>.bin`` + ``<prefix>.idx.json`` from an iterable of
    token-id documents (the preprocess_data.py role in megatron)."""
    dtype = np.uint16 if vocab_size <= np.iinfo(np.uint16).max + 1 else np.int32
    offsets: List[int] = [0]
    total = 0
    with open(prefix + ".bin", "wb") as f:
        for doc in docs:
            arr = np.asarray(doc, dtype=dtype)
            if arr.size and (arr.max() >= vocab_size or arr.min() < 0):
                raise ValueError(
                    f"document contains token ids outside [0, {vocab_size})"
                )
            arr.tofile(f)
            total += arr.size
            offsets.append(total)
    meta = {
        "dtype": np.dtype(dtype).name,
        "vocab_size": vocab_size,
        "num_tokens": total,
        "doc_offsets": offsets,
        "version": 1,
    }
    with open(prefix + ".idx.json", "w") as f:
        json.dump(meta, f)
    return meta


def tokenize_text_file(
    prefix: str, text_path: str, tokenizer, vocab_size: Optional[int] = None
) -> dict:
    """Encode a newline-delimited text file into the indexed format using a
    galvatron_tpu tokenizer (ByteTokenizer / HFTokenizer)."""

    def docs():
        with open(text_path) as f:
            for line in f:
                line = line.strip()
                if line:
                    yield tokenizer.encode(line)

    return write_indexed_dataset(prefix, docs(), vocab_size or tokenizer.vocab_size)


class IndexedTokenDataset:
    """Memory-mapped view of a ``write_indexed_dataset`` corpus."""

    def __init__(self, prefix: str):
        from galvatron_tpu.core.retry import with_retries

        idx_path = prefix + ".idx.json"
        if not os.path.exists(idx_path):
            raise FileNotFoundError(
                f"{idx_path} not found — build the corpus with "
                "write_indexed_dataset / tokenize_text_file first"
            )

        def read_meta():
            with open(idx_path) as f:
                return json.load(f)

        # corpus lives on network storage on pods: transient read errors are
        # retried with backoff instead of killing the run (core/retry.py)
        self.meta = with_retries(read_meta, describe=f"read {idx_path}")
        self.dtype = np.dtype(self.meta["dtype"])
        self.tokens = with_retries(
            lambda: np.memmap(prefix + ".bin", dtype=self.dtype, mode="r"),
            describe=f"map {prefix}.bin",
        )
        if self.tokens.size != self.meta["num_tokens"]:
            raise ValueError(
                f"{prefix}.bin has {self.tokens.size} tokens but the index "
                f"records {self.meta['num_tokens']} (corrupt or mismatched pair)"
            )
        self.doc_offsets = np.asarray(self.meta["doc_offsets"], np.int64)

    @property
    def num_docs(self) -> int:
        return len(self.doc_offsets) - 1

    @property
    def num_tokens(self) -> int:
        return int(self.meta["num_tokens"])

    def doc(self, i: int) -> np.ndarray:
        return np.asarray(self.tokens[self.doc_offsets[i] : self.doc_offsets[i + 1]])


class GPTWindowDataset:
    """Fixed-window LM samples over the concatenated token stream."""

    def __init__(self, indexed: IndexedTokenDataset, seq_len: int, seed: int = 1234):
        self.indexed = indexed
        self.seq_len = seq_len
        self.seed = seed
        self.num_samples = (indexed.num_tokens - 1) // seq_len
        if self.num_samples == 0:
            raise ValueError(
                f"corpus has {indexed.num_tokens} tokens — fewer than one "
                f"(seq_len+1)={seq_len + 1} window"
            )

    def __len__(self) -> int:
        return self.num_samples

    def sample(self, i: int) -> np.ndarray:
        s = i * self.seq_len
        return np.asarray(self.indexed.tokens[s : s + self.seq_len + 1], np.int32)

    def batches_per_epoch(self, global_batch_size: int) -> int:
        return self.num_samples // global_batch_size

    def batch_iterator(
        self, global_batch_size: int, epochs: Optional[int] = None, start_batch: int = 0
    ) -> Iterator[np.ndarray]:
        """(B, S+1) int32 batches; ``start_batch`` resumes by index arithmetic
        (window order depends only on (seed, epoch))."""
        per_epoch = self.batches_per_epoch(global_batch_size)
        if per_epoch == 0:
            raise ValueError(
                f"global_batch_size {global_batch_size} exceeds the "
                f"{self.num_samples} available windows"
            )
        from galvatron_tpu.core.data_native import mix_seed, shuffle_index

        epoch, skip = divmod(start_batch, per_epoch)
        while epochs is None or epoch < epochs:
            # mixed (seed, epoch) derivation, not seed + epoch: the additive
            # form aliases adjacent streams (seed s epoch 1 == seed s+1
            # epoch 0), silently replaying another run's order
            order = shuffle_index(self.num_samples, mix_seed(self.seed, epoch))
            for b in range(skip, per_epoch):
                idx = order[b * global_batch_size : (b + 1) * global_batch_size]
                yield np.stack([self.sample(int(i)) for i in idx])
            skip = 0
            epoch += 1
