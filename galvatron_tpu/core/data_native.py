"""Loader for the native data helpers (csrc/data_helpers.cpp).

Same build/bind pattern as the DP core (galvatron_tpu.search.native): g++ on
first use, C ABI via ctypes, and a NumPy fallback computing the *identical*
permutation (keyed-hash argsort with splitmix64), so epoch shuffles are
bit-equal with or without the native library. Reference analogue:
megatron/data/helpers.cpp sample/shuffle index builders.
"""

from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path
from typing import Optional

import numpy as np

_REPO_ROOT = Path(__file__).resolve().parents[2]
_SRC = _REPO_ROOT / "csrc" / "data_helpers.cpp"
_BUILD_DIR = _REPO_ROOT / "build"
_SO = _BUILD_DIR / "libgalvatron_data_helpers.so"

_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    _BUILD_DIR.mkdir(exist_ok=True)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", str(_SRC), "-o", str(_SO)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def get_data_helpers() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed:
        return None
    try:
        if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
            if not _build():
                _load_failed = True
                return None
        lib = ctypes.CDLL(str(_SO))
        lib.galvatron_shuffle_index.restype = None
        lib.galvatron_shuffle_index.argtypes = [
            ctypes.c_int64,
            ctypes.c_uint64,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ]
        _lib = lib
        return _lib
    except Exception:
        _load_failed = True
        return None


def _splitmix64_np(x: np.ndarray) -> np.ndarray:
    z = (x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64)
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def mix_seed(*vals: int) -> int:
    """Collision-resistant combine of integer seed components via a splitmix64
    chain. The additive ``seed + epoch`` scheme the loaders used aliases
    adjacent streams — ``(seed=s, epoch=1)`` replayed ``(seed=s+1, epoch=0)``
    exactly — so every (seed, epoch) / (seed, source, epoch) derivation goes
    through this instead."""
    h = np.uint64(0x9E3779B97F4A7C15)
    with np.errstate(over="ignore"):
        for v in vals:
            h = _splitmix64_np(h ^ np.uint64(int(v) % (1 << 64)))
    return int(h)


def shuffle_index(n: int, seed: int) -> np.ndarray:
    """Deterministic permutation of [0, n): stable argsort of
    splitmix64(seed ^ i). Native when available, numpy otherwise — identical
    output either way."""
    lib = get_data_helpers()
    if lib is not None:
        out = np.empty((n,), np.int64)
        lib.galvatron_shuffle_index(
            np.int64(n), np.uint64(np.uint64(seed) & np.uint64(2**64 - 1)), out
        )
        return out
    with np.errstate(over="ignore"):
        keys = _splitmix64_np(np.uint64(seed) ^ np.arange(n, dtype=np.uint64))
    return np.argsort(keys, kind="stable").astype(np.int64)
