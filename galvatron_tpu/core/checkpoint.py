"""Sharded checkpoint save/restore with resume — atomic and verified.

The reference's trainer never saves (SURVEY §5: only an unused --load_params
flag; the vendored Megatron checkpointing.py/dist_checkpointing are not
integrated). Here sharded save/restore is first-class via Orbax: each leaf is
written from its NamedSharding layout and restored into the (possibly
different) target sharding, so a run searched onto a new strategy can resume
from an old layout.

Commit protocol (the resilience layer — production TPU-pod training is
dominated by preemptions and transient storage faults):

1. data is written into a ``step_N.tmp`` staging directory;
2. a **manifest** (per-leaf shapes/dtypes + sha256 content digests, plus a
   sha256 digest of every file in the staging dir) is written into the
   staging dir *last* and fsynced — it is the commit marker: a directory
   without a parseable manifest is never a checkpoint;
3. one ``rename(step_N.tmp → step_N)`` publishes the step atomically.

File digests are verified BEFORE any restore is attempted: decoding
corrupted compressed chunks is undefined behaviour in the storage stack
(observed as heap corruption), so a corrupt step must be detected from the
raw bytes and never handed to the array reader. The per-leaf digests remain
as the end-to-end check on what was actually restored.

A kill at any point leaves either the old committed set untouched or a
``.tmp`` orphan that :func:`latest_step` garbage-collects and never selects.
Restores verify the manifest (shape/dtype/digest per leaf) and, when no
explicit step was requested, **fall back to the next-older committed step**
on corruption (``ckpt_fallback`` metrics event). Saves retry transient
I/O errors with exponential backoff (core/retry.py) and honour the
``--keep_last_n`` retention policy. On multi-controller deployments the
commit (file digests, manifest, rename) has exactly one writer — process 0
— with a cross-process barrier after it; leaves that cannot be
host-gathered from one process carry structure-only manifest records
(digest None), and the per-file digests remain the byte-level guard.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from galvatron_tpu.core import faults
from galvatron_tpu.core.retry import with_retries
from galvatron_tpu.obs.tracing import tracer as _obs_tracer

MANIFEST_NAME = "manifest.json"
MANIFEST_VERSION = 1
_STEP_RE = re.compile(r"^step_(\d+)$")
_TMP_SUFFIX = ".tmp"
_OLD_SUFFIX = ".old"


class CheckpointCorruptError(RuntimeError):
    """A committed checkpoint failed content verification (digest/shape/dtype
    mismatch against its manifest, or an unreadable payload whose structure
    the manifest proves should match)."""


class CheckpointVerificationIOError(CheckpointCorruptError):
    """Verification could not READ the step (transient I/O outlasted the
    retry budget) — indistinguishable from corruption for fallback purposes
    (skip to an older step), but it must never trigger quarantine: renaming
    healthy steps aside during a storage outage would hide every committed
    checkpoint and cause the silent restart-from-scratch this whole layer
    exists to prevent."""


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def parse_step_name(name: str) -> Optional[int]:
    """Strict committed-step-name parser: ``step_<digits>`` only — partial
    saves (``step_N.tmp``), renamed-aside dirs and arbitrary ``step_*``
    artifacts never parse."""
    m = _STEP_RE.match(name)
    return int(m.group(1)) if m else None


def step_path(ckpt_dir: str, step: int) -> str:
    return os.path.join(os.path.abspath(ckpt_dir), f"step_{step}")


def read_manifest(path: str) -> Optional[Dict[str, Any]]:
    """The step's manifest, or None when absent/unparseable (uncommitted or
    pre-manifest legacy dir)."""
    try:
        with open(os.path.join(path, MANIFEST_NAME)) as f:
            m = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(m, dict) or not isinstance(m.get("leaves"), dict):
        return None
    return m


def gc_stale_tmp(ckpt_dir: str) -> List[str]:
    """Best-effort cleanup of save-protocol leftovers. Orphaned staging dirs
    (a kill mid-save leaves ``step_N.tmp`` behind) are removed; a
    ``step_N.old`` renamed aside by an interrupted re-save swap is renamed
    BACK into place when ``step_N`` is missing (the old committed data must
    survive a kill between the swap's two renames) and removed once the swap
    is known complete. Single-writer per directory is assumed — the GC runs
    from the resume path and the saver's own process, never concurrently
    with another host's staging."""
    removed = []
    if not os.path.isdir(ckpt_dir):
        return removed
    for name in os.listdir(ckpt_dir):
        full = os.path.join(ckpt_dir, name)
        if not os.path.isdir(full):
            continue
        if name.endswith(_OLD_SUFFIX) and parse_step_name(
            name[: -len(_OLD_SUFFIX)]
        ) is not None:
            final = full[: -len(_OLD_SUFFIX)]
            if os.path.isdir(final):
                shutil.rmtree(full, ignore_errors=True)  # swap completed
                removed.append(full)
            else:
                # swap died mid-way: restore the old committed copy.
                # Best-effort — on multi-host resume every process scans the
                # shared dir and exactly one rename wins the race
                try:
                    os.rename(full, final)
                except OSError:
                    pass
        elif name.startswith("step_") and name.endswith(_TMP_SUFFIX):
            shutil.rmtree(full, ignore_errors=True)
            removed.append(full)
    return removed


def _scan_steps(ckpt_dir: str, with_manifest: bool) -> List[int]:
    """Ascending strictly-named step dirs, split by the commit marker (a
    parseable manifest) — one scan loop so future selection changes cannot
    diverge the committed vs legacy views."""
    if not os.path.isdir(ckpt_dir):
        return []
    steps = []
    for name in os.listdir(ckpt_dir):
        s = parse_step_name(name)
        if s is None:
            continue
        full = os.path.join(ckpt_dir, name)
        if os.path.isdir(full) and (read_manifest(full) is not None) == with_manifest:
            steps.append(s)
    return sorted(steps)


def committed_steps(ckpt_dir: str) -> List[int]:
    """Ascending step numbers whose directories are committed (strict name
    AND a parseable manifest — the commit marker)."""
    return _scan_steps(ckpt_dir, with_manifest=True)


def uncommitted_steps(ckpt_dir: str) -> List[int]:
    """Step-named directories with NO manifest: either a pre-manifest legacy
    checkpoint (written before the commit protocol — possibly resumable via
    an explicit ``step=``) or a partial save left by the pre-protocol code.
    Callers that find no committed steps should surface these instead of
    silently starting from scratch."""
    return _scan_steps(ckpt_dir, with_manifest=False)


def latest_step(ckpt_dir: str) -> Optional[int]:
    """Newest committed step (stale ``.tmp`` staging dirs are GC'd on the
    way); None when no committed checkpoint exists."""
    gc_stale_tmp(ckpt_dir)
    steps = committed_steps(ckpt_dir)
    return steps[-1] if steps else None


def _no_checkpoints_message(ckpt_dir: str) -> str:
    legacy = uncommitted_steps(ckpt_dir)
    if legacy:
        return (
            f"no committed checkpoints under {ckpt_dir} — but steps "
            f"{legacy} exist without a manifest (pre-commit-protocol legacy "
            "saves, or partial writes by a pre-protocol revision). Restore "
            "one explicitly with step=N to bypass the commit check, then "
            "re-save to commit it."
        )
    return f"no checkpoints under {ckpt_dir}"


def _leaf_digest(leaf: Any) -> Dict[str, Any]:
    if not getattr(leaf, "is_fully_addressable", True):
        # multi-controller: this process cannot host-gather a globally
        # sharded array — record structure only (digest None is understood
        # by verify_manifest as "not checkable"); the per-file digests still
        # guard the bytes on disk
        return {
            "shape": list(leaf.shape),
            "dtype": str(np.dtype(leaf.dtype)),
            "digest": None,
        }
    arr = np.ascontiguousarray(np.asarray(leaf))
    return {
        "shape": list(arr.shape),
        "dtype": str(arr.dtype),
        "digest": "sha256:" + hashlib.sha256(arr.tobytes()).hexdigest(),
    }


def _manifest_of(state: Any, step: int) -> Dict[str, Any]:
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    return {
        "version": MANIFEST_VERSION,
        "step": int(step),
        "leaves": {jax.tree_util.keystr(kp): _leaf_digest(x) for kp, x in flat},
    }


def _file_digests(root: str) -> Dict[str, Dict[str, Any]]:
    """sha256 + size of every file under a step directory (manifest
    excluded) — the pre-decode integrity record."""
    out: Dict[str, Dict[str, Any]] = {}
    for dirpath, _, files in os.walk(root):
        for fn in files:
            if fn == MANIFEST_NAME:
                continue
            full = os.path.join(dirpath, fn)
            h = hashlib.sha256()
            with open(full, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            out[os.path.relpath(full, root)] = {
                "size": os.path.getsize(full),
                "digest": "sha256:" + h.hexdigest(),
            }
    return out


def verify_files(path: str, manifest: Dict[str, Any]) -> List[str]:
    """Raw-byte verification of a step directory against its manifest's file
    records. Runs BEFORE any restore: corrupted compressed chunks must never
    reach the array decoder (undefined behaviour in the storage stack), so
    corruption is detected from the bytes on disk. Empty when the manifest
    predates file records."""
    want = manifest.get("files")
    if not want:
        return []
    errs: List[str] = []
    got = _file_digests(path)
    for rel in sorted(set(want) | set(got)):
        w, g = want.get(rel), got.get(rel)
        if w is None:
            errs.append(f"unexpected file {rel}")
        elif g is None:
            errs.append(f"missing file {rel}")
        elif g["size"] != w.get("size"):
            errs.append(
                f"file {rel} size mismatch ({g['size']} bytes, "
                f"manifest records {w.get('size')})"
            )
        elif g != w:
            errs.append(
                f"file {rel} content digest mismatch "
                f"(size {g['size']} matches — bytes corrupted in place)"
            )
    return errs


def _verify_files_pod(path: str, manifest: Dict[str, Any]) -> List[str]:
    """File verification with exactly one reader on multi-controller pods:
    process 0 hashes (mirroring the single-writer commit) and broadcasts the
    verdict, so every process raises — or proceeds into the collective
    restore — identically. N hosts independently re-hashing a multi-GB
    checkpoint would multiply the resume-critical-path I/O N-fold, and a
    host-local torn read diverging one process's verdict would mismatch the
    collective and hang the pod."""
    if jax.process_count() == 1:
        try:
            # the hash pass re-reads every checkpoint byte — the single most
            # I/O-heavy step of resume, so it gets the same transient-retry
            # treatment as the restore itself
            return with_retries(
                lambda: verify_files(path, manifest),
                describe=f"file verification of {path}",
            )
        except OSError as e:
            # still unreadable after retries: the fallback may move to an
            # older step, but the distinct type forbids quarantine — a
            # storage outage must not rename healthy checkpoints aside
            raise CheckpointVerificationIOError(
                f"could not read {path} for verification after retries: "
                f"{str(e)[:200]}"
            ) from e
    from jax.experimental import multihost_utils

    # verdict codes broadcast from the single verifier: 0 ok, 1 content
    # mismatch (quarantinable corruption), 2 verification read error
    errs: List[str] = []
    code = 0
    if jax.process_index() == 0:
        try:
            errs = with_retries(
                lambda: verify_files(path, manifest),
                describe=f"file verification of {path}",
            )
            code = 1 if errs else 0
        except Exception as e:
            # the broadcast below MUST be reached: peers are already parked
            # inside broadcast_one_to_all, and raising here would wedge the
            # pod — a read failure becomes a broadcast verdict, not a hang
            code = 2
            errs = [str(e)[:200]]
    code = int(multihost_utils.broadcast_one_to_all(np.int32(code)))
    if code == 2:
        raise CheckpointVerificationIOError(
            "file verification read failed on process 0"
            + (f": {errs[0]}" if errs else "")
        )
    if code == 1 and not errs:
        errs = ["file verification failed on process 0"]
    return errs if code else []


def _verify_step_files(
    path: str, step: int, where: str, manifest: Optional[Dict[str, Any]]
) -> None:
    """Shared pre-decode gate of every restore path: raise
    :class:`CheckpointCorruptError` when the step's bytes don't match its
    manifest's file records (no-op for manifests predating file records)."""
    if manifest is None:
        return
    ferrs = _verify_files_pod(path, manifest)
    if ferrs:
        raise CheckpointCorruptError(
            f"step {step} under {where} failed file verification: "
            + "; ".join(ferrs[:5])
        )


def verify_manifest(manifest: Dict[str, Any], state: Any) -> List[str]:
    """Per-leaf shape/dtype/content-digest check of a (restored) state tree
    against its manifest; returns human-readable mismatch descriptions."""
    errs: List[str] = []
    want = manifest.get("leaves", {})
    seen = set()
    for kp, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        k = jax.tree_util.keystr(kp)
        seen.add(k)
        rec = want.get(k)
        if rec is None:
            errs.append(f"leaf {k} not in manifest")
            continue
        got = _leaf_digest(leaf)
        for field in ("shape", "dtype", "digest"):
            if field == "digest" and (
                got["digest"] is None or rec.get("digest") is None
            ):
                # either side not host-gatherable (multi-controller):
                # content is guarded by the per-file digests instead
                continue
            if got[field] != rec.get(field):
                errs.append(
                    f"leaf {k} {field} mismatch: checkpoint has {got[field]}, "
                    f"manifest records {rec.get(field)}"
                )
                break
    errs.extend(f"manifest leaf {k} missing from checkpoint" for k in sorted(set(want) - seen))
    return errs


def _content_only_match(manifest: Dict[str, Any], state: Any) -> bool:
    """Keypath-free equality: the multiset of (shape, dtype, digest) leaf
    records matches the manifest's. A digest of None (either side — a
    structure-only record from a multihost save, or a non-addressable
    restored leaf) is a wildcard: within its (shape, dtype) group only the
    leaf COUNT is checked, since content there is guarded by the per-file
    digests instead — comparing None against a real sha256 would wrongly
    reject every healthy pod-written checkpoint restored raw."""
    from collections import defaultdict

    def grouped(records):
        groups: Dict[Any, List[Optional[str]]] = defaultdict(list)
        for r in records:
            groups[(tuple(r.get("shape", ())), r.get("dtype"))].append(
                r.get("digest")
            )
        return groups

    got = grouped(_leaf_digest(x) for x in jax.tree_util.tree_leaves(state))
    want = grouped(manifest.get("leaves", {}).values())
    if set(got) != set(want):
        return False
    for key, want_digests in want.items():
        got_digests = got[key]
        if len(got_digests) != len(want_digests):
            return False
        if None in got_digests or None in want_digests:
            continue  # wildcard group: count match is all that's checkable
        if sorted(got_digests) != sorted(want_digests):
            return False
    return True


def _pod_sync(tag: str) -> None:
    """Cross-process barrier on multi-controller deployments; no-op on a
    single controller (every test and CPU-sim path)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


def _retry_unless_collective(fn, describe: str):
    """I/O retry wrapper for orbax save/restore calls: on a multi-controller
    pod these are COLLECTIVE, and a lone process re-entering one while its
    peers have moved on deadlocks the pod — there the call gets exactly one
    try and the failure surfaces. Single controller retries as usual."""
    if jax.process_count() > 1:
        return fn()
    return with_retries(fn, describe=describe)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # not all filesystems expose dir fds; rename is still atomic
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _apply_retention(ckpt_dir: str, keep_last_n: int) -> None:
    for s in committed_steps(ckpt_dir)[:-keep_last_n]:
        shutil.rmtree(step_path(ckpt_dir, s), ignore_errors=True)


def save_checkpoint(
    ckpt_dir: str, state: Any, step: int, keep_last_n: int = 0,
    meta: Optional[Dict[str, Any]] = None,
) -> str:
    """Writes state (params/opt/step pytree) under ckpt_dir/step_N with the
    atomic commit protocol (staging dir → fsynced manifest → rename); retries
    transient I/O with backoff; ``keep_last_n > 0`` prunes older committed
    steps after the new one lands. ``meta`` (JSON-serializable) rides along
    in the manifest — the trainer records batches-consumed there, which
    diverges from the step count once anomaly skips happen."""
    # observability: saves are the dominant non-step pause in a training
    # timeline — one span per save (tracing off: no-op singleton, zero cost)
    with _obs_tracer.span("ckpt_save", step=int(step)):
        return _save_checkpoint_impl(ckpt_dir, state, step, keep_last_n, meta)


def _save_checkpoint_impl(
    ckpt_dir: str, state: Any, step: int, keep_last_n: int = 0,
    meta: Optional[Dict[str, Any]] = None,
) -> str:
    # injection point: a storage outage fails the whole save (per-save, not
    # per-attempt — see faults.storage_outage_gate). Raised before any
    # staging I/O so the directory is left exactly as it was.
    faults.storage_outage_gate()
    ocp = _ocp()
    base = os.path.abspath(ckpt_dir)
    final = os.path.join(base, f"step_{step}")
    tmp = final + _TMP_SUFFIX
    manifest = _manifest_of(state, step)
    if meta:
        manifest["meta"] = dict(meta)

    multi = jax.process_count() > 1

    def write_data():
        if os.path.isdir(tmp) and (not multi or jax.process_index() == 0):
            shutil.rmtree(tmp)
        if multi:
            _pod_sync(f"ckpt_clean_{step}")
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(tmp, state, force=True)
        ckptr.wait_until_finished()
        faults.crash("mid_save")  # injection point: preemption before commit

    def commit():
        manifest["files"] = _file_digests(tmp)
        mpath = os.path.join(tmp, MANIFEST_NAME)
        with open(mpath, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        if os.path.isdir(final):
            # re-save of an existing step: swap via a recoverable .old side
            # name — a kill between the two renames leaves step_N.old (the
            # old committed data), which gc_stale_tmp renames back into
            # place; at no point are both copies GC-able
            old = final + _OLD_SUFFIX
            if os.path.isdir(old):
                shutil.rmtree(old)
            os.rename(final, old)
            os.rename(tmp, final)
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.rename(tmp, final)
        _fsync_dir(base)

    if multi:
        # the orbax write is COLLECTIVE across processes: it must run exactly
        # once per process and never sit inside a retry loop (a lone process
        # retrying a collective — or re-entering the pre-clean barrier while
        # the others wait at the commit barrier — deadlocks the pod). Only
        # the single-writer commit I/O on process 0 is retried.
        write_data()
        try:
            if jax.process_index() == 0:
                with_retries(commit, describe=f"checkpoint commit step {step}")
        finally:
            # process 0 must reach the barrier even when the commit failed —
            # its peers are already waiting inside _pod_sync, and
            # sync_global_devices has no peer-failure detection, so raising
            # before the barrier would hang the pod instead of surfacing the
            # error. After the sync the peers' view stays consistent: an
            # uncommitted step has no manifest, so latest_step never selects
            # it and the failure propagates from process 0's exception.
            _pod_sync(f"ckpt_commit_{step}")  # no process races ahead
    else:
        # two retry units, not one: a transient failure in the tiny commit
        # (manifest write / rename) must not re-run the multi-GB data write
        with_retries(write_data, describe=f"checkpoint save step {step}")
        with_retries(commit, describe=f"checkpoint commit step {step}")
    faults.after_commit(final)  # injection point: post-commit storage corruption
    if keep_last_n > 0 and jax.process_index() == 0:
        _apply_retention(base, keep_last_n)
    return final


def restore_checkpoint(ckpt_dir: str, abstract_state: Any, step: Optional[int] = None) -> Any:
    """Restores into the shardings carried by ``abstract_state`` (a pytree of
    jax.ShapeDtypeStruct with .sharding — e.g. from eval_shape + the runtime's
    state_shardings). Cross-strategy resume falls out: Orbax reshards on
    load. The restored tree is verified against the step's manifest
    (shape/dtype/content digest per leaf); failures raise
    :class:`CheckpointCorruptError`, which the no-explicit-step portable
    restore path treats as "fall back to the next-older committed step".

    Layout note: the blocked fused-QKV change (models/modeling.py:qkv_dims)
    made MHA ``wqkv`` leaves rank-3; a checkpoint written by the earlier
    interleaved-only code no longer restores, and a silent reshape would
    scramble q/k/v (the interleave is per head-group, not per slot). Such a
    restore fails with an explicit migration error instead."""
    with _obs_tracer.span("ckpt_restore", step=-1 if step is None else int(step)):
        return _restore_checkpoint_impl(ckpt_dir, abstract_state, step)


def _restore_checkpoint_impl(
    ckpt_dir: str, abstract_state: Any, step: Optional[int] = None
) -> Any:
    ocp = _ocp()
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(_no_checkpoints_message(ckpt_dir))
    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{step}")
    manifest = read_manifest(path)
    # detected from raw bytes, before the array decoder ever sees corrupt chunks
    _verify_step_files(path, step, ckpt_dir, manifest)
    # the manifest proves what tree structure is on disk: when it matches the
    # target, a restore failure cannot be a layout mismatch — it is corruption
    structure_matches = manifest is not None and set(
        manifest["leaves"]
    ) == _tree_keypaths(abstract_state)
    ckptr = ocp.StandardCheckpointer()
    try:
        restored = _retry_unless_collective(
            lambda: ckptr.restore(path, abstract_state),
            describe=f"checkpoint restore step {step}",
        )
    except Exception as e:
        msg = _legacy_layout_message(abstract_state, str(e))
        if msg:
            raise ValueError(msg) from e
        if structure_matches:
            if isinstance(e, OSError):
                # transient I/O that outlasted the retry budget, not proven
                # corruption: fallback may proceed, quarantine must not
                raise CheckpointVerificationIOError(
                    f"step {step} under {ckpt_dir} could not be read after "
                    f"retries: {str(e)[:500]}"
                ) from e
            raise CheckpointCorruptError(
                f"step {step} under {ckpt_dir} matches the target structure "
                f"but failed to restore (corrupt payload): {str(e)[:500]}"
            ) from e
        raise
    if manifest is not None and structure_matches:
        errs = verify_manifest(manifest, restored)
        if errs:
            raise CheckpointCorruptError(
                f"step {step} under {ckpt_dir} failed content verification: "
                + "; ".join(errs[:5])
            )
    # defensive copy: restored leaves can be backed by the storage layer's
    # own buffers, and the trainer donates its state into train_step —
    # donating storage-owned buffers is a double-free (observed as heap
    # corruption on the second post-resume step). jnp.copy re-lands every
    # leaf in XLA-owned buffers; one transient 2x of state memory, at
    # restore time only.
    import jax.numpy as jnp

    restored = jax.tree.map(jnp.copy, restored)
    jax.block_until_ready(restored)
    return restored


def _legacy_layout_message(abstract_state: Any, err: str) -> Optional[str]:
    """Actionable message when a restore failure looks like one of the known
    parameter-layout changes rather than a corrupt checkpoint."""
    flat, _ = jax.tree_util.tree_flatten_with_path(abstract_state)

    def has(pred):
        return any(pred(kp, leaf) for kp, leaf in flat)

    low = err.lower()
    bias_keys = {"wqkv_b", "wo_b", "w1_b", "w2_b", "w13_b"}
    # Bias branch first, gated on a missing-key mismatch that NAMES a bias
    # leaf — orbax's structure-mismatch error lists the offending paths with
    # "Target: MISSING", and its ShapeDtypeStruct reprs mention "shape",
    # which would otherwise trip the wqkv branch. Errors that merely mention
    # a bias leaf without a missing-key mismatch (shape conflict, corrupt
    # array) must surface verbatim.
    if "missing" in low and any(bk in low for bk in bias_keys) and has(
        lambda kp, leaf: any(getattr(k, "key", None) in bias_keys for k in kp)
    ):
        return (
            "restore failed and the target model carries projection biases "
            "(use_bias — on by default for the gpt/bert presets since the "
            "GPT-2-faithful bias change): a checkpoint saved before that "
            "change has no *_b leaves. Re-export it with the producing "
            "revision, or add zero biases to the saved tree. Original "
            f"error: {err[:500]}"
        )
    if ("shape" in low or "rank" in low) and "missing" not in low and has(
        lambda kp, leaf: any(getattr(k, "key", None) == "wqkv" for k in kp)
        and hasattr(leaf, "shape")
        and len(leaf.shape) >= 3
    ):
        return (
            "checkpoint predates the blocked fused-QKV weight layout "
            "(wqkv is now (h, 3, n*head_dim) for non-GQA models): "
            "re-export it by loading with the producing revision and "
            "re-saving, e.g. transpose each wqkv from (h, n, 3, head_dim) "
            "column order to (h, 3, n*head_dim)"
        )
    return None


def save_checkpoint_portable(
    ckpt_dir: str, state: Any, step: int, runtime, keep_last_n: int = 0,
    meta: Optional[Dict[str, Any]] = None,
) -> str:
    """Save in the PORTABLE (flat-layers) layout: pipeline engines unstack
    their stage/virtual-stage parameter stacks first, so a checkpoint saved
    at any (pp, vpp, schedule, division) restores into any other — the
    cross-layout resume the reference cannot express (its trainer never
    saves at all, SURVEY §5)."""
    flat = portable_flat_state(state, runtime)
    return save_checkpoint(
        ckpt_dir, flat, step, keep_last_n=keep_last_n, meta=meta
    )


def portable_flat_state(state: Any, runtime) -> Any:
    """The PORTABLE (flat-layers) view of a train state — the tree the disk
    checkpoint and the in-memory peer replica (core/peer_store.py) both
    serialize, so the two recovery tiers share one schema. Identity when
    the runtime has no stage stacks to unstack."""
    f = runtime.flatten_params
    if f is None:
        return state

    def flatten_state(st):
        out = dict(st)
        out["params"] = f(st["params"])
        out["opt"] = {**st["opt"], "mu": f(st["opt"]["mu"]), "nu": f(st["opt"]["nu"])}
        return out

    # one compiled program instead of per-leaf eager slice dispatches
    return jax.jit(flatten_state)(state)


def restore_from_flat_leaves(runtime, leaves: Dict[str, np.ndarray]) -> Any:
    """Seat a ``{keypath: ndarray}`` map (a deserialized peer replica — the
    portable flat layout on the wire) onto this runtime's live state.

    Structure and shardings come from the runtime's own abstract flat tree
    (exactly like a flat disk restore); only content comes from the
    replica. Keypath/shape/dtype mismatches raise
    :class:`CheckpointCorruptError` — the caller's signal to fall back to
    the disk tier — never a silent partial resume."""
    flat_abstract = (
        flat_abstract_state_of(runtime)
        if runtime.restack_params is not None
        else abstract_state_of(runtime)
    )
    paths, treedef = jax.tree_util.tree_flatten_with_path(flat_abstract)
    want = {jax.tree_util.keystr(kp): s for kp, s in paths}
    missing = sorted(set(want) - set(leaves))
    extra = sorted(set(leaves) - set(want))
    if missing or extra:
        raise CheckpointCorruptError(
            f"peer replica structure mismatch: {len(missing)} leaves missing "
            f"(e.g. {missing[:3]}), {len(extra)} unexpected (e.g. {extra[:3]})"
        )
    seated = []
    for kp, s in paths:
        k = jax.tree_util.keystr(kp)
        arr = leaves[k]
        if tuple(arr.shape) != tuple(s.shape) or np.dtype(arr.dtype) != np.dtype(s.dtype):
            raise CheckpointCorruptError(
                f"peer replica leaf {k} is {arr.shape}/{arr.dtype}, runtime "
                f"expects {tuple(s.shape)}/{np.dtype(s.dtype)}"
            )
        # seat every shard through its OWN device_put: a whole-array
        # device_put of a replicated host array can hand multiple devices
        # the SAME underlying CPU buffer, and the trainer's donating
        # dispatch then applies the in-place update once per device to that
        # shared buffer — observed as step counters flakily advancing by
        # the replica count (and params double-applying updates) after a
        # peer-replica resume. Distinct per-shard buffers keep donation
        # sound.
        imap = s.sharding.addressable_devices_indices_map(tuple(arr.shape))
        shards = [
            jax.device_put(np.asarray(arr[idx], dtype=arr.dtype), d)
            for d, idx in imap.items()
        ]
        seated.append(
            jax.make_array_from_single_device_arrays(
                tuple(arr.shape), s.sharding, shards
            )
        )
    flat = jax.tree_util.tree_unflatten(treedef, seated)
    r = runtime.restack_params
    if r is None:
        jax.block_until_ready(flat)
        return flat

    def restack_state(st):
        out = dict(st)
        out["params"] = r(st["params"])
        out["opt"] = {**st["opt"], "mu": r(st["opt"]["mu"]), "nu": r(st["opt"]["nu"])}
        return out

    restored = jax.jit(restack_state, out_shardings=runtime.state_shardings)(flat)
    jax.block_until_ready(restored)
    return restored


def _tree_keypaths(tree) -> set:
    from jax.tree_util import tree_flatten_with_path

    leaves, _ = tree_flatten_with_path(tree)
    return {jax.tree_util.keystr(kp) for kp, _ in leaves}


def _checkpoint_layout(
    ckpt_dir: str, step: Optional[int], flat_abstract, stacked_abstract
) -> Optional[str]:
    """POSITIVE layout detection: compare the on-disk checkpoint tree
    structure (orbax metadata) against the two candidate layouts instead of
    classifying restore-exception text (which breaks whenever orbax rewords
    a structure mismatch). Returns 'flat' | 'stacked' | 'neither', or None
    when the metadata itself cannot be read (caller falls back to
    try-restore + exception classification)."""
    ocp = _ocp()
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(_no_checkpoints_message(ckpt_dir))
    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{step}")
    try:
        meta = ocp.StandardCheckpointer().metadata(path)
        # StepMetadata wraps the saved tree; the tree itself flattens with
        # the same keypaths as the state pytree
        disk = _tree_keypaths(getattr(meta, "item_metadata", meta))
    except Exception:
        return None
    if disk == _tree_keypaths(flat_abstract):
        return "flat"
    if disk == _tree_keypaths(stacked_abstract):
        return "stacked"
    return "neither"


def restore_checkpoint_portable(
    ckpt_dir: str, runtime, step: Optional[int] = None, metrics=None
) -> Any:
    """Restore a portable (flat-layout) checkpoint into the runtime's own
    layout, resharding as needed (see ``_restore_checkpoint_portable_at``).

    When no explicit ``step`` is requested, committed steps are tried newest
    → oldest: a checkpoint that fails manifest verification (or whose payload
    is unreadable despite a structure-matching manifest) is skipped with a
    ``ckpt_fallback`` event on ``metrics`` (any object with a
    ``.log(event, **fields)`` method, e.g. utils.metrics.MetricsLogger) —
    a corrupt latest save can no longer take down resume."""
    if step is not None:
        return _restore_checkpoint_portable_at(ckpt_dir, runtime, step)
    gc_stale_tmp(ckpt_dir)  # also recovers a .old from an interrupted swap
    steps = committed_steps(ckpt_dir)
    if not steps:
        raise FileNotFoundError(_no_checkpoints_message(ckpt_dir))
    return _try_newest_first(
        list(reversed(steps)),
        lambda s: _restore_checkpoint_portable_at(ckpt_dir, runtime, s),
        f"all {len(steps)} committed checkpoints under {ckpt_dir} failed "
        "verification",
        metrics=metrics,
        quarantine_base=os.path.abspath(ckpt_dir),
    )


def _try_newest_first(
    steps, restore_one, exhausted_msg: str, metrics=None,
    quarantine_base: Optional[str] = None,
):
    """THE fallback protocol, shared by every no-explicit-step restore path:
    try ``restore_one(step)`` newest → oldest, skipping steps that fail
    verification (``ckpt_fallback`` metrics event per skip when ``metrics``
    is given); raises :class:`CheckpointCorruptError` chaining the last
    failure once every candidate is exhausted. With ``quarantine_base`` set
    (the trainer's resume path), a corrupt step is renamed aside so it stops
    counting as committed."""
    last_err: Optional[CheckpointCorruptError] = None
    for s in steps:
        try:
            return restore_one(s)
        except CheckpointCorruptError as e:
            print(f"checkpoint step {s} corrupt, falling back: {str(e)[:200]}")
            if metrics is not None:
                metrics.log("ckpt_fallback", step=s, error=str(e)[:300])
            _obs_tracer.instant("ckpt_fallback", step=s, error=str(e)[:120])
            if quarantine_base is not None and not isinstance(
                e, CheckpointVerificationIOError
            ):
                # only PROVEN corruption is renamed aside — a verification
                # read error may just be a storage blip, and quarantining on
                # it would hide every healthy checkpoint during an outage
                _quarantine_step(quarantine_base, s)
            last_err = e
    raise CheckpointCorruptError(exhausted_msg) from last_err


def _quarantine_step(base: str, s: int) -> None:
    """Rename a corrupt committed step aside (``step_N`` → ``step_N.corrupt``,
    kept on disk for forensics) so name-based selection never sees it again.
    Without this, ``--keep_last_n`` retention after a fallback resume would
    prune the healthy OLDER steps the fallback just used while keeping the
    corrupt newest one, and a retrained run reaching the same step number
    would dedup its exit save against the corrupt dir and never persist.
    Multihost processes race the rename; the losers ignore the OSError."""
    src = step_path(base, s)
    dst = src + ".corrupt"
    # rename FIRST, clean a stale dst only on failure: pre-cleaning would
    # let a process that lost the multihost race rmtree the quarantine its
    # peer just created (src gone ⇒ dst IS the fresh forensic copy)
    for _ in range(2):
        try:
            os.rename(src, dst)
            print(f"quarantined corrupt checkpoint {src} → {dst}")
            return
        except OSError:
            if not os.path.isdir(src):
                return  # lost the race: a peer already quarantined it
            if os.path.isdir(dst):
                # stale quarantine of an earlier incarnation of this step:
                # clear it and retry once
                shutil.rmtree(dst, ignore_errors=True)
            else:
                return  # rename failed for another reason: best-effort, stop


def _restore_checkpoint_portable_at(ckpt_dir: str, runtime, step: int) -> Any:
    """Single-step portable restore: flat leaves restore under the per-layer
    GSPMD specs of the runtime's strategies (sharded over tp/dp, replicated
    over pp — a transient pp-fold duplication of each device's stage share),
    then a jitted restack lands them on the engine's stage stacks."""
    if runtime.restack_params is None:
        return restore_checkpoint(ckpt_dir, abstract_state_of(runtime), step)
    flat_abstract = flat_abstract_state_of(runtime)
    layout = _checkpoint_layout(
        ckpt_dir, step, flat_abstract, abstract_state_of(runtime)
    )
    if layout == "stacked":
        # pre-portable checkpoint in the engine's own stacked layout
        return restore_checkpoint(ckpt_dir, abstract_state_of(runtime), step)
    if layout == "neither":
        raise ValueError(
            "checkpoint matches neither the portable flat-layers layout "
            "nor this runtime's stacked layout — it was likely saved "
            "under a different pipeline configuration by a pre-portable "
            "revision; resume it once with its original configuration to "
            "re-save portably."
        )
    try:
        flat = restore_checkpoint(ckpt_dir, flat_abstract, step)
    except (FileNotFoundError, CheckpointCorruptError):
        # corruption is never a layout signal — surface it (the
        # no-explicit-step caller turns it into fallback to an older step)
        raise
    except Exception as flat_err:
        if layout == "flat":
            # structure positively identified as flat: any failure here is a
            # real restore error, surface it verbatim
            raise
        # metadata unavailable (layout is None): fall back to the old
        # exception-text classification before trying the stacked layout
        low = str(flat_err).lower()
        mismatch_words = (
            "missing", "mismatch", "structure", "rank", "shape", "not found",
        )
        structural = isinstance(flat_err, (KeyError, TypeError))
        if not structural and not any(w in low for w in mismatch_words):
            raise
        try:
            return restore_checkpoint(ckpt_dir, abstract_state_of(runtime), step)
        except Exception:
            raise ValueError(
                "checkpoint matches neither the portable flat-layers layout "
                "nor this runtime's stacked layout — it was likely saved "
                "under a different pipeline configuration by a pre-portable "
                "revision; resume it once with its original configuration to "
                f"re-save portably. Flat-restore error: {str(flat_err)[:500]}"
            ) from flat_err
    r = runtime.restack_params

    def restack_state(st):
        out = dict(st)
        out["params"] = r(st["params"])
        out["opt"] = {**st["opt"], "mu": r(st["opt"]["mu"]), "nu": r(st["opt"]["nu"])}
        return out

    return jax.jit(restack_state, out_shardings=runtime.state_shardings)(flat)


def flat_abstract_state_of(runtime) -> Any:
    """Abstract flat-layout train state (the portable checkpoint schema):
    shapes from the flat model init + Adam moments, shardings from the
    per-layer GSPMD specs over the runtime's mesh."""
    import jax.numpy as jnp

    from galvatron_tpu.core.optim import init_opt_state
    from galvatron_tpu.models import modeling
    from galvatron_tpu.parallel.hybrid import state_specs
    from galvatron_tpu.parallel.sharding import sharding_tree

    def flat_init(key):
        params = modeling.init_model_params(key, runtime.cfg)
        st = {
            "params": params,
            "opt": init_opt_state(params),
            "step": jnp.zeros((), jnp.int32),
        }
        if "scaler" in runtime.state_shardings:
            from galvatron_tpu.core.schedules import LossScalerConfig, init_scaler_state

            st["scaler"] = init_scaler_state(LossScalerConfig())
        return st

    shapes = jax.eval_shape(flat_init, jax.random.key(0))
    specs = state_specs(shapes, runtime.cfg, runtime.hp, runtime.axes)
    shardings = sharding_tree(runtime.mesh, specs)
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes,
        shardings,
        is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
    )


def abstract_state_of(runtime, init_key=None) -> Any:
    """Abstract (shape+sharding) pytree for the runtime's train state."""
    import jax.numpy as jnp

    key = init_key if init_key is not None else jax.random.key(0)
    shapes = jax.eval_shape(runtime.init_state, key)
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes,
        runtime.state_shardings,
    )


def _restore_raw_at(base: str, s: int) -> Any:
    """Single-step raw restore: file-verify → restore → content-verify, any
    failure raised as :class:`CheckpointCorruptError` (the fallback loop's
    skip signal)."""
    ocp = _ocp()
    path = step_path(base, s)
    manifest = read_manifest(path)
    _verify_step_files(path, s, base, manifest)
    try:
        raw = _retry_unless_collective(
            lambda: ocp.StandardCheckpointer().restore(path),
            describe=f"raw checkpoint restore step {s}",
        )
    except Exception as e:
        raise CheckpointCorruptError(
            f"step {s} under {base} failed to restore: {str(e)[:300]}"
        ) from e
    if manifest is not None:
        errs = verify_manifest(manifest, raw)
        # a raw restore may spell container keypaths differently than the
        # saved jax tree (list vs dict-of-indices); content equality as a
        # multiset of (shape, dtype, digest) is the keypath-free check
        if errs and not _content_only_match(manifest, raw):
            raise CheckpointCorruptError(
                f"step {s} under {base} failed content verification: "
                + "; ".join(errs[:5])
            )
    return raw


def restore_raw_checkpoint(ckpt_dir: str, step: Optional[int] = None) -> tuple:
    """Raw (no target tree) restore with manifest verification and the same
    newest-to-oldest fallback as the portable path (shared
    :func:`_try_newest_first` loop) — serves the model-only consumers
    (cli generate/serve/export-hf, which need ``params`` without a
    runtime). Returns ``(tree, step)``."""
    base = os.path.abspath(ckpt_dir)
    if step is not None:
        if not os.path.isdir(step_path(base, step)):
            # absence is not corruption: a typo'd step must not send the
            # operator hunting for storage faults
            raise FileNotFoundError(f"no step_{step} under {base}")
        return _restore_raw_at(base, step), step
    gc_stale_tmp(base)  # also recovers a .old from an interrupted swap
    steps = list(reversed(committed_steps(base)))
    if not steps:
        # inference-only consumers have no silent-restart risk, so
        # pre-manifest legacy dirs stay loadable (loudly, unverified) —
        # unlike the trainer, which refuses to resume from them
        legacy = list(reversed(uncommitted_steps(base)))
        if legacy:
            print(
                f"WARNING: no committed checkpoints under {base}; trying "
                f"pre-manifest legacy steps {legacy} WITHOUT content "
                "verification (re-save to commit them)"
            )
            steps = legacy
        else:
            raise FileNotFoundError(_no_checkpoints_message(base))
    return _try_newest_first(
        steps,
        lambda s: (_restore_raw_at(base, s), s),
        f"all {len(steps)} candidate checkpoints under {base} failed "
        "verification",
    )
