"""Sharded checkpoint save/restore with resume.

The reference's trainer never saves (SURVEY §5: only an unused --load_params
flag; the vendored Megatron checkpointing.py/dist_checkpointing are not
integrated). Here sharded save/restore is first-class via Orbax: each leaf is
written from its NamedSharding layout and restored into the (possibly
different) target sharding, so a run searched onto a new strategy can resume
from an old layout.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def save_checkpoint(ckpt_dir: str, state: Any, step: int) -> str:
    """Writes state (params/opt/step pytree) under ckpt_dir/step_N."""
    ocp = _ocp()
    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{step}")
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, state, force=True)
    ckptr.wait_until_finished()
    return path


def latest_step(ckpt_dir: str) -> Optional[int]:
    if not os.path.isdir(ckpt_dir):
        return None
    steps = []
    for name in os.listdir(ckpt_dir):
        if name.startswith("step_"):
            try:
                steps.append(int(name.split("_")[1]))
            except ValueError:
                pass
    return max(steps) if steps else None


def restore_checkpoint(ckpt_dir: str, abstract_state: Any, step: Optional[int] = None) -> Any:
    """Restores into the shardings carried by ``abstract_state`` (a pytree of
    jax.ShapeDtypeStruct with .sharding — e.g. from eval_shape + the runtime's
    state_shardings). Cross-strategy resume falls out: Orbax reshards on
    load.

    Layout note: the blocked fused-QKV change (models/modeling.py:qkv_dims)
    made MHA ``wqkv`` leaves rank-3; a checkpoint written by the earlier
    interleaved-only code no longer restores, and a silent reshape would
    scramble q/k/v (the interleave is per head-group, not per slot). Such a
    restore fails with an explicit migration error instead."""
    ocp = _ocp()
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {ckpt_dir}")
    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{step}")
    ckptr = ocp.StandardCheckpointer()
    try:
        return ckptr.restore(path, abstract_state)
    except Exception as e:
        msg = _legacy_layout_message(abstract_state, str(e))
        if msg:
            raise ValueError(msg) from e
        raise


def _legacy_layout_message(abstract_state: Any, err: str) -> Optional[str]:
    """Actionable message when a restore failure looks like one of the known
    parameter-layout changes rather than a corrupt checkpoint."""
    flat, _ = jax.tree_util.tree_flatten_with_path(abstract_state)

    def has(pred):
        return any(pred(kp, leaf) for kp, leaf in flat)

    low = err.lower()
    bias_keys = {"wqkv_b", "wo_b", "w1_b", "w2_b", "w13_b"}
    # Bias branch first, gated on a missing-key mismatch that NAMES a bias
    # leaf — orbax's structure-mismatch error lists the offending paths with
    # "Target: MISSING", and its ShapeDtypeStruct reprs mention "shape",
    # which would otherwise trip the wqkv branch. Errors that merely mention
    # a bias leaf without a missing-key mismatch (shape conflict, corrupt
    # array) must surface verbatim.
    if "missing" in low and any(bk in low for bk in bias_keys) and has(
        lambda kp, leaf: any(getattr(k, "key", None) in bias_keys for k in kp)
    ):
        return (
            "restore failed and the target model carries projection biases "
            "(use_bias — on by default for the gpt/bert presets since the "
            "GPT-2-faithful bias change): a checkpoint saved before that "
            "change has no *_b leaves. Re-export it with the producing "
            "revision, or add zero biases to the saved tree. Original "
            f"error: {err[:500]}"
        )
    if ("shape" in low or "rank" in low) and "missing" not in low and has(
        lambda kp, leaf: any(getattr(k, "key", None) == "wqkv" for k in kp)
        and hasattr(leaf, "shape")
        and len(leaf.shape) >= 3
    ):
        return (
            "checkpoint predates the blocked fused-QKV weight layout "
            "(wqkv is now (h, 3, n*head_dim) for non-GQA models): "
            "re-export it by loading with the producing revision and "
            "re-saving, e.g. transpose each wqkv from (h, n, 3, head_dim) "
            "column order to (h, 3, n*head_dim)"
        )
    return None


def save_checkpoint_portable(ckpt_dir: str, state: Any, step: int, runtime) -> str:
    """Save in the PORTABLE (flat-layers) layout: pipeline engines unstack
    their stage/virtual-stage parameter stacks first, so a checkpoint saved
    at any (pp, vpp, schedule, division) restores into any other — the
    cross-layout resume the reference cannot express (its trainer never
    saves at all, SURVEY §5)."""
    f = runtime.flatten_params
    if f is None:
        return save_checkpoint(ckpt_dir, state, step)

    def flatten_state(st):
        out = dict(st)
        out["params"] = f(st["params"])
        out["opt"] = {**st["opt"], "mu": f(st["opt"]["mu"]), "nu": f(st["opt"]["nu"])}
        return out

    # one compiled program instead of per-leaf eager slice dispatches
    flat = jax.jit(flatten_state)(state)
    return save_checkpoint(ckpt_dir, flat, step)


def _tree_keypaths(tree) -> set:
    from jax.tree_util import tree_flatten_with_path

    leaves, _ = tree_flatten_with_path(tree)
    return {jax.tree_util.keystr(kp) for kp, _ in leaves}


def _checkpoint_layout(
    ckpt_dir: str, step: Optional[int], flat_abstract, stacked_abstract
) -> Optional[str]:
    """POSITIVE layout detection: compare the on-disk checkpoint tree
    structure (orbax metadata) against the two candidate layouts instead of
    classifying restore-exception text (which breaks whenever orbax rewords
    a structure mismatch). Returns 'flat' | 'stacked' | 'neither', or None
    when the metadata itself cannot be read (caller falls back to
    try-restore + exception classification)."""
    ocp = _ocp()
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {ckpt_dir}")
    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{step}")
    try:
        meta = ocp.StandardCheckpointer().metadata(path)
        # StepMetadata wraps the saved tree; the tree itself flattens with
        # the same keypaths as the state pytree
        disk = _tree_keypaths(getattr(meta, "item_metadata", meta))
    except Exception:
        return None
    if disk == _tree_keypaths(flat_abstract):
        return "flat"
    if disk == _tree_keypaths(stacked_abstract):
        return "stacked"
    return "neither"


def restore_checkpoint_portable(ckpt_dir: str, runtime, step: Optional[int] = None) -> Any:
    """Restore a portable (flat-layout) checkpoint into the runtime's own
    layout, resharding as needed. Flat leaves restore under the per-layer
    GSPMD specs of the runtime's strategies (sharded over tp/dp, replicated
    over pp — a transient pp-fold duplication of each device's stage share),
    then a jitted restack lands them on the engine's stage stacks."""
    if runtime.restack_params is None:
        return restore_checkpoint(ckpt_dir, abstract_state_of(runtime), step)
    flat_abstract = flat_abstract_state_of(runtime)
    layout = _checkpoint_layout(
        ckpt_dir, step, flat_abstract, abstract_state_of(runtime)
    )
    if layout == "stacked":
        # pre-portable checkpoint in the engine's own stacked layout
        return restore_checkpoint(ckpt_dir, abstract_state_of(runtime), step)
    if layout == "neither":
        raise ValueError(
            "checkpoint matches neither the portable flat-layers layout "
            "nor this runtime's stacked layout — it was likely saved "
            "under a different pipeline configuration by a pre-portable "
            "revision; resume it once with its original configuration to "
            "re-save portably."
        )
    try:
        flat = restore_checkpoint(ckpt_dir, flat_abstract, step)
    except FileNotFoundError:
        raise
    except Exception as flat_err:
        if layout == "flat":
            # structure positively identified as flat: any failure here is a
            # real restore error, surface it verbatim
            raise
        # metadata unavailable (layout is None): fall back to the old
        # exception-text classification before trying the stacked layout
        low = str(flat_err).lower()
        mismatch_words = (
            "missing", "mismatch", "structure", "rank", "shape", "not found",
        )
        structural = isinstance(flat_err, (KeyError, TypeError))
        if not structural and not any(w in low for w in mismatch_words):
            raise
        try:
            return restore_checkpoint(ckpt_dir, abstract_state_of(runtime), step)
        except Exception:
            raise ValueError(
                "checkpoint matches neither the portable flat-layers layout "
                "nor this runtime's stacked layout — it was likely saved "
                "under a different pipeline configuration by a pre-portable "
                "revision; resume it once with its original configuration to "
                f"re-save portably. Flat-restore error: {str(flat_err)[:500]}"
            ) from flat_err
    r = runtime.restack_params

    def restack_state(st):
        out = dict(st)
        out["params"] = r(st["params"])
        out["opt"] = {**st["opt"], "mu": r(st["opt"]["mu"]), "nu": r(st["opt"]["nu"])}
        return out

    return jax.jit(restack_state, out_shardings=runtime.state_shardings)(flat)


def flat_abstract_state_of(runtime) -> Any:
    """Abstract flat-layout train state (the portable checkpoint schema):
    shapes from the flat model init + Adam moments, shardings from the
    per-layer GSPMD specs over the runtime's mesh."""
    import jax.numpy as jnp

    from galvatron_tpu.core.optim import init_opt_state
    from galvatron_tpu.models import modeling
    from galvatron_tpu.parallel.hybrid import state_specs
    from galvatron_tpu.parallel.sharding import sharding_tree

    def flat_init(key):
        params = modeling.init_model_params(key, runtime.cfg)
        st = {
            "params": params,
            "opt": init_opt_state(params),
            "step": jnp.zeros((), jnp.int32),
        }
        if "scaler" in runtime.state_shardings:
            from galvatron_tpu.core.schedules import LossScalerConfig, init_scaler_state

            st["scaler"] = init_scaler_state(LossScalerConfig())
        return st

    shapes = jax.eval_shape(flat_init, jax.random.key(0))
    specs = state_specs(shapes, runtime.cfg, runtime.hp, runtime.axes)
    shardings = sharding_tree(runtime.mesh, specs)
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes,
        shardings,
        is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
    )


def abstract_state_of(runtime, init_key=None) -> Any:
    """Abstract (shape+sharding) pytree for the runtime's train state."""
    import jax.numpy as jnp

    key = init_key if init_key is not None else jax.random.key(0)
    shapes = jax.eval_shape(runtime.init_state, key)
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes,
        runtime.state_shardings,
    )
