"""Elastic training: a preemption-aware supervisor around ``train()``.

Production TPU pods change topology under a run — preemptions, slice
shrinks, maintenance events are the dominant failure mode (`core/faults.py`
says so; Varuna EuroSys '21 and Bamboo NSDI '23 build whole systems around
it). PR 1 made single-topology crashes survivable and the observability
layer made runs legible; this module closes the loop by treating a topology
change as a *re-search event*: when the world shrinks from 8 to 4 devices,
re-run the DP for the new mesh and resume the portable checkpoint under
the new plan.

Two entry points:

- **supervisor** (``cli run-elastic`` → :func:`run_elastic`): spawns the
  training run as a child process, classifies every exit, and decides
  restart / backoff / give-up. It deliberately never touches the JAX
  backend (on a real pod the child owns the devices), so all
  topology-sensitive work happens child-side.
- **child** (``python -m galvatron_tpu.core.elastic child …`` →
  :func:`child_main`): compares the checkpoint's topology fingerprint
  against the live ``jax.device_count()`` (GTA017), re-plans on mismatch
  (`search/replan.py`: cache hit or a fresh ``SearchEngine`` run), then
  runs ``train()`` — which resumes via ``restore_checkpoint_portable``
  (resharding is free) with the data cursor converted from the batch
  domain to the sample domain (trainer) — and exits with a
  mode-describing code.

Exit-code contract (child → supervisor)::

    0                 completed      train_iters reached; supervision done
    75 EXIT_PREEMPTED preempted      SIGTERM/SIGINT observed; state saved
    76 EXIT_ANOMALY   anomaly_abort  AnomalyAbort (NaN budget exhausted)
    77 EXIT_HANG      hang           watchdog-declared stalled step
    78 EXIT_REPLAN_INFEASIBLE        no plan fits the live topology
    anything else     crash          unhandled exception / hard kill

Decisions: ``completed`` ends the run; ``anomaly_abort`` and
``replan_infeasible`` give up immediately (the skip budget already proved
restarting replays the same poison — resume never re-grants skips — and
an infeasible re-search is deterministic); ``preempted`` restarts immediately
(the child checkpointed; backoff would only waste the pod); ``crash`` and
``hang`` restart under `core/retry.py`-style exponential backoff with full
jitter, bounded by ``--max_restarts`` *consecutive restarts without
progress* — a newer committed checkpoint step resets the crash-loop
counter, so a month-long run is never budgeted like a boot loop. Every
decision is a tracer event, a JSONL record (``<save>/elastic_events.jsonl``)
and a flight-recorder note.

Chaos simulation: ``GALVATRON_FAULTS`` is handed to the FIRST child only
(the injected fault happens once; recovery must then be fault-free), and
``GALVATRON_FAULTS_WORLD="8,4"`` runs child k on a virtual CPU platform of
the k-th width (clamped to the last entry) — a reproducible 8→4 shrink on
any host, across real process restarts.

Preemption-aware extensions (this supervisor side):

- ``--peer_replicate N`` spawns N in-memory peer-store daemons
  (`core/peer_store.py`) standing in for surviving hosts' RAM; every child
  gets their addresses (``GALVATRON_PEER_STORE``) and ring-replicates each
  interval save. A child killed without grace then restores from the
  replica — newer than anything disk holds when storage was out.
- ``--heartbeat_timeout_s T`` makes the default spawn a monitored
  ``Popen``: the child beats ``<save>/heartbeat`` every step
  (``GALVATRON_HEARTBEAT_FILE``) and a stale beat gets the child SIGKILLed
  and accounted as a hang — the last line of defense when the child is too
  wedged for its own in-process watchdog.
- a graceful preemption WITH progress is a *free* restart
  (`core/restart_policy.py`): spot capacity can be evicted more than
  ``--max_restarts`` times in a healthy week.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from galvatron_tpu.core.watchdog import EXIT_HANG

EXIT_COMPLETED = 0
EXIT_PREEMPTED = 75  # EX_TEMPFAIL: the child saved and expects to be rerun
EXIT_ANOMALY = 76
# no feasible plan exists for the live topology under the re-plan budget:
# restarting would re-run the identical doomed search — supervisor gives up
EXIT_REPLAN_INFEASIBLE = 78

_EXIT_MODES = {
    EXIT_COMPLETED: "completed",
    EXIT_PREEMPTED: "preempted",
    EXIT_ANOMALY: "anomaly_abort",
    EXIT_HANG: "hang",
    EXIT_REPLAN_INFEASIBLE: "replan_infeasible",
}

#: child-side env var: force an N-device virtual CPU platform (set by the
#: supervisor from GALVATRON_FAULTS_WORLD; never set on real hardware)
SIM_WORLD_ENV = "GALVATRON_ELASTIC_SIM_WORLD"


def classify_exit(returncode: int) -> str:
    """Child exit → mode name (negative = killed by signal = crash)."""
    return _EXIT_MODES.get(returncode, "crash")


# ---------------------------------------------------------------------------
# child
# ---------------------------------------------------------------------------


def _bootstrap_sim_world() -> None:
    """Apply the supervisor's simulated-topology override BEFORE the first
    backend touch. Delegates to ``aot/warmup.force_cpu_world`` — the one
    copy of the XLA_FLAGS + platform-pin recipe (the program key hashes the
    resulting XLA_FLAGS tokens, so the warmup and elastic recipes must
    never drift apart)."""
    n = os.environ.get(SIM_WORLD_ENV)
    if not n:
        return
    from galvatron_tpu.aot.warmup import force_cpu_world

    force_cpu_world(int(n))


def prepare_topology(ns, verbose: bool = True) -> Optional[Dict[str, Any]]:
    """Child-side pre-train resolution of a topology change.

    Reads the newest committed checkpoint's topology fingerprint and
    compares it with the live device count. On mismatch (GTA017) a plan for
    the live mesh is resolved — from the plan caches or a fresh search —
    validated, and installed as ``ns.galvatron_config_path``;
    ``ns.allow_topology_change`` marks the resume as supervised so the
    trainer's own GTA017 gate admits it. Returns a summary dict when a
    re-plan happened, else None."""
    load = getattr(ns, "load", None)
    if not load:
        return None
    fp = _read_fingerprint(load)
    if not fp:
        return None  # no committed step, or a pre-elastic checkpoint

    import jax

    from galvatron_tpu.analysis import plan_check
    from galvatron_tpu.analysis.diagnostics import format_report
    from galvatron_tpu.obs.tracing import tracer

    world = jax.device_count()
    diags = plan_check.check_topology_fingerprint(fp, world, source=load)
    if not diags:
        # same topology: keep PLAN CONTINUITY. After an earlier restart
        # re-planned (shrink), this restart sees a matching world and the
        # ORIGINAL argv flags — which describe the pre-shrink plan; without
        # this, one more crash silently abandons the re-searched strategy.
        adopt_recorded_plan(ns, fp, world, verbose=verbose)
        return None
    # topology changed: this is the re-search event
    if verbose:
        print(format_report(diags))
    from galvatron_tpu.core.arguments import (
        model_config_from_args,
        resolve_execution_config,
    )
    from galvatron_tpu.search.replan import resolve_plan_for_topology

    cfg = resolve_execution_config(model_config_from_args(ns), ns)
    from galvatron_tpu.search.replan import default_cache_dirs

    replan_dir = os.path.join(os.path.abspath(load), "replans")
    plan_path, source = resolve_plan_for_topology(
        cfg,
        world,
        int(ns.global_train_batch_size),
        cache_dirs=default_cache_dirs(load),
        out_dir=replan_dir,
        model_name=getattr(ns, "model_size", "") or "",
        search_space=getattr(ns, "replan_search_space", "full"),
        memory_gb=getattr(ns, "replan_memory_gb", 16.0),
        mixed_precision=getattr(ns, "mixed_precision", "bf16"),
        verbose=verbose,
    )
    # validate against the LIVE topology before handing it to the trainer
    # (a cached plan passed check_plan in the lookup; a searched one was
    # self-checked by save_result — this re-check is the belt to those
    # braces, and gives file provenance on failure)
    plan_check.ensure_valid(
        plan_path, model_config=cfg, world_size=world,
        global_bsz=ns.global_train_batch_size,
        memory_budget_mb=getattr(ns, "replan_memory_gb", 16.0) * 1024.0,
        context=f"re-planned strategy invalid for the live mesh: {plan_path}",
        verbose=verbose,
    )
    ns.galvatron_config_path = plan_path
    ns.allow_topology_change = True
    tracer.instant(
        "replan", old_world=fp.get("world_size"), new_world=world,
        plan=plan_path, source=source,
    )
    info = {
        "old_world": fp.get("world_size"),
        "new_world": world,
        "plan_path": plan_path,
        "source": source,
        "old_plan_hash": fp.get("plan_hash"),
    }
    # prewarm the NEW plan's programs as part of the re-plan, BEFORE
    # training starts (galvatron_tpu/aot): restart downtime under a fresh
    # strategy becomes a cache lookup, and the trainer's startup consult
    # then proves the programs warm — shrinking the watchdog's first-step
    # compile grace to the normal deadline
    info["prewarm"] = _prewarm_plan(ns, plan_path, verbose=verbose)
    if verbose:
        print(
            f"topology change: {fp.get('world_size')} → {world} devices; "
            f"resuming under {plan_path} ({source})"
        )
    return info


def _prewarm_plan(ns, plan_path: str, verbose: bool = True) -> Optional[Dict[str, Any]]:
    """AOT-compile the plan's trainer programs into the compile-artifact
    cache (aot/warmup.py).  Best-effort by contract: a prewarm failure costs
    only warmth — the child trains exactly as it would have cold."""
    from galvatron_tpu.aot.cache import resolve_compile_cache_dir

    cache_dir = resolve_compile_cache_dir(ns)
    if not cache_dir:
        return None
    try:
        from galvatron_tpu.aot import warmup as aot_warmup
        from galvatron_tpu.aot.cache import ArtifactStore, enable_persistent_cache
        from galvatron_tpu.core.arguments import (
            adam_config_from_args,
            model_config_from_args,
            resolve_execution_config,
        )
        from galvatron_tpu.core.strategy import HybridParallelConfig
        from galvatron_tpu.obs.tracing import tracer

        # mirror the trainer's own config resolution (pack_sequences rides
        # the model config BEFORE attention resolution) so the prewarmed
        # programs are the programs the run will ask for
        cfg = model_config_from_args(ns)
        if getattr(ns, "pack_sequences", 0):
            cfg = cfg.replace(pack_sequences=True)
        cfg = resolve_execution_config(cfg, ns)
        store = ArtifactStore(enable_persistent_cache(cache_dir, override=True))
        # train_step only: a re-planned child RESUMES (restore, never init),
        # and eval_loss belongs to `cli warmup` — the step program is the
        # whole first-step compile the restart would otherwise pay
        reports = aot_warmup.warmup_plan(
            cfg, HybridParallelConfig.load(plan_path),
            global_bsz=int(ns.global_train_batch_size),
            store=store, include=("train_step",),
            adam=adam_config_from_args(ns), verbose=verbose,
        )
        # hand the SAME store to the trainer: its startup consult now
        # reports hits and arms the reduced first-step watchdog grace
        ns.compile_cache_dir = store.dir
        summ = aot_warmup.summarize(reports)
        tracer.instant("replan_prewarm", **summ)
        if verbose:
            print(
                f"re-plan prewarm: {summ['compiled']}/{summ['programs']} "
                f"programs warm ({summ['total_compile_ms']:.0f} ms compile)"
            )
        return summ
    except Exception as e:  # noqa: BLE001 — warmth is optional, training is not
        print(f"re-plan prewarm failed (continuing cold): "
              f"{type(e).__name__}: {str(e)[:200]}")
        return None


def adopt_recorded_plan(ns, fp: Dict[str, Any], world: int,
                        verbose: bool = True) -> Optional[str]:
    """Same-topology restart: if the checkpoint's recorded ``plan_hash``
    differs from the plan the argv flags produce, adopt the cached plan
    file with that hash (``<ckpt>/replans/`` first, then
    ``configs/strategies/``) so the run keeps training the strategy it was
    actually on. No hash-matching file → the argv plan proceeds (a legal
    cross-plan resume; the trainer logs ``plan_change``). Returns the
    adopted path, or None."""
    want = fp.get("plan_hash")
    if not want or not getattr(ns, "load", None):
        return None
    from galvatron_tpu.core.arguments import (
        hybrid_config_from_args,
        model_config_from_args,
        resolve_execution_config,
    )
    from galvatron_tpu.core.strategy import plan_hash

    try:
        cfg = resolve_execution_config(model_config_from_args(ns), ns)
        if plan_hash(hybrid_config_from_args(ns, cfg.total_layers, world)) == want:
            return None  # argv already describes the recorded plan
    except Exception:
        return None  # argv plan undecodable here: the trainer will report it
    from galvatron_tpu.search.replan import default_cache_dirs, find_plan_by_hash

    path = find_plan_by_hash(default_cache_dirs(ns.load), want)
    if path is not None:
        ns.galvatron_config_path = path
        if verbose:
            print(f"plan continuity: resuming under the checkpoint's "
                  f"recorded plan {path}")
    return path


def child_main(argv: List[str], model_default: Optional[str] = None) -> int:
    """One supervised training attempt; returns the exit-contract code.

    Everything that must see the live backend happens here: the simulated-
    world bootstrap, the fingerprint comparison, the re-plan, and
    ``train()`` itself. ``AnomalyAbort`` maps to its code; any other
    exception prints its traceback and maps to a hard crash (nonzero from
    ``__main__``)."""
    _bootstrap_sim_world()
    from galvatron_tpu.core.arguments import initialize_galvatron
    from galvatron_tpu.core.resilience import AnomalyAbort

    ns = initialize_galvatron("train", argv, model_default)
    # a supervised child running under the hang watchdog consults the
    # compile-artifact cache automatically: the warm hint exists to shrink
    # the watchdog's blind first-step compile grace, and the restart
    # lifecycle is exactly where a warm program cache pays. Without a
    # watchdog the consult stays opt-in (--compile_cache_dir) — the first
    # step then compiles lazily exactly as before, still served by any
    # configured persistent cache. The re-plan path prewarms + arms the
    # consult regardless (prepare_topology).
    if not getattr(ns, "compile_cache_dir", None) and getattr(ns, "step_timeout_s", 0):
        from galvatron_tpu.aot.cache import resolve_compile_cache_dir

        resolved = resolve_compile_cache_dir(ns)
        if resolved:
            ns.compile_cache_dir = resolved
    from galvatron_tpu.search.replan import ReplanInfeasibleError

    try:
        prepare_topology(ns)
        from galvatron_tpu.core.trainer import train

        out = train(ns)
    except AnomalyAbort as e:
        print(f"anomaly abort: {e}", file=sys.stderr, flush=True)
        return EXIT_ANOMALY
    except ReplanInfeasibleError as e:
        print(f"re-plan infeasible: {e}", file=sys.stderr, flush=True)
        return EXIT_REPLAN_INFEASIBLE
    if out.get("signaled") is not None:
        return EXIT_PREEMPTED
    return EXIT_COMPLETED


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------


def _read_fingerprint(save_dir: Optional[str]) -> Dict[str, Any]:
    """Newest committed checkpoint's fingerprint meta — pure file reads, no
    backend; shared by the supervisor's gauges and the child's GTA017 gate
    (one extraction, so the two views cannot diverge). Empty dict when
    there is no committed step or the checkpoint predates fingerprints."""
    if not save_dir:
        return {}
    from galvatron_tpu.core.checkpoint import latest_step, read_manifest, step_path

    step = latest_step(save_dir)
    if step is None:
        return {}
    m = read_manifest(step_path(save_dir, step))
    meta = m.get("meta") if m and isinstance(m.get("meta"), dict) else {}
    fp = meta.get("fingerprint")
    return fp if isinstance(fp, dict) else {}


def child_pythonpath_env(base_env: Dict[str, str]) -> Dict[str, str]:
    """Child-process env with the repo root on PYTHONPATH regardless of the
    child's cwd. Join only a NON-EMPTY inherited value: "<root>:" would put
    an empty entry — i.e. the child's cwd — on sys.path, letting a stray
    json.py in the operator's launch dir shadow the stdlib only inside
    children. Shared by this supervisor and the fleet router's replica
    spawns (serving/fleet.py) — one copy of the rule."""
    env = dict(base_env)
    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    prior = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = root + os.pathsep + prior if prior else root
    return env


def _child_env(base_env: Dict[str, str], attempt: int, worlds: List[int]) -> Dict[str, str]:
    env = child_pythonpath_env(base_env)
    if worlds:
        env[SIM_WORLD_ENV] = str(worlds[min(attempt, len(worlds) - 1)])
    if attempt > 0:
        # chaos injection is one-shot: the fault happened; the restarted
        # child proves RECOVERY, and re-arming kill_mid_save=1 in every
        # child would turn one injected fault into an injected crash loop
        env.pop("GALVATRON_FAULTS", None)
    return env


def run_elastic(
    argv: List[str],
    model_default: Optional[str] = None,
    spawn=None,
) -> int:
    """The supervisor loop (``cli run-elastic``). Returns a process exit
    code: 0 when a child completed, 1 on give-up (anomaly abort, restart
    budget exhausted, or a re-plan that found nothing feasible).

    ``spawn`` (tests) replaces the subprocess launch: a callable
    ``(cmd, env) -> returncode``."""
    from galvatron_tpu.core import faults
    from galvatron_tpu.core.arguments import initialize_galvatron
    from galvatron_tpu.core.restart_policy import RestartPolicy
    from galvatron_tpu.obs.tracing import tracer
    from galvatron_tpu.utils.metrics import MetricsLogger

    ns = initialize_galvatron("train", argv, model_default)
    # supervisor decisions are forensic events: with crash forensics asked
    # for (--flight_dir / --trace_spans) the tracer ring records them; the
    # JSONL event log below is unconditional when --save exists
    own_tracer = False
    if getattr(ns, "flight_dir", None) or getattr(ns, "trace_spans", None):
        if not tracer.enabled:
            tracer.enable(capacity=getattr(ns, "trace_ring", 4096))
            own_tracer = True
    events = MetricsLogger(
        os.path.join(ns.save, "elastic_events.jsonl") if ns.save else None
    )
    from galvatron_tpu.obs.prom import ElasticStats, ObsServer

    stats = ElasticStats()
    stats.watchdog_armed = bool(getattr(ns, "step_timeout_s", 0))
    obs_server = None
    if getattr(ns, "obs_port", 0):
        # the SUPERVISOR owns the sidecar port (the child gets --obs_port 0
        # appended — two listeners on one port is a bind error): an operator
        # scraping a supervised run needs the restart story, not one
        # child-lifetime of gauges that dies with every preemption
        obs_server = ObsServer(stats.render, port=ns.obs_port, health_fn=stats.health)
        run_elastic.last_obs_port = obs_server.port  # tests scrape the ephemeral port
        print(f"elastic supervisor sidecar: http://127.0.0.1:{obs_server.port}/healthz")
        # child train-gauge aggregation: the child logs train_iter JSONL to
        # --metrics_path and the sidecar tails the last 64KB at scrape time
        # (prom.ElasticStats.child_train_gauges) — mfu/bubble/tokens_per_s
        # survive on the supervisor's scrape target across child restarts
        # with no IPC and no second port. A user-passed --metrics_path is
        # honored; otherwise one is injected beside the checkpoints.
    # the child metrics JSONL is always placed (sidecar or not): the
    # supervisor's recovery accounting below tails it for the child's
    # `recovery` events, which is how MTTR becomes a supervisor-side fact
    if getattr(ns, "metrics_path", None):
        stats.child_metrics_path = ns.metrics_path
    elif ns.save:
        stats.child_metrics_path = os.path.join(ns.save, "train_metrics.jsonl")
    worlds = faults.world_schedule()
    # the shared supervisor decision table (core/restart_policy.py):
    # consecutive-no-progress budget, progress-resets-streak, full-jitter
    # backoff — identical arithmetic to the serving EngineSupervisor and
    # the fleet router's replica supervision
    policy = RestartPolicy(
        max_restarts=ns.max_restarts,
        backoff_s=ns.restart_backoff_s,
        backoff_cap_s=ns.restart_backoff_cap_s,
    )
    user_spawn = spawn is not None
    if spawn is None:
        spawn = lambda c, env: subprocess.call(c, env=env)  # noqa: E731

    def _child_cmd() -> List[str]:
        # the preemption lifecycle IS resume: once the run's own --save dir
        # holds a committed step, every child restarts from it (overriding,
        # argparse last-wins, an explicit --load warm start that is now
        # stale). Before the first save, the user's --load (or a fresh
        # init) applies.
        child_argv = list(argv) + ["--obs_port", "0"]
        if stats.child_metrics_path and not getattr(ns, "metrics_path", None):
            # injected (not user-passed): give the child the sidecar's
            # tail target so its train_iter gauges aggregate upward
            child_argv += ["--metrics_path", stats.child_metrics_path]
        if ns.save and (
            not getattr(ns, "load", None) or _last_step(ns.save) is not None
        ):
            child_argv += ["--load", ns.save]
        return [sys.executable, "-m", "galvatron_tpu.core.elastic", "child"] + child_argv

    def note(event: str, **fields):
        events.log(event, **fields)
        tracer.instant(f"elastic_{event}", **fields)

    # --- in-memory peer replica tier (--peer_replicate N) ---------------
    # N peer-store daemons stand in for the OTHER hosts of the slice: their
    # RAM outlives any one child, so a child killed without grace restores
    # from its ring neighbor instead of the last disk commit. Best-effort
    # by contract — a daemon that fails to come up degrades the run to
    # disk-only, it never blocks training.
    from galvatron_tpu.core import peer_store as peer_store_mod

    peer_n = int(getattr(ns, "peer_replicate", 0) or 0)
    peer_procs: List[subprocess.Popen] = []
    peer_addrs: List[str] = []
    if peer_n > 0:
        import tempfile

        ann_dir = tempfile.mkdtemp(prefix="galvatron_peers_")
        try:
            for i in range(peer_n):
                ann = os.path.join(ann_dir, f"peer{i}.addr")
                peer_procs.append(subprocess.Popen(
                    [sys.executable, "-m", "galvatron_tpu.core.peer_store",
                     "serve", "--announce", ann],
                    env=child_pythonpath_env(os.environ),
                ))
                deadline = time.monotonic() + 30.0
                while not (os.path.exists(ann) and os.path.getsize(ann)):
                    if peer_procs[-1].poll() is not None:
                        raise RuntimeError(
                            f"peer store {i} exited rc={peer_procs[-1].returncode}"
                        )
                    if time.monotonic() > deadline:
                        raise RuntimeError(f"peer store {i} never announced")
                    time.sleep(0.05)
                with open(ann) as f:
                    peer_addrs.append(f.read().strip())
            note("peer_store_start", count=peer_n,
                 addrs=",".join(peer_addrs))
        except Exception as e:  # noqa: BLE001 — RAM tier is optional
            print(f"run-elastic: peer stores unavailable ({e}); "
                  f"continuing disk-only", file=sys.stderr, flush=True)
            for p in peer_procs:
                p.kill()
            peer_procs, peer_addrs = [], []

    # --- supervisor-side heartbeat watchdog (--heartbeat_timeout_s) -----
    from galvatron_tpu.core.watchdog import HEARTBEAT_ENV, HeartbeatMonitor

    hb_timeout = float(getattr(ns, "heartbeat_timeout_s", 0) or 0)
    hb_path = None
    if hb_timeout > 0:
        hb_path = (
            os.path.join(ns.save, "heartbeat") if ns.save
            else os.path.join(
                __import__("tempfile").gettempdir(),
                f"galvatron_hb_{os.getpid()}",
            )
        )
        stats.watchdog_armed = True
    if hb_timeout > 0 and not user_spawn:
        def spawn(cmd, env, _hb=hb_path):  # noqa: F811 — monitored default
            # fresh file per child: a stale beat from the previous
            # incarnation must not vouch for this one
            try:
                os.remove(_hb)
            except OSError:
                pass
            mon = HeartbeatMonitor(
                _hb,
                # the first beat waits out XLA compilation — same
                # compile-length grace reasoning as HangWatchdog's warmup
                first_beat_grace_s=max(20.0 * hb_timeout, 120.0),
            )
            proc = subprocess.Popen(cmd, env=env)
            poll_s = max(0.05, min(0.5, hb_timeout / 4.0))
            while True:
                rc = proc.poll()
                if rc is not None:
                    return rc
                if mon.stale(hb_timeout):
                    age = mon.last_beat_age_s()
                    note("watchdog_kill", reason="heartbeat_stale",
                         age_s=None if age is None else round(age, 2),
                         timeout_s=hb_timeout)
                    print(
                        f"run-elastic: child heartbeat stale "
                        f"(> {hb_timeout}s); killing child",
                        file=sys.stderr, flush=True,
                    )
                    proc.kill()
                    proc.wait()
                    return EXIT_HANG
                time.sleep(poll_s)

    attempt = 0  # children launched so far
    rc_final = 1
    prev_exit_ts: Optional[float] = None  # wall time the last child died
    recovery_seen_ts = 0.0  # newest child `recovery` event already counted
    note("supervisor_start", max_restarts=ns.max_restarts,
         step_timeout_s=float(getattr(ns, "step_timeout_s", 0) or 0),
         sim_worlds=",".join(map(str, worlds)) if worlds else None)
    try:
        while True:
            prev_step = _last_step(ns.save)
            env = _child_env(os.environ, attempt, worlds)
            if peer_addrs:
                env[peer_store_mod.ADDRS_ENV] = ",".join(peer_addrs)
                env[peer_store_mod.RANK_ENV] = "0"
            if hb_path:
                env[HEARTBEAT_ENV] = hb_path
            stats.child_alive = True
            stats.world_size = int(env[SIM_WORLD_ENV]) if SIM_WORLD_ENV in env else None
            note("child_start", attempt=attempt,
                 world=stats.world_size, resumed_from=prev_step)
            rc = spawn(_child_cmd(), env)
            stats.child_alive = False
            exit_ts = time.time()
            # recovery accounting: the child logs a `recovery` event when it
            # restored (peer replica or disk); MTTR is that event's wall
            # time minus the PREVIOUS child's death — the operator's "how
            # long was the run actually down".
            for ev in _scan_recoveries(stats.child_metrics_path,
                                       recovery_seen_ts):
                recovery_seen_ts = max(recovery_seen_ts, float(ev.get("ts") or 0.0))
                stats.recoveries_total += 1
                stats.last_recovery_source = ev.get("source")
                mttr_ms = None
                if prev_exit_ts is not None and isinstance(
                    ev.get("ts"), (int, float)
                ):
                    mttr_ms = max(0.0, (ev["ts"] - prev_exit_ts) * 1000.0)
                    stats.last_recovery_ms = mttr_ms
                note("recovery_observed", source=ev.get("source"),
                     step=ev.get("step"),
                     mttr_ms=None if mttr_ms is None else round(mttr_ms, 1))
            prev_exit_ts = exit_ts
            mode = classify_exit(rc)
            new_step = _last_step(ns.save)
            progressed = new_step is not None and (
                prev_step is None or new_step > prev_step
            )
            fp = _read_fingerprint(ns.save)
            stats.last_exit_mode = mode
            stats.last_exit_code = rc
            stats.last_step = new_step
            if fp.get("plan_hash"):
                if stats.current_plan_hash not in (None, fp["plan_hash"]):
                    stats.replans_total += 1
                stats.current_plan_hash = fp["plan_hash"]
            note("child_exit", attempt=attempt, code=rc, mode=mode,
                 step=new_step, progressed=progressed,
                 plan_hash=fp.get("plan_hash"))
            attempt += 1
            if mode == "completed":
                print(f"run-elastic: completed after {attempt} attempt(s), "
                      f"{stats.restarts_total} restart(s)")
                note("supervisor_done", attempts=attempt,
                     restarts=stats.restarts_total, step=new_step)
                rc_final = 0
                break
            if mode == "anomaly_abort":
                # the skip budget is already resume-aware (never re-granted):
                # restarting replays the same poisoned data into an
                # exhausted budget — a decision only an operator can change
                print("run-elastic: giving up — anomaly abort (NaN skip "
                      "budget exhausted; restarting would replay the same "
                      "data)", file=sys.stderr, flush=True)
                note("give_up", reason="anomaly_abort", attempts=attempt)
                break
            if mode == "replan_infeasible":
                # deterministic: the identical search would fail on every
                # restart — only --replan_memory_gb / a bigger mesh fixes it
                print("run-elastic: giving up — no feasible plan for the "
                      "live topology under --replan_memory_gb",
                      file=sys.stderr, flush=True)
                note("give_up", reason="replan_infeasible", attempts=attempt)
                break
            # preempted children checkpointed and exited on a signal: restart
            # immediately — a preemption is the *expected* lifecycle, and
            # backoff here only donates pod-hours to the void (the failure
            # still counts against the no-progress budget)
            decision = policy.on_failure(
                progressed, immediate=(mode == "preempted"),
                # a graceful preemption that made progress is the platform's
                # EXPECTED lifecycle, not a failure of the run: it costs no
                # restart budget (spot capacity can be evicted more than
                # --max_restarts times in a healthy week). Preemptions
                # WITHOUT progress still count — a preempt-loop that never
                # advances must exhaust the budget.
                free=(mode == "preempted" and progressed),
            )
            if decision.give_up:
                print(f"run-elastic: giving up — {decision.consecutive} "
                      f"consecutive restarts without progress "
                      f"(--max_restarts {ns.max_restarts})",
                      file=sys.stderr, flush=True)
                note("give_up", reason="restart_budget", attempts=attempt,
                     consecutive=decision.consecutive)
                break
            # the eviction notice belongs to the OLD placement: a real
            # rescheduled host starts with a clean metadata flag, so the
            # supervisor clears the simulated one — a stale notice would
            # make every restarted child drain immediately, a preempt loop
            # that never advances
            notice_path = getattr(ns, "preempt_notice_file", None) or \
                os.environ.get("GALVATRON_PREEMPT_NOTICE")
            if mode == "preempted" and notice_path:
                try:
                    os.remove(notice_path)
                    note("preempt_notice_cleared", path=notice_path)
                except FileNotFoundError:
                    pass
                except OSError:
                    pass
            delay = decision.backoff_s
            stats.restarts_total += 1
            note("restart", attempt=attempt, mode=mode,
                 consecutive=decision.consecutive, backoff_s=round(delay, 3))
            print(f"run-elastic: child exit {rc} ({mode}); restart "
                  f"{stats.restarts_total} in {delay:.2f}s")
            if delay:
                time.sleep(delay)
    finally:
        if ns.save and getattr(ns, "flight_dir", None):
            from galvatron_tpu.obs.flight import dump_flight

            dump_flight(
                ns.flight_dir, tracer,
                reason=f"supervisor exit rc={rc_final} "
                       f"(last child: {stats.last_exit_mode})",
                extra={"restarts_total": stats.restarts_total},
            )
        # peer-store daemons die with their supervisor: their whole point is
        # RAM that outlives any one CHILD — an orphaned daemon after the
        # run would just hold a stale replica nobody can restore
        for p in peer_procs:
            try:
                p.terminate()
                p.wait(timeout=5)
            except Exception:  # noqa: BLE001 — teardown is best-effort
                p.kill()
        events.close()
        if obs_server is not None:
            obs_server.close()
        if own_tracer:
            tracer.disable()
            tracer.clear()
    return rc_final


def _scan_recoveries(metrics_path: Optional[str],
                     since_ts: float) -> List[Dict[str, Any]]:
    """Child ``recovery`` events newer than ``since_ts`` from the child's
    train-metrics JSONL. Pure file read, tolerant of a missing/torn file —
    recovery accounting must never take down the supervisor."""
    if not metrics_path or not os.path.exists(metrics_path):
        return []
    from galvatron_tpu.utils.metrics import read_metrics

    try:
        recs = read_metrics(metrics_path)
    except Exception:  # noqa: BLE001 — accounting is best-effort
        return []
    return [
        r for r in recs
        if r.get("event") == "recovery"
        and float(r.get("ts") or 0.0) > since_ts
    ]


def _last_step(save_dir: Optional[str]) -> Optional[int]:
    if not save_dir:
        return None
    from galvatron_tpu.core.checkpoint import latest_step

    return latest_step(save_dir)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "child":
        return child_main(argv[1:])
    return run_elastic(argv)


if __name__ == "__main__":
    raise SystemExit(main())
