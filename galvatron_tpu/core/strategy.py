"""Per-layer hybrid-parallelism strategy representation and codecs.

The reference encodes a model-wide hybrid strategy as per-layer integer vectors
{pp_deg, tp_sizes_enc, tp_consecutive_flags, dp_types_enc, checkpoint_flags_enc}
(reference: galvatron/core/hybrid_parallel_config.py:13-87) plus a compact
string form ``pp-tp-dp[f][*][-c]`` (galvatron/utils/strategy_utils.py:3-48) and
a JSON interchange file ``galvatron_config_*.json`` with comma-joined strings
(galvatron/core/search_engine.py:326-367).

Here a strategy is a small frozen dataclass per transformer layer, a model-wide
``HybridParallelConfig``, and loss-free codecs to/from the reference-compatible
JSON schema so searched configs round-trip between the search engine and the
runtime.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

DP_TYPES = ("ddp", "zero2", "zero3")
# Integer encoding used in config JSON, matching the reference's dp_types_enc
# (0 = default dp type, 1 = fsdp/zero3; we extend with explicit names).
_DP_TYPE_TO_INT = {"ddp": 0, "zero2": 0, "zero3": 1}

# Activation-recompute modes. The reference has full-layer checkpoint_wrapper
# wrapping (galvatron/core/parallel.py:109-132) plus Megatron's "selective"
# core-attention-only recompute (galvatron/core/tensor_parallel/
# transformer.py:597,615-636). JSON encoding extends the reference's 0/1
# `checkpoint` flags with 2 = selective.
_CKPT_NORMALIZE = {
    # bool keys omitted: False==0 / True==1 hash-equal, so 0/1 cover them
    0: False, "none": False, "": False, None: False,
    1: "full", "full": "full",
    2: "selective", "selective": "selective",
}
_CKPT_TO_INT = {False: 0, "full": 1, "selective": 2}


def _is_pow2(n: int) -> bool:
    return n >= 1 and (n & (n - 1)) == 0


@dataclass(frozen=True)
class LayerStrategy:
    """Hybrid-parallelism strategy for one transformer layer.

    Attributes:
      tp: tensor-parallel degree (power of two).
      tp_consec: if True, TP occupies the minor (adjacent-device) mesh axes —
        the reference's "consecutive" rank layout; if False the major axes
        (strided layout). (reference: galvatron/core/comm_groups.py:58-89)
      dp_type: 'ddp' (replicated params), 'zero2' (sharded optimizer state),
        'zero3' (fully sharded params — FSDP FULL_SHARD equivalent).
        (reference: galvatron/core/parallel.py:30-32)
      ckpt: activation rematerialization for this layer — False, 'full'
        (whole-layer remat; reference: checkpoint_wrapper wrapping,
        galvatron/core/parallel.py:109-132) or 'selective' (core-attention-only
        recompute; reference: transformer.py:597,615-636). Truthiness works:
        ``if s.ckpt`` means "any recompute".
      sp: Megatron-style sequence parallelism — activations sequence-sharded
        over the TP axes between blocks (reference: site_package/megatron/core/
        tensor_parallel/mappings_group.py:192-293).
      cp: context-parallel degree over the minor data axes; 1 disables. A
        TPU-native capability the reference lacks (SURVEY §5).
      cp_impl: 'ring' (K/V rotation with online softmax, parallel/ring.py) or
        'a2a' (Ulysses sequence↔head all-to-all, parallel/ulysses.py; needs
        num_heads % cp == 0).
      ep: expert-parallel degree for MoE layers — experts sharded over the
        minor data-parallel axes (reference EP groups: site_package/megatron/
        core/parallel_state.py:450-478; SwitchMLP transformer.py:161-295).
      tp_overlap: decomposed collective-matmul on the TP projection seams —
        the qkv/MLP-up all-gather and the output-projection reduce-scatter
        are pipelined against the matmul via shard_map/ppermute
        (ops/collective_matmul.py; Wang et al., ASPLOS'23) instead of left
        to GSPMD as blocking collectives. Only meaningful with tp>1 — the
        plan checker rejects tp_overlap on tp==1 layers (GTA018).
    """

    tp: int = 1
    tp_consec: bool = True
    dp_type: str = "ddp"
    ckpt: Any = False  # False | 'full' | 'selective' (True/0/1/2 accepted)
    sp: bool = False
    cp: int = 1
    ep: int = 1
    cp_impl: str = "ring"
    tp_overlap: bool = False

    def __post_init__(self):
        try:
            object.__setattr__(self, "ckpt", _CKPT_NORMALIZE[self.ckpt])
        except (KeyError, TypeError):
            raise ValueError(
                f"ckpt must be one of False/'full'/'selective' (or 0/1/2), got {self.ckpt!r}"
            )
        if not _is_pow2(self.tp):
            raise ValueError(f"tp degree must be a power of two, got {self.tp}")
        if not _is_pow2(self.cp):
            raise ValueError(f"cp degree must be a power of two, got {self.cp}")
        if not _is_pow2(self.ep):
            raise ValueError(f"ep degree must be a power of two, got {self.ep}")
        if self.cp > 1 and self.ep > 1:
            raise ValueError("cp and ep both >1 is unsupported (they share mesh axes)")
        if self.cp > 1 and self.ckpt == "selective":
            raise ValueError(
                "ckpt='selective' is not supported with cp>1 (the CP decoder "
                "layers have no attention-core remat hook); use ckpt='full'"
            )
        if self.cp_impl not in ("ring", "a2a"):
            raise ValueError(f"cp_impl must be 'ring' or 'a2a', got {self.cp_impl!r}")
        if self.dp_type not in DP_TYPES:
            raise ValueError(f"dp_type must be one of {DP_TYPES}, got {self.dp_type}")

    def with_(self, **kw) -> "LayerStrategy":
        return dataclasses.replace(self, **kw)


@dataclass
class HybridParallelConfig:
    """Model-wide hybrid strategy: one LayerStrategy per transformer layer plus
    global choices (reference: galvatron/core/hybrid_parallel_config.py:13-87).
    """

    pp: int = 1
    # virtual pipeline chunks per device (interleaved schedule; 1 = off).
    # Device s holds virtual stages {s, s+pp, ..., s+(vpp-1)pp}; the bubble
    # shrinks by the vpp factor (reference: the interleaved 1F1B of vendored
    # megatron core/pipeline_parallel/schedules.py:367, unused by Galvatron's
    # own engine — first-class here).
    vpp: int = 1
    layer_strategies: List[LayerStrategy] = field(default_factory=list)
    # layers per pipeline stage; len == pp, sum == len(layer_strategies)
    pp_division: Optional[List[int]] = None
    chunks: int = 1  # micro-batch count for pipeline / grad accumulation
    pipeline_type: str = "gpipe"  # 'gpipe' | 'pipedream_flush'
    vocab_tp: int = 1  # TP degree for embedding & LM head (vocab-parallel)
    vocab_sp: bool = False
    embed_dp_type: str = "ddp"  # 'embed_sdp' analogue: zero3 to shard embeddings
    # 'fp32' | 'bf16' (bf16 compute, fp32 master) | 'fp16' (+ dynamic loss
    # scaling with skip-on-overflow; reference: megatron grad_scaler.py)
    mixed_precision: str = "bf16"
    default_dp_type: str = "ddp"
    # activation-memory recompute over the MLP/norm/loss regions
    # (modeling.ModelConfig.mlp_recompute; DESIGN.md "Activation memory
    # accounting"): 'policy' (default — one gate save per layer, fp32
    # widenings rematerialized) | 'gate' (product-only remat) | 'off'
    mlp_recompute: str = "policy"
    # async ZeRO gradient overlap: pin each zero2/zero3 layer's parameter
    # cotangents to their reduce-scattered (opt-state) sharding AT THE LAYER'S
    # POINT in the backward graph (parallel/sharding.overlap_grad_sync), so
    # GSPMD issues one gradient reduce-scatter bucket per layer as its
    # backward completes — overlappable with the next layer's dgrad compute —
    # instead of a trailing blob after the whole backward. No numeric effect;
    # layout/schedule only (DESIGN.md "Overlap").
    grad_overlap: bool = False

    def __post_init__(self):
        if self.pipeline_type not in ("gpipe", "pipedream_flush"):
            raise ValueError(f"unknown pipeline_type {self.pipeline_type}")
        if self.mlp_recompute not in ("off", "gate", "policy"):
            raise ValueError(
                f"mlp_recompute must be 'off', 'gate' or 'policy', got "
                f"{self.mlp_recompute!r}"
            )
        if self.pp_division is None and self.layer_strategies:
            self.pp_division = balanced_division(len(self.layer_strategies), self.pp)

    @property
    def num_layers(self) -> int:
        return len(self.layer_strategies)

    def max_tp(self) -> int:
        degs = [s.tp * s.cp for s in self.layer_strategies] + [self.vocab_tp]
        return max(degs) if degs else 1

    def validate(self, world_size: int) -> None:
        """Strategy validity checks (reference: check_hp_config,
        galvatron/core/hybrid_parallel_config.py:109-128)."""
        if not _is_pow2(world_size):
            raise ValueError(f"world size must be a power of two, got {world_size}")
        if world_size % self.pp != 0:
            raise ValueError(f"pp={self.pp} must divide world size {world_size}")
        per_stage = world_size // self.pp
        for i, s in enumerate(self.layer_strategies):
            if s.tp * s.cp > per_stage:
                raise ValueError(
                    f"layer {i}: tp*cp={s.tp * s.cp} exceeds per-stage devices {per_stage}"
                )
            if s.ep > per_stage // (s.tp * s.cp):
                raise ValueError(
                    f"layer {i}: ep={s.ep} exceeds data-parallel extent "
                    f"{per_stage // (s.tp * s.cp)}"
                )
        if self.vocab_tp > per_stage:
            raise ValueError(f"vocab_tp={self.vocab_tp} exceeds per-stage devices")
        if self.pp_division is not None:
            # length 2*pp is the enc-dec layout: [enc division ‖ dec division]
            # (parallel/pipeline_encdec.EncDecLayout validates the split)
            if len(self.pp_division) not in (self.pp, 2 * self.pp):
                raise ValueError("pp_division length must equal pp (or 2*pp for enc-dec)")
            if sum(self.pp_division) != self.num_layers:
                raise ValueError("pp_division must sum to the layer count")
            # the 2*pp enc-dec layout allows zero-layer (fully masked)
            # stages for sub-stacks smaller than pp; single-stack pipelines
            # require at least one layer per stage
            floor = 0 if len(self.pp_division) == 2 * self.pp else 1
            if any(n < floor for n in self.pp_division):
                raise ValueError(f"pp_division entries must be >= {floor}")
            if self.vpp > 1 and len(set(self.pp_division)) > 1:
                raise ValueError(
                    "the interleaved schedule (vpp>1) requires a uniform "
                    "pp_division (virtual stages are evenly stacked)"
                )
        if self.pp > 1 and self.chunks < 1:
            raise ValueError("chunks must be >= 1")
        if self.vpp < 1:
            raise ValueError("vpp must be >= 1")
        if self.vpp > 1:
            if self.pp == 1:
                raise ValueError("vpp>1 (interleaved schedule) requires pp>1")
            # vpp composes with both schedules: 'gpipe' = interleaved clocked
            # scan (autodiff backward), 'pipedream_flush' = interleaved 1F1B
            # (hand-written mirrored backward wave, bounded activations)
            if self.num_layers % (self.pp * self.vpp) != 0:
                raise ValueError(
                    f"vpp={self.vpp} needs the layer count {self.num_layers} "
                    f"divisible by pp*vpp={self.pp * self.vpp}"
                )
            if self.chunks % self.pp != 0:
                raise ValueError(
                    f"interleaved schedule needs chunks {self.chunks} divisible "
                    f"by pp={self.pp} (micro-batches flow in groups of pp; "
                    "reference: megatron interleaved requires the same)"
                )

    # --- JSON codec (reference schema: comma-joined per-layer strings;
    # galvatron/utils/config_utils.py:34-50, search_engine.py:326-367) ---

    def to_json_dict(self) -> Dict[str, Any]:
        ls = self.layer_strategies
        return {
            "pp_deg": self.pp,
            "vpp_deg": self.vpp,
            "tp_sizes_enc": ",".join(str(s.tp) for s in ls),
            "tp_consecutive_flags": ",".join(str(int(s.tp_consec)) for s in ls),
            "dp_types_enc": ",".join(str(_DP_TYPE_TO_INT[s.dp_type]) for s in ls),
            # authoritative per-layer dp types (dp_types_enc's 0/1 is kept for
            # reference-schema compatibility but cannot distinguish ddp/zero2)
            "dp_type_names": ",".join(s.dp_type for s in ls),
            "checkpoint": ",".join(str(_CKPT_TO_INT[s.ckpt]) for s in ls),
            "sp_flags": ",".join(str(int(s.sp)) for s in ls),
            "cp_sizes_enc": ",".join(str(s.cp) for s in ls),
            "cp_impls": ",".join(s.cp_impl for s in ls),
            "ep_sizes_enc": ",".join(str(s.ep) for s in ls),
            "tp_overlap_flags": ",".join(str(int(s.tp_overlap)) for s in ls),
            "pp_division": ",".join(str(n) for n in (self.pp_division or [])),
            "chunks": self.chunks,
            "pipeline_type": self.pipeline_type,
            "vocab_tp": self.vocab_tp,
            "vocab_sp": int(self.vocab_sp),
            "embed_dp_type": self.embed_dp_type,
            "default_dp_type": self.default_dp_type,
            "mixed_precision": self.mixed_precision,
            "mlp_recompute": self.mlp_recompute,
            "grad_overlap": int(self.grad_overlap),
        }

    @classmethod
    def from_json_dict(cls, d: Dict[str, Any]) -> "HybridParallelConfig":
        def ints(key, default=None):
            v = d.get(key, default)
            if v is None or v == "":
                return None
            if isinstance(v, str):
                return [int(x) for x in v.split(",")]
            return [int(x) for x in v]

        tps = ints("tp_sizes_enc") or []
        n = len(tps)
        consec = ints("tp_consecutive_flags") or [1] * n
        default_dp = d.get("default_dp_type", "ddp")
        dp_enc = ints("dp_types_enc") or [0] * n
        dp_names = d.get("dp_type_names")
        dp_names = dp_names.split(",") if dp_names else None
        ckpt = ints("checkpoint") or [0] * n
        sp = ints("sp_flags") or [0] * n
        cp = ints("cp_sizes_enc") or [1] * n
        cp_impls = d.get("cp_impls")
        cp_impls = cp_impls.split(",") if cp_impls else ["ring"] * n
        ep = ints("ep_sizes_enc") or [1] * n
        tov = ints("tp_overlap_flags") or [0] * n
        strategies = [
            LayerStrategy(
                tp=tps[i],
                tp_consec=bool(consec[i]),
                dp_type=dp_names[i] if dp_names else ("zero3" if dp_enc[i] == 1 else default_dp),
                ckpt=ckpt[i],
                sp=bool(sp[i]),
                cp=cp[i],
                cp_impl=cp_impls[i],
                ep=ep[i],
                tp_overlap=bool(tov[i]),
            )
            for i in range(n)
        ]
        return cls(
            pp=int(d.get("pp_deg", 1)),
            vpp=int(d.get("vpp_deg", 1)),
            layer_strategies=strategies,
            pp_division=ints("pp_division"),
            chunks=int(d.get("chunks", 1)),
            pipeline_type=d.get("pipeline_type", "gpipe"),
            vocab_tp=int(d.get("vocab_tp", 1)),
            vocab_sp=bool(int(d.get("vocab_sp", 0))),
            embed_dp_type=d.get("embed_dp_type", "ddp"),
            default_dp_type=default_dp,
            mixed_precision=d.get("mixed_precision", "bf16"),
            mlp_recompute=d.get("mlp_recompute", "policy"),
            grad_overlap=bool(int(d.get("grad_overlap", 0))),
        )

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json_dict(), f, indent=2)

    @classmethod
    def load(cls, path: str) -> "HybridParallelConfig":
        with open(path) as f:
            return cls.from_json_dict(json.load(f))

    @classmethod
    def uniform(
        cls,
        num_layers: int,
        pp: int = 1,
        tp: int = 1,
        dp_type: str = "ddp",
        ckpt: bool = False,
        sp: bool = False,
        cp: int = 1,
        cp_impl: str = "ring",
        ep: int = 1,
        tp_consec: bool = True,
        tp_overlap: bool = False,
        **kw,
    ) -> "HybridParallelConfig":
        s = LayerStrategy(
            tp=tp, tp_consec=tp_consec, dp_type=dp_type, ckpt=ckpt, sp=sp,
            cp=cp, cp_impl=cp_impl, ep=ep, tp_overlap=tp_overlap,
        )
        return cls(pp=pp, layer_strategies=[s] * num_layers, vocab_tp=kw.pop("vocab_tp", tp), **kw)


def plan_hash(plan) -> str:
    """Stable content hash of a parallelism plan's SEMANTIC fields.

    ``plan`` is a :class:`HybridParallelConfig` or a strategy JSON dict;
    dicts are decoded first, so provenance keys (``search_cost_ms``,
    ``num_devices``, ``model_config``, ...) and key ordering never change
    the hash — re-searching the identical strategy for the same mesh hashes
    identically. Checkpoint manifests record this hash in their topology
    fingerprint (trainer), the elastic supervisor exposes it as
    ``current_plan_hash``, and a cross-plan resume is detected by comparing
    it (a *mismatch* is legal — portable checkpoints reshard — but worth an
    event)."""
    import hashlib

    if isinstance(plan, dict):
        plan = HybridParallelConfig.from_json_dict(plan)
    payload = json.dumps(plan.to_json_dict(), sort_keys=True)
    return "sha256:" + hashlib.sha256(payload.encode()).hexdigest()


def balanced_division(num_layers: int, pp: int) -> List[int]:
    """Even layer split across stages, remainder to the middle stages — the
    uniform fallback of the reference's memory-balanced division
    (galvatron/core/search_engine.py:586-654); the memory-aware version is
    ``galvatron_tpu.search.pp_division.pp_division_memory_balanced``."""
    base, rem = divmod(num_layers, pp)
    division = [base] * pp
    # give the extra layers to the later-middle stages (first/last stages carry
    # embedding / head memory; reference biases the same way)
    order = sorted(range(pp), key=lambda s: (abs(s - (pp - 1) / 2), -s))
    for i in range(rem):
        division[order[i]] += 1
    return division


def form_strategy(s: LayerStrategy, pp: int = 1, dp: int = 1) -> str:
    """Compact human-readable strategy string, reference style ``pp-tp-dp[f][*][-c]``
    (galvatron/utils/strategy_utils.py:3-48)."""
    tag = f"{pp}-{s.tp}-{dp}"
    if s.dp_type == "zero3":
        tag += "f"
    elif s.dp_type == "zero2":
        tag += "z"
    if not s.tp_consec:
        tag += "*"
    if s.sp:
        tag += "s"
    if s.tp_overlap:
        tag += "o"
    if s.cp > 1:
        tag += (f"r{s.cp}" if s.cp_impl == "ring" else f"u{s.cp}")
    if s.ckpt == "full":
        tag += "-c"
    elif s.ckpt == "selective":
        tag += "-cs"
    return tag
