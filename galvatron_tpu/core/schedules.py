"""Learning-rate schedules, batch-size ramp-up, and dynamic loss scaling.

TPU-native counterparts of three vendored-Megatron subsystems the reference
carries but never wires into its trainer (SURVEY §2.6 aux subsystems):

- ``LRSchedule`` — warmup + {constant, linear, cosine} decay
  (reference: site_package/megatron/optimizer_param_scheduler.py /
  training.py lr-decay flags);
- ``BatchSizeRampup`` — global-batch-size ramp-up by a fixed increment every
  N samples (reference: site_package/megatron/microbatches.py:1-144,
  RampupBatchsizeNumMicroBatches);
- ``DynamicLossScaler`` — fp16 loss scaling with growth/backoff
  (reference: site_package/megatron/optimizer/grad_scaler.py). On TPU the
  native precision is bf16 (no scaler needed); the scaler exists for fp16
  parity and is pure-jax so it composes with jit.

Everything here is traceable: schedule values are jnp scalars when given
traced steps, plain floats when given ints.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class LRSchedule:
    """lr(step): linear warmup from ``warmup_init_lr`` to ``lr`` over
    ``warmup_iters``, then decay to ``min_lr`` at ``decay_iters`` following
    ``decay_style``, constant afterwards."""

    lr: float = 1e-4
    min_lr: float = 0.0
    warmup_iters: int = 0
    decay_iters: int = 0  # 0 → no decay (constant after warmup)
    decay_style: str = "cosine"  # 'constant' | 'linear' | 'cosine'
    warmup_init_lr: float = 0.0

    def __post_init__(self):
        if self.decay_style not in ("constant", "linear", "cosine"):
            raise ValueError(f"unknown decay_style {self.decay_style!r}")
        if self.min_lr > self.lr:
            raise ValueError("min_lr must not exceed lr")

    def __call__(self, step):
        s = jnp.asarray(step, jnp.float32)
        warm = jnp.asarray(max(self.warmup_iters, 0), jnp.float32)
        # warmup branch value (guard warm==0 with a dummy denominator)
        wfrac = s / jnp.maximum(warm, 1.0)
        warm_lr = self.warmup_init_lr + (self.lr - self.warmup_init_lr) * wfrac
        if self.decay_style == "constant" or self.decay_iters <= 0:
            decayed = jnp.asarray(self.lr, jnp.float32)
        else:
            span = jnp.asarray(max(self.decay_iters - self.warmup_iters, 1), jnp.float32)
            dfrac = jnp.clip((s - warm) / span, 0.0, 1.0)
            if self.decay_style == "linear":
                coeff = 1.0 - dfrac
            else:  # cosine
                coeff = 0.5 * (1.0 + jnp.cos(jnp.pi * dfrac))
            decayed = self.min_lr + (self.lr - self.min_lr) * coeff
        out = jnp.where(s < warm, warm_lr, decayed)
        if isinstance(step, int):
            return float(out)
        return out

    def scale(self, step):
        """lr(step)/lr — multiplier form for ``adamw_update(..., lr_scale=)``."""
        return self(step) / self.lr if self.lr else 0.0


@dataclass(frozen=True)
class BatchSizeRampup:
    """Global batch size as a function of consumed samples
    (reference: megatron/microbatches.py RampupBatchsizeNumMicroBatches:
    ``--rampup-batch-size <start> <increment> <ramp-up samples>``).

    The size grows from ``start`` to ``target`` in steps of ``increment``;
    each intermediate size is held for an equal share of ``rampup_samples``.
    """

    start: int
    increment: int
    rampup_samples: int
    target: int

    def __post_init__(self):
        if self.increment <= 0 or self.start <= 0:
            raise ValueError("start and increment must be positive")
        if self.start > self.target:
            raise ValueError(f"start {self.start} must not exceed target {self.target}")
        if (self.target - self.start) % self.increment != 0:
            raise ValueError(
                f"target-start ({self.target}-{self.start}) must be a multiple of "
                f"increment {self.increment} (reference constraint, microbatches.py)"
            )

    def __call__(self, consumed_samples: int) -> int:
        steps = (self.target - self.start) // self.increment
        if steps == 0 or consumed_samples >= self.rampup_samples:
            return self.target
        per = self.rampup_samples / steps
        i = int(consumed_samples / per)
        return min(self.start + i * self.increment, self.target)

    def sizes(self):
        return list(range(self.start, self.target + 1, self.increment))


# ---------------------------------------------------------------------------
# fp16 dynamic loss scaling (pure-jax, jit-composable)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class LossScalerConfig:
    """(reference defaults: megatron/optimizer/grad_scaler.py DynamicGradScaler
    — initial 2^32, growth 2.0 every 1000 clean steps, backoff 0.5, min 1.0)"""

    initial_scale: float = 2.0**16
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    growth_interval: int = 1000
    min_scale: float = 1.0


def init_scaler_state(cfg: LossScalerConfig) -> Dict[str, Any]:
    return {
        "scale": jnp.asarray(cfg.initial_scale, jnp.float32),
        "good_steps": jnp.zeros((), jnp.int32),
    }


def all_finite(tree) -> jax.Array:
    leaves = [jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(tree)]
    return jnp.stack(leaves).all() if leaves else jnp.asarray(True)


def scaler_update(state: Dict[str, Any], finite, cfg: LossScalerConfig):
    """Next scaler state given whether this step's grads were all finite.
    Growth after ``growth_interval`` consecutive clean steps; backoff (and
    skipped update — caller's responsibility via the ``finite`` flag) on
    overflow."""
    grown = jnp.where(
        state["good_steps"] + 1 >= cfg.growth_interval,
        state["scale"] * cfg.growth_factor,
        state["scale"],
    )
    new_scale = jnp.where(
        finite,
        grown,
        jnp.maximum(state["scale"] * cfg.backoff_factor, cfg.min_scale),
    )
    new_good = jnp.where(
        finite & (state["good_steps"] + 1 < cfg.growth_interval),
        state["good_steps"] + 1,
        0,
    )
    return {"scale": new_scale, "good_steps": new_good}


def scaled_value_and_grad(loss_fn, scale):
    """``value_and_grad`` with the fp16 loss-scaling pattern: the backward
    runs on ``loss * scale``, gradients come back unscaled in fp32, the loss
    value is exact (un-scaled primal). One definition of the overflow-
    sensitive numerics shared by the pp=1, GPipe and enc-dec pipeline train
    steps; finiteness
    checking lives in ``optim.apply_update_with_scaler``."""

    def run(params, *args):
        def scaled(p):
            l = loss_fn(p, *args)
            return l * scale, l

        (_, loss), sgrads = jax.value_and_grad(scaled, has_aux=True)(params)
        return loss, jax.tree.map(lambda g: g.astype(jnp.float32) / scale, sgrads)

    return run
