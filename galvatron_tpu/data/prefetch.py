"""Async double-buffered host→device prefetch.

The trainer's input path was fully synchronous: assemble batch k on the host,
``device_put``, dispatch step k — data time adds to step time. The prefetcher
moves assembly + transfer to a background thread: while step k runs on the
device, the thread builds batch k+1 and calls ``put_fn`` (the runtime's
``shard_batch`` — ``jax.device_put`` onto the train step's input shardings),
so the trainer's ``data`` span collapses to a bounded-queue dequeue.

Correctness rules:

- **Fresh buffer per batch** (the GTL103 mutate-after-dispatch class, the
  PR 2 serving corruption): every batch the producer hands to ``put_fn`` is a
  newly allocated array that is never written again — the assembly fn
  allocates per call, and the producer drops its reference after enqueue.
- **Clean shutdown on every exit path**: ``close()`` is idempotent, drains
  the queue so a producer blocked on ``put`` can observe the stop flag, and
  joins the thread. The trainer calls it in its exit ``finally`` (after the
  watchdog stands down, before the exit checkpoint — a blocked producer must
  not hold batches hostage while the save runs).
- **Exceptions propagate**: a producer failure (corrupt shard, OOM) is
  re-raised in the consumer at the ``next()`` that would have returned the
  failed batch, with the prefetcher closed.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Optional, Tuple

_STOP = object()


class AsyncPrefetcher:
    """Iterator over ``(device_batch, meta)`` pairs produced ahead of time.

    ``make_item()`` returns ``(host_batch, meta)`` (meta: the per-batch stats
    dict the trainer logs); ``put_fn`` maps the host batch onto devices.
    ``depth`` bounds in-flight batches (2 = classic double buffering: one in
    the queue while the next is being assembled/transferred)."""

    def __init__(
        self,
        make_item: Callable[[], Tuple[Any, dict]],
        put_fn: Callable[[Any], Any],
        depth: int = 2,
    ):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._make_item = make_item
        self._put_fn = put_fn
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._exc: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, name="galvatron-data-prefetch", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                host_batch, meta = self._make_item()
                item = (self._put_fn(host_batch), meta)
                # the host buffer reference is dropped here — nothing can
                # mutate it behind the in-flight device_put
                del host_batch
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # noqa: BLE001 — re-raised in the consumer
            self._exc = e
            try:
                self._q.put(_STOP, timeout=0.1)
            except queue.Full:
                pass

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if self._exc is not None and self._q.empty():
                self.close()
                raise self._exc
            try:
                item = self._q.get(timeout=0.5)
            except queue.Empty:
                if not self._thread.is_alive() and self._exc is None:
                    raise StopIteration
                continue
            if item is _STOP:
                self.close()
                if self._exc is not None:
                    raise self._exc
                raise StopIteration
            return item

    def close(self) -> None:
        """Idempotent; callable from any trainer exit path. Drains the queue
        so a producer blocked on ``put`` sees the stop flag, then joins."""
        self._stop.set()
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)

    def __del__(self):  # safety net; the trainer's finally is the contract
        try:
            self._stop.set()
        except Exception:
            pass
