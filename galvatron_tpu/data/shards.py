"""Multi-file mmap-backed shard format for token corpora.

Successor of the single-file ``core/data.py`` indexed layout (which it reads
transparently — see ``open_token_dataset``): the token stream is split over
``<prefix>.shard-NNNNN.bin`` files with one JSON manifest
``<prefix>.shards.json`` carrying dtype, per-shard document offsets, and
totals. The manifest is committed atomically (tmp + fsync + rename, the
core/checkpoint.py publish discipline) so a writer killed mid-build can never
leave a readable-but-torn corpus; shard ``.bin`` files are memory-mapped on
open, so corpus size is bounded by disk, not host RAM (the Megatron
indexed_dataset contract, multi-file like its blended/split variants).

Documents never span shards: a shard is closed when the next document would
push it past ``shard_tokens`` (single documents larger than ``shard_tokens``
get a shard of their own). That keeps ``doc(i)`` a single contiguous mmap
slice — no stitch copies on the hot read path.
"""

from __future__ import annotations

import json
import os
from bisect import bisect_right
from typing import Iterable, List, Optional, Sequence

import numpy as np

MANIFEST_SUFFIX = ".shards.json"


def _commit_json(path: str, obj: dict) -> None:
    """Atomic JSON publish: tmp + fsync + rename + dir fsync."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    dfd = os.open(os.path.dirname(os.path.abspath(path)) or ".", os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def write_sharded_dataset(
    prefix: str,
    docs: Iterable[Sequence[int]],
    vocab_size: int,
    shard_tokens: int = 1 << 22,
) -> dict:
    """Build ``<prefix>.shard-NNNNN.bin`` files + the fsynced manifest from an
    iterable of token-id documents. Returns the manifest dict."""
    dtype = np.uint16 if vocab_size <= np.iinfo(np.uint16).max + 1 else np.int32
    shards: List[dict] = []
    cur_f = None
    cur_offsets: List[int] = [0]

    def close_shard():
        nonlocal cur_f
        if cur_f is None:
            return
        cur_f.flush()
        os.fsync(cur_f.fileno())
        cur_f.close()
        shards[-1]["doc_offsets"] = list(cur_offsets)
        shards[-1]["num_tokens"] = cur_offsets[-1]
        cur_f = None

    def open_shard():
        nonlocal cur_f, cur_offsets
        name = f"{os.path.basename(prefix)}.shard-{len(shards):05d}.bin"
        shards.append({"file": name})
        cur_offsets = [0]
        cur_f = open(os.path.join(os.path.dirname(prefix) or ".", name), "wb")

    n_docs = 0
    for doc in docs:
        arr = np.asarray(doc, dtype=dtype)
        if arr.size and (arr.max() >= vocab_size or arr.min() < 0):
            raise ValueError(f"document contains token ids outside [0, {vocab_size})")
        if arr.size == 0:
            continue
        if cur_f is None or (
            cur_offsets[-1] and cur_offsets[-1] + arr.size > shard_tokens
        ):
            close_shard()
            open_shard()
        arr.tofile(cur_f)
        cur_offsets.append(cur_offsets[-1] + arr.size)
        n_docs += 1
    close_shard()
    if n_docs == 0:
        # a committed-but-empty manifest would fail later with cryptic
        # numpy/index errors deep inside the packer or window sampler
        raise ValueError(
            f"{prefix}: corpus has no non-empty documents — nothing to write"
        )
    manifest = {
        "version": 1,
        "dtype": np.dtype(dtype).name,
        "vocab_size": vocab_size,
        "num_docs": n_docs,
        "num_tokens": sum(s["num_tokens"] for s in shards),
        "shards": shards,
    }
    _commit_json(prefix + MANIFEST_SUFFIX, manifest)
    return manifest


class ShardedTokenDataset:
    """Memory-mapped reader over a ``write_sharded_dataset`` corpus.

    Same duck type as the legacy ``IndexedTokenDataset`` (``num_docs`` /
    ``num_tokens`` / ``doc(i)`` / ``doc_lengths``) so the packer and the
    mixture treat both interchangeably. Manifest read and every shard mmap go
    through ``core/retry.py`` — corpora live on network storage on pods and a
    transient blip must not kill the run."""

    def __init__(self, prefix: str):
        from galvatron_tpu.core.retry import with_retries

        man_path = prefix + MANIFEST_SUFFIX
        if not os.path.exists(man_path):
            raise FileNotFoundError(
                f"{man_path} not found — build the corpus with "
                "write_sharded_dataset first (or pass a legacy single-file "
                "prefix through open_token_dataset)"
            )

        def read_manifest():
            with open(man_path) as f:
                return json.load(f)

        self.meta = with_retries(read_manifest, describe=f"read {man_path}")
        self.dtype = np.dtype(self.meta["dtype"])
        base = os.path.dirname(prefix) or "."
        self._maps: List[np.memmap] = []
        self._doc_offsets: List[np.ndarray] = []
        # cumulative doc counts per shard → global doc index via bisect
        self._doc_cum: List[int] = [0]
        for sh in self.meta["shards"]:
            path = os.path.join(base, sh["file"])
            m = with_retries(
                lambda p=path: np.memmap(p, dtype=self.dtype, mode="r"),
                describe=f"map {path}",
            )
            if m.size != sh["num_tokens"]:
                raise ValueError(
                    f"{path} has {m.size} tokens but the manifest records "
                    f"{sh['num_tokens']} (corrupt or mismatched shard)"
                )
            self._maps.append(m)
            offs = np.asarray(sh["doc_offsets"], np.int64)
            self._doc_offsets.append(offs)
            self._doc_cum.append(self._doc_cum[-1] + len(offs) - 1)
        if self._doc_cum[-1] != self.meta["num_docs"]:
            raise ValueError(
                f"manifest num_docs {self.meta['num_docs']} disagrees with the "
                f"per-shard offsets ({self._doc_cum[-1]} docs)"
            )
        if self.num_docs == 0:
            # hand-built or legacy-converted manifests: refuse here with a
            # clear message rather than crash in a downstream consumer
            raise ValueError(f"{prefix}: corpus has zero documents")

    @property
    def num_docs(self) -> int:
        return int(self.meta["num_docs"])

    @property
    def num_tokens(self) -> int:
        return int(self.meta["num_tokens"])

    @property
    def doc_lengths(self) -> np.ndarray:
        if not self._doc_offsets:
            return np.zeros(0, np.int64)
        return np.concatenate([np.diff(o) for o in self._doc_offsets])

    def doc(self, i: int) -> np.ndarray:
        if not 0 <= i < self.num_docs:
            raise IndexError(f"doc {i} out of range [0, {self.num_docs})")
        s = bisect_right(self._doc_cum, i) - 1
        j = i - self._doc_cum[s]
        offs = self._doc_offsets[s]
        return np.asarray(self._maps[s][offs[j] : offs[j + 1]])


class _LegacyAdapter:
    """``IndexedTokenDataset`` behind the sharded duck type."""

    def __init__(self, indexed):
        self.indexed = indexed
        self.meta = indexed.meta

    @property
    def num_docs(self) -> int:
        return self.indexed.num_docs

    @property
    def num_tokens(self) -> int:
        return self.indexed.num_tokens

    @property
    def doc_lengths(self) -> np.ndarray:
        return np.diff(self.indexed.doc_offsets)

    def doc(self, i: int) -> np.ndarray:
        return self.indexed.doc(i)


def open_token_dataset(prefix: str):
    """Open a corpus by prefix: the sharded manifest when present, else the
    legacy single-file ``<prefix>.idx.json`` layout — one entry point for
    every consumer (mixture sources, the packer, build_data_pipeline)."""
    if os.path.exists(prefix + MANIFEST_SUFFIX):
        return ShardedTokenDataset(prefix)
    from galvatron_tpu.core.data import IndexedTokenDataset

    return _LegacyAdapter(IndexedTokenDataset(prefix))


def tokenize_text_files(
    prefix: str,
    text_paths: Sequence[str],
    tokenizer,
    vocab_size: Optional[int] = None,
    shard_tokens: int = 1 << 22,
) -> dict:
    """Encode newline-delimited text files into the sharded format (one
    document per non-blank line, files concatenated in order)."""

    def docs():
        for path in text_paths:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        yield tokenizer.encode(line)

    return write_sharded_dataset(
        prefix, docs(), vocab_size or tokenizer.vocab_size, shard_tokens=shard_tokens
    )
