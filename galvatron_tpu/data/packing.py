"""Greedy first-fit sequence packing with segment ids.

T5-style packing: documents are bin-packed into fixed-capacity rows instead
of each padding to ``seq_len`` — padding waste drops from (1 − mean_doc_len /
seq_len) to the bin-packing residual. One packed sample row is

    ``[tokens (S+1)] ‖ [segment ids (S+1)]``  →  width 2·(S+1), int32

where ``S = seq_len``. Segment ids are 1-based per document within the row
and 0 on padding; they are monotonically non-decreasing along the row (the
model's per-segment position reset relies on that — see
``modeling.positions_from_segments``). Padding uses token id 0: those
positions are unreachable through attention (segment 0 never matches a real
segment) and carry no loss (``split_batch`` masks labels at every segment
boundary and on padding), so the pad id's embedding never influences
training.

Documents longer than the row capacity are split into capacity-sized pieces,
each its own segment (standard long-document truncation-into-chunks).

Packing is computed once at dataset open, over documents in corpus order —
deterministic, so ``sample(i)`` stays a pure function of the index and the
sample-domain resume cursor applies unchanged. First-fit scans a bounded
window of open bins (``max_open_bins``) for O(n·window) build time.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

Piece = Tuple[int, int, int]  # (doc id, start offset within doc, length)


def pack_documents(
    doc_lengths: np.ndarray, capacity: int, max_open_bins: int = 64
) -> List[List[Piece]]:
    """Greedy first-fit: each document (split into ≤``capacity`` pieces) goes
    into the first open bin with room, else opens a new bin; the oldest open
    bin is closed when more than ``max_open_bins`` are open. Returns the bins
    in the order they were opened."""
    if capacity < 2:
        raise ValueError(f"row capacity {capacity} too small to train on")
    closed: List[List[Piece]] = []
    open_bins: List[Tuple[int, List[Piece]]] = []  # (free tokens, pieces)
    for doc_id, length in enumerate(np.asarray(doc_lengths, np.int64)):
        start = 0
        while start < length:
            piece = (int(doc_id), int(start), int(min(capacity, length - start)))
            plen = piece[2]
            placed = False
            for b, (free, pieces) in enumerate(open_bins):
                if free >= plen:
                    pieces.append(piece)
                    if free == plen:
                        closed.append(pieces)
                        open_bins.pop(b)
                    else:
                        open_bins[b] = (free - plen, pieces)
                    placed = True
                    break
            if not placed:
                if plen == capacity:
                    closed.append([piece])  # exact fill: never opens
                else:
                    open_bins.append((capacity - plen, [piece]))
                    if len(open_bins) > max_open_bins:
                        closed.append(open_bins.pop(0)[1])
            start += plen
    closed.extend(pieces for _, pieces in open_bins)
    return closed


class PackedDataset:
    """Packed sample rows over a token dataset (sharded or legacy).

    ``sample(i)`` → ``(2·(seq_len+1),)`` int32: tokens ‖ segment ids."""

    def __init__(self, dataset, seq_len: int, max_open_bins: int = 64):
        self.dataset = dataset
        self.seq_len = seq_len
        self.capacity = seq_len + 1
        self.rows = pack_documents(
            dataset.doc_lengths, self.capacity, max_open_bins=max_open_bins
        )
        if not self.rows:
            raise ValueError("corpus has no documents to pack")
        filled = sum(p[2] for row in self.rows for p in row)
        self.packing_efficiency = filled / float(self.capacity * len(self.rows))

    @property
    def num_samples(self) -> int:
        return len(self.rows)

    def sample(self, i: int) -> np.ndarray:
        row = self.rows[i]
        tokens = np.zeros(self.capacity, np.int32)
        seg = np.zeros(self.capacity, np.int32)
        pos = 0
        for seg_id, (doc_id, start, length) in enumerate(row, start=1):
            tokens[pos : pos + length] = self.dataset.doc(doc_id)[start : start + length]
            seg[pos : pos + length] = seg_id
            pos += length
        return np.concatenate([tokens, seg])


class WindowedDataset:
    """Unpacked fixed windows over the concatenated document stream — the
    GPT-style sampling of ``core/data.GPTWindowDataset`` behind the
    position-addressable ``num_samples``/``sample(i)`` interface (mixture
    sources without ``--pack_sequences``). Windows may cross shard boundaries;
    the stitch copies one row, not the corpus."""

    def __init__(self, dataset, seq_len: int):
        self.dataset = dataset
        self.seq_len = seq_len
        self.num_samples = max(0, dataset.num_tokens - 1) // seq_len
        if self.num_samples <= 0:
            raise ValueError(
                f"corpus has {dataset.num_tokens} tokens — fewer than one "
                f"(seq_len+1)={seq_len + 1} window"
            )
        self._doc_lengths = np.asarray(dataset.doc_lengths, np.int64)
        self._doc_starts = np.concatenate([[0], np.cumsum(self._doc_lengths)])

    def sample(self, i: int) -> np.ndarray:
        start, stop = i * self.seq_len, i * self.seq_len + self.seq_len + 1
        out = np.empty(stop - start, np.int32)
        filled = 0
        # first doc overlapping `start`, then walk forward
        d = int(np.searchsorted(self._doc_starts, start, side="right")) - 1
        while filled < len(out):
            doc = self.dataset.doc(d)
            lo = start + filled - int(self._doc_starts[d])
            take = min(len(doc) - lo, len(out) - filled)
            out[filled : filled + take] = doc[lo : lo + take]
            filled += take
            d += 1
        return out


def packed_batch_meta(batch: np.ndarray) -> dict:
    """Host-side packing stats of one packed ``(B, 2·(S+1))`` batch: non-pad
    INPUT tokens (the S columns the model consumes — what true-token MFU
    counts), raw input tokens, and the fill fraction."""
    s1 = batch.shape[1] // 2
    seg_in = batch[:, s1 : 2 * s1 - 1]  # segment ids of the S input positions
    nonpad = int((seg_in > 0).sum())
    raw = int(seg_in.size)
    return {
        "nonpad_tokens": nonpad,
        "raw_tokens": raw,
        "packing_efficiency": nonpad / float(raw) if raw else 0.0,
    }
