"""Production data subsystem: sharded corpora, deterministic mixtures,
sequence packing, and async device prefetch (docs/DESIGN.md § Data pipeline).

The input path the trainer had before this package was a single-file indexed
corpus sampled in fixed windows, handed to the device synchronously — every
short document padded to ``seq_len`` (padded tokens burn real FLOPs and MFU
silently counted them as useful work), one corpus only, and data time
serialized against the step. This package is the production replacement:

- ``shards``   — mmap-backed multi-file shard format (fsynced manifest,
                 ``core/retry.py`` on reads) subsuming the legacy
                 ``IndexedTokenDataset`` single-file layout;
- ``mixture``  — deterministic weighted mixture over N corpora, seeded and
                 position-addressable so the sample-domain resume cursor
                 (PR 7) converts exactly across batch-size/topology changes;
- ``packing``  — greedy first-fit packing of documents into fixed-``seq_len``
                 rows with segment ids (cross-document attention provably
                 blocked by the model's intra-segment mask);
- ``prefetch`` — background host thread assembling + device-transferring
                 batch k+1 while step k runs (double-buffered, clean
                 shutdown on every trainer exit path);
- ``pipeline`` — the facade the trainer drives: ``build_data_pipeline``.
"""

from galvatron_tpu.data.mixture import (
    MixtureDataset,
    MixtureSchedule,
    MixtureSource,
    parse_mixture,
)
from galvatron_tpu.data.packing import PackedDataset, pack_documents
from galvatron_tpu.data.pipeline import DataPipeline, build_data_pipeline
from galvatron_tpu.data.prefetch import AsyncPrefetcher
from galvatron_tpu.data.shards import (
    ShardedTokenDataset,
    open_token_dataset,
    tokenize_text_files,
    write_sharded_dataset,
)

__all__ = [
    "AsyncPrefetcher",
    "DataPipeline",
    "MixtureDataset",
    "MixtureSchedule",
    "MixtureSource",
    "PackedDataset",
    "ShardedTokenDataset",
    "build_data_pipeline",
    "open_token_dataset",
    "pack_documents",
    "parse_mixture",
    "tokenize_text_files",
    "write_sharded_dataset",
]
