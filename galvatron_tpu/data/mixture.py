"""Deterministic weighted mixture sampling over N corpora.

The mixture is **position-addressable**: which source serves global sample
position ``k``, and the source-local index it serves, are pure functions of
``(weights, seed, k)`` — no consumed-state drift, no RNG stream to replay.
That is exactly the contract the sample-domain resume cursor needs (PR 7
converts a checkpoint's ``samples_consumed`` across batch-size/topology
changes by integer arithmetic): a resumed run at any batch size reconstructs
per-source consumption at position ``k`` by counting the assignment prefix,
so zero samples are replayed and zero are skipped per source.

Assignment rule (Megatron blended-dataset style, error-feedback greedy):
position ``k`` goes to the source maximizing ``w_s·(k+1) − c_s(k)`` where
``c_s(k)`` is how many of the first ``k`` positions source ``s`` already
received. The realized ratio error is bounded by 1 sample per source at every
prefix — mixture ratios hold at any cut, not just in expectation. ``seed``
rotates the tie-break/startup phase (a fractional initial credit per source)
so different seeds interleave differently while keeping the bound.

Within a source, local index ``j`` maps through a per-epoch permutation
seeded by ``(seed, source, epoch)`` (``core/data_native.shuffle_index`` —
bit-stable across native/numpy builds), with ``epoch = j // len(source)``.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from galvatron_tpu.core.data_native import mix_seed, shuffle_index


@dataclass(frozen=True)
class MixtureSource:
    name: str
    prefix: str
    weight: float


def parse_mixture(spec: str) -> List[MixtureSource]:
    """``--data_mixture`` forms: a JSON file (``{"sources": [{"name",
    "prefix", "weight"}, ...]}``) or an inline ``prefix=weight,prefix=weight``
    list (names default to the prefix basename)."""
    if os.path.exists(spec):
        with open(spec) as f:
            doc = json.load(f)
        srcs = doc.get("sources") if isinstance(doc, dict) else None
        if not isinstance(srcs, list) or not srcs:
            raise ValueError(
                f"{spec}: expected {{'sources': [{{'name','prefix','weight'}}, ...]}}"
            )
        out = []
        for i, s in enumerate(srcs):
            if not isinstance(s, dict) or "prefix" not in s:
                raise ValueError(f"{spec}: sources[{i}] needs at least a 'prefix'")
            out.append(
                MixtureSource(
                    name=str(s.get("name", os.path.basename(str(s["prefix"])))),
                    prefix=str(s["prefix"]),
                    weight=float(s.get("weight", 1.0)),
                )
            )
    else:
        out = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" in part:
                prefix, w = part.rsplit("=", 1)
                out.append(
                    MixtureSource(os.path.basename(prefix), prefix, float(w))
                )
            else:
                out.append(MixtureSource(os.path.basename(part), part, 1.0))
        if not out:
            raise ValueError(f"--data_mixture {spec!r}: no sources parsed")
    names = [s.name for s in out]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate mixture source names: {names}")
    total = sum(s.weight for s in out)
    if total <= 0 or any(s.weight < 0 for s in out):
        raise ValueError("mixture weights must be non-negative with a positive sum")
    return out


class MixtureSchedule:
    """Deterministic source-assignment sequence with BOUNDED memory.

    The greedy recurrence is inherently sequential, so the schedule keeps
    per-chunk STATE SNAPSHOTS (the (credit, counts) vectors every ``_CHUNK``
    positions — a few dozen bytes per snapshot) instead of materializing the
    assignment array: any chunk is recomputed exactly from its snapshot on
    demand (small LRU of decoded chunks for the sequential access pattern).
    Memory is O(k/_CHUNK · n_sources); a cold query at position k still pays
    one O(k) sequential replay to extend the snapshots (~1-5 M positions/s in
    pure Python — fine for realistic cursors; a closed-form WFQ/virtual-time
    formulation is the upgrade path if corpora ever reach 1e9+ samples).

    ``counts_at(k)`` recounts from the snapshots + one partial chunk — the
    resume-verification primitive, never a mutable counter. Thread-safe: the
    trainer's watchdog / save paths may query from another thread."""

    _CHUNK = 4096
    _CACHE = 8

    def __init__(self, weights: Sequence[float], seed: int = 1234):
        w = np.asarray(weights, np.float64)
        if w.ndim != 1 or len(w) == 0 or w.sum() <= 0 or (w < 0).any():
            raise ValueError(f"bad mixture weights {weights}")
        self.weights = w / w.sum()
        self.seed = seed
        self._lock = threading.Lock()
        n = len(self.weights)
        # seeded fractional startup credit: rotates which source leads the
        # interleave without affecting the ±1-per-source ratio bound
        jitter = np.array(
            [(mix_seed(seed, 0x5EED, s) % (1 << 20)) / float(1 << 20) for s in range(n)]
        )
        # snapshot i = exact (credit, counts) state entering position i·_CHUNK
        self._snaps: List[Tuple[List[float], List[int]]] = [
            (list(self.weights * jitter), [0] * n)
        ]
        self._chunk_cache: Dict[int, Tuple[List[int], List[int]]] = {}

    def _run_chunk(self, state, steps: int):
        """Advance ``steps`` positions from ``state`` (mutated in place),
        returning (per-position source ids, per-position source-local
        indices). Pure-Python inner loop: n_sources is small, and list ops
        beat numpy dispatch overhead at this grain."""
        credit, counts = state
        w = list(self.weights)
        n = len(w)
        src: List[int] = []
        local: List[int] = []
        for _ in range(steps):
            best, best_v = 0, credit[0] + w[0] - counts[0]
            for s in range(1, n):
                v = credit[s] + w[s] - counts[s]
                if v > best_v:
                    best, best_v = s, v
            for s in range(n):
                credit[s] += w[s]
            src.append(best)
            local.append(counts[best])
            counts[best] += 1
        return src, local

    def _ensure_snaps(self, chunk: int) -> None:
        while len(self._snaps) <= chunk:
            credit, counts = self._snaps[-1]
            state = (list(credit), list(counts))
            src, local = self._run_chunk(state, self._CHUNK)
            ci = len(self._snaps) - 1
            self._chunk_cache[ci] = (src, local)
            self._snaps.append(state)
            self._trim_cache()

    def _chunk(self, ci: int):
        self._ensure_snaps(ci + 1)
        got = self._chunk_cache.get(ci)
        if got is None:
            credit, counts = self._snaps[ci]
            got = self._run_chunk((list(credit), list(counts)), self._CHUNK)
            self._chunk_cache[ci] = got
            self._trim_cache()
        return got

    def _trim_cache(self) -> None:
        while len(self._chunk_cache) > self._CACHE:
            self._chunk_cache.pop(next(iter(self._chunk_cache)))

    def assignment(self, k: int) -> Tuple[int, int]:
        """Global position ``k`` → (source id, source-local index)."""
        with self._lock:
            ci, off = divmod(k, self._CHUNK)
            src, local = self._chunk(ci)
            return src[off], local[off]

    def counts_at(self, k: int) -> np.ndarray:
        """Per-source consumption over positions ``[0, k)`` — derived from
        the snapshot lattice + one partial chunk replay, never from mutable
        counters."""
        with self._lock:
            ci, off = divmod(k, self._CHUNK)
            self._ensure_snaps(ci)
            credit, counts = self._snaps[ci]
            if off == 0:
                return np.asarray(counts, np.int64)
            state = (list(credit), list(counts))
            self._run_chunk(state, off)
            return np.asarray(state[1], np.int64)


class MixtureDataset:
    """Weighted mixture of position-addressable sample streams.

    ``datasets[s]`` must expose ``num_samples`` and ``sample(i) -> np.ndarray``
    rows of one common width (all packed, or all windowed — never mixed).
    ``sample(k)`` resolves the global position through the schedule, then
    through the source's per-epoch permutation: pure in ``k``."""

    def __init__(self, names: Sequence[str], datasets: Sequence, weights: Sequence[float], seed: int = 1234):
        if not (len(names) == len(datasets) == len(weights)):
            raise ValueError("names/datasets/weights length mismatch")
        widths = {int(ds.sample(0).shape[0]) for ds in datasets}
        if len(widths) != 1:
            raise ValueError(
                f"mixture sources yield different row widths {sorted(widths)} — "
                "all sources must be packed, or all windowed, at one seq_len"
            )
        self.names = list(names)
        self.datasets = list(datasets)
        self.seed = seed
        self.schedule = MixtureSchedule(weights, seed=seed)
        self.row_width = widths.pop()
        self._perm_cache: Dict[Tuple[int, int], np.ndarray] = {}
        self._perm_lock = threading.Lock()

    @property
    def num_sources(self) -> int:
        return len(self.datasets)

    def _perm(self, s: int, epoch: int) -> np.ndarray:
        with self._perm_lock:
            key = (s, epoch)
            p = self._perm_cache.get(key)
            if p is None:
                p = shuffle_index(
                    self.datasets[s].num_samples, mix_seed(self.seed, s, epoch)
                )
                # bounded cache: sources wrap epochs at different rates; keep
                # the recent working set only
                if len(self._perm_cache) > 4 * len(self.datasets):
                    self._perm_cache.clear()
                self._perm_cache[key] = p
            return p

    def sample(self, k: int) -> np.ndarray:
        s, j = self.schedule.assignment(k)
        n = self.datasets[s].num_samples
        epoch, r = divmod(j, n)
        return self.datasets[s].sample(int(self._perm(s, epoch)[r]))

    def counts_at(self, k: int) -> Dict[str, int]:
        c = self.schedule.counts_at(k)
        return {name: int(c[i]) for i, name in enumerate(self.names)}

    def state_at(self, k: int) -> dict:
        """Checkpoint-meta record: the cursor in the sample domain plus the
        per-source consumption it implies (derived, so a restored record can
        be VERIFIED against a recount — see DataPipeline.verify_resume)."""
        return {
            "position": int(k),
            "per_source_consumed": self.counts_at(k),
            "weights": {n: float(w) for n, w in zip(self.names, self.schedule.weights)},
        }


class SingleSourceDataset(MixtureDataset):
    """One corpus behind the mixture interface — the degenerate mixture, so
    the pipeline/state/resume machinery has exactly one code path."""

    def __init__(self, name: str, dataset, seed: int = 1234):
        super().__init__([name], [dataset], [1.0], seed=seed)
