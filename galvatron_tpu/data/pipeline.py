"""DataPipeline: the trainer-facing facade over shards/mixture/packing/
prefetch (docs/DESIGN.md § Data pipeline).

One object that (a) yields device-ready batches (``put_fn`` applied — on the
prefetch thread when ``prefetch_depth > 0``, inline otherwise, so the trainer
has exactly one fetch call either way), (b) reports per-batch packing stats
(``last_meta``) for true-token MFU, (c) snapshots the sample-domain cursor +
per-source consumption for checkpoint meta (``state``), and (d) verifies a
restored cursor against a recount on resume (``verify_resume`` — the
replays-zero/skips-zero contract), and (e) shuts its prefetch thread down
cleanly from every trainer exit path (``close``).

Global sample position ``k`` is the single source of truth: batch ``b`` at
global batch size ``B`` serves positions ``[b·B, (b+1)·B)``. Everything
downstream of ``k`` (source choice, epoch, permutation slot, packed row) is
a pure function of ``(config, seed, k)``, which is what makes the PR 7
sample-domain cursor conversion exact across batch-size/topology changes.
"""

from __future__ import annotations

import threading
from typing import List, Optional

import numpy as np

from galvatron_tpu.data.mixture import (
    MixtureDataset,
    SingleSourceDataset,
    parse_mixture,
)
from galvatron_tpu.data.packing import PackedDataset, WindowedDataset, packed_batch_meta
from galvatron_tpu.data.prefetch import AsyncPrefetcher
from galvatron_tpu.data.shards import open_token_dataset


class DataPipeline:
    """Iterator of device-ready batches with cursor/stats side channels."""

    def __init__(
        self,
        dataset: MixtureDataset,
        global_batch_size: int,
        start_batch: int = 0,
        put_fn=None,
        prefetch_depth: int = 0,
        packed: bool = False,
    ):
        self.dataset = dataset
        self.global_batch_size = int(global_batch_size)
        self.packed = packed
        self.put_fn = put_fn if put_fn is not None else (lambda b: b)
        self.last_meta: dict = {}
        self._pos = start_batch * self.global_batch_size
        self._pos_lock = threading.Lock()
        # the prefetch thread starts LAZILY on the first fetch, not here:
        # the trainer builds the pipeline during setup, a few hundred lines
        # before the try/finally that owns close() — an eager thread would
        # leak (GC-rooted via threading._active) on any setup failure in
        # between, holding device batches and corpus mmaps forever
        self._prefetch_depth = prefetch_depth
        self._prefetcher: Optional[AsyncPrefetcher] = None
        self._closed = False

    def _make_item(self):
        """Assemble the next host batch (+ its meta). Runs on the prefetch
        thread when prefetching; the batch is freshly allocated every call
        (np.stack) and never written after hand-off (GTL103 discipline)."""
        with self._pos_lock:
            k0 = self._pos
            self._pos += self.global_batch_size
        batch = np.stack(
            [self.dataset.sample(k0 + r) for r in range(self.global_batch_size)]
        ).astype(np.int32, copy=False)
        meta = packed_batch_meta(batch) if self.packed else {}
        meta["position"] = k0
        return batch, meta

    def __iter__(self):
        return self

    def __next__(self):
        if self._prefetch_depth > 0:
            if self._prefetcher is None:
                if self._closed:
                    raise StopIteration
                self._prefetcher = AsyncPrefetcher(
                    self._make_item, self.put_fn, depth=self._prefetch_depth
                )
            batch, meta = next(self._prefetcher)
        else:
            host, meta = self._make_item()
            batch = self.put_fn(host)
        self.last_meta = meta
        return batch

    # --- cursor / resume -------------------------------------------------

    def state(self, samples_consumed: int) -> dict:
        """Checkpoint-meta record for a run that has consumed
        ``samples_consumed`` samples since stream start (the trainer's
        ``samples_done``) — pure in the position, so safe from the watchdog
        thread mid-step."""
        st = self.dataset.state_at(int(samples_consumed))
        if self.packed:
            st["packed"] = True
        return st

    def verify_resume(self, saved_state: dict, samples_consumed: int) -> None:
        """Assert a restored checkpoint's per-source counters match what this
        pipeline derives for the same sample position: equality means the
        resumed stream replays zero and skips zero samples per source; a
        mismatch means the mixture config (sources/weights/seed) changed under
        the checkpoint, and resuming would silently re-serve or drop data."""
        if not isinstance(saved_state, dict):
            return
        pos = int(saved_state.get("position", samples_consumed))
        if pos != int(samples_consumed):
            raise ValueError(
                f"data-pipeline resume: checkpoint records sample position "
                f"{pos} but the trainer resumes at {samples_consumed} — the "
                "sample-domain cursor did not convert cleanly"
            )
        if bool(saved_state.get("packed")) != bool(self.packed):
            raise ValueError(
                "data-pipeline resume: the checkpoint was written with "
                f"pack_sequences={bool(saved_state.get('packed'))} but this "
                f"run has pack_sequences={bool(self.packed)} — the sample "
                "streams differ (packed rows vs windows) even at an "
                "identical cursor"
            )
        saved = saved_state.get("per_source_consumed")
        if not isinstance(saved, dict):
            return
        derived = self.dataset.counts_at(pos)
        if set(saved) != set(derived) or any(
            int(saved[n]) != derived[n] for n in derived
        ):
            raise ValueError(
                "data-pipeline resume: per-source consumption mismatch — "
                f"checkpoint {saved} vs derived {derived} at position {pos}. "
                "The mixture (sources, weights, or seed) changed since the "
                "checkpoint; resuming would replay or skip samples."
            )

    # --- stats ------------------------------------------------------------

    def summary(self, samples_consumed: Optional[int] = None) -> dict:
        """End-of-run record for the metrics JSONL: realized per-source
        consumption + the dataset-level packing efficiency. Flat scalars —
        the JSONL sink rejects nested values by contract. Pass the trainer's
        ``samples_done``: the producer's own position runs ahead of training
        by the prefetch depth."""
        pos = int(self._pos if samples_consumed is None else samples_consumed)
        out = {
            f"consumed_{name}": count
            for name, count in self.dataset.counts_at(pos).items()
        }
        out["samples_consumed"] = pos
        effs = [
            ds.packing_efficiency
            for ds in self.dataset.datasets
            if hasattr(ds, "packing_efficiency")
        ]
        if effs:
            out["dataset_packing_efficiency"] = float(np.mean(effs))
        return out

    def close(self) -> None:
        self._closed = True
        if self._prefetcher is not None:
            self._prefetcher.close()
            self._prefetcher = None


def build_data_pipeline(
    cfg,
    global_batch_size: int,
    seq_len: int,
    seed: int = 1234,
    start_batch: int = 0,
    data_path: Optional[str] = None,
    mixture: Optional[str] = None,
    pack: bool = False,
    prefetch_depth: int = 0,
    put_fn=None,
    resume_state: Optional[dict] = None,
    max_open_bins: int = 64,
) -> DataPipeline:
    """Resolve (--data_path | --data_mixture) × --pack_sequences ×
    --prefetch_depth into a DataPipeline. ``resume_state`` (the checkpoint's
    ``data_state`` meta) is verified against the rebuilt cursor."""
    if cfg.image_size:
        raise ValueError(
            "the data pipeline (mixture/packing/prefetch) serves token "
            "corpora; vision models use the synthetic loader"
        )
    if pack and (cfg.objective != "clm" or cfg.enc_layers):
        raise ValueError(
            "--pack_sequences requires a decoder-only CLM model (segment "
            "masking and per-segment positions are defined for causal LM rows)"
        )
    if not data_path and not mixture:
        raise ValueError(
            "the data pipeline needs --data_path or --data_mixture (synthetic "
            "streams keep the legacy loader; packing needs real documents)"
        )

    if mixture:
        sources = parse_mixture(mixture)
        names = [s.name for s in sources]
        prefixes = [s.prefix for s in sources]
        weights = [s.weight for s in sources]
    else:
        import os

        names = [os.path.basename(data_path)]
        prefixes = [data_path]
        weights = [1.0]

    def rows_for(prefix: str):
        ds = open_token_dataset(prefix)
        if ds.meta.get("vocab_size", 0) > cfg.vocab_size:
            raise ValueError(
                f"corpus {prefix} vocab {ds.meta.get('vocab_size')} exceeds "
                f"the model vocab {cfg.vocab_size}"
            )
        if pack:
            return PackedDataset(ds, seq_len, max_open_bins=max_open_bins)
        return WindowedDataset(ds, seq_len)

    datasets = [rows_for(p) for p in prefixes]
    if len(datasets) == 1:
        mix = SingleSourceDataset(names[0], datasets[0], seed=seed)
    else:
        mix = MixtureDataset(names, datasets, weights, seed=seed)

    pipe = DataPipeline(
        mix,
        global_batch_size,
        start_batch=start_batch,
        put_fn=put_fn,
        prefetch_depth=prefetch_depth,
        packed=pack,
    )
    if resume_state is not None:
        try:
            pipe.verify_resume(resume_state, start_batch * global_batch_size)
        except Exception:
            pipe.close()  # don't leak the prefetch thread on a refused resume
            raise
    return pipe
