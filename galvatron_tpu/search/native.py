"""Loader for the native C++ DP core (csrc/dp_core.cpp).

The reference builds its DP kernel with pybind11 via setup.py (reference:
csrc/dp_core.cpp:92-94, setup.py:39-44, Makefile:1-20). pybind11 is not in
this environment, so the kernel is a plain C-ABI shared object compiled with
g++ on first use and bound with ctypes; dynamic_programming.py falls back to
NumPy when no compiler is available (mirroring the reference's NumPy fallback,
galvatron/core/dynamic_programming.py:98-128).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path
from typing import Optional

import numpy as np

_REPO_ROOT = Path(__file__).resolve().parents[2]
_SRC = _REPO_ROOT / "csrc" / "dp_core.cpp"
_BUILD_DIR = _REPO_ROOT / "build"
_SO = _BUILD_DIR / "libgalvatron_dp_core.so"

_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    _BUILD_DIR.mkdir(exist_ok=True)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", str(_SRC), "-o", str(_SO)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def get_dp_core() -> Optional[ctypes.CDLL]:
    """Returns the loaded library or None (→ NumPy fallback)."""
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed:
        return None
    try:
        if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
            if not _build():
                _load_failed = True
                return None
        lib = ctypes.CDLL(str(_SO))
        lib.galvatron_dp_core.restype = ctypes.c_double
        lib.galvatron_dp_core.argtypes = [
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ctypes.POINTER(ctypes.c_int32),
        ]
        _lib = lib
        return _lib
    except Exception:
        _load_failed = True
        return None


def dp_core_native(mem: np.ndarray, intra: np.ndarray, inter: np.ndarray, budget: int):
    """Run the native DP. mem: (L,S) int32 units; intra: (L,S); inter: (S,S).
    Returns (min_cost, res[L], mem_used) or None if the library is missing."""
    lib = get_dp_core()
    if lib is None:
        return None
    L, S = mem.shape
    res = np.full((L,), -1, np.int32)
    mem_used = ctypes.c_int32(0)
    cost = lib.galvatron_dp_core(
        np.int32(L), np.int32(budget), np.int32(S),
        np.ascontiguousarray(mem, np.int32),
        np.ascontiguousarray(intra, np.float64),
        np.ascontiguousarray(inter, np.float64),
        res, ctypes.byref(mem_used),
    )
    return float(cost), res, int(mem_used.value)
