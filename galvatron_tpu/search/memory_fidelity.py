"""Memory-fidelity harness: MemoryCost predictions vs compiled reality.

The memory side of the cost model decides DP *feasibility* — a strategy
mis-priced in MB silently deletes or falsely admits candidates — so its
terms must be validated against what XLA actually allocates, the way the
time side has its closed ``check_cost_model``/``validate_top_k`` loop
(reference bar: the MemoryCostModel ratio-curve *fits*,
galvatron/core/cost_model.py:56-60 — they fit theirs to measurement; ours
must be at least as grounded).

Measured side: the production ``train_step`` is AOT-compiled against a
device-less TPU **topology** (``jax.experimental.topologies``, e.g.
``v5e:2x4``) and the real TPU compiler's buffer assignment is read via
``memory_analysis()`` — authoritative per-device numbers, no chips needed.
The 8-device CPU simulation is NOT usable for this: its ``memory_analysis``
aggregates across all addressable devices and models none of the TPU
backend's buffer reuse.

Predicted side: the search's own pricing — ``layer_memory_cost`` summed over
the heaviest stage + ``other_memory_cost`` — so the harness validates
exactly what the DP consumes.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional

from galvatron_tpu.core.strategy import HybridParallelConfig
from galvatron_tpu.search.cost_model import (
    ProfiledModelCosts,
    layer_memory_cost,
    other_memory_cost,
    transient_overhead_mb,
)


# single-host topologies this module knows how to declare to libtpu:
# topology_name → (TPU_ACCELERATOR_TYPE, TPU_CHIPS_PER_HOST_BOUNDS)
_DECLARABLE_TOPOLOGIES = {
    "v5e:2x4": ("v5litepod-8", "2,4,1"),
}


def declare_local_tpu_topology_env(topology: str = "v5e:2x4") -> None:
    """Declare a single-host TPU topology to libtpu via the environment.

    Off GCE, libtpu's topology init retries the GCP metadata server for
    MINUTES (403s) before giving up and proceeding anyway — every
    ``get_topology_desc`` caller pays it, which is most of what a
    topology-AOT test costs.  Declaring the topology up front makes init
    instant.  ``setdefault`` throughout: a real pod's own environment always
    wins.  The MDS skip and the accelerator type must be set TOGETHER —
    type alone SIGILLs libtpu.

    Deliberately a no-op on hosts with local TPU devices (``/dev/accel*`` /
    ``/dev/vfio``): there libtpu's own metadata/env path is authoritative,
    and a declared shape that disagrees with the real machine would poison
    every later backend init in this process (and in forked children).
    Also a no-op for topologies outside ``_DECLARABLE_TOPOLOGIES`` — a
    v5e-8 declaration under a ``v4:...`` request would be a lie libtpu
    acts on."""
    import glob

    if glob.glob("/dev/accel*") or os.path.exists("/dev/vfio"):
        return
    spec = _DECLARABLE_TOPOLOGIES.get(topology)
    if spec is None:
        return
    accelerator_type, chip_bounds = spec
    if os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1") != "1":
        return
    os.environ.setdefault("TPU_ACCELERATOR_TYPE", accelerator_type)
    os.environ.setdefault("TPU_CHIPS_PER_HOST_BOUNDS", chip_bounds)
    os.environ.setdefault("TPU_HOST_BOUNDS", "1,1,1")
    os.environ.setdefault("TPU_WORKER_ID", "0")
    os.environ.setdefault("TPU_WORKER_HOSTNAMES", "localhost")


@dataclass
class FidelityRow:
    label: str
    predicted_mb: float
    measured_mb: float
    # measured decomposition (MB/device): state (arguments minus batch,
    # outputs aliased away), temps (grads + activations + scratch)
    state_mb: float
    temp_mb: float

    @property
    def ratio(self) -> float:
        return self.predicted_mb / max(self.measured_mb, 1e-9)


def predicted_train_mb(
    costs: ProfiledModelCosts,
    cfg,
    hp: HybridParallelConfig,
    world: int,
    global_bsz: int,
) -> float:
    """Per-device MB the search would charge this config: the heaviest
    stage's (positions x layer_memory_cost) + the embed/head/loss 'other'
    term (replicated over pp in this runtime, so charged on every stage)."""
    from galvatron_tpu.core.strategy import balanced_division

    lt = costs.layer_types[0]
    pp = hp.pp
    L = cfg.total_layers
    div = list(hp.pp_division) if hp.pp_division else balanced_division(L, pp)
    stage_mb = []
    off = 0
    for st in range(pp):
        mb = 0.0
        for j in range(div[st]):
            s = hp.layer_strategies[off + j]
            mb += layer_memory_cost(
                lt, s, world, pp, global_bsz, hp.chunks, stage_idx=st,
                pipeline_type=hp.pipeline_type, mixed_precision=hp.mixed_precision,
                vpp=hp.vpp,
            ).total_mb
        off += div[st]
        stage_mb.append(mb)
    other = other_memory_cost(
        costs, world, pp, hp.vocab_tp, hp.embed_dp_type, global_bsz, hp.chunks,
        hp.mixed_precision,
    )
    # single-stack/interleaved 1F1B per-device constants — THE SAME pricing
    # evaluate() charges (cost_model.single_1f1b_rings_mb), not a
    # re-derivation that could drift
    pf = 0.0
    if pp > 1 and hp.pipeline_type == "pipedream_flush":
        from galvatron_tpu.search.cost_model import single_1f1b_rings_mb

        pf = single_1f1b_rings_mb(
            lt, hp.layer_strategies[0], world, pp, global_bsz, hp.chunks,
            hp.mixed_precision, vpp=max(1, hp.vpp),
            layers_per_device=max(div),
        )
    trans = transient_overhead_mb(
        costs, min(s.tp for s in hp.layer_strategies), hp.mixed_precision
    )
    return max(stage_mb) + other + pf + trans


def measured_train_mb(
    cfg,
    hp: HybridParallelConfig,
    global_bsz: int,
    seq: Optional[int] = None,
    topology: str = "v5e:2x4",
) -> Optional[dict]:
    """AOT-compile the production train step against the TPU topology and
    read the per-device plan: state = arguments + outputs − aliased (the
    donated train state counts once), temp = scratch (grads + activations).
    Returns None where topology AOT is unavailable (no libtpu)."""
    import jax
    import jax.numpy as jnp

    try:
        from jax.experimental import topologies

        declare_local_tpu_topology_env(topology)
        topo = topologies.get_topology_desc(platform="tpu", topology_name=topology)
    except Exception:
        return None
    from galvatron_tpu.core.checkpoint import abstract_state_of
    from galvatron_tpu.core.optim import AdamConfig
    from galvatron_tpu.parallel.hybrid import build_runtime
    from galvatron_tpu.parallel.mesh import build_mesh

    seq = seq or cfg.max_seq_len
    mesh, axes = build_mesh(pp=hp.pp, devices=list(topo.devices))
    rt = build_runtime(
        cfg, hp, mesh=mesh, axes=axes, adam=AdamConfig(lr=1e-3),
        global_batch_size=global_bsz, seq_len=seq,
    )
    from galvatron_tpu.models.modeling import batch_row_width

    batch = jax.ShapeDtypeStruct(
        (global_bsz, batch_row_width(cfg, seq)),
        jnp.int32, sharding=rt.batch_sharding,
    )
    ma = rt.train_step.lower(abstract_state_of(rt), batch).compile().memory_analysis()
    if ma is None:
        return None
    state = (
        ma.argument_size_in_bytes
        + ma.output_size_in_bytes
        - ma.alias_size_in_bytes
    ) / 1e6
    temp = ma.temp_size_in_bytes / 1e6
    return {"state_mb": state, "temp_mb": temp, "total_mb": state + temp}


def calibrate_costs(
    cfg,
    costs: ProfiledModelCosts,
    global_bsz: int = 16,
    tps=(1, 2),
    topology: str = "v5e:2x4",
) -> Optional[ProfiledModelCosts]:
    """Replace the activation table with TOPOLOGY-MEASURED values — the
    production basis (profiling/model.py measures activations; the analytic
    table only seeds searches before any profiling exists).

    Per-layer per-sample activation at degree tp isolated by the DOUBLE
    difference of compiled temp bytes over (num_layers, batch): layer-count
    difference removes embed/head/loss temps, batch difference removes
    batch-independent transients (casts, per-layer grads) — the same
    difference method the reference's profiler uses on real runs
    (galvatron/core/profiler.py:243-401). Returns None where topology AOT
    is unavailable."""
    import dataclasses as _dc

    from galvatron_tpu.core.strategy import HybridParallelConfig, LayerStrategy

    world = 8
    act = {}
    for tp in tps:
        t = {}
        for L in (2, 4):
            for bsz in (global_bsz, 2 * global_bsz):
                c = cfg.replace(num_layers=L)
                h = HybridParallelConfig(
                    layer_strategies=[LayerStrategy(tp=tp)] * L,
                    vocab_tp=tp, mixed_precision="bf16",
                )
                m = measured_train_mb(c, h, bsz, topology=topology)
                if m is None:
                    return None
                t[(L, bsz)] = m["temp_mb"]
        dp = world // tp
        d_samples = global_bsz / dp  # extra samples/device at the 2x batch
        per_layer = (
            (t[(4, 2 * global_bsz)] - t[(2, 2 * global_bsz)])
            - (t[(4, global_bsz)] - t[(2, global_bsz)])
        ) / (2 * d_samples)
        act[tp] = max(per_layer, 0.01)
    lt = costs.layer_types[0]
    new_lt = _dc.replace(lt, activation_mb_per_sample=act)
    return _dc.replace(costs, layer_types={0: new_lt})


def fidelity_row(
    label: str,
    costs: ProfiledModelCosts,
    cfg,
    hp: HybridParallelConfig,
    global_bsz: int,
    world: int = 8,
    topology: str = "v5e:2x4",
) -> Optional[FidelityRow]:
    meas = measured_train_mb(cfg, hp, global_bsz, topology=topology)
    if meas is None:
        return None
    pred = predicted_train_mb(costs, cfg, hp, world, global_bsz)
    return FidelityRow(
        label=label,
        predicted_mb=pred,
        measured_mb=meas["total_mb"],
        state_mb=meas["state_mb"],
        temp_mb=meas["temp_mb"],
    )


def format_rows(rows: List[FidelityRow]) -> str:
    out = [
        f"{'cell':<34} {'pred MB':>9} {'meas MB':>9} {'state':>8} {'temp':>8} {'ratio':>6}"
    ]
    for r in rows:
        out.append(
            f"{r.label:<34} {r.predicted_mb:>9.1f} {r.measured_mb:>9.1f} "
            f"{r.state_mb:>8.1f} {r.temp_mb:>8.1f} {r.ratio:>6.3f}"
        )
    return "\n".join(out)
