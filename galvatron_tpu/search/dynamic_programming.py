"""Layer-strategy dynamic program: native C++ core with NumPy fallback.

Counterpart of the reference's DPAlg/DpOnModel (reference:
galvatron/core/dynamic_programming.py:39-128,130-494). The DP assigns one
strategy per layer (pp=1) or per stage-position (pp>1, matching the runtime's
SPMD stacking constraint) minimizing total time under a per-chip memory
budget, with inter-layer transition costs for activation resharding when the
TP degree/layout changes between adjacent layers (reference transition
matrix: dynamic_programming.py:233-272)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import numpy as np

from galvatron_tpu.core.strategy import LayerStrategy
from galvatron_tpu.search.cost_model import (
    ProfiledHardware,
    ProfiledLayerType,
    _allgather_ms,
)
from galvatron_tpu.search.native import dp_core_native


def dp_numpy(
    mem: np.ndarray, intra: np.ndarray, inter: np.ndarray, budget: int
) -> Tuple[float, np.ndarray, int]:
    """Pure-NumPy DP with the same semantics as csrc/dp_core.cpp (the
    reference keeps the same dual implementation,
    dynamic_programming.py:98-128). Vectorized over the budget axis."""
    L, S = mem.shape
    INF = np.inf
    V = budget
    f = np.full((V + 1, S), INF)
    choice = np.full((L, V + 1, S), -1, np.int16)
    for s in range(S):
        if mem[0, s] <= V and np.isfinite(intra[0, s]):
            f[mem[0, s] :, s] = intra[0, s]
    for i in range(1, L):
        fn = np.full((V + 1, S), INF)
        for s in range(S):
            m = mem[i, s]
            if m > V or not np.isfinite(intra[i, s]):
                continue
            prev = f[: V + 1 - m, :] + inter[:, s][None, :]  # (V+1-m, S)
            best_si = np.argmin(prev, axis=1)
            best = prev[np.arange(prev.shape[0]), best_si]
            ok = np.isfinite(best)
            fn[m:, s] = np.where(ok, best + intra[i, s], INF)
            choice[i, m:, s] = np.where(ok, best_si, -1)
        f = fn
    flat = np.argmin(f)
    v, s = np.unravel_index(flat, f.shape)
    if not np.isfinite(f[v, s]):
        return float("inf"), np.full((L,), -1, np.int32), 0
    cost = float(f[v, s])
    res = np.empty((L,), np.int32)
    vv, ss = int(v), int(s)
    for i in range(L - 1, -1, -1):
        res[i] = ss
        if i > 0:
            si = int(choice[i, vv, ss])
            vv -= int(mem[i, ss])
            ss = si
    return cost, res, int(v)


def run_dp(mem, intra, inter, budget) -> Tuple[float, np.ndarray, int]:
    out = dp_core_native(mem, intra, inter, budget)
    if out is not None:
        return out
    return dp_numpy(mem, intra, inter, budget)


def transition_cost_ms(
    a: LayerStrategy,
    b: LayerStrategy,
    lt: ProfiledLayerType,
    hw: ProfiledHardware,
    world: int,
    pp: int,
    global_bsz: int,
    mixed_precision: str = "bf16",
) -> float:
    """Activation-resharding time between adjacent layers with different
    TP/layout — in this runtime XLA emits the collectives at the
    with_sharding_constraint boundary; the cost is modeled as the all-gather
    of the boundary tensor over the axes whose sharding changes (reference:
    redistribution volume, dynamic_programming.py:233-246,357-372)."""
    if (a.tp, a.tp_consec, a.sp, a.cp) == (b.tp, b.tp_consec, b.sp, b.cp):
        return 0.0
    dp_b = world // (pp * b.tp * b.cp)
    bytes_factor = 0.5 if mixed_precision == "bf16" else 1.0
    msg = lt.boundary_activation_mb_per_sample * (global_bsz / dp_b) * bytes_factor
    # resharding ≈ all-gather over the union of changed axes, bounded by the
    # larger of the two tp groups; layout flips pay the strided bandwidth
    size = max(a.tp * a.cp, b.tp * b.cp)
    if size == 1:
        size = 2  # batch-dim resharding between different dp splits
    consec = a.tp_consec and b.tp_consec
    # fwd reshard + mirrored bwd reshard
    return 2.0 * _allgather_ms(msg, size, hw.bw(size, consec))
