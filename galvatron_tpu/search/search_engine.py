"""The automatic-parallelism search engine.

Counterpart of the reference's GalvatronSearchEngine (reference:
galvatron/core/search_engine.py:17-715): enumerate the hybrid-strategy space
over powers of two — {pp} × {tp, layout} × {zero2/zero3 vs ddp} × {sp} ×
{ckpt} (+ optional cp rings for long context) — evaluate micro-batch counts,
run the per-layer dynamic program under the per-chip HBM budget for every
(pp, bsz, chunks), refine with the pipeline cost model, and emit the winning
strategy as a runtime-loadable HybridParallelConfig JSON
(search flow: search_engine.py:168-324; config save :326-367).

Output throughput metric matches the reference's
``Max throughput = bsz / min_cost`` (search_engine.py:318-321).
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from galvatron_tpu.core.strategy import HybridParallelConfig, LayerStrategy, form_strategy
from galvatron_tpu.obs.tracing import tracer as _obs_tracer
from galvatron_tpu.search.cost_model import (
    REMAT_FULL_FACTOR,
    single_1f1b_rings_mb,
    stash_ring_mb,
    transient_overhead_mb,
    MemoryCost,
    ProfiledHardware,
    ProfiledLayerType,
    ProfiledModelCosts,
    layer_memory_cost,
    layer_time_cost,
    other_memory_cost,
    other_time_cost,
    pipeline_time_cost,
)
from galvatron_tpu.search.dynamic_programming import run_dp, transition_cost_ms
from galvatron_tpu.search.pp_division import pp_division_memory_balanced


@dataclass
class SearchSpace:
    world_size: int
    max_tp: Optional[int] = None
    allow_sp: bool = True
    allow_ckpt: bool = True
    allow_zero2: bool = True
    allow_zero3: bool = True
    allow_strided: bool = True
    allow_cp: bool = False
    # decomposed collective-matmul on the TP projection seams as a searched
    # dimension (LayerStrategy.tp_overlap; cost_model.TP_OVERLAP_RESIDUAL
    # prices the hidden collective). Opt-in: it doubles the tp>1 candidate
    # count and only helps where the projection collectives are exposed.
    allow_tp_overlap: bool = False
    # expert parallelism as a searched dimension (MoE models; the reference
    # carries SwitchMLP but never searches EP — SURVEY §2.3 ⚠). ep candidates
    # ∈ powers of two up to the dp extent (and max_ep) that divide
    # moe_experts — the runtime cannot shard E experts over a larger or
    # non-dividing ep and would silently replicate them instead.
    allow_ep: bool = False
    max_ep: Optional[int] = None
    moe_experts: int = 0  # the model's expert count (0 = dense → no ep)
    pp_choices: Optional[List[int]] = None
    pipeline_types: Tuple[str, ...] = ("gpipe", "pipedream_flush")
    # interleaved virtual stages: search vpp ∈ powers of two up to max_vpp
    # (gpipe schedule only; 1 = off)
    max_vpp: int = 1
    # model divisibility constraints (0 = unconstrained). tp candidates must
    # divide num_heads (head-sharded attention cannot split 25 GPT-2-XL
    # heads over tp=2) and vocab_tp candidates must divide vocab_size
    # (50257 is odd — any vocab_tp>1 would silently replicate the embedding
    # instead of sharding it, falsifying the memory model). Found by the
    # emit-path self-check (analysis/plan_check GTA007/GTA008); SearchEngine
    # fills these from model_config when given.
    num_heads: int = 0
    vocab_size: int = 0


def apply_search_space(space: SearchSpace, name: str) -> SearchSpace:
    """Restrict ``space`` in place per the ``--search_space`` presets
    (reference: the check_cost_model search-space modes). One rule shared by
    the CLI and the elastic re-plan entry point, so a supervised restart
    searches exactly the subspace the operator originally asked for."""
    if name == "dp":
        space.max_tp, space.pp_choices = 1, [1]
    elif name == "tp":
        space.pp_choices = [1]
    elif name == "pp":
        space.max_tp = 1
    elif name == "dp+tp":
        space.pp_choices = [1]
    elif name == "dp+pp":
        space.max_tp = 1
    elif name == "sdp":
        space.max_tp, space.pp_choices = 1, [1]
    elif name == "3d":
        # pure pp x tp x dp grid: no ZeRO/ckpt/layout/SP variants
        space.allow_zero2 = space.allow_zero3 = False
        space.allow_ckpt = space.allow_sp = space.allow_strided = False
    elif name != "full":
        raise ValueError(f"unknown search_space preset {name!r}")
    return space


def _pow2s(n: int) -> List[int]:
    out, v = [], 1
    while v <= n:
        out.append(v)
        v *= 2
    return out


def _vocab_strategy_pairs(world: int, pp: int, vocab_size: int = 0):
    """Searched (vocab_tp, embed_dp_type) candidates — one rule shared by
    evaluate() and check_cost_model(). vocab_tp must divide the vocab
    (vocab_size=0 = unconstrained): a non-dividing degree cannot shard the
    embedding table, so the runtime would silently replicate it."""
    for vt in _pow2s(world // pp):
        if vocab_size and vocab_size % vt:
            continue
        for et in ["ddp", "zero3"] if world // (pp * vt) > 1 else ["ddp"]:
            yield vt, et


def generate_layer_strategies(space: SearchSpace, pp: int) -> List[LayerStrategy]:
    """Per-layer strategy candidates for a given pp (reference:
    generate_strategies, search_engine.py:424-537)."""
    per_stage = space.world_size // pp
    tps = [
        t for t in _pow2s(per_stage)
        if (space.max_tp is None or t <= space.max_tp)
        and (space.num_heads == 0 or space.num_heads % t == 0)
    ]
    out: List[LayerStrategy] = []
    for tp in tps:
        dp = per_stage // tp
        consec_opts = [True, False] if (space.allow_strided and 1 < tp < per_stage) else [True]
        sp_opts = [False, True] if (space.allow_sp and tp > 1) else [False]
        dp_types = ["ddp"]
        if dp > 1 and space.allow_zero2:
            dp_types.append("zero2")
        if dp > 1 and space.allow_zero3:
            dp_types.append("zero3")
        cp_opts = [1]
        if space.allow_cp and dp > 1:
            cp_opts += [c for c in _pow2s(dp) if c > 1]
        ep_opts = [1]
        if space.allow_ep and dp > 1 and space.moe_experts > 0:
            ep_opts += [
                e for e in _pow2s(dp)
                if e > 1
                and (space.max_ep is None or e <= space.max_ep)
                and space.moe_experts % e == 0
            ]
        tov_opts = [False, True] if (space.allow_tp_overlap and tp > 1) else [False]
        for consec, sp, dpt, cp, ep, tov in itertools.product(
            consec_opts, sp_opts, dp_types, cp_opts, ep_opts, tov_opts
        ):
            if cp > 1 and sp:
                continue
            if cp > 1 and ep > 1:  # they share mesh axes (strategy.validate)
                continue
            if cp > 1 and tov:  # cp layers own their projection seams
                continue
            for ckpt in [False, True] if space.allow_ckpt else [False]:
                out.append(
                    LayerStrategy(
                        tp=tp, tp_consec=consec, dp_type=dpt, ckpt=ckpt, sp=sp,
                        cp=cp, ep=ep, tp_overlap=tov,
                    )
                )
    return out


@dataclass
class SearchResult:
    config: HybridParallelConfig
    cost_ms: float
    throughput_samples_per_s: float
    global_bsz: int
    memory_mb: float
    details: Dict = field(default_factory=dict)


class SearchEngine:
    """Ties profiled model + hardware data to the DP (reference:
    GalvatronSearchEngine.initialize_search_engine / parallelism_optimization,
    search_engine.py:85-90,168-228)."""

    def __init__(
        self,
        model_costs: ProfiledModelCosts,
        hardware: ProfiledHardware,
        num_layers: int,
        space: SearchSpace,
        memory_budget_mb: float,
        mixed_precision: str = "bf16",
        mem_unit_mb: float = 8.0,
        section_pipeline: bool = False,
        model_config=None,
        model_name: str = "",
    ):
        self.costs = model_costs
        self.hw = hardware
        self.L = num_layers
        self.space = space
        self.budget_mb = memory_budget_mb
        self.mp = mixed_precision
        self.unit = mem_unit_mb
        # provenance for save_result's emitted JSON (self-describing configs)
        # and the emit-path self-check (analysis.plan_check): when set, every
        # emitted plan is validated against the model before it is written
        self.model_config = model_config
        self.model_name = model_name
        if model_config is not None:
            # model divisibility constraints on the candidate space: a tp
            # that cannot split the heads or a vocab_tp that cannot shard
            # the vocab would emit a plan the plan checker (and the runtime)
            # rejects — the self-check in save_result pins this. Copy, never
            # mutate: a caller reusing one SearchSpace across engines for
            # different models must not inherit the first model's limits.
            self.space = space = dataclasses.replace(
                space,
                num_heads=space.num_heads
                or int(getattr(model_config, "num_heads", 0) or 0),
                vocab_size=space.vocab_size
                or int(getattr(model_config, "vocab_size", 0) or 0),
            )
        # structural bail-outs that fired during the last sweep (multi-type
        # schedule/shape classes the engines cannot realize) — written into
        # the emitted config as `search_restrictions` the way
        # fallback_bandwidths already labels unmeasured bandwidths. Every
        # remaining tag is a standing exclusion (interleaved vpp for
        # multi-type, odd section pair counts), so a fired tag is always
        # reported. (The former chunks-divisibility tag — the one case a
        # later grid point could "clear" — is gone: the coupled engines run
        # any chunk count.)
        self._restrictions: set = set()
        # True = multi-type groups are a vision pyramid (pipeline_swin's
        # K-section pair-stacked engine) even at K=2 — a 2-stage Swin profile
        # is otherwise indistinguishable from an enc-dec one (the CLI sets
        # this from cfg.swin_depths)
        self.section_pipeline = section_pipeline

    def _ring_mb(
        self, lt: ProfiledLayerType, s: LayerStrategy, slots: int,
        world: int, pp: int, global_bsz: int, chunks: int,
        stage_idx: int = 0, vpp: int = 1,
    ) -> float:
        """Per-device MB of ONE coupled-1F1B input-stash ring of ``slots``
        boundary micro-batch slots, priced at strategy ``s`` (which
        approximates the section input's sharding). Isolated as the
        difference of layer_memory_cost at bounds (slots, 0) so the formula
        stays the cost model's — the states terms cancel exactly. The
        runtime allocates one extra sacrificial slot per ring beyond the
        useful ones (pipeline_swin.py `(n_s[k] + 1,) + shp[k]`, same in
        pipeline_encdec), so the charge is min(chunks, slots) useful slots
        plus one unconditional."""
        return stash_ring_mb(
            lt, s, slots, world, pp, global_bsz, chunks, self.mp,
            stage_idx=stage_idx, vpp=vpp,
        )

    def _1f1b_rings_mb(
        self, lt: ProfiledLayerType, s: LayerStrategy, world: int, pp: int,
        global_bsz: int, chunks: int, vpp: int = 1, layers_per_device: int = 1,
    ) -> float:
        """See cost_model.single_1f1b_rings_mb (the one shared pricing)."""
        return single_1f1b_rings_mb(
            lt, s, world, pp, global_bsz, chunks, self.mp, vpp=vpp,
            layers_per_device=layers_per_device,
        )

    def _layer_type(self, i: int) -> ProfiledLayerType:
        lts = self.costs.layer_types
        return lts.get(i, lts[0]) if len(lts) > 1 else lts[0]

    def _vocab_use_measured(self) -> bool:
        """Consistent vocab pricing across the ENTIRE search: consume the
        measured fit only when every vocab_tp degree any pp in the sweep can
        select (powers of two up to world // min(pp)) is covered — a mixed
        sweep, whether within one pp or across pps, would bias toward
        unmeasured degrees (the measured fit carries the batch-independent
        optimizer const the analytic terms price at zero)."""
        min_pp = min(self.space.pp_choices) if self.space.pp_choices else 1
        return all(
            self.costs.vocab_measurement_for(vt, self.mp) is not None
            for vt in _pow2s(self.space.world_size // min_pp)
            if not (self.space.vocab_size and self.space.vocab_size % vt)
        )

    def _feasible_strategies(self, pp: int, global_bsz: int, chunks: int):
        """Strategy space under the strict chunk filter: the micro-batch
        (global_bsz / chunks) must split over each strategy's dp axes.
        Shared by evaluate() and homogeneity_gap() so the two cost models
        cannot diverge."""
        world = self.space.world_size

        def feasible(s: LayerStrategy) -> bool:
            dp = world // (pp * s.tp * s.cp)
            return (global_bsz % (dp * chunks * max(1, s.cp))) == 0

        return [s for s in generate_layer_strategies(self.space, pp) if feasible(s)]

    def _boundary_msg_mb(self, lt, global_bsz: int, chunks: int) -> float:
        """Per-micro-batch p2p boundary volume (comm-dtype bytes)."""
        return (
            lt.boundary_activation_mb_per_sample
            * (global_bsz / chunks)
            * (0.5 if self.mp in ("bf16", "fp16") else 1.0)
        )

    @staticmethod
    def _stage_tick_ms(intra, inter, res, chunks: int, vpp: int = 1) -> float:
        """Per-tick stage time for a chosen per-position assignment: layer
        compute plus the inter-position resharding every micro-batch pays on
        its stage pass (transition tables price the full global batch, so
        /chunks yields the per-micro-batch share)."""
        n_pos = len(res)
        inter_sum = sum(inter[res[j], res[j + 1]] for j in range(n_pos - 1))
        return (sum(intra[j, res[j]] for j in range(n_pos)) + inter_sum) * vpp / chunks

    def _type_groups(self):
        """Contiguous (start, count, layer_type) runs over layer indices.
        Grouped by VALUE equality — JSON-loaded profiles materialize a fresh
        ProfiledLayerType per index, so identity would split every layer."""
        groups = []
        for i in range(self.L):
            lt = self._layer_type(i)
            if groups and groups[-1][2] == lt:
                groups[-1][1] += 1
            else:
                groups.append([i, 1, lt])
        return groups

    def _coupled_total_ms(
        self, tick_ms: float, pp: int, chunks: int, pipeline_type: str,
        global_bsz: int, multi_type, swin_groups,
    ) -> float:
        """Iteration time of the coupled tick-synchronous pipelines from one
        bottleneck tick — the ONE pricing both evaluate() and
        homogeneity_gap() use (a divergence here would make the gap measure
        formula skew instead of the homogeneity restriction).

        enc-dec (pipeline_encdec.py): every tick runs one enc + one dec
        virtual stage; T = chunks + 2pp - 1 (gpipe autodiff) or
        chunks + 4pp - 2 (coupled 1F1B; its per-tick section recompute is
        priced in the intra table); three ppermutes per tick — enc out and
        ctx at the encoder boundary size, dec y at the decoder's.
        Swin (pipeline_swin.py): every tick runs one virtual stage of EVERY
        section; T = chunks + K*pp - 1 (gpipe autodiff, K ring ppermutes) or
        chunks + 2K*pp - 2 (coupled 1F1B: per-tick section recompute priced
        in the intra table, 3K-1 ring sends — K section outputs + K-1 merged
        outputs + K backward cotangents)."""
        bf = 0.5 if self.mp in ("bf16", "fp16") else 1.0
        if multi_type is not None:
            enc_b = self._layer_type(0).boundary_activation_mb_per_sample
            dec_b = self._layer_type(multi_type[0]).boundary_activation_mb_per_sample
            p2p_mb = (2.0 * enc_b + dec_b) * (global_bsz / chunks) * bf
            T = (
                chunks + 4 * pp - 2
                if pipeline_type == "pipedream_flush"
                else chunks + 2 * pp - 1
            )
        else:
            bs = [lt.boundary_activation_mb_per_sample for _, lt in swin_groups]
            Ks = len(swin_groups)
            if pipeline_type == "pipedream_flush":
                # per tick: K section-output sends + K-1 merged sends (next
                # section's size) + K backward dx sends (pipeline_swin.py)
                p2p_mb = (2.0 * sum(bs) + sum(bs[1:])) * (global_bsz / chunks) * bf
                T = chunks + 2 * Ks * pp - 2
            else:
                p2p_mb = sum(bs) * (global_bsz / chunks) * bf
                T = chunks + Ks * pp - 1
        return T * (tick_ms + p2p_mb / self.hw.p2p(pp))

    # -- single (pp, bsz, chunks, pipeline_type) evaluation ------------------

    def evaluate(
        self, pp: int, global_bsz: int, chunks: int, pipeline_type: str, vpp: int = 1
    ) -> Optional[SearchResult]:
        # one span per DP phase: the search timeline shows where the sweep's
        # time goes (per-candidate per-layer DP), not just its total
        with _obs_tracer.span(
            "search_dp", bsz=global_bsz, pp=pp, chunks=chunks,
            schedule=pipeline_type, vpp=vpp,
        ):
            return self._evaluate(pp, global_bsz, chunks, pipeline_type, vpp)

    def _evaluate(
        self, pp: int, global_bsz: int, chunks: int, pipeline_type: str, vpp: int = 1
    ) -> Optional[SearchResult]:
        space = self.space
        world = space.world_size
        if world % pp or self.L < pp:
            return None
        multi_type = None  # (n_first, n_second) for a 2-group pp>1 pipeline
        swin_groups = None  # [(count, layer_type)] for a K>2-section pipeline
        if pp > 1 and len(self.costs.layer_types) > 1:
            # heterogeneous layer types (the reference's multi-layer-type DP,
            # dynamic_programming.py:304-455): TWO contiguous groups ride the
            # enc-dec coupled sub-pipelines (parallel/pipeline_encdec.py,
            # ragged counts via per-sub-stack padded divisions); K>2 groups
            # with even counts ride the K-section pair-stacked pipeline
            # (parallel/pipeline_swin.py); any chunk count (ring alignment
            # is per-chunk — measured parity at chunks % pp != 0).
            groups = self._type_groups()
            if vpp > 1:
                self._restrictions.add("multi_type_pp_no_interleaved_vpp")
                return None
            if len(groups) == 2 and not self.section_pipeline:
                # sub-stacks smaller than pp are fine: balanced_division
                # yields zero-layer (fully-masked identity) stages, so e.g. a
                # 2-encoder-layer T5 pipelines at pp=4 (reference analogue:
                # arbitrary per-stage layer ranges, core/pipeline/pipeline.py:75-77)
                multi_type = (groups[0][1], groups[1][1])
                # both coupled schedules exist for 2-group models: gpipe
                # (T = chunks + 2pp - 1, autodiff backward, act x chunks)
                # and the hand-written coupled 1F1B (pipeline_encdec.py:
                # T = chunks + 4pp - 2, input-stash ring + section
                # recompute, bounded memory)
            elif all(cnt % 2 == 0 for _, cnt, _ in groups):
                # both coupled schedules exist for K-section models too:
                # gpipe (T = chunks + K*pp - 1, autodiff backward) and the
                # coupled 1F1B (pipeline_swin.py: T = chunks + 2K*pp - 2,
                # per-section input-stash rings min(chunks, 2(K-k)pp - 1),
                # per-tick section recompute)
                swin_groups = [(cnt, lt) for _, cnt, lt in groups]
            else:
                self._restrictions.add("section_pipeline_odd_pair_count_pp1_only")
                return None
        if global_bsz % chunks:
            return None
        if vpp > 1:
            # interleaved-schedule constraints (strategy.py validate);
            # both schedules compose with vpp (gpipe = autodiff backward,
            # pipedream_flush = interleaved 1F1B, bounded activations)
            if pp == 1:
                return None
            if self.L % (pp * vpp) or chunks % pp:
                return None
        # stage division: uniform when possible; memory-balanced (reference
        # pp_division_memory_balanced) for ragged layer counts — the runtime
        # realizes it with padded stage stacking (pipeline.stage_layout)
        lps = -(-self.L // pp)  # positions per stage = max(division)
        division: Optional[List[int]] = None
        if pp > 1 and self.L % pp and multi_type is None and swin_groups is None:
            # single layer type here (multi-type paths carry their own
            # per-section divisions), and the balanced division is
            # scale-invariant over uniform memories — unit weights give the
            # same split as any baseline cost
            division = pp_division_memory_balanced([1.0] * self.L, pp)
            lps = max(division)
        cands = self._feasible_strategies(pp, global_bsz, chunks)
        if not cands:
            return None
        S = len(cands)

        # positions: pp=1 → every layer; pp>1 → one per stage position (the
        # stage-stacking constraint makes positions the DP unit; vpp>1 tightens
        # the period to layers-per-virtual-stage); memory is identical across
        # stages, stage 0 carries the 1F1B worst case. Multi-type (enc-dec)
        # pp>1: a device holds one virtual stage of EACH type, so positions =
        # lpe enc positions followed by lpd dec positions.
        pos_layers = 1  # layers per searched position (2 for swin pairs)
        if multi_type is not None:
            # padded sub-stacks: positions per stack = ceil(count / pp); both
            # stacks place remainders by the same stage order
            # (balanced_division), so one stage holds the position maximum of
            # BOTH stacks — the DP's worst case is a real stage
            lpe, lpd = -(-multi_type[0] // pp), -(-multi_type[1] // pp)
            n_pos = lpe + lpd
            pos_lt = lambda j: (
                self._layer_type(0) if j < lpe else self._layer_type(multi_type[0])
            )
        elif swin_groups is not None:
            # pair-stacked sections (pipeline_swin.SwinLayout): positions per
            # section = max of the pair spread; the same _spread_pairs the
            # runtime uses, so emitted strategies land on the right layers
            from galvatron_tpu.parallel.pipeline_swin import _spread_pairs

            pos_layers = 2
            sec_div = [_spread_pairs(cnt // 2, pp) for cnt, _ in swin_groups]
            sec_lp = [max(dv) for dv in sec_div]
            n_pos = sum(sec_lp)
            pos_sec = [k for k, lp in enumerate(sec_lp) for _ in range(lp)]
            pos_lt = lambda j: swin_groups[pos_sec[j]][1]
        else:
            n_pos = self.L if pp == 1 else lps // vpp
            pos_lt = self._layer_type
        mem = np.zeros((n_pos, S), np.int32)
        intra = np.zeros((n_pos, S), np.float64)
        for j in range(n_pos):
            lt = pos_lt(j)
            # coupled 1F1B input-stash rings (pipeline_encdec.py: enc
            # min(chunks, 4pp-1), dec/ctx 2pp-1; pipeline_swin.py: section
            # k min(chunks, 2(K-k)pp - 1)) are PER SECTION, not per
            # position: the ring charges only the group's FIRST position
            # (whose strategy approximates the section input's sharding);
            # later positions keep one live micro-batch
            # (stash_boundary_bound=0 bypasses the single-stack in-flight
            # bound without adding ring slots)
            stash_bound, ring, single_ring = None, 0, False
            if multi_type is not None and pipeline_type == "pipedream_flush":
                stash_bound = 0
                if j in (0, lpe):
                    ring = (4 * pp - 1) if j < lpe else (2 * pp - 1)
            elif swin_groups is not None and pipeline_type == "pipedream_flush":
                stash_bound = 0
                if j == 0 or pos_sec[j] != pos_sec[j - 1]:
                    ring = 2 * (len(swin_groups) - pos_sec[j]) * pp - 1
            elif pp > 1 and pipeline_type == "pipedream_flush":
                # single-stack/interleaved 1F1B: input stash ring + fp32
                # dx_embed ring, charged once at the first position at the
                # strategy's own sharding (_1f1b_rings_mb)
                single_ring = j == 0
            # EVERY pipedream_flush engine (single-stack pipeline_1f1b,
            # interleaved, coupled enc-dec, Swin sections) recomputes its
            # (virtual) stage forward from the stashed input in the backward
            # tick, regardless of the layer's own ckpt setting —
            # layer_time_cost prices compute at max(strategy factor,
            # full-replay factor) and the TP replay, without inflating the
            # once-per-iteration DP reduction
            recompute = (
                REMAT_FULL_FACTOR
                if pp > 1 and pipeline_type == "pipedream_flush"
                else None
            )
            for k, s in enumerate(cands):
                mc = layer_memory_cost(
                    lt, s, world, pp, global_bsz, chunks, stage_idx=0,
                    pipeline_type=pipeline_type, mixed_precision=self.mp,
                    vpp=vpp, stash_boundary_bound=stash_bound,
                )
                # a device holds vpp layers per searched position
                # (interleaved) or 2 (swin pairs); the ring term is
                # per-section and does NOT scale with the position's layer
                # multiplicity
                total_mb = pos_layers * vpp * mc.total_mb + self._ring_mb(
                    lt, s, ring, world, pp, global_bsz, chunks, vpp=vpp
                )
                if single_ring:
                    total_mb += self._1f1b_rings_mb(
                        lt, s, world, pp, global_bsz, chunks, vpp=vpp,
                        layers_per_device=lps,
                    )
                mem[j, k] = max(1, int(np.ceil(total_mb / self.unit)))
                intra[j, k] = pos_layers * layer_time_cost(
                    lt, s, self.hw, world, pp, global_bsz, mixed_precision=self.mp,
                    recompute_factor=recompute,
                )
        lt0 = self._layer_type(0)
        inter = np.zeros((S, S), np.float64)
        for a in range(S):
            for b in range(S):
                inter[a, b] = transition_cost_ms(
                    cands[a], cands[b], lt0, self.hw, world, pp, global_bsz, self.mp
                )

        # XLA SPMD-partitioner CHECK-crash exclusion (BASELINE.md round 5):
        # pp>1 × pipedream_flush × tp>1 × sp=False × vocab_tp>1 reliably
        # CHECK-crashes the partitioner (spmd_partitioner_util.cc:506) on
        # real TPU — a compiler bug, attention-impl independent (sp=True,
        # gpipe, or vocab_tp=1 all compile; tests/test_topology_aot.py pins
        # the sp=True neighbour). Structural guard: vocab_tp>1 pairs only
        # ever run the DP over the sp-safe candidate subset (tp=1 or
        # sp=True), so NO flag combination — including --disable_sp 1 —
        # can emit the uncompilable cell.
        crash_guard = pp > 1 and pipeline_type == "pipedream_flush"
        safe_idx = (
            np.asarray(
                [k for k, s in enumerate(cands) if s.tp == 1 or s.sp],
                np.int64,
            )
            if crash_guard
            else np.arange(S)
        )
        # vocab/embedding strategy is a searched dimension (reference:
        # --vocab_tp / --embed_sdp, hybrid_parallel_config.py:141-179,
        # arguments.py:128-130): sweep (vocab_tp, embed_dp_type), re-running
        # the layer DP only when the remaining budget actually changes
        dp_cache: Dict[tuple, tuple] = {}
        best = None  # (total_ms, res, mem_used, vt, et, other_mb)
        pairs = list(_vocab_strategy_pairs(world, pp, self.space.vocab_size))
        use_measured = self._vocab_use_measured()
        pf_overhead = 0.0
        if multi_type is not None and pipeline_type == "pipedream_flush":
            # per-DEVICE constants the coupled 1F1B carries beyond the
            # per-position stash rings (pipeline_encdec.py carry): the
            # dxe/dxd fp32 input-cotangent buffers hold (chunks+1)
            # micro-batches ≈ the full per-device batch boundary (fp32), and
            # the ctx stash holds (min(chunks, 2pp-1)+1) enc-boundary
            # micro-batch slots. Sized at the candidate worst case
            # (largest per-device batch = smallest dp = largest tp).
            enc_b = self._layer_type(0).boundary_activation_mb_per_sample
            dec_b = self._layer_type(multi_type[0]).boundary_activation_mb_per_sample
            fp32x = 2.0 if self.mp in ("bf16", "fp16") else 1.0
            rows = global_bsz / max(1, world // (pp * max(s.tp for s in cands)))
            pf_overhead = (enc_b + dec_b) * rows * ((chunks + 1) / chunks) * fp32x
            pf_overhead += enc_b * (rows / chunks) * (min(chunks, 2 * pp - 1) + 1)
        elif swin_groups is not None and pipeline_type == "pipedream_flush":
            # the coupled K-section 1F1B's per-device constant beyond the
            # per-position stash rings: the dxe fp32 input-cotangent buffer
            # holds chunks+1 section-0 micro-batch boundaries
            sec0_b = self._layer_type(0).boundary_activation_mb_per_sample
            fp32x = 2.0 if self.mp in ("bf16", "fp16") else 1.0
            rows = global_bsz / max(1, world // (pp * max(s.tp for s in cands)))
            pf_overhead = sec0_b * rows * ((chunks + 1) / chunks) * fp32x
        # (single-stack/interleaved 1F1B rings are charged per strategy in
        # the mem table — _1f1b_rings_mb at the first position)
        # one-off transient working set (bf16 cast + in-flight grad of the
        # largest layer at the candidate worst-case tp)
        trans_mb = transient_overhead_mb(
            self.costs, min(s.tp for s in cands), self.mp
        )
        for vt, et in pairs:
            guarded = crash_guard and vt > 1 and len(safe_idx) < S
            if guarded:
                self._restrictions.add("spmd_crash_pp_1f1b_tp_no_sp_vocab_tp")
                if len(safe_idx) == 0:
                    continue  # e.g. --disable_sp with only tp>1 candidates
            other_mb = other_memory_cost(
                self.costs, world, pp, vocab_tp=vt, embed_dp_type=et,
                global_bsz=global_bsz, chunks=chunks, mixed_precision=self.mp,
            ) + pf_overhead + trans_mb
            budget = self.budget_mb - other_mb
            if budget <= 0:
                continue
            V = int(budget / self.unit)
            key = (V, guarded)
            if key not in dp_cache:
                if guarded:
                    c_, r_, m_ = run_dp(
                        mem[:, safe_idx], intra[:, safe_idx],
                        inter[np.ix_(safe_idx, safe_idx)], V,
                    )
                    # map subset choices back to full candidate indices
                    r_ = np.where(r_ >= 0, safe_idx[np.clip(r_, 0, None)], -1)
                    dp_cache[key] = (c_, r_, m_)
                else:
                    dp_cache[key] = run_dp(mem, intra, inter, V)
            cost, res, mem_used = dp_cache[key]
            if not np.isfinite(cost) or (res < 0).any():
                continue
            if pp > 1:
                # per-tick stage time: layer compute plus the inter-
                # position resharding every micro-batch pays on its stage
                # pass (the transition tables price the full global batch,
                # so /chunks yields the per-micro-batch share; riding the
                # tick time lets pipeline_time_cost amplify it by the
                # fill/steady factor instead of counting it flat)
                per_stage_ms = self._stage_tick_ms(intra, inter, res, chunks, vpp)
                if multi_type is not None or swin_groups is not None:
                    total_ms = self._coupled_total_ms(
                        per_stage_ms, pp, chunks, pipeline_type, global_bsz,
                        multi_type, swin_groups,
                    )
                else:
                    total_ms = pipeline_time_cost(
                        [per_stage_ms] * pp,
                        self._boundary_msg_mb(lt0, global_bsz, chunks),
                        pp, chunks, self.hw, vpp=vpp,
                        pipeline_type=pipeline_type,
                    )
            else:
                total_ms = cost
            total_ms += other_time_cost(
                self.costs, self.hw, world, pp, vt, et, global_bsz, self.mp,
                use_measured=use_measured,
            )
            if best is None or total_ms < best[0]:
                best = (total_ms, res, mem_used, vt, et, other_mb)
        if best is None:
            return None
        total_ms, res, mem_used, vocab_tp, embed_dp_type, other_mb = best

        chosen = [cands[k] for k in res]
        if pp > 1:
            # same per-position pattern in every (virtual) stage; uneven
            # divisions truncate the pattern on light stages
            if multi_type is not None:
                from galvatron_tpu.core.strategy import balanced_division

                div_e = balanced_division(multi_type[0], pp)
                div_d = balanced_division(multi_type[1], pp)
                lpe = max(div_e)
                enc_chosen, dec_chosen = chosen[:lpe], chosen[lpe:]
                layer_strategies = [
                    enc_chosen[q] for s in range(pp) for q in range(div_e[s])
                ] + [dec_chosen[q] for s in range(pp) for q in range(div_d[s])]
                division = div_e + div_d  # the 2*pp enc-dec layout
            elif swin_groups is not None:
                # per-layer strategies in the runtime's pair layout: section-
                # major, stage-major within a section, two layers per pair
                layer_strategies = []
                base = 0
                for k in range(len(swin_groups)):
                    sec_chosen = chosen[base:base + sec_lp[k]]
                    for s in range(pp):
                        for q in range(sec_div[k][s]):
                            layer_strategies += [sec_chosen[q], sec_chosen[q]]
                    base += sec_lp[k]
            elif division is not None:
                layer_strategies = [
                    chosen[j] for s in range(pp) for j in range(division[s])
                ]
            else:
                layer_strategies = chosen * (pp * vpp)
        else:
            layer_strategies = chosen

        hp = HybridParallelConfig(
            pp=pp,
            vpp=vpp,
            layer_strategies=layer_strategies,
            pp_division=division,
            chunks=chunks,
            pipeline_type=pipeline_type,
            vocab_tp=vocab_tp,
            embed_dp_type=embed_dp_type,
            mixed_precision=self.mp,
            default_dp_type="ddp",
        )
        return SearchResult(
            config=hp,
            cost_ms=float(total_ms),
            throughput_samples_per_s=global_bsz / (total_ms / 1000.0),
            global_bsz=global_bsz,
            memory_mb=float(mem_used * self.unit + other_mb),
            details={
                "pp": pp, "vpp": vpp, "chunks": chunks,
                "pipeline_type": pipeline_type,
                "vocab_tp": vocab_tp, "embed_dp_type": embed_dp_type,
                # includes coupled_1f1b_overhead_mb when that schedule is priced
                "other_memory_mb": float(other_mb),
                **(
                    {"coupled_1f1b_overhead_mb": float(pf_overhead)}
                    if pf_overhead else {}
                ),
                # non-empty => comm terms priced from built-in defaults, not
                # measured bandwidths (e.g. search ran on a single-chip host)
                "fallback_bandwidths": self.hw.fallback_sources(pp),
            },
        )

    # -- full optimization loop ---------------------------------------------

    def _iter_results(self, global_bsz_list, max_chunks, verbose=False):
        """Yield every feasible SearchResult in the (bsz, pp, chunks,
        schedule, vpp) sweep."""
        self._restrictions.clear()
        pps = self.space.pp_choices or [
            p for p in _pow2s(self.space.world_size) if p <= self.L
        ]
        for bsz in global_bsz_list:
            for pp in pps:
                chunk_opts = [c for c in _pow2s(min(max_chunks, bsz)) if bsz % c == 0]
                for chunks in chunk_opts:
                    for ptype in self.space.pipeline_types if pp > 1 else ("gpipe",):
                        vpps = [1]
                        if pp > 1:
                            # the L % (pp*vpp) constraint is interleaving's
                            # (strategy.validate) — vpp=1 must stay in the
                            # sweep for ANY L: evaluate() handles uneven
                            # divisions via pp_division_memory_balanced
                            vpps = [1] + [
                                v for v in _pow2s(self.space.max_vpp)
                                if v > 1 and self.L % (pp * v) == 0
                            ]
                        for vpp in vpps:
                            r = self.evaluate(pp, bsz, chunks, ptype, vpp=vpp)
                            if r is None:
                                continue
                            if verbose:
                                vtag = f" vpp={vpp}" if vpp > 1 else ""
                                print(
                                    f"bsz={bsz} pp={pp} chunks={chunks} {ptype}{vtag}: "
                                    f"{r.cost_ms:.1f} ms, "
                                    f"{r.throughput_samples_per_s:.2f} samples/s, "
                                    f"mem {r.memory_mb:.0f} MB"
                                )
                            yield r

    def _active_restrictions(self) -> List[str]:
        return sorted(self._restrictions)

    def search_topk(
        self, global_bsz_list: Sequence[int], k: int, max_chunks: int = 64,
        verbose: bool = False,
    ) -> List[SearchResult]:
        """The k highest-predicted-throughput results (distinct (pp, chunks,
        schedule, vpp, per-layer strategy) combinations) — the candidate set
        for measured validation (CLI --validate_top_k)."""
        seen = set()
        out: List[SearchResult] = []
        with _obs_tracer.span("search_sweep", phase="topk", k=k):
            for r in self._iter_results(global_bsz_list, max_chunks, verbose=verbose):
                key = (
                    r.global_bsz, r.config.pp, r.config.chunks, r.config.pipeline_type,
                    r.config.vpp, tuple(map(str, r.config.layer_strategies)),
                )
                if key in seen:
                    continue
                seen.add(key)
                out.append(r)
        out.sort(key=lambda r: -r.throughput_samples_per_s)
        rs = self._active_restrictions()
        if rs:
            for r in out:
                r.details["search_restrictions"] = rs
        return out[:k]

    def search(
        self,
        global_bsz_list: Sequence[int],
        max_chunks: int = 64,
        verbose: bool = False,
    ) -> Optional[SearchResult]:
        """Sweep (bsz, pp, chunks, schedule); maximize throughput (reference:
        parallelism_optimization, search_engine.py:168-324)."""
        best: Optional[SearchResult] = None
        with _obs_tracer.span("search_sweep", phase="best"):
            for r in self._iter_results(global_bsz_list, max_chunks, verbose=verbose):
                if best is None or (
                    r.throughput_samples_per_s > best.throughput_samples_per_s
                ):
                    best = r
        if best is not None:
            rs = self._active_restrictions()
            if rs:
                best.details["search_restrictions"] = rs
        if best is not None and verbose:
            s0 = best.config.layer_strategies[0]
            dp = self.space.world_size // (best.config.pp * s0.tp * s0.cp)
            print(
                f"Max throughput = {best.throughput_samples_per_s:.2f} samples/s "
                f"(bsz {best.global_bsz}, {form_strategy(s0, best.config.pp, dp)})"
            )
        return best

    def recommend_min_bsz(self, scale: int = 8) -> int:
        """Prune sweep batch sizes that are search-time waste (reference:
        recommend_min_bsz, search_engine.py:257-276): pure-strategy baselines
        (dp / ZeRO-3 / full-tp at pp=1) each have a maximum feasible global
        batch under the memory budget; throughput rises with bsz until
        memory binds, so the sweep starts 65% of the way from the smallest
        to the largest baseline maximum. Returns a lower bound for the
        caller's min_bsz (``scale`` when nothing is feasible — the sweep
        itself then reports infeasibility)."""
        world = self.space.world_size
        baselines = [LayerStrategy(), LayerStrategy(dp_type="zero3")]
        tp = min(world, self.space.max_tp or world)
        if tp > 1:
            baselines.append(LayerStrategy(tp=tp))

        groups = self._type_groups()  # type-aware: price every layer type

        def feasible(s: LayerStrategy, bsz: int) -> bool:
            mem = sum(
                cnt
                * layer_memory_cost(
                    lt, s, world, 1, bsz, 1, mixed_precision=self.mp
                ).total_mb
                for _, cnt, lt in groups
            )
            other = other_memory_cost(
                self.costs, world, 1, vocab_tp=1, embed_dp_type="ddp",
                global_bsz=bsz, chunks=1, mixed_precision=self.mp,
            )
            return mem + other <= self.budget_mb

        def max_feasible(s: LayerStrategy) -> int:
            # memory is monotone in bsz: geometric probe for an infeasible
            # upper bound, then bisect to `scale` granularity (~40 cost-model
            # evaluations instead of a linear scan)
            if not feasible(s, scale):
                return 0
            lo, hi = scale, 2 * scale
            while hi <= (1 << 20) and feasible(s, hi):
                lo, hi = hi, 2 * hi
            while hi - lo > scale:
                mid = (lo + hi) // 2 // scale * scale
                if mid in (lo, hi):
                    break
                lo, hi = (mid, hi) if feasible(s, mid) else (lo, mid)
            return lo

        vals = [max_feasible(s) for s in baselines]
        if not any(vals):
            return scale
        lo, hi = min(vals), max(vals)
        start = int((lo * 0.35 + hi * 0.65) // scale * scale)
        return max(start, scale)

    def homogeneity_gap(
        self, pp: int, global_bsz: int, chunks: int,
        pipeline_type: str = "pipedream_flush",
    ) -> Optional[Dict]:
        """Quantify the cross-stage homogeneity restriction (the reference
        places any strategy on any layer of any stage,
        hybrid_parallel_model.py:81-153; this runtime's padded SPMD stacking
        shares one strategy per stack position across stages).

        For homogeneous layers under a uniform budget, per-stage DPs are
        IDENTICAL subproblems, so the restriction costs nothing under gpipe.
        The gap comes from 1F1B's stage-varying activation bound
        (2(pp-1-s)+1 in-flight micro-batches): later stages have memory
        headroom the position-restricted DP — which prices stage 0's worst
        case everywhere — cannot exploit. This runs the layer DP once per
        stage with stage-specific memory (the reference's formulation) and
        reports the predicted iteration-time delta.

        Multi-type models are covered too: enc-dec stages run their own DPs
        over their REAL per-stage layer counts (ragged/sub-pp divisions give
        light stages headroom the shared-position search cannot use), with
        the coupled-1F1B stash memory and recompute pricing; Swin sections
        use their per-stage pair spreads.

        Returns {restricted_ms, unrestricted_ms, delta_pct, per_stage}.
        None = not defined for this shape/schedule (pp=1, vpp>1, odd swin
        sections, >2 non-section groups) or the restricted search itself
        finds nothing feasible."""
        r = self.evaluate(pp, global_bsz, chunks, pipeline_type)
        if r is None or pp == 1:
            return None
        world = self.space.world_size
        cands = self._feasible_strategies(pp, global_bsz, chunks)
        S = len(cands)
        lt0 = self._layer_type(0)
        vt = r.config.vocab_tp
        et = r.config.embed_dp_type
        other_mb = other_memory_cost(
            self.costs, world, pp, vocab_tp=vt, embed_dp_type=et,
            global_bsz=global_bsz, chunks=chunks, mixed_precision=self.mp,
        ) + r.details.get("coupled_1f1b_overhead_mb", 0.0) + transient_overhead_mb(
            self.costs, min(s.tp for s in cands), self.mp
        )
        budget = self.budget_mb - other_mb
        if budget <= 0:
            return None
        V = int(budget / self.unit)
        inter = np.zeros((S, S), np.float64)
        for a in range(S):
            for b in range(S):
                inter[a, b] = transition_cost_ms(
                    cands[a], cands[b], lt0, self.hw, world, pp, global_bsz, self.mp
                )

        # per-stage position descriptors: (layer_type, stash_bound, layers)
        groups = self._type_groups()
        recompute = None
        # position entries are (layer_type, stash_flag, n_layers, rings);
        # rings = ((ring_layer_type, slots), ...) charged at that position.
        # Under the coupled 1F1B the SPMD scan carry allocates EVERY
        # section's ring on EVERY device — including stages holding zero
        # layers of that section — so each stage charges every group's
        # ring: at the group's first position on that stage when it has
        # one, else at the stage's first position (a fully idle stage runs
        # only padding and is not priced — it chooses no strategy).
        def attach_rings(poss, gids, ring_list):
            out = [[lt_, stash_, n_, []] for (lt_, stash_, n_) in poss]
            if out and ring_list:
                first = {}
                for j, g in enumerate(gids):
                    first.setdefault(g, j)
                for g, ring in enumerate(ring_list):
                    out[first.get(g, 0)][3].append(ring)
            return [(a, b, c, tuple(r)) for a, b, c, r in out]

        single_pf = False
        if len(groups) == 1:
            mode = "single"
            if pipeline_type == "pipedream_flush":
                recompute = REMAT_FULL_FACTOR  # same per-tick stage replay
                single_pf = True
            lps = -(-self.L // pp)
            stage_positions = [[(lt0, None, 1, ())] * lps for _ in range(pp)]
        elif len(groups) == 2 and not self.section_pipeline:
            if pipeline_type not in ("gpipe", "pipedream_flush"):
                return None
            from galvatron_tpu.core.strategy import balanced_division

            mode = "encdec"
            E, D = groups[0][1], groups[1][1]
            div_e, div_d = balanced_division(E, pp), balanced_division(D, pp)
            lte, ltd = self._layer_type(0), self._layer_type(E)
            pf = pipeline_type == "pipedream_flush"
            if pf:
                recompute = REMAT_FULL_FACTOR
            stash = 0 if pf else None
            ring_list = [(lte, 4 * pp - 1), (ltd, 2 * pp - 1)] if pf else []
            stage_positions = [
                attach_rings(
                    [(lte, stash, 1)] * div_e[st] + [(ltd, stash, 1)] * div_d[st],
                    [0] * div_e[st] + [1] * div_d[st],
                    ring_list,
                )
                for st in range(pp)
            ]
        elif all(cnt % 2 == 0 for _, cnt, _ in groups):
            if pipeline_type not in ("gpipe", "pipedream_flush"):
                return None
            from galvatron_tpu.parallel.pipeline_swin import _spread_pairs

            mode = "swin"
            Kg = len(groups)
            pf = pipeline_type == "pipedream_flush"
            if pf:
                recompute = REMAT_FULL_FACTOR
            sec_div = [_spread_pairs(cnt // 2, pp) for _, cnt, _ in groups]
            stash = 0 if pf else None
            ring_list = (
                [(groups[k][2], 2 * (Kg - k) * pp - 1) for k in range(Kg)]
                if pf else []
            )
            stage_positions = [
                attach_rings(
                    [
                        (groups[k][2], stash, 2)
                        for k in range(Kg)
                        for _ in range(sec_div[k][st])
                    ],
                    [k for k in range(Kg) for _ in range(sec_div[k][st])],
                    ring_list,
                )
                for st in range(pp)
            ]
        else:
            return None

        intra_rows: Dict[int, np.ndarray] = {}

        def intra_row(lt) -> np.ndarray:
            key = id(lt)
            if key not in intra_rows:
                intra_rows[key] = np.array([
                    layer_time_cost(
                        lt, s, self.hw, world, pp, global_bsz,
                        mixed_precision=self.mp, recompute_factor=recompute,
                    )
                    for s in cands
                ])
            return intra_rows[key]

        mem_rows: Dict[tuple, np.ndarray] = {}

        def mem_row(lt, stash, n_lay, st, rings, first=False) -> np.ndarray:
            key = (id(lt), stash, n_lay, st, tuple((id(r), n) for r, n in rings), first)
            if key not in mem_rows:
                def total(s):
                    mc = layer_memory_cost(
                        lt, s, world, pp, global_bsz, chunks, stage_idx=st,
                        pipeline_type=pipeline_type, mixed_precision=self.mp,
                        stash_boundary_bound=stash,
                    ).total_mb
                    # rings are per-section, charged once (evaluate() rule)
                    out = n_lay * mc + sum(
                        self._ring_mb(
                            rlt, s, slots, world, pp, global_bsz, chunks,
                            stage_idx=st,
                        )
                        for rlt, slots in rings
                    )
                    if first:  # single-stack 1F1B stash + dx_embed rings
                        out += self._1f1b_rings_mb(
                            lt, s, world, pp, global_bsz, chunks
                        )
                    return out

                mem_rows[key] = np.array([
                    max(1, int(np.ceil(total(s) / self.unit))) for s in cands
                ], np.int32)
            return mem_rows[key]

        stage_ms, per_stage = [], []
        for st in range(pp):
            poss = stage_positions[st]
            if not poss:  # a stage holding only masked padding
                stage_ms.append(0.0)
                per_stage.append([])
                continue
            n_pos = len(poss)
            mem = np.zeros((n_pos, S), np.int32)
            intra = np.zeros((n_pos, S), np.float64)
            for j, (lt, stash, n_lay, rings) in enumerate(poss):
                intra[j] = intra_row(lt) * n_lay
                mem[j] = mem_row(lt, stash, n_lay, st, rings, first=single_pf and j == 0)
            cost, res, _ = run_dp(mem, intra, inter, V)
            if not np.isfinite(cost) or (res < 0).any():
                return None
            stage_ms.append(self._stage_tick_ms(intra, inter, res, chunks))
            per_stage.append([form_strategy(cands[k], pp, world // (pp * cands[k].tp * cands[k].cp)) for k in res])
        if mode == "single":
            unrestricted = pipeline_time_cost(
                stage_ms, self._boundary_msg_mb(lt0, global_bsz, chunks),
                pp, chunks, self.hw, pipeline_type=pipeline_type,
            )
        else:
            unrestricted = self._coupled_total_ms(
                max(stage_ms), pp, chunks, pipeline_type, global_bsz,
                (groups[0][1], groups[1][1]) if mode == "encdec" else None,
                [(cnt, lt) for _, cnt, lt in groups] if mode == "swin" else None,
            )
        unrestricted += other_time_cost(
            self.costs, self.hw, world, pp, vt, et, global_bsz, self.mp,
            use_measured=self._vocab_use_measured(),
        )
        return {
            "restricted_ms": float(r.cost_ms),
            "unrestricted_ms": float(unrestricted),
            "delta_pct": float(100.0 * (r.cost_ms - unrestricted) / r.cost_ms),
            "per_stage": per_stage,
        }

    def check_cost_model(
        self, global_bsz: int, chunks: int = 1, pp: int = 1,
        pipeline_type: str = "gpipe", strategies: Optional[Sequence[LayerStrategy]] = None,
    ) -> str:
        """Developer harness: per-strategy predicted memory/time table for
        manual comparison against profiled reality (reference:
        GalvatronSearchEngine.check_cost_model, search_engine.py:369-421).
        Returns the formatted table (also useful in tests)."""
        world = self.space.world_size
        cands = list(strategies) if strategies else generate_layer_strategies(self.space, pp)
        lines = [
            f"check_cost_model: bsz={global_bsz} chunks={chunks} pp={pp} "
            f"{pipeline_type} world={world}",
        ]
        # one per-strategy table per layer type (enc-dec models carry two)
        groups = self._type_groups()
        for gi, (start, cnt, lt) in enumerate(groups):
            if len(groups) > 1:
                lines.append(f"layer type {gi} (layers {start}..{start + cnt - 1}):")
            lines.append(
                f"{'strategy':>16} | {'states MB':>9} | {'act MB':>8} | "
                f"{'total MB':>8} | {'time ms':>8}"
            )
            # same stash-ring pricing evaluate() applies to the coupled
            # 1F1B schedules: enc-dec groups stash 4pp-1 / 2pp-1 slots,
            # K-section (swin) groups 2(K-gi)pp - 1
            stash_bound = None
            if pp > 1 and pipeline_type == "pipedream_flush" and len(groups) > 1:
                if len(groups) == 2 and not self.section_pipeline:
                    stash_bound = (4 * pp - 1) if gi == 0 else (2 * pp - 1)
                else:
                    stash_bound = 2 * (len(groups) - gi) * pp - 1
            for s in cands:
                dp = world // (pp * s.tp * s.cp)
                mc = layer_memory_cost(
                    lt, s, world, pp, global_bsz, chunks, stage_idx=0,
                    pipeline_type=pipeline_type, mixed_precision=self.mp,
                    stash_boundary_bound=stash_bound,
                )
                t = layer_time_cost(
                    lt, s, self.hw, world, pp, global_bsz, mixed_precision=self.mp
                )
                lines.append(
                    f"{form_strategy(s, pp, dp):>16} | {mc.states_mb:9.1f} | "
                    f"{mc.activation_mb:8.1f} | {mc.total_mb:8.1f} | {t:8.2f}"
                )
        # vocab/embedding strategy tradeoff (searched dimension); 'src' shows
        # whether the base term is measured (profile_vocab_costs table) or
        # analytic — with the same whole-sweep consistency gate evaluate()
        # applies (a mixed sweep would bias toward unmeasured degrees)
        pairs = list(_vocab_strategy_pairs(world, pp, self.space.vocab_size))
        use_measured = self._vocab_use_measured()
        lines.append(
            f"{'vocab strategy':>16} | {'other MB':>9} | {'other ms':>8} | {'src':>8}"
        )
        for vt, et in pairs:
                omb = other_memory_cost(
                    self.costs, world, pp, vocab_tp=vt, embed_dp_type=et,
                    global_bsz=global_bsz, chunks=chunks, mixed_precision=self.mp,
                )
                oms = other_time_cost(
                    self.costs, self.hw, world, pp, vt, et, global_bsz, self.mp,
                    use_measured=use_measured,
                )
                src = "measured" if use_measured else "analytic"
                tag = f"vtp{vt}-{et}"
                lines.append(f"{tag:>16} | {omb:9.1f} | {oms:8.2f} | {src:>8}")
        return "\n".join(lines)

    def save_result(self, result: SearchResult, path: str) -> None:
        d = result.config.to_json_dict()
        d["search_cost_ms"] = result.cost_ms
        d["search_throughput_samples_per_s"] = result.throughput_samples_per_s
        d["global_bsz"] = result.global_bsz
        d["memory_mb"] = result.memory_mb
        fb = result.details.get("fallback_bandwidths")
        if fb:
            d["fallback_bandwidths"] = fb  # priced from defaults, not measured
        rs = result.details.get("search_restrictions")
        if rs:
            # structural bail-outs that really excluded a schedule/shape
            # class from the sweep that produced this result
            d["search_restrictions"] = rs
        if "homogeneity_gap_pct" in result.details:
            d["homogeneity_gap_pct"] = result.details["homogeneity_gap_pct"]
        # self-describing provenance: check-plan (CLI/CI) reads these back
        # as defaults, so a checked-in config validates without extra flags
        d["num_devices"] = self.space.world_size
        # the budget this plan was searched under: check-plan's GTA015
        # feasibility gate reads it back, so a regenerated config keeps the
        # CI memory check without hand-editing
        d["memory_constraint_gb"] = self.budget_mb / 1024.0
        if self.model_name:
            d["model_size"] = self.model_name
        if self.model_config is not None:
            # effective shape, so check-plan needs no repeated CLI overrides
            # (a --num_layers 4 search against a 24-layer preset would
            # otherwise read back as a spurious layer-count mismatch)
            from galvatron_tpu.analysis.plan_check import model_shape_dict

            d["model_config"] = model_shape_dict(self.model_config)
        # emit-path self-check: the runtime materializes emitted plans
        # blindly, so an invalid one here is a SEARCH bug — refuse to write
        # it rather than hand the trainer a plan its own startup check (or
        # worse, the compiler) rejects minutes later
        from galvatron_tpu.analysis import plan_check

        plan_check.ensure_valid(
            d, model_config=self.model_config,
            world_size=self.space.world_size,
            memory_budget_mb=self.budget_mb,
            context=f"search emitted an invalid plan (search bug) for {path}",
            verbose=False,
        )
        with open(path, "w") as f:
            json.dump(d, f, indent=2)
