"""Analytic (no-profiling) parameter/memory estimates from a model config.

Counterpart of the vendored Megatron ``theoretical_memory_usage.py``
(reference: site_package/megatron/theoretical_memory_usage.py — unused by the
reference's own trainer, SURVEY §2.6), re-derived for this runtime:

- exact parameter counts from ModelConfig (GQA, SwiGLU/GeLU, tied embeddings);
- model-state memory per chip under a LayerStrategy (fp32 master + 2 Adam
  moments + optional bf16 working cast; ZeRO-2 shards moments, ZeRO-3 all);
- activation estimates per layer per sample for the three attention paths
  (flash never materializes the (S, S) score matrix; xla does).

Useful to seed the search before any profiling has run, and as the
cross-check for the profiler's measured numbers (``check_cost_model``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

from galvatron_tpu.core.strategy import LayerStrategy
from galvatron_tpu.models.modeling import ModelConfig

_BYTES = {"fp32": 4, "bf16": 2, "fp16": 2}


def moe_expert_params(cfg: ModelConfig) -> int:
    """Parameters in the expert stack (shardable by ep): E MLPs, w1/w2
    (+ w3 for swiglu) — matches moe.init_moe_params."""
    mats = 3 if cfg.act_fn == "swiglu" else 2
    return cfg.moe_experts * mats * cfg.hidden_size * cfg.ffn


def layer_param_count(cfg: ModelConfig, cross: bool = False) -> int:
    """Exact per-layer parameter count (matches init_layer_params).
    ``cross``: enc-dec decoder layers carry a cross-attention block
    (wq + wkv + wo + cross_norm)."""
    h, hd = cfg.hidden_size, cfg.head_dim
    q_out, kv_out = cfg.num_heads * hd, cfg.kv_heads * hd
    attn = h * q_out + 2 * h * kv_out + q_out * h
    if cross:
        attn += h * q_out + 2 * h * kv_out + q_out * h
        attn += h if cfg.norm_type == "rms" else 2 * h  # cross_norm
    if cfg.moe_experts > 0:
        # router + per-expert MLPs
        mlp = h * cfg.moe_experts + moe_expert_params(cfg)
    elif cfg.act_fn == "swiglu":
        mlp = 3 * h * cfg.ffn
    else:
        mlp = 2 * h * cfg.ffn
    norms = 2 * h if cfg.norm_type == "rms" else 4 * h
    bias = 0
    if cfg.use_bias:  # qkv slots + wo (+ dense-MLP biases; MoE MLPs carry none)
        bias = 3 * q_out + h
        if cfg.moe_experts == 0:
            bias += (2 * cfg.ffn if cfg.act_fn == "swiglu" else cfg.ffn) + h
    return attn + mlp + norms + bias


def other_param_count(cfg: ModelConfig) -> int:
    """Embedding + final norm + output head (+ Swin patch merges)."""
    if cfg.image_size:
        from galvatron_tpu.models.modeling import swin_geometry

        patch_dim = cfg.patch_size * cfg.patch_size * cfg.num_channels
        n = patch_dim * cfg.hidden_size + cfg.n_patches * cfg.hidden_size
        c_last = cfg.hidden_size << max(0, len(cfg.swin_depths) - 1)
        n += c_last * cfg.num_classes
        n += c_last if cfg.norm_type == "rms" else 2 * c_last
        for s in range(len(cfg.swin_depths) - 1):
            _, _, c, _ = swin_geometry(cfg, s)
            n += 4 * c * 2 * c + (4 * c if cfg.norm_type == "rms" else 8 * c)
        return n
    n = cfg.vocab_size * cfg.hidden_size  # token embedding
    if cfg.pos_embed == "learned":
        n += cfg.max_seq_len * cfg.hidden_size
    n += cfg.hidden_size if cfg.norm_type == "rms" else 2 * cfg.hidden_size
    if not cfg.tie_word_embeddings:
        n += cfg.hidden_size * cfg.vocab_size
    return n


def total_param_count(cfg: ModelConfig) -> int:
    if cfg.swin_depths:
        from galvatron_tpu.models.modeling import vision_layer_cfg

        layers = sum(
            layer_param_count(vision_layer_cfg(cfg, i)) for i in range(cfg.num_layers)
        )
        return layers + other_param_count(cfg)
    return cfg.num_layers * layer_param_count(cfg) + other_param_count(cfg)


def layer_states_mb(
    cfg: ModelConfig, s: LayerStrategy, world: int, pp: int = 1,
    mixed_precision: str = "bf16",
) -> float:
    """Per-chip model-state MB for one layer under strategy ``s`` — the
    analytic form of layer_memory_cost's states term."""
    dp = world // (pp * s.tp * s.cp)
    p_mb = layer_param_count(cfg) * 4 / 1e6 / s.tp  # fp32 MB after TP
    cast = 0.5 * p_mb if mixed_precision in ("bf16", "fp16") else 0.0
    if s.dp_type == "zero3":
        return 4.0 * p_mb / dp + cast
    if s.dp_type == "zero2":
        return 2.0 * p_mb + 2.0 * p_mb / dp + cast
    return 4.0 * p_mb + cast


def layer_activation_mb_per_sample(
    cfg: ModelConfig, s: LayerStrategy, seq_len: int = 0,
    mixed_precision: str = "bf16",
) -> float:
    """Analytic activation MB per layer per sample, no remat.

    Derivation (per token, compute dtype bytes b): residual h, two norm
    outputs 2h, qkv (1 + 2·kv/n)·h·(n·hd/h), attention context h, mlp inputs
    h + {3 ffn (swiglu: w1 out, w3 out, product) | 2 ffn (gelu)}. The xla
    attention path additionally saves the (n_heads, S, S) probs in fp32;
    flash saves only the (S, 1) LSE. TP divides the sharded intermediates;
    SP additionally shards the replicated residual/norm tensors.

    Under ``cfg.mlp_recompute`` ('gate'/'policy', the default) the MLP
    saves ONLY the gate/up projection output — the activation product is
    recomputed in the backward (modeling.mlp_residual) — so the mlp term
    drops by one ffn-wide save (swiglu 3→2, gelu/relu 2→1 ffn).
    """
    S = seq_len or cfg.max_seq_len
    h, n, kvn, hd = cfg.hidden_size, cfg.num_heads, cfg.kv_heads, cfg.head_dim
    b = _BYTES[mixed_precision]
    tp = s.tp
    # replicated (residual stream + norm inputs): sharded only under SP
    repl = 3 * h * b / (tp if s.sp else 1)
    # TP-sharded intermediates
    qkv = (n + 2 * kvn) * hd * b / tp
    ctx = n * hd * b / tp
    recompute = getattr(cfg, "mlp_recompute", "policy") in ("gate", "policy")
    if cfg.moe_experts > 0:
        mlp = 3 * cfg.ffn * b / tp  # per routed token (capacity ~1); the
        # recompute policy excludes MoE layers (modeling.mlp_residual)
    elif cfg.act_fn == "swiglu":
        mlp = (2 if recompute else 3) * cfg.ffn * b / tp
    else:
        mlp = (1 if recompute else 2) * cfg.ffn * b / tp
    per_token = repl + qkv + ctx + mlp
    total = per_token * S
    if cfg.attn_impl == "xla":
        total += 4.0 * (n / tp) * S * S  # fp32 probs
    else:
        total += 4.0 * (n / tp) * S  # flash LSE
    return total / 1e6 / max(1, s.cp)


def analytic_model_costs(
    cfg: ModelConfig, seq_len: int = 0, peak_tflops: float = 100.0, mfu: float = 0.4,
    mixed_precision: str = "bf16",
):
    """ProfiledModelCosts from pure analysis — lets the search run before any
    profiling exists (the reference cannot: it always requires profiled JSON,
    search_engine.py:92-121). fwd time from the 2·P·T FLOP estimate at an
    assumed MFU; activation table from layer_activation_mb_per_sample."""
    from galvatron_tpu.search.cost_model import ProfiledLayerType, ProfiledModelCosts

    if cfg.image_size:
        return _analytic_vision_costs(cfg, peak_tflops, mfu, mixed_precision)
    if cfg.enc_layers > 0:
        return _analytic_encdec_costs(cfg, peak_tflops, mfu, mixed_precision)
    S = seq_len or cfg.max_seq_len
    b = _BYTES[mixed_precision]
    p_layer = layer_param_count(cfg)
    flops = 2.0 * p_layer * S  # fwd multiply-accumulate per sample
    if cfg.attn_impl == "xla" or cfg.attn_impl == "flash":
        flops += 2.0 * 2.0 * cfg.num_heads * cfg.head_dim * S * S  # qk^T + pv
    fwd_ms = flops / (peak_tflops * 1e12 * mfu) * 1e3
    act = {
        tp: layer_activation_mb_per_sample(
            cfg, LayerStrategy(tp=tp), S, mixed_precision
        )
        for tp in (1, 2, 4, 8)
        if cfg.hidden_size % tp == 0
    }
    other_p = other_param_count(cfg)
    # logits dominate "other" activation
    other_act = S * cfg.vocab_size * b / 1e6
    other_flops = 2.0 * cfg.hidden_size * cfg.vocab_size * S
    # MoE: expert-stack fraction of the layer (shardable by ep) and the token
    # dispatch+combine all-to-all volume — one (S, h) activation each way
    frac = 0.0
    a2a = 0.0
    if cfg.moe_experts > 0:
        frac = moe_expert_params(cfg) / p_layer
        a2a = 2.0 * S * cfg.hidden_size * b / 1e6
    return ProfiledModelCosts(
        layer_types={
            0: ProfiledLayerType(
                fwd_ms_per_sample=fwd_ms,
                parameter_mb=p_layer * 4 / 1e6,
                activation_mb_per_sample=act,
                boundary_activation_mb_per_sample=S * cfg.hidden_size * b / 1e6,
                moe_expert_param_fraction=frac,
                moe_a2a_mb_per_sample=a2a,
            )
        },
        other_param_mb=other_p * 4 / 1e6,
        other_act_mb_per_sample=other_act,
        other_fwd_ms_per_sample=other_flops / (peak_tflops * 1e12 * mfu) * 1e3,
    )


def _analytic_encdec_costs(
    cfg: ModelConfig, peak_tflops: float, mfu: float, mixed_precision: str
):
    """Enc-dec variant: TWO layer types (encoder at enc_seq; decoder with
    cross-attention at max_seq_len) so the multi-layer-type search — incl.
    the pp>1 enc-dec pipeline path — gets per-type costs."""
    from galvatron_tpu.search.cost_model import ProfiledLayerType, ProfiledModelCosts

    b = _BYTES[mixed_precision]
    S_e, S_d = cfg.enc_seq, cfg.max_seq_len
    rate = peak_tflops * 1e12 * mfu

    def make_lt(S, cross):
        p = layer_param_count(cfg, cross=cross)
        flops = 2.0 * p * S
        flops += 4.0 * cfg.num_heads * cfg.head_dim * S * S  # self attn
        if cross:
            flops += 4.0 * cfg.num_heads * cfg.head_dim * S * S_e  # cross attn
            # the cross K/V projection runs over the ENCODER tokens (S_e),
            # not the decoder length the 2pS term assumed
            cross_kv = 2 * cfg.hidden_size * cfg.kv_heads * cfg.head_dim
            flops += 2.0 * cross_kv * (S_e - S)
        act = {
            tp: layer_activation_mb_per_sample(
                cfg, LayerStrategy(tp=tp), S, mixed_precision
            )
            # cross-attention roughly replays the attention activations
            * (1.5 if cross else 1.0)
            for tp in (1, 2, 4, 8)
            if cfg.hidden_size % tp == 0
        }
        frac = moe_expert_params(cfg) / p if cfg.moe_experts > 0 else 0.0
        a2a = 2.0 * S * cfg.hidden_size * b / 1e6 if cfg.moe_experts > 0 else 0.0
        return ProfiledLayerType(
            fwd_ms_per_sample=flops / rate * 1e3,
            parameter_mb=p * 4 / 1e6,
            activation_mb_per_sample=act,
            boundary_activation_mb_per_sample=S * cfg.hidden_size * b / 1e6,
            moe_expert_param_fraction=frac,
            moe_a2a_mb_per_sample=a2a,
        )

    enc_lt = make_lt(S_e, cross=False)
    dec_lt = make_lt(S_d, cross=True)
    layer_types = {i: enc_lt for i in range(cfg.enc_layers)}
    layer_types.update(
        {cfg.enc_layers + i: dec_lt for i in range(cfg.num_layers)}
    )
    other_p = other_param_count(cfg)
    other_flops = 2.0 * cfg.hidden_size * cfg.vocab_size * S_d
    return ProfiledModelCosts(
        layer_types=layer_types,
        other_param_mb=other_p * 4 / 1e6,
        other_act_mb_per_sample=S_d * cfg.vocab_size * b / 1e6,
        other_fwd_ms_per_sample=other_flops / rate * 1e3,
    )


def _analytic_vision_costs(
    cfg: ModelConfig, peak_tflops: float, mfu: float, mixed_precision: str
):
    """Vision variant of analytic_model_costs: ViT = one uniform layer type at
    seq = n_patches; Swin = one layer type per layer (the stage pyramid makes
    widths/resolutions layer-dependent — the multi-layer-type DP case,
    reference: _build_dp_and_run_multi_layer_type,
    galvatron/core/dynamic_programming.py:304-455)."""
    from galvatron_tpu.models.modeling import swin_geometry, swin_stage_of, vision_layer_cfg
    from galvatron_tpu.search.cost_model import ProfiledLayerType, ProfiledModelCosts

    b = _BYTES[mixed_precision]

    def layer_type_for(i: int) -> ProfiledLayerType:
        lcfg = vision_layer_cfg(cfg, i)
        if cfg.swin_depths:
            from galvatron_tpu.models.modeling import swin_window_for

            stage, _ = swin_stage_of(cfg, i)
            h_side, w_side, _, heads = swin_geometry(cfg, stage)
            S = h_side * w_side
            win = swin_window_for(cfg, stage)
            ctx = win * win  # each token attends its window
        else:
            S = cfg.n_patches
            heads, ctx = cfg.num_heads, cfg.n_patches
        p_layer = layer_param_count(lcfg)
        flops = 2.0 * p_layer * S + 2.0 * 2.0 * heads * lcfg.head_dim * S * ctx
        fwd_ms = flops / (peak_tflops * 1e12 * mfu) * 1e3
        act = {}
        for tp in (1, 2, 4, 8):
            if lcfg.hidden_size % tp:
                continue
            base = layer_activation_mb_per_sample(
                lcfg.replace(attn_impl="flash"), LayerStrategy(tp=tp), S, mixed_precision
            )
            # replace the flash-LSE term with the windowed fp32 probs
            act[tp] = base + 4.0 * (heads / tp) * S * (ctx - 1) / 1e6
        return ProfiledLayerType(
            fwd_ms_per_sample=fwd_ms,
            parameter_mb=p_layer * 4 / 1e6,
            activation_mb_per_sample=act,
            boundary_activation_mb_per_sample=S * lcfg.hidden_size * b / 1e6,
        )

    if cfg.swin_depths:
        layer_types = {i: layer_type_for(i) for i in range(cfg.num_layers)}
    else:
        layer_types = {0: layer_type_for(0)}
    other_p = other_param_count(cfg)
    patch_dim = cfg.patch_size * cfg.patch_size * cfg.num_channels
    other_flops = 2.0 * patch_dim * cfg.hidden_size * cfg.n_patches
    c_last = cfg.hidden_size << max(0, len(cfg.swin_depths) - 1)
    other_flops += 2.0 * c_last * cfg.num_classes
    # patch embedding output dominates "other" activation
    other_act = cfg.n_patches * cfg.hidden_size * b / 1e6
    return ProfiledModelCosts(
        layer_types=layer_types,
        other_param_mb=other_p * 4 / 1e6,
        other_act_mb_per_sample=other_act,
        other_fwd_ms_per_sample=other_flops / (peak_tflops * 1e12 * mfu) * 1e3,
    )


@dataclass
class TheoreticalReport:
    total_params: int
    per_layer_params: int
    other_params: int
    layer_states_mb: float
    layer_act_mb_per_sample: float
    model_states_total_mb: float

    def lines(self) -> str:
        return (
            f"params: total {self.total_params/1e9:.3f}B "
            f"(layer {self.per_layer_params/1e6:.1f}M x N + other {self.other_params/1e6:.1f}M)\n"
            f"per-chip layer states: {self.layer_states_mb:.1f} MB | "
            f"layer activation/sample: {self.layer_act_mb_per_sample:.2f} MB | "
            f"all-layer states: {self.model_states_total_mb:.0f} MB"
        )


def report(
    cfg: ModelConfig, s: LayerStrategy, world: int, pp: int = 1,
    seq_len: int = 0, mixed_precision: str = "bf16",
) -> TheoreticalReport:
    lsm = layer_states_mb(cfg, s, world, pp, mixed_precision)
    return TheoreticalReport(
        total_params=total_param_count(cfg),
        per_layer_params=layer_param_count(cfg),
        other_params=other_param_count(cfg),
        layer_states_mb=lsm,
        layer_act_mb_per_sample=layer_activation_mb_per_sample(
            cfg, s, seq_len, mixed_precision
        ),
        model_states_total_mb=lsm * (cfg.num_layers // pp),
    )
