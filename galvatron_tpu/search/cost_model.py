"""Memory and time cost models driving the strategy search.

Counterparts of the reference's MemoryCostModel / TimeCostModel /
pipeline_costmodel (reference: galvatron/core/cost_model.py:4-122,125-349,
372-427), re-derived for this runtime's actual semantics:

- model states are exact analytic fractions (fp32 master + fp32 Adam moments;
  ZeRO-2 shards moments, ZeRO-3 shards everything) instead of the reference's
  empirically-fit CUDA-allocator ratio curves (cost_model.py:56-60);
- activation terms follow the JAX runtime: GPipe stashes stage inputs per
  micro-batch, 1F1B holds at most 2(pp-1-s)+1 in-flight micro-batches,
  remat keeps only layer-boundary activations;
- communication terms use the profiled ICI bandwidth per (group size, axis
  layout) — consec = minor (adjacent) mesh axes — with allreduce volume
  2(n-1)/n·msg, all-gather/reduce-scatter (n-1)/n·msg, and the measured
  compute/comm overlap slowdown coefficient (reference overlap model:
  cost_model.py:230-246).

All sizes in MB, times in ms, bandwidths in GB/s.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, Optional

from galvatron_tpu.core.strategy import LayerStrategy


# ---------------------------------------------------------------------------
# Profiled inputs
# ---------------------------------------------------------------------------


# --- FITTED sharded-activation coefficients --------------------------------
# Provenance: topology-measured activation classes against the v5e:2x4
# compiler (experiments/act_memory_sweep.py; BASELINE.md round-5 probe and
# the round-6 mlp_recompute sweep). ACT_TP_UNSHARDED: replicated share of
# saved activations that does not shrink with tp (round-5 measured tp1->tp2
# at 0.71x => u = 2*0.71 - 1 = 0.42; the mlp_recompute policy removes the
# fp32-widened norm saves from that share, keeping the fit there).
# ACT_SP_SHARDED: fraction of the table-derived REPLICATED share sp shards
# over the tp group — the round-6 sweep measured the sp saving at ~1.0-1.2x
# the derived replicated share on both attention channels (the seed's flat
# 0.5+0.5/tp discount overstated sp on probs-heavy tables ~2-3x).
ACT_TP_UNSHARDED = 0.42
ACT_SP_SHARDED = 1.0


@dataclass
class ProfiledLayerType:
    """Per-layer profiled data (one transformer layer type).

    fwd_ms_per_sample: forward time, tp=1, one device, per sample
      (reference schema key layertype_i, computation_profiling_*.json).
    parameter_mb: fp32 parameter size in MB (4 bytes/param).
    activation_mb_per_sample: {tp: MB} measured activation per sample
      (memory_profiling_*.json tp_activation_per_bsz_dict equivalent).
    boundary_activation_mb_per_sample: one (S, H) boundary tensor — the remat
      floor and the p2p message size.
    """

    fwd_ms_per_sample: float
    parameter_mb: float
    activation_mb_per_sample: Dict[int, float]
    boundary_activation_mb_per_sample: float
    # MoE (switch) layers: fraction of parameter_mb (and, as a proxy, of
    # compute) that lives in the expert stack — shardable by the ep strategy
    # dim — and the token dispatch+combine all-to-all volume per sample.
    # 0 → dense layer; ep has no effect. The reference carries SwitchMLP but
    # never searches EP (SURVEY §2.3 ⚠) — this closes that gap.
    moe_expert_param_fraction: float = 0.0
    moe_a2a_mb_per_sample: float = 0.0
    # MEASURED share of the switch layer's fwd time that scales with ep
    # (the expert GEMMs; routing/sinkhorn/dispatch einsums do NOT shard by
    # ep). None → fall back to the param-fraction proxy. Measured on-chip by
    # profiling/model.py's two-point ffn fit (experiments/ab_moe.py,
    # BASELINE.md round-5).
    moe_expert_time_fraction: Optional[float] = None

    def __post_init__(self):
        if not (0.0 <= self.moe_expert_param_fraction < 1.0):
            raise ValueError(
                "moe_expert_param_fraction must be in [0, 1) — it is the "
                "expert-stack share of parameter_mb (a value >= 1 means the "
                "per-layer param count ignored the expert stack, which would "
                f"drive dense memory negative); got {self.moe_expert_param_fraction}"
            )

    def _replicated_mb(self) -> float:
        """Per-sample MB of the tp-REPLICATED activation share, derived from
        the table itself: with act(k) = repl + shard/k, two profiled degrees
        k1 < k2 solve repl = (k2·act(k2) − k1·act(k1)) / (k2 − k1). One
        profiled degree falls back to the fitted ACT_TP_UNSHARDED fraction.
        Clamped to [0, min(act)] against noisy profiles."""
        tab = self.activation_mb_per_sample
        if len(tab) >= 2:
            ks = sorted(tab)[:2]
            k1, k2 = ks
            repl = (k2 * tab[k2] - k1 * tab[k1]) / (k2 - k1)
        else:
            (k1,) = tab
            repl = ACT_TP_UNSHARDED * tab[k1] * (
                1.0 / (ACT_TP_UNSHARDED + (1.0 - ACT_TP_UNSHARDED) / k1)
            )
        return min(max(repl, 0.0), min(tab.values()))

    def act_mb(self, tp: int, sp: bool, cp: int = 1) -> float:
        """Per-sample activation MB at (tp, sp, cp).

        tp degrees missing from the profiled table extrapolate through
        ``act(tp) = act(1) * (u + (1-u)/tp)`` — a tp-replicated share ``u``
        (the residual/norm stream GSPMD keeps replicated without sp) does
        not shrink with tp, so the seed's pure-1/tp extrapolation
        systematically under-predicted tp>1 cells (round-5 measured the
        tp2 class at 0.71x where 1/tp says 0.5x). sp shards the REPLICATED
        share only — derived from the table (_replicated_mb), replacing the
        seed's unfitted flat ``0.5 + 0.5/tp`` discount which overstated the
        sp saving on attention-path-heavy tables. Coefficients fitted to
        the topology-measured sweeps (experiments/act_memory_sweep.py;
        tests/test_memory_fidelity.py pins)."""
        base = self.activation_mb_per_sample.get(tp)
        if base is None:
            k = min(self.activation_mb_per_sample, key=lambda t: abs(t - tp))
            scale = lambda t: ACT_TP_UNSHARDED + (1.0 - ACT_TP_UNSHARDED) / t
            base = self.activation_mb_per_sample[k] * scale(tp) / scale(k)
        if sp and tp > 1:
            base = base - ACT_SP_SHARDED * self._replicated_mb() * (1.0 - 1.0 / tp)
            base = max(base, 0.0)
        return base / cp


@dataclass
class ProfiledModelCosts:
    layer_types: Dict[int, ProfiledLayerType]
    # embedding + head ("other") memory, fp32 param MB
    other_param_mb: float = 0.0
    # per-sample activation of embed+head+loss (logits dominate)
    other_act_mb_per_sample: float = 0.0
    other_fwd_ms_per_sample: float = 0.0
    # model hidden size — lets other_time_cost derive the vocab-parallel
    # cross-entropy scalar volume from first principles instead of a constant
    hidden_size: int = 0
    # MEASURED embed+head+loss cost per vocab_tp as a two-point linear fit
    # over samples-per-device: slope (ms per sample) captures the batch-
    # linear compute + vocab-parallel collectives, const (ms per iteration)
    # the batch-independent share (the Adam update on the V·h params
    # dominates a zero-layer step at small batch). Measured on vocab_tp
    # devices at dp=1 (profiling/model.py::profile_vocab_costs);
    # other_time_cost consumes the fit only when the search precision
    # matches measured_vocab_mp.
    measured_vocab_slope_ms: Dict[int, float] = field(default_factory=dict)
    measured_vocab_const_ms: Dict[int, float] = field(default_factory=dict)
    measured_vocab_mp: str = ""

    def vocab_measurement_for(self, vocab_tp: int, mixed_precision: str):
        """(slope_ms_per_sample, const_ms) when a matching-precision
        measurement exists for this vocab_tp, else None."""
        if (
            vocab_tp in self.measured_vocab_slope_ms
            and self.measured_vocab_mp == mixed_precision
        ):
            return (
                self.measured_vocab_slope_ms[vocab_tp],
                self.measured_vocab_const_ms.get(vocab_tp, 0.0),
            )
        return None


@dataclass
class ProfiledHardware:
    """ICI bandwidths per (group size, consec layout) — the nccl-tests
    equivalent (reference: profile_hardware/hardware_configs/*.json)."""

    allreduce_bw: Dict[str, float] = field(default_factory=dict)  # "size_consec" → GB/s
    p2p_bw: Dict[int, float] = field(default_factory=dict)  # pp degree → GB/s
    overlap_coe: float = 1.1
    # which allreduce keys (and, with num_slices>1, every p2p degree) were
    # measured ACROSS the slice/DCN boundary — informational provenance:
    # entries already carry the boundary in their measured values because the
    # profiler builds the same slice-major mesh the runtime uses
    dcn_keys: list = field(default_factory=list)

    def fallback_sources(self, pp: int = 1) -> list:
        """Which bandwidth terms would come from built-in defaults rather than
        measurement — single-chip hosts cannot profile collectives/p2p
        (profiling/hardware.py degenerates there), so predictions priced from
        the defaults should be labeled (VERDICT: searched pp>1 configs were
        silently priced from the 50 GB/s fallback)."""
        out = []
        if not self.allreduce_bw:
            out.append("allreduce_bw")
        if pp > 1 and not self.p2p_bw:
            out.append("p2p_bw")
        return out

    def bw(self, size: int, consec: bool = True) -> float:
        if size <= 1:
            return float("inf")
        key = f"{size}_{int(consec)}"
        if key in self.allreduce_bw:
            return self.allreduce_bw[key]
        alt = f"{size}_{int(not consec)}"
        if alt in self.allreduce_bw:
            return self.allreduce_bw[alt]
        if self.allreduce_bw:
            return min(self.allreduce_bw.values())
        return 100.0  # ICI-order default

    def p2p(self, pp: int) -> float:
        if pp <= 1:
            return float("inf")
        if pp in self.p2p_bw:
            return self.p2p_bw[pp]
        if self.p2p_bw:
            return min(self.p2p_bw.values())
        return 50.0


# HBM bandwidth assumed when splitting a measured constant into its
# memory-traffic share (v5e-class default; used only for the zero3
# Adam-update correction in other_time_cost)
_HBM_GBPS = 800.0


def _allreduce_wire_mb(msg_mb: float, size: int) -> float:
    """On-wire MB per participant for a ring all-reduce of a ``msg_mb``
    message over ``size`` devices (reduce-scatter + all-gather halves)."""
    if size <= 1 or msg_mb == 0:
        return 0.0
    return 2.0 * (size - 1) / size * msg_mb


def _allgather_wire_mb(msg_mb: float, size: int) -> float:
    """On-wire MB per participant for an all-gather whose FULL (gathered)
    message is ``msg_mb`` — each device receives the other size-1 shards."""
    if size <= 1 or msg_mb == 0:
        return 0.0
    return (size - 1) / size * msg_mb


def _allreduce_ms(msg_mb: float, size: int, bw_gbps: float) -> float:
    return _allreduce_wire_mb(msg_mb, size) / bw_gbps  # MB / (GB/s) = ms


def _allgather_ms(msg_mb: float, size: int, bw_gbps: float) -> float:
    return _allgather_wire_mb(msg_mb, size) / bw_gbps


# ---------------------------------------------------------------------------
# Memory cost
# ---------------------------------------------------------------------------


@dataclass
class MemoryCost:
    states_mb: float
    activation_mb: float
    total_mb: float


def layer_memory_cost(
    lt: ProfiledLayerType,
    s: LayerStrategy,
    world: int,
    pp: int,
    global_bsz: int,
    chunks: int = 1,
    stage_idx: int = 0,
    pipeline_type: str = "gpipe",
    mixed_precision: str = "bf16",
    vpp: int = 1,
    stash_boundary_bound: Optional[int] = None,
) -> MemoryCost:
    """Per-chip memory for one layer under strategy ``s``
    (reference: MemoryCostModel, galvatron/core/cost_model.py:4-122).

    ``stash_boundary_bound``: the coupled enc-dec 1F1B
    (parallel/pipeline_encdec.py) stashes only section INPUTS in a ring of
    that many micro-batch slots and recomputes the section in its backward
    tick, so its activation term is boundary-sized per stashed chunk plus
    ONE live micro-batch of full activations — not act x in-flight like the
    single-stack 1F1B whose in-flight bound this branch bypasses."""
    dp = world // (pp * s.tp * s.cp)
    # fp32 MB after TP sharding; the expert fraction additionally shards by
    # ep, and its ZeRO sharding spreads only over the dp/ep extent left (the
    # runtime strips the ep axes from the fsdp axes — parallel/sharding.py)
    frac = lt.moe_expert_param_fraction
    ep = max(1, s.ep)
    dense_mb = lt.parameter_mb * (1.0 - frac) / s.tp
    exp_mb = lt.parameter_mb * frac / (s.tp * ep)
    dp_exp = max(1, dp // ep)
    p_mb = dense_mb + exp_mb
    sharded_mb = dense_mb / dp + exp_mb / dp_exp
    # Persistent states = fp32 master + two Adam moments = 3x. The naive
    # 4th "gradient" copy does NOT persist in this runtime: the donated
    # fused train step consumes grads layer-by-layer into the aliased
    # update, so a full-model gradient never materializes — EXCEPT when the
    # step accumulates (pp engines carry a per-stage fp32 dw in the tick
    # carry; the pp=1 accumulation scan carries one across micro-batches),
    # which adds one fp32 grad at the parameter's own sharding. The bf16
    # working cast is likewise per-layer transient (cast → consume → free),
    # not a persistent 0.5x copy — it is charged once per device as part of
    # transient_overhead_mb, not per layer. Measured: memory-fidelity sweep
    # vs the v5e:2x4 topology compiler, experiments/memory_fidelity.py
    # (BASELINE.md round-5).
    if s.dp_type == "zero3":
        states = 3.0 * sharded_mb
        grad_acc = sharded_mb
    elif s.dp_type == "zero2":
        states = p_mb + 2.0 * sharded_mb
        grad_acc = sharded_mb
    else:
        states = 3.0 * p_mb
        grad_acc = p_mb
    if pp > 1 or chunks > 1:
        states += grad_acc
    local_bsz = global_bsz / dp / max(1, s.cp)
    mb_bsz = local_bsz / chunks
    # 'full' remat stores only the layer-boundary activation; 'selective'
    # (attention-core-only recompute) stores the same per-layer activations as
    # no-remat on the flash path — scores are never materialized there — so it
    # is modeled as act_mb (conservative for the xla-attention path).
    act_per_mb = (
        lt.boundary_activation_mb_per_sample if s.ckpt == "full" else lt.act_mb(s.tp, s.sp, s.cp)
    ) * mb_bsz
    if pp == 1:
        act = act_per_mb  # accumulation scan keeps one micro-batch live
    elif stash_boundary_bound is not None:
        act = (
            lt.boundary_activation_mb_per_sample
            * mb_bsz
            * min(chunks, stash_boundary_bound)
            + act_per_mb
        )
    elif pipeline_type == "gpipe":
        # the clocked scan's autodiff saves the stage residuals EVERY tick —
        # bubble ticks included (invalid ticks compute on garbage but their
        # residuals are stacked all the same) — so the charge is per tick
        # (chunks + pp - 1), not per micro-batch. Under bf16/fp16 compute
        # the MEASURED per-tick residency is ~2x the compute-dtype estimate
        # (TPU-topology fit: needed factors 1.7-2.6 across shapes, 2.0
        # centers the class — consistent with fp32 widening of saved
        # residuals in the manual-region backward; BASELINE.md round-5
        # fidelity tables). fp32 compute is already wide.
        widen = 2.0 if mixed_precision in ("bf16", "fp16") else 1.0
        act = act_per_mb * (chunks + pp - 1) * widen
    else:
        # 1F1B engines (single-stack pipeline_1f1b and interleaved
        # pipeline_interleaved 1F1B) stash only (virtual-)stage INPUT
        # boundaries in a ring and recompute the stage forward in the
        # backward tick — the per-layer share is ONE live micro-batch of
        # residuals; the boundary stash rings + fp32 cotangent ring are
        # per-stage constants charged at the engine level
        # (search_engine pf_overhead), exactly like the coupled engines'.
        act = act_per_mb
    return MemoryCost(states, act, states + act)


def transient_overhead_mb(
    costs: ProfiledModelCosts,
    min_tp: int = 1,
    mixed_precision: str = "bf16",
) -> float:
    """Per-device transient working set charged ONCE (not per layer): the
    bf16 weight cast (0.5x the layer's params) plus one in-flight fp32
    gradient of the largest layer — the donated fused step keeps at most
    ~one layer's cast+grad live at a time (measured: the fidelity sweep's
    temp decomposition, BASELINE.md round-5). ``min_tp``: the smallest tp
    any layer may choose (the worst per-device share)."""
    if not costs.layer_types:
        return 0.0
    p_l = max(lt.parameter_mb for lt in costs.layer_types.values()) / max(1, min_tp)
    cast = 0.5 * p_l if mixed_precision in ("bf16", "fp16") else 0.0
    return cast + p_l


def stash_ring_mb(
    lt: ProfiledLayerType,
    s: LayerStrategy,
    slots: int,
    world: int,
    pp: int,
    global_bsz: int,
    chunks: int,
    mixed_precision: str = "bf16",
    stage_idx: int = 0,
    vpp: int = 1,
) -> float:
    """Per-device MB of ONE coupled/single-stack 1F1B input-stash ring of
    ``slots`` boundary micro-batch slots at strategy ``s``, isolated as the
    difference of layer_memory_cost at bounds (slots, 0) so the formula
    stays the cost model's (states cancel exactly). The runtime allocates
    one sacrificial slot beyond the useful min(chunks, slots)."""
    if not slots:
        return 0.0
    kw = dict(
        stage_idx=stage_idx, pipeline_type="pipedream_flush",
        mixed_precision=mixed_precision, vpp=vpp,
    )
    hi = layer_memory_cost(
        lt, s, world, pp, global_bsz, chunks, stash_boundary_bound=slots, **kw
    ).total_mb
    lo = layer_memory_cost(
        lt, s, world, pp, global_bsz, chunks, stash_boundary_bound=0, **kw
    ).total_mb
    useful = min(chunks, slots)
    return (hi - lo) * (useful + 1) / useful


# FITTED 1F1B buffer-reuse credit (refit of the round-5 small-shape
# over-charge): at small scales the TPU compiler's buffer assignment
# colocates the engines' per-stage fp32 dw accumulator and the transient
# cast/grad working set with the recompute workspace and the ring slots —
# the recorded small-shape cells (BASELINE.md: pp2-1F1B 163.6/114.9 = 1.42x,
# pp4 104.4/56.7 = 1.84x over-predicted) sit close to 3x-states + one
# micro-batch, i.e. the independent sums never materialize together. The
# credit is the smaller of the two pools, capped: colocation is a small-
# buffer phenomenon — at the 7B-representative scale the dw/transient are
# measured as truly resident (pp2-1F1B fidelity 0.86) and must stay charged.
# Fitted to the recorded cells: pp2 1.42 -> 1.21, pp4 1.84 -> 1.51 (the pp4
# residual stands until a pp-capable topology channel re-measures — this
# session's sandbox rejects PartitionId on the shard_map pipeline AOT path).
PF_REUSE_CAP_MB = 64.0


def pipedream_reuse_credit_mb(
    accum_mb: float, transient_mb: float, workspace_mb: float
) -> float:
    return min(accum_mb + transient_mb, workspace_mb, PF_REUSE_CAP_MB)


def grad_accum_mb(lt: ProfiledLayerType, s: LayerStrategy, world: int, pp: int) -> float:
    """One layer's fp32 gradient accumulator at its own sharding — the
    grad_acc term layer_memory_cost folds into states when accumulating."""
    dp = world // (pp * s.tp * s.cp)
    frac = lt.moe_expert_param_fraction
    ep = max(1, s.ep)
    dense_mb = lt.parameter_mb * (1.0 - frac) / s.tp
    exp_mb = lt.parameter_mb * frac / (s.tp * ep)
    dp_exp = max(1, dp // ep)
    if s.dp_type in ("zero2", "zero3"):
        return dense_mb / dp + exp_mb / dp_exp
    return dense_mb + exp_mb


def single_1f1b_rings_mb(
    lt: ProfiledLayerType,
    s: LayerStrategy,
    world: int,
    pp: int,
    global_bsz: int,
    chunks: int,
    mixed_precision: str = "bf16",
    vpp: int = 1,
    layers_per_device: int = 1,
) -> float:
    """Per-device constants of the single-stack/interleaved 1F1B engines
    (pipeline_1f1b.py / pipeline_interleaved.py carries), priced at the
    stage's own strategy sharding: the (virtual-)stage input stash ring —
    (min(chunks, n_stash)+1) boundary micro-batch slots, vpp rings when
    interleaved — plus the fp32 dx_embed input-cotangent buffer of chunks+1
    slots (allocated on every stage: the SPMD carry is uniform), MINUS the
    fitted buffer-reuse credit (pipedream_reuse_credit_mb — see the
    PF_REUSE_CAP_MB provenance block). ``layers_per_device``: layers on one
    device, sizing the accumulator/workspace pools the credit compares.
    The ONE pricing shared by the search (SearchEngine._1f1b_rings_mb) and
    the fidelity harness (memory_fidelity.predicted_train_mb)."""
    n_stash = (2 * pp - 1) if vpp == 1 else (3 * pp + 1)
    stash = stash_ring_mb(
        lt, s, n_stash, world, pp, global_bsz, chunks, mixed_precision, vpp=vpp
    ) * max(1, vpp)
    fp32x = 2.0 if mixed_precision in ("bf16", "fp16") else 1.0
    dx = stash_ring_mb(
        lt, s, chunks, world, pp, global_bsz, chunks, mixed_precision, vpp=vpp
    )
    rings = stash + dx * fp32x
    n_dev = max(1, layers_per_device)
    dp = world // (pp * s.tp * s.cp)
    mb_bsz = global_bsz / dp / max(1, s.cp) / chunks
    act_stage = lt.act_mb(s.tp, s.sp, s.cp) * mb_bsz * n_dev
    accum = grad_accum_mb(lt, s, world, pp) * n_dev
    # transient pool shape matches transient_overhead_mb's cast + one grad
    trans = (0.5 if mixed_precision in ("bf16", "fp16") else 0.0) + 1.0
    trans = trans * lt.parameter_mb / s.tp
    return rings - pipedream_reuse_credit_mb(accum, trans, act_stage + rings)


def other_memory_cost(
    costs: ProfiledModelCosts,
    world: int,
    pp: int,
    vocab_tp: int,
    embed_dp_type: str,
    global_bsz: int,
    chunks: int,
    mixed_precision: str = "bf16",
) -> float:
    """Embedding/head/loss memory on the first/last stage (reference 'other'
    memory, cost_model.py:78-106). In this runtime embed/head are replicated
    over pp and sharded by vocab_tp (+ZeRO over the data axes)."""
    dp = world // (pp * vocab_tp)
    p_mb = costs.other_param_mb / vocab_tp
    cast = 0.5 * p_mb if mixed_precision in ("bf16", "fp16") else 0.0
    if embed_dp_type == "zero3":
        states = 4.0 * p_mb / dp + cast
    else:
        states = 4.0 * p_mb + cast
    act = costs.other_act_mb_per_sample * (global_bsz / dp / chunks) / vocab_tp
    return states + act


def other_time_cost(
    costs: ProfiledModelCosts,
    hw: ProfiledHardware,
    world: int,
    pp: int,
    vocab_tp: int,
    embed_dp_type: str,
    global_bsz: int,
    mixed_precision: str = "bf16",
    use_measured: bool = True,
) -> float:
    """Embedding/head/loss time (ms) per iteration under the vocab strategy
    (the whole-model extension the reference prices via hp_config_whole_model,
    galvatron/core/hybrid_parallel_config.py:141-179).

    When the profile carries a MEASURED per-vocab_tp fit (slope + const from
    profile_vocab_costs, matching precision), the compute + vocab-parallel-
    collective part comes from measurement: const + slope · samples-per-
    device. The runtime computes embed/head OUTSIDE the pipelined section
    with the batch sharded over the pp axes too (full_spec), so samples per
    device = global_bsz·vocab_tp/world = global_bsz/(dp·pp). Only the
    dp-extent comm (grad reduction, ZeRO gathers) stays analytic.

    Analytic fallback: compute spread over the full mesh regardless of the
    (dp, pp, vocab_tp) split is EXACT for the head GEMM / embedding /
    elementwise loss under that same full-mesh batch sharding; the strategy
    moves only the comm terms."""
    dp = world // (pp * vocab_tp)
    comm_bytes = 0.5 if mixed_precision in ("bf16", "fp16") else 1.0
    p_mb = costs.other_param_mb / vocab_tp
    dp_consec = not (vocab_tp > 1)
    dp_bw = hw.bw(dp, dp_consec)
    # grad allreduce (ddp) / reduce-scatter+gathers (zero3 ≈ allreduce + 2
    # param all-gathers), same shape as the layer cost model
    comm = _allreduce_ms(p_mb * comm_bytes * GRAD_REDUCE_FP32_FACTOR, dp, dp_bw)
    if embed_dp_type == "zero3":
        comm += ZERO3_GATHER_PASSES * _allgather_ms(p_mb * comm_bytes, dp, dp_bw)
    fit = costs.vocab_measurement_for(vocab_tp, mixed_precision) if use_measured else None
    if fit is not None:
        slope, const = fit
        # under embed zero3 each device updates only its 1/dp param shard —
        # but ONLY the Adam-update share of the measured const shrinks; the
        # rest (dispatch and per-step fixed overheads, which dominate the
        # zero-layer measurement on this environment) does not. The update
        # share is estimated from its memory traffic: ~28 B/param (read
        # p/g/m/v fp32, write p/m/v) = 7x the fp32 param MB at HBM rate
        # (dividing the WHOLE const by dp systematically underpriced zero3
        # at large dp and biased the vocab-strategy choice toward it).
        if embed_dp_type == "zero3":
            adam_ms = min(const, 7.0 * p_mb / _HBM_GBPS)
            const = const - adam_ms + adam_ms / dp
        return const + slope * (global_bsz / (dp * pp)) + comm
    compute = costs.other_fwd_ms_per_sample * global_bsz / world * 3.0
    if vocab_tp > 1 and costs.layer_types:
        lt0 = next(iter(costs.layer_types.values()))
        # vocab-parallel embedding: each device holds a vocab shard, so the
        # (B, S, h) embedding output is a psum over the vocab_tp group, fwd
        # and mirrored bwd (Megatron VocabParallelEmbedding semantics)
        act_msg = (
            lt0.boundary_activation_mb_per_sample * (global_bsz / dp) * comm_bytes
        )
        comm += 2.0 * _allreduce_ms(act_msg, vocab_tp, hw.bw(vocab_tp, True))
        # vocab-parallel cross entropy allreduces per-token fp32 scalars
        # (max, sum-exp, picked logit + the mirrored backward share ≈ 4):
        # volume = S·4·4B per sample = boundary·(8/h) — derived, replacing
        # the old hand-waved 0.002 constant (which equals h=4096 exactly)
        h = costs.hidden_size or 4096
        scalar_msg = (
            lt0.boundary_activation_mb_per_sample * (global_bsz / dp) * (8.0 / h)
        )
        comm += _allreduce_ms(scalar_msg, vocab_tp, hw.bw(vocab_tp, True))
    return compute + comm


# ---------------------------------------------------------------------------
# Time cost
# ---------------------------------------------------------------------------

# fwd+2bwd = 3.0; remat replay factors MEASURED on v5e (h=2048/8-layer,
# bsz 8, flash path, one process): full 3.83, selective 3.22 — the replayed
# forward is cheaper than a standalone fwd (no loss/collective tail and XLA
# overlaps part of the recompute with the backward), so the naive 4.0 / 3.33
# overpriced ckpt by ~4%. Shared constants: the coupled enc-dec 1F1B pricing
# (search_engine) reuses the full-replay factor for its per-tick section
# recompute — re-measure in ONE place.
REMAT_FULL_FACTOR = 3.85
REMAT_SELECTIVE_FACTOR = 3.25
# Residual fraction of the blocking TP-collective time that survives when the
# layer runs the decomposed collective-matmul (s.tp_overlap — ops/
# collective_matmul.py): the ring hides T-1 of T hops behind the GEMM chunks,
# leaving the first hop, the per-chunk launch overhead, and (non-sp) the
# output-gather half exposed. ASPLOS'23 (Wang et al.) reports 60-80% of the
# collective hidden on TPU ICI for transformer projection shapes; priced
# conservatively until a measured profile replaces it.
TP_OVERLAP_RESIDUAL = 0.4
# Comm-volume conventions the analytic terms below price — named (instead of
# inline literals) because analysis/comm_audit.py replays them as
# ``comm_volume_breakdown`` and gates predicted-vs-lowered fidelity on the
# ratio: a re-tuned constant here moves the predicted side ONLY, so the GTC001
# gate catches a mispricing instead of a step-time regression doing it later.
TP_BOUNDARY_COLLECTIVES = 4.0  # Megatron f/g: 2 fwd + 2 bwd boundary allreduces
REMAT_TP_REPLAY = 1.5  # full-remat forward replay repeats the 2 fwd collectives
ZERO3_GATHER_PASSES = 2.0  # fwd + bwd param all-gathers per iteration
GRAD_REDUCE_FP32_FACTOR = 2.0  # grads reduce at fp32 = 2x the bf16 wire bytes


def layer_time_cost(
    lt: ProfiledLayerType,
    s: LayerStrategy,
    hw: ProfiledHardware,
    world: int,
    pp: int,
    global_bsz: int,
    mixed_precision: str = "bf16",
    recompute_factor: Optional[float] = None,
) -> float:
    """Per-iteration per-layer time (ms) under strategy ``s`` (reference:
    TimeCostModel, galvatron/core/cost_model.py:125-349): compute (bwd=2×fwd,
    remat adds one fwd), TP collectives on the critical path, DP grad
    reduction + ZeRO gathers overlapped under the measured slowdown
    coefficient.

    ``recompute_factor``: schedules that replay the layer's forward
    regardless of its own ckpt setting (the coupled enc-dec 1F1B recomputes
    each section from its stashed input) price compute at
    max(strategy factor, recompute_factor) and the TP collectives at the
    full-remat replay convention — per term, so the once-per-iteration DP
    grad reduction is NOT inflated."""
    dp = world // (pp * s.tp * s.cp)
    local_bsz = global_bsz / dp / max(1, s.cp)
    # expert compute divides by ep on top of tp; the dense remainder divides
    # by tp only. The ep-shardable share is the MEASURED expert-time
    # fraction when the profile carries one (routing/dispatch overhead does
    # not shard by ep — the param-fraction proxy overstates the ep win);
    # param fraction otherwise.
    frac = lt.moe_expert_param_fraction
    tfrac = (
        lt.moe_expert_time_fraction
        if lt.moe_expert_time_fraction is not None
        else frac
    )
    per_sample = lt.fwd_ms_per_sample * (
        (1.0 - tfrac) / s.tp + tfrac / (s.tp * max(1, s.ep))
    )
    fwd = per_sample * local_bsz
    factor = (
        REMAT_FULL_FACTOR if s.ckpt == "full"
        else REMAT_SELECTIVE_FACTOR if s.ckpt == "selective"
        else 3.0
    )
    if recompute_factor is not None:
        factor = max(factor, recompute_factor)
    compute = fwd * factor

    comm_bytes_factor = 0.5 if mixed_precision in ("bf16", "fp16") else 1.0
    # TP: 2 allreduces fwd + 2 bwd of one (b, s, h) activation (Megatron f/g;
    # with SP the all-gather+reduce-scatter pair moves the same volume)
    act_msg = lt.boundary_activation_mb_per_sample * local_bsz * comm_bytes_factor
    tp_bw = hw.bw(s.tp, s.tp_consec)
    tp_ms = TP_BOUNDARY_COLLECTIVES * _allreduce_ms(act_msg, s.tp, tp_bw)
    if s.ckpt == "full" or recompute_factor is not None:
        tp_ms *= REMAT_TP_REPLAY  # forward-replay schedules replay the fwd collectives
    if s.tp_overlap and s.tp > 1:
        # decomposed collective-matmul pipelines the projection collectives
        # behind the GEMM chunks — only the residual exposure is priced
        tp_ms *= TP_OVERLAP_RESIDUAL
    # (selective recompute replays no TP collectives: the attention core sits
    # between the column- and row-parallel linears)
    # CP: the ring rotates K/V cp-1 hops per pass (the diagonal hop is
    # local — parallel/ring.py computes it before the scan); fwd rotates
    # K+V, bwd rotates K+V and the homing dk/dv — ≈ 2 ring passes of
    # 2·(seq-sharded kv) volume. _allgather_ms already carries the
    # (cp-1)/cp hop factor, so ×cp yields 2 × (cp-1) hops × per-hop bytes.
    cp_ms = 0.0
    if s.cp > 1:
        cp_bw = hw.bw(s.cp, True)
        cp_ms = 2.0 * _allgather_ms(act_msg / s.cp * 2.0, s.cp, cp_bw) * s.cp

    # EP: moe_a2a_mb_per_sample already covers dispatch + combine; the
    # backward replays both, so total = 2× that volume in all-to-alls
    # (an all-to-all moves (ep-1)/ep of the routed volume)
    ep_ms = 0.0
    if s.ep > 1 and lt.moe_a2a_mb_per_sample > 0:
        a2a_msg = lt.moe_a2a_mb_per_sample * local_bsz * comm_bytes_factor
        ep_ms = 2.0 * _allgather_ms(a2a_msg, s.ep, hw.bw(s.ep, True))

    # DP: grad allreduce (once per iteration); ZeRO-3 adds fwd+bwd param
    # all-gathers; ZeRO-2 reduce-scatter+all-gather ≈ allreduce volume.
    # Expert grads reduce only over the dp/ep extent that replicates them.
    dense_mb = lt.parameter_mb * (1.0 - frac) / s.tp
    exp_mb = lt.parameter_mb * frac / (s.tp * max(1, s.ep))
    dp_exp = max(1, dp // max(1, s.ep))
    dp_consec = not s.tp_consec if s.tp > 1 else True
    dp_bw = hw.bw(dp, dp_consec)
    dp_ms = _allreduce_ms(dense_mb * comm_bytes_factor * GRAD_REDUCE_FP32_FACTOR, dp, dp_bw)
    dp_ms += _allreduce_ms(exp_mb * comm_bytes_factor * GRAD_REDUCE_FP32_FACTOR, dp_exp, dp_bw)
    if s.dp_type == "zero3":
        dp_ms += ZERO3_GATHER_PASSES * _allgather_ms(dense_mb * comm_bytes_factor, dp, dp_bw)
        dp_ms += ZERO3_GATHER_PASSES * _allgather_ms(exp_mb * comm_bytes_factor, dp_exp, dp_bw)

    # overlap model: DP traffic overlaps compute at a slowdown coefficient
    # (reference bct_dp_overlap, cost_model.py:230-246)
    if dp_ms == 0:
        overlapped = compute
    elif dp_ms <= compute:
        overlapped = hw.overlap_coe * compute
    else:
        overlapped = hw.overlap_coe * compute + (dp_ms - compute)
    return overlapped + tp_ms + cp_ms + ep_ms


def pipeline_time_cost(
    stage_ms: list,
    boundary_msg_mb: float,
    pp: int,
    chunks: int,
    hw: ProfiledHardware,
    vpp: int = 1,
    pipeline_type: str = "gpipe",
) -> float:
    """Iteration time of the clocked pipeline (reference: pipeline_costmodel,
    galvatron/core/cost_model.py:372-427): fill + steady-state bottleneck.
    stage_ms: per-stage per-micro-batch compute+TP time (callers price
    pipedream_flush's per-tick forward recompute into stage_ms via
    REMAT_FULL_FACTOR — the hand-written 1F1B engines replay the stage
    forward from the input stash in every backward tick).

    vpp>1 (interleaved schedule): ticks are one virtual stage (1/vpp of a
    physical stage) long, so the pp-1-tick fill bubble shrinks by vpp, while
    every micro-batch crosses vpp× more ring boundaries (p2p volume ×vpp).
    The vpp=1 case reduces to the plain formula.

    pipedream_flush tick counts come from the engines: single-stack
    T = chunks + 2(pp-1) (pipeline_1f1b.py) vs gpipe's chunks + pp - 1;
    interleaved 1F1B T = vpp*chunks + vpp*pp + pp - 1
    (pipeline_interleaved.py:276) — its drain scales with vpp too."""
    if pp == 1:
        return sum(stage_ms)
    p2p_ms = boundary_msg_mb / hw.p2p(pp) if boundary_msg_mb else 0.0
    per_tick = [c / vpp + p2p_ms for c in stage_ms]
    bottleneck = max(per_tick)
    extra = 0
    if pipeline_type == "pipedream_flush":
        extra = (pp - 1) if vpp == 1 else vpp * pp
    return sum(per_tick) + bottleneck * (vpp * chunks - 1 + extra)


# ---------------------------------------------------------------------------
# Comm-volume replay (the predicted side of the GTC fidelity gate)
# ---------------------------------------------------------------------------


def comm_volume_breakdown(
    costs: ProfiledModelCosts,
    hp,
    world: int,
    global_bsz: int,
    mixed_precision: str = "bf16",
) -> Dict[str, float]:
    """Per-term analytic comm VOLUME (on-wire MB per device per iteration,
    every term — ``pp_p2p`` sums all of an iteration's boundary crossings)
    for one plan — the exact message sizes and multiplicities
    ``layer_time_cost`` / ``other_time_cost`` / ``pipeline_time_cost``
    price, with the bandwidth divided back out.

    This is the *predicted* side of ``analysis/comm_audit.py``'s
    ``predicted_over_lowered`` gate: the audited (lowered) side re-derives
    the same volumes from the program's actual abstract shapes and lowered
    collectives with its own first-principles constants, so a drift in any
    constant above (TP_BOUNDARY_COLLECTIVES, ZERO3_GATHER_PASSES, …) or in a
    message-size formula here moves only this side and trips GTC001.

    Terms absent from the plan (degree 1) are omitted.  Multi-layer-type
    models (vision towers, MoE stacks) price every layer with its own
    strategy but layer type 0's sizes — the fidelity gate tolerance absorbs
    the approximation, and the audit report marks the basis.
    """
    f = 0.5 if mixed_precision in ("bf16", "fp16") else 1.0
    lt = costs.layer_types[min(costs.layer_types)] if costs.layer_types else None
    out: Dict[str, float] = {}

    def add(term: str, mb: float) -> None:
        if mb > 0.0:
            out[term] = out.get(term, 0.0) + mb

    pp = hp.pp
    for s in hp.layer_strategies:
        if lt is None:
            break
        dp = max(1, world // (pp * s.tp * max(1, s.cp)))
        local_bsz = global_bsz / dp / max(1, s.cp)
        act_msg = lt.boundary_activation_mb_per_sample * local_bsz * f
        if s.tp > 1:
            tp_mb = TP_BOUNDARY_COLLECTIVES * _allreduce_wire_mb(act_msg, s.tp)
            if s.ckpt == "full":
                tp_mb *= REMAT_TP_REPLAY
            add("tp_boundary", tp_mb)
        if s.cp > 1:
            add("cp_ring", 2.0 * _allgather_wire_mb(act_msg / s.cp * 2.0, s.cp) * s.cp)
        frac = lt.moe_expert_param_fraction
        ep = max(1, s.ep)
        if s.ep > 1 and lt.moe_a2a_mb_per_sample > 0:
            a2a_msg = lt.moe_a2a_mb_per_sample * local_bsz * f
            add("ep_a2a", 2.0 * _allgather_wire_mb(a2a_msg, s.ep))
        dense_mb = lt.parameter_mb * (1.0 - frac) / s.tp
        exp_mb = lt.parameter_mb * frac / (s.tp * ep)
        dp_exp = max(1, dp // ep)
        add("dp_grad", _allreduce_wire_mb(dense_mb * f * GRAD_REDUCE_FP32_FACTOR, dp))
        add("dp_grad", _allreduce_wire_mb(exp_mb * f * GRAD_REDUCE_FP32_FACTOR, dp_exp))
        if s.dp_type == "zero3":
            add("zero3_gather", ZERO3_GATHER_PASSES * _allgather_wire_mb(dense_mb * f, dp))
            add("zero3_gather", ZERO3_GATHER_PASSES * _allgather_wire_mb(exp_mb * f, dp_exp))

    # embedding / head / loss under the vocab strategy (other_time_cost's
    # analytic comm block, volumes only)
    vocab_tp = max(1, hp.vocab_tp)
    dp_o = max(1, world // (pp * vocab_tp))
    p_mb = costs.other_param_mb / vocab_tp
    add("embed_dp", _allreduce_wire_mb(p_mb * f * GRAD_REDUCE_FP32_FACTOR, dp_o))
    if hp.embed_dp_type == "zero3":
        add("embed_dp", ZERO3_GATHER_PASSES * _allgather_wire_mb(p_mb * f, dp_o))
    if vocab_tp > 1 and lt is not None:
        act_msg_v = lt.boundary_activation_mb_per_sample * (global_bsz / dp_o) * f
        add("vocab_embed", 2.0 * _allreduce_wire_mb(act_msg_v, vocab_tp))
        h = costs.hidden_size or 4096
        add("vocab_embed", _allreduce_wire_mb(
            lt.boundary_activation_mb_per_sample * (global_bsz / dp_o) * (8.0 / h),
            vocab_tp,
        ))

    if pp > 1 and lt is not None:
        # per-iteration per-device boundary p2p: every micro-batch crosses
        # each boundary fwd (activation out) and bwd (grad in), so chunks ×
        # the per-tick message pipeline_time_cost prices = the full local
        # batch, twice
        s0 = hp.layer_strategies[0]
        dp0 = max(1, world // (pp * s0.tp * max(1, s0.cp)))
        add("pp_p2p",
            2.0 * lt.boundary_activation_mb_per_sample * (global_bsz / dp0) * f)
    return out
