"""Topology-change re-plan: the search engine as a *resume* subsystem.

The paper's premise is that the best parallelism plan is a function of the
hardware topology — so when a TPU pod shrinks under a run (preemption,
slice maintenance), the correct response is not "retry the old plan on
whatever is left" (Varuna/Bamboo approximate this with hand-built
reconfiguration tables) but a *re-search*: run the DP for the mesh that
actually exists and resume the portable checkpoint under the winner.

This module is that entry point, called by the elastic supervisor's child
(`core/elastic.py`) when the checkpoint's topology fingerprint trips
GTA017:

1. :func:`find_cached_plan` — scan the plan caches (``<ckpt>/replans/``
   first: plans earlier restarts of THIS run searched; then
   ``configs/strategies/``: the checked-in exemplars) for a plan whose
   provenance matches (model, live world size, global batch) and that
   passes ``check_plan`` cleanly. A second restart at the same shrunken
   world must not pay the search again.
2. :func:`replan_for_world` — run :class:`SearchEngine` for the new mesh on
   analytic model costs (no profile exists for a topology that appeared
   mid-run; the analytic model is exactly the "search before profiling"
   path `search/theoretical.py` provides) at the run's own global batch
   size, and save the result through ``save_result`` — which self-checks
   the emitted plan and stamps the self-describing provenance the next
   cache lookup keys on.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, List, Optional, Tuple


class ReplanInfeasibleError(RuntimeError):
    """No strategy fits the live topology under the re-plan budget. The
    elastic child maps this to its own exit code so the supervisor gives up
    instead of re-running the identical doomed search every restart."""


def default_cache_dirs(load_dir: Optional[str]) -> List[str]:
    """The plan-cache tiers, in lookup order: the run's own ``replans/``
    (plans earlier restarts searched), then the repo's checked-in
    ``configs/strategies/`` — resolved against the PACKAGE root, not the
    cwd, so a run launched from anywhere still sees it."""
    dirs = []
    if load_dir:
        dirs.append(os.path.join(os.path.abspath(load_dir), "replans"))
    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    dirs.append(os.path.join(root, "configs", "strategies"))
    return dirs


def scan_plan_cache(
    cache_dirs: List[str], match: Callable[[str, Any], bool]
) -> Optional[str]:
    """First strategy JSON for which ``match(path, decoded)`` holds.
    Directories are scanned in order and files within one in sorted order
    (deterministic choice); unreadable/non-JSON candidates are skipped."""
    for cd in cache_dirs:
        if not cd or not os.path.isdir(cd):
            continue
        for name in sorted(os.listdir(cd)):
            if not name.endswith(".json"):
                continue
            path = os.path.join(cd, name)
            try:
                with open(path) as f:
                    d = json.load(f)
            except (OSError, ValueError):
                continue
            try:
                if match(path, d):
                    return path
            except Exception:
                continue  # a malformed candidate is "no match", never a crash
    return None


def find_plan_by_hash(cache_dirs: List[str], want_hash: str) -> Optional[str]:
    """Cached plan whose semantic hash equals ``want_hash`` (the plan-
    continuity lookup: a same-topology restart re-adopting the plan the
    checkpoint was actually training)."""
    from galvatron_tpu.core.strategy import plan_hash

    return scan_plan_cache(
        cache_dirs,
        lambda _path, d: isinstance(d, dict) and plan_hash(d) == want_hash,
    )


def plan_provenance_matches(
    d: Any, model_name: str, world: int, global_bsz: int
) -> bool:
    """True when a strategy JSON's self-describing provenance says it was
    searched for exactly this (model, world, batch) cell."""
    if not isinstance(d, dict):
        return False

    def _as_int(key):
        try:
            return int(d.get(key) or 0)
        except (TypeError, ValueError):
            return 0

    if _as_int("num_devices") != world:
        return False
    if global_bsz and _as_int("global_bsz") != global_bsz:
        return False
    if model_name and d.get("model_size") and d["model_size"] != model_name:
        return False
    return True


def find_cached_plan(
    cache_dirs: List[str],
    model_config,
    model_name: str,
    world: int,
    global_bsz: int,
    memory_budget_mb: Optional[float] = None,
    verbose: bool = True,
) -> Optional[str]:
    """First cached plan (provenance match + clean ``check_plan``) for the
    live topology, or None. ``memory_budget_mb`` is the LIVE re-plan budget:
    without it check_plan would fall back to the candidate's own embedded
    ``memory_constraint_gb`` — and a checked-in exemplar searched under a
    bigger budget would pass its own record only to OOM the shrunken
    devices the fresh-search path correctly sizes for."""
    from galvatron_tpu.analysis import plan_check
    from galvatron_tpu.analysis.diagnostics import errors

    def match(path, d):
        if not plan_provenance_matches(d, model_name, world, global_bsz):
            return False
        diags = plan_check.check_plan(
            d, model_config=model_config, world_size=world,
            global_bsz=global_bsz or None,
            memory_budget_mb=memory_budget_mb, source=path,
        )
        if errors(diags):
            if verbose:
                print(f"replan cache: {path} matches but fails check_plan; skipping")
            return False
        return True

    return scan_plan_cache(cache_dirs, match)


def replan_for_world(
    model_config,
    world: int,
    global_bsz: int,
    out_path: str,
    model_name: str = "",
    search_space: str = "full",
    memory_gb: float = 16.0,
    max_tp: int = 8,
    max_chunks: int = 16,
    mixed_precision: str = "bf16",
    verbose: bool = True,
) -> str:
    """Search a fresh plan for ``world`` devices at the run's global batch
    and save it (self-checked + self-describing) to ``out_path``. Raises
    :class:`ReplanInfeasibleError` when nothing is feasible under
    ``memory_gb`` — the elastic child exits with its own code and the
    supervisor gives up, not a crash loop that re-runs the doomed search."""
    from galvatron_tpu.search.cost_model import ProfiledHardware
    from galvatron_tpu.search.search_engine import (
        SearchEngine,
        SearchSpace,
        apply_search_space,
    )
    from galvatron_tpu.search.theoretical import analytic_model_costs

    costs = analytic_model_costs(model_config, mixed_precision=mixed_precision)
    space = apply_search_space(
        SearchSpace(
            world_size=world,
            max_tp=max_tp,
            moe_experts=getattr(model_config, "moe_experts", 0),
        ),
        search_space,
    )
    eng = SearchEngine(
        costs,
        ProfiledHardware(),
        num_layers=model_config.total_layers,
        space=space,
        memory_budget_mb=memory_gb * 1024.0,
        mixed_precision=mixed_precision,
        section_pipeline=bool(getattr(model_config, "swin_depths", ())),
        model_config=model_config,
        model_name=model_name,
    )
    res = eng.search([global_bsz], max_chunks=max_chunks, verbose=verbose)
    if res is None:
        raise ReplanInfeasibleError(
            f"re-plan failed: no feasible strategy for {world} devices at "
            f"global batch {global_bsz} under {memory_gb} GB/device "
            "(--replan_memory_gb raises the budget)"
        )
    d = os.path.dirname(os.path.abspath(out_path))
    if d:
        os.makedirs(d, exist_ok=True)
    eng.save_result(res, out_path)
    return out_path


def resolve_plan_for_topology(
    model_config,
    world: int,
    global_bsz: int,
    cache_dirs: List[str],
    out_dir: str,
    model_name: str = "",
    search_space: str = "full",
    memory_gb: float = 16.0,
    max_tp: int = 8,
    mixed_precision: str = "bf16",
    verbose: bool = True,
) -> Tuple[str, str]:
    """The supervisor-facing entry: ``(plan_path, source)`` where source is
    ``"cache"`` or ``"search"``. A fresh search lands in ``out_dir`` under a
    provenance-keyed name, which makes it the cache hit of the *next*
    restart at this topology."""
    cached = find_cached_plan(
        cache_dirs, model_config, model_name, world, global_bsz,
        memory_budget_mb=memory_gb * 1024.0, verbose=verbose,
    )
    if cached is not None:
        if verbose:
            print(f"re-plan: cached plan for {world} devices → {cached}")
        return cached, "cache"
    out_path = os.path.join(
        out_dir,
        f"replan_{model_name or 'model'}_{world}dev_bsz{global_bsz}.json",
    )
    if verbose:
        print(
            f"re-plan: searching a strategy for {world} devices "
            f"(bsz {global_bsz}, space {search_space!r}, analytic costs)"
        )
    replan_for_world(
        model_config, world, global_bsz, out_path,
        model_name=model_name, search_space=search_space,
        memory_gb=memory_gb, max_tp=max_tp,
        mixed_precision=mixed_precision, verbose=verbose,
    )
    return out_path, "search"
