"""Memory-balanced pipeline stage division.

Port of the reference's ``pp_division_memory_balanced``
(galvatron/core/search_engine.py:586-654): greedily fill stages from the LAST
stage backwards toward the average per-stage total (layer memory + per-stage
"other" memory), cap any over-full early stage at 1.3x the average by
shifting layers to the next stage, then repair empty stages.

Architecture note — why the search feeds UNIT weights, deliberately: under
this runtime's padded SPMD stage stacking (parallel/pipeline.stage_layout),
every device allocates and computes ALL max(division) stack positions (padding
slots are masked to identity, not skipped — stage-diverging lax.cond around
the in-layer collectives deadlocks, verified on the CPU sim). Consequently
per-device parameter memory, activation memory AND per-tick compute are each
a function of max(division) ALONE: every division with the same maximum is
exactly equivalent, and the cost-minimal division is any one minimizing
max(division) — the near-even split. Feeding real per-layer memories into
this greedy can only RAISE the maximum for skewed profiles (e.g. a heavy
first layer yields [1, 4] over [2, 3]), which in this architecture is a
strict pessimization — more padded compute per tick, no memory saved.
tests/test_pipeline_uneven.py pins both halves of this claim (same-max
divisions trajectory-identical; larger-max measurably slower). The reference
architecture (per-stage heterogeneous programs, arbitrary layer placement,
galvatron/core/search_engine.py:586-672) is where memory-balanced division
genuinely pays; this port exists for interop with reference-searched configs
and for the enc-dec pairing analysis.

Embedding/head compute OUTSIDE the pipelined section here, sharded over the
full mesh, so per-stage "other" memory is uniform — a no-op for the greedy's
relative fills either way.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


def pp_division_memory_balanced(
    layer_mem_mb: Sequence[float],
    pp: int,
    other_mem_per_stage_mb: Optional[Sequence[float]] = None,
) -> List[int]:
    """Stage division (len pp, entries >= 1, sum == len(layer_mem_mb)).

    layer_mem_mb: per-layer memory cost in stage order.
    other_mem_per_stage_mb: per-stage non-layer memory (len pp); zeros when
      omitted (this runtime spreads embed/head over the whole mesh).
    """
    L = len(layer_mem_mb)
    if pp == 1:
        return [L]
    if L < pp:
        raise ValueError(f"cannot divide {L} layers over {pp} stages (>=1 each)")
    mems = np.asarray(layer_mem_mb, np.float64)
    other = (
        np.zeros(pp)
        if other_mem_per_stage_mb is None
        else np.asarray(other_mem_per_stage_mb, np.float64)
    )
    if other.shape != (pp,):
        raise ValueError(f"other_mem_per_stage_mb must have length {pp}")
    avg = (mems.sum() + other.sum()) / pp

    # greedy fill, last stage first (reference search_engine.py:610-621)
    division = [0] * pp
    stage_mem = other.copy()
    idx = L - 1
    for i in range(pp - 1, -1, -1):
        while idx >= 0:
            if i > 0 and avg - stage_mem[i] < 0.5 * mems[idx]:
                break
            stage_mem[i] += mems[idx]
            idx -= 1
            division[i] += 1

    # cap early stages at 1.3x average (reference :624-632)
    for i in range(pp - 1):
        left, right = sum(division[:i]), sum(division[: i + 1])
        cur = mems[left:right].sum() + other[i]
        while division[i] > 0 and cur > avg * 1.3:
            division[i] -= 1
            division[i + 1] += 1
            right -= 1
            cur -= mems[right]

    # repair empty stages (reference :635-644)
    for i in range(pp - 1):
        while division[i] <= 0:
            division[i] += 1
            division[i + 1] -= 1
    for i in range(pp - 1, 0, -1):
        while division[i] <= 0:
            division[i] += 1
            division[i - 1] -= 1
    assert sum(division) == L and all(n >= 1 for n in division), division
    return division
