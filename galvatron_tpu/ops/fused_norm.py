"""Pallas TPU fused RMSNorm / LayerNorm (forward + backward, custom VJP).

Replaces the reference's fused-norm CUDA dependencies — Megatron's fused
layernorm / rms_norm modules (reference: site_package/megatron/model/
fused_layer_norm.py, rms_norm.py) and the flash-attn ``dropout_add_rms_norm``
op used on the baichuan path (reference: models/baichuan/
BaiChuanModel_sequential.py:6-25; installed by galvatron/scripts/
flash_attn_ops_install.sh) — with from-scratch Pallas kernels:

- one VMEM-resident pass per row block: moments, normalize, scale — no
  HBM round-trip for the intermediate moments;
- ``fused_add_rmsnorm`` fuses the residual add into the same pass and
  returns the summed residual stream alongside the normalized output
  (the dropout_add_rms_norm pattern, minus dropout — these LLM families
  train without dropout);
- backward kernels recompute the inverse-rms/std from saved per-row stats
  and emit per-block partial weight grads, reduced outside the kernel.

On CPU the public entry points fall back to the plain-jnp reference path
(fast under XLA:CPU); tests exercise the kernels via interpret mode.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _use_interpret() -> bool:
    return jax.default_backend() == "cpu"


# ---------------------------------------------------------------------------
# Reference (jnp) paths — used as CPU fallback and in tests
# ---------------------------------------------------------------------------


def rmsnorm_ref(x, scale, eps: float = 1e-5):
    dt = x.dtype
    x32 = x.astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * r * scale.astype(jnp.float32)).astype(dt)


def layernorm_ref(x, scale, bias, eps: float = 1e-5):
    dt = x.dtype
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    out = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (out * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dt)


# ---------------------------------------------------------------------------
# RMSNorm kernels
# ---------------------------------------------------------------------------


def _rms_fwd_kernel(x_ref, g_ref, y_ref, r_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)  # (rows, H)
    g = g_ref[...].astype(jnp.float32)  # (1, H)
    r = jax.lax.rsqrt(jnp.mean(x * x, axis=1, keepdims=True) + eps)  # (rows, 1)
    y_ref[...] = (x * r * g).astype(y_ref.dtype)
    r_ref[...] = r.astype(jnp.float32)


def _rms_bwd_kernel(x_ref, g_ref, r_ref, dy_ref, dx_ref, dg_ref, dg_scr, *, hidden, nblk):
    # sequential grid over row blocks; dg accumulates in VMEM scratch because
    # a (1, H) per-block output tile violates the (8, 128) TPU tiling rule
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dg_scr[:] = jnp.zeros_like(dg_scr)

    x = x_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    r = r_ref[...].astype(jnp.float32)  # (rows, 1)
    dy = dy_ref[...].astype(jnp.float32)
    dyg = dy * g
    # dx = r·(dy·g) − x·r³/H·Σ_j(dy_j g_j x_j)
    dot = jnp.sum(dyg * x, axis=1, keepdims=True)
    dx = r * dyg - x * (r * r * r) * (dot / hidden)
    dx_ref[...] = dx.astype(dx_ref.dtype)
    dg_scr[0:1, :] += jnp.sum(dy * x * r, axis=0, keepdims=True)

    @pl.when(i == nblk - 1)
    def _finalize():
        dg_ref[...] = dg_scr[:]


def _pick_block_rows(n_rows: int, hidden: int, budget_bytes: int = 1 << 20) -> int:
    """Rows per kernel block: largest divisor of n_rows whose fp32 working
    block stays within ``budget_bytes`` of VMEM.

    Measured on v5e (h=4096, 16k rows): 64-row blocks run the forward at
    0.024 ms (~4x faster than XLA's fused norm), while 256-row blocks brush
    the 16 MB scoped-VMEM ceiling, spill, and degrade ~400x to 12.5 ms — the
    budget keeps blocks far from the cliff across hidden sizes."""
    target = max(8, min(512, budget_bytes // (4 * hidden)))
    b = min(n_rows, target)
    while n_rows % b:
        b -= 1
    return b


def _rms_fwd(x2d, scale, eps, interpret):
    n, h = x2d.shape
    br = _pick_block_rows(n, h)
    grid = (n // br,)
    y, r = pl.pallas_call(
        functools.partial(_rms_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2d.dtype),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(dimension_semantics=("parallel",)),
        interpret=interpret,
    )(x2d, scale.reshape(1, h))
    return y, r


def _rms_bwd(x2d, scale, r, dy2d, interpret):
    n, h = x2d.shape
    br = _pick_block_rows(n, h)
    grid = (n // br,)
    dx, dg_acc = pl.pallas_call(
        functools.partial(_rms_bwd_kernel, hidden=float(h), nblk=n // br),
        grid=grid,
        in_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, h), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((8, h), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2d.dtype),
            jax.ShapeDtypeStruct((8, h), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((8, h), jnp.float32)],
        compiler_params=pltpu.CompilerParams(dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x2d, scale.reshape(1, h), r, dy2d)
    return dx, dg_acc[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rmsnorm(x2d, scale, eps):
    y, _ = _rms_fwd(x2d, scale, eps, _use_interpret())
    return y


def _rmsnorm_fwd_rule(x2d, scale, eps):
    y, r = _rms_fwd(x2d, scale, eps, _use_interpret())
    return y, (x2d, scale, r)


def _rmsnorm_bwd_rule(eps, res, dy):
    x2d, scale, r = res
    dx, dg = _rms_bwd(x2d, scale, r, dy, _use_interpret())
    return dx, dg.astype(scale.dtype)


_rmsnorm.defvjp(_rmsnorm_fwd_rule, _rmsnorm_bwd_rule)


# ---------------------------------------------------------------------------
# LayerNorm kernels
# ---------------------------------------------------------------------------


def _ln_fwd_kernel(x_ref, g_ref, b_ref, y_ref, mu_ref, rstd_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    b = b_ref[...].astype(jnp.float32)
    mu = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mu
    rstd = jax.lax.rsqrt(jnp.mean(xc * xc, axis=1, keepdims=True) + eps)
    y_ref[...] = (xc * rstd * g + b).astype(y_ref.dtype)
    mu_ref[...] = mu
    rstd_ref[...] = rstd


def _ln_bwd_kernel(
    x_ref, g_ref, mu_ref, rstd_ref, dy_ref, dx_ref, dg_ref, db_ref, dg_scr, db_scr, *, nblk
):
    # sequential grid; dg/db accumulate in scratch (see _rms_bwd_kernel note)
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dg_scr[:] = jnp.zeros_like(dg_scr)
        db_scr[:] = jnp.zeros_like(db_scr)

    x = x_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    mu = mu_ref[...]
    rstd = rstd_ref[...]
    dy = dy_ref[...].astype(jnp.float32)
    xhat = (x - mu) * rstd
    dxhat = dy * g
    m1 = jnp.mean(dxhat, axis=1, keepdims=True)
    m2 = jnp.mean(dxhat * xhat, axis=1, keepdims=True)
    dx_ref[...] = (rstd * (dxhat - m1 - xhat * m2)).astype(dx_ref.dtype)
    dg_scr[0:1, :] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_scr[0:1, :] += jnp.sum(dy, axis=0, keepdims=True)

    @pl.when(i == nblk - 1)
    def _finalize():
        dg_ref[...] = dg_scr[:]
        db_ref[...] = db_scr[:]


def _ln_fwd(x2d, scale, bias, eps, interpret):
    n, h = x2d.shape
    br = _pick_block_rows(n, h)
    return pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=(n // br,),
        in_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2d.dtype),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(dimension_semantics=("parallel",)),
        interpret=interpret,
    )(x2d, scale.reshape(1, h), bias.reshape(1, h))


def _ln_bwd(x2d, scale, mu, rstd, dy2d, interpret):
    n, h = x2d.shape
    br = _pick_block_rows(n, h)
    dx, dg_acc, db_acc = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, nblk=n // br),
        grid=(n // br,),
        in_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, h), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((8, h), lambda i: (0, 0)),
            pl.BlockSpec((8, h), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2d.dtype),
            jax.ShapeDtypeStruct((8, h), jnp.float32),
            jax.ShapeDtypeStruct((8, h), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((8, h), jnp.float32),
            pltpu.VMEM((8, h), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x2d, scale.reshape(1, h), mu, rstd, dy2d)
    return dx, dg_acc[0], db_acc[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _layernorm(x2d, scale, bias, eps):
    y, _, _ = _ln_fwd(x2d, scale, bias, eps, _use_interpret())
    return y


def _layernorm_fwd_rule(x2d, scale, bias, eps):
    y, mu, rstd = _ln_fwd(x2d, scale, bias, eps, _use_interpret())
    return y, (x2d, scale, mu, rstd)


def _layernorm_bwd_rule(eps, res, dy):
    x2d, scale, mu, rstd = res
    dx, dg, db = _ln_bwd(x2d, scale, mu, rstd, dy, _use_interpret())
    return dx, dg.astype(scale.dtype), db.astype(scale.dtype)


_layernorm.defvjp(_layernorm_fwd_rule, _layernorm_bwd_rule)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def _tiles(h: int) -> bool:
    return h % 128 == 0


def fused_rmsnorm(x, scale, eps: float = 1e-5, force_pallas: bool = False):
    """RMSNorm over the last dim. x: (..., H); scale: (H,).

    Dispatches to the Pallas kernel on TPU (jnp reference on CPU, or when H
    doesn't tile the 128-lane registers). ``force_pallas`` runs the kernel in
    interpret mode on CPU — test hook."""
    h = x.shape[-1]
    if not _tiles(h) or (_use_interpret() and not force_pallas):
        return rmsnorm_ref(x, scale, eps)
    y2d = _rmsnorm(x.reshape(-1, h), scale, eps)
    return y2d.reshape(x.shape)


def fused_layernorm(x, scale, bias, eps: float = 1e-5, force_pallas: bool = False):
    """LayerNorm over the last dim. x: (..., H); scale, bias: (H,)."""
    h = x.shape[-1]
    if not _tiles(h) or (_use_interpret() and not force_pallas):
        return layernorm_ref(x, scale, bias, eps)
    y2d = _layernorm(x.reshape(-1, h), scale, bias, eps)
    return y2d.reshape(x.shape)


def fused_add_rmsnorm(
    x, residual, scale, eps: float = 1e-5, force_pallas: bool = False
) -> Tuple[jax.Array, jax.Array]:
    """(normed, new_residual) where new_residual = x + residual and normed =
    rmsnorm(new_residual) — the flash-attn ``dropout_add_rms_norm`` pattern
    (reference: models/baichuan/BaiChuanModel_sequential.py:6-25) without
    dropout. XLA fuses the add into the kernel's input read."""
    s = x + residual
    return fused_rmsnorm(s, scale, eps, force_pallas=force_pallas), s
