"""Pallas TPU flash attention (forward + backward kernels, custom VJP).

Replaces the reference's FlashAttention-2 CUDA dependency
(flash_attn_unpadded_func import, reference: galvatron/core/tensor_parallel/
transformer.py:33-39,437-496) with a from-scratch FlashAttention-2-style
online-softmax kernel for the MXU:

- forward: grid (batch, heads, q_blocks, k_blocks), k innermost; running
  (m, l, acc) in VMEM scratch; causal blocks above the diagonal skipped with
  ``pl.when``; emits the per-row log-sum-exp for the backward.
- backward: two kernels — dK/dV (grid over k blocks, q innermost) and dQ
  (grid over q blocks, k innermost) — recomputing probabilities from the
  saved LSE, never materializing the (S, S) score matrix.

Falls back to the einsum path automatically on CPU (interpret mode is used in
tests) and for shapes that don't tile (seq % block != 0).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.default_backend() == "cpu"


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *, sm_scale, causal, block_q, block_k, num_k_blocks):
    i = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: k block j contributes to q block i iff some (row, col) with
    # row >= col overlaps, i.e. (i+1)*block_q - 1 >= j*block_k (block sizes
    # may differ)
    if causal:
        last_j = jnp.minimum(((i + 1) * block_q - 1) // block_k, num_k_blocks - 1)
        contributes = ((i + 1) * block_q - 1) >= j * block_k
    else:
        last_j = num_k_blocks - 1
        contributes = jnp.bool_(True)

    @pl.when(contributes)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # (block_q, block_k)
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_old = m_scr[:, :1]  # (block_q, 1), lanes replicated
        m_new = jnp.maximum(m_old, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_old - m_new)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = alpha * acc_scr[:] + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == last_j)
    def _finalize():
        l = l_scr[:, :1]
        o_ref[0, 0] = (acc_scr[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_scr[:, :1] + jnp.log(jnp.maximum(l, 1e-30))).astype(
            jnp.float32
        )


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    b, h, s, d = q.shape
    nq, nk = s // block_q, s // block_k
    grid = (b, h, nq, nk)
    kernel = functools.partial(
        _fwd_kernel,
        sm_scale=sm_scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        num_k_blocks=nk,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i, j: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i, j: (b_, h_, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            # trailing unit dim keeps the block 2D-tileable on real TPUs
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *, sm_scale, causal, block_q, block_k, num_q_blocks):
    j = pl.program_id(2)  # k block
    i = pl.program_id(3)  # q block (innermost)

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    contributes = (
        ((i + 1) * block_q - 1) >= j * block_k if causal else jnp.bool_(True)
    )

    @pl.when(contributes)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0].astype(jnp.float32)  # (block_q, 1)
        delta = delta_ref[0, 0].astype(jnp.float32)  # (block_q, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)  # softmax probs
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * sm_scale
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(i == num_q_blocks - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr, *, sm_scale, causal, block_q, block_k, num_k_blocks):
    i = pl.program_id(2)  # q block
    j = pl.program_id(3)  # k block (innermost)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    if causal:
        last_j = jnp.minimum(((i + 1) * block_q - 1) // block_k, num_k_blocks - 1)
        contributes = ((i + 1) * block_q - 1) >= j * block_k
    else:
        last_j = num_k_blocks - 1
        contributes = jnp.bool_(True)

    @pl.when(contributes)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0].astype(jnp.float32)  # (block_q, 1)
        delta = delta_ref[0, 0].astype(jnp.float32)  # (block_q, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * sm_scale
        dq_scr[:] += jax.lax.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(j == last_j)
    def _finalize():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd(res, do_bhsd, sm_scale, causal, block_q, block_k, interpret):
    q, k, v, out, lse = res
    b, h, s, d = q.shape
    nq, nk = s // block_q, s // block_k
    delta = jnp.sum(
        do_bhsd.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True
    )  # (b, h, s, 1)

    qspec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, j, i: (b_, h_, i, 0))
    kspec = pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, j, i: (b_, h_, j, 0))
    rowspec = pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, j, i: (b_, h_, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel,
            sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, num_q_blocks=nq,
        ),
        grid=(b, h, nk, nq),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, j, i: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, j, i: (b_, h_, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(q, k, v, do_bhsd, lse, delta)

    qspec2 = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    kspec2 = pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i, j: (b_, h_, j, 0))
    rowspec2 = pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, i, 0))
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel,
            sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, num_k_blocks=nk,
        ),
        grid=(b, h, nq, nk),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowspec2, rowspec2],
        out_specs=pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(q, k, v, do_bhsd, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public API with custom VJP ((B, S, n, d) layout, matching modeling.attention)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, sm_scale, causal, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, _use_interpret())
    return out


def _flash_fwd_rule(q, k, v, sm_scale, causal, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, _use_interpret())
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(sm_scale, causal, block_q, block_k, res, do):
    dq, dk, dv = _flash_bwd(res, do, sm_scale, causal, block_q, block_k, _use_interpret())
    return dq, dk, dv


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(
    q,
    k,
    v,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = 1024,
    block_k: int = 1024,
):
    """q, k, v: (batch, seq, heads, head_dim); returns same layout.

    GQA callers repeat kv heads first (modeling._repeat_kv). Tiles of
    (block_q, block_k); shapes that don't tile fall back to the einsum path.
    Defaults tuned on v5e (b8 x s2048 x h32 x d128): 1024/1024 runs the
    forward at 18.5 ms and fwd+bwd at 29.6 ms vs 21.3/34.2 at 512/512 (XLA
    attention: 45 ms forward); 2048/512 is marginally faster forward-only but
    fails to compile the backward.
    """
    b, s, n, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(d))
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        from galvatron_tpu.models import modeling

        cfg = modeling.ModelConfig(num_heads=n, hidden_size=n * d, attn_impl="xla")
        return modeling.attention_xla(q, k, v, cfg)
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    out = _flash(qt, kt, vt, sm_scale, causal, block_q, block_k)
    return jnp.transpose(out, (0, 2, 1, 3))
