"""Pallas TPU flash attention (forward + backward kernels, custom VJP).

Replaces the reference's FlashAttention-2 CUDA dependency
(flash_attn_unpadded_func import, reference: galvatron/core/tensor_parallel/
transformer.py:33-39,437-496) with a from-scratch FlashAttention-2-style
online-softmax kernel for the MXU:

- forward: grid (batch, heads, q_blocks, k_blocks), k innermost; running
  (m, l, acc) in VMEM scratch; causal blocks above the diagonal skipped with
  ``pl.when``; emits the per-row log-sum-exp for the backward.
- backward: two kernels — dK/dV (grid over k blocks, q innermost) and dQ
  (grid over q blocks, k innermost) — recomputing probabilities from the
  saved LSE, never materializing the (S, S) score matrix.

Falls back to the einsum path automatically on CPU (interpret mode is used in
tests) and for shapes that don't tile (seq % block != 0).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LOG2E = 1.4426950408889634  # log2(e)
LN2 = 0.6931471805599453  # 1/log2(e)


def _use_interpret() -> bool:
    return jax.default_backend() == "cpu"


# Mosaic's default per-kernel scoped-VMEM budget is ~16 MB, but the v5e chip
# runs kernels with >=120 MB resident blocks when vmem_limit_bytes is raised
# (experiments/vmem_probe.py, measured on-chip). The kernels here request a
# larger budget so the combined blocked backward serves the 7B shape
# (s=4096: 21.4 MB scoped) and bigger block configs become legal.
# GALVATRON_FLASH_VMEM_MB=0 restores the Mosaic default.
_VMEM_LIMIT_MB = int(os.environ.get("GALVATRON_FLASH_VMEM_MB", "64"))


# jax < 0.6 spells the Mosaic params class TPUCompilerParams
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams


def _compiler_params(**kw):
    if _VMEM_LIMIT_MB:
        kw.setdefault("vmem_limit_bytes", _VMEM_LIMIT_MB << 20)
    return _CompilerParams(**kw)


def _single_buffered(shape, index_map) -> pl.BlockSpec:
    """BlockSpec pinned to single-buffering where pallas supports it
    (pl.Buffered, jax >= 0.6); older pallas falls back to Mosaic's default
    double-buffering — a VMEM-budget optimization only, numerics identical
    (the raised vmem_limit_bytes still covers the measured shapes there)."""
    if hasattr(pl, "Buffered"):
        return pl.BlockSpec(
            shape, index_map, pipeline_mode=pl.Buffered(buffer_count=1)
        )
    return pl.BlockSpec(shape, index_map)


def _rope_rows(x, c, s):
    """Rotate-half RoPE on one (rows, d) block; c/s are (rows, d/2) fp32.
    Returns fp32 (cast back to the MXU dtype at the dot)."""
    xf = x.astype(jnp.float32)
    d2 = xf.shape[-1] // 2
    x1, x2 = xf[:, :d2], xf[:, d2:]
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)


def _rope_rows_t(y, c, s):
    """Transpose (inverse) rotation — maps gradients w.r.t. roped vectors back
    to gradients w.r.t. the raw q/k rows."""
    d2 = y.shape[-1] // 2
    y1, y2 = y[:, :d2], y[:, d2:]
    return jnp.concatenate([y1 * c + y2 * s, y2 * c - y1 * s], axis=-1)


def _rope_io(rope, block_q: int, block_k: int, d: int, qk_order: str):
    """(extra in_specs, extra inputs) for the fused-rope kernels: cos/sin row
    blocks for the q rows then the k rows. ``qk_order`` is 'ij' when the grid
    is (..., q_block, k_block) and 'ji' when it is (..., k_block, q_block)."""
    if rope is None:
        return [], []
    cos, sin = rope
    if qk_order == "ij":
        qrow = pl.BlockSpec((block_q, d // 2), lambda b_, h_, i, j: (i, 0))
        krow = pl.BlockSpec((block_k, d // 2), lambda b_, h_, i, j: (j, 0))
    else:
        qrow = pl.BlockSpec((block_q, d // 2), lambda b_, h_, j, i: (i, 0))
        krow = pl.BlockSpec((block_k, d // 2), lambda b_, h_, j, i: (j, 0))
    return [qrow, qrow, krow, krow], [cos, sin, cos, sin]


def _dispatch_causal(causal, contributes, fully_below, accum):
    """Run ``accum(masked)`` under the right predicate. Causal blocks fully
    below the diagonal skip the mask arithmetic (it is a no-op there — and
    iota/where on every score element is a sizeable share of a VPU-bound
    kernel); diagonal-straddling blocks apply it; non-causal blocks always
    run unmasked. ``fully_below`` implies ``contributes``, so the two
    branches are disjoint and exhaustive over contributing blocks."""
    if not causal:
        accum(False)
        return
    pl.when(fully_below)(lambda: accum(False))
    pl.when(contributes & jnp.logical_not(fully_below))(lambda: accum(True))


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(*refs, sm_scale, causal, block_q, block_k, num_k_blocks, rope):
    if rope:
        q_ref, k_ref, v_ref, cq_ref, sq_ref, ck_ref, sk_ref = refs[:7]
        o_ref, lse_ref, m_scr, l_scr, acc_scr = refs[7:]
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    i = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: k block j contributes to q block i iff some (row, col) with
    # row >= col overlaps, i.e. (i+1)*block_q - 1 >= j*block_k (block sizes
    # may differ)
    if causal:
        last_j = jnp.minimum(((i + 1) * block_q - 1) // block_k, num_k_blocks - 1)
        contributes = ((i + 1) * block_q - 1) >= j * block_k
        # every row >= every col: min row i*bq, max col (j+1)*bk - 1
        fully_below = (i * block_q) >= ((j + 1) * block_k - 1)
    else:
        last_j = num_k_blocks - 1
        contributes = fully_below = None

    def _accum(masked):
        # keep q/k/v in their storage dtype (bf16): fp32 MXU matmul runs at a
        # fraction of the bf16 rate; accumulation stays fp32 via
        # preferred_element_type, softmax math stays fp32. RoPE (when fused)
        # rotates the VMEM-resident blocks — the roped q/k never round-trip
        # through HBM. The softmax scale folds the exp→exp2 base change into
        # its (single, fp32, post-matmul) multiply: the running max lives in
        # base-2 units and exp2 replaces exp. Scaling q instead would save
        # that multiply but requantizes q to bf16, doubling the output error.
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        if rope:
            q = _rope_rows(q, cq_ref[...], sq_ref[...]).astype(q_ref.dtype)
            k = _rope_rows(k, ck_ref[...], sk_ref[...]).astype(k_ref.dtype)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * (sm_scale * LOG2E)  # (block_q, block_k), base-2 logits
        if masked:
            rows = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_old = m_scr[:, :1]  # (block_q, 1), lanes replicated
        m_new = jnp.maximum(m_old, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp2(s - m_new)
        alpha = jnp.exp2(m_old - m_new)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = alpha * acc_scr[:] + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    _dispatch_causal(causal, contributes, fully_below, _accum)

    @pl.when(j == last_j)
    def _finalize():
        l = l_scr[:, :1]
        o_ref[0, 0] = (acc_scr[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        # running max is in base-2 units; emit the natural-log LSE the
        # backward (and ring-attention combining) expects
        lse_ref[0, 0] = (
            m_scr[:, :1] * LN2 + jnp.log(jnp.maximum(l, 1e-30))
        ).astype(jnp.float32)


def _flash_fwd(q, k, v, rope, sm_scale, causal, block_q, block_k, interpret,
               out_dtype=None, kv_rep: int = 1):
    """``out_dtype`` overrides the output dtype (ring attention asks for fp32
    so per-hop block outputs are not requantized before the lse recombine).

    ``kv_rep`` > 1: GQA-native serving — k/v carry kv_heads = h/kv_rep and
    their index maps send head h to kv group h // kv_rep, so the group's
    queries share the RESIDENT K/V block (consecutive grid steps with an
    unchanged block index skip the re-fetch) instead of reading a
    materialized group-times-repeated copy from HBM."""
    b, h, s, d = q.shape
    nq, nk = s // block_q, s // block_k
    grid = (b, h, nq, nk)
    kernel = functools.partial(
        _fwd_kernel,
        sm_scale=sm_scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        num_k_blocks=nk,
        rope=rope is not None,
    )
    rope_specs, rope_inputs = _rope_io(rope, block_q, block_k, d, "ij")
    in_specs = [
        pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
        pl.BlockSpec((1, 1, block_k, d),
                     lambda b_, h_, i, j: (b_, h_ // kv_rep, j, 0)),
        pl.BlockSpec((1, 1, block_k, d),
                     lambda b_, h_, i, j: (b_, h_ // kv_rep, j, 0)),
    ] + rope_specs
    inputs = [q, k, v] + rope_inputs
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            # trailing unit dim keeps the block 2D-tileable on real TPUs
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), out_dtype or q.dtype),
            jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(*inputs)
    return out, lse


# ---------------------------------------------------------------------------
# Blocked-causal forward: one pallas call per q row block, statically
# unrolled k loop, value-carried (m, l, acc)
# ---------------------------------------------------------------------------
#
# For the common causal+rope case the grid-scan kernel above leaves real time
# on the table (measured on v5e, LLaMA-7B shape: ~0.14 ms/layer/sample):
# every (i, j) grid step re-ropes q, pays scratch init/finalize bookkeeping,
# and diagonal blocks run an iota+compare+select mask over the full score
# block. Specializing ONE pallas call per q row block makes the causal
# structure static — call i unrolls exactly the j <= i contributing k blocks,
# the diagonal block applies a precomputed additive triangular bias, q is
# roped once, and (m, l, acc) stay SSA values so Mosaic sees the whole
# dependence graph. The softmax scale (and the exp->exp2 base change) is
# folded into the q-side rope tables at trace time: the fp32 rotation output
# is cast to bf16 regardless, so the scale costs nothing and the score block
# needs no post-matmul multiply.


def _fwd_kernel_blocked(*refs, nkb, block_q, block_k, stacked=False):
    (q_ref, k_ref, v_ref, cq_ref, sq_ref, ck_ref, sk_ref, tri_ref,
     o_ref, lse_ref) = refs
    # ``stacked``: q/k/v are index-mapped blocks of ONE (b, 3, h, s, d)
    # array (one extra leading unit dim) — feeding the projection's stacked
    # output directly removes the q/k/v slice copies XLA otherwise
    # materializes for the custom-call operands (~1.2 ms/layer-batch on the
    # v5e 7B bench, the last structural copy the trace showed)
    lead = (0, 0, 0) if stacked else (0, 0)
    # cq/sq pre-scaled by sm_scale*LOG2E: scores come out in base-2 units
    q = _rope_rows(q_ref[lead], cq_ref[...], sq_ref[...]).astype(q_ref.dtype)
    kf = _rope_rows(k_ref[lead], ck_ref[...], sk_ref[...]).astype(k_ref.dtype)
    vf = v_ref[lead]
    m = l = acc = None
    for j in range(nkb):
        kj = kf[j * block_k:(j + 1) * block_k]
        s = jax.lax.dot_general(
            q, kj, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if j == nkb - 1:  # bq == bk: only the last block straddles the diagonal
            s = s + tri_ref[...].astype(jnp.float32)
        if j == 0:
            m = jnp.max(s, axis=1, keepdims=True)
            p = jnp.exp2(s - m)
            l = jnp.sum(p, axis=1, keepdims=True)
            acc = jax.lax.dot(
                p.astype(vf.dtype), vf[:block_k], preferred_element_type=jnp.float32
            )
        else:
            m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp2(s - m_new)
            alpha = jnp.exp2(m - m_new)
            l = alpha * l + jnp.sum(p, axis=1, keepdims=True)
            acc = alpha * acc + jax.lax.dot(
                p.astype(vf.dtype), vf[j * block_k:(j + 1) * block_k],
                preferred_element_type=jnp.float32,
            )
            m = m_new
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse_ref[0, 0] = (m * LN2 + jnp.log(jnp.maximum(l, 1e-30))).astype(jnp.float32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _flash_qkv(qkv, rope, sm_scale, block_q):
    out, _ = _flash_fwd_blocked_qkv(qkv, rope, sm_scale, block_q, _use_interpret())
    return out


def _flash_qkv_fwd_rule(qkv, rope, sm_scale, block_q):
    out, lse = _flash_fwd_blocked_qkv(qkv, rope, sm_scale, block_q, _use_interpret())
    return out, (qkv, out, lse, rope)


def _flash_qkv_bwd_rule(sm_scale, block_q, res, do):
    qkv, out, lse, rope = res
    s, d = qkv.shape[3], qkv.shape[4]
    if _use_blocked_bwd(s, d, True, rope, block_q, block_q):
        bk, bq_sub = _bwd_blocks(block_q)
        dqkv = _flash_bwd_blocked(
            None, None, None, do, out, lse, rope, sm_scale, bk, bq_sub,
            _use_interpret(), qkv=qkv, do_stacked_out=True,
        )
    else:
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        dq, dk, dv = _flash_bwd(
            (q, k, v, out, lse, rope), do, sm_scale, True, block_q, block_q,
            _use_interpret(),
        )
        dqkv = jnp.stack([dq, dk, dv], axis=1)
    drope = None if rope is None else jax.tree.map(jnp.zeros_like, rope)
    return dqkv, drope


_flash_qkv.defvjp(_flash_qkv_fwd_rule, _flash_qkv_bwd_rule)


def flash_attention_qkv(qkv, sm_scale=None, block_q: int = 1024, rope=None):
    """Stacked head-major entry: ``qkv`` is the fused projection's
    (b, 3, h, s, d) output, consumed directly (causal + fused-rope path
    only — callers gate on flash_qkv_supported)."""
    d = qkv.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(d))
    return _flash_qkv(qkv, rope, sm_scale, min(block_q, qkv.shape[3]))


def flash_qkv_supported(s: int, d: int, causal: bool, rope, block_q: int = 1024) -> bool:
    """Whether the stacked-qkv blocked path applies (modeling's gate)."""
    return _use_blocked(s, d, causal, rope, min(block_q, s), min(block_q, s))


# The last q-block call keeps the full k prefix resident in VMEM (k, v, rope
# rows, fp32 rope intermediates scale with s*d) and statically unrolls nq k
# iterations; both must stay bounded. With the raised vmem_limit_bytes
# (see _compiler_params: the 16 MB figure was Mosaic's default, not the
# chip's — experiments/vmem_probe.py) the envelope extends to s=8192 at
# d=128, measured −15% on the full train step vs the grid kernels at that
# shape (experiments/ab_flash_bwd.py, v5e). When the env knob shrinks the
# budget below what a wide envelope actually charges, that envelope shrinks
# back so shapes route to the grid kernels instead of failing Mosaic's VMEM
# check at compile time. Each envelope's threshold is derived from its
# measured scoped-VMEM anchor (charges scale ~linearly in s·d): fwd ~24 MB
# at s=8192·d=128; bwd 21.4 MB at s=4096·d=128 ⇒ ~43 MB at s=8192 — so the
# bwd 8k extension needs a ≥ ~48 MB budget, not the fwd's ≥ 32 (a budget in
# [32, 42] passed the old shared gate but would fail the bwd compile).
_VMEM_EFF_MB = _VMEM_LIMIT_MB if _VMEM_LIMIT_MB else 16  # 0 → Mosaic default


def _seq_envelope(mb_per_sxd, candidates, floor, budget_mb=None):
    """Largest s·d envelope whose estimated scoped charge (with a 1.1×
    safety factor) fits the effective VMEM budget. The floor is the envelope
    proven under Mosaic's 16 MB default; a budget squeezed below even that
    disables the blocked path entirely (0) rather than risking a
    compile-time VMEM failure."""
    budget = _VMEM_EFF_MB if budget_mb is None else budget_mb
    for sxd in candidates + (floor,):
        if budget >= mb_per_sxd * sxd * 1.1:
            return sxd
    return 0


_FWD_MB_PER_SXD = 24.0 / (8192 * 128)
_BLOCKED_MAX_SEQ_X_DIM = _seq_envelope(_FWD_MB_PER_SXD, (8192 * 128,), 4096 * 128)
_BLOCKED_MAX_UNROLL = 8


def _use_blocked(s, d, causal, rope, block_q, block_k):
    return (
        causal
        and rope is not None
        and block_q == block_k
        and s % block_q == 0
        and s * d <= _BLOCKED_MAX_SEQ_X_DIM
        and s // block_q <= _BLOCKED_MAX_UNROLL
    )


def _flash_fwd_blocked(
    q, k, v, rope, sm_scale, block_q, interpret, out_dtype=None, qkv=None,
    kv_rep: int = 1,
):
    """Blocked-causal forward. Either q/k/v (b, h, s, d) separately, or
    ``qkv`` stacked (b, 3, h, s, d) consumed via index-mapped block specs
    (no slice copies). Returns (out, lse). ``kv_rep`` > 1: GQA-native k/v at
    kv_heads = h/kv_rep, index-mapped h -> h // kv_rep (see _flash_fwd)."""
    stacked = qkv is not None
    if stacked:
        b, _, h, s, d = qkv.shape
        dtype = qkv.dtype
        inputs = (qkv, qkv, qkv)
    else:
        b, h, s, d = q.shape
        dtype = q.dtype
        inputs = (q, k, v)
    nq = s // block_q
    lam = sm_scale * LOG2E
    cos, sin = rope
    cqs, sqs = cos * lam, sin * lam
    r = np.arange(block_q)
    tri = jnp.asarray(
        np.where(r[:, None] >= r[None, :], 0.0, NEG_INF), jnp.bfloat16
    )
    outs, lses = [], []
    for i in range(nq):
        nkb = i + 1
        kl = nkb * block_q
        if stacked:
            qkv_specs = [
                pl.BlockSpec((1, 1, 1, block_q, d), lambda b_, h_, i=i: (b_, 0, h_, i, 0)),
                pl.BlockSpec((1, 1, 1, kl, d), lambda b_, h_: (b_, 1, h_, 0, 0)),
                pl.BlockSpec((1, 1, 1, kl, d), lambda b_, h_: (b_, 2, h_, 0, 0)),
            ]
        else:
            qkv_specs = [
                pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i=i: (b_, h_, i, 0)),
                pl.BlockSpec((1, 1, kl, d), lambda b_, h_: (b_, h_ // kv_rep, 0, 0)),
                pl.BlockSpec((1, 1, kl, d), lambda b_, h_: (b_, h_ // kv_rep, 0, 0)),
            ]
        out_i, lse_i = pl.pallas_call(
            functools.partial(
                _fwd_kernel_blocked, nkb=nkb, block_q=block_q, block_k=block_q,
                stacked=stacked,
            ),
            grid=(b, h),
            in_specs=qkv_specs + [
                pl.BlockSpec((block_q, d // 2), lambda b_, h_, i=i: (i, 0)),
                pl.BlockSpec((block_q, d // 2), lambda b_, h_, i=i: (i, 0)),
                pl.BlockSpec((kl, d // 2), lambda b_, h_: (0, 0)),
                pl.BlockSpec((kl, d // 2), lambda b_, h_: (0, 0)),
                pl.BlockSpec((block_q, block_q), lambda b_, h_: (0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, block_q, d), lambda b_, h_: (b_, h_, 0, 0)),
                pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_: (b_, h_, 0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b, h, block_q, d), out_dtype or dtype),
                jax.ShapeDtypeStruct((b, h, block_q, 1), jnp.float32),
            ],
            compiler_params=_compiler_params(
                dimension_semantics=("parallel", "parallel")
            ),
            interpret=interpret,
        )(*inputs, cqs, sqs, cos, sin, tri)
        outs.append(out_i)
        lses.append(lse_i)
    if nq == 1:
        return outs[0], lses[0]
    return jnp.concatenate(outs, axis=2), jnp.concatenate(lses, axis=2)


def _flash_fwd_blocked_qkv(qkv, rope, sm_scale, block_q, interpret):
    return _flash_fwd_blocked(
        None, None, None, rope, sm_scale, block_q, interpret, qkv=qkv
    )


# ---------------------------------------------------------------------------
# Blocked-causal COMBINED backward: one pallas call per (batch, head),
# k-block-outer / q-sub-block-inner, dq + dk + dv in one pass
# ---------------------------------------------------------------------------
#
# The grid-style dK/dV + dQ kernels below recompute the score and dp matmuls
# in BOTH kernels (7 dots per block pair) and pay per-(i,j) grid bookkeeping;
# a round-4 train-step trace (experiments/trace_train.py) measured them at
# 13.7 ms/layer-batch plus 3.3 ms for the separate delta pass — 4.7x the
# blocked forward's 3.59 ms for 3.5x the FLOPs. This kernel applies the
# forward's round-3 treatment to the backward: ONE invocation per (b, h)
# with a statically unrolled causal loop (k blocks outer, q sub-blocks
# inner), sharing the recomputed p and dp across dq/dk/dv (5 dots per pair),
# computing delta = sum(do*out) in-kernel from operands it already reads,
# and (on the stacked path) consuming the (b, 3, h, s, d) qkv residual and
# emitting a stacked (b, 3, h, s, d) dqkv via index-mapped block specs so
# the fused-projection backward sees slice-copy-free operands.
#
# Scale folding (mirrors the forward): q is roped through tables pre-scaled
# by sm_scale*LOG2E, so base-2 scores are a plain dot and
#   dk_roped = sm_scale * ds^T @ R(q) = LN2 * ds^T @ q_scaled
#   dq_roped = sm_scale * ds   @ R(k)
# with the counter-rotations using the UNSCALED tables.


def _bwd_kernel_blocked(*refs, nk, ratio, bq_sub, bk, stacked, sm_scale):
    (q_ref, k_ref, v_ref, do_ref, out_ref, lse_ref,
     cos_ref, sin_ref) = refs[:8]
    if stacked:
        (dqkv_ref,) = refs[8:]
    else:
        dq_ref, dk_ref, dv_ref = refs[8:]
    lead = (0, 0, 0) if stacked else (0, 0)
    s_len = q_ref.shape[-2]
    nqs = s_len // bq_sub
    lam = jnp.float32(sm_scale * LOG2E)

    # q sub-blocks roped lazily through scale-folded tables derived from the
    # unscaled ones in-kernel (separate scaled inputs would cost another
    # s x d/2 x 2 fp32 of VMEM; full-s rope would hold s x d fp32
    # intermediates — per-block keeps transients at bq_sub x d)
    q_s = [None] * nqs

    def q_rows(i):
        if q_s[i] is None:
            rows = slice(i * bq_sub, (i + 1) * bq_sub)
            q_s[i] = _rope_rows(
                q_ref[lead][rows], cos_ref[rows] * lam, sin_ref[rows] * lam
            ).astype(q_ref.dtype)
        return q_s[i]

    do = do_ref[0, 0]
    # delta = sum(do*out) per row, computed lazily per q sub-block (a full-s
    # fp32 product would transiently hold s x d fp32)
    delta_c = [None] * nqs

    def delta_rows(i):
        if delta_c[i] is None:
            rows = slice(i * bq_sub, (i + 1) * bq_sub)
            delta_c[i] = jnp.sum(
                do[rows].astype(jnp.float32)
                * out_ref[0, 0][rows].astype(jnp.float32),
                axis=-1, keepdims=True,
            )
        return delta_c[i]

    lse2 = lse_ref[0, 0].astype(jnp.float32) * LOG2E  # base-2

    dq = [None] * nqs
    for j in range(nk):
        k_r = _rope_rows(
            k_ref[lead][j * bk:(j + 1) * bk],
            cos_ref[j * bk:(j + 1) * bk], sin_ref[j * bk:(j + 1) * bk],
        ).astype(k_ref.dtype)
        v_j = v_ref[lead][j * bk:(j + 1) * bk]
        dk_acc = dv_acc = None
        for i in range(j * ratio, nqs):
            rows = slice(i * bq_sub, (i + 1) * bq_sub)
            s2 = jax.lax.dot_general(
                q_rows(i), k_r, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            t = i - j * ratio
            if t < ratio:  # diagonal-straddling sub-block: iota mask with
                # the static row offset (cheaper in VMEM than a mask input)
                r_io = t * bq_sub + jax.lax.broadcasted_iota(
                    jnp.int32, (bq_sub, bk), 0
                )
                c_io = jax.lax.broadcasted_iota(jnp.int32, (bq_sub, bk), 1)
                s2 = jnp.where(r_io >= c_io, s2, NEG_INF)
            p = jnp.exp2(s2 - lse2[rows])
            do_i = do[rows]
            pv = jax.lax.dot_general(
                p.astype(do.dtype), do_i, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dv_acc = pv if dv_acc is None else dv_acc + pv
            dp = jax.lax.dot_general(
                do_i, v_j, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = (p * (dp - delta_rows(i))).astype(q_ref.dtype)
            dk_i = jax.lax.dot_general(
                ds, q_rows(i), (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dk_acc = dk_i if dk_acc is None else dk_acc + dk_i
            dq_i = jax.lax.dot(ds, k_r, preferred_element_type=jnp.float32)
            dq[i] = dq_i if dq[i] is None else dq[i] + dq_i
        cols = slice(j * bk, (j + 1) * bk)
        dk_out = _rope_rows_t(dk_acc * LN2, cos_ref[cols], sin_ref[cols])
        if stacked:
            dqkv_ref[0, 1, 0, cols] = dk_out.astype(dqkv_ref.dtype)
            dqkv_ref[0, 2, 0, cols] = dv_acc.astype(dqkv_ref.dtype)
        else:
            dk_ref[0, 0, cols] = dk_out.astype(dk_ref.dtype)
            dv_ref[0, 0, cols] = dv_acc.astype(dv_ref.dtype)
    for i in range(nqs):
        rows = slice(i * bq_sub, (i + 1) * bq_sub)
        # dq was accumulated against R(k) (unscaled tables)
        dq_out = _rope_rows_t(dq[i] * sm_scale, cos_ref[rows], sin_ref[rows])
        if stacked:
            dqkv_ref[0, 0, 0, rows] = dq_out.astype(dqkv_ref.dtype)
        else:
            dq_ref[0, 0, rows] = dq_out.astype(dq_ref.dtype)


def _flash_bwd_blocked(
    q, k, v, do, out, lse, rope, sm_scale, bk, bq_sub, interpret, qkv=None, do_stacked_out=False
):
    """Combined blocked-causal backward. Either separate (b, h, s, d) q/k/v
    (returns dq, dk, dv) or stacked ``qkv`` (b, 3, h, s, d) with
    ``do_stacked_out`` (returns dqkv)."""
    stacked = qkv is not None
    if stacked:
        b, _, h, s, d = qkv.shape
        dtype = qkv.dtype
    else:
        b, h, s, d = q.shape
        dtype = q.dtype
    nk = s // bk
    ratio = bk // bq_sub
    cos, sin = rope
    # single-buffer the big (s, d) slabs: Mosaic's default double-buffering
    # across grid steps costs 2x VMEM on every operand, which blows the 16M
    # scoped limit at the 7B shape (measured 19.3M); per-invocation compute
    # (~4 GFLOP) dwarfs the unoverlapped slab fetch
    if stacked:
        qkv_specs = [
            _single_buffered((1, 1, 1, s, d), lambda b_, h_: (b_, 0, h_, 0, 0)),
            _single_buffered((1, 1, 1, s, d), lambda b_, h_: (b_, 1, h_, 0, 0)),
            _single_buffered((1, 1, 1, s, d), lambda b_, h_: (b_, 2, h_, 0, 0)),
        ]
        qkv_inputs = (qkv, qkv, qkv)
    else:
        spec = _single_buffered((1, 1, s, d), lambda b_, h_: (b_, h_, 0, 0))
        qkv_specs = [spec, spec, spec]
        qkv_inputs = (q, k, v)
    bhsd = _single_buffered((1, 1, s, d), lambda b_, h_: (b_, h_, 0, 0))
    rows = _single_buffered((s, d // 2), lambda b_, h_: (0, 0))
    if do_stacked_out:
        out_specs = [_single_buffered((1, 3, 1, s, d), lambda b_, h_: (b_, 0, h_, 0, 0))]
        out_shape = [jax.ShapeDtypeStruct((b, 3, h, s, d), dtype)]
    else:
        out_specs = [bhsd, bhsd, bhsd]
        out_shape = [jax.ShapeDtypeStruct((b, h, s, d), dtype)] * 3
    res = pl.pallas_call(
        functools.partial(
            _bwd_kernel_blocked, nk=nk, ratio=ratio, bq_sub=bq_sub, bk=bk,
            stacked=stacked, sm_scale=float(sm_scale),
        ),
        grid=(b, h),
        in_specs=qkv_specs + [
            bhsd,  # do
            bhsd,  # out
            # (s, 1) pads to (s, 128) lanes under TPU tiling — 1M fp32, so
            # single-buffer it like the slabs
            _single_buffered((1, 1, s, 1), lambda b_, h_: (b_, h_, 0, 0)),
            rows, rows,
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel")
        ),
        interpret=interpret,
    )(*qkv_inputs, do, out, lse, cos, sin)
    return res[0] if do_stacked_out else tuple(res)


# VMEM budget for the combined backward: resident operands + the (bq_sub, bk)
# fp32 score/p/dp/ds transients. (256, 512) was originally forced by
# Mosaic's 16 MB default budget; with the raised limit, (512, 512) and
# (512, 1024) are legal but measure FLAT on the full train step at s=2048
# and within noise at s=4096 (experiments/ab_flash_bwd.py) — per-block
# bookkeeping is not what bounds this kernel — so the proven config stays.
_BWD_BQ_SUB = 256
_BWD_BK = 512
# the combined backward keeps ALL slabs + dq accumulators resident per
# invocation (s=4096/d=128 measures 21.4M scoped), which overflowed Mosaic's
# 16 MB default budget beyond s=2048; under the raised vmem_limit_bytes the
# envelope extends to s=8192, measured −9% (s=4096) / −15% (s=8192, with the
# forward extension) on the full train step vs the grid kernels
# (experiments/ab_flash_bwd.py, v5e). Beyond this — or whenever the env
# knob shrinks the budget below what the wide envelope charges (per-shape
# thresholds derived from the 21.4 MB s=4096 anchor; see _seq_envelope) —
# the grid kernels serve.
_BWD_MB_PER_SXD = 21.4 / (4096 * 128)
_BWD_MAX_SEQ_X_DIM = _seq_envelope(
    _BWD_MB_PER_SXD, (8192 * 128, 4096 * 128), 2048 * 128
)


def _bwd_blocks(block_q):
    """(bk, bq_sub) the combined backward actually uses for a forward block
    size ``block_q``."""
    bk = min(_BWD_BK, block_q)
    return bk, min(_BWD_BQ_SUB, bk)


def _use_blocked_bwd(s, d, causal, rope, block_q, block_k):
    bk, bq_sub = _bwd_blocks(block_q)
    return (
        _use_blocked(s, d, causal, rope, block_q, block_k)
        and s * d <= _BWD_MAX_SEQ_X_DIM
        and s % bk == 0
        and bk % bq_sub == 0
    )


# ---------------------------------------------------------------------------
# Backward kernels (grid style — non-causal / no-rope / ring per-hop paths)
# ---------------------------------------------------------------------------


def _bwd_dkv_kernel(*refs, sm_scale, causal, block_q, block_k, num_q_blocks, rope):
    if rope:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         cq_ref, sq_ref, ck_ref, sk_ref, dk_ref, dv_ref, dk_scr, dv_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
    j = pl.program_id(2)  # k block
    i = pl.program_id(3)  # q block (innermost)

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    if causal:
        contributes = ((i + 1) * block_q - 1) >= j * block_k
        fully_below = (i * block_q) >= ((j + 1) * block_k - 1)
    else:
        contributes = fully_below = None

    def _accum(masked):
        # bf16 MXU inputs, fp32 accumulate/softmax, base-2 logits with the
        # base change folded into the fp32 post-matmul scale (see _fwd_kernel
        # note). ds omits the sm_scale factor; the dk finalize multiplies it
        # back in once per k block.
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        if rope:
            q = _rope_rows(q, cq_ref[...], sq_ref[...]).astype(q_ref.dtype)
            k = _rope_rows(k, ck_ref[...], sk_ref[...]).astype(k_ref.dtype)
        lse2 = lse_ref[0, 0].astype(jnp.float32) * LOG2E  # (block_q, 1), base-2
        delta = delta_ref[0, 0].astype(jnp.float32)  # (block_q, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * (sm_scale * LOG2E)
        if masked:
            rows = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp2(s - lse2)  # softmax probs
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)  # natural-units dL/ds except the sm_scale factor
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    _dispatch_causal(causal, contributes, fully_below, _accum)

    @pl.when(i == num_q_blocks - 1)
    def _finalize():
        dk = dk_scr[:] * sm_scale  # ds omitted sm_scale in the accumulation
        if rope:
            # dk was accumulated w.r.t. the ROPED k — counter-rotate back
            dk = _rope_rows_t(dk, ck_ref[...], sk_ref[...])
        dk_ref[0, 0] = dk.astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(*refs, sm_scale, causal, block_q, block_k, num_k_blocks, rope):
    if rope:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         cq_ref, sq_ref, ck_ref, sk_ref, dq_ref, dq_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr) = refs
    i = pl.program_id(2)  # q block
    j = pl.program_id(3)  # k block (innermost)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    if causal:
        last_j = jnp.minimum(((i + 1) * block_q - 1) // block_k, num_k_blocks - 1)
        contributes = ((i + 1) * block_q - 1) >= j * block_k
        fully_below = (i * block_q) >= ((j + 1) * block_k - 1)
    else:
        last_j = num_k_blocks - 1
        contributes = fully_below = None

    def _accum(masked):
        # bf16 MXU inputs, fp32 accumulate/softmax, base-2 logits with the
        # base change folded into the fp32 post-matmul scale (see _fwd_kernel
        # note). ds omits the sm_scale factor; the finalize multiplies it
        # back in once per q block.
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        if rope:
            q = _rope_rows(q, cq_ref[...], sq_ref[...]).astype(q_ref.dtype)
            k = _rope_rows(k, ck_ref[...], sk_ref[...]).astype(k_ref.dtype)
        lse2 = lse_ref[0, 0].astype(jnp.float32) * LOG2E  # (block_q, 1), base-2
        delta = delta_ref[0, 0].astype(jnp.float32)  # (block_q, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * (sm_scale * LOG2E)
        if masked:
            rows = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp2(s - lse2)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dq_scr[:] += jax.lax.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32
        )

    _dispatch_causal(causal, contributes, fully_below, _accum)

    @pl.when(j == last_j)
    def _finalize():
        dq = dq_scr[:] * sm_scale  # ds omitted sm_scale in the accumulation
        if rope:
            # dq was accumulated w.r.t. the ROPED q — counter-rotate back
            dq = _rope_rows_t(dq, cq_ref[...], sq_ref[...])
        dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _flash_bwd(res, do_bhsd, sm_scale, causal, block_q, block_k, interpret):
    q, k, v, out, lse, rope = res
    delta = jnp.sum(
        do_bhsd.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True
    )  # (b, h, s, 1)
    return _flash_bwd_parts(
        q, k, v, do_bhsd, lse, delta, rope, sm_scale, causal, block_q, block_k,
        interpret,
    )


def _flash_bwd_parts(
    q, k, v, do_bhsd, lse, delta, rope, sm_scale, causal, block_q, block_k, interpret
):
    """dq/dk/dv kernels given the (possibly GLOBAL, e.g. ring-combined) LSE
    and delta = sum(do*out) — the flash decomposition makes per-k-block
    gradient contributions independent once those per-row statistics are
    fixed, which is what lets ring attention run these kernels per ring hop."""
    b, h, s, d = q.shape
    nq, nk = s // block_q, s // block_k

    qspec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, j, i: (b_, h_, i, 0))
    kspec = pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, j, i: (b_, h_, j, 0))
    rowspec = pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, j, i: (b_, h_, i, 0))
    rope_specs_ji, rope_inputs = _rope_io(rope, block_q, block_k, d, "ji")
    dkv_in_specs = [qspec, kspec, kspec, qspec, rowspec, rowspec] + rope_specs_ji
    dkv_inputs = [q, k, v, do_bhsd, lse, delta] + rope_inputs
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel,
            sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, num_q_blocks=nq,
            rope=rope is not None,
        ),
        grid=(b, h, nk, nq),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, j, i: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, j, i: (b_, h_, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(*dkv_inputs)

    qspec2 = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    kspec2 = pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i, j: (b_, h_, j, 0))
    rowspec2 = pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, i, 0))
    rope_specs_ij, rope_inputs_ij = _rope_io(rope, block_q, block_k, d, "ij")
    dq_in_specs = [qspec2, kspec2, kspec2, qspec2, rowspec2, rowspec2] + rope_specs_ij
    dq_inputs = [q, k, v, do_bhsd, lse, delta] + rope_inputs_ij
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel,
            sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, num_k_blocks=nk,
            rope=rope is not None,
        ),
        grid=(b, h, nq, nk),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(*dq_inputs)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public API with custom VJP ((B, S, n, d) layout, matching modeling.attention)
# ---------------------------------------------------------------------------


def _fwd_dispatch(q, k, v, rope, sm_scale, causal, block_q, block_k, interpret):
    # GQA-native: k/v may carry kv_heads < heads; the kernels serve each kv
    # group's queries from the resident grouped K/V block (h -> h // rep
    # index maps) instead of a materialized repeated copy
    kv_rep = q.shape[1] // k.shape[1]
    if _use_blocked(q.shape[2], q.shape[3], causal, rope, block_q, block_k):
        return _flash_fwd_blocked(
            q, k, v, rope, sm_scale, block_q, interpret, kv_rep=kv_rep
        )
    return _flash_fwd(
        q, k, v, rope, sm_scale, causal, block_q, block_k, interpret,
        kv_rep=kv_rep,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, rope, sm_scale, causal, block_q, block_k):
    out, _ = _fwd_dispatch(q, k, v, rope, sm_scale, causal, block_q, block_k, _use_interpret())
    return out


def _flash_fwd_rule(q, k, v, rope, sm_scale, causal, block_q, block_k):
    out, lse = _fwd_dispatch(q, k, v, rope, sm_scale, causal, block_q, block_k, _use_interpret())
    return out, (q, k, v, out, lse, rope)


def _flash_bwd_rule(sm_scale, causal, block_q, block_k, res, do):
    q, k, v, out, lse, rope = res
    kv_rep = q.shape[1] // k.shape[1]
    if kv_rep > 1:
        # backward serves the repeated layout (the bwd kernels accumulate dk
        # per full head); group gradients are the exact sum over the group
        b, kvh, s, d = k.shape
        k = jnp.broadcast_to(k[:, :, None], (b, kvh, kv_rep, s, d)).reshape(
            b, kvh * kv_rep, s, d
        )
        v = jnp.broadcast_to(v[:, :, None], (b, kvh, kv_rep, s, d)).reshape(
            b, kvh * kv_rep, s, d
        )
        res = (q, k, v, out, lse, rope)
    if _use_blocked_bwd(q.shape[2], q.shape[3], causal, rope, block_q, block_k):
        bk, bq_sub = _bwd_blocks(block_q)
        dq, dk, dv = _flash_bwd_blocked(
            q, k, v, do, out, lse, rope, sm_scale, bk, bq_sub, _use_interpret(),
        )
    else:
        dq, dk, dv = _flash_bwd(res, do, sm_scale, causal, block_q, block_k, _use_interpret())
    if kv_rep > 1:
        b, h, s, d = dk.shape
        dk = dk.reshape(b, h // kv_rep, kv_rep, s, d).sum(axis=2)
        dv = dv.reshape(b, h // kv_rep, kv_rep, s, d).sum(axis=2)
    drope = None if rope is None else jax.tree.map(jnp.zeros_like, rope)
    return dq, dk, dv, drope


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention_hm(
    q,
    k,
    v,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = 1024,
    block_k: int = 1024,
    rope=None,
):
    """Head-major entry: q/k/v and the result are (batch, heads, seq, head_dim).

    The kernels are head-major internally, so this skips the (B,S,H,D) <->
    (B,H,S,D) boundary transposes entirely. Callers that can produce q/k/v
    head-major (modeling's einsum projection) should use this; measured
    ~0.32 ms/layer/sample on the v5e 7B-shape bench vs the transposing
    wrapper. Untileable shapes fall back through the (B,S,H,D) path.

    GQA-NATIVE: k/v may carry kv_heads < heads (heads % kv_heads == 0) —
    the forward kernels serve each kv group's queries from the resident
    grouped K/V block instead of a materialized repeated copy (group-factor
    less K/V HBM traffic; reference serves GQA natively the same way via
    head-group splitting, galvatron/core/tensor_parallel/transformer.py:
    679-708)."""
    b, h, s, d = q.shape
    if h % k.shape[1]:
        raise ValueError(f"heads {h} not divisible by kv_heads {k.shape[1]}")
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(d))
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if not flash_tileable(s, block_q) or not flash_tileable(s, block_k):
        rep = h // k.shape[1]
        if rep > 1:  # the (B,S,H,D) fallback expects repeated K/V
            kvh = k.shape[1]
            k = jnp.broadcast_to(k[:, :, None], (b, kvh, rep, s, d)).reshape(b, h, s, d)
            v = jnp.broadcast_to(v[:, :, None], (b, kvh, rep, s, d)).reshape(b, h, s, d)
        out = flash_attention(
            jnp.transpose(q, (0, 2, 1, 3)),
            jnp.transpose(k, (0, 2, 1, 3)),
            jnp.transpose(v, (0, 2, 1, 3)),
            causal=causal, sm_scale=sm_scale, block_q=block_q, block_k=block_k,
            rope=rope,
        )
        return jnp.transpose(out, (0, 2, 1, 3))
    return _flash(q, k, v, rope, sm_scale, causal, block_q, block_k)


def flash_tileable(s: int, block: int = 1024) -> bool:
    """True when a (…, s, …) shape takes the kernel path (no einsum
    fallback). The ONE tileability predicate: both wrappers and modeling's
    head-major gate key on it, so they cannot drift apart."""
    return s % min(block, s) == 0


def flash_attention(
    q,
    k,
    v,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = 1024,
    block_k: int = 1024,
    rope=None,
):
    """q, k, v: (batch, seq, heads, head_dim); returns same layout.

    GQA callers repeat kv heads first (modeling._repeat_kv). Tiles of
    (block_q, block_k); shapes that don't tile fall back to the einsum path.

    ``rope``: optional (cos, sin) tables, each (seq, head_dim/2) fp32 — the
    rotate-half rotary embedding is applied to q/k blocks INSIDE the kernels
    (forward and both backward passes, with the transpose rotation mapping
    dq/dk back to raw coordinates). Fusing it removes the HBM round-trip of
    materialized roped q/k that a separate apply_rope costs (~0.27 ms/layer/
    sample on the v5e LLaMA-7B-shape bench).

    Defaults tuned on v5e (b8 x s2048 x h32 x d128) with bf16 MXU inputs:
    1024/1024 is fastest end-to-end; fp32 operands would run the MXU at a
    fraction of the bf16 rate (softmax/accumulation stay fp32).
    """
    b, s, n, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(d))
    if s == 1:
        # one query row: the tiled kernels degenerate to block 1 with zero
        # reuse — the dot-product decode path is exact and cheaper. With a
        # single same-length key, causal and full masks coincide.
        if rope is not None:
            from galvatron_tpu.models import modeling

            q = modeling.apply_rope(q, *rope)
            k = modeling.apply_rope(k, *rope)
        return decode_attention(
            q, k, v, q_offset=k.shape[1] - 1, sm_scale=sm_scale
        )
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if not flash_tileable(s, block_q) or not flash_tileable(s, block_k):
        from galvatron_tpu.models import modeling

        if rope is not None:
            q = modeling.apply_rope(q, *rope)
            k = modeling.apply_rope(k, *rope)
        # honor the caller's mask and scale (attention_xla divides by sqrt(d),
        # so pre-scale q to express an arbitrary sm_scale)
        q = q * jnp.asarray(sm_scale * np.sqrt(d), q.dtype)
        cfg = modeling.ModelConfig(num_heads=n, hidden_size=n * d, attn_impl="xla", causal=causal)
        return modeling.attention_xla(q, k, v, cfg)
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    out = _flash(qt, kt, vt, rope, sm_scale, causal, block_q, block_k)
    return jnp.transpose(out, (0, 2, 1, 3))


def decode_attention(q, k, v, q_offset=0, sm_scale=None):
    """Single-query attention for KV-cache decode (q_len == 1).

    q: (B, 1, n, d); k/v: (B, S, kv, d), n % kv == 0. Flash tiling buys
    nothing for one query row — there is no q x k tile reuse, and the
    (block_q, block_k) kernels cannot even launch on q_len 1. The decode
    step is a pure dot-product: two einsums and a masked fp32 softmax.

    GQA-native: kv heads are NOT repeated. The group dim ``g = n // kv``
    rides inside the einsum (q reshaped head-dim (kv, g), kv-major to match
    modeling._repeat_kv's interleave), so the KV cache — the dominant HBM
    traffic of a decode step — is read once instead of materialized g x.

    ``q_offset``: absolute position of the query token, scalar or (B,)
    (continuous batching: each slot at its own depth). Keys at positions
    > offset are masked; cache tails past the write point never leak in.
    """
    b, q_len, n, d = q.shape
    assert q_len == 1, f"decode_attention requires q_len == 1, got {q_len}"
    kv = k.shape[2]
    g = n // kv
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(d))
    qg = q[:, 0].reshape(b, kv, g, d)
    scores = jnp.einsum("bkgh,bskh->bkgs", qg, k).astype(jnp.float32)
    scores = scores * sm_scale
    k_pos = jnp.arange(k.shape[1])
    allowed = k_pos[None] <= jnp.reshape(jnp.asarray(q_offset), (-1, 1))
    scores = jnp.where(allowed[:, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgs,bskh->bkgh", probs, v)
    return out.reshape(b, 1, n, d)


# ---------------------------------------------------------------------------
# Paged decode: K/V live in a block pool, addressed through block tables
# ---------------------------------------------------------------------------


def _paged_decode_kernel(
    tables_ref,  # scalar-prefetch (B, mb) int32 — logical block j of row b
    off_ref,  # scalar-prefetch (B,) int32 — absolute position of the query
    q_ref,  # (1, 1, g, d) block of (B, kv, g, d)
    k_ref,  # (1, bs, 1, d) page of (N, bs, kv, d), chosen by the index map
    v_ref,
    o_ref,  # (1, 1, g, d)
    m_ref,  # VMEM (g, 1) fp32 running max
    l_ref,  # VMEM (g, 1) fp32 running denominator
    acc_ref,  # VMEM (g, d) fp32 running numerator
    *,
    sm_scale: float,
    block_size: int,
    max_blocks: int,
):
    """One grid step = one (row, kv head, logical block): FlashAttention-style
    online softmax over the row's pages. The page lives wherever the block
    table says — the index map resolves ``tables_ref[b, j]`` at prefetch time,
    so the DMA engine streams exactly the pages this row owns and the gather
    is never materialized in HBM."""
    bi = pl.program_id(0)
    ji = pl.program_id(2)

    @pl.when(ji == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # skip pages entirely past the query position (their scores would all
    # mask out anyway; the predicate saves the VPU work)
    @pl.when(ji * block_size <= off_ref[bi])
    def _accum():
        qb = q_ref[0, 0].astype(jnp.float32)  # (g, d)
        kb = k_ref[0, :, 0].astype(jnp.float32)  # (bs, d)
        vb = v_ref[0, :, 0].astype(jnp.float32)
        s = jnp.dot(qb, kb.T) * sm_scale  # (g, bs)
        k_pos = ji * block_size + jax.lax.broadcasted_iota(jnp.int32, (1, block_size), 1)
        s = jnp.where(k_pos <= off_ref[bi], s, NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        m_ref[...] = m_new
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jnp.dot(p, vb)

    @pl.when(ji == max_blocks - 1)
    def _finalize():
        o_ref[0, 0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def paged_decode_attention(
    q, k_pages, v_pages, block_tables, q_offset, sm_scale=None, impl: str = "auto"
):
    """``decode_attention`` over paged K/V: one query token per row, keys and
    values gathered through a block table instead of a contiguous cache row.

    q: (B, 1, n, d); k_pages/v_pages: (num_blocks, block_size, kv, d) — the
    serving block pool for ONE layer; block_tables: (B, max_blocks) int32
    mapping row b's logical block j to a pool block (entries past a row's
    reserved capacity point at the null block and are masked by ``q_offset``);
    q_offset: (B,) absolute query positions.

    ``impl``: 'xla' gathers pages into a contiguous (B, S, kv, d) view and
    delegates to :func:`decode_attention` — bit-identical to the slot
    engine's decode when block_size divides its max_seq_len, which is what
    the paged/slot parity tests pin. 'pallas' runs the online-softmax kernel
    above (per-page DMA via scalar-prefetched tables, no materialized
    gather; interpret mode on CPU). 'auto' picks pallas on TPU, xla
    elsewhere.
    """
    b, q_len, n, d = q.shape
    assert q_len == 1, f"paged_decode_attention requires q_len == 1, got {q_len}"
    num_blocks, block_size, kv, _ = k_pages.shape
    max_blocks = block_tables.shape[1]
    g = n // kv
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(d))
    if impl not in ("auto", "xla", "pallas"):
        raise ValueError(f"impl must be auto|xla|pallas, got {impl!r}")
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    offsets = jnp.broadcast_to(jnp.asarray(q_offset, jnp.int32).reshape(-1), (b,))

    if impl == "xla":
        k = k_pages[block_tables].reshape(b, max_blocks * block_size, kv, d)
        v = v_pages[block_tables].reshape(b, max_blocks * block_size, kv, d)
        return decode_attention(q, k, v, q_offset=offsets, sm_scale=sm_scale)

    qg = q[:, 0].reshape(b, kv, g, d)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, kv, max_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, g, d), lambda bi, hi, ji, tables, off: (bi, hi, 0, 0)),
            pl.BlockSpec(
                (1, block_size, 1, d),
                lambda bi, hi, ji, tables, off: (tables[bi, ji], 0, hi, 0),
            ),
            pl.BlockSpec(
                (1, block_size, 1, d),
                lambda bi, hi, ji, tables, off: (tables[bi, ji], 0, hi, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, g, d), lambda bi, hi, ji, tables, off: (bi, hi, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _paged_decode_kernel,
            sm_scale=float(sm_scale),
            block_size=block_size,
            max_blocks=max_blocks,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kv, g, d), q.dtype),
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=_use_interpret(),
    )(block_tables.astype(jnp.int32), offsets, qg, k_pages, v_pages)
    return out.reshape(b, 1, n, d)
