"""Per-channel symmetric int8 weight quantization for the serving path.

Decode is bandwidth-bound: every generated token re-reads the full weight
set, so int8 weights are a near-linear tokens/s win and halve the HBM a
model holds (LLM.int8 — Dettmers et al. 2022 — absmax per-channel recipe,
weight-only variant: activations stay in the compute dtype).

The recipe, per weight W stored ``(in, out…)`` (every dense weight in this
repo contracts over axis 0 — modeling._dense_init):

  scale[c] = max(|W[:, c]|) / 127          (absmax, one per output channel)
  Q[:, c]  = round(W[:, c] / scale[c])     (int8; zero-point 0 — symmetric)

and the matmul dequantizes IN the kernel: ``y = (x · Q) * scale`` with an
fp32 accumulator (``preferred_element_type``), so the int8→compute-dtype
convert fuses into the GEMM and the wide weight tensor is read at 1 byte
per element. int8 values (|q| ≤ 127) are exactly representable in bf16, so
the convert itself is lossless; the only error is the per-channel rounding,
which the engine parity-gates against a declared max-abs logit drift.

``QuantTensor`` is a pytree (NamedTuple) that impersonates the weight array
just enough for the modeling seams: ``.astype`` is the identity (dequant
happens inside the matmul, not ahead of it), ``.shape``/``.ndim`` answer
for the logical (unquantized) weight. Dispatch lives at the TP projection
seams (modeling._proj_up/_proj_down, qkv_project, attn_output, lm_head) —
the same seams the collective-matmul overlap owns — via an isinstance
check, so training code never sees a branch.

Quantization happens ONCE, at engine load / ``cli warmup``
(``--serve_quant int8``); the decode step never touches fp weights.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class QuantParityError(ValueError):
    """Quantized logits drifted past the declared bound (--quant_drift_max)."""


class QuantTensor(NamedTuple):
    """int8 weight + per-output-channel f32 scales.

    ``q`` keeps the stored weight's exact shape ``(in, out…)``; ``scale``
    has shape ``q.shape[1:]`` (one scale per output channel, broadcasting
    over the contraction axis). NamedTuple ⇒ automatically a pytree, so
    quantized params flow through jit/eval_shape/tree_map unchanged.
    """

    q: Any      # int8, shape (in, out…)
    scale: Any  # float32, shape (out…)

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def dtype(self):
        # the LOGICAL dtype is "whatever the matmul computes in"; report the
        # storage dtype so memory accounting (size × itemsize) stays honest
        return self.q.dtype

    @property
    def size(self):
        return self.q.size

    def astype(self, dtype):
        """Identity: the modeling seams cast weights to the activation dtype
        right before the matmul — for a QuantTensor the dequantize happens
        inside ``qeinsum`` instead, so the cast is a no-op."""
        del dtype
        return self

    def dequantize(self, dtype=jnp.float32):
        """Materialize the fp weight (fallback paths only — e.g. the
        collective-matmul overlap ring, which streams fp shards)."""
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)


def quantize_int8(w) -> QuantTensor:
    """Symmetric per-channel absmax quantization of one stored weight.

    Contraction axis is ALWAYS axis 0 in this repo's weight layout
    (modeling._dense_init: ``(in, out…)``; the blocked wqkv's (h, 3, n·hd)
    trailing dims are all output channels). All-zero channels get scale 0
    and quantize to exact zeros — the dequantized matmul contribution is
    exactly 0.0, not NaN.
    """
    w32 = jnp.asarray(w, jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=0)          # (out…)
    scale = absmax / 127.0
    safe = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(w32 / safe), -127, 127).astype(jnp.int8)
    return QuantTensor(q=q, scale=scale.astype(jnp.float32))


def _out_suffix_ok(subscripts: str, qw: QuantTensor) -> None:
    """The scale broadcast below relies on every seam's einsum putting the
    weight's output letters LAST in the output, in order — true for all of
    qkv_project / attn_output / _proj_up / _proj_down / lm_head. Fail
    loudly (at trace time, free at runtime) if a new caller breaks that."""
    inputs, out = subscripts.replace("...", "").split("->")
    x_sub, w_sub = inputs.split(",")
    w_out = "".join(c for c in w_sub if c not in x_sub)
    if not out.endswith(w_out):
        raise ValueError(
            f"qeinsum needs the weight's output axes trailing in the "
            f"output ({subscripts!r}: weight-only axes {w_out!r} vs "
            f"output {out!r})"
        )
    if qw.scale.ndim != len(w_out):
        raise ValueError(
            f"scale rank {qw.scale.ndim} != weight output rank "
            f"{len(w_out)} for {subscripts!r}"
        )


def qeinsum(subscripts: str, x, qw: QuantTensor):
    """Dequantize-in-kernel einsum: ``einsum(x, q)`` with an fp32
    accumulator, then the per-channel scale applied to the (narrow) output.

    The int8→x.dtype convert is exact (|q| ≤ 127 fits bf16's mantissa) and
    fuses into the GEMM on TPU, so HBM reads the weight at int8 width; the
    scale multiply touches only the output activations — O(out) work, not
    O(in·out).
    """
    _out_suffix_ok(subscripts, qw)
    y = jnp.einsum(
        subscripts, x, qw.q.astype(x.dtype),
        preferred_element_type=jnp.float32,
    )
    return (y * qw.scale).astype(x.dtype)


def qmatmul(x, qw: QuantTensor):
    """``x @ w`` for a 2-D quantized weight (lm_head / interleaved qkv)."""
    y = jnp.matmul(
        x, qw.q.astype(x.dtype), preferred_element_type=jnp.float32
    )
    return (y * qw.scale).astype(x.dtype)


# weight keys eligible for quantization, per param sub-dict. Biases, norms,
# and the embedding table (a gather, not a GEMM) stay in the param dtype;
# MoE experts keep fp too (the dispatch einsums contract over the expert
# axis — a different layout contract than the per-channel recipe assumes).
_ATTN_KEYS = ("wqkv", "wo")
_CROSS_KEYS = ("wq", "wkv", "wo")
_MLP_KEYS = ("w13", "w1", "w2")


def quantize_params(params: Dict[str, Any], cfg) -> Dict[str, Any]:
    """Quantize the GEMM weights of a decoder param tree, returning a new
    tree with ``QuantTensor`` leaves at the projection seams and everything
    else untouched. Safe under ``jax.eval_shape`` (AOT program keys derive
    the int8 avals from this same function)."""
    out = dict(params)
    layers = []
    for layer in params.get("layers", []):
        lp = dict(layer)
        for group, keys in (("attn", _ATTN_KEYS), ("cross", _CROSS_KEYS)):
            if group in lp:
                gp = dict(lp[group])
                for k in keys:
                    if k in gp and not isinstance(gp[k], QuantTensor):
                        gp[k] = quantize_int8(gp[k])
                lp[group] = gp
        if "mlp" in lp and getattr(cfg, "moe_experts", 0) == 0:
            mp = dict(lp["mlp"])
            for k in _MLP_KEYS:
                if k in mp and not isinstance(mp[k], QuantTensor):
                    mp[k] = quantize_int8(mp[k])
            lp["mlp"] = mp
        layers.append(lp)
    if layers:
        out["layers"] = layers
    if "head" in params and not getattr(cfg, "tie_word_embeddings", False):
        hp = dict(params["head"])
        if "w" in hp and not isinstance(hp["w"], QuantTensor):
            hp["w"] = quantize_int8(hp["w"])
        out["head"] = hp
    # tied embeddings: lm_head reads the embedding table transposed — the
    # table also feeds a gather, so it stays fp (quantizing it would trade
    # the embed lookup's exactness for one matmul's bandwidth)
    return out


def quantized_fraction(params: Dict[str, Any]) -> float:
    """Fraction of param ELEMENTS now stored int8 (reporting only)."""
    total = quant = 0
    for leaf in jax.tree_util.tree_leaves(
        params, is_leaf=lambda x: isinstance(x, QuantTensor)
    ):
        if isinstance(leaf, QuantTensor):
            quant += int(np.prod(leaf.q.shape))
            total += int(np.prod(leaf.q.shape))
        else:
            total += int(np.prod(leaf.shape))
    return quant / total if total else 0.0


def parity_report(params_fp, params_q, cfg, *, drift_max: float,
                  probe_tokens=None) -> Dict[str, Any]:
    """Measure (not assume) the quantization drift: run one probe forward
    through both param sets and report the max-abs logit drift plus the
    greedy top-1 agreement over every probe position. Raises
    :class:`QuantParityError` when the drift exceeds the declared bound —
    the engine refuses to serve a quantization that left its budget.
    """
    from galvatron_tpu.models import modeling

    if probe_tokens is None:
        s = int(min(16, cfg.max_seq_len))
        probe_tokens = (np.arange(s, dtype=np.int32) * 7 + 1) % cfg.vocab_size
        probe_tokens = probe_tokens[None, :]
    toks = jnp.asarray(probe_tokens, jnp.int32)
    ref = np.asarray(modeling.forward(params_fp, toks, cfg), np.float32)
    got = np.asarray(modeling.forward(params_q, toks, cfg), np.float32)
    drift = float(np.max(np.abs(got - ref)))
    agree = float(np.mean(np.argmax(got, -1) == np.argmax(ref, -1)))
    report = {
        "max_abs_logit_drift": round(drift, 6),
        "greedy_agree_frac": round(agree, 4),
        "drift_bound": float(drift_max),
        "probe_positions": int(toks.shape[-1]),
    }
    if drift > drift_max:
        raise QuantParityError(
            f"int8 logit drift {drift:.4f} exceeds the declared bound "
            f"{drift_max} (greedy agreement {agree:.2%}) — raise "
            f"--quant_drift_max only if the accuracy budget allows it"
        )
    return report
