"""Decomposed collective-matmul for the TP projection seams.

GSPMD partitions a sequence-parallel column-parallel projection as
``all-gather(x over seq) → matmul`` and its row-parallel dual as
``matmul → reduce-scatter(y over seq)`` — both with the collective
*blocking* the GEMM. This module implements the decomposition of
"Overlap Communication with Dependent Computation via Decomposition in
Large Deep Learning Models" (Wang et al., ASPLOS'23): the operand (or the
partial-sum accumulator) circulates the TP ring one chunk per step via
``ppermute`` while the GEMM runs on the chunk already in hand, so the
per-hop transfer hides behind a 1/T-sized matmul instead of serializing
in front of a full one.

Two entry points, einsum-parameterized so one implementation serves the
qkv / MLP-up / attention-out / MLP-down seams (modeling._proj_up /
_proj_down dispatch here when the layer strategy sets ``tp_overlap``):

- :func:`allgather_einsum` — all-gather⊗matmul. ``x`` arrives logically
  seq-sharded over the TP axes (the sp layer boundary layout); each
  device GEMMs the seq chunk it holds against its local weight shard and
  rotates the chunk to its ring neighbor, writing each result at the
  originating chunk's seq offset. Output: full seq, weight-shard dim
  TP-sharded — bit-compatible with GSPMD's gather→matmul.
- :func:`einsum_reducescatter` — matmul⊗reduce-scatter. Each device
  GEMMs one seq chunk per step and adds it into an accumulator that
  rotates the ring; after T steps device i holds the fully-summed chunk
  i (the sp seq-sharded output layout). ``scatter_output=False`` (no sp)
  appends tiled all-gathers to reconstruct the replicated output — the
  gather half of the all-reduce still blocks, but the reduce half is
  pipelined.

Both fall back to a plain ``jnp.einsum`` (GSPMD collectives) whenever the
decomposition cannot apply: single device, T == 1, or a seq / shard dim
the ring chunking does not divide. The ring index over multiple binary
mesh axes is ``jax.lax.axis_index(tuple(tp_axes))`` — row-major, first
axis most significant — and the ``ppermute`` permutation is expressed in
that same flattened index space, so tp_consec=True and False layouts
share one code path. Autodiff needs no custom VJP: shard_map transposes
``ppermute`` to the reverse rotation and ``dynamic_update_slice`` to the
matching slice, which is exactly the dual ring (the transpose of
AG⊗matmul is RS⊗matmul — the parity tests check gradients through both).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from galvatron_tpu import compat


def tp_group_size(mesh, tp_axes: Sequence[str]) -> int:
    """Flattened TP ring size T — the product of the tp mesh-axis extents."""
    t = 1
    for a in tp_axes or ():
        t *= mesh.shape[a]
    return int(t)


def _parse(subscripts: str) -> Tuple[str, str, str]:
    ins, out = subscripts.replace(" ", "").split("->")
    x_sub, w_sub = ins.split(",")
    return x_sub, w_sub, out


def _axis_entry(axes: Tuple[str, ...]):
    """PartitionSpec entry for a (possibly multi-) mesh-axis group."""
    return axes if len(axes) > 1 else axes[0]


def _batch_indivisible(x, mesh, dp: Tuple[str, ...]) -> bool:
    """shard_map needs every sharded dim to divide exactly — bail to the
    plain einsum when the (leading) batch dim does not."""
    return bool(dp) and x.shape[0] % tp_group_size(mesh, dp) != 0


def allgather_einsum(
    subscripts: str,
    x,
    w,
    *,
    mesh,
    dp_axes: Sequence[str],
    tp_axes: Sequence[str],
    w_shard_dim: int,
    seq: str = "s",
):
    """``einsum(subscripts, x, w)`` with the seq all-gather of ``x`` pipelined
    behind the GEMM chunks. ``x``'s first dim is the dp-sharded batch, its
    ``seq`` dim is logically sharded over ``tp_axes``; ``w`` is TP-sharded at
    ``w_shard_dim`` (the column-parallel output dim). Global shapes in, global
    shapes out — only the layout differs from the plain einsum."""
    from galvatron_tpu.parallel.mesh import ambient_or, manual_axis_names
    from jax.sharding import PartitionSpec as P

    x_sub, w_sub, out_sub = _parse(subscripts)
    tp = tuple(tp_axes or ())
    dp = tuple(dp_axes or ())
    T = tp_group_size(mesh, tp)
    seq_x = x_sub.index(seq)
    shard_letter = w_sub[w_shard_dim]
    if (
        T <= 1
        or mesh.devices.size <= 1
        or x.shape[seq_x] % T != 0
        or w.shape[w_shard_dim] % T != 0
        or _batch_indivisible(x, mesh, dp)
    ):
        return jnp.einsum(subscripts, x, w)
    seq_out = out_sub.index(seq)
    shard_out = out_sub.index(shard_letter)
    batch_letter = x_sub[0]

    def spec(sub: str, entries: dict) -> P:
        return P(*[entries.get(c) for c in sub])

    x_entries = {seq: _axis_entry(tp)}
    out_entries = {shard_letter: _axis_entry(tp)}
    if dp:
        x_entries[batch_letter] = _axis_entry(dp)
        out_entries[batch_letter] = _axis_entry(dp)
    w_spec = P(*[_axis_entry(tp) if i == w_shard_dim else None for i in range(w.ndim)])
    s_local = x.shape[seq_x] // T
    perm = [(j, (j + 1) % T) for j in range(T)]

    def local_fn(x_l, w_l):
        idx = jax.lax.axis_index(tp)
        out_shape = [0] * len(out_sub)
        chunk_shape = dict(zip(x_sub, x_l.shape))
        chunk_shape.update(
            {c: d for c, d in zip(w_sub, w_l.shape) if c not in x_sub}
        )
        for i, c in enumerate(out_sub):
            out_shape[i] = chunk_shape[c] if c != seq else x.shape[seq_x]
        out = jnp.zeros(out_shape, dtype=jnp.result_type(x_l.dtype, w_l.dtype))
        chunk = x_l
        for t in range(T):
            # chunk in hand originated at ring position (idx - t); GEMM it
            # while (on hardware, under the latency-hiding scheduler) the
            # next hop's ppermute is in flight
            src = (idx - t) % T
            y_c = jnp.einsum(subscripts, chunk, w_l)
            out = jax.lax.dynamic_update_slice_in_dim(
                out, y_c.astype(out.dtype), src * s_local, axis=seq_out
            )
            if t < T - 1:
                chunk = jax.lax.ppermute(chunk, tp, perm)
        return out

    am = ambient_or(mesh)
    return compat.shard_map(
        local_fn,
        mesh=am,
        in_specs=(spec(x_sub, x_entries), w_spec),
        out_specs=spec(out_sub, out_entries),
        axis_names=manual_axis_names(am),
        check_vma=False,
    )(x, w)


def einsum_reducescatter(
    subscripts: str,
    x,
    w,
    *,
    mesh,
    dp_axes: Sequence[str],
    tp_axes: Sequence[str],
    w_shard_dim: int,
    scatter_output: bool = True,
    seq: str = "s",
):
    """``einsum(subscripts, x, w)`` with the trailing TP reduction pipelined
    behind the GEMM chunks. ``w`` is TP-sharded at ``w_shard_dim`` (the
    row-parallel *contracted* dim, whose letter also indexes ``x``'s
    TP-sharded dim), so each device's einsum yields a partial sum. The
    accumulator ring reduces it seq-chunk by seq-chunk: ``scatter_output=True``
    returns the sp layout (out seq-sharded over tp); ``False`` appends tiled
    all-gathers (minor axis first, matching the row-major ring index) for a
    replicated output — the full all-reduce's gather half."""
    from galvatron_tpu.parallel.mesh import ambient_or, manual_axis_names
    from jax.sharding import PartitionSpec as P

    x_sub, w_sub, out_sub = _parse(subscripts)
    tp = tuple(tp_axes or ())
    dp = tuple(dp_axes or ())
    T = tp_group_size(mesh, tp)
    shard_letter = w_sub[w_shard_dim]
    seq_x = x_sub.index(seq)
    x_shard_dim = x_sub.index(shard_letter)
    if (
        T <= 1
        or mesh.devices.size <= 1
        or x.shape[seq_x] % T != 0
        or x.shape[x_shard_dim] % T != 0
        or _batch_indivisible(x, mesh, dp)
    ):
        return jnp.einsum(subscripts, x, w)
    seq_out = out_sub.index(seq)
    batch_letter = x_sub[0]

    def spec(sub: str, entries: dict) -> P:
        return P(*[entries.get(c) for c in sub])

    x_entries = {shard_letter: _axis_entry(tp)}
    out_entries = {}
    if scatter_output:
        out_entries[seq] = _axis_entry(tp)
    if dp:
        x_entries[batch_letter] = _axis_entry(dp)
        out_entries[batch_letter] = _axis_entry(dp)
    w_spec = P(*[_axis_entry(tp) if i == w_shard_dim else None for i in range(w.ndim)])
    s_global = x.shape[seq_x]
    s_local = s_global // T
    perm = [(j, (j + 1) % T) for j in range(T)]

    def local_fn(x_l, w_l):
        idx = jax.lax.axis_index(tp)

        def partial_chunk(c):
            x_c = jax.lax.dynamic_slice_in_dim(x_l, c * s_local, s_local, axis=seq_x)
            return jnp.einsum(subscripts, x_c, w_l)

        # the accumulator that rests on device i visits i+1, ..., i+T = i;
        # at step t device i therefore contributes its partial for chunk
        # (i - 1 - t) mod T, overlapping the GEMM with the incoming hop
        acc = partial_chunk((idx - 1) % T)
        for t in range(1, T):
            acc = jax.lax.ppermute(acc, tp, perm)
            acc = acc + partial_chunk((idx - 1 - t) % T)
        if not scatter_output:
            # minor (fastest-varying) axis first: each tiled gather then
            # concatenates ring-contiguous seq blocks in index order
            for a in reversed(tp):
                acc = jax.lax.all_gather(acc, a, axis=seq_out, tiled=True)
        return acc

    am = ambient_or(mesh)
    return compat.shard_map(
        local_fn,
        mesh=am,
        in_specs=(spec(x_sub, x_entries), w_spec),
        out_specs=spec(out_sub, out_entries),
        axis_names=manual_axis_names(am),
        check_vma=False,
    )(x, w)
