"""Unified CLI: python -m galvatron_tpu.cli <mode> [--model_size ...] ...

Modes mirror the reference's per-model entry scripts (reference L7,
models/<name>/{train_dist,search_dist,profiler}.py + profile_hardware):

  train             hybrid-parallel training (train_dist equivalent)
  search            parallelism optimization → galvatron_config JSON
  profile           model computation/memory profiling → JSON
  profile-hardware  ICI bandwidth + overlap sweep → JSON

The per-model modules (galvatron_tpu.models.<family>) re-export these with
family defaults, mirroring the reference's directory-per-model layout.
"""

from __future__ import annotations

import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None, model_default: Optional[str] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    mode, rest = argv[0], argv[1:]

    from galvatron_tpu.core.arguments import initialize_galvatron, model_config_from_args

    if mode == "train":
        from galvatron_tpu.core.trainer import train

        ns = initialize_galvatron("train", rest, model_default)
        train(ns)
        return 0

    if mode == "search":
        ns = initialize_galvatron("search", rest, model_default)
        cfg = model_config_from_args(ns)
        from galvatron_tpu.profiling.model import profile_model
        from galvatron_tpu.search.cost_model import ProfiledHardware
        from galvatron_tpu.search.search_engine import SearchEngine, SearchSpace
        from galvatron_tpu.utils.config_utils import (
            load_profiled_hardware,
            load_profiled_model,
        )

        if bool(ns.time_profile_path) != bool(ns.memory_profile_path):
            print(
                "error: --time_profile_path and --memory_profile_path must be "
                "given together (got only one; refusing to silently re-profile)"
            )
            return 2
        if ns.time_profile_path and ns.memory_profile_path:
            costs = load_profiled_model(ns.time_profile_path, ns.memory_profile_path)
        else:
            print("no profiled model data given; profiling in-process (measured on this host)")
            costs = profile_model(cfg, bsz=ns.min_bsz)
        hw = (
            load_profiled_hardware(ns.hardware_profile_path)
            if ns.hardware_profile_path
            else ProfiledHardware()
        )
        sspace = SearchSpace(
            world_size=ns.num_devices,
            max_tp=ns.max_tp_deg,
            allow_sp=not ns.disable_sp,
            allow_ckpt=not ns.disable_ckpt,
            allow_zero2=not ns.disable_sdp,
            allow_zero3=not ns.disable_sdp,
            allow_strided=not ns.disable_tp_consec,
            allow_cp=bool(ns.enable_cp),
        )
        if ns.search_space == "dp":
            sspace.max_tp, sspace.pp_choices = 1, [1]
        elif ns.search_space == "tp":
            sspace.pp_choices = [1]
        elif ns.search_space == "pp":
            sspace.max_tp = 1
        elif ns.search_space == "dp+tp":
            sspace.pp_choices = [1]
        elif ns.search_space == "dp+pp":
            sspace.max_tp = 1
        elif ns.search_space == "sdp":
            sspace.max_tp, sspace.pp_choices = 1, [1]
        elif ns.search_space == "3d":
            # pure pp x tp x dp grid: no ZeRO/ckpt/layout/SP variants
            sspace.allow_zero2 = sspace.allow_zero3 = False
            sspace.allow_ckpt = sspace.allow_sp = sspace.allow_strided = False
        eng = SearchEngine(
            costs, hw, num_layers=cfg.num_layers, space=sspace,
            memory_budget_mb=ns.memory_constraint_gb * 1024.0,
            mixed_precision="bf16",
        )
        if ns.settle_bsz > 0:
            bszs = [ns.settle_bsz]
        else:
            if ns.bsz_scale < 2:
                print(f"error: --bsz_scale must be >= 2, got {ns.bsz_scale}")
                return 2
            bszs, b = [], ns.min_bsz
            while b <= ns.max_bsz:
                bszs.append(b)
                b *= ns.bsz_scale
        res = eng.search(bszs, max_chunks=ns.max_chunks, verbose=True)
        if res is None:
            print("no feasible strategy under the memory budget")
            return 1
        out = ns.output_config_path or f"galvatron_config_{ns.model_size}_{ns.num_devices}dev.json"
        eng.save_result(res, out)
        print(f"saved searched strategy → {out}")
        return 0

    if mode == "profile":
        ns = initialize_galvatron("profile", rest, model_default)
        cfg = model_config_from_args(ns)
        from galvatron_tpu.profiling.model import profile_model

        prefix = ns.output_prefix or f"profile_{ns.model_size}"
        costs = profile_model(
            cfg, bsz=ns.profile_batch_size,
            layernums=(ns.layernum_min, ns.layernum_max),
            measure_time=ns.profile_type in ("computation", "both"),
        )
        from galvatron_tpu.utils.config_utils import save_profiled_model

        comp = f"{prefix}_computation.json" if ns.profile_type in ("computation", "both") else None
        mem = f"{prefix}_memory.json" if ns.profile_type in ("memory", "both") else None
        save_profiled_model(costs, comp, mem)
        print(f"saved → {', '.join(p for p in (comp, mem) if p)}")
        return 0

    if mode == "profile-hardware":
        ns = initialize_galvatron("profile_hardware", rest, model_default)
        from galvatron_tpu.profiling.hardware import profile_hardware

        hw = profile_hardware(msg_mb=ns.profile_size_mb, out_path=ns.hardware_output_path)
        print(f"allreduce: {hw.allreduce_bw}")
        print(f"p2p: {hw.p2p_bw}")
        print(f"overlap_coe: {hw.overlap_coe}")
        print(f"saved → {ns.hardware_output_path}")
        return 0

    print(f"unknown mode {mode!r}; expected train|search|profile|profile-hardware")
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
