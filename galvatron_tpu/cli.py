"""Unified CLI: python -m galvatron_tpu.cli <mode> [--model_size ...] ...

Modes mirror the reference's per-model entry scripts (reference L7,
models/<name>/{train_dist,search_dist,profiler}.py + profile_hardware):

  train             hybrid-parallel training (train_dist equivalent)
  run-elastic       train under the preemption-aware elastic supervisor
                    (core/elastic.py): child exits are classified
                    (completed / preempted-save / anomaly / watchdog hang /
                    crash) into restart-with-jittered-backoff or give-up
                    decisions, a topology change (pod shrink) triggers an
                    automatic re-search + portable resume under the new
                    plan, and --step_timeout_s arms a hang watchdog;
                    --peer_replicate N keeps an in-memory peer replica of
                    every interval save (core/peer_store.py) so a host
                    killed without grace restores from RAM, a preemption
                    NOTICE (--preempt_notice_file / SIGTERM) drains within
                    --preempt_grace_s, a shrink continues at degraded DP
                    width down to --degraded_min_dp, and
                    --heartbeat_timeout_s kills+restarts a child whose
                    per-step heartbeat goes stale
  peer-store        run one in-memory peer checkpoint store daemon
                    (core/peer_store.py serve; the elastic supervisor
                    spawns these itself under --peer_replicate)
  search            parallelism optimization → galvatron_config JSON
  profile           model computation/memory profiling → JSON
  profile-hardware  ICI bandwidth + overlap sweep → JSON
  check-plan        static plan validation (analysis/plan_check.py): reject a
                    bad strategy JSON in milliseconds with stable GTA…
                    diagnostics — no device, no XLA compile; CI runs it over
                    configs/
  warmup            AOT-compile every registered program of the given plan
                    JSON(s) from abstract shapes into the persistent
                    compile-artifact cache (galvatron_tpu/aot): a later
                    trainer start / elastic restart / serving cold-start on
                    the same plan pays a cache lookup instead of XLA
                    compiles; per-program lower_ms/compile_ms +
                    memory_analysis peak-buffer stats land in a JSONL
                    report, with the comm footprint beside it
  audit-comm        static HLO collective audit (analysis/comm_audit.py):
                    AOT-lower (never compile/execute) every program of the
                    given plan JSON(s) on a forced CPU world, extract the
                    collective footprint from the StableHLO text, gate the
                    cost model's per-term comm volumes against it
                    (predicted_over_lowered, GTC001) and lint for
                    partitioner-inserted resharding the plan never asked
                    for (GTC003/010/011/012); CI runs it over configs/
  trace-export      convert a crash flight-recorder dump (flight_<ts>.json)
                    or raw span records into Chrome trace-event JSON loadable
                    in Perfetto / chrome://tracing (obs/tracing.py);
                    --merge DIR fuses every dump under a directory into ONE
                    clock-aligned multi-process timeline (obs/correlate.py)
  generate          KV-cache text generation from a checkpoint (or random init)
  serve             REST generation server (text_generation_server equivalent);
                    continuous-batching engine by default (--num_slots,
                    --prefill_chunk, --request_ttl_s; --num_slots 0 = legacy
                    serialized path)
  serve-fleet       resilient multi-replica router (serving/fleet.py):
                    fronts N `serve` replica subprocesses with health-driven
                    least-loaded dispatch, mid-flight failover inside the
                    end-to-end deadline (--retry_budget), supervised replica
                    restarts under the shared core/restart_policy.py table,
                    and rolling drain (POST /drain?rolling=1) for
                    zero-downtime deploys
  export-hf         trainer checkpoint → HuggingFace-format checkpoint

The per-model modules (galvatron_tpu.models.<family>) re-export these with
family defaults, mirroring the reference's directory-per-model layout.
"""

from __future__ import annotations

import json
import os
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None, model_default: Optional[str] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    mode, rest = argv[0], argv[1:]

    from galvatron_tpu.core.arguments import initialize_galvatron, model_config_from_args

    if mode == "train":
        from galvatron_tpu.core.trainer import train

        ns = initialize_galvatron("train", rest, model_default)
        train(ns)
        return 0

    if mode == "run-elastic":
        # the supervisor parses the SAME train flags (plus --max_restarts /
        # --step_timeout_s / --replan_*) and forwards them verbatim to each
        # child, so a train command line becomes elastic by renaming the mode
        from galvatron_tpu.core.elastic import run_elastic

        return run_elastic(rest, model_default)

    if mode == "peer-store":
        # standalone daemon entry (multi-host deployments run one per host;
        # the sim supervisor spawns its own): `cli peer-store serve ...`
        from galvatron_tpu.core.peer_store import main as peer_store_main

        return peer_store_main(rest)

    if mode == "search":
        ns = initialize_galvatron("search", rest, model_default)
        from galvatron_tpu.core.arguments import resolve_execution_config

        # profile the exact execution config the training run will use
        # (kernel + dtype) — otherwise predicted-vs-measured fidelity is
        # broken by construction
        cfg = resolve_execution_config(model_config_from_args(ns), ns)
        from galvatron_tpu.profiling.model import profile_model
        from galvatron_tpu.search.cost_model import ProfiledHardware
        from galvatron_tpu.search.search_engine import SearchEngine, SearchSpace
        from galvatron_tpu.utils.config_utils import (
            load_profiled_hardware,
            load_profiled_model,
        )

        if bool(ns.time_profile_path) != bool(ns.memory_profile_path):
            print(
                "error: --time_profile_path and --memory_profile_path must be "
                "given together (got only one; refusing to silently re-profile)"
            )
            return 2
        if ns.time_profile_path and ns.memory_profile_path:
            costs = load_profiled_model(ns.time_profile_path, ns.memory_profile_path)
        elif ns.analytic_costs or ns.check_cost_model:
            from galvatron_tpu.search.theoretical import analytic_model_costs

            print("using analytic (unprofiled) model costs")
            costs = analytic_model_costs(cfg)
        else:
            print("no profiled model data given; profiling in-process (measured on this host)")
            costs = profile_model(cfg, bsz=ns.min_bsz)
        hw = (
            load_profiled_hardware(ns.hardware_profile_path)
            if ns.hardware_profile_path
            else ProfiledHardware()
        )
        sspace = SearchSpace(
            world_size=ns.num_devices,
            max_tp=ns.max_tp_deg,
            allow_sp=not ns.disable_sp,
            allow_ckpt=not ns.disable_ckpt,
            allow_zero2=not ns.disable_sdp,
            allow_zero3=not ns.disable_sdp,
            allow_strided=not ns.disable_tp_consec,
            allow_cp=bool(ns.enable_cp),
            allow_ep=bool(ns.enable_ep),
            allow_tp_overlap=bool(getattr(ns, "enable_tp_overlap", 0)),
            max_ep=ns.max_ep_deg,
            moe_experts=cfg.moe_experts,
            max_vpp=ns.max_vpp_deg,
        )
        from galvatron_tpu.search.search_engine import apply_search_space

        apply_search_space(sspace, ns.search_space)
        eng = SearchEngine(
            costs, hw, num_layers=cfg.total_layers, space=sspace,
            memory_budget_mb=ns.memory_constraint_gb * 1024.0,
            mixed_precision=ns.mixed_precision,
            section_pipeline=bool(cfg.swin_depths),
            model_config=cfg, model_name=ns.model_size,
        )
        if ns.check_cost_model:
            bsz = ns.settle_bsz if ns.settle_bsz > 0 else ns.min_bsz
            print(eng.check_cost_model(bsz, chunks=1, pp=1))
            from galvatron_tpu.search.theoretical import report as theo_report
            from galvatron_tpu.core.strategy import LayerStrategy as _LS

            print(theo_report(cfg, _LS(), ns.num_devices).lines())
            return 0
        if ns.settle_bsz > 0:
            bszs = [ns.settle_bsz]
        else:
            if ns.bsz_scale < 2:
                print(f"error: --bsz_scale must be >= 2, got {ns.bsz_scale}")
                return 2
            rec = 0
            if ns.recommend_min_bsz:
                # PRUNE the grid (drop points below the recommendation) —
                # shifting its anchor would skip points ABOVE it too
                rec = min(eng.recommend_min_bsz(), ns.max_bsz)
                if rec > ns.min_bsz:
                    print(f"recommend_min_bsz: pruning sweep below {rec}")
            bszs, b = [], ns.min_bsz
            while b <= ns.max_bsz:
                if b >= rec:
                    bszs.append(b)
                b *= ns.bsz_scale
            if not bszs:
                bszs = [ns.max_bsz]  # rec sat between the last grid point and the cap
        if ns.validate_top_k > 0:
            # one sweep serves both the saved result and the validation
            # candidates (search_topk ranks by predicted throughput, same
            # criterion search() maximizes)
            cands = eng.search_topk(
                bszs, k=ns.validate_top_k, max_chunks=ns.max_chunks, verbose=True
            )
            res = cands[0] if cands else None
        else:
            cands = None
            res = eng.search(bszs, max_chunks=ns.max_chunks, verbose=True)
        if res is None:
            print("no feasible strategy under the memory budget")
            return 1
        if cands:
            print(
                f"Max throughput = {res.throughput_samples_per_s:.2f} samples/s "
                f"(bsz {res.global_bsz})"
            )
            _validate_search(cands, cfg, ns)
        if ns.report_homogeneity_gap and res.config.pp > 1 and res.config.vpp == 1:
            g = eng.homogeneity_gap(
                res.config.pp, res.global_bsz, res.config.chunks,
                res.config.pipeline_type,
            )
            if g is None:
                print("homogeneity gap: n/a (not defined for this "
                      "shape/schedule, or the per-stage DP is infeasible)")
            else:
                print(
                    f"homogeneity gap: restricted {g['restricted_ms']:.1f} ms vs "
                    f"unrestricted per-stage {g['unrestricted_ms']:.1f} ms "
                    f"(delta {g['delta_pct']:+.3f}%)"
                )
                res.details["homogeneity_gap_pct"] = g["delta_pct"]
        elif ns.report_homogeneity_gap and res.config.vpp > 1:
            print("homogeneity gap: n/a for interleaved (vpp>1) schedules")
        out = ns.output_config_path or f"galvatron_config_{ns.model_size}_{ns.num_devices}dev.json"
        eng.save_result(res, out)
        print(f"saved searched strategy → {out}")
        return 0

    if mode == "profile":
        ns = initialize_galvatron("profile", rest, model_default)
        cfg = model_config_from_args(ns)
        # same attention + dtype resolution as the trainer: profile the
        # program the training run will actually use (flash on accelerators —
        # the xla path materializes (heads, S, S) fp32 probs and OOMs at real
        # shapes; fp32 compute would overstate bf16 layer times ~2x)
        from galvatron_tpu.core.arguments import resolve_execution_config

        cfg = resolve_execution_config(cfg, ns)
        from galvatron_tpu.profiling.model import profile_model

        prefix = ns.output_prefix or f"profile_{ns.model_size}"
        if bool(ns.layernum_min) != bool(ns.layernum_max):
            print("error: --layernum_min and --layernum_max must be given "
                  "together (0,0 = adaptive basis)")
            return 2
        costs = profile_model(
            cfg, bsz=ns.profile_batch_size,
            layernums=(ns.layernum_min, ns.layernum_max) if ns.layernum_max else None,
            measure_time=ns.profile_type in ("computation", "both"),
        )
        from galvatron_tpu.utils.config_utils import save_profiled_model

        comp = f"{prefix}_computation.json" if ns.profile_type in ("computation", "both") else None
        mem = f"{prefix}_memory.json" if ns.profile_type in ("memory", "both") else None
        save_profiled_model(costs, comp, mem)
        print(f"saved → {', '.join(p for p in (comp, mem) if p)}")
        return 0

    if mode == "profile-hardware":
        ns = initialize_galvatron("profile_hardware", rest, model_default)
        from galvatron_tpu.profiling.hardware import profile_hardware

        hw = profile_hardware(
            msg_mb=ns.profile_size_mb, out_path=ns.hardware_output_path,
            num_slices=ns.num_slices or None,
        )
        print(f"allreduce: {hw.allreduce_bw}")
        print(f"p2p: {hw.p2p_bw}")
        print(f"overlap_coe: {hw.overlap_coe}")
        print(f"saved → {ns.hardware_output_path}")
        return 0

    if mode == "export-hf":
        ns = initialize_galvatron("export_hf", rest, model_default)
        if not ns.output_dir:
            print("error: export-hf needs --output_dir")
            return 2
        cfg = model_config_from_args(ns)
        from galvatron_tpu.models.convert import to_hf_gpt2, to_hf_llama

        if cfg.act_fn == "relu":
            print(
                "error: export-hf does not support the OPT family — the +2 "
                "position offset dropped at import cannot be reconstructed "
                "for HF's padded-position rows"
            )
            return 2
        if not cfg.causal or cfg.objective != "clm" or cfg.image_size:
            print(
                "error: export-hf exports causal LM decoders only "
                "(encoder/vision families have no HF causal-LM counterpart)"
            )
            return 2
        # architecture by config shape: GPT-2-style (learned positions +
        # biases + gelu) exports as GPT2LMHeadModel, else LlamaForCausalLM
        gpt2_style = (
            cfg.pos_embed == "learned" and cfg.use_bias and cfg.act_fn == "gelu"
        )
        params = _load_or_init_params(ns, cfg)  # validates shape vs config
        sd = (to_hf_gpt2 if gpt2_style else to_hf_llama)(params, cfg)
        import numpy as _np

        os.makedirs(ns.output_dir, exist_ok=True)
        try:
            import torch

            if gpt2_style:
                from transformers import GPT2Config, GPT2LMHeadModel

                hf_cfg = GPT2Config(
                    vocab_size=cfg.vocab_size, n_embd=cfg.hidden_size,
                    n_layer=cfg.num_layers, n_head=cfg.num_heads,
                    n_inner=cfg.ffn, n_positions=cfg.max_seq_len,
                    layer_norm_epsilon=cfg.norm_eps,
                )
                model = GPT2LMHeadModel(hf_cfg)
            else:
                from transformers import LlamaConfig, LlamaForCausalLM

                hf_cfg = LlamaConfig(
                    vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
                    intermediate_size=cfg.ffn, num_hidden_layers=cfg.num_layers,
                    num_attention_heads=cfg.num_heads, num_key_value_heads=cfg.kv_heads,
                    max_position_embeddings=cfg.max_seq_len,
                    rms_norm_eps=cfg.norm_eps, rope_theta=cfg.rope_theta,
                    tie_word_embeddings=cfg.tie_word_embeddings,
                )
                model = LlamaForCausalLM(hf_cfg)
            model.load_state_dict({k: torch.tensor(v) for k, v in sd.items()})
            model.save_pretrained(ns.output_dir)
            print(f"exported HF checkpoint → {ns.output_dir}")
        except ImportError:
            _np.savez(os.path.join(ns.output_dir, "state_dict.npz"), **sd)
            print(f"transformers unavailable; wrote raw state dict → "
                  f"{ns.output_dir}/state_dict.npz")
        return 0

    if mode == "check-plan":
        ns = initialize_galvatron("check_plan", rest, model_default)
        return _check_plan_mode(ns)

    if mode == "warmup":
        ns = initialize_galvatron("warmup", rest, model_default)
        return _warmup_mode(ns)

    if mode == "audit-comm":
        ns = initialize_galvatron("audit_comm", rest, model_default)
        return _audit_comm_mode(ns)

    if mode == "trace-export":
        ns = initialize_galvatron("trace_export", rest, model_default)
        return _trace_export_mode(ns)

    if mode == "serve-fleet":
        # the multi-replica router (serving/fleet.py): parses the serve
        # flags plus the fleet group, forwards everything non-fleet
        # verbatim to N replica `cli serve` subprocesses
        from galvatron_tpu.serving.fleet import serve_fleet_main

        ns = initialize_galvatron("serve_fleet", rest, model_default)
        return serve_fleet_main(ns, rest)

    if mode in ("generate", "serve"):
        import jax

        from galvatron_tpu.models.tokenizer import build_tokenizer

        ns = initialize_galvatron(mode, rest, model_default)
        tok = build_tokenizer(ns.tokenizer)
        if getattr(ns, "load_hf", None):
            if getattr(ns, "load", None):
                raise ValueError(
                    "--load and --load_hf are mutually exclusive here: pick "
                    "the fine-tuned trainer checkpoint (--load) or the raw "
                    "pretrained HF weights (--load_hf)"
                )
            from galvatron_tpu.models.convert import load_hf_llama

            params, cfg = load_hf_llama(ns.load_hf)
            if tok.vocab_size > cfg.vocab_size:
                raise ValueError(
                    f"tokenizer vocab {tok.vocab_size} exceeds the pretrained "
                    f"embedding {cfg.vocab_size} — ids past the table would "
                    "silently clamp; use the checkpoint's own tokenizer"
                )
        else:
            cfg = model_config_from_args(ns)
            if tok.vocab_size > cfg.vocab_size:
                cfg = cfg.replace(vocab_size=tok.vocab_size)
            params = _load_or_init_params(ns, cfg)
        # an EXPLICIT --attn_impl reaches the executed config ('auto' keeps
        # the model's own default — serving was designed on the xla path and
        # must not silently switch kernels by backend); the plan-free
        # `cli warmup` serving sweep applies the identical rule so the warmed
        # program keys are the keys this engine consults
        if getattr(ns, "attn_impl", "auto") != "auto":
            cfg = cfg.replace(attn_impl=ns.attn_impl)
        if mode == "generate":
            from galvatron_tpu.models import generation

            prompts = ns.prompt or ["Hello"]
            outs = generation.generate_np(
                params, cfg, [tok.encode(p) for p in prompts],
                max_new_tokens=ns.max_new_tokens, temperature=ns.temperature,
                top_k=ns.top_k, top_p=ns.top_p,
                eos_id=tok.eos_id if tok.eos_id is not None else -1,
                pad_id=tok.pad_id if tok.pad_id is not None else 0,
                key=jax.random.key(ns.seed),
            )
            for p, o in zip(prompts, outs):
                print(json.dumps({"prompt": p, "completion": tok.decode(o[len(tok.encode(p)):])}))
            return 0
        from galvatron_tpu.server import GenerationService, run_server

        # chaos hooks (engine_crash_at_iter / prefill_fail_at /
        # slow_decode_ms / client_stall): no-ops unless GALVATRON_FAULTS is
        # set — same contract as the trainer
        from galvatron_tpu.core import faults as _faults

        _faults.init_from_env()
        engine = None
        if getattr(ns, "flight_dir", None):
            # --flight_dir alone arms span tracing (same contract as the
            # trainer): a crash flight dump with an empty ring is a no-op
            from galvatron_tpu.obs.tracing import tracer as _tracer

            if not _tracer.enabled:
                _tracer.enable()
        if ns.num_slots > 0:
            from galvatron_tpu.serving import Engine

            engine = Engine(
                params, cfg,
                num_slots=ns.num_slots,
                prefill_chunk=ns.prefill_chunk,
                max_queue=ns.max_queue,
                request_ttl_s=ns.request_ttl_s if ns.request_ttl_s > 0 else None,
                eos_id=tok.eos_id if tok.eos_id is not None else -1,
                pad_id=tok.pad_id if tok.pad_id is not None else 0,
                seed=ns.seed,
                deadline_policy=ns.deadline_policy,
                max_engine_restarts=ns.max_engine_restarts,
                drain_timeout_s=ns.drain_timeout_s,
                flight_dir=ns.flight_dir,
                kv_block_size=ns.kv_block_size,
                kv_num_blocks=ns.kv_num_blocks,
                prefix_cache=ns.prefix_cache == "on",
                serve_quant=ns.serve_quant,
                quant_drift_max=ns.quant_drift_max,
                spec_decode_k=ns.spec_decode_k,
                spec_drafter=ns.spec_drafter,
            )
            if engine.quant_parity is not None:
                qp = engine.quant_parity
                print(
                    f"serving quant: int8 per-channel, max-abs logit drift "
                    f"{qp['max_abs_logit_drift']} (bound {qp['drift_bound']}), "
                    f"greedy agreement {qp['greedy_agree_frac']:.2%} over "
                    f"{qp['probe_positions']} probe positions", flush=True,
                )
        service = GenerationService(params, cfg, tok, ns.max_new_tokens,
                                    ns.seed, engine=engine)
        if getattr(ns, "slo", 0):
            # server-side SLO engine: this replica observes TTFT (the router
            # cannot see first-token time through a non-streaming proxy) plus
            # its own availability/deadline outcomes. Events land beside the
            # flight dumps when --flight_dir is set; gauges + /healthz
            # degraded_reasons work either way.
            from galvatron_tpu.obs.slo import SLOEngine, build_serving_rules

            service.slo = SLOEngine(
                rules=build_serving_rules(ns),
                events_path=(os.path.join(ns.flight_dir, "slo_events.jsonl")
                             if getattr(ns, "flight_dir", None) else None),
                source="server",
            )
        import threading as _threading

        listening = _threading.Event()
        if engine is not None:
            # startup readiness gating: the server LISTENS first (so a
            # router/load-balancer can poll /readyz and get an honest 503
            # "starting"), then the engine warms on a side thread — the
            # persistent-cache warm start plus one real generation through
            # the scheduler, so the jitted programs genuinely exist — and
            # only then does /readyz flip to 200. Direct /api clients are
            # still accepted while starting; they simply share the compile,
            # exactly the old lazy-first-request behavior.
            service.starting = True
            _threading.Thread(
                target=_serve_warmup, args=(ns, engine, service, listening),
                name="serve-warmup", daemon=True,
            ).start()
        run_server(
            service,
            port=ns.port, host=ns.host, max_pending=ns.max_pending,
            drain_timeout_s=ns.drain_timeout_s, ready_event=listening,
        )
        # a drained SIGTERM/POST-/drain shutdown exits 0: zero-downtime
        # rollouts treat this process as cleanly replaceable
        return 0

    print(
        f"unknown mode {mode!r}; expected "
        "train|run-elastic|peer-store|search|profile|profile-hardware|"
        "check-plan|warmup|audit-comm|trace-export|generate|serve|serve-fleet|"
        "export-hf"
    )
    return 2


def _serve_warmup(ns, engine, service, listening) -> None:
    """`cli serve` startup warm (side thread): persistent-cache warm start
    of the two pinned programs (when a cache is wired), then ONE real
    generation through the scheduler so the jitted entry points exist —
    only then does ``service.starting`` clear and ``/readyz`` report ready.
    Warmth is best-effort: any failure degrades to the lazy-compile path
    (the first request pays it) but never blocks readiness forever."""
    listening.wait(timeout=60.0)
    try:
        if getattr(ns, "compile_cache_dir", None):
            # resolved like the trainer flag: '0'/'off'/'none' disables
            from galvatron_tpu.aot import warmup as aot_warmup
            from galvatron_tpu.aot.cache import (
                ArtifactStore,
                enable_persistent_cache,
                resolve_compile_cache_dir,
            )

            serve_cache_dir = resolve_compile_cache_dir(ns)
            if serve_cache_dir:
                eff = enable_persistent_cache(serve_cache_dir, override=True)
                reports = engine.warm_start(ArtifactStore(eff))
                s = aot_warmup.summarize(reports)
                print(
                    f"serving warm-start: {s['compiled']}/{s['programs']} "
                    f"programs ({s['hits']} cache hits, "
                    f"{s['total_compile_ms']:.0f} ms)", flush=True,
                )
        # the first scheduler iteration: an AOT lower/compile populates the
        # persistent cache but not the jit call cache — one real request
        # proves the engine serves before /readyz says so
        engine.generate([[1]], max_new_tokens=2)
    except Exception as e:  # noqa: BLE001 — warmth is optional, serving is not
        print(f"serving warm-start failed (first request compiles lazily): "
              f"{type(e).__name__}: {str(e)[:200]}", flush=True)
    finally:
        service.starting = False
        print("serving ready: warm start complete, /readyz now 200",
              flush=True)


def _warmup_mode(ns) -> int:
    """AOT-warm every registered program for the given plan JSON(s).

    Per-plan and per-program failure isolation: a plan that fails static
    validation is skipped with its diagnostics, a program that fails to
    compile (this container's protobuf pipeline-compile class) degrades to
    a warning — the sweep itself never aborts.  rc 0 when at least one
    program compiled (or reported a hit), else 1."""
    from galvatron_tpu.aot import warmup as aot_warmup

    if ns.force_world:
        aot_warmup.force_cpu_world(ns.force_world)
    import jax

    from galvatron_tpu.analysis import plan_check
    from galvatron_tpu.analysis.diagnostics import errors, format_report
    from galvatron_tpu.aot.cache import (
        ArtifactStore,
        enable_persistent_cache,
        resolve_compile_cache_dir,
    )
    from galvatron_tpu.core.arguments import model_config_from_args
    from galvatron_tpu.core.strategy import HybridParallelConfig

    # same sentinel rules as train/serve: '0'/'off'/'none' disables the
    # persistent layer — the sweep still compiles (a compile-only run is a
    # legitimate memory-feasibility check) but persists and accounts nothing
    wdir = resolve_compile_cache_dir(ns)
    if wdir is None and not ns.compile_cache_dir:
        # nothing wired anywhere (no flag, no JAX_COMPILATION_CACHE_DIR, no
        # configured jax cache): default to ./.jax_cache. A default that
        # lived on the argparse flag instead would SHADOW the operator's
        # env wiring — warming a cache no later run consults. An explicit
        # 0/off/none sentinel keeps the sweep compile-only.
        wdir = os.path.abspath(".jax_cache")
    store = None
    if wdir:
        eff = enable_persistent_cache(wdir, override=True)
        store = ArtifactStore(eff)
        print(f"compile cache: {eff}")
    else:
        print("compile cache: disabled")
    include = [s.strip() for s in (ns.include or "").split(",") if s.strip()] or None
    world = jax.device_count()
    paths = list(ns.config_paths or []) + list(ns.galvatron_config_path or [])
    all_reports = []
    # when a report is requested, ride the lowering we are doing anyway:
    # extract each program's collective footprint from the StableHLO text
    # (zero extra lower/compile work) and write it beside the report
    footprints = []
    sink = None
    if ns.report:
        from galvatron_tpu.analysis import comm_audit

        def sink(spec, text):  # noqa: E306
            footprints.append(comm_audit.extract_footprint(text, program=spec.name))
    if not paths:
        # plan-free warmup: serving/generate families from the model flags
        from galvatron_tpu.aot import registry as aot_registry
        from galvatron_tpu.models.modeling import PRESETS

        base = PRESETS.get(ns.model_size or "llama-0.3b")
        if base is None:
            print(f"error: unknown --model_size {ns.model_size!r}")
            return 2
        # mirror `cli serve`/`generate` EXACTLY, not the trainer: those
        # surfaces run the model's own attn/dtype defaults and apply only an
        # explicit --attn_impl, so resolving 'auto' here (flash on
        # accelerators) would warm keys the serving engine never consults
        cfg = model_config_from_args(ns, base=base)
        if getattr(ns, "attn_impl", "auto") != "auto":
            cfg = cfg.replace(attn_impl=ns.attn_impl)
        ctx = aot_registry.ProgramContext(
            cfg=cfg, num_slots=ns.num_slots, prefill_chunk=ns.prefill_chunk,
            kv_block_size=getattr(ns, "kv_block_size", 16),
            kv_num_blocks=getattr(ns, "kv_num_blocks", 0),
            serve_quant=getattr(ns, "serve_quant", "off"),
            spec_decode_k=getattr(ns, "spec_decode_k", 0),
        )
        specs = aot_registry.enumerate_programs(ctx, include=include)
        all_reports += aot_warmup.warmup_programs(
            specs, store, model_cfg=cfg, serialize=bool(ns.serialize),
            footprint_sink=sink,
        )
    for path in paths:
        print(f"== {path}")
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, ValueError) as e:
            print(f"warmup: cannot read {path}: {e}; skipping")
            continue
        plan_world = int(d.get("num_devices") or 0)
        if plan_world and plan_world != world:
            print(
                f"warmup: {path} was searched for {plan_world} devices but "
                f"this backend has {world}; skipping (re-run under "
                f"--force_world {plan_world} on CPU, or on the right mesh)"
            )
            continue
        # resolve the plan's self-describing model shape (same rules as
        # check-plan: explicit --model_size wins, else the embedded
        # model_config, else the model_size provenance key)
        cfg = _warmup_model_config(ns, d, path)
        if cfg is None:
            continue
        bsz = ns.global_train_batch_size or int(d.get("global_bsz") or 8)
        diags = plan_check.check_plan(
            d, source=path, model_config=cfg, world_size=world, global_bsz=bsz,
        )
        if errors(diags):
            print(format_report(diags))
            print(f"warmup: {path} fails static validation; skipping")
            continue
        hp = HybridParallelConfig.from_json_dict(d)
        # exact optimizer mirror (core/elastic.py prewarm does the same):
        # the adam constants are burned into the compiled step, so a sweep
        # warmed with different hyperparameters would never hit for the run
        from galvatron_tpu.core.arguments import adam_config_from_args

        all_reports += aot_warmup.warmup_plan(
            cfg, hp, global_bsz=bsz, store=store, include=include,
            num_slots=ns.num_slots, prefill_chunk=ns.prefill_chunk,
            kv_block_size=getattr(ns, "kv_block_size", 16),
            kv_num_blocks=getattr(ns, "kv_num_blocks", 0),
            serve_quant=getattr(ns, "serve_quant", "off"),
            spec_decode_k=getattr(ns, "spec_decode_k", 0),
            adam=adam_config_from_args(ns),
            serialize=bool(ns.serialize),
            footprint_sink=sink,
        )
    summary = aot_warmup.summarize(all_reports)
    manifest_note = (
        f"manifest: {store.stats()['entries']} entries" if store is not None
        else "manifest: disabled"
    )
    print(
        f"warmup: {summary['programs']} programs — {summary['hits']} hits, "
        f"{summary['misses']} misses, {summary['failed']} failed, "
        f"{summary['total_compile_ms']:.0f} ms total compile ({manifest_note})"
    )
    if ns.report:
        aot_warmup.write_report(ns.report, all_reports)
        print(f"report → {ns.report}")
        if footprints:
            from galvatron_tpu.analysis import comm_audit

            fp_path = ns.report + ".footprint.jsonl"
            comm_audit.write_footprint_jsonl(fp_path, footprints)
            print(f"comm footprint → {fp_path}")
    return 0 if summary["compiled"] > 0 else 1


def _warmup_model_config(ns, d: dict, path: str):
    """check-plan's model-resolution rules, shared shape: explicit
    --model_size > embedded model_config > the JSON's model_size key.

    Keep the precedence in lockstep with _check_plan_mode's resolution
    block (the failure handling legitimately differs: check-plan degrades
    to structural-only diagnostics, a warmup sweep skips the plan) — a
    drift here warms keys computed from a different effective model than
    the one check-plan/trainer validate against."""
    from galvatron_tpu.core.arguments import model_config_from_args
    from galvatron_tpu.models.modeling import PRESETS, ModelConfig

    model_size = ns.model_size or d.get("model_size")
    shape = d.get("model_config")
    shape = shape if isinstance(shape, dict) else None
    base = PRESETS.get(model_size) if model_size else None
    if ns.model_size and base is None:
        print(f"error: unknown --model_size {ns.model_size!r}")
        return None
    if not ns.model_size and shape is not None:
        from galvatron_tpu.analysis.plan_check import apply_model_shape

        base = apply_model_shape(base if base is not None else ModelConfig(), shape)
    if base is None:
        print(f"warmup: {path} names no resolvable model "
              f"(model_size {model_size!r}, no embedded model_config); skipping")
        return None
    from galvatron_tpu.core.arguments import resolve_execution_config

    cfg = model_config_from_args(ns, base=base)
    # mirror the trainer's own resolution (pack_sequences rides the model
    # config BEFORE attention resolution, core/elastic.py prewarm idem)
    if getattr(ns, "pack_sequences", 0):
        cfg = cfg.replace(pack_sequences=True)
    return resolve_execution_config(cfg, ns)


def _audit_comm_mode(ns) -> int:
    """Static HLO collective audit of strategy JSON(s) — lower-only.

    Forces a CPU world of the first plan's ``num_devices`` before the first
    backend touch (no hardware, no compile, no execute), then per plan:
    AOT-lower every program, extract the collective footprint, run the
    fidelity gate and the resharding lint.  rc 0 = every audited plan
    clean, 1 = GTC errors (or any GTC finding under --strict), 2 = usage
    error (no configs, unreadable JSON, unresolvable model)."""
    from galvatron_tpu.aot import warmup as aot_warmup

    paths = list(ns.config_paths or []) + list(ns.galvatron_config_path or [])
    if not paths:
        print("audit-comm: no strategy JSONs given")
        return 2
    plans = []
    for path in paths:
        try:
            with open(path) as f:
                plans.append((path, json.load(f)))
        except (OSError, ValueError) as e:
            print(f"audit-comm: cannot read {path}: {e}")
            return 2
    # the audit world comes from the plans themselves: force the CPU
    # platform before the first backend touch (lower-only — any host works)
    world = int(plans[0][1].get("num_devices") or 0) or 8
    aot_warmup.force_cpu_world(world)
    import jax

    from galvatron_tpu.analysis import comm_audit, plan_check
    from galvatron_tpu.analysis.diagnostics import errors, format_report
    from galvatron_tpu.core.strategy import HybridParallelConfig

    include = [s.strip() for s in (ns.include or "").split(",") if s.strip()] or None
    world = jax.device_count()
    all_footprints = []
    rc = 0
    audited = 0
    for path, d in plans:
        print(f"== {path}")
        plan_world = int(d.get("num_devices") or 0) or world
        if plan_world != world:
            # one process = one forced world; same skip rule as warmup so a
            # sweep over mixed-world configs audits what it can (the final
            # audited/total line keeps the gap visible)
            print(
                f"audit-comm: {path} targets {plan_world} devices but this "
                f"audit world is {world}; skipping (audit it in its own "
                f"invocation)"
            )
            continue
        cfg = _warmup_model_config(ns, d, path)
        if cfg is None:
            rc = max(rc, 2)
            continue
        bsz = ns.global_train_batch_size or int(d.get("global_bsz") or 8)
        diags = plan_check.check_plan(
            d, source=path, model_config=cfg, world_size=world, global_bsz=bsz,
        )
        if errors(diags):
            print(format_report(diags))
            print(f"audit-comm: {path} fails static validation")
            rc = max(rc, 1)
            continue
        try:
            hp = HybridParallelConfig.from_json_dict(d)
        except (ValueError, KeyError) as e:
            print(f"audit-comm: {path} does not decode: {e}")
            rc = max(rc, 2)
            continue
        res = comm_audit.audit_plan(
            cfg, hp, world=world, global_bsz=bsz, include=include,
            tolerance=ns.tolerance, source=path, verbose=True,
        )
        audited += 1
        print(comm_audit.format_fidelity_table(res.rows))
        if res.diagnostics:
            print(format_report(res.diagnostics, clean=""))
        all_footprints += res.footprints
        if errors(res.diagnostics) or (ns.strict and res.diagnostics):
            rc = max(rc, 1)
    if ns.report and all_footprints:
        comm_audit.write_footprint_jsonl(ns.report, all_footprints)
        print(f"comm footprint → {ns.report}")
    if not audited and rc == 0:
        print("audit-comm: no plan audited")
        return 2
    print(f"audit-comm: {audited}/{len(plans)} plan(s) audited, rc {rc}")
    return rc


def _trace_export_mode(ns) -> int:
    """Flight dump / span records → Chrome trace-event JSON (Perfetto).

    ``--merge`` fuses every ``flight_*.json`` under a directory into ONE
    timeline (obs/correlate.py): per-process pid track groups, clocks
    aligned via each dump's ``epoch_wall`` anchor — a fleet request's
    trace_id visibly hops router → replica-A → replica-B. Torn dumps are
    skipped with a warning (same contract as ``read_metrics``' torn tail).
    """
    if getattr(ns, "merge", False):
        from galvatron_tpu.obs.correlate import merge_directory

        try:
            out, used = merge_directory(ns.input_path, ns.output)
        except ValueError as e:
            print(f"error: {e}")
            return 2
        print(f"merged {len(used)} flight dump(s) → {out} "
              "(load in Perfetto or chrome://tracing)")
        return 0
    from galvatron_tpu.obs.flight import FLIGHT_SCHEMA
    from galvatron_tpu.obs.tracing import chrome_trace

    try:
        with open(ns.input_path) as f:
            doc = json.load(f)
    except OSError as e:
        print(f"error: cannot read {ns.input_path}: {e}")
        return 2
    except ValueError as e:
        # torn/partial dump (crash mid-write): diagnose, don't traceback —
        # the merge path skips these; single-file export has nothing left
        lineno = getattr(e, "lineno", "?")
        print(f"error: {ns.input_path}: torn/partial flight dump (crash "
              f"mid-write?) — JSON parse failed at line {lineno}")
        return 2
    if isinstance(doc, dict) and doc.get("schema") == FLIGHT_SCHEMA:
        spans = doc.get("spans", [])
    elif isinstance(doc, dict) and "traceEvents" in doc:
        print(f"{ns.input_path} is already Chrome trace-event JSON; nothing to do")
        return 2
    elif isinstance(doc, list):
        spans = doc
    else:
        print(
            f"error: {ns.input_path} is neither a {FLIGHT_SCHEMA} flight dump "
            "nor a JSON list of span records"
        )
        return 2
    out = ns.output or ns.input_path + ".trace.json"
    with open(out, "w") as f:
        json.dump(chrome_trace(spans), f)
    print(f"wrote {len(spans)} events → {out} (load in Perfetto or chrome://tracing)")
    return 0


def _check_plan_mode(ns) -> int:
    """Validate strategy JSONs statically; exit 1 on any error diagnostic
    (warnings too under --strict). Model/world/batch/budget default to the
    JSON's own provenance keys (search-emitted configs are self-describing)."""
    from galvatron_tpu.analysis import plan_check
    from galvatron_tpu.analysis.diagnostics import errors, format_report, warnings
    from galvatron_tpu.core.arguments import model_config_from_args

    paths = list(ns.config_paths or []) + list(ns.galvatron_config_path or [])
    if not paths:
        print("error: check-plan needs at least one strategy JSON path")
        return 2
    rc = 0
    cli_model_size = ns.model_size  # per-file JSON defaults must not leak across files
    for path in paths:
        try:
            with open(path) as f:
                d = json.load(f)
            if not isinstance(d, dict):
                d = {}
        except (OSError, ValueError):
            d = {}  # check_plan reports the parse failure as GTA002
        model_size = cli_model_size or d.get("model_size")
        shape = d.get("model_config")
        shape = shape if isinstance(shape, dict) else None
        cfg = None
        base = None
        if model_size:
            from galvatron_tpu.models.modeling import PRESETS

            base = PRESETS.get(model_size)
            if base is None and cli_model_size:
                # an explicit model the user asked to validate against —
                # falling back to anything else would answer a different
                # question with a confident exit code
                print(f"error: unknown --model_size {cli_model_size!r}")
                return 2
            if base is None and shape is None:
                print(f"{path}: unknown model_size {model_size!r} and no "
                      "embedded model_config; running structural checks only")
        if cli_model_size:
            # an EXPLICIT --model_size asks "does this plan fit THAT model" —
            # the plan's embedded shape must not overlay it (it would make
            # validation against a different model silently vacuous)
            if shape is not None:
                print(f"{path}: validating against --model_size "
                      f"{cli_model_size} (plan's embedded model_config "
                      "shape ignored)")
        elif shape is not None:
            # no explicit model: the plan's embedded EFFECTIVE shape is the
            # default (covers search-time overrides like --num_layers);
            # explicit per-field flags still win below
            from galvatron_tpu.analysis.plan_check import apply_model_shape
            from galvatron_tpu.models.modeling import ModelConfig

            base = apply_model_shape(base if base is not None else ModelConfig(), shape)
        if base is not None:
            cfg = model_config_from_args(ns, base=base)
        def _num(v):
            # provenance keys come from arbitrary hand-edited JSON: a
            # string-typed "8" must not crash the tool whose job is turning
            # malformed configs into diagnostics
            try:
                return float(v)
            except (TypeError, ValueError):
                return 0.0

        world = int(ns.num_devices or _num(d.get("num_devices")))
        budget_gb = ns.memory_constraint_gb or _num(d.get("memory_constraint_gb"))
        diags = plan_check.check_plan(
            # already decoded above — re-reading the file would duplicate
            # I/O and race a concurrent rewrite; the path branch is kept
            # only to surface the parse failure as GTA002
            d if d else path,
            source=path,
            model_config=cfg,
            world_size=world or None,
            global_bsz=ns.global_bsz or None,
            memory_budget_mb=budget_gb * 1024.0 or None,
            abstract_pass=not ns.no_abstract_pass,
        )
        scope = []
        if cfg is None:
            scope.append("no model config: structural checks only")
        if not world:
            scope.append("no num_devices: topology checks skipped")
        tag = f"  ({'; '.join(scope)})" if scope else ""
        print(f"== {path}{tag}")
        print(format_report(diags))
        if errors(diags) or (ns.strict and warnings(diags)):
            rc = 1
    return rc


def _validate_search(cands, cfg, ns):
    """Measured validation of the predicted ranking: train the top-k searched
    candidates a few steps each and report predicted vs measured iteration
    time. Ordering compares THROUGHPUT (the criterion the search maximizes —
    candidates may differ in global batch size, so iteration time alone is
    not comparable). The reference's check_cost_model stops at printed
    predictions ("for developers", search_engine.py:369-421); this closes
    the loop on real steps."""
    import jax

    from galvatron_tpu.profiling.model import measure_strategy_ms

    world = len(jax.devices())
    if world != ns.num_devices:
        print(
            f"--validate_top_k skipped: search was for {ns.num_devices} "
            f"devices but this host has {world}"
        )
        return
    rows = []
    for r in cands:
        try:
            ms = measure_strategy_ms(cfg, r.config, r.global_bsz)
        except Exception as e:  # candidate may not fit this host's memory
            print(f"  candidate pp={r.config.pp} failed to run: {str(e)[:120]}")
            continue
        rows.append((r, r.global_bsz / (ms / 1000.0)))
        print(
            f"  pp={r.config.pp} chunks={r.config.chunks} "
            f"{r.config.pipeline_type} vpp={r.config.vpp} bsz={r.global_bsz}: "
            f"predicted {r.cost_ms:.1f} ms, measured {ms:.1f} ms "
            f"(fidelity {r.cost_ms / ms:.3f})"
        )
    if len(rows) >= 2:
        pred_order = [
            id(r) for r, _ in sorted(rows, key=lambda x: -x[0].throughput_samples_per_s)
        ]
        meas_order = [id(r) for r, _ in sorted(rows, key=lambda x: -x[1])]
        agree = sum(a == b for a, b in zip(pred_order, meas_order))
        print(
            f"predicted-vs-measured rank agreement: {agree}/{len(rows)} "
            f"positions (best candidate "
            f"{'confirmed' if pred_order[0] == meas_order[0] else 'NOT fastest measured'})"
        )


def _load_or_init_params(ns, cfg):
    """Params from a trainer checkpoint (--load) or fresh random init."""
    import jax

    from galvatron_tpu.models import modeling

    if getattr(ns, "load", None):
        from galvatron_tpu.core.checkpoint import restore_raw_checkpoint

        # verified restore with newest→oldest fallback: a corrupt latest
        # checkpoint cannot silently serve garbage weights
        raw, _step = restore_raw_checkpoint(os.path.abspath(ns.load))
        params = raw["params"] if isinstance(raw, dict) and "params" in raw else raw
        # validate against the model config before silently generating garbage
        abstract = jax.eval_shape(lambda k: modeling.init_model_params(k, cfg), jax.random.key(0))
        got, want = _shape_map(params), _shape_map(abstract)
        if got != want:
            diff = {k: (got.get(k), want.get(k)) for k in sorted(set(got) | set(want))
                    if got.get(k) != want.get(k)}
            raise ValueError(
                f"checkpoint under {ns.load} does not match the model config "
                f"(e.g. --vocab_size/--tokenizer mismatch); got vs want: {diff}"
            )
        return params
    return modeling.init_model_params(jax.random.key(0), cfg)


def _shape_map(tree):
    """path → shape, with list indices and '0'-style dict keys normalized."""
    import jax

    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        keys = [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]
        out["/".join(keys)] = tuple(getattr(leaf, "shape", ()))
    return out


if __name__ == "__main__":
    raise SystemExit(main())
