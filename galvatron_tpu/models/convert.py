"""HuggingFace checkpoint import.

The reference's model layer literally wraps HF models carrying their
pretrained weights (reference: models/llama_hf/train_dist.py builds
``LlamaForCausalLM(config)`` and swaps layers in place;
models/llama_hf/arguments.py exposes HF meta-configs). This module delivers
the same capability TPU-natively: map an HF ``LlamaForCausalLM``-architecture
state dict (LLaMA/Baichuan-style: RMSNorm, SwiGLU, RoPE, no biases) onto the
functional parameter pytree, packing per-projection weights into the fused
layouts (``modeling.qkv_dims``: blocked ``(h, 3, n·hd)`` without GQA,
interleaved-by-kv-group with GQA; swiglu ``w13``).

Numerical parity with the HF torch forward is pinned by
tests/test_convert.py (logits agree to ~1e-4 in fp32).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping

import numpy as np

from galvatron_tpu.models.modeling import ModelConfig, Params


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor
        return t.detach().to("cpu").float().numpy()
    return np.asarray(t, np.float32)


def _getter(sd: Mapping[str, Any], family: str):
    """Missing-key accessor shared by every importer (one copy of the
    diagnostics instead of one per family)."""

    def get(name: str) -> np.ndarray:
        if name not in sd:
            raise KeyError(
                f"HF state dict is missing '{name}' — not a {family} "
                f"checkpoint? (keys like {list(sd)[:3]})"
            )
        return _np(sd[name])

    return get


def config_from_hf_llama(hf_config) -> ModelConfig:
    """ModelConfig from a ``transformers.LlamaConfig``-shaped object.

    Rejects config features the fused layouts here do not carry — silently
    dropping them would produce a numerically wrong model."""
    if getattr(hf_config, "rope_scaling", None):
        raise ValueError(
            "HF checkpoint uses rope_scaling (Llama-3.1-style scaled RoPE), "
            "which this importer does not implement — frequencies would be "
            "wrong; refusing to convert"
        )
    if getattr(hf_config, "attention_bias", False) or getattr(
        hf_config, "mlp_bias", False
    ):
        raise ValueError(
            "HF checkpoint carries attention/MLP biases; the fused layouts "
            "here have no bias slots — refusing to silently drop them"
        )
    return ModelConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=getattr(hf_config, "num_key_value_heads", None),
        ffn_dim=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        norm_eps=float(getattr(hf_config, "rms_norm_eps", 1e-5)),
        tie_word_embeddings=bool(getattr(hf_config, "tie_word_embeddings", False)),
    )


def pack_qkv(wq: np.ndarray, wk: np.ndarray, wv: np.ndarray, cfg: ModelConfig) -> np.ndarray:
    """Per-projection (h, out) matrices (already input-major, i.e. HF weights
    transposed) → the fused wqkv layout."""
    h, hd = cfg.hidden_size, cfg.head_dim
    n, kv = cfg.num_heads, cfg.kv_heads
    if cfg.qkv_blocked:
        return np.stack([wq, wk, wv], axis=1)  # (h, 3, n*hd)
    npg = n // kv
    q = wq.reshape(h, kv, npg, hd)
    k = wk.reshape(h, kv, 1, hd)
    v = wv.reshape(h, kv, 1, hd)
    inter = np.concatenate([q, k, v], axis=2)  # (h, kv, npg+2, hd)
    return inter.reshape(h, kv * (npg + 2) * hd)


def from_hf_llama(model_or_state_dict: Any, cfg: ModelConfig) -> Params:
    """HF ``LlamaForCausalLM`` (or its state dict) → parameter pytree in
    ``cfg.param_dtype``. ``cfg`` must describe the same architecture
    (``config_from_hf_llama``)."""
    sd: Mapping[str, Any] = (
        model_or_state_dict
        if isinstance(model_or_state_dict, Mapping)
        else model_or_state_dict.state_dict()
    )
    # leaves stay numpy (host RAM): committing them to the default device
    # here would single-device-OOM checkpoints that only fit SHARDED — the
    # runtime's jitted init_state_from places them per its out_shardings.
    # (numpy handles bfloat16 via the ml_dtypes registration jax ships.)
    dt = cfg.param_dtype
    get = _getter(sd, "LLaMA-architecture")

    params: Params = {
        "embed": {"tok": get("model.embed_tokens.weight").astype(dt)},
        "layers": [],
        "final_norm": {"scale": get("model.norm.weight").astype(dt)},
    }
    for i in range(cfg.num_layers):
        pre = f"model.layers.{i}."
        wq = get(pre + "self_attn.q_proj.weight").T  # (h, n*hd)
        wk = get(pre + "self_attn.k_proj.weight").T
        wv = get(pre + "self_attn.v_proj.weight").T
        w13 = np.concatenate(
            [get(pre + "mlp.gate_proj.weight").T, get(pre + "mlp.up_proj.weight").T],
            axis=1,
        )
        params["layers"].append(
            {
                "attn_norm": {
                    "scale": get(pre + "input_layernorm.weight").astype(dt)
                },
                "attn": {
                    "wqkv": pack_qkv(wq, wk, wv, cfg).astype(dt),
                    "wo": np.ascontiguousarray(
                        get(pre + "self_attn.o_proj.weight").T
                    ).astype(dt),
                },
                "mlp_norm": {
                    "scale": get(pre + "post_attention_layernorm.weight").astype(dt)
                },
                "mlp": {
                    "w13": w13.astype(dt),
                    "w2": np.ascontiguousarray(
                        get(pre + "mlp.down_proj.weight").T
                    ).astype(dt),
                },
            }
        )
    if not cfg.tie_word_embeddings:
        params["head"] = {"w": np.ascontiguousarray(get("lm_head.weight").T).astype(dt)}
    return params


def config_from_hf_baichuan(hf_config) -> ModelConfig:
    """ModelConfig from a Baichuan-1 HF config (model_type 'baichuan' —
    trust_remote_code architecture, so there is no transformers config class
    to type-check against; the reference's baichuan family builds from these
    HF configs the same way, models/baichuan/BaiChuanModel_sequential.py:6-25).

    The 7B checkpoint uses rotary positions and carries
    ``max_position_embeddings``; the 13B checkpoint uses ALiBi and carries
    ``model_max_length`` instead — that field difference is the published
    config discriminator between the two architectures."""
    if hf_config.vocab_size > 100000:
        # Baichuan-2 shares model_type 'baichuan' but normalizes the lm_head
        # rows at forward time (NormHead) and its 7B uses RoPE despite
        # carrying only model_max_length — importing it with Baichuan-1 math
        # would silently produce wrong logits. Its 125696-token vocab (vs
        # Baichuan-1's 64000) is the reliable config discriminator.
        raise ValueError(
            f"vocab_size {hf_config.vocab_size} indicates a Baichuan-2 "
            "checkpoint (NormHead + different position-scheme config "
            "encoding), which this importer does not implement — refusing "
            "to silently import it with Baichuan-1 math"
        )
    mpe = getattr(hf_config, "max_position_embeddings", None)
    alibi = mpe is None
    if alibi and getattr(hf_config, "model_max_length", None) is None:
        raise ValueError(
            "baichuan config carries neither max_position_embeddings (7B, "
            "rotary) nor model_max_length (13B, ALiBi) — cannot infer the "
            "position-embedding scheme"
        )
    return ModelConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        ffn_dim=hf_config.intermediate_size,
        max_seq_len=mpe if mpe is not None else hf_config.model_max_length,
        pos_embed="alibi" if alibi else "rope",
        norm_eps=float(getattr(hf_config, "rms_norm_eps", 1e-6)),
        tie_word_embeddings=bool(getattr(hf_config, "tie_word_embeddings", False)),
    )


def from_hf_baichuan(model_or_state_dict: Any, cfg: ModelConfig) -> Params:
    """HF Baichuan-1 state dict (or model) → parameter pytree. Baichuan is
    LLaMA-architecture (RMSNorm/SwiGLU, untied head, no biases) except the
    attention input projection is already fused: ``self_attn.W_pack.weight``
    is (3·h, h) in [Q; K; V] row order — transposing gives input-major
    [Q | K | V] columns, which is exactly the blocked wqkv layout (no GQA in
    either published size)."""
    sd: Mapping[str, Any] = (
        model_or_state_dict
        if isinstance(model_or_state_dict, Mapping)
        else model_or_state_dict.state_dict()
    )
    dt = cfg.param_dtype
    h, nd = cfg.hidden_size, cfg.num_heads * cfg.head_dim
    get = _getter(sd, "Baichuan")

    params: Params = {
        "embed": {"tok": get("model.embed_tokens.weight").astype(dt)},
        "layers": [],
        "final_norm": {"scale": get("model.norm.weight").astype(dt)},
    }
    for i in range(cfg.num_layers):
        pre = f"model.layers.{i}."
        wpack = get(pre + "self_attn.W_pack.weight").T  # (h, 3*nd)
        params["layers"].append(
            {
                "attn_norm": {
                    "scale": get(pre + "input_layernorm.weight").astype(dt)
                },
                "attn": {
                    "wqkv": np.ascontiguousarray(
                        wpack.reshape(h, 3, nd)
                    ).astype(dt),
                    "wo": np.ascontiguousarray(
                        get(pre + "self_attn.o_proj.weight").T
                    ).astype(dt),
                },
                "mlp_norm": {
                    "scale": get(pre + "post_attention_layernorm.weight").astype(dt)
                },
                "mlp": {
                    "w13": np.concatenate(
                        [
                            get(pre + "mlp.gate_proj.weight").T,
                            get(pre + "mlp.up_proj.weight").T,
                        ],
                        axis=1,
                    ).astype(dt),
                    "w2": np.ascontiguousarray(
                        get(pre + "mlp.down_proj.weight").T
                    ).astype(dt),
                },
            }
        )
    if not cfg.tie_word_embeddings:
        params["head"] = {"w": np.ascontiguousarray(get("lm_head.weight").T).astype(dt)}
    return params


def config_from_hf_gpt2(hf_config) -> ModelConfig:
    """ModelConfig from a ``transformers.GPT2Config``-shaped object (the
    reference's gpt_hf family wraps exactly this model —
    models/gpt_hf/GPTModel_hybrid_parallel.py)."""
    act = getattr(hf_config, "activation_function", "gelu_new")
    if act not in ("gelu_new", "gelu_pytorch_tanh"):
        raise ValueError(
            f"unsupported GPT-2 activation {act!r} (the MLP here uses the "
            "tanh-approximate gelu, i.e. HF's gelu_new)"
        )
    if getattr(hf_config, "scale_attn_by_inverse_layer_idx", False):
        raise ValueError("scale_attn_by_inverse_layer_idx is not implemented")
    return ModelConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.n_embd,
        num_layers=hf_config.n_layer,
        num_heads=hf_config.n_head,
        ffn_dim=hf_config.n_inner or 4 * hf_config.n_embd,
        max_seq_len=hf_config.n_positions,
        pos_embed="learned",
        norm_type="layernorm",
        act_fn="gelu",
        use_bias=True,
        tie_word_embeddings=True,
        norm_eps=float(getattr(hf_config, "layer_norm_epsilon", 1e-5)),
    )


def from_hf_gpt2(model_or_state_dict: Any, cfg: ModelConfig) -> Params:
    """HF ``GPT2LMHeadModel`` (or its state dict) → parameter pytree. GPT-2's
    Conv1D weights are already input-major (h_in, h_out) and its fused
    ``c_attn`` is already in the blocked [Q | K | V] column order, so the
    mapping is reshape-only."""
    sd: Mapping[str, Any] = (
        model_or_state_dict
        if isinstance(model_or_state_dict, Mapping)
        else model_or_state_dict.state_dict()
    )
    dt = cfg.param_dtype
    h, nd = cfg.hidden_size, cfg.num_heads * cfg.head_dim
    get = _getter(sd, "GPT-2")

    params: Params = {
        "embed": {
            "tok": get("transformer.wte.weight").astype(dt),
            "pos": get("transformer.wpe.weight").astype(dt),
        },
        "layers": [],
        "final_norm": {
            "scale": get("transformer.ln_f.weight").astype(dt),
            "bias": get("transformer.ln_f.bias").astype(dt),
        },
    }
    for i in range(cfg.num_layers):
        pre = f"transformer.h.{i}."
        params["layers"].append(
            {
                "attn_norm": {
                    "scale": get(pre + "ln_1.weight").astype(dt),
                    "bias": get(pre + "ln_1.bias").astype(dt),
                },
                "attn": {
                    "wqkv": get(pre + "attn.c_attn.weight").reshape(h, 3, nd).astype(dt),
                    "wqkv_b": get(pre + "attn.c_attn.bias").reshape(3, nd).astype(dt),
                    "wo": get(pre + "attn.c_proj.weight").astype(dt),
                    "wo_b": get(pre + "attn.c_proj.bias").astype(dt),
                },
                "mlp_norm": {
                    "scale": get(pre + "ln_2.weight").astype(dt),
                    "bias": get(pre + "ln_2.bias").astype(dt),
                },
                "mlp": {
                    "w1": get(pre + "mlp.c_fc.weight").astype(dt),
                    "w1_b": get(pre + "mlp.c_fc.bias").astype(dt),
                    "w2": get(pre + "mlp.c_proj.weight").astype(dt),
                    "w2_b": get(pre + "mlp.c_proj.bias").astype(dt),
                },
            }
        )
    return params


def unpack_qkv(wqkv: np.ndarray, cfg: ModelConfig):
    """Inverse of pack_qkv: fused wqkv → per-projection (h, out) matrices."""
    h, hd = cfg.hidden_size, cfg.head_dim
    n, kv = cfg.num_heads, cfg.kv_heads
    if cfg.qkv_blocked:
        return wqkv[:, 0, :], wqkv[:, 1, :], wqkv[:, 2, :]
    npg = n // kv
    r = wqkv.reshape(h, kv, npg + 2, hd)
    wq = r[:, :, :npg, :].reshape(h, n * hd)
    wk = r[:, :, npg, :].reshape(h, kv * hd)
    wv = r[:, :, npg + 1, :].reshape(h, kv * hd)
    return wq, wk, wv


def to_hf_llama(params: Params, cfg: ModelConfig) -> Dict[str, np.ndarray]:
    """Parameter pytree → an HF ``LlamaForCausalLM`` state dict (numpy fp32,
    HF's output-major weight orientation) — the export half of the round
    trip, so a model fine-tuned here can be served by any HF stack.
    ``LlamaForCausalLM(config).load_state_dict`` accepts it after wrapping
    leaves in torch tensors (tests/test_convert.py round-trips it)."""
    if cfg.act_fn != "swiglu" or cfg.norm_type != "rms" or cfg.use_bias:
        raise ValueError(
            "to_hf_llama exports the LLaMA architecture family only "
            "(RMSNorm + SwiGLU, no projection biases)"
        )
    f32 = lambda a: np.asarray(a, np.float32)
    sd: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": f32(params["embed"]["tok"]),
        "model.norm.weight": f32(params["final_norm"]["scale"]),
    }
    for i, lp in enumerate(params["layers"]):
        pre = f"model.layers.{i}."
        wq, wk, wv = unpack_qkv(f32(lp["attn"]["wqkv"]), cfg)
        sd[pre + "self_attn.q_proj.weight"] = np.ascontiguousarray(wq.T)
        sd[pre + "self_attn.k_proj.weight"] = np.ascontiguousarray(wk.T)
        sd[pre + "self_attn.v_proj.weight"] = np.ascontiguousarray(wv.T)
        sd[pre + "self_attn.o_proj.weight"] = np.ascontiguousarray(f32(lp["attn"]["wo"]).T)
        w13 = f32(lp["mlp"]["w13"])
        f = w13.shape[-1] // 2
        sd[pre + "mlp.gate_proj.weight"] = np.ascontiguousarray(w13[:, :f].T)
        sd[pre + "mlp.up_proj.weight"] = np.ascontiguousarray(w13[:, f:].T)
        sd[pre + "mlp.down_proj.weight"] = np.ascontiguousarray(f32(lp["mlp"]["w2"]).T)
        sd[pre + "input_layernorm.weight"] = f32(lp["attn_norm"]["scale"])
        sd[pre + "post_attention_layernorm.weight"] = f32(lp["mlp_norm"]["scale"])
    if not cfg.tie_word_embeddings:
        sd["lm_head.weight"] = np.ascontiguousarray(f32(params["head"]["w"]).T)
    else:
        sd["lm_head.weight"] = sd["model.embed_tokens.weight"]
    return sd


def config_from_hf_opt(hf_config) -> ModelConfig:
    """ModelConfig from a ``transformers.OPTConfig``-shaped object (decoder-
    only, ReLU MLPs, LayerNorm, learned positions with OPT's +2 offset)."""
    if getattr(hf_config, "word_embed_proj_dim", hf_config.hidden_size) != hf_config.hidden_size:
        raise ValueError(
            "OPT checkpoints with projected embeddings (word_embed_proj_dim "
            "!= hidden_size, e.g. opt-350m) are not supported"
        )
    if not getattr(hf_config, "do_layer_norm_before", True):
        raise ValueError(
            "post-norm OPT variants (do_layer_norm_before=False, opt-350m) "
            "are not supported (this decoder is pre-norm)"
        )
    act = getattr(hf_config, "activation_function", "relu")
    if act != "relu":
        raise ValueError(f"unsupported OPT activation {act!r} (expected relu)")
    if not getattr(hf_config, "tie_word_embeddings", True):
        raise ValueError(
            "untied OPT checkpoints (tie_word_embeddings=False) are not "
            "supported — the lm_head would be silently dropped"
        )
    return ModelConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        ffn_dim=hf_config.ffn_dim,
        max_seq_len=hf_config.max_position_embeddings,
        pos_embed="learned",
        norm_type="layernorm",
        act_fn="relu",
        use_bias=True,
        tie_word_embeddings=True,
    )


def from_hf_opt(model_or_state_dict: Any, cfg: ModelConfig) -> Params:
    """HF ``OPTForCausalLM`` (or its state dict) → parameter pytree. OPT has
    separate q/k/v projections with biases (packed into the blocked fused
    layout here) and a learned position table indexed at position+2 — the
    offset is baked in by slicing the table, exactly equivalent for
    left-aligned (unpadded) sequences, which is this runtime's batch
    contract."""
    sd: Mapping[str, Any] = (
        model_or_state_dict
        if isinstance(model_or_state_dict, Mapping)
        else model_or_state_dict.state_dict()
    )
    dt = cfg.param_dtype
    get = _getter(sd, "OPT")

    pos = get("model.decoder.embed_positions.weight")[2 : 2 + cfg.max_seq_len]
    params: Params = {
        "embed": {
            "tok": get("model.decoder.embed_tokens.weight").astype(dt),
            "pos": pos.astype(dt),
        },
        "layers": [],
        "final_norm": {
            "scale": get("model.decoder.final_layer_norm.weight").astype(dt),
            "bias": get("model.decoder.final_layer_norm.bias").astype(dt),
        },
    }
    for i in range(cfg.num_layers):
        pre = f"model.decoder.layers.{i}."
        wq = get(pre + "self_attn.q_proj.weight").T
        wk = get(pre + "self_attn.k_proj.weight").T
        wv = get(pre + "self_attn.v_proj.weight").T
        bq = get(pre + "self_attn.q_proj.bias")
        bk = get(pre + "self_attn.k_proj.bias")
        bv = get(pre + "self_attn.v_proj.bias")
        params["layers"].append(
            {
                "attn_norm": {
                    "scale": get(pre + "self_attn_layer_norm.weight").astype(dt),
                    "bias": get(pre + "self_attn_layer_norm.bias").astype(dt),
                },
                "attn": {
                    "wqkv": pack_qkv(wq, wk, wv, cfg).astype(dt),
                    "wqkv_b": np.stack([bq, bk, bv], axis=0).astype(dt),
                    "wo": np.ascontiguousarray(
                        get(pre + "self_attn.out_proj.weight").T
                    ).astype(dt),
                    "wo_b": get(pre + "self_attn.out_proj.bias").astype(dt),
                },
                "mlp_norm": {
                    "scale": get(pre + "final_layer_norm.weight").astype(dt),
                    "bias": get(pre + "final_layer_norm.bias").astype(dt),
                },
                "mlp": {
                    "w1": np.ascontiguousarray(get(pre + "fc1.weight").T).astype(dt),
                    "w1_b": get(pre + "fc1.bias").astype(dt),
                    "w2": np.ascontiguousarray(get(pre + "fc2.weight").T).astype(dt),
                    "w2_b": get(pre + "fc2.bias").astype(dt),
                },
            }
        )
    return params


def to_hf_gpt2(params: Params, cfg: ModelConfig) -> Dict[str, np.ndarray]:
    """Parameter pytree → an HF ``GPT2LMHeadModel`` state dict (numpy fp32;
    GPT-2's Conv1D weights are input-major, so this is reshape-only) — the
    export half of the GPT-2 round trip."""
    if not cfg.tie_word_embeddings:
        raise ValueError(
            "to_hf_gpt2 exports tied-embedding models only (GPT2LMHeadModel "
            "ties lm_head to wte); an untied head would be silently dropped"
        )
    h, nd = cfg.hidden_size, cfg.num_heads * cfg.head_dim
    np32 = lambda a: np.asarray(a, np.float32)
    sd: Dict[str, np.ndarray] = {
        "transformer.wte.weight": np32(params["embed"]["tok"]),
        "transformer.wpe.weight": np32(params["embed"]["pos"]),
        "transformer.ln_f.weight": np32(params["final_norm"]["scale"]),
        "transformer.ln_f.bias": np32(params["final_norm"]["bias"]),
        "lm_head.weight": np32(params["embed"]["tok"]),
    }
    for i, lp in enumerate(params["layers"]):
        pre = f"transformer.h.{i}."
        sd[pre + "ln_1.weight"] = np32(lp["attn_norm"]["scale"])
        sd[pre + "ln_1.bias"] = np32(lp["attn_norm"]["bias"])
        sd[pre + "attn.c_attn.weight"] = np32(lp["attn"]["wqkv"]).reshape(h, 3 * nd)
        sd[pre + "attn.c_attn.bias"] = np32(lp["attn"]["wqkv_b"]).reshape(3 * nd)
        sd[pre + "attn.c_proj.weight"] = np32(lp["attn"]["wo"])
        sd[pre + "attn.c_proj.bias"] = np32(lp["attn"]["wo_b"])
        sd[pre + "ln_2.weight"] = np32(lp["mlp_norm"]["scale"])
        sd[pre + "ln_2.bias"] = np32(lp["mlp_norm"]["bias"])
        sd[pre + "mlp.c_fc.weight"] = np32(lp["mlp"]["w1"])
        sd[pre + "mlp.c_fc.bias"] = np32(lp["mlp"]["w1_b"])
        sd[pre + "mlp.c_proj.weight"] = np32(lp["mlp"]["w2"])
        sd[pre + "mlp.c_proj.bias"] = np32(lp["mlp"]["w2_b"])
    return sd


def _state_dict_from_dir(path: str) -> Dict[str, Any]:
    """Raw weight load from an HF checkpoint directory (safetensors or torch
    .bin, sharded or not) WITHOUT instantiating the model class — required
    for trust_remote_code architectures like Baichuan whose modeling code we
    neither have nor want to execute."""
    import json
    import os

    from galvatron_tpu.core.retry import with_retries

    def load_file(fn):
        # multi-GB shard reads off network storage: retry transient I/O
        # instead of abandoning the whole import (core/retry.py)
        full = os.path.join(path, fn)
        if fn.endswith(".safetensors"):
            from safetensors.numpy import load_file as st_load

            return with_retries(lambda: st_load(full), describe=f"read {fn}")
        import torch

        return with_retries(
            lambda: torch.load(full, map_location="cpu", weights_only=True),
            describe=f"read {fn}",
        )

    def read_index(idx):
        with open(idx) as f:
            return sorted(set(json.load(f)["weight_map"].values()))

    sd: Dict[str, Any] = {}
    for index in ("model.safetensors.index.json", "pytorch_model.bin.index.json"):
        idx = os.path.join(path, index)
        if os.path.exists(idx):
            shards = with_retries(
                lambda i=idx: read_index(i), describe=f"read {index}"
            )
            for fn in shards:
                sd.update(load_file(fn))
            return sd
    for single in ("model.safetensors", "pytorch_model.bin"):
        if os.path.exists(os.path.join(path, single)):
            return dict(load_file(single))
    raise FileNotFoundError(f"no model weights (safetensors/bin) under {path}")


def load_hf_checkpoint(path_or_model: Any) -> tuple:
    """(params, cfg) from a local HF checkpoint directory or an in-memory HF
    model. Supported architectures: LLaMA family (RMSNorm/SwiGLU/RoPE, no
    biases), Baichuan-1 (7B rotary / 13B ALiBi, fused W_pack), GPT-2
    (LayerNorm/GeLU/learned positions, biases) and OPT (LayerNorm/ReLU/
    learned positions with the +2 offset, biases).

    Baichuan requires a LOCAL checkpoint directory (the config.json sniff +
    raw weight read happen before transformers sees the path): a hub id
    would fall through to AutoConfig, which refuses trust_remote_code
    architectures. The other families accept whatever AutoModel resolves."""
    if isinstance(path_or_model, str):
        import json
        import os
        from types import SimpleNamespace

        # sniff model_type from the raw config.json first: baichuan is a
        # trust_remote_code architecture AutoConfig refuses to load (and
        # whose bundled modeling code we must not execute)
        raw_arch = None
        cfg_json = os.path.join(path_or_model, "config.json")
        if os.path.isfile(cfg_json):
            with open(cfg_json) as f:
                raw_cfg = json.load(f)
            raw_arch = raw_cfg.get("model_type")
        if raw_arch == "baichuan":
            hf_cfg: Any = SimpleNamespace(**raw_cfg)
            model: Any = _state_dict_from_dir(path_or_model)
        else:
            from transformers import AutoConfig, AutoModelForCausalLM

            hf_cfg = AutoConfig.from_pretrained(path_or_model)
            # exact model_type match — class-name substrings would misroute
            # any future config class whose lowercase name contains 'opt'
            arch = getattr(hf_cfg, "model_type", None)
            if arch not in ("llama", "gpt2", "opt"):
                raise ValueError(
                    f"--load_hf supports LLaMA-architecture, Baichuan, GPT-2 "
                    f"and OPT checkpoints; got {type(hf_cfg).__name__} "
                    f"(model_type={arch!r})"
                )
            # low_cpu_mem_usage streams weights instead of materializing a
            # full randomly-initialized module first (~halves host peak, 7B+)
            model = AutoModelForCausalLM.from_pretrained(
                path_or_model, low_cpu_mem_usage=True
            )
    else:
        model = path_or_model
        hf_cfg = model.config
    arch = getattr(hf_cfg, "model_type", "")
    if arch == "gpt2":
        cfg = config_from_hf_gpt2(hf_cfg)
        return from_hf_gpt2(model, cfg), cfg
    if arch == "opt":
        cfg = config_from_hf_opt(hf_cfg)
        return from_hf_opt(model, cfg), cfg
    if arch == "baichuan":
        cfg = config_from_hf_baichuan(hf_cfg)
        return from_hf_baichuan(model, cfg), cfg
    cfg = config_from_hf_llama(hf_cfg)
    return from_hf_llama(model, cfg), cfg


# back-compat name (LLaMA was the first supported architecture)
load_hf_llama = load_hf_checkpoint
