"""BERT encoder family entry — masked-LM pretraining.

The reference carries encoder support only as legacy branches (bert handling
in galvatron/core/parallel.py:64-89 and cost_model.py model_type); here it is
a live family: bidirectional attention (``causal=False``) through the same
hybrid-parallel runtime, deterministic token-hash MLM objective
(modeling.mlm_loss_sum), sizes bert-base/large.
"""

DEFAULT_MODEL = "bert-base"
SIZES = ("bert-base", "bert-large")


def main(argv=None):
    from galvatron_tpu.cli import main as cli_main

    return cli_main(argv, model_default=DEFAULT_MODEL)
