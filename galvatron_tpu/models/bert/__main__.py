from galvatron_tpu.models.bert import main

raise SystemExit(main())
