"""Autoregressive text generation with a static KV cache.

TPU-native counterpart of the reference's text-generation subsystem
(reference: galvatron/site_package/megatron/text_generation/{api.py,
generation.py,sampling.py} and text_generation_server.py): prefill + one
token-per-step decode over a preallocated KV cache, with greedy /
temperature / top-k / top-p sampling.

Design differences from the reference (which loops in Python over
dynamically growing torch tensors): the cache is a static-shape pytree and
the decode loop is a single ``lax.scan`` inside one ``jit`` — XLA sees a
fixed-shape program, so the whole generation runs on-device without host
round-trips per token.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from galvatron_tpu.models import modeling
from galvatron_tpu.models.modeling import ModelConfig, Params


class KVCache(NamedTuple):
    """Per-layer key/value tensors, (L, B, max_len, kv_heads, head_dim)."""

    k: jax.Array
    v: jax.Array


def init_kv_cache(cfg: ModelConfig, batch_size: int, max_len: int) -> KVCache:
    shape = (cfg.num_layers, batch_size, max_len, cfg.kv_heads, cfg.head_dim)
    return KVCache(jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))


def _cached_attention(q, k_cache, v_cache, q_offset, cfg: ModelConfig, alibi=None):
    """q: (B, s, nh, hd); caches: (B, Smax, kvh, hd). Delegates to
    modeling.attention_xla (same mask/softmax core); only the ALiBi bias needs
    the absolute-position rewrite here."""
    s, smax = q.shape[1], k_cache.shape[1]
    bias = None
    if alibi is not None:
        q_pos = q_offset + jnp.arange(s)
        k_pos = jnp.arange(smax)
        rel = k_pos[None, :] - q_pos[:, None]  # (s, Smax)
        bias = (alibi[:, None, None] * rel[None]).astype(jnp.float32)[None]
    return modeling.attention_xla(q, k_cache, v_cache, cfg, bias=bias, q_offset=q_offset)


def _layer_with_cache(x, p, cfg: ModelConfig, k_cache, v_cache, offset, cos_sin, alibi):
    """decoder_layer variant that reads/writes the KV cache at ``offset``.
    Returns (x_out, k_cache, v_cache)."""
    b, s, h = x.shape
    hd = cfg.head_dim
    xa = modeling.norm(x, p["attn_norm"], cfg)
    pa = p["attn"]
    q, k, v = modeling.project_qkv_heads(xa, pa, cfg)
    if cfg.pos_embed == "rope":
        cos, sin = cos_sin
        q = modeling.apply_rope(q, cos, sin)
        k = modeling.apply_rope(k, cos, sin)
    k_cache = jax.lax.dynamic_update_slice(k_cache, k.astype(k_cache.dtype), (0, offset, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(v_cache, v.astype(v_cache.dtype), (0, offset, 0, 0))
    o = _cached_attention(q, k_cache, v_cache, offset, cfg, alibi=alibi)
    x = x + modeling.attn_output(o, pa, cfg, x.dtype)
    x = x + modeling.mlp_block(
        modeling.norm(x, p["mlp_norm"], cfg), p["mlp"], cfg, train=False
    )
    return x, k_cache, v_cache


def forward_with_cache(params: Params, tokens, cfg: ModelConfig, cache: KVCache, offset):
    """Run ``tokens`` (B, s) through the model at absolute position ``offset``,
    updating the cache. Returns (logits, new_cache). ``offset`` may be traced."""
    s = tokens.shape[1]
    if cfg.pos_embed == "rope":
        # full-length tables indexed dynamically so offset can be traced
        cos_all, sin_all = modeling.rope_tables(cfg, cache.k.shape[2])
        cos = jax.lax.dynamic_slice_in_dim(cos_all, offset, s, axis=0)
        sin = jax.lax.dynamic_slice_in_dim(sin_all, offset, s, axis=0)
        cos_sin = (cos, sin)
    else:
        cos_sin = None
    alibi = (
        jnp.asarray(modeling.alibi_slopes(cfg.num_heads)) if cfg.pos_embed == "alibi" else None
    )
    x = params["embed"]["tok"].astype(cfg.dtype)[tokens]
    if cfg.pos_embed == "learned":
        pos = offset + jnp.arange(s)
        x = x + params["embed"]["pos"].astype(cfg.dtype)[pos][None]
    new_k, new_v = [], []
    for i, lp in enumerate(params["layers"]):
        x, ki, vi = _layer_with_cache(
            x, lp, cfg, cache.k[i], cache.v[i], offset, cos_sin, alibi
        )
        new_k.append(ki)
        new_v.append(vi)
    x = modeling.norm(x, params["final_norm"], cfg)
    logits = modeling.lm_head(x, params, cfg)
    return logits, KVCache(jnp.stack(new_k), jnp.stack(new_v))


# ---------------------------------------------------------------------------
# Slot-wise forward: every batch row at its own absolute position
# (continuous-batching serving — each row is a different request)
# ---------------------------------------------------------------------------


def _layer_with_cache_slots(x, p, cfg: ModelConfig, k_cache, v_cache, offsets,
                            cos_sin, alibi):
    """``_layer_with_cache`` variant where ``offsets`` is (B,): row ``b``
    reads/writes its cache at its own position. Returns (x, k_cache, v_cache)."""
    b, s, h = x.shape
    xa = modeling.norm(x, p["attn_norm"], cfg)
    pa = p["attn"]
    q, k, v = modeling.project_qkv_heads(xa, pa, cfg)
    if cfg.pos_embed == "rope":
        cos, sin = cos_sin  # (B, s, hd/2) per-row tables
        q = modeling.apply_rope(q, cos, sin)
        k = modeling.apply_rope(k, cos, sin)
    row_update = jax.vmap(
        lambda c, u, o: jax.lax.dynamic_update_slice(c, u, (o, 0, 0))
    )
    k_cache = row_update(k_cache, k.astype(k_cache.dtype), offsets)
    v_cache = row_update(v_cache, v.astype(v_cache.dtype), offsets)
    bias = None
    if alibi is not None:
        q_pos = offsets[:, None] + jnp.arange(s)[None]  # (B, s)
        k_pos = jnp.arange(k_cache.shape[1])
        rel = k_pos[None, None, :] - q_pos[:, :, None]  # (B, s, Smax)
        bias = (alibi[None, :, None, None] * rel[:, None]).astype(jnp.float32)
    o = modeling.attention_xla(q, k_cache, v_cache, cfg, bias=bias, q_offset=offsets)
    x = x + modeling.attn_output(o, pa, cfg, x.dtype)
    x = x + modeling.mlp_block(
        modeling.norm(x, p["mlp_norm"], cfg), p["mlp"], cfg, train=False
    )
    return x, k_cache, v_cache


def forward_with_cache_slots(params: Params, tokens, cfg: ModelConfig,
                             cache: KVCache, offsets):
    """Run ``tokens`` (B, s) through the model with PER-ROW absolute positions
    ``offsets`` (B,), updating row ``b`` of the cache at ``offsets[b]``.
    Returns (logits, new_cache). ``offsets`` may be traced.

    This is the forward the continuous-batching engine runs once per decode
    iteration over all slots: rows are independent requests at arbitrary
    depths into their sequences; rows holding no request are simply masked by
    the caller (their writes land at their own row's offset and are
    overwritten by the next prefill before ever becoming visible — causal
    masking keeps positions > a row's own offset invisible)."""
    b, s = tokens.shape
    smax = cache.k.shape[2]
    if cfg.pos_embed == "rope":
        cos_all, sin_all = modeling.rope_tables(cfg, smax)
        pos = offsets[:, None] + jnp.arange(s)[None]  # (B, s)
        cos_sin = (cos_all[pos], sin_all[pos])
    else:
        cos_sin = None
    alibi = (
        jnp.asarray(modeling.alibi_slopes(cfg.num_heads)) if cfg.pos_embed == "alibi" else None
    )
    x = params["embed"]["tok"].astype(cfg.dtype)[tokens]
    if cfg.pos_embed == "learned":
        pos = offsets[:, None] + jnp.arange(s)[None]
        x = x + params["embed"]["pos"].astype(cfg.dtype)[pos]
    new_k, new_v = [], []
    for i, lp in enumerate(params["layers"]):
        x, ki, vi = _layer_with_cache_slots(
            x, lp, cfg, cache.k[i], cache.v[i], offsets, cos_sin, alibi
        )
        new_k.append(ki)
        new_v.append(vi)
    x = modeling.norm(x, params["final_norm"], cfg)
    logits = modeling.lm_head(x, params, cfg)
    return logits, KVCache(jnp.stack(new_k), jnp.stack(new_v))


# ---------------------------------------------------------------------------
# Paged forward: K/V live in a shared block pool, addressed via block tables
# (serving/paged_kv.py owns the pool and the host-side allocator)
# ---------------------------------------------------------------------------


def _layer_with_cache_paged(x, p, cfg: ModelConfig, pool_k, pool_v, tables,
                            offsets, cos_sin, alibi):
    """``_layer_with_cache_slots`` variant over a paged pool: ``pool_k``/
    ``pool_v`` are (num_blocks, block_size, kvh, hd), ``tables`` is (B,
    max_blocks) int32 and row ``b``'s logical position ``p`` lives at
    ``(tables[b, p // bs], p % bs)``. Returns (x, pool_k, pool_v)."""
    from galvatron_tpu.ops import flash_attention

    b, s, h = x.shape
    bs = pool_k.shape[1]
    smax = tables.shape[1] * bs
    xa = modeling.norm(x, p["attn_norm"], cfg)
    pa = p["attn"]
    q, k, v = modeling.project_qkv_heads(xa, pa, cfg)
    if cfg.pos_embed == "rope":
        cos, sin = cos_sin  # (B, s, hd/2) per-row tables
        q = modeling.apply_rope(q, cos, sin)
        k = modeling.apply_rope(k, cos, sin)
    # scatter the new k/v through the table (duplicate targets only arise on
    # the null block, whose contents are never attended)
    pos = offsets[:, None] + jnp.arange(s)[None]  # (B, s)
    blk = jnp.take_along_axis(tables, pos // bs, axis=1)  # (B, s)
    sub = pos % bs
    pool_k = pool_k.at[blk, sub].set(k.astype(pool_k.dtype))
    pool_v = pool_v.at[blk, sub].set(v.astype(pool_v.dtype))
    if s == 1 and alibi is None and cfg.causal:
        # decode step: paged attention reads pages through the table (XLA
        # gather fallback is bit-identical to the slot engine's decode core)
        o = flash_attention.paged_decode_attention(q, pool_k, pool_v, tables, offsets)
    else:
        # prefill chunk (or bias'd attention): materialize the row's context
        # contiguously and reuse the slot attention core unchanged
        k_ctx = pool_k[tables].reshape(b, smax, *pool_k.shape[2:])
        v_ctx = pool_v[tables].reshape(b, smax, *pool_v.shape[2:])
        bias = None
        if alibi is not None:
            q_pos = offsets[:, None] + jnp.arange(s)[None]  # (B, s)
            k_pos = jnp.arange(smax)
            rel = k_pos[None, None, :] - q_pos[:, :, None]  # (B, s, Smax)
            bias = (alibi[None, :, None, None] * rel[:, None]).astype(jnp.float32)
        o = modeling.attention_xla(q, k_ctx, v_ctx, cfg, bias=bias, q_offset=offsets)
    x = x + modeling.attn_output(o, pa, cfg, x.dtype)
    x = x + modeling.mlp_block(
        modeling.norm(x, p["mlp_norm"], cfg), p["mlp"], cfg, train=False
    )
    return x, pool_k, pool_v


def forward_with_cache_paged(params: Params, tokens, cfg: ModelConfig,
                             pool: KVCache, tables, offsets):
    """Run ``tokens`` (B, s) through the model with PER-ROW positions
    ``offsets`` (B,), reading/writing K/V through ``tables`` (B, max_blocks)
    into the shared block ``pool`` (L, num_blocks, block_size, kvh, hd).
    Returns (logits, new_pool). ``tables`` and ``offsets`` may be traced —
    both are fixed-shape operands, so the compiled program is reused across
    every allocation pattern the host-side allocator produces.

    Numerics match :func:`forward_with_cache_slots` bit-for-bit when
    ``block_size * max_blocks`` equals the slot cache's max_seq_len: per-row
    rope tables, scatter-then-attend ordering and the decode attention core
    are all shared, only the K/V addressing differs (the paged/slot parity
    tests pin this)."""
    b, s = tokens.shape
    smax = tables.shape[1] * pool.k.shape[2]
    if cfg.pos_embed == "rope":
        cos_all, sin_all = modeling.rope_tables(cfg, smax)
        pos = offsets[:, None] + jnp.arange(s)[None]  # (B, s)
        cos_sin = (cos_all[pos], sin_all[pos])
    else:
        cos_sin = None
    alibi = (
        jnp.asarray(modeling.alibi_slopes(cfg.num_heads)) if cfg.pos_embed == "alibi" else None
    )
    x = params["embed"]["tok"].astype(cfg.dtype)[tokens]
    if cfg.pos_embed == "learned":
        pos = offsets[:, None] + jnp.arange(s)[None]
        x = x + params["embed"]["pos"].astype(cfg.dtype)[pos]
    new_k, new_v = [], []
    for i, lp in enumerate(params["layers"]):
        x, ki, vi = _layer_with_cache_paged(
            x, lp, cfg, pool.k[i], pool.v[i], tables, offsets, cos_sin, alibi
        )
        new_k.append(ki)
        new_v.append(vi)
    x = modeling.norm(x, params["final_norm"], cfg)
    logits = modeling.lm_head(x, params, cfg)
    return logits, KVCache(jnp.stack(new_k), jnp.stack(new_v))


# ---------------------------------------------------------------------------
# Sampling (reference: megatron/text_generation/sampling.py modify_logits_for_
# top_k_filtering / top_p_filtering + sample)
# ---------------------------------------------------------------------------


def sample_logits(key, logits, temperature=1.0, top_k: int = 0, top_p=0.0,
                  use_top_p: Optional[bool] = None):
    """logits: (B, V) → token ids (B,). temperature 0 (or <0) → greedy.

    ``temperature`` and ``top_p`` may be traced values — under jit, varying
    them does NOT recompile. ``top_k`` must be static (lax.top_k needs a
    concrete k), as must ``use_top_p``, the gate that includes the nucleus
    sort in the program (defaults from ``top_p`` when that is concrete)."""
    if use_top_p is None:
        use_top_p = (not isinstance(top_p, (int, float))) or top_p > 0
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1)
    t = jnp.asarray(temperature, jnp.float32)
    scaled = logits / jnp.where(t > 0, t, 1.0)
    if top_k > 0:
        kth = jax.lax.top_k(scaled, top_k)[0][..., -1:]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    if use_top_p:
        p = jnp.asarray(top_p, jnp.float32)
        sorted_logits = jnp.sort(scaled, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative prob >= top_p (always >= 1 tok)
        cutoff_mask = cum - probs < p
        threshold = jnp.min(jnp.where(cutoff_mask, sorted_logits, jnp.inf), axis=-1, keepdims=True)
        scaled = jnp.where((p > 0) & (scaled < threshold), -jnp.inf, scaled)
    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(t <= 0, greedy, sampled)


def host_probs(logits, temperature: float, top_k: int, top_p: float):
    """Host-side (numpy, float64) mirror of :func:`sample_logits`'s
    processed distribution over ONE position: temperature scaling, top-k
    filter, nucleus cutoff (smallest prefix with cumulative prob >= top_p,
    always >= 1 token) → normalized probabilities (V,).

    Shared by the serving engine's per-slot sampler and the speculative
    verifier's acceptance test — both must score tokens under the SAME
    distribution the sampler draws from, or rejection sampling stops being
    exact. Greedy (temperature <= 0) returns a one-hot at the argmax.
    """
    logits = np.asarray(logits, np.float64)
    p = np.zeros_like(logits)
    if temperature <= 0:
        p[np.argmax(logits)] = 1.0
        return p
    scaled = logits / temperature
    if top_k > 0:
        kth = np.sort(scaled)[-min(top_k, len(scaled))]
        scaled = np.where(scaled < kth, -np.inf, scaled)
    if top_p > 0:
        sorted_logits = np.sort(scaled)[::-1]
        shifted = sorted_logits - sorted_logits[0]
        probs = np.exp(shifted) / np.exp(shifted).sum()
        cum = np.cumsum(probs)
        keep = cum - probs < top_p
        threshold = sorted_logits[keep].min()
        scaled = np.where(scaled < threshold, -np.inf, scaled)
    shifted = scaled - scaled.max()
    p = np.exp(shifted)
    return p / p.sum()


# ---------------------------------------------------------------------------
# Generation loop
# ---------------------------------------------------------------------------


@partial(
    jax.jit,
    static_argnames=(
        "cfg",
        "max_new_tokens",
        "min_prompt_len",
        "top_k",
        "use_top_p",
        "eos_id",
        "pad_id",
    ),
)
def generate(
    params: Params,
    prompt: jax.Array,  # (B, P) int32, right-padded with pad_id
    prompt_lengths: jax.Array,  # (B,) true lengths
    cfg: ModelConfig,
    key: jax.Array,
    max_new_tokens: int = 32,
    min_prompt_len: Optional[int] = None,  # static int(prompt_lengths.min())
    temperature=0.0,  # traced: varying it does not recompile
    top_k: int = 0,
    top_p=0.0,  # traced; use_top_p gates the nucleus sort into the program
    use_top_p: bool = False,
    eos_id: int = -1,
    pad_id: int = 0,
) -> jax.Array:
    """Prefill + lockstep scan decode (the reference's scheme: right-padded
    prompts, generation starts at min(context_length), prompt tokens override
    sampled ones until each row's own prompt is exhausted — megatron/
    text_generation/generation.py generate_tokens_probs_and_return_on_first_
    stage). Returns (B, P + max_new_tokens); positions past a row's eos are
    ``pad_id``."""
    if not cfg.causal or cfg.objective != "clm" or cfg.enc_layers > 0:
        raise ValueError(
            "generation requires a decoder-only causal LM (encoder families "
            "train with objective='mlm'; enc-dec decode is not implemented)"
        )
    b, p_len = prompt.shape
    if min_prompt_len is None:
        min_prompt_len = p_len
    max_len = p_len + max_new_tokens
    cache = init_kv_cache(cfg, b, max_len)

    # prefill positions [0, min_prompt_len); all rows have real tokens there
    logits, cache = forward_with_cache(
        params, prompt[:, :min_prompt_len], cfg, cache, 0
    )
    last = logits[:, -1]  # (B, V) — logits at position min_prompt_len-1

    out = jnp.concatenate(
        [prompt, jnp.full((b, max_new_tokens), pad_id, jnp.int32)], axis=1
    )

    def step(carry, i):
        cache, last, key, done, out = carry
        key, sub = jax.random.split(key)
        sampled = sample_logits(
            sub, last, temperature, top_k, top_p, use_top_p=use_top_p
        ).astype(jnp.int32)
        in_prompt = i < prompt_lengths  # (B,) teacher-force rows still in prompt
        tok = jnp.where(in_prompt, out[:, i], jnp.where(done, pad_id, sampled))
        done = done | (~in_prompt & (tok == eos_id))
        out = jax.lax.dynamic_update_slice(out, tok[:, None], (0, i))

        def do_fwd(cache):  # predict position i+1
            logits, cache = forward_with_cache(params, tok[:, None], cfg, cache, i)
            return logits[:, 0], cache

        def skip_fwd(cache):  # last step: nothing left to predict
            return last, cache

        last2, cache = jax.lax.cond(i < max_len - 1, do_fwd, skip_fwd, cache)
        return (cache, last2, key, done, out), None

    done = jnp.zeros((b,), bool)
    steps = jnp.arange(min_prompt_len, max_len)
    carry = (cache, last, key, done, out)
    (cache, _, _, _, out), _ = jax.lax.scan(step, carry, steps)
    return out


def generate_np(params, cfg: ModelConfig, prompts, length_bucket: int = 64, **kw):
    """Host-side convenience: list of variable-length token lists → padded
    arrays → ``generate`` → list of token lists (stopping at eos).

    Prompt length is padded UP and min_prompt_len rounded DOWN to multiples of
    ``length_bucket`` so repeat calls with naturally varying prompt lengths
    hit the jit cache instead of recompiling per length."""
    lengths = np.asarray([len(p) for p in prompts], np.int32)
    if int(lengths.min()) < 1:
        raise ValueError("empty prompt")
    max_new = kw.get("max_new_tokens", 32)
    p_raw = int(lengths.max())
    if p_raw + max_new > cfg.max_seq_len:
        raise ValueError(
            f"prompt ({p_raw}) + max_new_tokens ({max_new}) exceeds "
            f"max_seq_len {cfg.max_seq_len}"
        )
    # pad up to the bucket when the seq-len window allows it
    p_len = min(-(-p_raw // length_bucket) * length_bucket,
                max(p_raw, cfg.max_seq_len - max_new))
    pad_id = kw.get("pad_id", 0)
    batch = np.full((len(prompts), p_len), pad_id, np.int32)
    for i, p in enumerate(prompts):
        batch[i, : len(p)] = p
    key = kw.pop("key", jax.random.key(0))
    tp = kw.get("top_p", 0.0)
    kw.setdefault("use_top_p", not isinstance(tp, (int, float)) or tp > 0)
    min_len = max(1, int(lengths.min()) // length_bucket * length_bucket)
    out = generate(
        params,
        jnp.asarray(batch),
        jnp.asarray(lengths),
        cfg,
        key,
        min_prompt_len=min_len,
        **kw,
    )
    out = np.asarray(out)
    eos_id = kw.get("eos_id", -1)
    res = []
    for i, row in enumerate(out):
        toks = row[: lengths[i]].tolist()
        for t in row[lengths[i] : lengths[i] + kw.get("max_new_tokens", 32)]:
            if t == eos_id:
                break
            toks.append(int(t))
        res.append(toks)
    return res
