from galvatron_tpu.models.llama_fa import main

raise SystemExit(main())
