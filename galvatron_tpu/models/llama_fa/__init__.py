"""LLaMA flash-attention family entry (reference: galvatron/models/llama_fa/ —
the flash-attn GPT backbone variant of llama_hf, models/llama_fa/
LlamaModel_tensor_parallel.py:1-14).

On TPU the flash path is the Pallas flash-attention kernel
(galvatron_tpu.ops.flash_attention) rather than an alternative backbone: the
same functional model runs with ``attn_impl='flash'`` forced, which this entry
defaults (the reference's *_fa families likewise exist to pin the fused
attention implementation and its BSH activation layout; here the layout is
XLA's concern).
"""

from galvatron_tpu.models.llama import SIZES  # noqa: F401 — same sizes

DEFAULT_MODEL = "llama-7b"

# modes whose arg parser carries --attn_impl (train/profile share training args)
_ATTN_MODES = ("train", "train_dist", "profile")


def fa_main(argv, model_default: str):
    """Shared *_fa entry: forward to the CLI with the family's size default
    and ``--attn_impl flash`` injected unless the user chose an impl."""
    import sys

    from galvatron_tpu.cli import main as cli_main

    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in _ATTN_MODES and not any(
        a == "--attn_impl" or a.startswith("--attn_impl=") for a in argv
    ):
        argv += ["--attn_impl", "flash"]
    return cli_main(argv, model_default=model_default)


def main(argv=None):
    return fa_main(argv, DEFAULT_MODEL)
