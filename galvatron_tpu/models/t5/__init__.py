"""T5 encoder-decoder family entry.

The reference carries t5 only as legacy branches (model_type handling in
galvatron/core/parallel.py:64-89 and cost_model.py); here it is a live
family: bidirectional encoder stack + causal decoder with cross-attention
through the hybrid-parallel runtime (pp=1; per-layer strategies cover the
encoder then the decoder — the two layer types feed the multi-layer-type
search). Sizes t5-base/large/3b. Positions are learned embeddings rather
than T5's relative bias (documented deviation, modeling.PRESETS).
"""

DEFAULT_MODEL = "t5-base"
SIZES = ("t5-base", "t5-large", "t5-3b")


def main(argv=None):
    from galvatron_tpu.cli import main as cli_main

    return cli_main(argv, model_default=DEFAULT_MODEL)
