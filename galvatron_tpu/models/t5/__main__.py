from galvatron_tpu.models.t5 import main

raise SystemExit(main())
