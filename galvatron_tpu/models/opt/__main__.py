from galvatron_tpu.models.opt import main

raise SystemExit(main())
