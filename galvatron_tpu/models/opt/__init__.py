"""OPT family entry (decoder-only, ReLU MLPs, learned positions; HF import
via models/convert.py — the gpt_hf-style HF-wrapping family pattern,
reference: galvatron/models/gpt_hf/)."""

DEFAULT_MODEL = "opt-1.3b"
SIZES = ("opt-125m", "opt-1.3b", "opt-6.7b", "opt-13b", "opt-30b")


def main(argv=None):
    from galvatron_tpu.cli import main as cli_main

    return cli_main(argv, model_default=DEFAULT_MODEL)
