"""GPT-2 family entry (reference: galvatron/models/gpt_hf/ and gpt_fa/).
Sizes: gpt-0.3b/1.5b/2.7b/6.7b (reference arguments.py:6)."""

DEFAULT_MODEL = "gpt-1.5b"
SIZES = ("gpt-0.3b", "gpt-1.5b", "gpt-2.7b", "gpt-6.7b")


def main(argv=None):
    from galvatron_tpu.cli import main as cli_main

    return cli_main(argv, model_default=DEFAULT_MODEL)
