from galvatron_tpu.models.gpt import main

raise SystemExit(main())
