"""Switch-style Mixture-of-Experts MLP with expert parallelism.

Counterpart of the reference's ``SwitchMLP`` (reference:
galvatron/core/tensor_parallel/transformer.py:161-295): a top-1 router with
sinkhorn load balancing during training and expert weights distributed across
data-parallel ranks (expert parallelism; reference group plumbing:
site_package/megatron/core/parallel_state.py:450-478,611-621,890-901).

The TPU-native formulation is the GShard/Mesh-TensorFlow dense-dispatch
recipe rather than the reference's gather/scatter over token lists: a static
per-expert capacity C turns routing into two einsums against a (tokens,
experts, capacity) one-hot dispatch tensor, so every shape is static, the
expert FFN is one big batched matmul on the MXU, and sharding the expert
dimension over the ``ep`` mesh axes makes XLA insert the all-to-all that
Megatron's expert-parallel ``gather_from_sequence_parallel_region`` hand
codes. Tokens overflowing an expert's capacity pass through on the residual
path (standard switch-transformer semantics).

Router normalization is batch-dependent (sinkhorn balances over the routed
token group), so micro-batched execution — pipeline engines and chunked
accumulation route per micro-batch — yields slightly different assignments
than one full-batch forward (measured ~0.2% on a tiny model's eval loss at
chunks=2). This is inherent to capacity-style MoE under micro-batching (the
reference's SwitchMLP normalizes per forward call the same way), not an
engine discrepancy: at chunks=1 the pipeline path is exact against the flat
model (pinned in test_moe.py::test_moe_pipeline_parallel_parity).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

Params = Dict[str, Any]


def sinkhorn(logits: jax.Array, n_iters: int = 8) -> jax.Array:
    """Sinkhorn-normalized routing scores (balanced assignment), fixed
    iteration count for XLA (the reference iterates to tolerance on host,
    transformer.py:163-174 — data-dependent loops don't trace)."""
    cost = jnp.exp(logits - jax.lax.stop_gradient(logits.max()))
    T, E = cost.shape
    d1 = jnp.ones((E,), cost.dtype)

    def body(_, d1):
        d0 = 1.0 / (T * (cost @ d1 + 1e-8))
        return 1.0 / (E * (d0 @ cost + 1e-8))

    d1 = jax.lax.fori_loop(0, n_iters, body, d1)
    d0 = 1.0 / (T * (cost @ d1 + 1e-8))
    return cost * d0[:, None] * d1[None, :]


def moe_capacity(num_tokens: int, num_experts: int, capacity_factor: float) -> int:
    """Static per-expert token capacity, padded to a multiple of 8 for TPU
    tiling."""
    c = int(np.ceil(num_tokens / num_experts * capacity_factor))
    return max(8, (c + 7) // 8 * 8)


def route_top1(logits: jax.Array, capacity: int, *, sinkhorn_iters: int = 8,
               train: bool = True):
    """Top-1 switch routing with capacity limiting.

    During training the assignment comes from the sinkhorn-balanced scores; at
    inference it is the raw-logit argmax (the reference does the same:
    sinkhorn under no_grad for training routing, plain argmax at eval,
    transformer.py:231-246 — and sinkhorn over a tiny batch degenerates to
    uniform scores, so batch-1 decode would always pick expert 0). The gate
    value is the sigmoid of the raw logit at the chosen expert either way.

    Returns (dispatch, combine): dispatch is a (T, E, C) one-hot used to
    scatter tokens into per-expert slots; combine = dispatch · gate gathers
    expert outputs back, zero for capacity-dropped tokens.
    """
    T, E = logits.shape
    if train:
        scores = sinkhorn(logits.astype(jnp.float32), sinkhorn_iters)
    else:
        scores = logits.astype(jnp.float32)
    expert_idx = jnp.argmax(scores, axis=-1)  # (T,)
    gate = jax.nn.sigmoid(
        jnp.take_along_axis(logits.astype(jnp.float32), expert_idx[:, None], axis=1)[:, 0]
    )
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # (T, E)
    # position of each token within its expert's buffer
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # (T, E)
    pos_in_expert = jnp.sum(pos, axis=-1).astype(jnp.int32)  # (T,)
    kept = (pos_in_expert < capacity).astype(jnp.float32)
    dispatch = (
        onehot[:, :, None] * jax.nn.one_hot(pos_in_expert, capacity, dtype=jnp.float32)[:, None, :]
    ) * kept[:, None, None]  # (T, E, C)
    combine = dispatch * gate[:, None, None]
    return dispatch, combine


def init_moe_params(key, cfg) -> Params:
    """Router + stacked expert FFN weights (E leading dim)."""
    h, f, e = cfg.hidden_size, cfg.ffn, cfg.moe_experts
    ks = jax.random.split(key, 4)
    scale_in = 1.0 / np.sqrt(h)
    scale_out = 1.0 / np.sqrt(f)
    p: Params = {
        "router": {"w": jax.random.normal(ks[0], (h, e), cfg.param_dtype) * 0.02},
        "w1": jax.random.uniform(ks[1], (e, h, f), cfg.param_dtype, -scale_in, scale_in),
        "w2": jax.random.uniform(ks[2], (e, f, h), cfg.param_dtype, -scale_out, scale_out),
    }
    if cfg.act_fn == "swiglu":
        p["w3"] = jax.random.uniform(ks[3], (e, h, f), cfg.param_dtype, -scale_in, scale_in)
    return p


def moe_annotations(cfg) -> Params:
    """Logical axes: 'ep' shards the expert dim over the expert-parallel mesh
    axes; within an expert the FFN dims carry the usual Megatron 'tp'
    column/row sharding; 'fsdp' dims ZeRO-shard over the non-EP data axes.

    The router weight stays replicated: it is a tiny (h, E) matrix, and
    ZeRO-sharding its h dim propagates an h-sharding onto the flattened
    token activations, which forced an SPMD "involuntary full
    rematerialization" (replicate-then-repartition) on the dispatch reshape
    — measurable HBM traffic for ~zero memory savings."""
    a: Params = {
        "router": {"w": (None, None)},
        "w1": ("ep", "fsdp", "tp"),
        "w2": ("ep", "tp", "fsdp"),
    }
    if cfg.act_fn == "swiglu":
        a["w3"] = ("ep", "fsdp", "tp")
    return a


def moe_block(x: jax.Array, p: Params, cfg, train: bool = True) -> jax.Array:
    """Switch-MoE MLP on a (B, S, H) activation (SwitchMLP.forward equivalent,
    reference: transformer.py:210-295).

    When ``cfg.moe_shard_ctx`` is installed (layer hooks, ep>1), the token-
    side tensors are pinned to the token/batch sharding and the per-expert
    buffers to the ep sharding, so the expert all-to-all happens exactly at
    the dispatch/combine einsums — without the pins, sharding propagation
    let the backward pick an SPMD replicate-and-repartition ("involuntary
    full rematerialization") on the dispatch reshape."""
    from jax.sharding import PartitionSpec as P

    ctx = cfg.moe_shard_ctx

    def pin_tok(a):  # (T, ...) token-major
        if ctx is None:
            return a
        from galvatron_tpu.parallel.sharding import constrain

        mesh, _, tok_ax = ctx
        return constrain(a, mesh, P(tok_ax, *([None] * (a.ndim - 1))))

    def pin_ep(a):  # (E, ...) expert-major
        if ctx is None:
            return a
        from galvatron_tpu.parallel.sharding import constrain

        mesh, ep_ax, _ = ctx
        return constrain(a, mesh, P(ep_ax, *([None] * (a.ndim - 1))))

    b, s, h = x.shape
    T = b * s
    E = cfg.moe_experts
    xt = pin_tok(x.reshape(T, h))
    logits = xt.astype(jnp.float32) @ p["router"]["w"].astype(jnp.float32)  # (T, E)
    C = moe_capacity(T, E, cfg.moe_capacity_factor)
    dispatch, combine = route_top1(
        logits, C, sinkhorn_iters=cfg.moe_sinkhorn_iters, train=train
    )
    dispatch, combine = pin_tok(dispatch), pin_tok(combine)

    # scatter tokens into per-expert buffers: (E, C, H). XLA turns the expert
    # dim's sharding mismatch (tokens batch-sharded vs experts ep-sharded)
    # into the expert-parallel all-to-all.
    xe = pin_ep(jnp.einsum("tec,th->ech", dispatch.astype(x.dtype), xt))
    w1 = p["w1"].astype(x.dtype)
    w2 = p["w2"].astype(x.dtype)
    if cfg.act_fn == "swiglu":
        w3 = p["w3"].astype(x.dtype)
        hmid = jax.nn.silu(jnp.einsum("ech,ehf->ecf", xe, w1)) * jnp.einsum(
            "ech,ehf->ecf", xe, w3
        )
    else:
        hmid = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", xe, w1), approximate=True)
    ye = pin_ep(jnp.einsum("ecf,efh->ech", hmid, w2))
    yt = pin_tok(jnp.einsum("tec,ech->th", combine.astype(x.dtype), ye))
    return yt.reshape(b, s, h)
