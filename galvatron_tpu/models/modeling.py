"""Functional decoder-only Transformer covering the reference model zoo.

One configurable implementation replaces the reference's five per-family
variants (galvatron/models/{gpt_hf,llama_hf,gpt_fa,llama_fa,baichuan}):

- GPT-2 style: learned positions + LayerNorm + GeLU MLP + tied embeddings
  (reference: models/gpt_hf/GPTModel_sequential.py, GPTModel_tensor_parallel.py)
- LLaMA style: RoPE + RMSNorm + SwiGLU + GQA
  (reference: models/llama_hf/LlamaModel_tensor_parallel.py:10-75)
- Baichuan style: LLaMA-like, ALiBi option for the 13B variant
  (reference: models/baichuan/BaiChuanModel_sequential.py)

Everything is pure functions over parameter pytrees — no Module wrapping — so
per-layer hybrid strategies are just per-layer sharding specs applied to the
same code (SURVEY §7 design stance). Each parameter has a logical-axes
annotation consumed by galvatron_tpu.parallel.sharding.

Attention dispatch mirrors the reference's core-vs-flash switch
(galvatron/core/tensor_parallel/transformer.py:805-820): "xla" einsum path,
"flash" Pallas kernel, "ring" context-parallel ring attention.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
from jax.ad_checkpoint import checkpoint_name

from galvatron_tpu import compat
import jax.numpy as jnp
import numpy as np

from galvatron_tpu.ops.quant import QuantTensor, qeinsum, qmatmul

Params = Dict[str, Any]


@dataclass(frozen=True)
class ModelConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: Optional[int] = None  # None → MHA; < num_heads → GQA
    ffn_dim: Optional[int] = None  # None → 4h (gelu) or llama 8h/3 rounding
    max_seq_len: int = 2048
    pos_embed: str = "rope"  # 'rope' | 'learned' | 'alibi'
    norm_type: str = "rms"  # 'rms' | 'layernorm'
    act_fn: str = "swiglu"  # 'swiglu' | 'gelu' | 'relu' (OPT-style)
    tie_word_embeddings: bool = False
    # GPT-2-style projection biases on qkv/out/mlp GEMMs (norm biases are
    # governed by norm_type). Requires the blocked qkv layout (no GQA).
    use_bias: bool = False
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    attn_impl: str = "xla"  # 'xla' | 'flash' | 'ring'
    # decoder (causal LM) vs encoder (bidirectional, e.g. BERT) attention.
    # The reference's legacy encoder support (bert/vit branches,
    # galvatron/core/parallel.py:64-89, cost_model.py model_type).
    causal: bool = True
    # encoder-decoder (T5-class; reference legacy t5 model_type): > 0 adds
    # that many bidirectional encoder layers; the ``num_layers`` decoder
    # layers gain cross-attention over the encoder output. Samples are
    # (B, enc_seq + max_seq_len + 1) token rows: encoder input ‖ decoder
    # stream (deviation from T5: RoPE/learned positions, not relative bias).
    enc_layers: int = 0
    enc_seq: int = 0
    # training objective: 'clm' next-token LM; 'mlm' masked-LM (encoder
    # pretraining) with deterministic token-hash masking (see mlm_loss_sum)
    objective: str = "clm"
    mlm_mask_rate: float = 0.15
    # Pallas fused rms/layernorm kernels (opt-in). Off by default: measured
    # on the v5e 7B-shape bench (2026-07-30), XLA's own norm fusion beats the
    # custom kernels by ~0.05 ms/layer/sample fwd and ~0.27 fwd+bwd — the
    # custom-call boundary blocks producer/consumer fusion with the residual
    # adds and GEMMs around the norm (BASELINE.md round-2 notes).
    fused_norm: bool = False
    dtype: Any = jnp.bfloat16  # compute dtype
    param_dtype: Any = jnp.float32
    # Mixture-of-Experts (SwitchMLP equivalent, reference:
    # galvatron/core/tensor_parallel/transformer.py:161-295). 0 → dense MLP.
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_sinkhorn_iters: int = 8
    # (mesh, ep_axes, token_axes) installed by the layer hooks for ep>1
    # layers so moe_block can pin dispatch-buffer shardings (keeps the
    # expert all-to-all at the dispatch einsum instead of an SPMD
    # replicate-and-repartition). None → unconstrained (single-device paths).
    moe_shard_ctx: Optional[Any] = None
    # (mesh, batch_axes) installed by the layer hooks for zero3+tp layers:
    # attn_block pins the attention context o to batch-sharded/head-replicated
    # before the output projection. Without it the dWo^T grad dot (output
    # sharded fsdp x tp) finds no common axes with the batch-sharded dy and
    # the SPMD partitioner falls back to an involuntary full rematerialization
    # (world-wide replicate) of dy — XLA b/433785288. The pin trades that for
    # a tp-wide gather of o in forward. None → unconstrained.
    attn_out_shard_ctx: Optional[Any] = None
    # (mesh, batch_axes, head_axes) installed by the layer hooks for tp>1
    # flash layers: _attn_block_headmajor pins the stacked (b, 3, n, s, d)
    # qkv projection output to (dp, -, tp, -, -). The forward pin is a no-op
    # (it matches propagation), but with_sharding_constraint's transpose
    # applies the same spec to the BACKWARD cotangent — without it GSPMD has
    # been seen sharding the combined bwd kernel's dqkv along the size-3
    # stack axis (padding it across tp x dp devices) and paying an
    # involuntary replicate-and-repartition. None → unconstrained.
    qkv_shard_ctx: Optional[Any] = None
    # (mesh, batch_axes, head_axes) installed by the layer hooks for flash
    # layers on ANY multi-device mesh: GSPMD cannot partition Mosaic custom
    # calls ("Mosaic kernels cannot be automatically partitioned"), so every
    # kernel invocation is wrapped in a shard_map over the batch (dp) and
    # head (tp) axes — each device runs the kernel on its local shard. The
    # CPU simulation never surfaces this (interpret-mode kernels are plain
    # jnp ops GSPMD can partition); a real-TPU topology AOT compile does
    # (tests/test_topology_aot.py). None → direct call (single device).
    flash_shard_ctx: Optional[Any] = None
    # (mesh, dp_axes, tp_axes, sp) installed by the layer hooks for tp>1
    # layers whose plan sets tp_overlap (core/strategy.LayerStrategy): the
    # column-parallel projections (_proj_up: qkv, MLP gate/up) route through
    # ops.collective_matmul.allgather_einsum on sp layers — the blocking
    # GSPMD seq all-gather becomes a ppermute ring pipelined behind the GEMM
    # chunks — and the row-parallel projections (_proj_down: wo, w2) through
    # einsum_reducescatter, which pipelines the trailing all-reduce /
    # reduce-scatter as an accumulator ring. None → plain einsums (GSPMD
    # inserts the blocking collectives).
    tp_overlap_ctx: Optional[Any] = None
    # vision families (reference legacy vit/swin model_type branches,
    # galvatron/core/parallel.py:64-89, cost_model.py:76,87-106).
    # image_size > 0 switches the input pipeline from token ids to uint8
    # pixel rows: one sample = (image_size² · num_channels) pixel values in
    # 0..255 stored as int32 ‖ one class label — so the whole runtime keeps
    # its single (B, sample_len+1) int32 batch contract (pipelines, loaders,
    # checkpoints all unchanged).
    image_size: int = 0
    patch_size: int = 16
    num_channels: int = 3
    num_classes: int = 1000
    # Swin: non-empty depths → hierarchical stages; stage s runs depths[s]
    # windowed-attention layers at width hidden_size·2^s and resolution
    # (image_size/patch_size)/2^s per side, with a patch-merging projection
    # between stages. Empty → plain ViT encoder.
    swin_depths: Tuple[int, ...] = ()
    swin_window: int = 7
    # Head-major flash dataflow (einsum projections straight to (b, 3, n, s,
    # hd) + head-major kernels — the production flash path; see
    # _attn_block_headmajor). False routes flash layers through the legacy
    # project→transpose→flash_attention wrapper instead — used by kernel A/B
    # harnesses (experiments/ab_flash.py) that monkeypatch
    # ops.flash_attention.flash_attention, which the head-major wiring
    # bypasses.
    flash_headmajor: bool = True
    # Activation-memory recompute over the MLP/norm/loss regions
    # (--mlp_recompute; DESIGN.md "Activation memory accounting"). The HLO
    # buffer audit (BASELINE.md round 5) showed the backward holding TWO
    # saved copies of the swiglu gate per layer plus fp32-widened (B, S, H)/
    # vocab-shard copies of bf16 activations (norm statistics and the
    # cross-entropy cast) — real HBM that caps feasible batch size.
    #   'policy': jax.checkpoint over the norm+MLP residual branch with a
    #     save_only_these_names('mlp_gate') policy — the gate projection
    #     output is saved exactly once (compute dtype) and everything else
    #     (the fp32 norm statistics, the silu·gate / gelu product) is
    #     recomputed in the backward; standalone norms and the cross-entropy
    #     fp32 cast are likewise rematerialized from their narrow inputs
    #     (cast at the consumer, never saved widened). The default.
    #   'gate': only the activation-product remat — the shape
    #     experiments/swiglu_recompute_probe.py measured (one gate save,
    #     fp32 widenings untouched).
    #   'off': the pre-policy behaviour (double gate save + widened saves).
    mlp_recompute: str = "policy"
    # Packed-sequence input rows (--pack_sequences; galvatron_tpu.data):
    # a sample row is [tokens (S+1) ‖ segment ids (S+1)] — documents
    # bin-packed into one fixed-S row. The model then (a) blocks attention
    # across segment boundaries (intra-segment causal mask — cross-document
    # attention is provably impossible), (b) resets rope/learned positions
    # per segment (positions_from_segments), and (c) masks loss at segment
    # boundaries and on padding (split_batch). CLM decoder-only; requires
    # the 'xla' attention path (the Pallas kernels carry no segment mask).
    pack_sequences: bool = False

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def total_layers(self) -> int:
        """Layers carrying a per-layer strategy: encoder + decoder."""
        return self.enc_layers + self.num_layers

    @property
    def grid(self) -> int:
        """Vision: patches per image side at stage 0."""
        return self.image_size // self.patch_size

    @property
    def n_patches(self) -> int:
        return self.grid * self.grid

    @property
    def sample_len(self) -> int:
        """Token length of one training sample (before the +1 label shift)."""
        if self.image_size:
            return self.image_size * self.image_size * self.num_channels
        return self.enc_seq + self.max_seq_len if self.enc_layers else self.max_seq_len

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def qkv_blocked(self) -> bool:
        """Fused-QKV weight layout: blocked (h, 3, n·hd) without GQA —
        contiguous q/k/v extraction — vs GQA-interleaved (see qkv_dims)."""
        return self.kv_heads == self.num_heads

    @property
    def ffn(self) -> int:
        if self.ffn_dim is not None:
            return self.ffn_dim
        if self.act_fn == "swiglu":
            # llama convention: 2/3 * 4h rounded up to multiple of 256
            f = int(2 * 4 * self.hidden_size / 3)
            return (f + 255) // 256 * 256
        return 4 * self.hidden_size

    def replace(self, **kw) -> "ModelConfig":
        return dataclasses.replace(self, **kw)


# ---------------------------------------------------------------------------
# Parameter initialization + logical-axes annotations
# ---------------------------------------------------------------------------


def _dense_init(key, in_dim, out_dim, dtype):
    scale = 1.0 / np.sqrt(in_dim)
    return jax.random.uniform(key, (in_dim, out_dim), dtype, -scale, scale)


def qkv_dims(cfg: ModelConfig) -> Tuple[int, int]:
    """(kv groups, per-group width) of the fused QKV projection in the GQA
    (interleaved) layout: columns are interleaved by kv-head group — group g
    holds its n/kv query heads then its k head then its v head (Megatron's
    fused-QKV ColumnParallel layout with GQA head-group splitting, reference:
    galvatron/core/tensor_parallel/transformer.py:679-708) — so TP shards at
    kv-group boundaries never split a q|k|v slice.

    Without GQA (kv_heads == num_heads, ``cfg.qkv_blocked``) the weight is
    instead stored 3D as (h, 3, n·hd) — one slot each for Q/K/V, TP sharding
    the head dim of every slot. The blocked layout makes the q/k/v extraction
    a contiguous slice; the interleaved layout's per-head strided gather
    costs ~2 ms/layer-batch at the 7B shape on v5e."""
    group = (cfg.num_heads // cfg.kv_heads + 2) * cfg.head_dim
    return cfg.kv_heads, group


def qkv_project(x, w, cfg: ModelConfig):
    """Fused QKV GEMM in the stored layout's natural shape: blocked weights
    (h, 3, n·hd) contract via einsum to (…, 3, n·hd); interleaved weights
    (h, kv·group) via a plain matmul. int8-quantized weights (serving,
    ops.quant) dequantize inside the GEMM with an fp32 accumulator."""
    if isinstance(w, QuantTensor):
        if cfg.qkv_blocked:
            return qeinsum("...h,hcd->...cd", x, w)
        return qmatmul(x, w)
    if cfg.qkv_blocked:
        return jnp.einsum("...h,hcd->...cd", x, w.astype(x.dtype))
    return x @ w.astype(x.dtype)


def project_qkv_heads(x, p_attn, cfg: ModelConfig):
    """Fused projection straight to per-head q/k/v — the only supported way
    to consume an attention param dict (qkv_project and split_qkv are
    layout-dependent halves that must always be paired; the optional
    GPT-2-style bias rides the blocked (3, n·hd) slots)."""
    y = qkv_project(x, p_attn["wqkv"], cfg)
    if "wqkv_b" in p_attn:
        y = y + p_attn["wqkv_b"].astype(y.dtype)
    return split_qkv(y, cfg)


def attn_output(o, p_attn, cfg: ModelConfig, dtype):
    """(B, S, n, hd) attention context → (B, S, h) via the output projection
    (+ optional bias, added after the row-parallel reduction)."""
    b, s = o.shape[:2]
    ctx = o.reshape(b, s, cfg.num_heads * cfg.head_dim)
    wo = p_attn["wo"]
    y = qmatmul(ctx, wo) if isinstance(wo, QuantTensor) else ctx @ wo.astype(dtype)
    if "wo_b" in p_attn:
        y = y + p_attn["wo_b"].astype(dtype)
    return y


def split_qkv(qkv, cfg: ModelConfig):
    """Fused projection → q (…, n, hd), k/v (…, kv, hd). Accepts the blocked
    (…, 3, n·hd) or interleaved (…, kv·group) projection output."""
    if cfg.qkv_blocked:
        lead = qkv.shape[:-2]
        r = qkv.reshape(*lead, 3, cfg.num_heads, cfg.head_dim)
        return r[..., 0, :, :], r[..., 1, :, :], r[..., 2, :, :]
    kv, group = qkv_dims(cfg)
    npg = cfg.num_heads // cfg.kv_heads  # query heads per kv group
    r = qkv.reshape(*qkv.shape[:-1], kv, npg + 2, cfg.head_dim)
    q = r[..., :npg, :].reshape(*qkv.shape[:-1], cfg.num_heads, cfg.head_dim)
    return q, r[..., npg, :], r[..., npg + 1, :]


def init_layer_params(key, cfg: ModelConfig, cross: bool = False) -> Params:
    h, hd = cfg.hidden_size, cfg.head_dim
    q_out = cfg.num_heads * hd
    kv_out = cfg.kv_heads * hd
    kv, group = qkv_dims(cfg)
    ks = jax.random.split(key, 8)
    wqkv = _dense_init(ks[0], h, kv * group, cfg.param_dtype)
    if cfg.qkv_blocked:
        wqkv = wqkv.reshape(h, 3, q_out)
    p: Params = {
        "attn_norm": {"scale": jnp.ones((h,), cfg.param_dtype)},
        "attn": {
            "wqkv": wqkv,
            "wo": _dense_init(ks[3], q_out, h, cfg.param_dtype),
        },
        "mlp_norm": {"scale": jnp.ones((h,), cfg.param_dtype)},
    }
    if cfg.use_bias:
        if not cfg.qkv_blocked:
            raise ValueError("use_bias needs the blocked qkv layout (no GQA)")
        p["attn"]["wqkv_b"] = jnp.zeros((3, q_out), cfg.param_dtype)
        p["attn"]["wo_b"] = jnp.zeros((h,), cfg.param_dtype)
    if cross:  # enc-dec decoder layer: cross-attention over the encoder output
        ck = jax.random.split(ks[7], 4)
        p["cross_norm"] = {"scale": jnp.ones((h,), cfg.param_dtype)}
        p["cross"] = {
            "wq": _dense_init(ck[0], h, q_out, cfg.param_dtype),
            "wkv": _dense_init(ck[1], h, 2 * kv_out, cfg.param_dtype),
            "wo": _dense_init(ck[3], q_out, h, cfg.param_dtype),
        }
        if cfg.norm_type == "layernorm":
            p["cross_norm"]["bias"] = jnp.zeros((h,), cfg.param_dtype)
    if cfg.moe_experts > 0:
        from galvatron_tpu.models import moe

        p["mlp"] = moe.init_moe_params(ks[4], cfg)
    elif cfg.act_fn == "swiglu":
        # fused gate pair [w1 | w3] (Megatron dense_h_to_4h with swiglu,
        # reference ParallelMLP transformer.py:78-159): one wide GEMM; the F
        # boundary aligns with every power-of-two TP shard
        p["mlp"] = {
            "w13": _dense_init(ks[4], h, 2 * cfg.ffn, cfg.param_dtype),
            "w2": _dense_init(ks[6], cfg.ffn, h, cfg.param_dtype),
        }
        if cfg.use_bias:
            p["mlp"]["w13_b"] = jnp.zeros((2 * cfg.ffn,), cfg.param_dtype)
            p["mlp"]["w2_b"] = jnp.zeros((h,), cfg.param_dtype)
    else:
        p["mlp"] = {
            "w1": _dense_init(ks[4], h, cfg.ffn, cfg.param_dtype),
            "w2": _dense_init(ks[6], cfg.ffn, h, cfg.param_dtype),
        }
        if cfg.use_bias:
            p["mlp"]["w1_b"] = jnp.zeros((cfg.ffn,), cfg.param_dtype)
            p["mlp"]["w2_b"] = jnp.zeros((h,), cfg.param_dtype)
    if cfg.norm_type == "layernorm":
        p["attn_norm"]["bias"] = jnp.zeros((h,), cfg.param_dtype)
        p["mlp_norm"]["bias"] = jnp.zeros((h,), cfg.param_dtype)
    return p


def layer_annotations(cfg: ModelConfig, cross: bool = False) -> Params:
    """Logical axes per layer param: 'tp' = Megatron-sharded dim (column-out /
    row-in), 'fsdp' = the dim ZeRO shards (reference: FSDP flat-param sharding,
    galvatron/core/parallel.py:174-207)."""
    a: Params = {
        "attn_norm": {"scale": ("fsdp",)},
        "attn": {
            # blocked layout: TP shards the head dim of each q/k/v slot
            "wqkv": ("fsdp", None, "tp") if cfg.qkv_blocked else ("fsdp", "tp"),
            "wo": ("tp", "fsdp"),
        },
        "mlp_norm": {"scale": ("fsdp",)},
    }
    if cfg.use_bias:
        # column-parallel biases shard with their output dim; the
        # row-parallel output bias is added once after the reduction
        a["attn"]["wqkv_b"] = (None, "tp")
        a["attn"]["wo_b"] = ("fsdp",)
    if cross:
        a["cross_norm"] = {"scale": ("fsdp",)}
        a["cross"] = {
            "wq": ("fsdp", "tp"),
            "wkv": ("fsdp", "tp"),
            "wo": ("tp", "fsdp"),
        }
        if cfg.norm_type == "layernorm":
            a["cross_norm"]["bias"] = ("fsdp",)
    if cfg.moe_experts > 0:
        from galvatron_tpu.models import moe

        a["mlp"] = moe.moe_annotations(cfg)
    elif cfg.act_fn == "swiglu":
        a["mlp"] = {"w13": ("fsdp", "tp"), "w2": ("tp", "fsdp")}
        if cfg.use_bias:
            a["mlp"]["w13_b"] = ("tp",)
            a["mlp"]["w2_b"] = ("fsdp",)
    else:
        a["mlp"] = {"w1": ("fsdp", "tp"), "w2": ("tp", "fsdp")}
        if cfg.use_bias:
            a["mlp"]["w1_b"] = ("tp",)
            a["mlp"]["w2_b"] = ("fsdp",)
    if cfg.norm_type == "layernorm":
        a["attn_norm"]["bias"] = ("fsdp",)
        a["mlp_norm"]["bias"] = ("fsdp",)
    return a


# --- vision (ViT / Swin) static geometry -----------------------------------


def swin_stage_of(cfg: ModelConfig, i: int) -> Tuple[int, int]:
    """Layer index → (stage, index within stage) for hierarchical Swin."""
    for s, d in enumerate(cfg.swin_depths):
        if i < d:
            return s, i
        i -= d
    raise IndexError(f"layer {i} beyond swin_depths {cfg.swin_depths}")


def swin_geometry(cfg: ModelConfig, stage: int) -> Tuple[int, int, int, int]:
    """Stage → (H, W, C, heads): resolution halves and width/heads double per
    stage (Swin's hierarchical pyramid)."""
    side = cfg.grid >> stage
    return side, side, cfg.hidden_size << stage, cfg.num_heads << stage


def swin_window_for(cfg: ModelConfig, stage: int) -> int:
    """Static per-stage window: ``swin_window`` shrunk to the largest value
    that divides the stage's side (windows must tile the feature map; the
    canonical 224/patch-4 presets keep the full 7)."""
    side = cfg.grid >> stage
    w = min(cfg.swin_window, side)
    while side % w:
        w -= 1
    return w


def vision_layer_cfg(cfg: ModelConfig, i: int) -> ModelConfig:
    """Per-layer shape config for vision layers: identity for ViT; for Swin
    the stage-s widening (C·2^s, heads·2^s — head_dim constant) so the same
    init_layer_params/layer_annotations serve every stage."""
    if not cfg.swin_depths:
        return cfg
    s, _ = swin_stage_of(cfg, i)
    _, _, c, heads = swin_geometry(cfg, s)
    return cfg.replace(hidden_size=c, num_heads=heads, num_kv_heads=None)


def init_vision_base_params(ks, cfg: ModelConfig) -> Params:
    """Non-layer vision params (patch-projection embed / final norm / class
    head) from three keys — the single source both the GSPMD init and the
    pipeline engines' base init draw from. Swin's final_norm/head sit at
    c_last = hidden·2^(stages-1)."""
    patch_dim = cfg.patch_size * cfg.patch_size * cfg.num_channels
    c_last = cfg.hidden_size << max(0, len(cfg.swin_depths) - 1)
    base: Params = {
        "embed": {
            "proj": _dense_init(ks[0], patch_dim, cfg.hidden_size, cfg.param_dtype),
            "pos": jax.random.normal(
                ks[1], (cfg.n_patches, cfg.hidden_size), cfg.param_dtype
            )
            * 0.02,
        },
        "final_norm": {"scale": jnp.ones((c_last,), cfg.param_dtype)},
        "head": {"w": _dense_init(ks[2], c_last, cfg.num_classes, cfg.param_dtype)},
    }
    if cfg.norm_type == "layernorm":
        base["final_norm"]["bias"] = jnp.zeros((c_last,), cfg.param_dtype)
    return base


def vision_base_annotations(cfg: ModelConfig) -> Params:
    a: Params = {
        "embed": {"proj": ("fsdp", "tp"), "pos": ("fsdp", None)},
        "final_norm": {"scale": ("fsdp",)},
        "head": {"w": ("fsdp", "tp")},
    }
    if cfg.norm_type == "layernorm":
        a["final_norm"]["bias"] = ("fsdp",)
    return a


def init_vision_params(key, cfg: ModelConfig) -> Params:
    """ViT/Swin parameter tree: patch-projection embedding + learned position
    table + encoder layers (+ Swin patch-merging projections) + pooled
    classification head. Reference carries vit/swin only as legacy wrapping
    branches (galvatron/core/parallel.py:64-89); here they are live families."""
    if cfg.swin_depths and sum(cfg.swin_depths) != cfg.num_layers:
        raise ValueError(
            f"swin_depths {cfg.swin_depths} sum to {sum(cfg.swin_depths)} but "
            f"num_layers is {cfg.num_layers} (per-layer strategies index the "
            "flattened stage layers; keep them equal)"
        )
    if cfg.image_size % cfg.patch_size:
        raise ValueError(
            f"patch_size {cfg.patch_size} must divide image_size {cfg.image_size}"
        )
    L = cfg.num_layers
    ks = jax.random.split(key, L + 4)
    params = init_vision_base_params([ks[0], ks[1], ks[-1]], cfg)
    params["layers"] = [
        init_layer_params(ks[i + 2], vision_layer_cfg(cfg, i)) for i in range(L)
    ]
    if cfg.swin_depths:
        n_stages = len(cfg.swin_depths)
        mks = jax.random.split(ks[-2], max(1, n_stages - 1))
        params["merges"] = []
        for s in range(n_stages - 1):
            c = cfg.hidden_size << s
            m = {"w": _dense_init(mks[s], 4 * c, 2 * c, cfg.param_dtype),
                 "norm": {"scale": jnp.ones((4 * c,), cfg.param_dtype)}}
            if cfg.norm_type == "layernorm":
                m["norm"]["bias"] = jnp.zeros((4 * c,), cfg.param_dtype)
            params["merges"].append(m)
    return params


def vision_annotations(cfg: ModelConfig) -> Params:
    a = vision_base_annotations(cfg)
    a["layers"] = [
        layer_annotations(vision_layer_cfg(cfg, i)) for i in range(cfg.num_layers)
    ]
    if cfg.swin_depths:
        a["merges"] = []
        for s in range(len(cfg.swin_depths) - 1):
            m = {"w": ("fsdp", None), "norm": {"scale": ("fsdp",)}}
            if cfg.norm_type == "layernorm":
                m["norm"]["bias"] = ("fsdp",)
            a["merges"].append(m)
    return a


def init_model_params(key, cfg: ModelConfig) -> Params:
    if cfg.image_size:
        return init_vision_params(key, cfg)
    ks = jax.random.split(key, cfg.total_layers + 3)
    cross = cfg.enc_layers > 0
    params: Params = {
        "embed": {
            "tok": jax.random.normal(ks[0], (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
            * 0.02
        },
        "layers": [
            init_layer_params(ks[cfg.enc_layers + i + 1], cfg, cross=cross)
            for i in range(cfg.num_layers)
        ],
        "final_norm": {"scale": jnp.ones((cfg.hidden_size,), cfg.param_dtype)},
    }
    if cross:
        params["enc_layers"] = [
            init_layer_params(ks[i + 1], cfg) for i in range(cfg.enc_layers)
        ]
        params["enc_final_norm"] = {"scale": jnp.ones((cfg.hidden_size,), cfg.param_dtype)}
        if cfg.norm_type == "layernorm":
            params["enc_final_norm"]["bias"] = jnp.zeros((cfg.hidden_size,), cfg.param_dtype)
    if cfg.pos_embed == "learned":
        pos_len = max(cfg.max_seq_len, cfg.enc_seq)
        params["embed"]["pos"] = (
            jax.random.normal(ks[-2], (pos_len, cfg.hidden_size), cfg.param_dtype) * 0.02
        )
    if cfg.norm_type == "layernorm":
        params["final_norm"]["bias"] = jnp.zeros((cfg.hidden_size,), cfg.param_dtype)
    if not cfg.tie_word_embeddings:
        params["head"] = {
            "w": _dense_init(ks[-1], cfg.hidden_size, cfg.vocab_size, cfg.param_dtype)
        }
    return params


def model_annotations(cfg: ModelConfig) -> Params:
    """Embedding is vocab-parallel over its TP axes (reference:
    VocabParallelEmbedding, site_package/megatron/core/tensor_parallel/
    layers.py:157; vocab_tp flag galvatron/core/arguments.py:128-130)."""
    if cfg.image_size:
        return vision_annotations(cfg)
    cross = cfg.enc_layers > 0
    a: Params = {
        "embed": {"tok": ("tp", "fsdp")},
        "layers": [layer_annotations(cfg, cross=cross) for _ in range(cfg.num_layers)],
        "final_norm": {"scale": ("fsdp",)},
    }
    if cross:
        a["enc_layers"] = [layer_annotations(cfg) for _ in range(cfg.enc_layers)]
        a["enc_final_norm"] = {"scale": ("fsdp",)}
        if cfg.norm_type == "layernorm":
            a["enc_final_norm"]["bias"] = ("fsdp",)
    if cfg.pos_embed == "learned":
        a["embed"]["pos"] = ("fsdp", None)
    if cfg.norm_type == "layernorm":
        a["final_norm"]["bias"] = ("fsdp",)
    if not cfg.tie_word_embeddings:
        a["head"] = {"w": ("fsdp", "tp")}
    return a


# ---------------------------------------------------------------------------
# Forward pieces
# ---------------------------------------------------------------------------


def _norm_impl(x, p, cfg: ModelConfig):
    dt = x.dtype
    x32 = x.astype(jnp.float32)
    if cfg.norm_type == "rms":
        x32 = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + cfg.norm_eps)
        out = x32 * p["scale"].astype(jnp.float32)
    else:
        mu = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
        out = (x32 - mu) * jax.lax.rsqrt(var + cfg.norm_eps)
        out = out * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return out.astype(dt)


def norm(x, p, cfg: ModelConfig):
    """RMSNorm / LayerNorm; Pallas fused kernel on TPU when cfg.fused_norm
    (reference fused-norm CUDA ops: megatron fused_layer_norm / rms_norm,
    flash-attn dropout_add_rms_norm — SURVEY §2.1).

    Under ``mlp_recompute='policy'`` the fp32 statistics are rematerialized
    in the backward from the compute-dtype input — without the wrap, autodiff
    saves an fp32-widened (B, S, H) copy of every normed activation (the
    round-5 HLO buffer audit's 67 MB/layer class)."""
    if cfg.fused_norm:
        from galvatron_tpu.ops import fused_norm

        if cfg.norm_type == "rms":
            return fused_norm.fused_rmsnorm(x, p["scale"], cfg.norm_eps)
        return fused_norm.fused_layernorm(x, p["scale"], p["bias"], cfg.norm_eps)
    if cfg.mlp_recompute == "policy":
        return jax.checkpoint(lambda x_, p_: _norm_impl(x_, p_, cfg))(x, p)
    return _norm_impl(x, p, cfg)


def rope_tables(cfg: ModelConfig, seq_len: int, offset: int = 0):
    pos = np.arange(offset, offset + seq_len)
    inv = 1.0 / (cfg.rope_theta ** (np.arange(0, cfg.head_dim, 2) / cfg.head_dim))
    freqs = np.outer(pos, inv)  # (S, hd/2)
    return jnp.asarray(np.cos(freqs), jnp.float32), jnp.asarray(np.sin(freqs), jnp.float32)


def apply_rope(x, cos, sin):
    """x: (B, S, n, hd). Rotate-half convention (reference: rotary_pos_embedding
    apply_rotary_pos_emb, site_package/megatron/core/models/common/embeddings/
    rotary_pos_embedding.py:144).

    ``cos``/``sin`` are ``(S, hd/2)`` tables shared across the batch, or
    ``(B, S, hd/2)`` per-row tables (slot-wise decode: each batch row sits at
    its own absolute position)."""
    dt = x.dtype
    x = x.astype(jnp.float32)
    x1, x2 = jnp.split(x, 2, axis=-1)
    if cos.ndim == 3:
        c = cos[:, :, None, :]
        s = sin[:, :, None, :]
    else:
        c = cos[None, :, None, :]
        s = sin[None, :, None, :]
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(dt)


def positions_from_segments(seg):
    """Per-segment position ids from a ``(B, S)`` packed segment-id array:
    position i's index within its own segment. Relies on the packer's layout
    contract — segment ids are monotonically non-decreasing along the row
    (documents are laid out contiguously), so a segment's start is the last
    index where the id changed."""
    idx = jnp.arange(seg.shape[1], dtype=jnp.int32)
    is_start = jnp.concatenate(
        [jnp.ones_like(seg[:, :1], bool), seg[:, 1:] != seg[:, :-1]], axis=1
    )
    seg_start = jax.lax.cummax(jnp.where(is_start, idx[None], 0), axis=1)
    return idx[None] - seg_start


def split_packed_inputs(inputs):
    """Packed model-input rows ``(B, 2·S)`` = tokens ‖ segment ids →
    (tokens (B, S), segment ids (B, S), per-segment position ids (B, S))."""
    s = inputs.shape[1] // 2
    tokens = inputs[:, :s]
    seg = inputs[:, s:]
    return tokens, seg, positions_from_segments(seg)


def packed_rope_tables(cfg: ModelConfig, pos_ids):
    """Per-row rope tables for packed sequences: the shared ``(S, hd/2)``
    tables gathered by per-segment positions → ``(B, S, hd/2)`` (the same
    per-row form the serving engine's slot-wise decode uses). For a row that
    is one whole segment this gathers ``arange(S)`` — bit-identical values to
    the unpacked broadcast path."""
    cos, sin = rope_tables(cfg, pos_ids.shape[1])
    return cos[pos_ids], sin[pos_ids]


def alibi_slopes(n_heads: int) -> np.ndarray:
    # standard ALiBi slope schedule (press et al.); baichuan-13B path
    def pow2slopes(n):
        start = 2 ** (-(2 ** -(np.log2(n) - 3)))
        return start * (start ** np.arange(n))

    if np.log2(n_heads).is_integer():
        return pow2slopes(n_heads)
    k = 2 ** int(np.floor(np.log2(n_heads)))
    return np.concatenate([pow2slopes(k), pow2slopes(2 * k)[0::2][: n_heads - k]])


def _repeat_kv(x, n_rep: int):
    if n_rep == 1:
        return x
    b, s, kvh, hd = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, s, kvh, n_rep, hd)).reshape(
        b, s, kvh * n_rep, hd
    )


def attention_xla(q, k, v, cfg: ModelConfig, bias=None, q_offset=0, seg_ids=None):
    """Reference einsum attention (the 'CoreAttention' path, reference:
    galvatron/core/tensor_parallel/transformer.py:298-435).

    k/v may be longer than q (KV-cache decode): query i sits at absolute
    position ``q_offset + i`` and sees keys at positions <= its own.
    ``q_offset`` may be a traced scalar, or a traced ``(B,)`` vector giving
    each batch row its own absolute position — the slot-wise entry point used
    by the continuous-batching serving engine, where every row of the batch
    is a different request at a different depth into its sequence.

    ``seg_ids`` ((B, S), packed sequences): the causal predicate tightens to
    intra-segment — query i attends to key j only when ``seg[i] == seg[j]``,
    so cross-document attention is structurally impossible. The combine is a
    logical AND on the SAME where/-1e30 pattern the plain causal mask uses:
    a row holding a single segment produces a bit-identical mask, which is
    what makes the packed-vs-padded gradient-parity test exact."""
    b, s, nh, hd = q.shape
    if s == 1 and bias is None and seg_ids is None and cfg.causal:
        # KV-cache decode: skip the _repeat_kv materialization and the
        # (b, n, 1, k) score reshuffle — the GQA-native dot-product path
        # reads the cache once (tests/test_flash_attention.py parity case)
        from galvatron_tpu.ops.flash_attention import decode_attention

        return decode_attention(q, k, v, q_offset=q_offset)
    k = _repeat_kv(k, nh // k.shape[2])
    v = _repeat_kv(v, nh // v.shape[2])
    scores = jnp.einsum("bqnh,bknh->bnqk", q, k).astype(jnp.float32) / np.sqrt(hd)
    if bias is not None:
        scores = scores + bias
    if cfg.causal:
        # (1|B, s): scalar offset broadcasts over the batch; a (B,) offset
        # yields a per-row mask (scores are (b, n, q, k))
        q_pos = jnp.reshape(jnp.asarray(q_offset), (-1, 1)) + jnp.arange(s)[None]
        k_pos = jnp.arange(k.shape[1])
        allowed = k_pos[None, None, :] <= q_pos[:, :, None]
        if seg_ids is not None:
            allowed = allowed & (seg_ids[:, :, None] == seg_ids[:, None, :])
        scores = jnp.where(allowed[:, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bnqk,bknh->bqnh", probs, v)


def attention(q, k, v, cfg: ModelConfig, bias=None, rope=None, seg_ids=None):
    """``rope``: optional (cos, sin) tables. On the flash path they are fused
    into the Pallas kernels (no HBM round-trip of roped q/k); otherwise
    apply_rope runs here before the einsum path. ``seg_ids`` (packed
    sequences) forces the einsum path — the Pallas kernels carry no segment
    mask (build_runtime rejects pack_sequences with attn_impl='flash')."""
    if cfg.attn_impl == "flash" and bias is None and seg_ids is None:
        from galvatron_tpu.ops.flash_attention import flash_attention

        nh = q.shape[2]
        k = _repeat_kv(k, nh // k.shape[2])
        v = _repeat_kv(v, nh // v.shape[2])
        bsnd = (0, 2)  # (b, s, n, d) layout: batch dim 0, head dim 2
        if rope is None:
            kernel = _flash_shard_map(
                cfg,
                lambda q_, k_, v_: flash_attention(q_, k_, v_, causal=cfg.causal),
                [bsnd] * 3,
                bsnd,
            )
            return kernel(q, k, v)
        kernel = _flash_shard_map(
            cfg,
            lambda q_, k_, v_, c_, s_: flash_attention(
                q_, k_, v_, causal=cfg.causal, rope=(c_, s_)
            ),
            [bsnd] * 3 + [(None, None)] * 2,
            bsnd,
        )
        return kernel(q, k, v, *rope)
    if rope is not None:
        q = apply_rope(q, *rope)
        k = apply_rope(k, *rope)
    return attention_xla(q, k, v, cfg, bias=bias, seg_ids=seg_ids)


def _repeat_kv_hm(x, n_rep: int):
    """Head-major GQA repeat: (b, kvh, s, hd) -> (b, kvh*n_rep, s, hd),
    kv-major head order (matches _repeat_kv's interleaving)."""
    if n_rep == 1:
        return x
    b, kvh, s, hd = x.shape
    return jnp.broadcast_to(x[:, :, None], (b, kvh, n_rep, s, hd)).reshape(
        b, kvh * n_rep, s, hd
    )


def _flash_shard_map(cfg: ModelConfig, fn, arg_dims, out_dims):
    """Wrap a flash-kernel entry in a shard_map over the layer's (dp, tp)
    axes when flash_shard_ctx is installed (multi-device mesh) — Mosaic
    custom calls cannot be partitioned by GSPMD, so each device must invoke
    the kernel on its local (batch, head) shard. ``arg_dims``/``out_dims``:
    per-array (batch_dim, head_dim) positions; rope tables (replicated) are
    passed through with empty dims. Nests inside the pp engines' manual
    region via ambient_or. Identity when the ctx is absent."""
    if cfg.flash_shard_ctx is None:
        return fn
    from jax.sharding import PartitionSpec as P

    from galvatron_tpu.parallel.mesh import ambient_or

    mesh, dp_ax, tp_ax = cfg.flash_shard_ctx
    dp = tuple(dp_ax) if dp_ax else ()
    tp = tuple(tp_ax) if tp_ax else ()
    if not dp and not tp:
        return fn

    def spec(dims, ndim):
        entries = [None] * ndim
        b_dim, h_dim = dims
        if b_dim is not None and dp:
            entries[b_dim] = dp if len(dp) > 1 else dp[0]
        if h_dim is not None and tp:
            entries[h_dim] = tp if len(tp) > 1 else tp[0]
        return P(*entries)

    def wrapped(*args):
        from galvatron_tpu.parallel.mesh import manual_axis_names

        in_specs = tuple(spec(d, a.ndim) for d, a in zip(arg_dims, args))
        out_shape = jax.eval_shape(fn, *args)
        am = ambient_or(mesh)
        return compat.shard_map(
            fn, mesh=am, in_specs=in_specs,
            out_specs=spec(out_dims, len(out_shape.shape)),
            axis_names=manual_axis_names(am), check_vma=False,
        )(*args)

    return wrapped


def _proj_up(subscripts, x, w, cfg: ModelConfig, w_shard_dim: int):
    """Column-parallel projection einsum (qkv, MLP gate/up). With
    tp_overlap_ctx installed and the layer sequence-parallel, ``x`` arrives
    seq-sharded over the tp axes and the GSPMD-inserted blocking seq
    all-gather is replaced by the decomposed all-gather⊗matmul ring
    (ops.collective_matmul). Non-sp layers keep the plain einsum — x is
    already tp-replicated, there is no gather to overlap.

    int8 weights (serving, ops.quant) dequantize inside the plain einsum;
    the overlap ring streams fp weight shards, so under tp_overlap_ctx a
    quantized weight is materialized back to fp first (serving never
    installs the overlap ctx — this branch exists for safety, not speed)."""
    if cfg.tp_overlap_ctx is None:
        if isinstance(w, QuantTensor):
            return qeinsum(subscripts, x, w)
        return jnp.einsum(subscripts, x, w)
    if isinstance(w, QuantTensor):
        w = w.dequantize(x.dtype)
    from galvatron_tpu.ops import collective_matmul as cm

    mesh, dp_ax, tp_ax, sp = cfg.tp_overlap_ctx
    if not sp:
        return jnp.einsum(subscripts, x, w)
    return cm.allgather_einsum(
        subscripts, x, w, mesh=mesh, dp_axes=dp_ax, tp_axes=tp_ax,
        w_shard_dim=w_shard_dim,
    )


def _proj_down(subscripts, x, w, cfg: ModelConfig, w_shard_dim: int):
    """Row-parallel projection einsum (wo, MLP down). With tp_overlap_ctx
    installed the trailing TP reduction is pipelined as the accumulator-ring
    reduce-scatter⊗matmul (ops.collective_matmul): sp layers keep the
    seq-scattered output layout; non-sp layers gather it back (the reduce
    half of the all-reduce still overlaps)."""
    if cfg.tp_overlap_ctx is None:
        if isinstance(w, QuantTensor):
            return qeinsum(subscripts, x, w)
        return jnp.einsum(subscripts, x, w)
    if isinstance(w, QuantTensor):
        w = w.dequantize(x.dtype)
    from galvatron_tpu.ops import collective_matmul as cm

    mesh, dp_ax, tp_ax, sp = cfg.tp_overlap_ctx
    return cm.einsum_reducescatter(
        subscripts, x, w, mesh=mesh, dp_axes=dp_ax, tp_axes=tp_ax,
        w_shard_dim=w_shard_dim, scatter_output=bool(sp),
    )


def _constrain_qkv(qkv, cfg: ModelConfig):
    """Pin the stacked (b, 3, n, s, d) qkv (and, via the vjp transpose, its
    dqkv cotangent) to (dp, -, tp, -, -) when the layer hook installed
    qkv_shard_ctx — see the ModelConfig field comment."""
    if cfg.qkv_shard_ctx is None:
        return qkv
    from jax.sharding import PartitionSpec as P

    from galvatron_tpu.parallel.sharding import constrain

    mesh, dp_ax, tp_ax = cfg.qkv_shard_ctx
    return constrain(
        qkv, mesh, P(dp_ax or None, None, tp_ax or None, None, None)
    )


def _constrain_attn_out(o, cfg: ModelConfig):
    """Pin the attention context to batch-sharded/head-replicated when the
    layer hook installed attn_out_shard_ctx (zero3+tp layers) — see the
    ModelConfig field comment. ``o``: (B, S, n, hd) or (B, n, S, hd)."""
    if cfg.attn_out_shard_ctx is None:
        return o
    from jax.sharding import PartitionSpec as P

    from galvatron_tpu.parallel.sharding import constrain

    mesh, dp_ax = cfg.attn_out_shard_ctx
    return constrain(o, mesh, P(dp_ax or None, *([None] * (o.ndim - 1))))


def _attn_block_headmajor(x, p, cfg: ModelConfig, rope, remat_attn: bool):
    """Flash-path attention with head-major (b, h, s, d) dataflow end to end:
    the QKV projection einsums straight to (b, 3, n, s, hd) and the output
    projection consumes (b, n, s, hd), so XLA realizes the head-major layout
    inside the GEMMs instead of materializing reshape+transpose copies
    between the projection and the kernels (~0.32 ms/layer/sample on the
    v5e 7B-shape bench; the copies were ~2.9 ms/layer-batch in the trace)."""
    from galvatron_tpu.ops.flash_attention import (
        flash_attention_hm,
        flash_attention_qkv,
        flash_qkv_supported,
    )

    b, s, h = x.shape
    hd = cfg.head_dim
    n = cfg.num_heads
    w = p["wqkv"].astype(x.dtype)
    if cfg.qkv_blocked:
        qkv = _proj_up("bsh,hcnd->bcnsd", x, w.reshape(h, 3, n, hd), cfg, w_shard_dim=2)
        if "wqkv_b" in p:
            qkv = qkv + p["wqkv_b"].astype(x.dtype).reshape(3, n, hd)[None, :, :, None, :]
        qkv = _constrain_qkv(qkv, cfg)
        if flash_qkv_supported(s, hd, cfg.causal, rope):
            # the kernels consume the STACKED projection output directly —
            # index-mapped block specs instead of q/k/v slice copies
            kernel = _flash_shard_map(
                cfg,
                lambda qkv_, c_, s_: flash_attention_qkv(qkv_, rope=(c_, s_)),
                [(0, 2), (None, None), (None, None)],
                (0, 1),
            )

            def core_qkv(qkv_):
                return kernel(qkv_, *rope)

            if remat_attn:
                core_qkv = jax.checkpoint(core_qkv)
            o = _constrain_attn_out(core_qkv(qkv), cfg)
            y = _proj_down(
                "bnsd,nde->bse", o, p["wo"].astype(x.dtype).reshape(n, hd, h),
                cfg, w_shard_dim=0,
            )
            if "wo_b" in p:
                y = y + p["wo_b"].astype(x.dtype)
            return y
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
    else:
        kv, group = qkv_dims(cfg)
        npg = group // hd - 2  # query heads per kv group, per the stored layout
        r = jnp.einsum("bsh,hknd->bknsd", x, w.reshape(h, kv, npg + 2, hd))
        q = r[:, :, :npg].reshape(b, n, s, hd)
        # GQA-NATIVE: K/V stay at kv_heads — the flash kernels serve each kv
        # group's queries from the resident grouped block (flash_attention_hm
        # kv_rep index maps), group-factor less K/V HBM traffic than the old
        # materialized _repeat_kv_hm copy. EXCEPT when the layer's tp degree
        # does not divide kv_heads: _flash_shard_map shards the head dim
        # over the tp axes, so grouped K/V must be repeated first (the same
        # guard ulysses applies) — q heads always divide tp.
        k = r[:, :, npg]
        v = r[:, :, npg + 1]
        if cfg.flash_shard_ctx is not None:
            mesh_, _, tp_ax = cfg.flash_shard_ctx
            tp_deg = int(np.prod([mesh_.shape[a] for a in (tp_ax or ())]))
            if tp_deg > 1 and kv % tp_deg:
                k = _repeat_kv_hm(k, npg)
                v = _repeat_kv_hm(v, npg)

    qkv_dim, rep_dim = (0, 1), (None, None)
    if rope is None:
        kernel = _flash_shard_map(
            cfg,
            lambda q_, k_, v_: flash_attention_hm(q_, k_, v_, causal=cfg.causal),
            [qkv_dim] * 3,
            qkv_dim,
        )

        def core(q_, k_, v_):
            return kernel(q_, k_, v_)
    else:
        kernel = _flash_shard_map(
            cfg,
            lambda q_, k_, v_, c_, s_: flash_attention_hm(
                q_, k_, v_, causal=cfg.causal, rope=(c_, s_)
            ),
            [qkv_dim] * 3 + [rep_dim, rep_dim],
            qkv_dim,
        )

        def core(q_, k_, v_):
            return kernel(q_, k_, v_, *rope)

    if remat_attn:
        core = jax.checkpoint(core)
    o = _constrain_attn_out(core(q, k, v), cfg)
    y = _proj_down(
        "bnsd,nde->bse", o, p["wo"].astype(x.dtype).reshape(n, hd, h),
        cfg, w_shard_dim=0,
    )
    if "wo_b" in p:
        y = y + p["wo_b"].astype(x.dtype)
    return y


def attn_block(x, p, cfg: ModelConfig, cos_sin=None, alibi=None, remat_attn: bool = False,
               seg_ids=None):
    """``remat_attn`` rematerializes only the attention core (scores/softmax/
    context) in the backward pass — Megatron's "selective" recompute
    (reference: galvatron/core/tensor_parallel/transformer.py:597,615-636).

    ``seg_ids`` (packed sequences) routes through the einsum path with the
    intra-segment mask; the head-major flash fast path is skipped (the Pallas
    kernels carry no segment mask)."""
    b, s, h = x.shape
    hd = cfg.head_dim
    if (
        cfg.attn_impl == "flash" and cfg.pos_embed != "alibi"
        and cfg.flash_headmajor and seg_ids is None
    ):
        from galvatron_tpu.ops.flash_attention import flash_tileable

        if flash_tileable(s) and ("wqkv_b" not in p or cfg.qkv_blocked):
            rope = cos_sin if cfg.pos_embed == "rope" else None
            return _attn_block_headmajor(x, p, cfg, rope, remat_attn)
    # one fused qkv GEMM (~2 ms/layer-batch over three narrow matmuls on the
    # v5e 7B-shape bench); layout per qkv_dims/qkv_project
    q, k, v = project_qkv_heads(x, p, cfg)
    rope = cos_sin if cfg.pos_embed == "rope" else None
    bias = None
    if cfg.pos_embed == "alibi":
        pos = jnp.arange(s)
        rel = pos[None, :] - pos[:, None]  # (q, k) negative below diag
        bias = (alibi[:, None, None] * rel[None]).astype(jnp.float32)[None]  # (1,n,q,k)

    def core(q_, k_, v_, bias_, seg_):
        return attention(q_, k_, v_, cfg, bias=bias_, rope=rope, seg_ids=seg_)

    if remat_attn:
        core = jax.checkpoint(core)
    o = _constrain_attn_out(core(q, k, v, bias, seg_ids), cfg)
    return attn_output(o, p, cfg, x.dtype)


def mlp_block(x, p, cfg: ModelConfig, train: bool = True):
    """SwiGLU or GeLU MLP (reference: ParallelMLP, galvatron/core/
    tensor_parallel/transformer.py:78-159); switch-MoE when moe_experts > 0
    (SwitchMLP, transformer.py:161-295). ``train`` only affects MoE routing
    (sinkhorn-balanced vs raw-argmax).

    The gate/up projection output is checkpoint-named 'mlp_gate': under the
    mlp_residual saveable policy it is the ONE saved residual of the MLP
    branch — the activation product feeding w2 is recomputed in the backward
    instead of being saved as a second full-width copy."""
    if cfg.moe_experts > 0:
        from galvatron_tpu.models import moe

        return moe.moe_block(x, p, cfg, train=train)
    # _proj_up/_proj_down only serve the (B, S, H) token stream; vision /
    # windowed layouts keep the plain matmul (tp_overlap_ctx is token-only)
    plain = lambda x_, w_: (  # noqa: E731 — non-token (vision) layouts
        qmatmul(x_, w_) if isinstance(w_, QuantTensor) else x_ @ w_
    )
    up = (
        (lambda x_, w_: _proj_up("bsh,hf->bsf", x_, w_, cfg, w_shard_dim=1))
        if x.ndim == 3
        else plain
    )
    down = (
        (lambda x_, w_: _proj_down("bsf,fh->bsh", x_, w_, cfg, w_shard_dim=0))
        if x.ndim == 3
        else plain
    )
    if cfg.act_fn == "swiglu":
        # fused [w1 | w3] gate GEMM (~3.5 ms/layer-batch over two narrow
        # matmuls on the v5e 7B-shape bench)
        f = p["w13"].shape[-1] // 2
        g = up(x, p["w13"].astype(x.dtype))
        if "w13_b" in p:
            g = g + p["w13_b"].astype(x.dtype)
        g = checkpoint_name(g, "mlp_gate")
        prod = lambda g_: jax.nn.silu(g_[..., :f]) * g_[..., f:]
        if cfg.mlp_recompute == "gate" or (
            cfg.mlp_recompute == "policy" and cfg.fused_norm
        ):
            # 'policy' with fused_norm: mlp_residual skips the policy region
            # (the fused kernels carry custom-VJP residuals it cannot
            # reach), so the one-gate-save guarantee falls back to the
            # product-only remat here
            prod = jax.checkpoint(prod)
        y = down(prod(g), p["w2"].astype(x.dtype))
    else:
        g = up(x, p["w1"].astype(x.dtype))
        if "w1_b" in p:
            g = g + p["w1_b"].astype(x.dtype)
        g = checkpoint_name(g, "mlp_gate")
        act = jax.nn.relu if cfg.act_fn == "relu" else partial(
            jax.nn.gelu, approximate=True
        )
        if cfg.mlp_recompute == "gate" or (
            cfg.mlp_recompute == "policy" and cfg.fused_norm
        ):
            act = jax.checkpoint(act)
        y = down(act(g), p["w2"].astype(x.dtype))
    if "w2_b" in p:
        y = y + p["w2_b"].astype(x.dtype)
    return y


def mlp_residual(x, p, cfg: ModelConfig, train: bool = True):
    """x + MLP(norm(x)) — the per-layer MLP residual branch, with the
    activation-memory saveable policy applied when cfg.mlp_recompute ==
    'policy': jax.checkpoint over the norm+MLP region saving ONLY the
    'mlp_gate'-named projection output, so (a) the gate is saved exactly once
    per layer (the probe's jax.checkpoint(silu·gate) shape, now reaching the
    norm too) and (b) no fp32-widened copies of the bf16 residual stream
    survive into the backward — the fp32 norm statistics are recomputed from
    the saved compute-dtype layer input. MoE layers fall back to the plain
    branch (dispatch buffers carry their own sharding pins; the router is
    deterministic but its recompute under a policy region is unvalidated)."""
    if cfg.mlp_recompute == "policy" and cfg.moe_experts == 0 and not cfg.fused_norm:
        # _norm_impl, not norm: the policy region already remats everything
        # unnamed — a nested per-norm checkpoint would only add bookkeeping.
        # fused_norm layers keep the plain branch (the Pallas kernels carry
        # their own custom-VJP residuals the policy cannot reach).
        branch = jax.checkpoint(
            lambda x_, pn_, pm_: mlp_block(_norm_impl(x_, pn_, cfg), pm_, cfg, train=train),
            policy=jax.checkpoint_policies.save_only_these_names("mlp_gate"),
        )
        return x + branch(x, p["mlp_norm"], p["mlp"])
    return x + mlp_block(norm(x, p["mlp_norm"], cfg), p["mlp"], cfg, train=train)


def cross_attn_block(x, enc_out, p, cfg: ModelConfig):
    """Cross-attention: queries from the decoder stream, keys/values from the
    encoder output (reference legacy t5 model_type; architecture per standard
    enc-dec transformers). Full (non-causal) visibility over encoder
    positions; no rotary — positions live in the respective streams."""
    b, s, h = x.shape
    hd = cfg.head_dim
    kv_out = cfg.kv_heads * hd
    se = enc_out.shape[1]
    q = (x @ p["wq"].astype(x.dtype)).reshape(b, s, cfg.num_heads, hd)
    kvp = enc_out.astype(x.dtype) @ p["wkv"].astype(x.dtype)  # fused [k | v] GEMM
    k = kvp[..., :kv_out].reshape(b, se, cfg.kv_heads, hd)
    v = kvp[..., kv_out:].reshape(b, se, cfg.kv_heads, hd)
    o = attention_xla(q, k, v, cfg.replace(causal=False))
    return o.reshape(b, s, cfg.num_heads * hd) @ p["wo"].astype(x.dtype)


def encoder_layer(x, p, cfg: ModelConfig, cos_sin=None, remat_attn: bool = False):
    """Bidirectional self-attention + MLP (the enc-dec encoder stack)."""
    ecfg = cfg if not cfg.causal else cfg.replace(causal=False)
    x = x + attn_block(
        norm(x, p["attn_norm"], cfg), p["attn"], ecfg, cos_sin, None, remat_attn=remat_attn
    )
    return mlp_residual(x, p, cfg)


def decoder_layer(
    x, p, cfg: ModelConfig, cos_sin=None, alibi=None, remat_attn: bool = False,
    enc_out=None, seg_ids=None
):
    x = x + attn_block(
        norm(x, p["attn_norm"], cfg), p["attn"], cfg, cos_sin, alibi,
        remat_attn=remat_attn, seg_ids=seg_ids,
    )
    if enc_out is not None and "cross" in p:
        x = x + cross_attn_block(norm(x, p["cross_norm"], cfg), enc_out, p["cross"], cfg)
    return mlp_residual(x, p, cfg)


def embed(tokens, params, cfg: ModelConfig, pos_ids=None):
    """``pos_ids`` ((B, S), packed sequences): learned positions gathered by
    per-segment position ids instead of the ``arange(S)`` slice — each packed
    document restarts at position 0 (rope gets the same treatment via
    packed_rope_tables)."""
    x = params["embed"]["tok"].astype(cfg.dtype)[tokens]
    if cfg.pos_embed == "learned":
        s = tokens.shape[1]
        table = params["embed"]["pos"].astype(cfg.dtype)[:s]
        if pos_ids is not None:
            # broadcast-then-gather (not a direct table gather): the backward
            # is then a per-row placement scatter followed by the SAME
            # over-batch reduction the unpacked broadcast-add produces, so a
            # trivially-packed row (positions == arange) yields bit-identical
            # position-table gradients — the packed-vs-padded parity contract
            b = pos_ids.shape[0]
            tbl = jnp.broadcast_to(table[None], (b,) + table.shape)
            x = x + jnp.take_along_axis(tbl, pos_ids[:, :, None], axis=1)
        else:
            x = x + table[None]
    return x


def lm_head(x, params, cfg: ModelConfig):
    if cfg.tie_word_embeddings:
        # the tied table also feeds the embed gather — it stays fp
        w = params["embed"]["tok"].astype(x.dtype).T
    else:
        w = params["head"]["w"]
        if isinstance(w, QuantTensor):
            return qmatmul(x, w)
        w = w.astype(x.dtype)
    return x @ w


def forward(params, tokens, cfg: ModelConfig, layer_hook=None):
    """Full forward → logits. ``layer_hook(i, x)`` lets the hybrid-parallel
    runtime insert per-layer sharding constraints and remat (the
    Module_with_relocation + checkpoint_wrapper equivalent, reference:
    galvatron/core/parallel.py:109-172).

    Packed sequences (cfg.pack_sequences): ``tokens`` is the (B, 2·S) packed
    input row (tokens ‖ segment ids, from split_batch); the segment ids drive
    the intra-segment attention mask and per-segment position reset, and are
    handed to the hook as keyword args only in packed mode so non-packing
    hooks keep their signature."""
    seg = pos_ids = None
    if cfg.pack_sequences:
        tokens, seg, pos_ids = split_packed_inputs(tokens)
    if cfg.pos_embed == "rope":
        cos_sin = (
            packed_rope_tables(cfg, pos_ids)
            if pos_ids is not None
            else rope_tables(cfg, tokens.shape[1])
        )
    else:
        cos_sin = None
    alibi = jnp.asarray(alibi_slopes(cfg.num_heads)) if cfg.pos_embed == "alibi" else None
    hook_kw = {"seg_ids": seg} if seg is not None else {}
    x = embed(tokens, params, cfg, pos_ids=pos_ids)
    for i, lp in enumerate(params["layers"]):
        if layer_hook is not None:
            x = layer_hook(i, x, lp, **hook_kw)
        else:
            x = decoder_layer(x, lp, cfg, cos_sin, alibi, seg_ids=seg)
    x = norm(x, params["final_norm"], cfg)
    return lm_head(x, params, cfg)


def forward_encdec(params, enc_tokens, dec_tokens, cfg: ModelConfig, layer_hook=None):
    """Encoder-decoder forward → decoder logits. Layer-hook indices cover the
    encoder stack first (0..enc_layers-1) then the decoder
    (enc_layers..total_layers-1); decoder hooks receive ``enc_out``."""
    E = cfg.enc_layers
    cos_e = rope_tables(cfg, enc_tokens.shape[1]) if cfg.pos_embed == "rope" else None
    cos_d = rope_tables(cfg, dec_tokens.shape[1]) if cfg.pos_embed == "rope" else None
    x = embed(enc_tokens, params, cfg)
    for i, lp in enumerate(params["enc_layers"]):
        if layer_hook is not None:
            x = layer_hook(i, x, lp)
        else:
            x = encoder_layer(x, lp, cfg, cos_e)
    enc_out = norm(x, params["enc_final_norm"], cfg)
    y = embed(dec_tokens, params, cfg)
    for j, lp in enumerate(params["layers"]):
        if layer_hook is not None:
            y = layer_hook(E + j, y, lp, enc_out=enc_out)
        else:
            y = decoder_layer(y, lp, cfg, cos_d, None, enc_out=enc_out)
    y = norm(y, params["final_norm"], cfg)
    return lm_head(y, params, cfg)


# ---------------------------------------------------------------------------
# Vision forward (ViT / Swin)
# ---------------------------------------------------------------------------


def vision_embed(pixels, params, cfg: ModelConfig):
    """(B, H·W·C) int32 pixel rows → (B, n_patches, hidden): normalize to
    [-1, 1], patchify by reshape/transpose, linear-project, add learned
    positions. The patchify runs as pure data movement + one batched matmul —
    MXU-shaped, no gather."""
    b = pixels.shape[0]
    p_, g, c = cfg.patch_size, cfg.grid, cfg.num_channels
    x = pixels.astype(cfg.dtype).reshape(b, g, p_, g, p_, c) / 127.5 - 1.0
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, g * g, p_ * p_ * c)
    x = x @ params["embed"]["proj"].astype(cfg.dtype)
    return x + params["embed"]["pos"].astype(cfg.dtype)[None]


def _swin_attn_mask(h: int, w: int, window: int, shift: int) -> np.ndarray:
    """Static (num_windows, w², w²) True=may-attend mask for shifted windows:
    after the cyclic roll, positions wrapped across the image boundary land in
    the same window but must not attend to each other (Swin's shifted-window
    mask, computed here at trace time as a numpy constant)."""
    img = np.zeros((h, w), np.int32)
    cnt = 0
    for hs in (slice(0, h - window), slice(h - window, h - shift), slice(h - shift, None)):
        for ws in (slice(0, w - window), slice(w - window, w - shift), slice(w - shift, None)):
            img[hs, ws] = cnt
            cnt += 1
    wins = (
        img.reshape(h // window, window, w // window, window)
        .transpose(0, 2, 1, 3)
        .reshape(-1, window * window)
    )
    return wins[:, :, None] == wins[:, None, :]


def swin_attention(x, p, lcfg: ModelConfig, h: int, w: int, window: int, shift: int):
    """Windowed multi-head self-attention over an (B, h·w, C) feature map:
    optional cyclic shift, window partition, per-window attention (+ wrap
    mask), reverse. Window sequences are tiny (w²≈49) so the plain XLA einsum
    path is the right kernel — the batched GEMMs land on the MXU."""
    b, _, c = x.shape
    heads, hd = lcfg.num_heads, c // lcfg.num_heads
    x4 = x.reshape(b, h, w, c)
    if shift:
        x4 = jnp.roll(x4, (-shift, -shift), (1, 2))
    nh, nw = h // window, w // window
    ws2 = window * window
    xw = (
        x4.reshape(b, nh, window, nw, window, c)
        .transpose(0, 1, 3, 2, 4, 5)
        .reshape(b * nh * nw, ws2, c)
    )
    q, k, v = project_qkv_heads(xw, p, lcfg)  # fused projection
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / np.sqrt(hd)
    if shift:
        mask = jnp.asarray(_swin_attn_mask(h, w, window, shift))  # (nW, ws2, ws2)
        scores = scores.reshape(b, nh * nw, heads, ws2, ws2)
        scores = jnp.where(mask[None, :, None], scores, -1e30)
        scores = scores.reshape(b * nh * nw, heads, ws2, ws2)
    attn = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    o = jnp.einsum("bhqk,bkhd->bqhd", attn, v).reshape(-1, ws2, c)
    o = o @ p["wo"].astype(x.dtype)
    o = (
        o.reshape(b, nh, nw, window, window, c)
        .transpose(0, 1, 3, 2, 4, 5)
        .reshape(b, h, w, c)
    )
    if shift:
        o = jnp.roll(o, (shift, shift), (1, 2))
    return o.reshape(b, h * w, c)


def swin_layer(x, p, cfg: ModelConfig, i: int, remat_attn: bool = False):
    """One Swin block: layer index → static (stage, geometry); odd blocks in a
    stage use the shifted window. Residual + norm + MLP reuse the shared
    transformer pieces at the stage's width."""
    stage, j = swin_stage_of(cfg, i)
    h, w, c, _ = swin_geometry(cfg, stage)
    lcfg = vision_layer_cfg(cfg, i)
    window = swin_window_for(cfg, stage)
    shift = window // 2 if (j % 2 == 1 and window < h) else 0

    def attn(x_):
        return swin_attention(x_, p["attn"], lcfg, h, w, window, shift)

    if remat_attn:
        attn = jax.checkpoint(attn)
    x = x + attn(norm(x, p["attn_norm"], lcfg))
    return mlp_residual(x, p, lcfg)


def patch_merge(x, p, cfg: ModelConfig, stage: int):
    """Swin downsampling between stages: 2×2 neighborhood concat (4C) →
    norm → linear to 2C; resolution quarters, width doubles."""
    h, w, c, _ = swin_geometry(cfg, stage)
    b = x.shape[0]
    x = (
        x.reshape(b, h // 2, 2, w // 2, 2, c)
        .transpose(0, 1, 3, 2, 4, 5)
        .reshape(b, (h // 2) * (w // 2), 4 * c)
    )
    x = norm(x, p["norm"], cfg)
    return x @ p["w"].astype(x.dtype)


def cls_head(y, params, cfg: ModelConfig):
    """Mean-pooled classification head: (B, L, C) → (B, num_classes)."""
    pooled = y.mean(axis=1)
    return pooled @ params["head"]["w"].astype(y.dtype)


def forward_vision(params, pixels, cfg: ModelConfig, layer_hook=None):
    """ViT/Swin forward → class logits. ``layer_hook(i, x, lp)`` carries the
    per-layer hybrid strategies exactly as in the token models; Swin's
    patch-merging projections sit between stages as model-level params (like
    final_norm — replicated/ZeRO, never a per-layer strategy)."""
    x = vision_embed(pixels, params, cfg)
    if cfg.swin_depths:
        i = 0
        for s, depth in enumerate(cfg.swin_depths):
            for _ in range(depth):
                if layer_hook is not None:
                    x = layer_hook(i, x, params["layers"][i])
                else:
                    x = swin_layer(x, params["layers"][i], cfg, i)
                i += 1
            if s < len(cfg.swin_depths) - 1:
                x = patch_merge(x, params["merges"][s], cfg, s)
    else:
        for i, lp in enumerate(params["layers"]):
            if layer_hook is not None:
                x = layer_hook(i, x, lp)
            else:
                x = decoder_layer(x, lp, cfg)  # causal=False → encoder block
    x = norm(x, params["final_norm"], cfg)
    return cls_head(x, params, cfg)


def cls_loss_sum(params, batch, cfg: ModelConfig, layer_hook=None):
    """(nll_sum, sample_count) for image classification on the int32 pixel
    batch contract: row = pixels ‖ label."""
    pixels, labels = split_batch(batch, cfg)
    logits = forward_vision(params, pixels, cfg, layer_hook=layer_hook)
    return cross_entropy_sum(logits, labels, remat=ce_remat(cfg))


def _cross_entropy_sum_impl(logits, labels, ignore_index: int = -100):
    logits = logits.astype(jnp.float32)
    mask = labels != ignore_index
    safe = jnp.where(mask, labels, 0)
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    nll = (lse - picked) * mask
    return nll.sum(), mask.sum()


def cross_entropy_sum(logits, labels, ignore_index: int = -100, remat: bool = False):
    """(nll_sum, valid_token_count) in fp32 — the accumulation-safe form:
    micro-batch sums combine exactly into the global token-mean even when
    ignore_index masks are unevenly distributed across chunks.

    ``remat``: rematerialize the fp32 cast / log-sum-exp in the backward from
    the compute-dtype logits instead of letting autodiff save the fp32-widened
    (B, S, V/vocab_tp) copy — the "cast at the consumer" rule; loss-carrying
    callers pass ``cfg.mlp_recompute == 'policy'``."""
    if remat:
        return jax.checkpoint(
            partial(_cross_entropy_sum_impl, ignore_index=ignore_index)
        )(logits, labels)
    return _cross_entropy_sum_impl(logits, labels, ignore_index)


def cross_entropy_loss(logits, labels, ignore_index: int = -100):
    """Token-mean cross entropy in fp32. Written shard-friendly: when logits
    are vocab-sharded (vocab_tp), XLA keeps the log-sum-exp partial per shard
    and psums scalars — the vocab-parallel cross entropy of the reference
    (site_package/megatron/core/tensor_parallel/cross_entropy.py:18-155)
    without the hand-written autograd Function."""
    s, n = cross_entropy_sum(logits, labels, ignore_index)
    return s / jnp.maximum(n, 1)


def mlm_positions(tokens, cfg: ModelConfig):
    """Deterministic masked-LM positions: multiplicative token⊕position hash
    thresholded at ``mlm_mask_rate``. Keeping masking a pure function of the
    batch (instead of RNG state) preserves the framework-wide contract that
    loss depends only on (params, batch) — resume/parity tests hold for
    encoders exactly as for decoders."""
    pos = jnp.arange(tokens.shape[-1], dtype=jnp.uint32)
    h = tokens.astype(jnp.uint32) * jnp.uint32(2654435761) + pos * jnp.uint32(40503)
    h = (h ^ (h >> jnp.uint32(16))) * jnp.uint32(2246822519)
    frac = (h & jnp.uint32(0xFFFF)).astype(jnp.float32) / 65536.0
    return frac < cfg.mlm_mask_rate


def mlm_loss_sum(params, batch, cfg: ModelConfig, layer_hook=None):
    """(nll_sum, masked_token_count) BERT-style masked-LM pieces on the same
    (B, S+1) token batches the CLM path uses. The last vocab id serves as
    [MASK]; only masked positions contribute loss."""
    inputs, labels = split_batch(batch, cfg)
    logits = forward(params, inputs, cfg, layer_hook=layer_hook)
    return cross_entropy_sum(logits, labels, remat=ce_remat(cfg))


def batch_row_width(cfg: ModelConfig, seq: int) -> int:
    """Width of one loader batch row — the shape side of the ``split_batch``
    contract, shared by every abstract-batch builder (aot warmup, fidelity
    harness) so they lower the SAME program the run dispatches: vision rows
    flatten to sample_len pixels + label; packed CLM rows are tokens ‖
    segment ids, 2·(S+1) (data/packing.py); plain windows are S+1."""
    if cfg.image_size:
        return cfg.sample_len + 1
    if cfg.pack_sequences:
        return 2 * (seq + 1)
    return seq + 1


def split_batch(batch, cfg: ModelConfig):
    """One (B, sample_len+1) int32 batch row → (model inputs, loss labels) per
    objective. Centralized so the pipeline engines (which re-implement the
    embed→stages→head seam) agree with the GSPMD path on every objective:
    'clm' next-token shift, 'mlm' deterministic masking, 'cls' pixels‖label."""
    if cfg.objective == "cls":
        return batch[:, :-1], batch[:, -1]
    if cfg.objective == "mlm":
        tokens = batch[:, :-1]
        mask = mlm_positions(tokens, cfg)
        return jnp.where(mask, cfg.vocab_size - 1, tokens), jnp.where(mask, tokens, -100)
    if cfg.pack_sequences:
        # packed row (B, 2·(S+1)) = tokens ‖ segment ids. Inputs keep both
        # halves (the model needs the segment ids at every layer); labels are
        # next-token WITHIN a segment only — a position whose successor
        # belongs to a different segment (document boundary) or to padding
        # (segment 0) carries no loss.
        s1 = batch.shape[1] // 2
        tokens, seg = batch[:, :s1], batch[:, s1:]
        inputs = jnp.concatenate([tokens[:, :-1], seg[:, :-1]], axis=1)
        same = (seg[:, 1:] == seg[:, :-1]) & (seg[:, 1:] > 0)
        return inputs, jnp.where(same, tokens[:, 1:], -100)
    return batch[:, :-1], batch[:, 1:]


def embed_any(inputs, params, cfg: ModelConfig):
    """Input embedding for either modality: token table or patch projection."""
    if cfg.image_size:
        return vision_embed(inputs, params, cfg)
    return embed(inputs, params, cfg)


def ce_remat(cfg: ModelConfig) -> bool:
    """Whether loss paths should rematerialize the cross-entropy fp32 cast
    (one rule for the GSPMD path and every pipeline engine's head seam)."""
    return cfg.mlp_recompute == "policy"


def head_loss_sum(y, params, labels, cfg: ModelConfig):
    """Final-norm'd features (B, S, H) → (nll_sum, count): LM head + token
    cross entropy, or pooled classification head + class cross entropy."""
    if cfg.objective == "cls":
        return cross_entropy_sum(cls_head(y, params, cfg), labels, remat=ce_remat(cfg))
    return cross_entropy_sum(lm_head(y, params, cfg), labels, remat=ce_remat(cfg))


def loss_tokens_per_sample(cfg: ModelConfig, seq_len: int) -> int:
    """Static count of loss-carrying positions per sample (fp16 scale seeding;
    mlm uses the expected masked fraction)."""
    if cfg.objective == "cls":
        return 1
    if cfg.objective == "mlm":
        return max(1, int(seq_len * cfg.mlm_mask_rate))
    if cfg.enc_layers > 0:
        return seq_len - cfg.enc_seq
    return seq_len


def lm_loss_sum(params, batch, cfg: ModelConfig, layer_hook=None):
    """(nll_sum, token_count) loss pieces on a (B, S+1) token batch
    (reference synthetic-data convention: models/llama_hf/dataloader.py:5-30).
    Dispatches on cfg.objective: 'clm' next-token; 'mlm' masked-LM; 'cls'
    image classification (vision families); enc-dec models (enc_layers > 0)
    run seq2seq next-token loss on the decoder half of the
    (B, enc_seq + dec_seq + 1) sample."""
    if cfg.objective == "cls":
        return cls_loss_sum(params, batch, cfg, layer_hook=layer_hook)
    if cfg.objective == "mlm":
        return mlm_loss_sum(params, batch, cfg, layer_hook=layer_hook)
    if cfg.enc_layers > 0:
        enc_tokens = batch[:, : cfg.enc_seq]
        dec = batch[:, cfg.enc_seq :]
        logits = forward_encdec(params, enc_tokens, dec[:, :-1], cfg, layer_hook=layer_hook)
        return cross_entropy_sum(logits, dec[:, 1:], remat=ce_remat(cfg))
    # split_batch, not ad-hoc slicing: packed rows carry segment ids the
    # boundary-masked labels must be derived from
    tokens, labels = split_batch(batch, cfg)
    logits = forward(params, tokens, cfg, layer_hook=layer_hook)
    return cross_entropy_sum(logits, labels, remat=ce_remat(cfg))


def lm_loss(params, batch, cfg: ModelConfig, layer_hook=None):
    s, n = lm_loss_sum(params, batch, cfg, layer_hook=layer_hook)
    return s / jnp.maximum(n, 1)


# Preset configs mirroring the reference model zoo sizes
# (galvatron/models/llama_hf/arguments.py:6, gpt_hf/arguments.py:6)
PRESETS: Dict[str, ModelConfig] = {
    "llama-0.3b": ModelConfig(
        vocab_size=32000, hidden_size=1024, num_layers=24, num_heads=16, max_seq_len=2048
    ),
    "llama-7b": ModelConfig(
        vocab_size=32000, hidden_size=4096, num_layers=32, num_heads=32,
        ffn_dim=11008, max_seq_len=2048,
    ),
    "llama-13b": ModelConfig(
        vocab_size=32000, hidden_size=5120, num_layers=40, num_heads=40,
        ffn_dim=13824, max_seq_len=2048,
    ),
    "llama-30b": ModelConfig(
        vocab_size=32000, hidden_size=6656, num_layers=60, num_heads=52,
        ffn_dim=17920, max_seq_len=2048,
    ),
    "gpt-0.3b": ModelConfig(
        use_bias=True,
        vocab_size=50257, hidden_size=1024, num_layers=24, num_heads=16,
        max_seq_len=1024, pos_embed="learned", norm_type="layernorm", act_fn="gelu",
        tie_word_embeddings=True,
    ),
    "gpt-1.5b": ModelConfig(
        use_bias=True,
        vocab_size=50257, hidden_size=1600, num_layers=48, num_heads=25,
        max_seq_len=1024, pos_embed="learned", norm_type="layernorm", act_fn="gelu",
        tie_word_embeddings=True,
    ),
    "gpt-2.7b": ModelConfig(
        use_bias=True,
        vocab_size=50257, hidden_size=2560, num_layers=32, num_heads=32,
        max_seq_len=2048, pos_embed="learned", norm_type="layernorm", act_fn="gelu",
        tie_word_embeddings=True,
    ),
    "gpt-6.7b": ModelConfig(
        use_bias=True,
        vocab_size=50257, hidden_size=4096, num_layers=32, num_heads=32,
        max_seq_len=2048, pos_embed="learned", norm_type="layernorm", act_fn="gelu",
        tie_word_embeddings=True,
    ),
    # OPT family (decoder-only, ReLU MLPs, learned positions with the
    # characteristic +2 offset — handled at HF import by slicing the table;
    # reference parity target: the gpt_hf-style HF-wrapping family pattern)
    "opt-125m": ModelConfig(
        use_bias=True,
        vocab_size=50272, hidden_size=768, num_layers=12, num_heads=12,
        max_seq_len=2048, pos_embed="learned", norm_type="layernorm", act_fn="relu",
        tie_word_embeddings=True,
    ),
    "opt-1.3b": ModelConfig(
        use_bias=True,
        vocab_size=50272, hidden_size=2048, num_layers=24, num_heads=32,
        max_seq_len=2048, pos_embed="learned", norm_type="layernorm", act_fn="relu",
        tie_word_embeddings=True,
    ),
    "opt-6.7b": ModelConfig(
        use_bias=True,
        vocab_size=50272, hidden_size=4096, num_layers=32, num_heads=32,
        max_seq_len=2048, pos_embed="learned", norm_type="layernorm", act_fn="relu",
        tie_word_embeddings=True,
    ),
    "opt-13b": ModelConfig(
        use_bias=True,
        vocab_size=50272, hidden_size=5120, num_layers=40, num_heads=40,
        max_seq_len=2048, pos_embed="learned", norm_type="layernorm", act_fn="relu",
        tie_word_embeddings=True,
    ),
    "opt-30b": ModelConfig(
        use_bias=True,
        vocab_size=50272, hidden_size=7168, num_layers=48, num_heads=56,
        max_seq_len=2048, pos_embed="learned", norm_type="layernorm", act_fn="relu",
        tie_word_embeddings=True,
    ),
    # encoder families (reference legacy bert support: core/parallel.py:64-89,
    # cost_model.py model_type handling)
    "bert-base": ModelConfig(
        use_bias=True,
        vocab_size=30528, hidden_size=768, num_layers=12, num_heads=12,
        max_seq_len=512, pos_embed="learned", norm_type="layernorm", act_fn="gelu",
        tie_word_embeddings=True, causal=False, objective="mlm",
    ),
    "bert-large": ModelConfig(
        use_bias=True,
        vocab_size=30528, hidden_size=1024, num_layers=24, num_heads=16,
        max_seq_len=512, pos_embed="learned", norm_type="layernorm", act_fn="gelu",
        tie_word_embeddings=True, causal=False, objective="mlm",
    ),
    # encoder-decoder family (reference legacy t5 model_type; positions are
    # learned, not T5 relative bias — documented deviation)
    "t5-base": ModelConfig(
        vocab_size=32128, hidden_size=768, num_layers=12, num_heads=12,
        ffn_dim=3072, max_seq_len=512, enc_layers=12, enc_seq=512,
        pos_embed="learned", norm_type="rms", act_fn="gelu",
        tie_word_embeddings=True,
    ),
    "t5-large": ModelConfig(
        vocab_size=32128, hidden_size=1024, num_layers=24, num_heads=16,
        ffn_dim=4096, max_seq_len=512, enc_layers=24, enc_seq=512,
        pos_embed="learned", norm_type="rms", act_fn="gelu",
        tie_word_embeddings=True,
    ),
    "t5-3b": ModelConfig(
        vocab_size=32128, hidden_size=1024, num_layers=24, num_heads=32,
        ffn_dim=16384, max_seq_len=512, enc_layers=24, enc_seq=512,
        pos_embed="learned", norm_type="rms", act_fn="gelu",
        tie_word_embeddings=True,
    ),
    # vision families (reference legacy vit/swin model_type branches,
    # core/parallel.py:64-89, cost_model.py:76,87-106)
    "vit-base": ModelConfig(
        use_bias=True,
        vocab_size=1, hidden_size=768, num_layers=12, num_heads=12,
        max_seq_len=0, pos_embed="learned", norm_type="layernorm", act_fn="gelu",
        causal=False, objective="cls", image_size=224, patch_size=16,
    ),
    "vit-large": ModelConfig(
        use_bias=True,
        vocab_size=1, hidden_size=1024, num_layers=24, num_heads=16,
        max_seq_len=0, pos_embed="learned", norm_type="layernorm", act_fn="gelu",
        causal=False, objective="cls", image_size=224, patch_size=16,
    ),
    "vit-huge": ModelConfig(
        use_bias=True,
        vocab_size=1, hidden_size=1280, num_layers=32, num_heads=16,
        max_seq_len=0, pos_embed="learned", norm_type="layernorm", act_fn="gelu",
        causal=False, objective="cls", image_size=224, patch_size=14,
    ),
    "swin-base": ModelConfig(
        use_bias=True,
        vocab_size=1, hidden_size=128, num_layers=24, num_heads=4,
        max_seq_len=0, pos_embed="learned", norm_type="layernorm", act_fn="gelu",
        causal=False, objective="cls", image_size=224, patch_size=4,
        swin_depths=(2, 2, 18, 2), swin_window=7,
    ),
    "swin-large": ModelConfig(
        use_bias=True,
        vocab_size=1, hidden_size=192, num_layers=24, num_heads=6,
        max_seq_len=0, pos_embed="learned", norm_type="layernorm", act_fn="gelu",
        causal=False, objective="cls", image_size=224, patch_size=4,
        swin_depths=(2, 2, 18, 2), swin_window=7,
    ),
    "baichuan-7b": ModelConfig(
        vocab_size=64000, hidden_size=4096, num_layers=32, num_heads=32,
        ffn_dim=11008, max_seq_len=4096,
    ),
    "baichuan-13b": ModelConfig(
        vocab_size=64000, hidden_size=5120, num_layers=40, num_heads=40,
        ffn_dim=13696, max_seq_len=4096, pos_embed="alibi",
    ),
}
