from galvatron_tpu.models.swin import main

raise SystemExit(main())
