"""Swin hierarchical vision family entry — image classification.

The reference carries swin only as a legacy model_type branch
(galvatron/core/parallel.py:64-89, cost_model.py:87-106); here it is a live
family: shifted-window attention with trace-time wrap masks, patch-merging
pyramid (width doubles / resolution quarters per stage —
modeling.swin_layer/patch_merge), pooled classification head. Stages have
heterogeneous widths, so Swin runs on the pp=1 GSPMD path with per-layer
TP/SP/ZeRO/ckpt strategies (the multi-layer-type search case, like enc-dec).
Sizes swin-base/large.
"""

DEFAULT_MODEL = "swin-base"
SIZES = ("swin-base", "swin-large")


def main(argv=None):
    from galvatron_tpu.cli import main as cli_main

    return cli_main(argv, model_default=DEFAULT_MODEL)
