from galvatron_tpu.models.gpt_fa import main

raise SystemExit(main())
