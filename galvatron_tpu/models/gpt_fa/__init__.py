"""GPT flash-attention family entry (reference: galvatron/models/gpt_fa/ —
the flash-attn GPT backbone variant of gpt_hf, models/gpt_fa/
GPTModel_tensor_parallel.py:1-14).

Same sizes as the gpt family; ``attn_impl='flash'`` (the Pallas kernel,
galvatron_tpu.ops.flash_attention) forced by default — see
galvatron_tpu.models.llama_fa for the design note.
"""

from galvatron_tpu.models.gpt import SIZES  # noqa: F401 — same sizes
from galvatron_tpu.models.llama_fa import fa_main

DEFAULT_MODEL = "gpt-1.5b"


def main(argv=None):
    return fa_main(argv, DEFAULT_MODEL)
