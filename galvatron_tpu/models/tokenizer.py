"""Tokenizers for the generation path.

Counterpart of the reference's tokenizer subsystem (reference:
galvatron/site_package/megatron/tokenizer/tokenizer.py — build_tokenizer with
BPE/sentencepiece backends + vocab-size padding for TP divisibility). Here:

- ``ByteTokenizer``: dependency-free UTF-8 byte-level tokenizer (ids 0..255
  are bytes, then bos/eos/pad) — always available, used by demos and tests.
- ``HFTokenizer``: wraps a ``transformers`` tokenizer loaded from a LOCAL
  path (no network egress); gated import.

``pad_vocab_size`` mirrors the reference's make-vocab-size-divisible logic
(megatron/tokenizer/tokenizer.py _vocab_size_with_padding) so vocab-parallel
embedding shards stay equal-sized under any ``vocab_tp``.
"""

from __future__ import annotations

from typing import List, Optional, Sequence


def pad_vocab_size(n: int, divisor: int = 128) -> int:
    """Round vocab up so TP shards divide evenly."""
    return (n + divisor - 1) // divisor * divisor


class ByteTokenizer:
    """UTF-8 bytes; ids 256/257/258 = bos/eos/pad."""

    def __init__(self):
        self.bos_id = 256
        self.eos_id = 257
        self.pad_id = 258

    @property
    def vocab_size(self) -> int:
        return pad_vocab_size(259)

    def encode(self, text: str, bos: bool = True) -> List[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_id] if bos else []) + ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """transformers tokenizer from a local directory (offline)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self.tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.bos_id = self.tok.bos_token_id
        self.eos_id = self.tok.eos_token_id
        self.pad_id = self.tok.pad_token_id
        if self.pad_id is None:
            self.pad_id = self.eos_id if self.eos_id is not None else 0

    @property
    def vocab_size(self) -> int:
        return pad_vocab_size(len(self.tok))

    def encode(self, text: str, bos: bool = True) -> List[int]:
        ids = self.tok.encode(text, add_special_tokens=False)
        if bos and self.bos_id is not None:
            ids = [self.bos_id] + ids
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return self.tok.decode(list(ids), skip_special_tokens=True)


def build_tokenizer(name_or_path: Optional[str] = None):
    """(reference: build_tokenizer, megatron/tokenizer/tokenizer.py)"""
    if name_or_path in (None, "", "byte"):
        return ByteTokenizer()
    return HFTokenizer(name_or_path)
