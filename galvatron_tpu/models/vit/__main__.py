from galvatron_tpu.models.vit import main

raise SystemExit(main())
