"""ViT vision-encoder family entry — image classification.

The reference carries vision support only as legacy wrapping branches (vit
handling in galvatron/core/parallel.py:64-89 and cost_model.py model_type);
here it is a live family: patch-projection embedding + bidirectional encoder
blocks over the full hybrid-parallel runtime (per-layer TP/SP/ZeRO/ckpt and
all pipeline schedules — layers are homogeneous), pooled classification head,
sizes vit-base/large/huge. Samples are uint8 pixel rows ‖ class label in the
framework-wide int32 batch contract (modeling.vision_embed).
"""

DEFAULT_MODEL = "vit-base"
SIZES = ("vit-base", "vit-large", "vit-huge")


def main(argv=None):
    from galvatron_tpu.cli import main as cli_main

    return cli_main(argv, model_default=DEFAULT_MODEL)
