from galvatron_tpu.models.llama import main

raise SystemExit(main())
