"""LLaMA family entry (reference: galvatron/models/llama_hf/ and llama_fa/ —
the flash-attention variant is the same family here with attn_impl='flash',
which is the default on TPU). Sizes: llama-0.3b/7b/13b/30b
(reference arguments.py:6)."""

DEFAULT_MODEL = "llama-7b"
SIZES = ("llama-0.3b", "llama-7b", "llama-13b", "llama-30b")


def main(argv=None):
    from galvatron_tpu.cli import main as cli_main

    return cli_main(argv, model_default=DEFAULT_MODEL)
