"""Baichuan family entry (reference: galvatron/models/baichuan/ — flash-attn
GPT with HF configs; the 13B variant uses ALiBi positions, see
PRESETS['baichuan-13b'])."""

DEFAULT_MODEL = "baichuan-7b"
SIZES = ("baichuan-7b", "baichuan-13b")


def main(argv=None):
    from galvatron_tpu.cli import main as cli_main

    return cli_main(argv, model_default=DEFAULT_MODEL)
