from galvatron_tpu.models.baichuan import main

raise SystemExit(main())
