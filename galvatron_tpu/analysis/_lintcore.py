"""Shared core for the AST linters (`lint.py` trace hygiene, `concurrency.py`
lock discipline): the ONE suppression contract, finding dedup, file walking
and CLI scaffolding — extracted so the GTL1xx and GTL2xx families cannot
drift on how ``# gta: disable=<CODE> — <reason>`` is parsed or reported.
"""

from __future__ import annotations

import ast
import io
import os
import re
import sys
import tokenize
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from galvatron_tpu.analysis.diagnostics import Diagnostic, format_report

# codes must LOOK like codes (GTL101/GTA012) so a plain-word reason after a
# space ("# gta: disable=GTL101 gated by flag") parses as the reason, not as
# part of the code list
SUPPRESS_RE = re.compile(
    r"#\s*gta:\s*disable=((?:GT[A-Z]\d+\s*,\s*)*GT[A-Z]\d+)(.*)"
)


class Suppressions:
    """Per-file suppression map: ``# gta: disable=<CODE> — <reason>`` by
    line. A reasonless suppression is itself a finding (GTL100), collected
    in ``malformed``."""

    def __init__(self, src: str, path: str):
        self.by_line: Dict[int, Set[str]] = {}
        self.malformed: List[Diagnostic] = []
        try:
            toks = tokenize.generate_tokens(io.StringIO(src).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                m = SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
                reason = m.group(2).strip().lstrip("—-: ").strip()
                if not reason:
                    self.malformed.append(
                        Diagnostic(
                            "GTL100",
                            "suppression without a reason — say why the rule "
                            "does not apply here",
                            hint="# gta: disable=<CODE> — <reason>",
                            source=path,
                            line=tok.start[0],
                        )
                    )
                    continue
                self.by_line.setdefault(tok.start[0], set()).update(codes)
        except tokenize.TokenError:
            pass

    def active(self, line: int, code: str) -> bool:
        return code in self.by_line.get(line, ())


def comment_lines(src: str) -> Dict[int, str]:
    """{line: comment text} for every comment token — the channel the
    guarded-by annotation grammar rides (tokenize, not regex, so strings
    containing '#' cannot fake an annotation)."""
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except tokenize.TokenError:
        pass
    return out


def dotted(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """('np', 'random', 'randint') for np.random.randint; None otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


class BaseLinter:
    """Suppression-aware finding collector both linters subclass: ``_emit``
    drops suppressed findings (counting each site once even when a rule
    re-walks a region), ``finalize`` dedups by (code, line, message)."""

    def __init__(self, src: str, path: str):
        self.src = src
        self.path = path
        self.findings: List[Diagnostic] = []
        self.suppressed = 0
        self._sup_seen: set = set()
        self.sup = Suppressions(src, path)

    def parse(self) -> Optional[ast.AST]:
        try:
            return ast.parse(self.src)
        except SyntaxError as e:
            # not a linter's job; flag nothing (py_compile/CI catches it)
            print(f"{self.path}: skipped (syntax error: {e})", file=sys.stderr)
            return None

    def _emit(self, code: str, line: int, message: str, hint: str = ""):
        if self.sup.active(line, code):
            # same dedup key as the findings list: a rule's double pass over
            # loop bodies (and nested-loop re-walks) must not over-count one
            # suppression
            key = (code, line, message)
            if key not in self._sup_seen:
                self._sup_seen.add(key)
                self.suppressed += 1
            return
        self.findings.append(
            Diagnostic(code, message, hint=hint, source=self.path, line=line)
        )

    def finalize(self) -> List[Diagnostic]:
        seen = set()
        unique = []
        for f in self.findings:
            key = (f.code, f.line, f.message)
            if key not in seen:
                seen.add(key)
                unique.append(f)
        self.findings = unique
        return self.findings


LintFn = Callable[[str, str], Tuple[List[Diagnostic], int]]


def walk_py_files(paths: Sequence[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                if "__pycache__" in root:
                    continue
                files += [os.path.join(root, n) for n in names if n.endswith(".py")]
        elif p.endswith(".py"):
            files.append(p)
    return sorted(files)


def lint_paths_with(lint_source: LintFn, paths: Sequence[str]) -> Tuple[List[Diagnostic], int]:
    findings: List[Diagnostic] = []
    suppressed = 0
    for f in walk_py_files(paths):
        with open(f, encoding="utf-8") as fh:
            fs, sup = lint_source(fh.read(), f)
        findings += fs
        suppressed += sup
    return findings, suppressed


def cli_main(lint_source: LintFn, doc: str,
             argv: Optional[Sequence[str]] = None) -> int:
    """The shared ``python -m …`` entry for every analysis pass.

    Exit-code contract (identical for analysis.lint and
    analysis.concurrency, pinned by tests/test_lint.py):

    - 0 — clean, INCLUDING suppressed-only findings (a suppression is an
      explicit reviewed decision; the count is always printed so a
      silently-suppressed tree stays visible in the CI log);
    - 1 — at least one unsuppressed finding;
    - 2 — usage error: no paths given, or the given paths match no ``.py``
      file (a typo'd path must not masquerade as a clean run).

    ``-h``/``--help`` prints the pass's doc and exits 0."""
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(doc)
        return 0
    if not argv:
        print(doc)
        print("error: no paths given", file=sys.stderr)
        return 2
    if not walk_py_files(argv):
        print(f"error: no .py files under {argv}", file=sys.stderr)
        return 2
    findings, suppressed = lint_paths_with(lint_source, argv)
    if findings:
        print(format_report(findings, clean=""))
        print(f"({suppressed} suppressed)")
        return 1
    print(f"lint clean ({suppressed} suppressed)")
    return 0
