"""Structured diagnostics shared by the plan checker and the linter.

Every failure class has a STABLE code — ``GTA0xx`` for plan diagnostics,
``GTL1xx`` for trace-hygiene lint rules, ``GTL2xx`` for lock-discipline
lint rules, ``GTC0xx`` for the lowered-HLO collective auditor — so CI can
gate on specific codes, suppressions
can name them, and the docs table (DESIGN.md "Static analysis") stays the
single reference. Codes are append-only: a retired rule keeps its number.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

ERROR = "error"
WARN = "warn"

# code → (summary, default severity). The one registry both pillars and the
# DESIGN.md table draw from; tests assert the table and this dict agree.
CODES = {
    # --- plan checker (GTA0xx) ---
    "GTA001": ("unknown key in strategy/config JSON (typo'd fields silently no-op)", WARN),
    "GTA002": ("field fails to decode/validate (bad degree, dp_type, enum value)", ERROR),
    "GTA003": ("world size not a power of two, or pp does not divide it", ERROR),
    "GTA004": ("parallel-degree product exceeds the per-stage mesh extent", ERROR),
    "GTA005": ("pp_division malformed (length, sum vs layer count, empty stage)", ERROR),
    "GTA006": ("plan layer count disagrees with the model's total layers", ERROR),
    "GTA007": ("attention heads not divisible by the tp (or a2a cp) degree", ERROR),
    "GTA008": ("vocab size not divisible by vocab_tp", ERROR),
    "GTA009": ("global batch not divisible by chunks × the layer's dp extent", ERROR),
    "GTA010": ("sequence length not divisible by the sp/cp shard degree", ERROR),
    "GTA011": ("interleaved-schedule (vpp) constraint violated", ERROR),
    "GTA012": ("known XLA SPMD CHECK-crash cell: pp>1 × 1F1B × tp>1 × sp=0 × vocab_tp>1", ERROR),
    "GTA013": ("stage-stack seam: layers at the same stage position disagree (pp>1)", ERROR),
    "GTA014": ("expert-parallel degree invalid for the model's expert count", ERROR),
    "GTA015": ("cost-model memory estimate exceeds the device budget", ERROR),
    "GTA016": ("abstract sharding pass: annotated dim unsharded or spec invalid", WARN),
    "GTA017": ("checkpoint topology/plan fingerprint does not match the live mesh", ERROR),
    "GTA018": ("tp_overlap (collective-matmul) set on a layer with tp == 1", ERROR),
    # --- trace-hygiene linter (GTL1xx) ---
    "GTL100": ("malformed suppression: '# gta: disable=<rule>' needs a reason", ERROR),
    "GTL101": ("host-device sync on a jitted result inside a hot loop", WARN),
    "GTL102": ("Python/numpy RNG inside a traced (jitted) function", ERROR),
    "GTL103": ("numpy buffer mutated after being handed to async dispatch", ERROR),
    "GTL104": ("Python branch on a traced argument inside a jitted function", ERROR),
    "GTL105": ("jax.jit constructed inside a loop (fresh cache per iteration)", WARN),
    "GTL106": ("unhashable literal passed as a static jit argument", ERROR),
    # --- lock-discipline linter (GTL2xx, analysis/concurrency.py) ---
    "GTL200": ("guarded-by declaration names a lock the class never creates", ERROR),
    "GTL201": ("guarded field accessed outside its declared lock", ERROR),
    "GTL202": ("lock-order inversion: acquisition-order graph has a cycle", ERROR),
    "GTL203": ("blocking call while holding a lock", ERROR),
    "GTL204": ("thread leak: non-daemon thread without a reachable join, or started before __init__ completes", ERROR),
    "GTL205": ("Condition.wait outside a while-predicate loop (lost wakeup)", ERROR),
    "GTL206": ("check-then-act: guarded read and dependent write hold the lock separately", ERROR),
    # --- HLO collective auditor (GTC0xx, analysis/comm_audit.py) ---
    "GTC001": ("comm fidelity: predicted/lowered volume ratio outside the tolerance band", ERROR),
    "GTC002": ("plan term predicts communication but the lowering grounds none", WARN),
    "GTC003": ("lowered collective attributable to no plan term (unsolicited comm)", WARN),
    "GTC004": ("program failed to lower during the comm audit", ERROR),
    "GTC005": ("collective replica groups match no mesh-axis subgroup", WARN),
    "GTC010": ("silent replication: plan-sharded tensor lowered fully replicated", WARN),
    "GTC011": ("inter-layer resharding seam the plan never declared", WARN),
    "GTC012": ("tp_overlap layer still lowers a monolithic (non-overlapped) collective", WARN),
}


@dataclass(frozen=True)
class Diagnostic:
    """One finding: stable code + provenance + a one-line fix hint."""

    code: str
    message: str  # one-line statement of the defect
    hint: str = ""  # one-line fix hint naming the offending field
    field: str = ""  # JSON field / config attribute (e.g. "tp_sizes_enc[3]")
    source: Optional[str] = None  # file path when checking a file
    line: int = 0  # 1-based source line (linter findings)
    severity: str = ""  # defaulted from CODES when empty

    def __post_init__(self):
        if self.code not in CODES:
            raise ValueError(f"unregistered diagnostic code {self.code!r}")
        if not self.severity:
            object.__setattr__(self, "severity", CODES[self.code][1])

    def render(self) -> str:
        where = ""
        if self.source:
            where = f"{self.source}:{self.line}: " if self.line else f"{self.source}: "
        fld = f" [{self.field}]" if self.field else ""
        hint = f"\n    hint: {self.hint}" if self.hint else ""
        return f"{where}{self.code} {self.severity}: {self.message}{fld}{hint}"


def errors(diags: List[Diagnostic]) -> List[Diagnostic]:
    return [d for d in diags if d.severity == ERROR]


def warnings(diags: List[Diagnostic]) -> List[Diagnostic]:
    return [d for d in diags if d.severity == WARN]


def format_report(diags: List[Diagnostic], clean: str = "plan OK") -> str:
    if not diags:
        return clean
    lines = [d.render() for d in diags]
    ne, nw = len(errors(diags)), len(warnings(diags))
    lines.append(f"{ne} error(s), {nw} warning(s)")
    return "\n".join(lines)
