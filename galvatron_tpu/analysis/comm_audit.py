"""HLO collective auditor: static comm-footprint extraction, plan-vs-lowered
fidelity gates, and resharding lint.

The cost model (search/cost_model.py) prices per-term communication volumes
that drive the whole strategy search, yet nothing checks those terms against
what XLA actually lowers — and Alpa/GSPMD both report that *silent resharding
inserted by the SPMD partitioner* is the dominant source of surprise comm
volume.  This module closes the static half of that loop with zero execution
and zero devices:

1. **Footprint extraction** (``extract_footprint``): AOT-``lower`` every
   registered program of a (plan × ModelConfig × mesh) via the aot registry
   (abstract inputs only) and walk the StableHLO text.  Two tiers, because
   the two lowering paths leave different evidence:

   - shard_map programs (pipeline engines, tp_overlap collective-matmul,
     ring CP) lower EXPLICIT ``stablehlo.all_reduce`` / ``all_gather`` /
     ``reduce_scatter`` / ``all_to_all`` / ``collective_permute`` ops with
     replica groups and per-shard tensor types → parsed into
     :class:`CollectiveSite` (kind, bytes, replica-group → mesh-axis
     attribution, call-site count, inside-a-loop flag);
   - the GSPMD (pp=1 jit) path lowers NO collectives — only
     ``mhlo.sharding`` entry annotations and ``custom_call @Sharding``
     constraints; those become :class:`ShardingSite` records (tile counts,
     replicated tails) — the evidence the resharding lint and the
     annotation-basis fidelity terms work from.

2. **Fidelity gate** (``fidelity_report``): per plan term, compare the cost
   model's analytic volume (``cost_model.comm_volume_breakdown``, replaying
   the model's OWN constants) against a volume re-derived here from the
   program's *actual* abstract parameter/batch shapes and lowered
   collectives using independent first-principles constants.  A
   ``predicted_over_lowered`` ratio outside the tolerance band is a
   ``GTC001``; a mispriced cost-model constant moves only the predicted
   side and trips the gate in CI instead of surfacing later as an
   unexplained step-time regression.

3. **Resharding lint** (``resharding_lint``): diagnose comm the plan never
   asked for — fully-replicated lowerings of plan-sharded tensors (GTC010,
   generalizing GTA016 from abstract shardings to lowered reality),
   boundary resharding seams a uniform plan never declared (GTC011),
   tp_overlap layers whose lowering still contains the monolithic
   collective the decomposed matmul was supposed to replace (GTC012), and
   collectives on mesh-axis groups no plan term owns (GTC003).

Everything runs under ``JAX_PLATFORMS=cpu`` with a forced host-device world
(``aot.warmup.force_cpu_world``): ``lower()`` only — never ``compile()``,
never execute.
"""

from __future__ import annotations

import json
import re
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from galvatron_tpu.analysis.diagnostics import Diagnostic

# ---------------------------------------------------------------------------
# StableHLO text parsing
# ---------------------------------------------------------------------------

# MLIR element types → bytes (the subset this runtime emits)
DTYPE_BYTES = {
    "f64": 8.0, "f32": 4.0, "bf16": 2.0, "f16": 2.0,
    "f8E4M3FN": 1.0, "f8E5M2": 1.0,
    "i64": 8.0, "ui64": 8.0, "i32": 4.0, "ui32": 4.0,
    "i16": 2.0, "ui16": 2.0, "i8": 1.0, "ui8": 1.0, "i1": 1.0,
}

COLLECTIVE_KINDS = (
    "all_reduce", "all_gather", "reduce_scatter", "all_to_all",
    "collective_permute",
)

_TENSOR_RE = re.compile(r"tensor<((?:\d+x)*)([a-zA-Z][0-9A-Za-z]*)>")
_COLL_RE = re.compile(r"stablehlo\.(%s)\b" % "|".join(COLLECTIVE_KINDS))
# the operand type of a lowered op: `... : (tensor<...>) -> ...` — the
# parenthesis distinguishes it from attribute types like
# `replica_groups = dense<...> : tensor<2x4xi64>` on the same line
_OPERAND_RE = re.compile(r":\s*\((tensor<[^>]*>)")
_GROUPS_RE = re.compile(
    r"(?:replica_groups|source_target_pairs)\s*=\s*dense<(\[\[.*?\]\]|\[\]|[-0-9]+)>"
    r"\s*:\s*tensor<([0-9x]+)i64>"
)
_SHARDING_ATTR_RE = re.compile(r'mhlo\.sharding\s*=\s*"([^"]*)"')
_DEVICES_RE = re.compile(r"devices=\[([0-9,]+)\]")
_ARG_RE = re.compile(
    r"%arg\d+:\s*(tensor<[^>]*>)\s*\{[^}]*mhlo\.sharding\s*=\s*\"([^\"]*)\""
)


def parse_tensor_type(text: str) -> Optional[Tuple[Tuple[int, ...], str, float]]:
    """First ``tensor<...>`` in ``text`` → ``(shape, dtype, MB)``.  None if
    absent or the element type is unknown (tuple/token/dynamic types)."""
    m = _TENSOR_RE.search(text)
    if not m:
        return None
    dims, dtype = m.group(1), m.group(2)
    if dtype not in DTYPE_BYTES:
        return None
    shape = tuple(int(d) for d in dims.split("x") if d)
    n = 1
    for d in shape:
        n *= d
    return shape, dtype, n * DTYPE_BYTES[dtype] / 1e6


def parse_groups(text: str) -> Optional[Tuple[Tuple[int, ...], ...]]:
    """``replica_groups``/``source_target_pairs`` dense attr → group tuples.
    Handles the splat form (``dense<0> : tensor<1x1xi64>``)."""
    m = _GROUPS_RE.search(text)
    if not m:
        return None
    body = m.group(1)
    dims = [int(d) for d in m.group(2).split("x") if d]
    if body.startswith("["):
        try:
            rows = json.loads(body)
        except ValueError:
            return None
        return tuple(tuple(int(v) for v in row) for row in rows)
    v = int(body)  # splat: one value broadcast over the dense shape
    rows, cols = (dims + [1, 1])[:2]
    return tuple(tuple(v for _ in range(cols)) for _ in range(rows))


@dataclass(frozen=True)
class ShardingInfo:
    """One parsed ``mhlo.sharding`` attribute."""

    raw: str
    tile: Tuple[int, ...] = ()  # per-dim tile counts (replicated tail dropped)
    replicated: bool = False

    @property
    def sharded(self) -> bool:
        return any(t > 1 for t in self.tile)


def parse_sharding_attr(raw: str) -> ShardingInfo:
    """``{devices=[4,2,1]<=[8]}`` / ``{replicated}`` / ``{maximal ...}`` →
    structured tile counts.  ``last_tile_dim_replicate`` marks the trailing
    tile entry as a replication factor, not a tensor-dim shard."""
    raw = raw.strip()
    if "replicated" in raw and "last_tile" not in raw:
        return ShardingInfo(raw=raw, replicated=True)
    m = _DEVICES_RE.search(raw)
    if not m:
        return ShardingInfo(raw=raw, replicated="maximal" not in raw)
    tile = tuple(int(v) for v in m.group(1).split(","))
    if "last_tile_dim_replicate" in raw and tile:
        tile = tile[:-1]
    if any(t > 1 for t in tile):
        return ShardingInfo(raw=raw, tile=tile)
    return ShardingInfo(raw=raw, tile=tile, replicated=True)


@dataclass(frozen=True)
class CollectiveSite:
    """One explicit collective op in the lowered text (identical sites
    collapsed via ``count``).  ``tensor_mb`` is the operand's MB as lowered —
    inside a shard_map region that is the PER-DEVICE shard."""

    kind: str
    shape: Tuple[int, ...]
    dtype: str
    tensor_mb: float
    groups: Tuple[Tuple[int, ...], ...]
    group_size: int
    axes: Tuple[str, ...] = ()  # attributed mesh axes; () = unattributed
    in_loop: bool = False
    count: int = 1

    @property
    def wire_mb(self) -> float:
        """Per-participant on-wire MB per execution of this site × count.
        Ring conventions, per device: all_reduce moves 2(g-1)/g × operand;
        all_gather's operand is the SHARD and each device receives g-1 of
        them; reduce_scatter/all_to_all move (g-1)/g of the operand; a
        permute sends the operand once."""
        g = max(1, self.group_size)
        b = self.tensor_mb
        if self.kind == "all_reduce":
            per = 2.0 * (g - 1) / g * b
        elif self.kind == "all_gather":
            per = (g - 1) * b
        elif self.kind in ("reduce_scatter", "all_to_all"):
            per = (g - 1) / g * b
        else:  # collective_permute: one hop
            per = b
        return per * self.count


@dataclass(frozen=True)
class ShardingSite:
    """One sharding annotation: a ``custom_call @Sharding`` constraint
    (``site='constraint'``) or an entry-argument attribute (``site='arg'``,
    same-signature args collapsed via ``count``)."""

    site: str
    shape: Tuple[int, ...]
    dtype: str
    tensor_mb: float
    sharding: ShardingInfo
    count: int = 1


@dataclass
class CommFootprint:
    """The static collective footprint of ONE lowered program."""

    program: str
    collectives: List[CollectiveSite] = field(default_factory=list)
    shardings: List[ShardingSite] = field(default_factory=list)
    module_lines: int = 0
    lower_ms: float = 0.0
    error: Optional[str] = None

    def wire_mb_by_kind(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for c in self.collectives:
            out[c.kind] = out.get(c.kind, 0.0) + c.wire_mb
        return out

    def to_json(self) -> Dict[str, Any]:
        return {
            "program": self.program,
            "module_lines": self.module_lines,
            "lower_ms": round(self.lower_ms, 1),
            "error": self.error,
            "collectives": [
                {
                    "kind": c.kind, "shape": list(c.shape), "dtype": c.dtype,
                    "tensor_mb": round(c.tensor_mb, 6),
                    "wire_mb": round(c.wire_mb, 6),
                    "group_size": c.group_size, "groups": len(c.groups),
                    "axes": list(c.axes), "in_loop": c.in_loop,
                    "count": c.count,
                }
                for c in self.collectives
            ],
            "shardings": [
                {
                    "site": s.site, "shape": list(s.shape), "dtype": s.dtype,
                    "tensor_mb": round(s.tensor_mb, 6),
                    "sharding": s.sharding.raw, "tile": list(s.sharding.tile),
                    "replicated": s.sharding.replicated, "count": s.count,
                }
                for s in self.shardings
            ],
        }


def extract_footprint(text: str, program: str = "?") -> CommFootprint:
    """Walk lowered StableHLO text into a :class:`CommFootprint` — pure text
    analysis, no jax import, so canned modules unit-test the parser."""
    fp = CommFootprint(program=program)
    lines = text.splitlines()
    fp.module_lines = len(lines)

    coll_sites: Dict[Tuple, Dict[str, Any]] = {}
    arg_sites: Dict[Tuple, Dict[str, Any]] = {}
    constraint_sites: List[ShardingSite] = []
    # open-region brace balance of each enclosing stablehlo.while — a
    # collective inside one executes per trip, not once (count stays the
    # STATIC site count; in_loop flags the dynamic multiplicity)
    loop_stack: List[int] = []

    for i, line in enumerate(lines):
        net = line.count("{") - line.count("}")
        is_while = "stablehlo.while" in line
        if loop_stack and not is_while:
            loop_stack[-1] += net
            # only a closing line can end the region (the balance sits at 0
            # between the while header and its `cond {` opener)
            if net < 0:
                while loop_stack and loop_stack[-1] <= 0:
                    loop_stack.pop()
        if is_while:
            loop_stack.append(max(net, 0))

        m = _COLL_RE.search(line)
        if m and "custom_call" not in line:
            kind = m.group(1)
            groups = parse_groups(line) or ()
            tt = None
            om = _OPERAND_RE.search(line)
            if om:
                tt = parse_tensor_type(om.group(1))
            else:
                # region ops (all_reduce/reduce_scatter) print the operand
                # type on the region-closing line — bounded forward scan
                for j in range(i + 1, min(i + 60, len(lines))):
                    om = _OPERAND_RE.search(lines[j])
                    if om:
                        tt = parse_tensor_type(om.group(1))
                        break
                    if _COLL_RE.search(lines[j]):
                        break  # never steal another op's operand line
            shape, dtype, mb = tt if tt else ((), "f32", 0.0)
            if kind == "collective_permute":
                gsize = 2
            else:
                gsize = max((len(g) for g in groups), default=1)
            key = (kind, shape, dtype, groups, bool(loop_stack))
            ent = coll_sites.setdefault(
                key, {"kind": kind, "shape": shape, "dtype": dtype, "mb": mb,
                      "groups": groups, "gsize": gsize,
                      "in_loop": bool(loop_stack), "count": 0},
            )
            ent["count"] += 1
            continue

        if "@Sharding" in line:
            sm = _SHARDING_ATTR_RE.search(line)
            tt = parse_tensor_type(line.rsplit(":", 1)[-1])
            if sm and tt:
                shape, dtype, mb = tt
                constraint_sites.append(ShardingSite(
                    site="constraint", shape=shape, dtype=dtype, tensor_mb=mb,
                    sharding=parse_sharding_attr(sm.group(1)),
                ))
            continue

        if "%arg" in line and "mhlo.sharding" in line:
            for am in _ARG_RE.finditer(line):
                tt = parse_tensor_type(am.group(1))
                if tt is None:
                    continue
                shape, dtype, mb = tt
                key = (shape, dtype, am.group(2))
                ent = arg_sites.setdefault(
                    key, {"shape": shape, "dtype": dtype, "mb": mb,
                          "raw": am.group(2), "count": 0})
                ent["count"] += 1

    fp.collectives = [
        CollectiveSite(
            kind=e["kind"], shape=e["shape"], dtype=e["dtype"],
            tensor_mb=e["mb"], groups=e["groups"], group_size=e["gsize"],
            in_loop=e["in_loop"], count=e["count"],
        )
        for e in coll_sites.values()
    ]
    fp.shardings = constraint_sites + [
        ShardingSite(
            site="arg", shape=e["shape"], dtype=e["dtype"], tensor_mb=e["mb"],
            sharding=parse_sharding_attr(e["raw"]), count=e["count"],
        )
        for _, e in sorted(arg_sites.items(), key=lambda kv: repr(kv[0]))
    ]
    return fp


# ---------------------------------------------------------------------------
# Replica-group → mesh-axis attribution
# ---------------------------------------------------------------------------


def mesh_axis_groups(devices, axis_names: Sequence[str]):
    """For every non-empty subset of mesh axes, the device-id partition that
    varies exactly those axes: ``[(axes_subset, frozenset_of_groups), ...]``
    ordered smallest subset first, so attribution picks the tightest match.
    ``devices`` is the mesh's ndarray of device ids (or Devices, via
    ``.id``)."""
    import itertools

    import numpy as np

    arr = np.asarray(devices)
    ids = np.vectorize(lambda d: getattr(d, "id", d), otypes=[np.int64])(arr)
    n_ax = ids.ndim
    out = []
    for r in range(1, n_ax + 1):
        for subset in itertools.combinations(range(n_ax), r):
            rest = [a for a in range(n_ax) if a not in subset]
            perm = tuple(rest) + subset
            width = 1
            for a in subset:
                width *= ids.shape[a]
            moved = np.transpose(ids, perm).reshape(-1, width)
            groups = frozenset(frozenset(int(v) for v in row) for row in moved)
            out.append((tuple(axis_names[a] for a in subset), groups))
    return out


def attribute_collectives(
    fp: CommFootprint, devices, axis_names: Sequence[str],
) -> List[Diagnostic]:
    """Fill each CollectiveSite's ``axes`` from the mesh layout; GTC005 for
    replica groups that match no mesh-axis subgroup."""
    table = mesh_axis_groups(devices, axis_names)
    diags: List[Diagnostic] = []
    new = []
    for c in fp.collectives:
        axes: Tuple[str, ...] = ()
        if c.groups:
            if c.kind == "collective_permute":
                # a permute lists (src, tgt) pairs: attribute to the smallest
                # axis subset where every pair stays inside one subgroup
                pairs = [frozenset(p) for p in c.groups if len(p) == 2]
                for subset, groups in table:
                    if pairs and all(any(p <= g for g in groups) for p in pairs):
                        axes = subset
                        break
            else:
                want = frozenset(frozenset(g) for g in c.groups)
                for subset, groups in table:
                    if want == groups:
                        axes = subset
                        break
            if not axes:
                diags.append(Diagnostic(
                    "GTC005",
                    f"{fp.program}: {c.kind} over groups of size "
                    f"{c.group_size} matches no mesh-axis subgroup",
                    hint="the lowered grouping disagrees with the plan's "
                    "factored mesh — check tp_consec / axis assignment",
                    field=fp.program,
                ))
        new.append(CollectiveSite(
            kind=c.kind, shape=c.shape, dtype=c.dtype, tensor_mb=c.tensor_mb,
            groups=c.groups, group_size=c.group_size, axes=axes,
            in_loop=c.in_loop, count=c.count,
        ))
    fp.collectives = new
    return diags


def _plan_axis_roles(hp, world: int) -> Dict[Tuple[str, ...], str]:
    """Map each mesh-axis subset the plan's strategies legitimately
    communicate over → its role ('tp'/'cp'/'ep'/'dp'/'pp').  The complement
    of this map is what GTC003 flags as unsolicited."""
    from galvatron_tpu.parallel.mesh import MeshAxes

    pp = max(1, hp.pp)
    m = max(0, (world // pp).bit_length() - 1)
    axes = MeshAxes(pp="pp", data_axes=tuple(f"x{i}" for i in range(m)))
    roles: Dict[Tuple[str, ...], str] = {("pp",): "pp"}
    for s in hp.layer_strategies:
        try:
            if s.tp > 1:
                roles.setdefault(tuple(sorted(axes.tp_axes(s.tp, s.tp_consec))), "tp")
            if s.cp > 1:
                roles.setdefault(tuple(sorted(axes.cp_axes(s.tp, s.tp_consec, s.cp))), "cp")
            if s.ep > 1:
                roles.setdefault(tuple(sorted(axes.ep_axes(s.tp, s.tp_consec, s.ep))), "ep")
            dp = axes.dp_axes(s.tp, s.tp_consec, max(1, s.cp))
            if dp:
                roles.setdefault(tuple(sorted(dp)), "dp")
        except ValueError:
            continue  # plan checker (GTA004) owns degree/extent mismatches
    if hp.vocab_tp > 1:
        try:
            roles.setdefault(tuple(sorted(axes.tp_axes(hp.vocab_tp, True))), "tp")
            dp = axes.dp_axes(hp.vocab_tp, True, 1)
            if dp:
                roles.setdefault(tuple(sorted(dp)), "dp")
        except ValueError:
            pass
    if m:  # full data block: zero3 over all non-pp axes / fused grad sync
        roles.setdefault(tuple(sorted(axes.data_axes)), "dp")
    return roles


# ---------------------------------------------------------------------------
# Lower-only audit over the program registry
# ---------------------------------------------------------------------------


def lower_programs(
    cfg,
    hp,
    *,
    global_bsz: int,
    seq_len: Optional[int] = None,
    include: Optional[Sequence[str]] = None,
    adam: Any = None,
    verbose: bool = False,
) -> Tuple[List[CommFootprint], Any]:
    """AOT-lower every registered program for the plan (``lower()`` only —
    no compile, no execute, no data) and extract each footprint, with
    replica groups attributed against the runtime's own mesh.  Returns
    ``(footprints, mesh)``; a program that fails to lower degrades to a
    footprint carrying ``error`` (the fidelity gate turns it into GTC004)."""
    from galvatron_tpu.aot import registry as aot_registry
    from galvatron_tpu.parallel.hybrid import build_runtime

    kw: Dict[str, Any] = {"global_batch_size": global_bsz, "seq_len": seq_len}
    if adam is not None:
        kw["adam"] = adam
    rt = build_runtime(cfg, hp, **kw)
    ctx = aot_registry.ProgramContext(
        cfg=cfg, hp=hp, global_bsz=global_bsz, seq_len=seq_len,
        mesh=rt.mesh, axes=rt.axes, runtime=rt, adam=adam,
    )
    specs = aot_registry.enumerate_programs(
        ctx, include=include if include is not None else ("trainer",)
    )
    fps: List[CommFootprint] = []
    for spec in specs:
        t0 = time.perf_counter()
        try:
            lowered = spec.fn.lower(*spec.args, **spec.kwargs)
            fp = extract_footprint(lowered.as_text(), program=spec.name)
        except Exception as e:  # noqa: BLE001 — per-program isolation
            fp = CommFootprint(program=spec.name,
                               error=f"{type(e).__name__}: {str(e)[:300]}")
        fp.lower_ms = (time.perf_counter() - t0) * 1000.0
        if fp.error is None:
            fp.attribution_diags = attribute_collectives(  # type: ignore[attr-defined]
                fp, rt.mesh.devices, rt.mesh.axis_names)
        else:
            fp.attribution_diags = []  # type: ignore[attr-defined]
        if verbose:
            print(f"audit: {spec.name}: {fp.module_lines} lines, "
                  f"{len(fp.collectives)} collective site(s), "
                  f"{len(fp.shardings)} sharding site(s), "
                  f"lower {fp.lower_ms:.0f} ms"
                  + (f" — FAILED: {fp.error}" if fp.error else ""))
        fps.append(fp)
    return fps, rt.mesh


# ---------------------------------------------------------------------------
# Fidelity gate: predicted_over_lowered per plan term
# ---------------------------------------------------------------------------

# Independent first-principles constants for the AUDITED side.  Deliberately
# NOT imported from search/cost_model.py: the gate's whole point is that a
# drift in the cost model's constants moves only the predicted side.
_AUDIT_TP_BOUNDARY_COLLECTIVES = 4.0  # Megatron f/g: 2 fwd + 2 bwd
_AUDIT_REMAT_TP_REPLAY = 1.5  # full remat replays the 2 fwd collectives
_AUDIT_ZERO3_GATHER_PASSES = 2.0  # fwd + bwd param gather
_AUDIT_GRAD_FP32_FACTOR = 2.0  # fp32 grad reduce over bf16-priced wire


def _ar_wire(mb: float, n: int) -> float:
    return 0.0 if n <= 1 else 2.0 * (n - 1) / n * mb


def _ag_wire(mb: float, n: int) -> float:
    return 0.0 if n <= 1 else (n - 1) / n * mb


def _param_mb_by_scope(cfg) -> Tuple[Dict[int, float], float]:
    """Actual fp32 parameter MB from the model's abstract init tree:
    ``({layer_idx: MB}, other_MB)`` — the audited side's ground truth for
    parameter-proportional terms, independent of the cost model's analytic
    ``parameter_mb`` arithmetic."""
    import jax

    from galvatron_tpu.models import modeling

    tree = jax.eval_shape(
        lambda k: modeling.init_model_params(k, cfg), jax.random.key(0)
    )
    per_layer: Dict[int, float] = {}
    other = 0.0
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        keys = [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]
        mb = float(leaf.dtype.itemsize)
        for d in leaf.shape:
            mb *= d
        mb /= 1e6
        li = None
        for a, b in zip(keys, keys[1:]):
            if a in ("layers", "enc_layers", "blocks") and b.isdigit():
                li = int(b)
                break
        if li is None:
            other += mb
        else:
            per_layer[li] = per_layer.get(li, 0.0) + mb
    return per_layer, other


@dataclass
class FidelityRow:
    term: str
    predicted_mb: float
    lowered_mb: float
    basis: str  # 'collectives' | 'avals' | 'annotations' | 'none'
    tolerance: float

    @property
    def ratio(self) -> Optional[float]:
        if self.lowered_mb <= 0.0:
            return None
        return self.predicted_mb / self.lowered_mb

    @property
    def within(self) -> bool:
        r = self.ratio
        return r is not None and (1.0 / self.tolerance) <= r <= self.tolerance


def lowered_volume_breakdown(
    cfg, hp, world: int, global_bsz: int,
    footprints: Sequence[CommFootprint],
    seq_len: Optional[int] = None,
) -> Dict[str, Tuple[float, str]]:
    """The AUDITED side: per-term on-wire MB per device re-derived from the
    programs' actual abstract shapes and lowered collectives —
    ``{term: (mb, basis)}``.  Where a term's collectives are explicit in the
    lowered text (shard_map paths) the extracted sites ground it directly
    (basis ``collectives``); GSPMD-implied terms — invisible until the
    partitioner runs at compile time — are grounded in the actual parameter
    avals (basis ``avals``) or the boundary activation types the annotations
    carry (basis ``annotations``) instead."""
    f = 0.5 if hp.mixed_precision in ("bf16", "fp16") else 1.0
    per_layer, other_mb = _param_mb_by_scope(cfg)
    seq = seq_len or cfg.sample_len
    hidden = cfg.hidden_size
    act_bytes = 2.0 if f == 0.5 else 4.0
    pp = max(1, hp.pp)
    out: Dict[str, Tuple[float, str]] = {}

    def add(term: str, mb: float, basis: str) -> None:
        if mb <= 0.0:
            return
        prev = out.get(term)
        # explicit-collective grounding beats analytic re-derivation
        if prev is not None and prev[1] == "collectives" and basis != "collectives":
            return
        out[term] = ((prev[0] if prev and prev[1] == basis else 0.0) + mb, basis)

    # explicit-collective grounding: classify attributed sites by role axes
    roles = _plan_axis_roles(hp, world)
    tp_mb = cp_mb = ep_mb = pp_mb = 0.0
    train_fp = next((fp for fp in footprints if fp.program == "train_step"), None)
    if train_fp is not None and train_fp.error is None:
        for c in train_fp.collectives:
            if not c.axes:
                continue
            role = roles.get(tuple(sorted(c.axes)))
            if c.kind == "collective_permute" and "pp" in c.axes:
                # a permute inside a scan over micro-batches executes chunks
                # times per iteration; an unrolled/batched one executes once
                pp_mb += c.wire_mb * (max(1, hp.chunks) if c.in_loop else 1)
            elif role == "tp":
                tp_mb += c.wire_mb
            elif role == "cp":
                cp_mb += c.wire_mb
            elif role == "ep":
                ep_mb += c.wire_mb
    if tp_mb > 0.0:
        add("tp_boundary", tp_mb, "collectives")
    if cp_mb > 0.0:
        add("cp_ring", cp_mb, "collectives")
    if ep_mb > 0.0:
        add("ep_a2a", ep_mb, "collectives")
    if pp_mb > 0.0:
        add("pp_p2p", pp_mb, "collectives")

    # aval/annotation grounding for the GSPMD-implied terms
    for i, s in enumerate(hp.layer_strategies):
        dp = max(1, world // (pp * s.tp * max(1, s.cp)))
        dense_mb = per_layer.get(i, 0.0) / s.tp
        add("dp_grad", _ar_wire(dense_mb * f * _AUDIT_GRAD_FP32_FACTOR, dp), "avals")
        if s.dp_type == "zero3":
            add("zero3_gather",
                _AUDIT_ZERO3_GATHER_PASSES * _ag_wire(dense_mb * f, dp), "avals")
        if s.tp > 1:
            # boundary activation bytes from the model's actual (b, s, h)
            # global types — the same types the @Sharding annotations carry
            local_bsz = global_bsz / dp / max(1, s.cp)
            act_mb = local_bsz * seq * hidden * act_bytes / 1e6
            mb = _AUDIT_TP_BOUNDARY_COLLECTIVES * _ar_wire(act_mb, s.tp)
            if s.ckpt == "full":
                mb *= _AUDIT_REMAT_TP_REPLAY
            add("tp_boundary", mb, "annotations")

    # embedding / head under the vocab strategy
    vocab_tp = max(1, hp.vocab_tp)
    dp_o = max(1, world // (pp * vocab_tp))
    p_mb = other_mb / vocab_tp
    add("embed_dp", _ar_wire(p_mb * f * _AUDIT_GRAD_FP32_FACTOR, dp_o), "avals")
    if hp.embed_dp_type == "zero3":
        add("embed_dp", _AUDIT_ZERO3_GATHER_PASSES * _ag_wire(p_mb * f, dp_o), "avals")
    if vocab_tp > 1:
        act_mb = (global_bsz / dp_o) * seq * hidden * act_bytes / 1e6
        add("vocab_embed", 2.0 * _ar_wire(act_mb, vocab_tp), "annotations")
    return out


def fidelity_report(
    cfg, hp, world: int, global_bsz: int,
    footprints: Sequence[CommFootprint],
    *,
    seq_len: Optional[int] = None,
    tolerance: float = 3.0,
    source: Optional[str] = None,
) -> Tuple[List[FidelityRow], List[Diagnostic]]:
    """``predicted_over_lowered`` per plan term.  The predicted side replays
    the cost model's own volume constants (``comm_volume_breakdown``); the
    lowered side re-derives volumes from actual avals + extracted
    collectives.  Terms outside ``[1/tolerance, tolerance]`` → GTC001;
    predicted terms with zero grounding → GTC002; a failed lowering →
    GTC004 (which suppresses GTC002 — the failure already explains the
    missing grounding)."""
    from galvatron_tpu.search import cost_model
    from galvatron_tpu.search.theoretical import analytic_model_costs

    diags: List[Diagnostic] = []
    any_failed = False
    for fp in footprints:
        if fp.error is not None:
            any_failed = True
            diags.append(Diagnostic(
                "GTC004", f"{fp.program} failed to lower: {fp.error}",
                hint="fix the program (or exclude its family) before "
                "trusting the plan's comm profile", field=fp.program,
                source=source,
            ))
        diags.extend(getattr(fp, "attribution_diags", []))

    predicted = cost_model.comm_volume_breakdown(
        analytic_model_costs(cfg, seq_len=seq_len or 0), hp, world, global_bsz,
        mixed_precision=hp.mixed_precision,
    )
    lowered = lowered_volume_breakdown(
        cfg, hp, world, global_bsz, footprints, seq_len=seq_len
    )
    rows: List[FidelityRow] = []
    for term in sorted(set(predicted) | set(lowered)):
        p = predicted.get(term, 0.0)
        low, basis = lowered.get(term, (0.0, "none"))
        row = FidelityRow(term=term, predicted_mb=p, lowered_mb=low,
                          basis=basis, tolerance=tolerance)
        rows.append(row)
        if p > 0.0 and low <= 0.0:
            if not any_failed:
                diags.append(Diagnostic(
                    "GTC002",
                    f"plan term '{term}' predicts {p:.3f} MB/device but the "
                    "lowering grounds none of it",
                    hint="the engine may have elided the collective (or the "
                    "auditor cannot see this path) — verify before trusting "
                    "the term", field=term, source=source,
                ))
        elif not row.within and row.ratio is not None:
            diags.append(Diagnostic(
                "GTC001",
                f"term '{term}': predicted {p:.3f} MB vs lowered {low:.3f} MB "
                f"per device (ratio {row.ratio:.2f} outside "
                f"[{1.0 / tolerance:.2f}, {tolerance:.2f}], basis {basis})",
                hint="re-derive the cost-model constant for this term (or "
                "raise --tolerance with a comment saying why)",
                field=term, source=source,
            ))
    return rows, diags


def format_fidelity_table(rows: Sequence[FidelityRow]) -> str:
    if not rows:
        return "no comm terms (plan has no multi-device strategy dimension)"
    out = [f"{'term':<14} {'predicted_mb':>12} {'lowered_mb':>11} "
           f"{'pred/lowered':>12} {'basis':<12} status"]
    for r in rows:
        ratio = f"{r.ratio:.3f}" if r.ratio is not None else "—"
        status = ("ok" if r.within
                  else ("ungrounded" if r.ratio is None else "OUT-OF-BAND"))
        out.append(f"{r.term:<14} {r.predicted_mb:>12.3f} {r.lowered_mb:>11.3f} "
                   f"{ratio:>12} {r.basis:<12} {status}")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Resharding lint
# ---------------------------------------------------------------------------


def resharding_lint(
    hp,
    footprints: Sequence[CommFootprint],
    *,
    world: int = 0,
    source: Optional[str] = None,
) -> List[Diagnostic]:
    """Diagnose comm the plan never asked for:

    - GTC003: an attributed collective over a mesh-axis subset no plan term
      owns — exactly the partitioner-inserted resharding Alpa/GSPMD warn of;
    - GTC010: the plan shards params (zero2/3, tp) or activations
      (tp/sp/cp/vocab_tp) but the lowering left EVERY corresponding
      annotation fully replicated — GSPMD will silently replicate what the
      plan believes is sharded (GTA016 generalized to lowered reality);
    - GTC011: same-shaped boundary constraints carry more distinct shardings
      than the plan declares strategy seams — an undeclared redistribution;
    - GTC012: a tp_overlap layer's lowering has no decomposed ring
      (collective_permute) yet keeps monolithic tp-group collectives — the
      collective-matmul did not fire and its pricing discount is unearned.
    """
    diags: List[Diagnostic] = []
    train_fp = next((fp for fp in footprints if fp.program == "train_step"), None)
    if train_fp is None or train_fp.error is not None:
        return diags

    if world:
        roles = _plan_axis_roles(hp, world)
        stray: Dict[Tuple[str, Tuple[str, ...]], int] = {}
        for c in train_fp.collectives:
            key = tuple(sorted(c.axes))
            if c.axes and key not in roles and not (
                c.kind == "collective_permute" and "pp" in c.axes
            ):
                stray[(c.kind, c.axes)] = stray.get((c.kind, c.axes), 0) + c.count
        for (kind, axes), n in sorted(stray.items()):
            diags.append(Diagnostic(
                "GTC003",
                f"{n} lowered {kind} site(s) over mesh axes {list(axes)} "
                "that no plan term communicates over",
                hint="the partitioner inserted resharding the cost model "
                "never priced — check the layer-boundary sharding specs",
                field="train_step", source=source,
            ))

    wants_param_shard = any(
        s.dp_type in ("zero2", "zero3") or s.tp > 1 for s in hp.layer_strategies
    )
    wants_act_shard = any(
        s.tp > 1 or s.sp or s.cp > 1 for s in hp.layer_strategies
    ) or hp.vocab_tp > 1
    args = [s for s in train_fp.shardings if s.site == "arg"]
    constraints = [s for s in train_fp.shardings if s.site == "constraint"]
    if wants_param_shard and args and not any(s.sharding.sharded for s in args):
        diags.append(Diagnostic(
            "GTC010",
            "plan shards parameters (zero3/tp) but every lowered entry "
            "argument is fully replicated",
            hint="param_spec/model annotations did not reach the jit "
            "in_shardings — each device will hold (and all-gather) full "
            "copies", field="train_step", source=source,
        ))
    if wants_act_shard and constraints and not any(
        s.sharding.sharded for s in constraints
    ):
        diags.append(Diagnostic(
            "GTC010",
            "plan shards activations (tp/sp/cp/vocab_tp) but every lowered "
            "boundary constraint is fully replicated",
            hint="the layer-boundary with_sharding_constraint hook lost its "
            "specs — GSPMD will replicate the boundary and insert gathers",
            field="train_step", source=source,
        ))

    # undeclared seams: distinct shardings per same-shape constraint class.
    # Boundary activations are rank-3 (b, s, h); params of one shape can
    # legitimately shard differently (e.g. wq vs wo), so gate on rank 3.
    declared_seams = sum(
        1 for a, b in zip(hp.layer_strategies, hp.layer_strategies[1:])
        if (a.tp, a.tp_consec, a.sp, a.cp) != (b.tp, b.tp_consec, b.sp, b.cp)
    )
    by_shape: Dict[Tuple, set] = {}
    for s in constraints:
        if len(s.shape) == 3:
            by_shape.setdefault((s.shape, s.dtype), set()).add(s.sharding.raw)
    for (shape, dtype), shardings in sorted(by_shape.items()):
        if len(shardings) > declared_seams + 1:
            diags.append(Diagnostic(
                "GTC011",
                f"boundary tensor {dtype}{list(shape)} lowers under "
                f"{len(shardings)} distinct shardings but the plan declares "
                f"only {declared_seams} strategy seam(s)",
                hint="an undeclared redistribution: every extra sharding is "
                "a resharding collective the cost model never priced",
                field="train_step", source=source,
            ))

    overlap_layers = [i for i, s in enumerate(hp.layer_strategies)
                      if s.tp_overlap and s.tp > 1]
    if overlap_layers:
        has_ring = any(
            c.kind == "collective_permute" and "pp" not in c.axes
            for c in train_fp.collectives
        )
        overlap_tp = {s.tp for s in hp.layer_strategies if s.tp_overlap}
        monolith = [
            c for c in train_fp.collectives
            if c.kind in ("all_gather", "all_reduce")
            and c.group_size in overlap_tp
        ]
        if not has_ring and monolith:
            diags.append(Diagnostic(
                "GTC012",
                f"{len(overlap_layers)} tp_overlap layer(s) lower no "
                "collective_permute ring yet keep "
                f"{sum(c.count for c in monolith)} monolithic tp-group "
                "collective site(s)",
                hint="ops/collective_matmul did not fire (shape/dtype gate?) "
                "— the plan's TP_OVERLAP_RESIDUAL pricing is unearned",
                field=f"tp_overlap_flags[{overlap_layers[0]}]", source=source,
            ))
    return diags


# ---------------------------------------------------------------------------
# High-level driver + JSONL artifact
# ---------------------------------------------------------------------------


@dataclass
class AuditResult:
    footprints: List[CommFootprint]
    rows: List[FidelityRow]
    diagnostics: List[Diagnostic]


def audit_plan(
    cfg,
    hp,
    *,
    world: int,
    global_bsz: int,
    seq_len: Optional[int] = None,
    include: Optional[Sequence[str]] = None,
    tolerance: float = 3.0,
    adam: Any = None,
    source: Optional[str] = None,
    verbose: bool = False,
) -> AuditResult:
    """Lower-only audit of one (plan × model × mesh): footprints + fidelity
    rows + GTC diagnostics.  Needs ``jax.device_count() == world`` (use
    ``aot.warmup.force_cpu_world`` first — host devices, no hardware)."""
    fps, _mesh = lower_programs(
        cfg, hp, global_bsz=global_bsz, seq_len=seq_len, include=include,
        adam=adam, verbose=verbose,
    )
    rows, diags = fidelity_report(
        cfg, hp, world, global_bsz, fps, seq_len=seq_len,
        tolerance=tolerance, source=source,
    )
    diags.extend(resharding_lint(hp, fps, world=world, source=source))
    return AuditResult(footprints=fps, rows=rows, diagnostics=diags)


def write_footprint_jsonl(path: str, footprints: Sequence[CommFootprint],
                          extra: Optional[Dict[str, Any]] = None) -> None:
    """One record per program (+ an optional trailing context record) — the
    artifact ``cli warmup --report`` writes next to ``memory_analysis`` and
    the CI audit job uploads."""
    with open(path, "w") as f:
        for fp in footprints:
            f.write(json.dumps(fp.to_json()) + "\n")
        if extra:
            f.write(json.dumps(extra) + "\n")
