"""Runtime lock-order / contention validator for the host-side control plane.

The static linter (`analysis/concurrency.py`, GTL2xx) proves lock discipline
for the acquisition orders it can SEE; this module validates the orders that
actually happen. Armed by ``GALVATRON_LOCK_CHECK=1`` (same pattern as
``GALVATRON_RECOMPILE_GUARD``): off, the factories return plain
``threading`` primitives — zero overhead, zero behavior change. On, every
lock is wrapped in an instrumented shim that

- keeps a **thread-local held stack** and records every (outer → inner)
  acquisition edge into a process-global order graph;
- raises :class:`LockOrderError` the moment a reverse edge appears — with
  BOTH stacks (where the forward edge was recorded, and where the inversion
  is being attempted), so the report reads like the deadlock that would
  eventually happen instead of a probabilistic hang;
- counts **contention** (acquire had to wait) and accumulates **hold time**
  per lock name, exported through :func:`lock_metrics` into ``/metrics`` as
  ``galvatron_lock_hold_ms`` / ``galvatron_lock_contended_total``;
- exposes :func:`held_snapshot` — {thread name: [lock names]} — which the
  flight recorder folds into hang/crash dumps, so "which thread holds what"
  is in the artifact instead of being reconstructed from a core.

Use the factories, not the classes::

    from galvatron_tpu.analysis.locks import make_lock, make_rlock, make_condition
    self._lock = make_lock("scheduler.q")

Lock NAMES are the unit of ordering: two instances created under the same
name are the same node in the order graph (a fleet of per-replica locks
named "replica.state" must be consistently ordered against "fleet.gate"
regardless of which replica instance is involved). Per-instance cycles on a
shared name are therefore reported conservatively — that is the point: a
discipline that depends on WHICH instance you hold is already broken.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

LOCK_CHECK_ENV = "GALVATRON_LOCK_CHECK"


def lock_check_armed() -> bool:
    """True when ``GALVATRON_LOCK_CHECK`` is set to anything but ''/'0'."""
    return os.environ.get(LOCK_CHECK_ENV, "0") not in ("", "0")


class LockOrderError(RuntimeError):
    """An acquisition edge that reverses a previously recorded edge.

    Carries both ends of the would-be deadlock: ``forward_stack`` is where
    (outer → inner) was first recorded, ``reverse_stack`` is the acquisition
    being attempted now (inner held, outer wanted)."""

    def __init__(self, msg: str, forward_stack: str = "", reverse_stack: str = ""):
        super().__init__(msg)
        self.forward_stack = forward_stack
        self.reverse_stack = reverse_stack


# --- process-global registries (armed mode only) -----------------------------

_tls = threading.local()

# (outer name, inner name) → stack text where the edge was first recorded.
# Guarded by _registry_lock; this meta-lock nests inside user locks only
# for bounded dict ops, so it cannot itself deadlock with instrumented locks.
_order_edges: Dict[Tuple[str, str], str] = {}
_registry_lock = threading.Lock()

# name → [hold_ms_total, contended_total, acquisitions_total]
_stats: Dict[str, List[float]] = {}


def _held_stack() -> List["_InstrumentedBase"]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = []
        _tls.held = stack
    return stack


def _record_edges(inner: "_InstrumentedBase") -> None:
    """Record (outer → inner) for every lock currently held; raise on a
    previously seen reverse edge."""
    here = "".join(traceback.format_stack(limit=16)[:-2])
    for outer in _held_stack():
        if outer.name == inner.name:
            continue  # reentrant same-name nesting orders nothing
        with _registry_lock:
            fwd = _order_edges.get((inner.name, outer.name))
            if fwd is not None:
                raise LockOrderError(
                    f"lock-order inversion: acquiring {inner.name!r} while "
                    f"holding {outer.name!r}, but {outer.name!r} was "
                    f"previously acquired while holding {inner.name!r}",
                    forward_stack=fwd,
                    reverse_stack=here,
                )
            _order_edges.setdefault((outer.name, inner.name), here)


def _bump(name: str, hold_ms: float = 0.0, contended: int = 0,
          acquired: int = 0) -> None:
    with _registry_lock:
        row = _stats.setdefault(name, [0.0, 0, 0])
        row[0] += hold_ms
        row[1] += contended
        row[2] += acquired


def reset_registry() -> None:
    """Drop recorded edges and counters (tests: isolate one scenario's order
    graph from the next)."""
    with _registry_lock:
        _order_edges.clear()
        _stats.clear()


def lock_metrics() -> Dict[str, Dict[str, float]]:
    """{name: {hold_ms, contended_total, acquired_total}} — the /metrics
    families. Empty when nothing has been acquired (or check is off)."""
    with _registry_lock:
        return {
            name: {"hold_ms": row[0], "contended_total": row[1],
                   "acquired_total": row[2]}
            for name, row in sorted(_stats.items())
        }


def order_edges() -> Dict[Tuple[str, str], str]:
    """Snapshot of the recorded acquisition-order graph (tests/debugging)."""
    with _registry_lock:
        return dict(_order_edges)


def held_snapshot() -> Dict[str, List[str]]:
    """{thread name: [lock names held, outermost first]} across all threads.

    Snapshotted from each instrumented lock's owner bookkeeping — safe to
    call from the watchdog thread while other threads are blocked."""
    with _registry_lock:
        out: Dict[str, List[str]] = {}
        for (name, tname) in _live_holds:
            out.setdefault(tname, []).append(name)
        return out


# (lock name, thread name) entries for currently-held locks, in acquisition
# order per thread (list, not set: RLock re-entry appears once)
_live_holds: List[Tuple[str, str]] = []


class _InstrumentedBase:
    """Shared acquire/release instrumentation over a wrapped primitive."""

    def __init__(self, name: str, inner):
        self.name = name
        self._inner = inner
        self._acquired_at = 0.0
        self._depth = 0  # >0 only while held by some thread (RLock: nesting)

    # -- context manager ----------------------------------------------------

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # -- core protocol -------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        reentry = self._depth > 0 and self in _held_stack()
        if not reentry:
            _record_edges(self)
        # contention = the uncontended fast path failed and we had to wait
        got = self._inner.acquire(False)
        contended = 0
        if not got:
            contended = 1
            if not blocking:
                _bump(self.name, contended=1)
                return False
            if timeout is None or timeout < 0:
                got = self._inner.acquire(True)
            else:
                got = self._inner.acquire(True, timeout)
            if not got:
                _bump(self.name, contended=1)
                return False
        self._depth += 1
        if self._depth == 1:
            self._acquired_at = time.monotonic()
            with _registry_lock:
                _live_holds.append((self.name, threading.current_thread().name))
        _held_stack().append(self)
        _bump(self.name, contended=contended, acquired=1)
        return True

    def release(self) -> None:
        stack = _held_stack()
        if self in stack:
            # remove the innermost entry of THIS lock (out-of-order releases
            # are legal threading; the stack is for edge recording only)
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] is self:
                    del stack[i]
                    break
        self._depth -= 1
        if self._depth == 0:
            hold_ms = (time.monotonic() - self._acquired_at) * 1e3
            _bump(self.name, hold_ms=hold_ms)
            tname = threading.current_thread().name
            with _registry_lock:
                for i in range(len(_live_holds) - 1, -1, -1):
                    if _live_holds[i] == (self.name, tname):
                        del _live_holds[i]
                        break
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()


class InstrumentedLock(_InstrumentedBase):
    def __init__(self, name: str):
        super().__init__(name, threading.Lock())


class InstrumentedRLock(_InstrumentedBase):
    def __init__(self, name: str):
        super().__init__(name, threading.RLock())

    def locked(self) -> bool:  # RLock has no .locked() before 3.12
        return self._depth > 0


class InstrumentedCondition(_InstrumentedBase):
    """Condition over an instrumented lock: wait/notify keep the held-stack
    honest (wait releases the lock, so its entry leaves the stack for the
    duration — a watchdog snapshot during a wait must not claim the lock is
    held)."""

    def __init__(self, name: str):
        lock = threading.Lock()
        super().__init__(name, lock)
        self._cond = threading.Condition(lock)

    def wait(self, timeout: Optional[float] = None) -> bool:
        self._pause_hold()
        try:
            return self._cond.wait(timeout)  # gta: disable=GTL205 — pass-through wrapper; the predicate loop is the call site's contract
        finally:
            self._resume_hold()

    def wait_for(self, predicate, timeout: Optional[float] = None) -> bool:
        self._pause_hold()
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            self._resume_hold()

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()

    def _pause_hold(self) -> None:
        stack = _held_stack()
        if self in stack:
            stack.remove(self)
        self._depth -= 1
        hold_ms = (time.monotonic() - self._acquired_at) * 1e3
        _bump(self.name, hold_ms=hold_ms)
        tname = threading.current_thread().name
        with _registry_lock:
            for i in range(len(_live_holds) - 1, -1, -1):
                if _live_holds[i] == (self.name, tname):
                    del _live_holds[i]
                    break

    def _resume_hold(self) -> None:
        self._depth += 1
        self._acquired_at = time.monotonic()
        _held_stack().append(self)
        with _registry_lock:
            _live_holds.append((self.name, threading.current_thread().name))


# --- factories (the public API) ----------------------------------------------


def make_lock(name: str):
    """A named mutex: plain ``threading.Lock`` normally, instrumented under
    ``GALVATRON_LOCK_CHECK=1``."""
    if lock_check_armed():
        return InstrumentedLock(name)
    return threading.Lock()


def make_rlock(name: str):
    if lock_check_armed():
        return InstrumentedRLock(name)
    return threading.RLock()


def make_condition(name: str):
    if lock_check_armed():
        return InstrumentedCondition(name)
    return threading.Condition()
