"""Static analysis for plans and trace hygiene.

Two pillars (neither compiles anything):

- **Plan checker** (``plan_check``): validates a hybrid-parallelism plan
  (strategy JSON × ModelConfig × mesh topology) in milliseconds, emitting
  stable ``GTA…`` diagnostics instead of the cryptic compiler abort or
  silent memory blowout the runtime would otherwise produce minutes into
  startup. Trainer startup and the search engine's emit path both run it
  (fail-fast / self-check); ``python -m galvatron_tpu.cli check-plan``
  exposes it for CI and checked-in configs.
- **Trace-hygiene linter** (``lint``): AST rules for JAX footguns — host
  syncs in hot loops, Python RNG under trace, mutation of a numpy buffer
  after async dispatch (the exact serving-engine corruption bug class),
  recompilation hazards. ``python -m galvatron_tpu.analysis.lint <paths>``.

- **Concurrency linter** (``concurrency``): lock discipline for the
  host-side control plane — ``# guarded-by:`` annotations checked against
  lock regions, static lock-order cycles, blocking calls under locks,
  ``Condition.wait`` predicate loops, thread leaks (``GTL2…`` codes).
  ``python -m galvatron_tpu.analysis.concurrency <paths>``. Its runtime
  twin (``locks``) swaps ``make_lock``/``make_rlock``/``make_condition``
  to instrumented primitives under ``GALVATRON_LOCK_CHECK=1``: actual
  acquisition-order validation (``LockOrderError`` with both stacks),
  per-lock hold/contention counters for /metrics, held-lock snapshots for
  the flight recorder and watchdog.

- **Collective auditor** (``comm_audit``): AOT-lowers every registered
  program for a plan (eval_shape inputs — no devices, no compile, no
  execute) and walks the StableHLO text: which collectives, over which
  mesh axes, moving how many wire-MB. Three products, all ``GTC…``
  codes: a plan-vs-lowered fidelity gate (each ``comm_volume_breakdown``
  term vs what XLA actually materialized), a resharding lint (stray
  axes, silent replication, undeclared seams, dead tp_overlap), and the
  comm-footprint JSONL that ``cli warmup --report`` writes beside the
  memory report. ``python -m galvatron_tpu.cli audit-comm <plan.json>``.

Plus ``recompile_guard`` (``guards``): a context manager generalizing the
``generate._cache_size()`` test pins so tests and the serving engine can
assert bounded jit-cache growth.
"""

from galvatron_tpu.analysis.comm_audit import (
    CollectiveSite,
    CommFootprint,
    audit_plan,
    extract_footprint,
)
from galvatron_tpu.analysis.diagnostics import Diagnostic, format_report
from galvatron_tpu.analysis.guards import RecompileError, recompile_guard
from galvatron_tpu.analysis.locks import (
    LockOrderError,
    held_snapshot,
    lock_check_armed,
    lock_metrics,
    make_condition,
    make_lock,
    make_rlock,
)
from galvatron_tpu.analysis.plan_check import PlanError, check_plan

__all__ = [
    "CollectiveSite",
    "CommFootprint",
    "Diagnostic",
    "LockOrderError",
    "PlanError",
    "RecompileError",
    "audit_plan",
    "check_plan",
    "extract_footprint",
    "format_report",
    "held_snapshot",
    "lock_check_armed",
    "lock_metrics",
    "make_condition",
    "make_lock",
    "make_rlock",
    "recompile_guard",
]
