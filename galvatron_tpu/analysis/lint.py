"""Trace-hygiene linter: AST rules for JAX footguns, run in CI.

``python -m galvatron_tpu.analysis.lint galvatron_tpu/`` — exit 0 when clean
(suppressed-only findings are clean), 1 on any unsuppressed finding, 2 on a
usage error (no paths, or paths matching no .py files).
Rules (codes in diagnostics.CODES):

  GTL101  host-device sync (``float()``/``int()``/``.item()``/``np.asarray``/
          ``.tolist()``/``jax.device_get``/``.block_until_ready()``) on a
          value produced by a jitted call inside a ``for``/``while`` loop —
          each one serializes dispatch with device compute; hot loops should
          sync once per window, not per iteration.
  GTL102  Python/``np.random`` RNG inside a jit-traced function — the value
          is baked at trace time, silently constant across calls.
  GTL103  a numpy buffer mutated after being handed to async dispatch
          (``jnp.asarray``/``jax.device_put``/a jitted call): on CPU the
          device array may alias the host buffer, so the mutation corrupts
          the in-flight computation (the serving-engine prefill bug class).
          Loop bodies are scanned twice so mutation-next-iteration is caught;
          rebinding the name (fresh buffer) clears the hazard.
  GTL104  Python ``if``/``while`` on a traced (non-static) parameter of a
          jitted function — TracerBoolConversionError at best, a per-value
          recompile at worst. ``.shape``/``.ndim``/``.dtype``/``.size``
          accesses are static and exempt.
  GTL105  ``jax.jit(...)`` constructed inside a loop — a fresh cache per
          iteration, so every call recompiles.
  GTL106  a list/dict/set literal passed as a static argument of a known
          jitted function — unhashable, fails (or defeats) the jit cache.

Suppression: the finding's line must carry ``# gta: disable=<CODE>`` WITH a
reason after the code(s), e.g. ``# gta: disable=GTL101 — gated by sync_each``.
A reasonless suppression is itself a finding (GTL100).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from galvatron_tpu.analysis._lintcore import (
    BaseLinter,
    SUPPRESS_RE as _SUPPRESS_RE,  # re-exported: tests pin the contract here
    cli_main,
    dotted as _dotted,
    lint_paths_with,
)
from galvatron_tpu.analysis.diagnostics import Diagnostic

# host-sync call forms: bare builtins over a device value, np conversions,
# and method calls on the value itself
_SYNC_BUILTINS = {"float", "int", "bool"}
_SYNC_NP_FUNCS = {"asarray", "array"}
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval"}
# attribute calls treated as jit producers even without a module-level
# definition (the runtime's step entry points)
_PRODUCER_ATTRS = {"train_step", "eval_step"}
# calls that hand a host buffer to async dispatch
_DISPATCH_CHAINS = {
    ("jnp", "asarray"),
    ("jnp", "array"),
    ("jax", "device_put"),
    ("jax", "numpy", "asarray"),
    ("jax", "numpy", "array"),
}

def _is_jax_jit(node: ast.AST) -> bool:
    d = _dotted(node)
    return d in (("jax", "jit"), ("jit",))


def _jit_decoration(dec: ast.AST) -> Optional[Set[str]]:
    """If ``dec`` marks a function as jitted, return its static argnames."""
    if _is_jax_jit(dec):
        return set()
    if isinstance(dec, ast.Call):
        if _is_jax_jit(dec.func):
            return _static_names(dec.keywords)
        d = _dotted(dec.func)
        if d and d[-1] == "partial" and dec.args and _is_jax_jit(dec.args[0]):
            return _static_names(dec.keywords)
    return None


def _static_names(keywords) -> Set[str]:
    for kw in keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            vals = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            return {
                e.value for e in vals
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            }
    return set()


def _assigned_names(target: ast.AST) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out = []
        for e in target.elts:
            out.extend(_assigned_names(e))
        return out
    return []


class _ModuleIndex(ast.NodeVisitor):
    """Module-level jit landscape: which names are jitted callables, and
    their static argnames (for GTL101 producers and GTL106 call sites)."""

    def __init__(self):
        self.jitted: Dict[str, Set[str]] = {}

    def visit_FunctionDef(self, node: ast.FunctionDef):
        for dec in node.decorator_list:
            statics = _jit_decoration(dec)
            if statics is not None:
                self.jitted[node.name] = statics
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node: ast.Assign):
        v = node.value
        statics: Optional[Set[str]] = None
        if isinstance(v, ast.Call) and _is_jax_jit(v.func):
            statics = _static_names(v.keywords)
        elif isinstance(v, ast.Call):
            d = _dotted(v.func)
            if d and d[-1] == "partial" and v.args and _is_jax_jit(v.args[0]):
                statics = _static_names(v.keywords)
        if statics is not None:
            for name in _assigned_names(node.targets[0] if len(node.targets) == 1 else ast.Tuple(elts=node.targets)):
                self.jitted[name] = statics
        self.generic_visit(node)


class Linter(BaseLinter):
    def run(self) -> List[Diagnostic]:
        tree = self.parse()
        if tree is None:
            return []
        idx = _ModuleIndex()
        idx.visit(tree)
        self.jitted = idx.jitted
        self.findings.extend(self.sup.malformed)
        # module body too: the aliasing bug class (GTL103) is just as fatal
        # in script-style top-level code as inside a def
        self._check_buffer_mutation(tree)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                statics = None
                for dec in node.decorator_list:
                    s = _jit_decoration(dec)
                    if s is not None:
                        statics = s
                if statics is not None:
                    self._check_traced_body(node, statics)
                self._check_buffer_mutation(node)
            if isinstance(node, (ast.For, ast.While)):
                self._check_loop(node)
            if isinstance(node, ast.Call):
                self._check_static_literal(node)
        # nested loops are visited by the outer loop's walk too — dedup
        return self.finalize()

    # -- GTL102 / GTL104: inside jit-traced functions ----------------------

    def _check_traced_body(self, fn, statics: Set[str]):
        args = fn.args
        all_params = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
        traced = {p for p in all_params if p not in statics and p != "self"}
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d and (
                    (d[0] == "random" and len(d) == 2)
                    or (d[0] in ("np", "numpy") and len(d) >= 3 and d[1] == "random")
                ):
                    self._emit(
                        "GTL102", node.lineno,
                        f"{'.'.join(d)} inside jitted {fn.name!r}: the value is "
                        "baked at trace time (constant across calls)",
                        hint="thread a jax.random key through the function instead",
                    )
            if isinstance(node, (ast.If, ast.While)):
                bad = self._traced_names_in_test(node.test, traced)
                for name, line in bad:
                    self._emit(
                        "GTL104", line,
                        f"Python branch on traced parameter {name!r} inside "
                        f"jitted {fn.name!r}",
                        hint="use jnp.where/lax.cond, or declare it in "
                        "static_argnames if it is genuinely static",
                    )

    def _traced_names_in_test(self, test: ast.AST, traced: Set[str]):
        parents = {}
        for parent in ast.walk(test):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        out = []
        for node in ast.walk(test):
            if isinstance(node, ast.Name) and node.id in traced:
                p = parents.get(node)
                if isinstance(p, ast.Attribute) and p.attr in _STATIC_ATTRS:
                    continue
                # `x is None` / `x is not None` sentinel checks are host-side
                if isinstance(p, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in p.ops
                ):
                    continue
                out.append((node.id, node.lineno))
        return out

    # -- GTL101 / GTL105: hot loops ----------------------------------------

    def _check_loop(self, loop):
        device_names: Set[str] = set()
        for node in ast.walk(loop):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if self._is_jit_producer(node.value):
                    for t in node.targets:
                        device_names.update(_assigned_names(t))
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d and (d in (("jax", "jit"), ("jit",)) or (
                d[-1] == "partial" and node.args and _is_jax_jit(node.args[0])
            )):
                self._emit(
                    "GTL105", node.lineno,
                    "jax.jit constructed inside a loop: a fresh cache per "
                    "iteration means every call recompiles",
                    hint="hoist the jit (or the partial) out of the loop",
                )
            target = self._sync_target(node)
            if target and target in device_names:
                self._emit(
                    "GTL101", node.lineno,
                    f"host sync on jitted result {target!r} inside a hot "
                    "loop: serializes dispatch with device compute",
                    hint="sync once per window (or gate it), not per iteration",
                )

    def _is_jit_producer(self, call: ast.Call) -> bool:
        if isinstance(call.func, ast.Name):
            return call.func.id in self.jitted
        d = _dotted(call.func)
        return bool(d) and d[-1] in (_PRODUCER_ATTRS | set(self.jitted))

    def _sync_target(self, call: ast.Call) -> Optional[str]:
        """The name being host-synced by this call, if any."""
        def root_name(node):
            if isinstance(node, ast.Subscript):
                node = node.value
            return node.id if isinstance(node, ast.Name) else None

        if isinstance(call.func, ast.Name) and call.func.id in _SYNC_BUILTINS:
            return root_name(call.args[0]) if call.args else None
        d = _dotted(call.func)
        if d and len(d) == 2 and d[0] in ("np", "numpy") and d[1] in _SYNC_NP_FUNCS:
            return root_name(call.args[0]) if call.args else None
        if d and d in (("jax", "device_get"),):
            return root_name(call.args[0]) if call.args else None
        if isinstance(call.func, ast.Attribute) and call.func.attr in _SYNC_METHODS:
            return root_name(call.func.value)
        return None

    # -- GTL103: buffer mutation after dispatch ----------------------------

    def _check_buffer_mutation(self, fn):
        dispatched: Dict[str, int] = {}  # name → line of the dispatch

        def names_in(node) -> Set[str]:
            return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}

        def scan_dispatch(expr):
            """Record names handed to async dispatch anywhere in ``expr``."""
            for node in ast.walk(expr):
                if not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func)
                is_dispatch = (
                    (d is not None and d in _DISPATCH_CHAINS)
                    or (isinstance(node.func, ast.Name) and node.func.id in self.jitted)
                    or (isinstance(node.func, ast.Attribute)
                        and node.func.attr in (_PRODUCER_ATTRS | set(self.jitted)))
                )
                if is_dispatch:
                    for arg in list(node.args) + [k.value for k in node.keywords]:
                        for name in names_in(arg):
                            dispatched.setdefault(name, node.lineno)

        def mutation(name: str, line: int, how: str):
            self._emit(
                "GTL103", line,
                f"{name!r} {how} after being handed to async dispatch at "
                f"line {dispatched[name]}: the device array may alias this "
                "host buffer and the in-flight computation reads garbage",
                hint="allocate a fresh buffer per dispatch instead of "
                "reusing and mutating this one",
            )

        def handle_simple(stmt):
            scan_dispatch(stmt)
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Subscript) and isinstance(t.value, ast.Name):
                        if t.value.id in dispatched:
                            mutation(t.value.id, stmt.lineno, "mutated in place")
                    for name in _assigned_names(t):
                        dispatched.pop(name, None)  # fresh binding clears it
            elif isinstance(stmt, ast.AugAssign):
                t = stmt.target
                name = (
                    t.id if isinstance(t, ast.Name)
                    else t.value.id
                    if isinstance(t, ast.Subscript) and isinstance(t.value, ast.Name)
                    else None
                )
                if name and name in dispatched:
                    mutation(name, stmt.lineno, "mutated (augmented assign)")
            elif isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
                f = stmt.value.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr in ("fill", "sort", "put", "resize", "partition")
                    and isinstance(f.value, ast.Name)
                    and f.value.id in dispatched
                ):
                    mutation(f.value.id, stmt.lineno, f"mutated via .{f.attr}()")

        def process_block(stmts, passes: int = 1):
            for _ in range(passes):
                for stmt in stmts:
                    if isinstance(stmt, (ast.For, ast.AsyncFor)):
                        scan_dispatch(stmt.iter)
                        # two passes over the body: a dispatch late in
                        # iteration k and a mutation early in k+1 is the
                        # classic reuse bug — state survives the back edge,
                        # a fresh binding at the top clears it
                        process_block(stmt.body, passes=2)
                        process_block(stmt.orelse)
                    elif isinstance(stmt, ast.While):
                        scan_dispatch(stmt.test)
                        process_block(stmt.body, passes=2)
                        process_block(stmt.orelse)
                    elif isinstance(stmt, ast.If):
                        scan_dispatch(stmt.test)
                        process_block(stmt.body)
                        process_block(stmt.orelse)
                    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                        for item in stmt.items:
                            scan_dispatch(item.context_expr)
                        process_block(stmt.body)
                    elif isinstance(stmt, ast.Try):
                        process_block(stmt.body)
                        for h in stmt.handlers:
                            process_block(h.body)
                        process_block(stmt.orelse)
                        process_block(stmt.finalbody)
                    elif isinstance(
                        stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                    ):
                        pass  # nested defs get their own pass (own state)
                    else:
                        handle_simple(stmt)

        process_block(fn.body)

    # -- GTL106: unhashable static args ------------------------------------

    def _check_static_literal(self, call: ast.Call):
        if not isinstance(call.func, ast.Name):
            return
        statics = getattr(self, "jitted", {}).get(call.func.id)
        if not statics:
            return
        for kw in call.keywords:
            if kw.arg in statics and isinstance(
                kw.value,
                (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp),
            ):
                self._emit(
                    "GTL106", kw.value.lineno,
                    f"static argument {kw.arg!r} of jitted "
                    f"{call.func.id!r} is an unhashable literal",
                    hint="pass a tuple (or another hashable) for static args",
                )


def lint_source(src: str, path: str = "<string>") -> Tuple[List[Diagnostic], int]:
    linter = Linter(src, path)
    findings = linter.run()
    return findings, linter.suppressed


def lint_paths(paths: Sequence[str]) -> Tuple[List[Diagnostic], int]:
    return lint_paths_with(lint_source, paths)


def main(argv: Optional[Sequence[str]] = None) -> int:
    return cli_main(lint_source, __doc__, argv)


if __name__ == "__main__":
    raise SystemExit(main())
