"""Lock-discipline linter: AST rules for the threaded host control plane.

``python -m galvatron_tpu.analysis.concurrency galvatron_tpu/`` — exit 0
when clean (suppressed-only findings are clean), 1 on any unsuppressed
finding, 2 on a usage error (no paths, or paths matching no .py files).
The serving engine, fleet router, paged-KV
allocator, peer store and watchdogs are classic multithreaded Python; every
bug class a chaos harness has caught here is encoded as a static rule, in
the spirit of ``@GuardedBy``/Clang Thread Safety Analysis (guarded fields)
and lockdep (acquisition-order graphs).

Annotation grammar (DESIGN.md § Static analysis has the full table):

  self._q = deque()          # guarded-by: self._lock
      declares ``_q`` guarded by ``_lock`` (on the assignment line);
  _GUARDED_BY = {"_q": "_lock"}
      the class-map equivalent (one dict, many fields);
  def _drop(self):  # holds: self._lock
      an assert-hold helper: its body is analyzed as holding the lock, and
      calling it at a site that does NOT hold the lock is a finding.

Rules (codes in diagnostics.CODES; ``RULES`` maps code → summary):

  GTL200  a guarded-by/holds declaration names a lock attribute the class
          never creates — the annotation would silently check nothing.
  GTL201  a guarded field read or written outside its declared lock
          (``__init__`` is exempt: the object is not yet shared).
  GTL202  lock-order inversion: the static acquisition-order graph (per
          class, plus cross-class edges through ``self.<attr>.<method>()``
          resolution) contains a cycle; the diagnostic names both paths.
  GTL203  a blocking call while holding a lock: ``time.sleep``, socket
          send/recv/accept/connect, ``subprocess`` wait/communicate,
          ``Future.result()``/``Queue.get()``/``.join()``/``.wait()``
          without a timeout, HTTP requests, ``block_until_ready``.
  GTL204  thread leak: a non-daemon Thread started without a reachable
          ``join``; or a thread started in ``__init__`` before the rest of
          the instance state is assigned (the thread can observe a
          half-constructed object).
  GTL205  ``Condition.wait`` outside a ``while``-predicate loop — a lost or
          spurious wakeup turns into a hang or a premature continue.
  GTL206  check-then-act: one ``with lock:`` block reads a guarded field,
          a later block in the same suite writes it — the decision is stale
          by the time it is applied (the ``try_advance`` bug class).

Suppression: the finding's line must carry ``# gta: disable=<CODE>`` WITH a
reason, e.g. ``# gta: disable=GTL203 — bounded by the socket timeout set at
connect``. A reasonless suppression is itself a finding (GTL100).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from galvatron_tpu.analysis._lintcore import (
    BaseLinter,
    cli_main,
    comment_lines,
    dotted,
    lint_paths_with,
)
from galvatron_tpu.analysis.diagnostics import CODES, Diagnostic

#: code → one-line summary; the single source the DESIGN.md table is pinned
#: to (doc-sync test in tests/test_concurrency.py)
RULES: Dict[str, str] = {
    c: CODES[c][0] for c in sorted(CODES) if c.startswith("GTL2")
}

_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*self\.(\w+)")
_HOLDS_RE = re.compile(r"#\s*holds:\s*self\.(\w+)")

# constructor names that create a lock-like attribute (threading primitives
# and the analysis/locks.py instrumented drop-ins / factories)
_LOCK_CTORS = {
    "Lock", "RLock", "Condition",
    "InstrumentedLock", "InstrumentedRLock", "InstrumentedCondition",
    "make_lock", "make_rlock", "make_condition",
}
_CONDITION_CTORS = {"Condition", "InstrumentedCondition", "make_condition"}

# dotted call heads that block regardless of arguments
_BLOCKING_DOTTED_TAILS = {
    ("time", "sleep"),
    ("urllib", "request", "urlopen"),
    ("urlopen",),
}
_BLOCKING_DOTTED_HEADS = {"requests"}  # requests.get / requests.post / ...
# socket-style method names that block on the peer
_BLOCKING_METHODS = {"send", "sendall", "recv", "recv_into", "accept",
                     "connect", "communicate", "block_until_ready"}
# methods that block only when called WITHOUT a timeout
_TIMEOUT_METHODS = {"result", "get", "join", "wait"}


def _self_attr(node: ast.AST) -> Optional[str]:
    """'_lock' for the AST of ``self._lock``; None otherwise."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _has_timeout(call: ast.Call) -> bool:
    return bool(call.args) or any(
        kw.arg in ("timeout", "block") for kw in call.keywords
    )


def _is_thread_ctor(call: ast.Call) -> bool:
    return dotted(call.func) in (("threading", "Thread"), ("Thread",))


def _daemon_kw(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


class _ClassInfo:
    """Pass-1 harvest of one class: its locks, guarded-field declarations,
    assert-hold annotations, per-method acquisition sets, and same-module
    attribute types (for cross-class lock-order edges)."""

    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.name = node.name
        self.locks: Set[str] = set()
        self.conditions: Set[str] = set()
        self.guarded: Dict[str, str] = {}
        self.decl_lines: Dict[str, int] = {}
        self.attr_types: Dict[str, str] = {}
        self.methods: Dict[str, ast.FunctionDef] = {}
        self.holds: Dict[str, Set[str]] = {}
        self.acquires: Dict[str, Set[str]] = {}


class ConcurrencyLinter(BaseLinter):
    def run(self) -> List[Diagnostic]:
        tree = self.parse()
        if tree is None:
            return []
        self.findings.extend(self.sup.malformed)
        self.comments = comment_lines(self.src)
        self.classes: Dict[str, _ClassInfo] = {}
        # edge (u, v) of lock-node tuples → (line, human description)
        self.graph: Dict[Tuple[Tuple[str, str], Tuple[str, str]], Tuple[int, str]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                info = self._collect_class(node)
                self.classes[info.name] = info
        for info in self.classes.values():
            self._analyze_class(info)
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_thread_leaks(node, cls=None)
        self._check_lock_order_cycles()
        return self.finalize()

    # -- pass 1: harvest ---------------------------------------------------

    def _collect_class(self, node: ast.ClassDef) -> _ClassInfo:
        info = _ClassInfo(node)
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.methods[stmt.name] = stmt
            elif isinstance(stmt, ast.Assign):
                # class map: _GUARDED_BY = {"_field": "_lock", ...}
                for t in stmt.targets:
                    if (isinstance(t, ast.Name) and t.id == "_GUARDED_BY"
                            and isinstance(stmt.value, ast.Dict)):
                        for k, v in zip(stmt.value.keys, stmt.value.values):
                            if (isinstance(k, ast.Constant)
                                    and isinstance(v, ast.Constant)):
                                info.guarded[str(k.value)] = str(v.value)
                                info.decl_lines[str(k.value)] = stmt.lineno
        for fn in info.methods.values():
            # lock attributes: self.X = <anything containing a Lock ctor>
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Assign):
                    attr = None
                    for t in sub.targets:
                        a = _self_attr(t)
                        if a:
                            attr = a
                    if not attr:
                        continue
                    for c in ast.walk(sub.value):
                        if isinstance(c, ast.Call):
                            d = dotted(c.func)
                            if d and d[-1] in _LOCK_CTORS:
                                info.locks.add(attr)
                                if d[-1] in _CONDITION_CTORS:
                                    info.conditions.add(attr)
                            elif (d and d[-1][0:1].isupper()
                                  and d[-1] in self.classes):
                                info.attr_types[attr] = d[-1]
                    # guarded-by comment on the assignment line
                    m = _GUARDED_BY_RE.search(self.comments.get(sub.lineno, ""))
                    if m:
                        info.guarded[attr] = m.group(1)
                        info.decl_lines[attr] = sub.lineno
            # assert-hold annotation on the def line
            m = _HOLDS_RE.search(self.comments.get(fn.lineno, ""))
            if m:
                info.holds[fn.name] = {m.group(1)}
        # a second sweep for attr types: classes defined later in the module
        for fn in info.methods.values():
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
                    attr = None
                    for t in sub.targets:
                        a = _self_attr(t)
                        if a:
                            attr = a
                    d = dotted(sub.value.func)
                    if attr and d and len(d) == 1 and d[0][0:1].isupper():
                        info.attr_types.setdefault(attr, d[0])
        # per-method acquisition sets (for call-through edge resolution)
        for name, fn in info.methods.items():
            acq: Set[str] = set(info.holds.get(name, ()))
            for sub in ast.walk(fn):
                if isinstance(sub, (ast.With, ast.AsyncWith)):
                    for item in sub.items:
                        a = _self_attr(item.context_expr)
                        if a and a in info.locks:
                            acq.add(a)
                if isinstance(sub, ast.Call):
                    f = sub.func
                    if (isinstance(f, ast.Attribute) and f.attr == "acquire"):
                        a = _self_attr(f.value)
                        if a and a in info.locks:
                            acq.add(a)
            info.acquires[name] = acq
        return info

    # -- pass 2: per-class analysis ----------------------------------------

    def _analyze_class(self, info: _ClassInfo) -> None:
        # GTL200: declarations must name a real lock (otherwise the
        # annotation checks nothing and the field is silently unguarded)
        for field_name, lock in sorted(info.guarded.items()):
            if lock not in info.locks:
                self._emit(
                    "GTL200", info.decl_lines.get(field_name, info.node.lineno),
                    f"{info.name}.{field_name} declared guarded by "
                    f"self.{lock}, but the class never creates that lock",
                    hint="create the lock in __init__, or fix the name in "
                    "the guarded-by declaration",
                )
        for field_name in [f for f, lk in info.guarded.items()
                           if lk not in info.locks]:
            del info.guarded[field_name]  # don't cascade into GTL201 noise
        for name, locks in sorted(info.holds.items()):
            for lock in sorted(locks - info.locks):
                self._emit(
                    "GTL200", info.methods[name].lineno,
                    f"{info.name}.{name} asserts it holds self.{lock}, but "
                    "the class never creates that lock",
                )
        for name, fn in info.methods.items():
            held = frozenset(info.holds.get(name, set()) & info.locks)
            self._walk_stmts(info, fn, fn.body, held)
            self._check_cond_wait(info, fn)
            self._check_check_then_act(info, fn)
        self._check_thread_leaks_class(info)

    # ---- lock-region walker (GTL201, GTL202 edges, GTL203) ----------------

    def _walk_stmts(self, info: _ClassInfo, fn, stmts, held: FrozenSet[str]):
        held = frozenset(held)
        for stmt in stmts:
            held = self._walk_stmt(info, fn, stmt, held)

    def _walk_stmt(self, info, fn, stmt, held: FrozenSet[str]) -> FrozenSet[str]:
        """Process one statement under ``held``; returns the held set for the
        NEXT statement in the same suite (bare acquire()/release() calls
        mutate it — ``with`` blocks do not outlive their body)."""
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = held
            for item in stmt.items:
                a = _self_attr(item.context_expr)
                if a and a in info.locks:
                    self._record_acquire(info, fn, inner, a, stmt.lineno)
                    inner = inner | {a}
                else:
                    self._scan_expr(info, fn, item.context_expr, inner)
            self._walk_stmts(info, fn, stmt.body, inner)
            return held
        if isinstance(stmt, (ast.If,)):
            self._scan_expr(info, fn, stmt.test, held)
            self._walk_stmts(info, fn, stmt.body, held)
            self._walk_stmts(info, fn, stmt.orelse, held)
            return held
        if isinstance(stmt, ast.While):
            self._scan_expr(info, fn, stmt.test, held)
            self._walk_stmts(info, fn, stmt.body, held)
            self._walk_stmts(info, fn, stmt.orelse, held)
            return held
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._scan_expr(info, fn, stmt.iter, held)
            self._scan_expr(info, fn, stmt.target, held)
            self._walk_stmts(info, fn, stmt.body, held)
            self._walk_stmts(info, fn, stmt.orelse, held)
            return held
        if isinstance(stmt, ast.Try):
            self._walk_stmts(info, fn, stmt.body, held)
            for h in stmt.handlers:
                self._walk_stmts(info, fn, h.body, held)
            self._walk_stmts(info, fn, stmt.orelse, held)
            self._walk_stmts(info, fn, stmt.finalbody, held)
            return held
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def is a deferred body (thread target, callback): it
            # does NOT inherit the lexical lock scope — analyzed lock-free
            self._walk_stmts(info, fn, stmt.body, frozenset())
            return held
        if isinstance(stmt, ast.ClassDef):
            return held
        # bare acquire()/release() tracked linearly through the suite
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            f = stmt.value.func
            if isinstance(f, ast.Attribute):
                a = _self_attr(f.value)
                if a and a in info.locks:
                    if f.attr == "acquire":
                        self._record_acquire(info, fn, held, a, stmt.lineno)
                        self._scan_expr(info, fn, stmt.value, held)
                        return held | {a}
                    if f.attr == "release":
                        return held - {a}
        for child in ast.iter_child_nodes(stmt):
            self._scan_expr(info, fn, child, held)
        return held

    def _record_acquire(self, info, fn, held: FrozenSet[str], lock: str,
                        line: int) -> None:
        for h in held:
            if h == lock:
                continue
            self._add_edge((info.name, h), (info.name, lock), line,
                           f"{info.name}.{fn.name}")

    def _add_edge(self, u: Tuple[str, str], v: Tuple[str, str], line: int,
                  where: str) -> None:
        if u != v:
            self.graph.setdefault((u, v), (line, where))

    def _scan_expr(self, info, fn, expr, held: FrozenSet[str]) -> None:
        in_init = fn.name == "__init__"
        for node in ast.walk(expr):
            a = _self_attr(node)
            if a and a in info.guarded and not in_init:
                guard = info.guarded[a]
                if guard not in held:
                    ctx = "written" if isinstance(
                        getattr(node, "ctx", None), (ast.Store, ast.Del)
                    ) else "read"
                    self._emit(
                        "GTL201", node.lineno,
                        f"{info.name}.{a} is guarded by self.{guard} but "
                        f"{ctx} here without it (in {fn.name})",
                        hint=f"wrap the access in `with self.{guard}:` (or "
                        "annotate the method `# holds: self."
                        f"{guard}` if every caller already holds it)",
                    )
            if isinstance(node, ast.Call):
                self._scan_call(info, fn, node, held)

    def _scan_call(self, info, fn, call: ast.Call, held: FrozenSet[str]) -> None:
        f = call.func
        # call-through resolution: self.m() and self.attr.m()
        if isinstance(f, ast.Attribute):
            recv_attr = _self_attr(f.value)
            if isinstance(f.value, ast.Name) and f.value.id == "self":
                callee = f.attr
                if callee in info.holds:
                    missing = info.holds[callee] - held
                    for lock in sorted(missing & info.locks):
                        self._emit(
                            "GTL201", call.lineno,
                            f"call to {info.name}.{callee} (asserts it holds "
                            f"self.{lock}) without holding the lock",
                            hint=f"acquire self.{lock} around the call",
                        )
                for acq in sorted(info.acquires.get(callee, ())):
                    self._record_acquire(info, fn, held, acq, call.lineno)
            elif recv_attr and recv_attr in info.attr_types:
                other = self.classes.get(info.attr_types[recv_attr])
                if other is not None:
                    for acq in sorted(other.acquires.get(f.attr, ())):
                        for h in held:
                            self._add_edge(
                                (info.name, h), (other.name, acq),
                                call.lineno, f"{info.name}.{fn.name}")
        if held:
            self._check_blocking(info, fn, call, held)

    def _check_blocking(self, info, fn, call: ast.Call,
                        held: FrozenSet[str]) -> None:
        f = call.func
        d = dotted(f)
        what = None
        if d is not None:
            if d in _BLOCKING_DOTTED_TAILS or (
                    len(d) >= 2 and d[-2:] in _BLOCKING_DOTTED_TAILS):
                what = ".".join(d)
            elif d[0] in _BLOCKING_DOTTED_HEADS and len(d) >= 2:
                what = ".".join(d)
        if what is None and isinstance(f, ast.Attribute):
            if f.attr in _BLOCKING_METHODS:
                what = f".{f.attr}()"
            elif f.attr in _TIMEOUT_METHODS and not _has_timeout(call):
                recv = _self_attr(f.value)
                if f.attr == "wait" and recv is not None and recv in held:
                    # self._cond.wait() releases the condition's own lock
                    # while parked; held-other-locks still block (below)
                    if len(held) == 1:
                        return
                what = f".{f.attr}() without a timeout"
        if what is None:
            return
        locks = ", ".join(f"self.{h}" for h in sorted(held))
        self._emit(
            "GTL203", call.lineno,
            f"blocking call {what} while holding {locks} (in "
            f"{info.name}.{fn.name}): every thread contending the lock "
            "stalls behind this wait",
            hint="move the blocking call outside the lock, or bound it "
            "with a timeout",
        )

    # ---- GTL205: Condition.wait predicate loops ---------------------------

    def _check_cond_wait(self, info: _ClassInfo, fn) -> None:
        parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(fn):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute) and f.attr == "wait"):
                continue
            recv = _self_attr(f.value)
            if recv not in info.conditions:
                continue
            p = parents.get(node)
            in_while = False
            while p is not None and not isinstance(
                    p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                if isinstance(p, ast.While):
                    in_while = True
                    break
                p = parents.get(p)
            if not in_while:
                self._emit(
                    "GTL205", node.lineno,
                    f"self.{recv}.wait() outside a while-predicate loop (in "
                    f"{info.name}.{fn.name}): a spurious or lost wakeup "
                    "continues without the condition being true",
                    hint="wrap it: `while not <predicate>: cond.wait(...)` "
                    "(or use cond.wait_for(predicate))",
                )

    # ---- GTL206: check-then-act across split lock regions -----------------

    def _check_check_then_act(self, info: _ClassInfo, fn) -> None:
        if fn.name == "__init__" or not info.guarded:
            return
        regions: List[Tuple[int, str, Set[str], Set[str], int]] = []

        def collect(stmts, block_id: int):
            nonlocal next_block
            for stmt in stmts:
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    lock = None
                    for item in stmt.items:
                        a = _self_attr(item.context_expr)
                        if a and a in info.locks:
                            lock = a
                    if lock is not None:
                        reads: Set[str] = set()
                        writes: Set[str] = set()
                        for sub in ast.walk(stmt):
                            a = _self_attr(sub)
                            if a and info.guarded.get(a) == lock:
                                if isinstance(sub.ctx, (ast.Store, ast.Del)):
                                    writes.add(a)
                                else:
                                    reads.add(a)
                        regions.append(
                            (block_id, lock, reads, writes, stmt.lineno))
                        continue  # the region is atomic; don't recurse
                for child_block in (
                    getattr(stmt, "body", None), getattr(stmt, "orelse", None),
                    getattr(stmt, "finalbody", None),
                ):
                    if isinstance(child_block, list) and child_block and not (
                        isinstance(stmt, (ast.FunctionDef,
                                          ast.AsyncFunctionDef, ast.ClassDef))
                    ):
                        next_block += 1
                        collect(child_block, next_block)
                if isinstance(stmt, ast.Try):
                    for h in stmt.handlers:
                        next_block += 1
                        collect(h.body, next_block)

        next_block = 0
        collect(fn.body, 0)
        for i, (bi, lock_i, reads_i, writes_i, line_i) in enumerate(regions):
            for bj, lock_j, _reads_j, writes_j, line_j in regions[i + 1:]:
                if bi != bj or lock_i != lock_j:
                    continue
                stale = (reads_i - writes_i) & writes_j
                for field_name in sorted(stale):
                    self._emit(
                        "GTL206", line_j,
                        f"check-then-act on {info.name}.{field_name}: read "
                        f"under self.{lock_i} at line {line_i}, written "
                        f"under a separate acquisition here — the check is "
                        "stale by the time it is applied",
                        hint="hold the lock across check and act, or "
                        "re-validate inside the writing region "
                        "(the try_advance pattern)",
                    )

    # ---- GTL204: thread leaks ---------------------------------------------

    def _check_thread_leaks_class(self, info: _ClassInfo) -> None:
        joined_attrs: Set[str] = set()
        daemon_attrs: Set[str] = set()
        started_attrs: Dict[str, int] = {}
        for fn in info.methods.values():
            for node in ast.walk(fn):
                if isinstance(node, ast.Attribute):
                    a = _self_attr(node.value)
                    if a:
                        if node.attr == "join":
                            joined_attrs.add(a)
                        elif node.attr == "start":
                            started_attrs.setdefault(a, node.lineno)
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if (isinstance(t, ast.Attribute)
                                and t.attr == "daemon"):
                            a = _self_attr(t.value)
                            if a and isinstance(node.value, ast.Constant) \
                                    and node.value.value:
                                daemon_attrs.add(a)
        for fn in info.methods.values():
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)
                        and _is_thread_ctor(node.value)):
                    continue
                attr = None
                for t in node.targets:
                    a = _self_attr(t)
                    if a:
                        attr = a
                if attr is None:
                    continue
                if attr not in started_attrs:
                    continue
                if _daemon_kw(node.value) or attr in daemon_attrs:
                    continue
                if attr not in joined_attrs:
                    self._emit(
                        "GTL204", node.lineno,
                        f"non-daemon thread self.{attr} is started but "
                        f"never joined anywhere in {info.name}",
                        hint="join it in close()/a finally block, or mark "
                        "it daemon=True if it must not block exit",
                    )
            self._check_thread_leaks(fn, cls=info)
        init = info.methods.get("__init__")
        if init is not None:
            self._check_init_start_order(info, init)

    def _check_init_start_order(self, info: _ClassInfo, init) -> None:
        thread_attrs: Set[str] = set()
        start_lines: List[Tuple[int, str]] = []
        for node in ast.walk(init):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                    and _is_thread_ctor(node.value):
                for t in node.targets:
                    a = _self_attr(t)
                    if a:
                        thread_attrs.add(a)
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "start":
                a = _self_attr(node.func.value)
                if a:
                    start_lines.append((node.lineno, a))
        for line, attr in start_lines:
            if attr not in thread_attrs:
                continue
            late = [
                (n.lineno, t.attr)
                for n in ast.walk(init) if isinstance(n, ast.Assign)
                for t in n.targets
                if isinstance(t, ast.Attribute) and _self_attr(t)
                and t.attr != attr and n.lineno > line
            ]
            if late:
                lline, lattr = min(late)
                self._emit(
                    "GTL204", line,
                    f"thread self.{attr} started in {info.name}.__init__ "
                    f"before state init completes (self.{lattr} assigned at "
                    f"line {lline}): the thread can observe a "
                    "half-constructed object",
                    hint="start the thread as the LAST statement of "
                    "__init__ (or from an explicit start method)",
                )

    def _check_thread_leaks(self, fn, cls: Optional[_ClassInfo]) -> None:
        """Local (non-self) threads inside one function: non-daemon +
        started + not joined in the same function ⇒ leak."""
        created: Dict[str, Tuple[int, bool]] = {}  # var → (line, daemon)
        joined: Set[str] = set()
        started: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                    and _is_thread_ctor(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        created[t.id] = (node.lineno, _daemon_kw(node.value))
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (isinstance(t, ast.Attribute) and t.attr == "daemon"
                            and isinstance(t.value, ast.Name)
                            and isinstance(node.value, ast.Constant)
                            and node.value.value):
                        created[t.value.id] = (
                            created.get(t.value.id, (node.lineno, False))[0],
                            True,
                        )
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name):
                if node.func.attr == "join":
                    joined.add(node.func.value.id)
                elif node.func.attr == "start":
                    started.add(node.func.value.id)
        for var, (line, daemon) in sorted(created.items()):
            if daemon or var not in started or var in joined:
                continue
            self._emit(
                "GTL204", line,
                f"non-daemon thread {var!r} started without a reachable "
                f"join in {fn.name}",
                hint="join it (a finally block survives exceptions), or "
                "mark it daemon=True",
            )

    # ---- GTL202: cycle detection over the acquisition graph ---------------

    def _check_lock_order_cycles(self) -> None:
        succ: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
        for (u, v) in self.graph:
            succ.setdefault(u, []).append(v)
        reported: Set[FrozenSet[Tuple[str, str]]] = set()
        for (u, v), (line, where) in sorted(
                self.graph.items(), key=lambda kv: kv[1][0]):
            path = self._find_path(succ, v, u)
            if path is None:
                continue
            nodes = frozenset([u, v] + path)
            if nodes in reported:
                continue
            reported.add(nodes)
            fwd = f"{u[0]}.{u[1]} → {v[0]}.{v[1]} (in {where}, line {line})"
            back_hops = [v] + path
            back_descr = []
            for a, b in zip(back_hops, back_hops[1:]):
                bl, bw = self.graph[(a, b)]
                back_descr.append(
                    f"{a[0]}.{a[1]} → {b[0]}.{b[1]} (in {bw}, line {bl})")
            self._emit(
                "GTL202", line,
                "lock-order inversion: " + fwd + " but also "
                + "; ".join(back_descr),
                hint="pick one global acquisition order and restructure "
                "the second path to follow it (or merge the locks)",
            )

    @staticmethod
    def _find_path(succ, src, dst) -> Optional[List[Tuple[str, str]]]:
        """Shortest path src→dst as the list of nodes AFTER src (BFS);
        None when unreachable. src == dst returns [] only via a real hop."""
        from collections import deque
        prev: Dict[Tuple[str, str], Tuple[str, str]] = {}
        q = deque([src])
        seen = {src}
        while q:
            n = q.popleft()
            for m in succ.get(n, ()):
                if m == dst:
                    path = [m]
                    while n != src:
                        path.append(n)
                        n = prev[n]
                    return list(reversed(path))
                if m not in seen:
                    seen.add(m)
                    prev[m] = n
                    q.append(m)
        return None


def lint_source(src: str, path: str = "<string>") -> Tuple[List[Diagnostic], int]:
    linter = ConcurrencyLinter(src, path)
    findings = linter.run()
    return findings, linter.suppressed


def lint_paths(paths: Sequence[str]) -> Tuple[List[Diagnostic], int]:
    return lint_paths_with(lint_source, paths)


def main(argv: Optional[Sequence[str]] = None) -> int:
    return cli_main(lint_source, __doc__, argv)


if __name__ == "__main__":
    raise SystemExit(main())
