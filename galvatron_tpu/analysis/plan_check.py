"""Static plan checker: reject bad parallelism plans in milliseconds.

The search engine emits a per-layer hybrid-parallelism plan the runtime
blindly materializes — an invalid plan (heads not divisible by tp, a
pp_division that doesn't sum to the layer count, the known XLA SPMD
CHECK-crash cell) otherwise surfaces as a cryptic compiler abort or a silent
memory blowout minutes into startup. Alpa-style plan validation and GSPMD's
sharding-consistency checks show this class of error is statically decidable:
``check_plan`` validates (strategy JSON × ModelConfig × mesh topology)
without compiling anything and returns structured ``GTA…`` diagnostics
(diagnostics.CODES) with field provenance and a one-line fix hint.

Call sites: trainer startup (fail-fast before the mesh is built),
``SearchEngine.save_result`` (self-check — an emitted plan that fails is a
search bug), and the ``check-plan`` CLI subcommand (CI over ``configs/``).

The checks, in order:
 1. JSON schema: unknown keys (GTA001 — typo'd fields silently no-op) and
    per-field decode failures (GTA002).
 2. Structural: world/pp arithmetic (GTA003), degree-product vs mesh
    capacity (GTA004), pp_division shape (GTA005), interleave constraints
    (GTA011), the SPMD crash cell (GTA012), stage-stack seam legality
    (GTA013 — re-derived from parallel/pipeline.position_strategies: a
    (pp, …)-stacked parameter has exactly one sharding, so real layers at
    the same stack position must share one strategy).
 3. Model-dependent: layer count (GTA006), head/vocab/sequence divisibility
    (GTA007/GTA008/GTA010), expert parallelism vs expert count (GTA014).
 4. Batch: chunks and per-layer dp-extent divisibility (GTA009 — mirrors
    the search engine's strict chunk filter, which is the runtime's static
    reshape requirement).
 5. Memory: cost-model feasibility vs a device budget (GTA015).
 6. Abstract sharding: ``jax.eval_shape`` of the parameter init plus each
    layer's ``param_spec`` instantiated as a ``NamedSharding`` on an
    ``AbstractMesh`` of the plan's topology — confirms every annotation is
    consistent (spec axes exist, shard shapes divide) and complete (a
    tp/fsdp-annotated dim the spec could not shard is silently replicated —
    real HBM; GTA016). No device, no compile.

Separately, :func:`check_topology_fingerprint` (GTA017) compares a
checkpoint's recorded topology fingerprint against the live mesh — the
resume-path check the trainer and the elastic supervisor
(`core/elastic.py`) run before training under a stale plan.
"""

from __future__ import annotations

import difflib
import json
from typing import Any, Dict, List, Optional, Tuple

from galvatron_tpu.analysis.diagnostics import (
    ERROR,
    WARN,
    Diagnostic,
    errors,
    format_report,
)
from galvatron_tpu.core.strategy import (
    HybridParallelConfig,
    LayerStrategy,
    balanced_division,
)

# The strategy-JSON schema: codec keys (strategy.to_json_dict) plus the
# extras SearchEngine.save_result and the checked-in configs carry. Anything
# else is a typo'd field that would silently no-op (GTA001).
KNOWN_KEYS = frozenset(
    HybridParallelConfig(pp=1, layer_strategies=[LayerStrategy()]).to_json_dict()
) | {
    # save_result provenance/result keys
    "search_cost_ms",
    "search_throughput_samples_per_s",
    "global_bsz",
    "memory_mb",
    "fallback_bandwidths",
    "search_restrictions",
    "homogeneity_gap_pct",
    # self-describing checked-in configs (check-plan reads these as defaults)
    "model_size",
    "model_config",
    "num_devices",
    "memory_constraint_gb",
}

# the shape fields a search emits alongside model_size so check-plan can
# rebuild the EFFECTIVE model without the caller repeating CLI overrides
# (--num_layers etc.) — the subset of ModelConfig the argument system can
# override, all JSON-serializable scalars (+ the swin_depths tuple)
MODEL_SHAPE_FIELDS = (
    "vocab_size", "hidden_size", "num_layers", "num_heads", "num_kv_heads",
    "ffn_dim", "max_seq_len", "enc_layers", "enc_seq", "image_size",
    "patch_size", "num_classes", "swin_window", "swin_depths",
    "moe_experts", "moe_capacity_factor",
)


def model_shape_dict(cfg) -> Dict[str, Any]:
    """The JSON-embeddable effective shape of ``cfg`` (save_result)."""
    out: Dict[str, Any] = {}
    for k in MODEL_SHAPE_FIELDS:
        v = getattr(cfg, k, None)
        if isinstance(v, tuple):
            v = list(v)
        out[k] = v
    return out


# fields whose ModelConfig default is None (None passes through); everything
# else coerces to int except the float-typed capacity factor
_OPTIONAL_SHAPE_FIELDS = frozenset({"num_kv_heads", "ffn_dim"})


def apply_model_shape(cfg, shape: Dict[str, Any]):
    """Overlay a plan's embedded ``model_config`` shape onto ``cfg``.
    Values are type-coerced per field; garbage entries (``"4x"``, a float
    where an int belongs) are DROPPED, never passed through —
    ``dataclasses.replace`` does not type-check, and a mistyped layer count
    would otherwise crash deep in the checker instead of degrading."""
    import dataclasses

    kw = {}
    for k in MODEL_SHAPE_FIELDS:
        if k not in shape:
            continue
        v = shape[k]
        try:
            if k == "swin_depths":
                v = tuple(int(x) for x in (v or ()))
            elif v is None:
                if k not in _OPTIONAL_SHAPE_FIELDS:
                    continue
            elif k == "moe_capacity_factor":
                v = float(v)
            else:
                v = int(v)
        except (TypeError, ValueError):
            continue
        kw[k] = v
    try:
        return dataclasses.replace(cfg, **kw)
    except (TypeError, ValueError):
        return cfg

# per-layer list keys (length mismatches against tp_sizes_enc are a classic
# hand-edit failure; dp_type_names/cp_impls are name lists, same rule)
_LAYER_LIST_KEYS = (
    "tp_consecutive_flags",
    "dp_types_enc",
    "dp_type_names",
    "checkpoint",
    "sp_flags",
    "cp_sizes_enc",
    "cp_impls",
    "ep_sizes_enc",
    "tp_overlap_flags",
)


class PlanError(ValueError):
    """Raised by fail-fast call sites; carries the structured diagnostics."""

    def __init__(self, diags: List[Diagnostic], context: str = "invalid parallelism plan"):
        self.diagnostics = diags
        super().__init__(f"{context}:\n{format_report(diags)}")


def _is_pow2(n: int) -> bool:
    return n >= 1 and (n & (n - 1)) == 0


def check_plan(
    plan: Any,
    model_config: Any = None,
    world_size: Optional[int] = None,
    *,
    global_bsz: Optional[int] = None,
    memory_budget_mb: Optional[float] = None,
    costs: Any = None,
    source: Optional[str] = None,
    abstract_pass: bool = True,
) -> List[Diagnostic]:
    """Validate a plan; returns diagnostics (empty = clean).

    ``plan`` may be a JSON file path, a decoded strategy dict, or a
    ``HybridParallelConfig``. ``model_config`` (a ``ModelConfig``) enables
    the model-dependent checks; ``world_size`` the topology checks;
    ``global_bsz`` the batch-divisibility checks; ``memory_budget_mb`` (with
    ``costs`` — a ``ProfiledModelCosts``, or analytic costs derived from the
    model config when omitted) the memory-feasibility check. Checks whose
    inputs are missing are skipped, never guessed.
    """
    diags: List[Diagnostic] = []
    d: Optional[Dict[str, Any]] = None
    plan_memory_mb: Optional[float] = None

    if isinstance(plan, str):
        source = source or plan
        try:
            with open(plan) as f:
                d = json.load(f)
        except (OSError, ValueError) as e:
            return [
                Diagnostic(
                    "GTA002",
                    f"cannot read strategy JSON: {e}",
                    hint="the file must be a JSON object in the galvatron_config schema",
                    source=source,
                )
            ]
        if not isinstance(d, dict):
            return [
                Diagnostic(
                    "GTA002",
                    f"strategy JSON must be an object, got {type(d).__name__}",
                    source=source,
                )
            ]
    elif isinstance(plan, dict):
        d = plan

    if d is not None:
        diags += _check_unknown_keys(d, source)
        hp, decode_diags = _decode(d, source)
        diags += decode_diags
        if hp is None:
            return _sorted(diags)
        # self-describing provenance keys fill any input the caller omitted —
        # a library call on an emitted config runs the SAME checks the CLI
        # would, not a silently weaker structural subset. Explicit arguments
        # always win; garbage values degrade to "absent".
        def _as_int(key):
            try:
                return int(d[key]) if d.get(key) else None
            except (TypeError, ValueError):
                return None

        if global_bsz is None:
            global_bsz = _as_int("global_bsz")
        if world_size is None:
            world_size = _as_int("num_devices")
        if memory_budget_mb is None:
            try:
                gb = float(d.get("memory_constraint_gb") or 0.0)
            except (TypeError, ValueError):
                gb = 0.0
            memory_budget_mb = gb * 1024.0 or None
        if model_config is None:
            shape = d.get("model_config")
            base = None
            if d.get("model_size"):
                from galvatron_tpu.models.modeling import PRESETS

                base = PRESETS.get(d["model_size"])
            if isinstance(shape, dict):
                from galvatron_tpu.models.modeling import ModelConfig

                model_config = apply_model_shape(
                    base if base is not None else ModelConfig(), shape
                )
            else:
                model_config = base
        if isinstance(d.get("memory_mb"), (int, float)):
            plan_memory_mb = float(d["memory_mb"])
    else:
        hp = plan

    diags += _check_structural(hp, world_size, source)
    if model_config is not None:
        diags += _check_model(hp, model_config, source)
    if world_size and global_bsz:
        diags += _check_batch(hp, world_size, global_bsz, source)
    if memory_budget_mb:
        diags += _check_budget(
            hp, model_config, world_size, global_bsz, memory_budget_mb,
            costs, plan_memory_mb, source,
        )
    if (
        abstract_pass
        and model_config is not None
        and world_size
        and not errors(diags)  # topology/degree errors make the mesh unbuildable
    ):
        diags += _abstract_sharding_pass(hp, model_config, world_size, source)
    return _sorted(diags)


def _sorted(diags: List[Diagnostic]) -> List[Diagnostic]:
    return sorted(diags, key=lambda x: (x.severity != ERROR, x.code, x.field))


def ensure_valid(
    plan: Any,
    model_config: Any = None,
    world_size: Optional[int] = None,
    *,
    context: str = "invalid parallelism plan",
    verbose: bool = True,
    **kw,
) -> List[Diagnostic]:
    """Fail-fast wrapper: run ``check_plan``, raise ``PlanError`` on any
    error-severity diagnostic, print warnings. Returns the diagnostics."""
    diags = check_plan(plan, model_config, world_size, **kw)
    if errors(diags):
        raise PlanError(diags, context=context)
    if verbose and diags:
        print(format_report(diags))
    return diags


def check_topology_fingerprint(
    fingerprint: Dict[str, Any],
    world_size: Optional[int],
    source: Optional[str] = None,
) -> List[Diagnostic]:
    """GTA017: a checkpoint's recorded topology vs the live mesh.

    ``fingerprint`` is the manifest-meta record the trainer writes with
    every save (``world_size``, ``mesh_shape``, ``plan_hash``,
    ``global_bsz``). A mismatching world size — the preemption/slice-shrink
    signature — is an ERROR: the plan the checkpoint was training under was
    searched for a mesh that no longer exists, and silently resuming it
    would train a different (typically memory-infeasible or throughput-
    pessimal) parallelization than anything the search ever endorsed. The
    elastic supervisor (`cli run-elastic`) treats this diagnostic as its
    re-plan trigger; plain ``train`` refuses with it. A changed *plan hash*
    or mesh axis layout on the SAME device count is deliberately not
    flagged: portable checkpoints reshard across plans by design
    (``mesh_shape`` rides the fingerprint for forensics, not as a gate).
    """
    out: List[Diagnostic] = []
    if not isinstance(fingerprint, dict):
        return out
    try:
        rec_world = int(fingerprint.get("world_size") or 0)
    except (TypeError, ValueError):
        rec_world = 0
    if rec_world and world_size and rec_world != world_size:
        out.append(
            Diagnostic(
                "GTA017",
                f"checkpoint was written on {rec_world} devices but the live "
                f"topology has {world_size}",
                hint="re-search a plan for this mesh and resume the portable "
                "checkpoint under it — `cli run-elastic` does this "
                "automatically (plan cache: <ckpt>/replans/, "
                "configs/strategies/)",
                field="fingerprint.world_size",
                source=source,
            )
        )
    return out


# ---------------------------------------------------------------------------
# 1. JSON schema
# ---------------------------------------------------------------------------


def _check_unknown_keys(d: Dict[str, Any], source) -> List[Diagnostic]:
    out = []
    for k in sorted(set(d) - KNOWN_KEYS):
        close = difflib.get_close_matches(k, sorted(KNOWN_KEYS), n=1)
        hint = (
            f"did you mean {close[0]!r}?"
            if close
            else "remove it, or add it to the schema if it is a new field"
        )
        out.append(
            Diagnostic(
                "GTA001",
                f"unknown key {k!r} — the runtime ignores it silently",
                hint=hint,
                field=k,
                source=source,
            )
        )
    return out


def _decode(
    d: Dict[str, Any], source
) -> Tuple[Optional[HybridParallelConfig], List[Diagnostic]]:
    """Tolerant decode with per-field provenance: list-length mismatches and
    per-layer value errors name the offending key/index instead of
    surfacing as a bare ValueError/IndexError from the codec."""
    out: List[Diagnostic] = []
    tps = d.get("tp_sizes_enc", "")
    try:
        n = len(
            [int(x) for x in (tps.split(",") if isinstance(tps, str) else tps) if x != ""]
        )
    except (ValueError, TypeError):
        n = -1
    if n == 0:
        out.append(
            Diagnostic(
                "GTA002",
                "tp_sizes_enc is missing/empty — a plan with no per-layer "
                "strategies cannot drive the runtime",
                hint="give one tp degree per layer (comma-joined string)",
                field="tp_sizes_enc",
                source=source,
            )
        )
        return None, out
    if n > 0:
        for key in _LAYER_LIST_KEYS:
            v = d.get(key)
            if v in (None, ""):
                continue
            try:
                m = len(v.split(",")) if isinstance(v, str) else len(v)
            except TypeError:  # scalar where a per-layer list belongs
                out.append(
                    Diagnostic(
                        "GTA002",
                        f"{key} must be a comma-joined string or list "
                        f"(one entry per layer), got {v!r}",
                        hint=f"write {key} like tp_sizes_enc: \"1,1,2,2\"",
                        field=key,
                        source=source,
                    )
                )
                continue
            if m != n:
                out.append(
                    Diagnostic(
                        "GTA002",
                        f"{key} has {m} entries but tp_sizes_enc has {n}",
                        hint=f"give {key} one entry per layer (or drop it for the default)",
                        field=key,
                        source=source,
                    )
                )
        if out:
            return None, out
    try:
        hp = HybridParallelConfig.from_json_dict(d)
    except (ValueError, TypeError, KeyError, IndexError, ZeroDivisionError) as e:
        out.append(
            Diagnostic(
                "GTA002",
                f"strategy fails to decode: {e}",
                hint="fix the named field; degrees must be powers of two, "
                "enums one of their documented values",
                source=source,
            )
        )
        return None, out
    return hp, out


# ---------------------------------------------------------------------------
# 2. Structural checks (no model, no device)
# ---------------------------------------------------------------------------


def _check_structural(
    hp: HybridParallelConfig, world: Optional[int], source
) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    L = hp.num_layers
    if hp.chunks < 1:
        out.append(
            Diagnostic(
                "GTA002", f"chunks must be >= 1, got {hp.chunks}",
                hint="set chunks to the micro-batch count (1 = no accumulation)",
                field="chunks", source=source,
            )
        )
    if hp.vpp < 1:
        out.append(
            Diagnostic(
                "GTA002", f"vpp_deg must be >= 1, got {hp.vpp}",
                hint="1 disables the interleaved schedule", field="vpp_deg",
                source=source,
            )
        )

    per_stage = None
    if world:
        if not _is_pow2(world) or not _is_pow2(hp.pp) or world % hp.pp:
            out.append(
                Diagnostic(
                    "GTA003",
                    f"world={world}, pp={hp.pp}: world and pp must be powers "
                    "of two with pp dividing world",
                    hint="pick pp from the powers of two dividing the device count",
                    field="pp_deg",
                    source=source,
                )
            )
        else:
            per_stage = world // hp.pp

    if per_stage is not None:
        for i, s in enumerate(hp.layer_strategies):
            if s.tp * s.cp > per_stage:
                out.append(
                    Diagnostic(
                        "GTA004",
                        f"layer {i}: tp*cp = {s.tp}*{s.cp} exceeds the "
                        f"per-stage extent {per_stage} (= world/pp)",
                        hint=f"lower tp_sizes_enc[{i}]/cp_sizes_enc[{i}] or pp_deg",
                        field=f"tp_sizes_enc[{i}]",
                        source=source,
                    )
                )
            elif s.ep > per_stage // (s.tp * s.cp):
                out.append(
                    Diagnostic(
                        "GTA004",
                        f"layer {i}: ep={s.ep} exceeds the data-parallel "
                        f"extent {per_stage // (s.tp * s.cp)}",
                        hint=f"lower ep_sizes_enc[{i}] to a divisor of the dp extent",
                        field=f"ep_sizes_enc[{i}]",
                        source=source,
                    )
                )
        if hp.vocab_tp > per_stage:
            out.append(
                Diagnostic(
                    "GTA004",
                    f"vocab_tp={hp.vocab_tp} exceeds the per-stage extent {per_stage}",
                    hint="vocab_tp is bounded by world/pp",
                    field="vocab_tp",
                    source=source,
                )
            )

    div = hp.pp_division
    if div is not None:
        encdec = len(div) == 2 * hp.pp and hp.pp > 1
        if len(div) not in (hp.pp, 2 * hp.pp):
            out.append(
                Diagnostic(
                    "GTA005",
                    f"pp_division has {len(div)} entries; pp={hp.pp} needs "
                    f"{hp.pp} (or {2 * hp.pp} for enc-dec)",
                    hint="one entry per pipeline stage (enc ‖ dec for enc-dec)",
                    field="pp_division",
                    source=source,
                )
            )
        elif sum(div) != L:
            out.append(
                Diagnostic(
                    "GTA005",
                    f"pp_division sums to {sum(div)} but the plan has {L} layers",
                    hint="stage layer counts must partition the layer list",
                    field="pp_division",
                    source=source,
                )
            )
        elif any(x < (0 if encdec else 1) for x in div):
            out.append(
                Diagnostic(
                    "GTA005",
                    f"pp_division {div} has an empty stage (single-stack "
                    "pipelines need >= 1 layer per stage)",
                    hint="rebalance pp_division or lower pp_deg",
                    field="pp_division",
                    source=source,
                )
            )
    elif hp.pp > L > 0:
        out.append(
            Diagnostic(
                "GTA005",
                f"pp={hp.pp} exceeds the layer count {L}: some stage holds no layer",
                hint="lower pp_deg to at most the layer count",
                field="pp_deg",
                source=source,
            )
        )

    if hp.vpp > 1:
        if hp.pp <= 1:
            out.append(
                Diagnostic(
                    "GTA011", "vpp>1 (interleaved schedule) requires pp>1",
                    hint="set pp_deg>1 or vpp_deg=1", field="vpp_deg",
                    source=source,
                )
            )
        else:
            if L % (hp.pp * hp.vpp):
                out.append(
                    Diagnostic(
                        "GTA011",
                        f"vpp={hp.vpp} needs the layer count {L} divisible by "
                        f"pp*vpp = {hp.pp * hp.vpp}",
                        hint="pick vpp_deg so layers split evenly into virtual stages",
                        field="vpp_deg",
                        source=source,
                    )
                )
            if hp.chunks % hp.pp:
                out.append(
                    Diagnostic(
                        "GTA011",
                        f"interleaved schedule needs chunks {hp.chunks} "
                        f"divisible by pp={hp.pp}",
                        hint="micro-batches flow in groups of pp",
                        field="chunks",
                        source=source,
                    )
                )
            if div is not None and len(set(div)) > 1:
                out.append(
                    Diagnostic(
                        "GTA011",
                        "vpp>1 requires a uniform pp_division (virtual stages "
                        "are evenly stacked)",
                        hint="drop pp_division or make every stage equal",
                        field="pp_division",
                        source=source,
                    )
                )

    # known XLA SPMD-partitioner CHECK-crash cell (BASELINE.md round 5; the
    # search engine's structural guard — re-derived here as a diagnostic so
    # hand-written plans cannot reach the uncompilable cell either)
    if hp.pp > 1 and hp.pipeline_type == "pipedream_flush" and hp.vocab_tp > 1:
        bad = [i for i, s in enumerate(hp.layer_strategies) if s.tp > 1 and not s.sp]
        if bad:
            out.append(
                Diagnostic(
                    "GTA012",
                    f"pp>1 × pipedream_flush × vocab_tp>1 with tp>1, sp=0 "
                    f"layers {bad[:8]} CHECK-crashes the XLA SPMD partitioner "
                    "(spmd_partitioner_util.cc:506) on real TPU",
                    hint=f"enable sp_flags on those layers, set vocab_tp=1, or "
                    "use the gpipe schedule",
                    field=f"sp_flags[{bad[0]}]",
                    source=source,
                )
            )

    # tp_overlap is a TP-seam rewrite: without TP there is no projection
    # collective to overlap, and the runtime would silently ignore the flag
    # (the dispatch gates on tp > 1) — a plan carrying it lies about itself
    for i, s in enumerate(hp.layer_strategies):
        if s.tp_overlap and s.tp <= 1:
            out.append(
                Diagnostic(
                    "GTA018",
                    f"layer {i}: tp_overlap_flags is set but tp={s.tp} — there "
                    "is no TP projection collective to overlap",
                    hint=f"clear tp_overlap_flags[{i}] or raise tp_sizes_enc[{i}]",
                    field=f"tp_overlap_flags[{i}]",
                    source=source,
                )
            )

    out += _check_seams(hp, source)
    return out


def _check_seams(hp: HybridParallelConfig, source) -> List[Diagnostic]:
    """Stage-stack seam legality at pp>1: a (pp, …)-stacked parameter has
    exactly one sharding, so real layers at the same stack position must
    share one strategy across stages (parallel/pipeline.position_strategies;
    the enc-dec layout applies the rule per sub-stack). Redistribution
    between ADJACENT positions is always legal — XLA inserts the resharding
    collective — so the seam rule is purely the cross-stage one."""
    out: List[Diagnostic] = []
    if hp.pp <= 1 or not hp.layer_strategies:
        return out
    L = hp.num_layers
    div = hp.pp_division
    stacks: List[Tuple[str, List[int], int]] = []  # (label, division, strategy offset)
    if div is not None and len(div) == 2 * hp.pp:
        stacks = [
            ("enc", list(div[: hp.pp]), 0),
            ("dec", list(div[hp.pp:]), sum(div[: hp.pp])),
        ]
    else:
        d = list(div) if div is not None else balanced_division(L, hp.pp)
        if len(d) != hp.pp or sum(d) != L:
            return out  # malformed division already reported (GTA005)
        stacks = [("", d, 0)]
    for label, d, base in stacks:
        if sum(d) == 0:
            continue
        offsets = [base]
        for x in d[:-1]:
            offsets.append(offsets[-1] + x)
        for j in range(max(d)):
            idxs = [offsets[s] + j for s in range(hp.pp) if d[s] > j]
            if any(i >= L for i in idxs):
                return out  # malformed division already reported
            ss = {hp.layer_strategies[i] for i in idxs}
            if len(ss) > 1:
                tag = f"{label} " if label else ""
                out.append(
                    Diagnostic(
                        "GTA013",
                        f"{tag}layers {idxs} share stage position {j} but "
                        f"carry different strategies "
                        f"({sorted(str(s) for s in ss)}) — a stacked "
                        "parameter has one sharding",
                        hint="make per-layer strategies agree at each stage "
                        "position (vary by position, not by stage), or run pp=1",
                        field=f"tp_sizes_enc[{idxs[1]}]",
                        source=source,
                    )
                )
    return out


# ---------------------------------------------------------------------------
# 3. Model-dependent checks
# ---------------------------------------------------------------------------


def _check_model(hp: HybridParallelConfig, cfg, source) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    if hp.num_layers != cfg.total_layers:
        out.append(
            Diagnostic(
                "GTA006",
                f"plan has {hp.num_layers} layer strategies but the model has "
                f"{cfg.total_layers} layers (encoder + decoder)",
                hint="regenerate the plan for this model (or fix --num_layers)",
                field="tp_sizes_enc",
                source=source,
            )
        )
        return out  # per-layer zips below would misalign
    enc = getattr(cfg, "enc_layers", 0)
    for i, s in enumerate(hp.layer_strategies):
        seq = cfg.enc_seq if (enc and i < enc) else cfg.max_seq_len
        if s.tp > 1 and cfg.num_heads % s.tp:
            out.append(
                Diagnostic(
                    "GTA007",
                    f"layer {i}: num_heads={cfg.num_heads} is not divisible "
                    f"by tp={s.tp} — head-sharded attention cannot split",
                    hint=f"lower tp_sizes_enc[{i}] to a divisor of num_heads",
                    field=f"tp_sizes_enc[{i}]",
                    source=source,
                )
            )
        if s.cp > 1 and s.cp_impl == "a2a" and cfg.num_heads % s.cp:
            out.append(
                Diagnostic(
                    "GTA007",
                    f"layer {i}: Ulysses (a2a) cp={s.cp} needs num_heads="
                    f"{cfg.num_heads} divisible by cp",
                    hint=f"use cp_impls[{i}]='ring' or a dividing cp degree",
                    field=f"cp_sizes_enc[{i}]",
                    source=source,
                )
            )
        if s.sp and s.tp > 1 and seq % s.tp:
            out.append(
                Diagnostic(
                    "GTA010",
                    f"layer {i}: sequence parallelism shards seq={seq} over "
                    f"tp={s.tp}, which does not divide it",
                    hint=f"disable sp_flags[{i}] or pad the sequence length",
                    field=f"sp_flags[{i}]",
                    source=source,
                )
            )
        if s.cp > 1 and seq % s.cp:
            out.append(
                Diagnostic(
                    "GTA010",
                    f"layer {i}: context parallelism splits seq={seq} into "
                    f"cp={s.cp} chunks, which does not divide it",
                    hint=f"lower cp_sizes_enc[{i}] to a divisor of the sequence",
                    field=f"cp_sizes_enc[{i}]",
                    source=source,
                )
            )
        if s.ep > 1 and (cfg.moe_experts == 0 or cfg.moe_experts % s.ep):
            out.append(
                Diagnostic(
                    "GTA014",
                    f"layer {i}: ep={s.ep} but the model has "
                    f"{cfg.moe_experts} experts"
                    + ("" if cfg.moe_experts else " (dense MLP)"),
                    hint=f"ep_sizes_enc[{i}] must divide moe_experts (1 for dense)",
                    field=f"ep_sizes_enc[{i}]",
                    source=source,
                )
            )
    if hp.vocab_tp > 1 and cfg.vocab_size % hp.vocab_tp:
        out.append(
            Diagnostic(
                "GTA008",
                f"vocab_size={cfg.vocab_size} is not divisible by "
                f"vocab_tp={hp.vocab_tp}",
                hint="pad the vocab to a multiple of vocab_tp or lower vocab_tp",
                field="vocab_tp",
                source=source,
            )
        )
    return out


# ---------------------------------------------------------------------------
# 4. Batch divisibility
# ---------------------------------------------------------------------------


def _check_batch(
    hp: HybridParallelConfig, world: int, global_bsz: int, source
) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    if (
        not _is_pow2(world) or not _is_pow2(hp.pp) or world % hp.pp
        or hp.chunks < 1
    ):
        return out  # GTA002/GTA003 already cover it; extents are undefined
    if global_bsz % hp.chunks:
        out.append(
            Diagnostic(
                "GTA009",
                f"global batch {global_bsz} is not divisible by chunks={hp.chunks}",
                hint="XLA needs static micro-batch shapes — no ragged last chunk",
                field="chunks",
                source=source,
            )
        )
        return out
    mb = global_bsz // hp.chunks
    per_stage = world // hp.pp
    seen = set()
    for i, s in enumerate(hp.layer_strategies):
        if s.tp * s.cp > per_stage:
            continue  # GTA004 already reported; dp extent undefined
        dp = per_stage // (s.tp * s.cp)
        need = dp * s.cp  # the search engine's strict chunk filter
        if mb % need and (dp, s.cp) not in seen:
            seen.add((dp, s.cp))
            out.append(
                Diagnostic(
                    "GTA009",
                    f"layer {i}: micro-batch {mb} (= {global_bsz}/{hp.chunks} "
                    f"chunks) does not split over dp×cp = {dp}×{s.cp}",
                    hint="adjust global batch or chunks so every micro-batch "
                    "shards evenly over the layer's data axes",
                    field=f"tp_sizes_enc[{i}]",
                    source=source,
                )
            )
    return out


# ---------------------------------------------------------------------------
# 5. Memory feasibility
# ---------------------------------------------------------------------------


def _check_budget(
    hp: HybridParallelConfig,
    cfg,
    world: Optional[int],
    global_bsz: Optional[int],
    budget_mb: float,
    costs,
    plan_memory_mb: Optional[float],
    source,
) -> List[Diagnostic]:
    if plan_memory_mb is not None:
        if plan_memory_mb > budget_mb:
            return [
                Diagnostic(
                    "GTA015",
                    f"the plan's own memory_mb={plan_memory_mb:.0f} exceeds "
                    f"the budget {budget_mb:.0f} MB",
                    hint="re-search under this budget or raise --memory_constraint_gb",
                    field="memory_mb",
                    source=source,
                )
            ]
        return []
    if not (world and global_bsz) or (costs is None and cfg is None):
        return []
    if (
        not _is_pow2(world) or not _is_pow2(hp.pp) or world % hp.pp
        or hp.num_layers < 1 or hp.chunks < 1
        or (hp.vpp > 1 and hp.num_layers % (hp.pp * hp.vpp))
    ):
        return []  # GTA002/GTA003/GTA011 already reported; extents undefined
    try:
        if costs is None:
            from galvatron_tpu.search.theoretical import analytic_model_costs

            costs = analytic_model_costs(cfg, mixed_precision=hp.mixed_precision)
        from galvatron_tpu.search.cost_model import layer_memory_cost, other_memory_cost

        lts = costs.layer_types
        layer_type = lambda i: lts.get(i, lts[0]) if len(lts) > 1 else lts[0]
        # per-device layer set: pp=1 → all; vpp>1 → L/pp (uniform virtual
        # stacking); else the heaviest stage of the division
        L = hp.num_layers
        if hp.pp == 1:
            device_layers = list(range(L))
        elif hp.vpp > 1:
            step = L // (hp.pp * hp.vpp)
            device_layers = [
                v * hp.pp * step + q for v in range(hp.vpp) for q in range(step)
            ]
        else:
            div = hp.pp_division or balanced_division(L, hp.pp)
            if len(div) == 2 * hp.pp:
                div = [div[s] + div[hp.pp + s] for s in range(hp.pp)]
            offs = [0]
            for x in div[:-1]:
                offs.append(offs[-1] + x)
            heavy = max(range(hp.pp), key=lambda s: div[s])
            device_layers = list(range(offs[heavy], offs[heavy] + div[heavy]))
        mem = sum(
            layer_memory_cost(
                layer_type(i), hp.layer_strategies[i], world, hp.pp, global_bsz,
                hp.chunks, stage_idx=0, pipeline_type=hp.pipeline_type,
                mixed_precision=hp.mixed_precision, vpp=hp.vpp,
            ).total_mb
            for i in device_layers
        )
        mem += other_memory_cost(
            costs, world, hp.pp, vocab_tp=hp.vocab_tp,
            embed_dp_type=hp.embed_dp_type, global_bsz=global_bsz,
            chunks=hp.chunks, mixed_precision=hp.mixed_precision,
        )
    except Exception as e:  # a cost-model gap must not mask the other checks
        return [
            Diagnostic(
                "GTA015",
                f"memory feasibility could not be evaluated: {e}",
                hint="pass profiled costs, or skip the budget check",
                severity=WARN,
                source=source,
            )
        ]
    if mem > budget_mb:
        return [
            Diagnostic(
                "GTA015",
                f"cost-model memory estimate {mem:.0f} MB exceeds the "
                f"budget {budget_mb:.0f} MB (estimate excludes pipeline "
                "stash rings — the real footprint is higher)",
                hint="raise the budget, add recompute/zero3, or re-search",
                field="memory_mb",
                source=source,
            )
        ]
    return []


# ---------------------------------------------------------------------------
# 6. Abstract sharding pass (eval_shape + AbstractMesh; no device, no compile)
# ---------------------------------------------------------------------------


def _abstract_sharding_pass(
    hp: HybridParallelConfig, cfg, world: int, source
) -> List[Diagnostic]:
    import jax
    from jax.sharding import NamedSharding

    from galvatron_tpu.models import modeling
    from galvatron_tpu.parallel.mesh import MeshAxes
    from galvatron_tpu.parallel.sharding import param_spec

    if hp.num_layers != cfg.total_layers:
        return []  # GTA006 already reported; trees would misalign
    m = (world // hp.pp).bit_length() - 1
    data_axes = tuple(f"x{i}" for i in range(m))
    try:
        am = jax.sharding.AbstractMesh(
            (("pp", hp.pp),) + tuple((a, 2) for a in data_axes)
        )
    except TypeError:  # older AbstractMesh signature
        am = jax.sharding.AbstractMesh(
            axis_sizes=(hp.pp,) + (2,) * m, axis_names=("pp",) + data_axes
        )
    axes = MeshAxes(pp="pp", data_axes=data_axes)
    abstract = jax.eval_shape(
        lambda k: modeling.init_model_params(k, cfg), jax.random.key(0)
    )
    annots = modeling.model_annotations(cfg)

    msgs: Dict[Tuple[str, str], Tuple[str, str]] = {}  # (code-ish, msg) dedup

    def leaf_paths(tree, prefix=""):
        if isinstance(tree, dict):
            for k, v in tree.items():
                yield from leaf_paths(v, f"{prefix}/{k}")
        elif isinstance(tree, (list, tuple)) and not (
            tree and isinstance(tree[0], (str, type(None)))
        ):
            for i, v in enumerate(tree):
                yield from leaf_paths(v, f"{prefix}[{i}]")
        else:
            yield prefix, tree

    def check_tree(params, annot_tree, s: LayerStrategy, label: str):
        ann = dict(leaf_paths(annot_tree))
        for path, leaf in leaf_paths(params):
            shape = tuple(getattr(leaf, "shape", ()))
            annot = ann.get(path)
            if annot is None or not shape:
                continue
            for for_opt in (False, True) if s.dp_type in ("zero2", "zero3") else (False,):
                try:
                    spec = param_spec(shape, annot, axes, s, for_opt_state=for_opt)
                    NamedSharding(am, spec).shard_shape(shape)
                except ValueError as e:
                    msgs[(label, path, "spec")] = (
                        f"{label}{path}: sharding spec invalid for shape "
                        f"{shape}: {str(e)[:160]}",
                        ERROR,
                    )
                    continue
                for dim, tag, entry in zip(shape, annot, tuple(spec) + (None,) * 8):
                    want = None
                    if tag == "tp" and s.tp > 1:
                        want = ("tp", s.tp)
                    elif tag == "fsdp" and (
                        s.dp_type == "zero3" or (for_opt and s.dp_type == "zero2")
                    ):
                        dp_ax = axes.dp_axes(s.tp, s.tp_consec, s.cp)
                        if dp_ax:
                            want = ("fsdp" if not for_opt else "fsdp opt-state",
                                    2 ** len(dp_ax))
                    if want and entry is None:
                        kind, deg = want
                        msgs[(label, path, tag + str(for_opt))] = (
                            f"{label}{path}: {kind}-annotated dim {dim} is not "
                            f"divisible by the {kind.split()[0]} degree {deg} — "
                            "the parameter is silently replicated (memory "
                            "blowout instead of a shard)",
                            WARN,
                        )

    enc = getattr(cfg, "enc_layers", 0)
    seen_strategies = set()
    for i, s in enumerate(hp.layer_strategies):
        if enc and i < enc:
            params, ann = abstract["enc_layers"][i], annots["enc_layers"][i]
            label = f"enc_layers[{i}]"
        else:
            j = i - enc
            params, ann = abstract["layers"][j], annots["layers"][j]
            label = f"layers[{j}]"
        # homogeneous stacks: one pass per distinct (strategy, layer shape
        # class); vision pyramids vary per layer, so key on the shapes too
        key = (s, tuple(sorted(p for p, _ in leaf_paths(params))),
               cfg.image_size and i)
        if key in seen_strategies:
            continue
        seen_strategies.add(key)
        check_tree(params, ann, s, label)

    vocab_s = LayerStrategy(
        tp=hp.vocab_tp, dp_type=hp.embed_dp_type, sp=hp.vocab_sp
    )
    for top in ("embed", "head", "final_norm", "enc_final_norm"):
        if top in abstract and top in annots:
            check_tree(abstract[top], annots[top], vocab_s, f"{top}/")

    return [
        Diagnostic("GTA016", msg, severity=sev,
                   hint="make the dim a multiple of its shard degree, or "
                   "drop the degree", field=key[1].strip("/"), source=source)
        for key, (msg, sev) in sorted(msgs.items())
    ]
