"""Runtime trace-hygiene guards: assert bounded jit-cache growth.

Generalizes the ``generate._cache_size()`` pins the generation/serving tests
hand-roll: wrap a traffic window in ``recompile_guard`` and any jit-cache
growth beyond the allowance raises ``RecompileError`` naming the function
that recompiled. The serving engine arms one over its steady-state loop when
``GALVATRON_RECOMPILE_GUARD=1`` (debug/CI), so an accidental shape or static
arg leak fails loudly instead of silently compiling per request.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Any, Dict, Sequence


class RecompileError(AssertionError):
    """A jitted function compiled more programs than the guard allows."""


def cache_sizes(fns: Sequence[Any]) -> Dict[str, int]:
    """{name: compiled-program count} for jit-wrapped functions. Same-named
    functions get positional suffixes so a collision cannot hide one
    function's growth behind the other's count."""
    out: Dict[str, int] = {}
    for i, f in enumerate(fns):
        name = getattr(f, "__name__", repr(f))
        if name in out:
            name = f"{name}#{i}"
        out[name] = int(f._cache_size())
    return out


@contextmanager
def recompile_guard(*fns, allowed: int = 0, label: str = ""):
    """Assert the jit caches of ``fns`` grow by at most ``allowed`` entries
    across the block.

    ``allowed`` is the TOTAL growth budget across all guarded functions: 0
    pins "everything is already compiled" (steady-state serving, sweep
    tests); N>0 admits exactly the N programs a warmup is expected to add.
    Growth beyond it raises ``RecompileError`` with the per-function
    breakdown, so the offender is named instead of inferred.
    """
    if not fns:
        raise ValueError("recompile_guard needs at least one jitted function")
    for f in fns:
        if not hasattr(f, "_cache_size"):
            raise TypeError(
                f"{getattr(f, '__name__', f)!r} is not a jit-wrapped function "
                "(no _cache_size); pass the jitted callable itself"
            )
    before = cache_sizes(fns)
    yield
    after = cache_sizes(fns)
    growth = {k: after[k] - before[k] for k in after if after[k] != before[k]}
    total = sum(growth.values())
    if total > allowed:
        tag = f" [{label}]" if label else ""
        detail = ", ".join(f"{k}: {before[k]}→{after[k]}" for k in growth)
        raise RecompileError(
            f"recompile_guard{tag}: jit cache grew by {total} "
            f"(allowed {allowed}) — {detail}. A static argument or shape is "
            "varying per call; make it a traced operand or bucket it."
        )
