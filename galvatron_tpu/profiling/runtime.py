"""Runtime profiler: per-iteration time/memory during real training.

Counterpart of the reference's in-trainer GalvatronProfiler hooks
(reference: galvatron/core/profiler.py:88-191 — CUDA allocator snapshots at
Before-Forward/After-Forward/After-Backward and CUDA-event timing). On TPU:
wall timing around the donated train step with host sync, and
``device.memory_stats()`` for HBM peaks where the backend exposes it.

Also hosts the cost-model fidelity check — predicted vs measured iteration
time — which is the reproducible benchmark the reference itself optimizes
(SURVEY §6; search print: search_engine.py:318-321).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import numpy as np


@dataclass
class RuntimeProfiler:
    """Two timing modes:

    - per-iter (``windowed=False``): host-syncs every iteration (pass the
      loss to ``end_iter``). Exact per-iter times, but the sync serializes
      host dispatch with device compute — measured time includes the host
      round-trip, which on remote-dispatch setups dwarfs real step time.
    - windowed (``windowed=True``, the trainer's default when nothing else
      forces a per-iter sync): dispatch runs free; one sync closes the
      warmup, one closes the window (``finish``), avg = window/iters. This
      measures what async training actually sustains.
    """

    warmup_iters: int = 2
    windowed: bool = False
    iter_times_ms: List[float] = field(default_factory=list)
    _t0: Optional[float] = None
    _iter: int = 0
    _window_t0: Optional[float] = None
    _window_iters: int = 0

    def begin_iter(self):
        self._t0 = time.perf_counter()

    def end_iter(self, sync_value=None):
        """Per-iter mode: pass a device scalar (e.g. the loss) to force
        completion. Windowed mode: syncs only to close the warmup."""
        self._iter += 1
        if self.windowed:
            if self._iter == self.warmup_iters:
                if sync_value is not None:
                    _ = float(sync_value)
                self._window_t0 = time.perf_counter()
            elif self._iter > self.warmup_iters:
                self._window_iters += 1
            return
        if sync_value is not None:
            _ = float(sync_value)
        dt = (time.perf_counter() - self._t0) * 1000.0
        if self._iter > self.warmup_iters:
            self.iter_times_ms.append(dt)

    def finish(self, sync_value=None):
        """Close the measurement window (windowed mode; no-op otherwise)."""
        if not self.windowed or self._window_t0 is None or self._window_iters == 0:
            return
        if sync_value is not None:
            _ = float(sync_value)
        avg = (time.perf_counter() - self._window_t0) * 1000.0 / self._window_iters
        self.iter_times_ms = [avg] * self._window_iters
        self._window_t0 = None

    @property
    def avg_iter_ms(self) -> float:
        return float(np.mean(self.iter_times_ms)) if self.iter_times_ms else float("nan")

    def throughput(self, global_bsz: int, seq_len: int) -> Dict[str, float]:
        ms = self.avg_iter_ms
        return {
            "iter_ms": ms,
            "samples_per_s": global_bsz / (ms / 1000.0),
            "tokens_per_s": global_bsz * seq_len / (ms / 1000.0),
        }

    def memory_stats(self) -> Dict[str, float]:
        """Per-device HBM stats in MB where the backend reports them
        (utils/memory_utils.py:3-14 equivalent)."""
        out: Dict[str, float] = {}
        for d in jax.devices():
            try:
                st = d.memory_stats()
            except Exception:
                st = None
            if st:
                out[f"dev{d.id}_bytes_in_use_mb"] = st.get("bytes_in_use", 0) / 1e6
                out[f"dev{d.id}_peak_bytes_mb"] = st.get("peak_bytes_in_use", 0) / 1e6
        return out

    def report(self, global_bsz: int, seq_len: int, predicted_ms: Optional[float] = None,
               step_stats=None):
        tp = self.throughput(global_bsz, seq_len)
        lines = [
            f"avg iter: {tp['iter_ms']:.2f} ms | "
            f"{tp['samples_per_s']:.2f} samples/s | {tp['tokens_per_s']:.0f} tokens/s"
        ]
        if step_stats is not None and np.isfinite(tp["iter_ms"]):
            # achieved model TFLOP/s + MFU/HFU from the analytic FLOPs
            # estimate (obs.stepstats.StepStats) — utilization next to
            # throughput in every training summary
            st = step_stats.per_iter(tp["iter_ms"], global_bsz)
            if st["tflops_per_device"] is not None:
                line = f"achieved {st['tflops_per_device']:.2f} TFLOP/s/device"
                if st["mfu"] is not None:
                    line += f" | MFU {st['mfu'] * 100:.1f}% | HFU {st['hfu'] * 100:.1f}%"
                lines.append(line)
        if predicted_ms is not None and np.isfinite(tp["iter_ms"]):
            fidelity = predicted_ms / tp["iter_ms"]
            lines.append(
                f"cost-model fidelity: predicted {predicted_ms:.4g} ms / measured "
                f"{tp['iter_ms']:.4g} ms = {fidelity:.3f}"
            )
        mem = self.memory_stats()
        if mem:
            peak = max((v for k, v in mem.items() if "peak" in k), default=0.0)
            lines.append(f"peak HBM: {peak:.0f} MB")
        return "\n".join(lines)
