"""Hardware profiler: ICI/DCN collective bandwidth + overlap coefficient.

The nccl-tests replacement (reference: galvatron/core/profiler.py:404-532
shells out to all_reduce_perf/sendrecv_perf and parses 'Avg bus bandwidth';
profile_overlap.py:14-160 measures the compute/comm overlap slowdown with
CUDA streams). Here each measurement is a jitted collective over a subset of
mesh axes, timed with forced host synchronization:

- allreduce bus bandwidth per (group size, consec-vs-strided axis layout) —
  consec = minor mesh axes (ICI-adjacent), strided = major axes, the layout
  dimension the search engine prices (hardware_configs/allreduce_bandwidth_*);
- p2p bandwidth per pipeline degree via ppermute along the pp axis;
- overlap coefficient: slowdown of a matmul+allreduce program vs
  max(matmul, allreduce) alone.

Writes the ProfiledHardware JSON schema consumed by the search engine.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from galvatron_tpu.parallel.mesh import MeshAxes, build_mesh
from galvatron_tpu.search.cost_model import ProfiledHardware


def _default_chain() -> int:
    """Measurement window length: on accelerators, chain dependent in-jit
    applications and sync once per window — per-call host syncs would fold
    the host round-trip into every sample (it dwarfs a single collective on
    remote-dispatch setups and pads small-message bandwidths everywhere).
    On the CPU simulation the numbers are synthetic anyway and the scanned
    program compiles much slower, so stay with per-call timing."""
    return 1 if jax.default_backend() == "cpu" else 8


def _time_fn(fn, *args, iters: int = 5, chain: Optional[int] = None) -> float:
    """Median wall time (s) per application of ``fn`` (shape-preserving —
    every profiled collective here is), timed in windows of ``chain``
    dependent applications (see _default_chain)."""
    chain = chain or _default_chain()
    single = len(args) == 1
    if chain == 1:
        run = fn if getattr(fn, "lower", None) else jax.jit(fn)
    else:

        @jax.jit
        def run(*a):
            def body(c, _):
                o = fn(*c)
                return ((o,) if single else tuple(o)), None

            c, _ = jax.lax.scan(body, tuple(a), None, length=chain)
            return c

    out = run(*args)  # compile + warm
    jax.block_until_ready(out)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = run(*args)
        jax.block_until_ready(out)
        times.append((time.perf_counter() - t0) / chain)
    return float(np.median(times))


def profile_allreduce(
    mesh: Mesh,
    axes: MeshAxes,
    msg_mb: float = 64.0,
    dtype=jnp.bfloat16,
) -> Dict[str, float]:
    """Bus bandwidth (GB/s) for every (group size, consec) the mesh supports."""
    out: Dict[str, float] = {}
    m = len(axes.data_axes)
    nbytes = np.dtype(dtype).itemsize
    n_elem = int(msg_mb * 1e6 / nbytes)
    x = jnp.ones((n_elem,), dtype)
    for k in range(1, m + 1):
        size = 2**k
        for consec in (True, False):
            if k == m and not consec:
                continue  # full-extent group has one layout
            group = axes.tp_axes(size, consec)

            @jax.jit
            def ar(x, group=group):
                y = jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, P(axes.data_axes))
                )
                return jax.shard_map(
                    lambda v: jax.lax.psum(v, group),
                    mesh=mesh,
                    in_specs=P(axes.data_axes),
                    out_specs=P(axes.data_axes),
                    axis_names=set(axes.data_axes) | {axes.pp},
                    check_vma=False,
                )(y)

            t = _time_fn(ar, x)
            bus_gb = 2.0 * (size - 1) / size * (n_elem * nbytes / size) / t / 1e9
            out[f"{size}_{int(consec)}"] = round(bus_gb * size, 3)
    return out


def profile_p2p(
    world: int, msg_mb: float = 64.0, dtype=jnp.bfloat16
) -> Dict[int, float]:
    """ppermute bandwidth (GB/s) per pipeline degree (reference p2p profile:
    core/profiler.py:429-441)."""
    out: Dict[int, float] = {}
    nbytes = np.dtype(dtype).itemsize
    pp = 2
    while pp <= world:
        mesh, axes = build_mesh(pp=pp)
        n_per = int(msg_mb * 1e6 / nbytes)  # message size per stage boundary
        x = jnp.ones((pp, n_per), dtype)
        perm = [(i, (i + 1) % pp) for i in range(pp)]

        @jax.jit
        def send(x, mesh=mesh, perm=perm):
            return jax.shard_map(
                lambda v: jax.lax.ppermute(v, "pp", perm),
                mesh=mesh,
                in_specs=P("pp"),
                out_specs=P("pp"),
                axis_names={"pp"},
                check_vma=False,
            )(x)

        t = _time_fn(send, x)
        out[pp] = round((n_per * nbytes) / t / 1e9, 3)
        pp *= 2
    return out


def profile_overlap_coe(mesh: Mesh, axes: MeshAxes, size_mb: float = 64.0) -> float:
    """Compute/communication overlap slowdown (reference:
    profile_hardware/profile_overlap.py — gemm + allreduce on parallel CUDA
    streams; here: one XLA program containing both, which XLA overlaps)."""
    n = 2048
    a = jnp.ones((n, n), jnp.bfloat16)
    nbytes = int(size_mb * 1e6 / 2)
    x = jnp.ones((nbytes,), jnp.bfloat16)
    group = axes.data_axes

    def mm(a):
        for _ in range(8):
            a = a @ a * 0.01
        return a

    sm = lambda f: jax.shard_map(
        f, mesh=mesh, in_specs=P(axes.data_axes), out_specs=P(axes.data_axes),
        axis_names=set(axes.data_axes) | {axes.pp}, check_vma=False,
    )
    ar = lambda v: jax.lax.psum(v, group)
    t_mm = _time_fn(jax.jit(mm), a)
    t_ar = _time_fn(jax.jit(sm(ar)), x)
    t_both = _time_fn(jax.jit(lambda a, x: (mm(a), sm(ar)(x))), a, x)
    coe = t_both / max(t_mm, t_ar)
    return round(max(1.0, float(coe)), 4)


def profile_hardware(
    msg_mb: float = 64.0, out_path: Optional[str] = None
) -> ProfiledHardware:
    """Full sweep (reference entry: profile_hardware/profile_hardware.py)."""
    mesh, axes = build_mesh(pp=1)
    world = mesh.devices.size
    hw = ProfiledHardware(
        allreduce_bw=profile_allreduce(mesh, axes, msg_mb),
        p2p_bw=profile_p2p(world, msg_mb) if world > 1 else {},
        overlap_coe=profile_overlap_coe(mesh, axes, msg_mb) if world > 1 else 1.1,
    )
    if out_path:
        from galvatron_tpu.utils.config_utils import save_profiled_hardware

        save_profiled_hardware(hw, out_path)
    return hw
