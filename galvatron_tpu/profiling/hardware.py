"""Hardware profiler: ICI/DCN collective bandwidth + overlap coefficient.

The nccl-tests replacement (reference: galvatron/core/profiler.py:404-532
shells out to all_reduce_perf/sendrecv_perf and parses 'Avg bus bandwidth';
profile_overlap.py:14-160 measures the compute/comm overlap slowdown with
CUDA streams). Here each measurement is a jitted collective over a subset of
mesh axes, timed with forced host synchronization:

- allreduce bus bandwidth per (group size, consec-vs-strided axis layout) —
  consec = minor mesh axes (ICI-adjacent), strided = major axes, the layout
  dimension the search engine prices (hardware_configs/allreduce_bandwidth_*);
- p2p bandwidth per pipeline degree via ppermute along the pp axis;
- overlap coefficient: slowdown of a matmul+allreduce program vs
  max(matmul, allreduce) alone.

Writes the ProfiledHardware JSON schema consumed by the search engine.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence

import jax

from galvatron_tpu import compat
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from galvatron_tpu.parallel.mesh import MeshAxes, build_mesh
from galvatron_tpu.search.cost_model import ProfiledHardware


def _default_chain() -> int:
    """Measurement window length: on accelerators, chain dependent in-jit
    applications and sync once per window — per-call host syncs would fold
    the host round-trip into every sample (it dwarfs a single collective on
    remote-dispatch setups and pads small-message bandwidths everywhere).
    On the CPU simulation the numbers are synthetic anyway and the scanned
    program compiles much slower, so stay with per-call timing."""
    return 1 if jax.default_backend() == "cpu" else 8


def _time_fn(fn, *args, iters: int = 5, chain: Optional[int] = None) -> float:
    """Median wall time (s) per application of ``fn`` (shape-preserving —
    every profiled collective here is), timed in windows of ``chain``
    dependent applications (see _default_chain)."""
    chain = chain or _default_chain()
    single = len(args) == 1
    if chain == 1:
        run = fn if getattr(fn, "lower", None) else jax.jit(fn)
    else:

        @jax.jit
        def run(*a):
            def body(c, _):
                o = fn(*c)
                return ((o,) if single else tuple(o)), None

            c, _ = jax.lax.scan(body, tuple(a), None, length=chain)
            return c

    out = run(*args)  # compile + warm
    jax.block_until_ready(out)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = run(*args)
        jax.block_until_ready(out)
        times.append((time.perf_counter() - t0) / chain)
    return float(np.median(times))


def profile_allreduce(
    mesh: Mesh,
    axes: MeshAxes,
    msg_mb: float = 64.0,
    dtype=jnp.bfloat16,
) -> Dict[str, float]:
    """Bus bandwidth (GB/s) for every (group size, consec) the mesh supports."""
    out: Dict[str, float] = {}
    m = len(axes.data_axes)
    nbytes = np.dtype(dtype).itemsize
    n_elem = int(msg_mb * 1e6 / nbytes)
    x = jnp.ones((n_elem,), dtype)
    for k in range(1, m + 1):
        size = 2**k
        for consec in (True, False):
            if k == m and not consec:
                continue  # full-extent group has one layout
            group = axes.tp_axes(size, consec)

            @jax.jit
            def ar(x, group=group):
                y = jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, P(axes.data_axes))
                )
                return compat.shard_map(
                    lambda v: jax.lax.psum(v, group),
                    mesh=mesh,
                    in_specs=P(axes.data_axes),
                    out_specs=P(axes.data_axes),
                    axis_names=set(axes.data_axes) | {axes.pp},
                    check_vma=False,
                )(y)

            t = _time_fn(ar, x)
            bus_gb = 2.0 * (size - 1) / size * (n_elem * nbytes / size) / t / 1e9
            out[f"{size}_{int(consec)}"] = round(bus_gb * size, 3)
    return out


def profile_p2p(
    world: int, msg_mb: float = 64.0, dtype=jnp.bfloat16, num_slices: int = 1
) -> Dict[int, float]:
    """ppermute bandwidth (GB/s) per pipeline degree (reference p2p profile:
    core/profiler.py:429-441). With ``num_slices``>1 the mesh is built
    slice-major exactly as the runtime's (mesh.build_mesh), so the pp ring
    crosses the DCN boundary and the measured bandwidth IS the DCN number
    the search will price pp>1 with."""
    out: Dict[int, float] = {}
    nbytes = np.dtype(dtype).itemsize
    pp = 2
    while pp <= world:
        mesh, axes = build_mesh(pp=pp, num_slices=num_slices if num_slices > 1 else None)
        n_per = int(msg_mb * 1e6 / nbytes)  # message size per stage boundary
        x = jnp.ones((pp, n_per), dtype)
        perm = [(i, (i + 1) % pp) for i in range(pp)]

        @jax.jit
        def send(x, mesh=mesh, perm=perm):
            return compat.shard_map(
                lambda v: jax.lax.ppermute(v, "pp", perm),
                mesh=mesh,
                in_specs=P("pp"),
                out_specs=P("pp"),
                axis_names={"pp"},
                check_vma=False,
            )(x)

        t = _time_fn(send, x)
        out[pp] = round((n_per * nbytes) / t / 1e9, 3)
        pp *= 2
    return out


def profile_overlap_coe(mesh: Mesh, axes: MeshAxes, size_mb: float = 64.0) -> float:
    """Compute/communication overlap slowdown (reference:
    profile_hardware/profile_overlap.py — gemm + allreduce on parallel CUDA
    streams; here: one XLA program containing both, which XLA overlaps)."""
    n = 2048
    a = jnp.ones((n, n), jnp.bfloat16)
    nbytes = int(size_mb * 1e6 / 2)
    x = jnp.ones((nbytes,), jnp.bfloat16)
    group = axes.data_axes

    def mm(a):
        for _ in range(8):
            a = a @ a * 0.01
        return a

    sm = lambda f: compat.shard_map(
        f, mesh=mesh, in_specs=P(axes.data_axes), out_specs=P(axes.data_axes),
        axis_names=set(axes.data_axes) | {axes.pp}, check_vma=False,
    )
    ar = lambda v: jax.lax.psum(v, group)
    t_mm = _time_fn(jax.jit(mm), a)
    t_ar = _time_fn(jax.jit(sm(ar)), x)
    t_both = _time_fn(jax.jit(lambda a, x: (mm(a), sm(ar)(x))), a, x)
    coe = t_both / max(t_mm, t_ar)
    return round(max(1.0, float(coe)), 4)


def dcn_crossing_keys(world: int, num_slices: int) -> list:
    """Which "size_consec" allreduce keys cross the slice/DCN boundary under
    the runtime's slice-major mesh ordering (mesh.build_mesh): the top
    log2(num_slices) data axes span slices, so every STRIDED (major-axis)
    group crosses, and a CONSECUTIVE group crosses once it outgrows one
    slice's extent. (The pp axis is outermost, so with num_slices>1 every
    p2p degree crosses too.)"""
    if num_slices <= 1 or world <= 1:
        return []
    m = int(np.log2(world))
    s = int(np.log2(num_slices))
    out = []
    for k in range(1, m + 1):
        if k < m:
            out.append(f"{2 ** k}_0")  # strided: always on the major axes
        if k > m - s:
            out.append(f"{2 ** k}_1")  # consecutive group wider than a slice
    return out


def profile_hardware(
    msg_mb: float = 64.0, out_path: Optional[str] = None,
    num_slices: Optional[int] = None,
) -> ProfiledHardware:
    """Full sweep (reference entry: profile_hardware/profile_hardware.py).

    Pods/multislice recipe (docs/HARDWARE_PROFILING.md): run this once on
    the target topology (``profile-hardware --num_slices N`` on a DCN-
    connected deployment; N is auto-detected from device slice indices when
    omitted). The profiler builds the SAME slice-major mesh the runtime
    uses, so the (size, consec) groups it times are exactly the axis
    combinations the search prices — strided/major groups and the pp ring
    ride the DCN and their measured entries carry the DCN bandwidth, keyed
    identically. ``dcn_keys`` records which entries crossed the boundary."""
    mesh, axes = build_mesh(pp=1, num_slices=num_slices)
    world = mesh.devices.size
    eff_slices = num_slices or len(
        {getattr(d, "slice_index", 0) for d in np.asarray(mesh.devices).ravel()}
    )
    # mirror build_mesh's inference guard: it only slice-major-orders clean
    # binary factors, so anything else must be treated as one slice here too
    # (a 3-slice detection would otherwise crash the p2p mesh build and
    # mislabel dcn_keys)
    if eff_slices < 1 or eff_slices & (eff_slices - 1) or world % eff_slices:
        eff_slices = 1
    hw = ProfiledHardware(
        allreduce_bw=profile_allreduce(mesh, axes, msg_mb),
        p2p_bw=profile_p2p(world, msg_mb, num_slices=eff_slices) if world > 1 else {},
        overlap_coe=profile_overlap_coe(mesh, axes, msg_mb) if world > 1 else 1.1,
        dcn_keys=dcn_crossing_keys(world, eff_slices),
    )
    if out_path:
        from galvatron_tpu.utils.config_utils import save_profiled_hardware

        save_profiled_hardware(hw, out_path)
    return hw
