"""Model profiler: per-layer compute time and memory.

Counterpart of the reference's launcher-based profiler (reference:
galvatron/core/profiler.py:194-401 — launches train_dist.py across
{layernum_min,max} x tp x ckpt via os.system, then differences the results).
Here no process launches are needed: the layernum-difference method runs two
jitted training programs in-process, and memory comes from XLA's compile-time
memory analysis instead of allocator snapshots:

  per-layer fwd ms  = (iter(L2) - iter(L1)) / (L2 - L1) / bsz / 3
  per-layer act MB  = (temp_bytes(L2) - temp_bytes(L1)) / (L2 - L1) / bsz

(the /3 removes the bwd≈2x fwd share from a full training step; the reference
separates fwd via profile hooks, core/profiler.py:133-171).

Parameter sizes are computed analytically from the model config.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from galvatron_tpu.core.optim import AdamConfig
from galvatron_tpu.core.strategy import HybridParallelConfig
from galvatron_tpu.models import modeling
from galvatron_tpu.models.modeling import ModelConfig
from galvatron_tpu.search.cost_model import ProfiledLayerType, ProfiledModelCosts

# Single source of truth for analytic parameter counts (MoE-aware: the
# expert-stack branch matters — a dense count here once made
# moe_expert_param_fraction exceed 1 and turned dense_mb negative in the
# cost model).
from galvatron_tpu.search import theoretical
from galvatron_tpu.search.theoretical import layer_param_count, other_param_count


def measure_strategy_ms(
    cfg: ModelConfig,
    hp,
    bsz: int,
    seq: Optional[int] = None,
    iters: int = 4,
    devices=None,
) -> float:
    """Measured wall time per training iteration of ``hp`` through the hybrid
    runtime's own train_step (windowed: one sync to open, one to close). The
    reference profiles through its real trainer the same way (train_dist.py
    --profile, core/profiler.py:194-240); a separate plain-model loop was
    ~10% slower than what training actually runs (no buffer donation,
    different loss plumbing), which skewed predicted-vs-measured fidelity."""
    from galvatron_tpu.parallel.hybrid import build_runtime
    from galvatron_tpu.parallel.mesh import build_mesh

    mesh, axes = build_mesh(pp=hp.pp, devices=devices)
    if cfg.objective == "cls":
        rt = build_runtime(
            cfg, hp, mesh=mesh, axes=axes, adam=AdamConfig(lr=1e-4),
            global_batch_size=bsz,
        )
        batch = jnp.zeros((bsz, cfg.sample_len + 1), jnp.int32)
    else:
        rt = build_runtime(
            cfg, hp, mesh=mesh, axes=axes, adam=AdamConfig(lr=1e-4),
            global_batch_size=bsz, seq_len=seq,
        )
        # match build_runtime's own seq resolution (seq_len or cfg.sample_len
        # — enc-dec samples are enc_seq + max_seq_len tokens)
        batch = jnp.zeros((bsz, (seq or cfg.sample_len) + 1), jnp.int32)
    batch = rt.shard_batch(batch)
    state = rt.init_state(jax.random.key(0))
    state, loss = rt.train_step(state, batch)  # compile
    _ = float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = rt.train_step(state, batch)
    _ = float(loss)  # host sync
    return (time.perf_counter() - t0) / iters * 1000.0


def _iter_time_ms(cfg: ModelConfig, bsz: int, seq: int, iters: int = 4) -> float:
    """Single-device trivial-strategy iteration time — the per-layer profile
    basis (tp=1, ddp, chunks=1 on ONE device)."""
    from galvatron_tpu.core.strategy import HybridParallelConfig, LayerStrategy

    hp = HybridParallelConfig(
        pp=1,
        layer_strategies=[LayerStrategy()] * cfg.total_layers,  # enc + dec
        chunks=1,
        vocab_tp=1,
        mixed_precision=_mp_of(cfg),
    )
    return measure_strategy_ms(cfg, hp, bsz, seq, iters, devices=jax.devices()[:1])


def _mp_of(cfg: ModelConfig) -> str:
    return {jnp.bfloat16: "bf16", jnp.float16: "fp16"}.get(cfg.dtype, "fp32")


def profile_vocab_costs(
    cfg: ModelConfig,
    bsz: int,
    vocab_tps: Optional[Sequence[int]] = None,
    seq: Optional[int] = None,
    iters: int = 4,
) -> Tuple[dict, dict, str]:
    """MEASURED embed+head+loss cost per vocab_tp as (slope ms/sample,
    const ms/iteration, precision): a ZERO-LAYER model on exactly vocab_tp
    devices (dp=1) runs precisely the computation the cost model's "other"
    terms price — embedding gather, head GEMM, (vocab-parallel) cross-
    entropy with its per-token scalar reductions, and the optimizer update
    on those params — with the runtime's real shardings. Two batch sizes
    (bsz, 2·bsz) separate the batch-linear share from the batch-independent
    one (the Adam update on V·h params dominates a zero-layer step at small
    batch, so a single-point linear scaling would grossly over-price large
    per-device batches). dp=1 keeps the dp-extent comm OUT of the
    measurement; other_time_cost adds it analytically for the search
    topology. Skips vocab_tp degrees the host cannot supply (>1 on a single
    chip) — those fall back to the analytic terms."""
    seq = seq or cfg.max_seq_len
    mp = _mp_of(cfg)
    if cfg.enc_layers > 0 or cfg.objective == "cls":
        return {}, {}, mp  # enc-dec / cls 'other' paths keep the analytic model
    if vocab_tps is None:
        # every power of two this host can supply — the search consumes the
        # fit only when ALL degrees its sweep can select are covered
        # (SearchEngine._vocab_use_measured), so a capped default would
        # silently disable measured pricing on larger hosts
        n = len(jax.devices())
        vocab_tps = [2 ** k for k in range(int(np.log2(n)) + 1)]
    cfg0 = cfg.replace(num_layers=0)
    slope, const = {}, {}
    for vt in vocab_tps:
        if vt > len(jax.devices()) or cfg.vocab_size % vt:
            continue
        hp = HybridParallelConfig(
            pp=1, layer_strategies=[], chunks=1, vocab_tp=vt, mixed_precision=mp
        )
        try:
            t1 = measure_strategy_ms(cfg0, hp, bsz, seq, iters, devices=jax.devices()[:vt])
            t2 = measure_strategy_ms(
                cfg0, hp, 2 * bsz, seq, iters, devices=jax.devices()[:vt]
            )
        except Exception:
            continue  # leave this degree to the analytic fallback
        m = max(0.0, (t2 - t1) / bsz)  # ms per sample-per-device
        slope[int(vt)] = float(m)
        const[int(vt)] = float(max(0.0, t1 - m * bsz))
    return slope, const, mp


def _temp_bytes(cfg: ModelConfig, bsz: int, seq: int) -> Optional[int]:
    """XLA-reported temporary (activation) bytes for a jitted loss+grad."""

    def f(params, batch):
        return jax.value_and_grad(lambda p: modeling.lm_loss(p, batch, cfg))(params)

    params = jax.eval_shape(lambda k: modeling.init_model_params(k, cfg), jax.random.key(0))
    batch = jax.ShapeDtypeStruct((bsz, seq + 1), jnp.int32)
    try:
        compiled = jax.jit(f).lower(params, batch).compile()
        ma = compiled.memory_analysis()
        if ma is None:
            return None
        return int(ma.temp_size_in_bytes)
    except Exception:
        return None


def _temp_bytes_tp(cfg: ModelConfig, bsz: int, seq: int, tp: int) -> Optional[int]:
    """Per-device XLA temp bytes of the ACTUAL tp-sharded train step,
    compiled (not run) on ``tp`` local devices — the measured counterpart of
    the reference's per-tp memory profiling sweep (core/profiler.py:194-240
    launches real runs across tp degrees). Needs >= tp devices (a pod host);
    single-chip hosts fall back to the analytic ~1/tp curve."""
    if tp > len(jax.devices()):
        return None
    try:
        from galvatron_tpu.core.checkpoint import abstract_state_of
        from galvatron_tpu.core.strategy import HybridParallelConfig, LayerStrategy
        from galvatron_tpu.parallel.hybrid import build_runtime
        from galvatron_tpu.parallel.mesh import build_mesh

        mesh, axes = build_mesh(pp=1, devices=jax.devices()[:tp])
        hp = HybridParallelConfig(
            pp=1,
            layer_strategies=[LayerStrategy(tp=tp)] * cfg.num_layers,
            chunks=1, vocab_tp=tp, mixed_precision=_mp_of(cfg),
        )
        rt = build_runtime(
            cfg, hp, mesh=mesh, axes=axes, adam=AdamConfig(lr=1e-4),
            global_batch_size=bsz, seq_len=seq,
        )
        abstract = abstract_state_of(rt)
        batch = jax.ShapeDtypeStruct(
            (bsz, seq + 1), jnp.int32, sharding=rt.batch_sharding
        )
        ma = rt.train_step.lower(abstract, batch).compile().memory_analysis()
        if ma is None:
            return None
        return int(ma.temp_size_in_bytes)
    except Exception:
        return None


def _act_fallback_mb(cfg: ModelConfig, S: int) -> float:
    """Analytic activation fallback (bf16): residuals + attn + mlp
    intermediates per layer per sample."""
    return S * cfg.hidden_size * (10 + 4 * cfg.ffn / cfg.hidden_size) * 2 / 1e6


def _maybe_save(costs: ProfiledModelCosts, out_prefix: Optional[str]) -> None:
    if out_prefix:
        from galvatron_tpu.utils.config_utils import save_profiled_model

        save_profiled_model(
            costs, f"{out_prefix}_computation.json", f"{out_prefix}_memory.json"
        )


# adaptive-layernum cap: profiling AT the target layer count removes the
# extrapolation bias of the (2,4) basis — the marginal per-layer iteration
# cost is NOT constant in L (measured h=2048/bsz 8, one process:
# 37.7 ms/layer at 2→4, 35.9 at 4→8, 48.1 at 8→12 as the model approaches
# HBM pressure) — but compile+measure time grows with L, so the upper point
# is capped; beyond it the difference method extrapolates as before.
_PROFILE_MAX_LAYERS = 12


def _default_layernums(total_layers: int) -> Tuple[int, int]:
    l2 = max(2, min(total_layers, _PROFILE_MAX_LAYERS))
    return max(1, l2 // 2), l2


def profile_model(
    cfg: ModelConfig,
    bsz: int = 8,
    seq: Optional[int] = None,
    layernums: Optional[Tuple[int, int]] = None,
    measure_time: bool = True,
    out_prefix: Optional[str] = None,
) -> ProfiledModelCosts:
    """Difference-method profile (reference: process_profiled_data,
    core/profiler.py:243-401). Writes reference-schema JSONs if out_prefix.

    ``layernums=None`` picks (total_layers//2, total_layers) capped at
    ``_PROFILE_MAX_LAYERS`` so models that fit are profiled at their real
    depth; an OOM at the adaptively-chosen sizes falls back to halved layer
    counts (explicitly-passed layernums are never silently overridden).
    Enc-dec profiles keep the fixed (2, 4) three-point basis of
    ``_profile_encdec_model`` — the adaptive depth scaling does not apply
    there yet."""
    if cfg.enc_layers > 0:
        if seq is not None:
            raise ValueError(
                "seq does not apply to enc-dec profiles (two sequence "
                "lengths); set cfg.enc_seq / cfg.max_seq_len instead"
            )
        return _profile_encdec_model(
            cfg, bsz, layernums or (2, 4), measure_time, out_prefix
        )
    if cfg.swin_depths:
        if seq is not None or layernums is not None:
            raise ValueError(
                "seq/layernums do not apply to swin profiles (the pyramid "
                "fixes per-section resolutions; the sweep varies section "
                "depths)"
            )
        return _profile_swin_model(cfg, bsz, measure_time, out_prefix)
    seq = seq or cfg.max_seq_len
    adaptive = layernums is None
    l1, l2 = layernums or _default_layernums(cfg.total_layers)

    if measure_time:
        t_cache: dict = {}

        def t_of(ln: int) -> float:
            if ln not in t_cache:
                t_cache[ln] = _iter_time_ms(cfg.replace(num_layers=ln), bsz, seq)
            return t_cache[ln]

        while True:
            try:
                t1, t2 = t_of(l1), t_of(l2)
                break
            except Exception as e:
                # only the ADAPTIVE basis falls back, and only on memory
                # exhaustion — explicit layernums and deterministic errors
                # surface to the caller
                oom = any(
                    m in str(e)
                    for m in ("RESOURCE_EXHAUSTED", "Ran out of memory", "OOM")
                )
                if not adaptive or not oom or l2 <= 2:
                    raise
                l2 = max(2, l2 // 2)
                l1 = max(1, l2 // 2)
        fwd_ms = max(1e-4, (t2 - t1) / (l2 - l1) / bsz / 3.0)
        other_ms = max(0.0, (t1 - fwd_ms * 3.0 * bsz * l1) / bsz / 3.0)
    else:
        fwd_ms, other_ms = 1.0, 0.1

    # MoE: MEASURE the expert-time fraction (the ep-shardable share of the
    # switch layer's time) by a two-point fit of the marginal layer time
    # over the expert FFN width — t(f) = a + b*f, expert share = b*f/(a+b*f);
    # the intercept a is the routing/sinkhorn/dispatch overhead that does
    # NOT shard by ep (the param-fraction proxy overstated the ep win by
    # pricing it as shardable). Measured on-chip (experiments/ab_moe.py).
    moe_tfrac = None
    if measure_time and cfg.moe_experts > 0:
        try:
            f1 = cfg.ffn
            f2 = max(256, (f1 // 4 + 255) // 256 * 256)
            if f2 < f1:
                cfg_small = cfg.replace(ffn_dim=f2)
                ts1 = _iter_time_ms(cfg_small.replace(num_layers=l1), bsz, seq)
                ts2 = _iter_time_ms(cfg_small.replace(num_layers=l2), bsz, seq)
                fwd_small = max(1e-4, (ts2 - ts1) / (l2 - l1) / bsz / 3.0)
                b_slope = (fwd_ms - fwd_small) / (f1 - f2)
                # a degenerate fit (non-positive slope: noise or a too-small
                # model) must fall back to the param proxy, not price EP as
                # zero benefit
                if b_slope > 0:
                    moe_tfrac = float(min(b_slope * f1 / fwd_ms, 0.99))
        except Exception:
            moe_tfrac = None  # leave the param-fraction proxy in place
    cfg1, cfg2 = cfg.replace(num_layers=l1), cfg.replace(num_layers=l2)

    b1, b2 = _temp_bytes(cfg1, bsz, seq), _temp_bytes(cfg2, bsz, seq)
    if b1 is not None and b2 is not None and b2 > b1:
        act_mb = (b2 - b1) / (l2 - l1) / bsz / 1e6
    else:
        act_mb = _act_fallback_mb(cfg, seq)
    # per-tp curve: measured (compiled tp-sharded step) where the host has
    # enough devices, ~1/tp analytic otherwise (reference sweeps real runs
    # across tp degrees, core/profiler.py:194-240)
    act_curve = {1: float(act_mb)}
    for t in (2, 4, 8):
        if cfg.hidden_size % t or cfg.num_heads % t or bsz % t:
            act_curve[t] = float(act_mb / t)
            continue
        bt1 = _temp_bytes_tp(cfg1, bsz, seq, t)
        bt2 = _temp_bytes_tp(cfg2, bsz, seq, t)
        if bt1 is not None and bt2 is not None and bt2 > bt1:
            act_curve[t] = (bt2 - bt1) / (l2 - l1) / bsz / 1e6
        else:
            act_curve[t] = float(act_mb / t)

    boundary_mb = seq * cfg.hidden_size * 2 / 1e6  # one bf16 (S, H) tensor
    p_layer = layer_param_count(cfg)
    p_mb = p_layer * 4 / 1e6
    # MoE: expert-stack param fraction + dispatch/combine a2a volume — the
    # analytic structural facts the measured profile cannot see (search/
    # theoretical.py uses the same derivation)
    moe_frac, moe_a2a = 0.0, 0.0
    if cfg.moe_experts > 0:
        moe_frac = theoretical.moe_expert_params(cfg) / p_layer
        moe_a2a = 2.0 * seq * cfg.hidden_size * 2 / 1e6  # bf16, each way
    costs = ProfiledModelCosts(
        layer_types={
            0: ProfiledLayerType(
                fwd_ms_per_sample=float(fwd_ms),
                parameter_mb=float(p_mb),
                activation_mb_per_sample=act_curve,
                boundary_activation_mb_per_sample=float(boundary_mb),
                moe_expert_param_fraction=float(moe_frac),
                moe_a2a_mb_per_sample=float(moe_a2a),
                moe_expert_time_fraction=moe_tfrac,
            )
        },
        other_param_mb=float(other_param_count(cfg) * 4 / 1e6),
        other_act_mb_per_sample=float(seq * cfg.vocab_size * 4 / 1e6),  # logits fp32
        other_fwd_ms_per_sample=float(other_ms),
        hidden_size=cfg.hidden_size,
    )
    # vocab measurement costs ~2 jitted builds per feasible vocab_tp — worth
    # it on real hardware, but on the CPU simulation the numbers are
    # synthetic (like the hardware profiler's) and the compiles are slow, so
    # it defaults off there; call profile_vocab_costs directly to force
    if measure_time and jax.default_backend() != "cpu":
        vslope, vconst, vmp = profile_vocab_costs(cfg, bsz, seq=seq)
        costs.measured_vocab_slope_ms = vslope
        costs.measured_vocab_const_ms = vconst
        costs.measured_vocab_mp = vmp
    _maybe_save(costs, out_prefix)
    return costs


def _profile_swin_model(
    cfg: ModelConfig,
    bsz: int,
    measure_time: bool,
    out_prefix: Optional[str],
) -> ProfiledModelCosts:
    """Swin difference profile: one layer type PER SECTION from a (K+1)-point
    sweep — a base pyramid of one PAIR (two layers) per section, then +1
    pair in section k holding the others fixed (the reference's
    multi-layer-type layernum launch matrix, core/profiler.py:194-240, for
    its legacy swin branch; pairs because Swin alternates plain/shifted
    windows per position parity, models/modeling.py::swin_layer)."""
    from galvatron_tpu.models.modeling import swin_geometry, vision_layer_cfg

    K = len(cfg.swin_depths)

    def with_depths(d):
        return cfg.replace(num_layers=sum(d), swin_depths=tuple(d))

    cfg_base = with_depths((2,) * K)
    var_cfgs = [
        with_depths(tuple(4 if j == k else 2 for j in range(K))) for k in range(K)
    ]
    if measure_time:
        t_base = _iter_time_ms(cfg_base, bsz, None)
        t_var = [_iter_time_ms(c, bsz, None) for c in var_cfgs]
        sec_ms = [max(1e-4, (t - t_base) / 2.0 / bsz / 3.0) for t in t_var]
        other_ms = max(0.0, (t_base - sum(sec_ms) * 2.0 * 3.0 * bsz) / bsz / 3.0)
    else:
        sec_ms = [1.0] * K
        other_ms = 0.1

    S = cfg.sample_len
    b_base = _temp_bytes(cfg_base, bsz, S)
    b_var = [_temp_bytes(c, bsz, S) for c in var_cfgs]
    base_idx = np.cumsum([0] + list(cfg.swin_depths[:-1]))

    sec_lts = []
    for k in range(K):
        h, w, c_k, _ = swin_geometry(cfg, k)
        S_k = h * w
        lcfg = vision_layer_cfg(cfg, int(base_idx[k]))
        if b_base is not None and b_var[k] is not None and b_var[k] > b_base:
            act_mb = (b_var[k] - b_base) / 2.0 / bsz / 1e6
        else:
            act_mb = _act_fallback_mb(lcfg, S_k)
        curve = {t: float(act_mb / t) for t in (1, 2, 4, 8) if c_k % t == 0}
        sec_lts.append(
            ProfiledLayerType(
                fwd_ms_per_sample=float(sec_ms[k]),
                parameter_mb=float(layer_param_count(lcfg) * 4 / 1e6),
                activation_mb_per_sample=curve,
                boundary_activation_mb_per_sample=float(S_k * c_k * 2 / 1e6),
            )
        )
    layer_types = {}
    i = 0
    for k, d in enumerate(cfg.swin_depths):
        for _ in range(d):
            layer_types[i] = sec_lts[k]
            i += 1
    costs = ProfiledModelCosts(
        layer_types=layer_types,
        other_param_mb=float(other_param_count(cfg) * 4 / 1e6),
        # patch-embedding output dominates "other" activations (cls logits
        # are tiny) — same structural term the analytic path uses
        other_act_mb_per_sample=float(cfg.n_patches * cfg.hidden_size * 2 / 1e6),
        other_fwd_ms_per_sample=float(other_ms),
        hidden_size=cfg.hidden_size,
    )
    _maybe_save(costs, out_prefix)
    return costs


def _profile_encdec_model(
    cfg: ModelConfig,
    bsz: int,
    layernums: Tuple[int, int],
    measure_time: bool,
    out_prefix: Optional[str],
) -> ProfiledModelCosts:
    """Enc-dec difference profile: TWO layer types from a three-point sweep —
    vary the decoder count at fixed encoder count, then the encoder count at
    fixed decoder count (the reference's multi-layer-type layernum lists,
    core/profiler.py:194-240 launch matrix)."""
    l1, l2 = layernums
    S_e, S_d = cfg.enc_seq, cfg.max_seq_len
    c11 = cfg.replace(num_layers=l1, enc_layers=l1)
    c12 = cfg.replace(num_layers=l2, enc_layers=l1)
    c21 = cfg.replace(num_layers=l1, enc_layers=l2)

    if measure_time:
        t11 = _iter_time_ms(c11, bsz, None)
        t12 = _iter_time_ms(c12, bsz, None)
        t21 = _iter_time_ms(c21, bsz, None)
        dec_ms = max(1e-4, (t12 - t11) / (l2 - l1) / bsz / 3.0)
        enc_ms = max(1e-4, (t21 - t11) / (l2 - l1) / bsz / 3.0)
        other_ms = max(
            0.0, (t11 - (enc_ms + dec_ms) * 3.0 * bsz * l1) / bsz / 3.0
        )
    else:
        enc_ms, dec_ms, other_ms = 1.0, 1.5, 0.1

    S = cfg.sample_len
    b11, b12, b21 = (
        _temp_bytes(c11, bsz, S), _temp_bytes(c12, bsz, S), _temp_bytes(c21, bsz, S)
    )

    def act_of(b_hi, b_lo, S_type):
        if b_hi is not None and b_lo is not None and b_hi > b_lo:
            return (b_hi - b_lo) / (l2 - l1) / bsz / 1e6
        return _act_fallback_mb(cfg, S_type)

    enc_act = act_of(b21, b11, S_e)
    dec_act = act_of(b12, b11, S_d)

    def make_lt(fwd, act_mb, S_type, cross):
        p_mb = layer_param_count(cfg, cross=cross) * 4 / 1e6
        curve = {
            t: float(act_mb / t)
            for t in (1, 2, 4, 8)
            if cfg.hidden_size % t == 0
        }
        return ProfiledLayerType(
            fwd_ms_per_sample=float(fwd),
            parameter_mb=float(p_mb),
            activation_mb_per_sample=curve,
            boundary_activation_mb_per_sample=float(S_type * cfg.hidden_size * 2 / 1e6),
        )

    enc_lt = make_lt(enc_ms, enc_act, S_e, cross=False)
    dec_lt = make_lt(dec_ms, dec_act, S_d, cross=True)
    layer_types = {i: enc_lt for i in range(cfg.enc_layers)}
    layer_types.update({cfg.enc_layers + i: dec_lt for i in range(cfg.num_layers)})
    costs = ProfiledModelCosts(
        layer_types=layer_types,
        other_param_mb=float(other_param_count(cfg) * 4 / 1e6),
        other_act_mb_per_sample=float(S_d * cfg.vocab_size * 4 / 1e6),
        other_fwd_ms_per_sample=float(other_ms),
        hidden_size=cfg.hidden_size,
    )
    _maybe_save(costs, out_prefix)
    return costs
