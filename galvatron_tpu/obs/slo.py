"""Declarative SLOs with multi-window burn-rate alerting.

Counters and gauges say what the system *did*; an SLO says whether that is
*acceptable* — and the standard way to alert on one (Google SRE workbook
ch. 5) is the error-budget burn rate over TWO sliding windows: a fast
window that reacts in seconds and a slow window that filters blips. A rule
breaches only when BOTH windows burn faster than their thresholds, so a
single slow request never pages but a sustained regression pages quickly.

The rule table (:data:`RULES`) is the declarative contract — DESIGN.md's
SLO table renders these exact rules and a doc-sync test keeps them matched:

- ``availability``        — fraction of finished requests that did not fail
- ``ttft_p99``            — time-to-first-token against a latency target
- ``deadline_miss_ratio`` — requests that expired (queue or mid-decode)
- ``step_time_drift``     — trainer iteration time vs the cost model's
  predicted step time; the drift gauge this rule watches is the explicit
  hook ROADMAP item 2's online re-planner will consume.

Breaches fan out everywhere the system already looks: a tracer instant
(``slo_breach``), a versioned ``slo_events.jsonl`` record, per-rule
/metrics gauges (``prom.render_slo``), and a ``degraded_reasons`` list on
/healthz so a load balancer's probe sees degradation without scraping.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from galvatron_tpu.obs.tracing import tracer
from galvatron_tpu.utils.metrics import SCHEMA_VERSION, MetricsLogger

#: schema name stamped on every slo_events.jsonl record (with the shared
#: ``schema`` version from utils.metrics — readers tolerate newer fields)
EVENT_NAME = "slo_breach"


@dataclass(frozen=True)
class SLORule:
    """One declarative SLO. ``kind`` picks the evaluation:

    - ``ratio``: observations are good/bad booleans; ``target`` is the
      minimum good fraction (error budget = 1 - target).
    - ``latency``: observations are seconds; a sample is "bad" when it
      exceeds ``threshold_s``; ``target`` is the fraction that must be fast
      (e.g. 0.99 for a p99 objective).
    - ``drift``: observations are signed ratios ((observed-predicted)/
      predicted); a sample is "bad" when it exceeds ``threshold_s`` (here a
      unitless ratio, e.g. 0.25 = 25% slower than predicted).
    """

    name: str
    kind: str                      # "ratio" | "latency" | "drift"
    target: float                  # required good fraction (error budget = 1-target)
    description: str
    threshold_s: Optional[float] = None   # latency/drift cut line
    window_fast_s: float = 30.0
    window_slow_s: float = 300.0
    burn_fast: float = 14.0        # fast-window burn-rate threshold
    burn_slow: float = 6.0         # slow-window burn-rate threshold


#: the fleet's rule table. Thresholds/windows are defaults — serve flags
#: (--slo_*) override targets and window lengths at wiring time
#: (``build_serving_rules`` / ``build_training_rules``).
RULES: Tuple[SLORule, ...] = (
    SLORule(
        name="availability",
        kind="ratio",
        target=0.99,
        description="fraction of finished requests that did not fail "
                    "(completed / (completed + failed))",
    ),
    SLORule(
        name="ttft_p99",
        kind="latency",
        target=0.99,
        threshold_s=2.0,
        description="99% of requests must see their first token within "
                    "the TTFT target",
    ),
    SLORule(
        name="deadline_miss_ratio",
        kind="ratio",
        target=0.95,
        description="fraction of finished requests that did not expire "
                    "against their end-to-end deadline",
    ),
    SLORule(
        name="step_time_drift",
        kind="drift",
        target=0.95,
        threshold_s=0.25,
        description="trainer step time vs the cost model's predicted step "
                    "time; sustained drift is the online re-plan trigger "
                    "(ROADMAP item 2)",
    ),
)


def get_rule(name: str) -> SLORule:
    for r in RULES:
        if r.name == name:
            return r
    raise KeyError(f"unknown SLO rule {name!r}")


class _RuleState:
    """Sliding-window good/bad sample store for one rule. Samples are
    ``(ts, bad)`` pairs in a deque; eviction happens lazily at read time
    against the SLOW window (the fast window is a suffix of it)."""

    def __init__(self, rule: SLORule):
        self.rule = rule
        self.samples: deque = deque()
        self.breached = False
        self.breaches_total = 0
        self.last_value: Optional[float] = None
        self.last_breach_ts: Optional[float] = None

    def observe(self, bad: bool, now: float, value: Optional[float] = None) -> None:
        self.samples.append((now, bad))
        if value is not None:
            self.last_value = float(value)
        self._evict(now)

    def _evict(self, now: float) -> None:
        horizon = now - self.rule.window_slow_s
        while self.samples and self.samples[0][0] < horizon:
            self.samples.popleft()

    def burn_rates(self, now: float) -> Tuple[Optional[float], Optional[float]]:
        """(fast, slow) burn rates: (bad fraction in window) / error budget.
        None when the window holds no samples — no data is not a breach."""
        self._evict(now)
        budget = max(1e-9, 1.0 - self.rule.target)
        fast_cut = now - self.rule.window_fast_s
        n_fast = bad_fast = n_slow = bad_slow = 0
        for ts, bad in self.samples:
            n_slow += 1
            bad_slow += bad
            if ts >= fast_cut:
                n_fast += 1
                bad_fast += bad
        fast = (bad_fast / n_fast) / budget if n_fast else None
        slow = (bad_slow / n_slow) / budget if n_slow else None
        return fast, slow


class SLOEngine:
    """Evaluates a rule set over sliding windows; fans breaches out to the
    tracer, a versioned JSONL event log, /metrics gauges and /healthz.

    Thread-safe: serving handler threads and the engine loop both observe.
    Evaluation happens inline on observe (amortized O(window)) — the rule
    windows are small and the serving path already pays a counter lock.
    """

    def __init__(self, rules: Optional[List[SLORule]] = None,
                 events_path: Optional[str] = None,
                 source: str = "server"):
        self.rules = list(rules if rules is not None else RULES)
        self._state = {r.name: _RuleState(r) for r in self.rules}
        self._events = MetricsLogger(events_path)
        self.source = source
        self._lock = threading.Lock()

    # -- observation entry points ------------------------------------------

    def observe(self, rule_name: str, bad: bool,
                value: Optional[float] = None,
                now: Optional[float] = None, **info) -> bool:
        """Record one sample for ``rule_name``; returns True when this
        observation RAISED a breach (edge, not level — the event fires once
        per excursion; the ``slo_breached`` gauge holds the level)."""
        st = self._state.get(rule_name)
        if st is None:
            return False
        now = time.time() if now is None else now
        with self._lock:
            st.observe(bad, now, value)
            fast, slow = st.burn_rates(now)
            r = st.rule
            breaching = (
                fast is not None and slow is not None
                and fast >= r.burn_fast and slow >= r.burn_slow
            )
            raised = breaching and not st.breached
            cleared = st.breached and not breaching
            st.breached = breaching
            if raised:
                st.breaches_total += 1
                st.last_breach_ts = now
        if raised:
            tracer.instant(
                "slo_breach", rule=rule_name, burn_fast=round(fast, 3),
                burn_slow=round(slow, 3), value=value, source=self.source,
                **info,
            )
            self._events.log(
                EVENT_NAME, schema=SCHEMA_VERSION, rule=rule_name,
                source=self.source, burn_fast=round(fast, 4),
                burn_slow=round(slow, 4), value=value,
                target=st.rule.target, threshold_s=st.rule.threshold_s,
                **info,
            )
        elif cleared:
            tracer.instant("slo_clear", rule=rule_name, source=self.source)
            self._events.log(
                "slo_clear", schema=SCHEMA_VERSION, rule=rule_name,
                source=self.source,
            )
        return raised

    def observe_latency(self, rule_name: str, seconds: float, **info) -> bool:
        r = get_rule_from(self.rules, rule_name)
        if r is None:
            return False
        return self.observe(
            rule_name, bad=seconds > float(r.threshold_s or float("inf")),
            value=seconds, **info,
        )

    def observe_drift(self, rule_name: str, drift: float, **info) -> bool:
        r = get_rule_from(self.rules, rule_name)
        if r is None:
            return False
        return self.observe(
            rule_name, bad=drift > float(r.threshold_s or float("inf")),
            value=drift, **info,
        )

    # -- readouts -----------------------------------------------------------

    def gauges(self) -> List[Dict[str, Any]]:
        """One row per rule for ``prom.render_slo``."""
        now = time.time()
        rows = []
        with self._lock:
            for name, st in self._state.items():
                fast, slow = st.burn_rates(now)
                rows.append({
                    "rule": name,
                    "burn_fast": fast,
                    "burn_slow": slow,
                    "breached": st.breached,
                    "breaches_total": st.breaches_total,
                    "value": st.last_value,
                })
        return rows

    def degraded_reasons(self) -> List[str]:
        """Rules currently in breach, as ``"slo:<rule>"`` strings — the
        /healthz ``degraded_reasons`` list (empty = healthy)."""
        with self._lock:
            return [f"slo:{n}" for n, st in self._state.items() if st.breached]

    def close(self) -> None:
        self._events.close()


def get_rule_from(rules, name: str) -> Optional[SLORule]:
    for r in rules:
        if r.name == name:
            return r
    return None


def _override(rule: SLORule, **kw) -> SLORule:
    from dataclasses import replace

    return replace(rule, **{k: v for k, v in kw.items() if v is not None})


def build_serving_rules(ns) -> List[SLORule]:
    """The serving rule set with ``--slo_*`` flag overrides applied. The
    trainer-only drift rule is excluded — a replica never observes it."""
    fast = getattr(ns, "slo_window_fast_s", None)
    slow = getattr(ns, "slo_window_slow_s", None)
    return [
        _override(get_rule("availability"),
                  target=getattr(ns, "slo_availability", None),
                  window_fast_s=fast, window_slow_s=slow),
        _override(get_rule("ttft_p99"),
                  threshold_s=getattr(ns, "slo_ttft_p99_s", None),
                  window_fast_s=fast, window_slow_s=slow),
        _override(get_rule("deadline_miss_ratio"),
                  target=getattr(ns, "slo_deadline_miss_ratio", None),
                  window_fast_s=fast, window_slow_s=slow),
    ]


def build_training_rules(ns) -> List[SLORule]:
    """The trainer's drift rule with the ``--slo_step_time_drift`` override
    (the flag doubles as the arm switch: 0/absent keeps the table default —
    the trainer only builds this set at all when the flag is truthy)."""
    thr = getattr(ns, "slo_step_time_drift", None)
    return [
        _override(get_rule("step_time_drift"),
                  threshold_s=float(thr) if thr else None),
    ]
