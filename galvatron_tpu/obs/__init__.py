"""Unified observability layer (DESIGN.md § Observability).

Four coordinated pieces:

- :mod:`~galvatron_tpu.obs.tracing` — nestable host-side spans with Chrome
  trace-event / Perfetto export; the module-level ``tracer`` singleton is
  the process-wide timeline every subsystem records into.
- :mod:`~galvatron_tpu.obs.stepstats` — model-FLOPs accounting → tokens/s,
  achieved TFLOP/s, MFU/HFU per training iteration.
- :mod:`~galvatron_tpu.obs.prom` — Prometheus text exposition for
  ``GET /metrics`` and the ``--obs_port`` trainer sidecar.
- :mod:`~galvatron_tpu.obs.flight` — crash flight recorder (the tracer ring
  dumped from the trainer's crash path) and bounded ``jax.profiler`` windows
  (``--profile_steps``, ``POST /profile``).
"""

from galvatron_tpu.obs.tracing import Tracer, chrome_trace, emit_tick_spans, tracer
from galvatron_tpu.obs.stepstats import StepStats, peak_flops_per_device
from galvatron_tpu.obs.flight import ProfilerWindow, dump_flight, parse_profile_steps
from galvatron_tpu.obs.prom import ObsServer, PromText, TrainStats, server_metrics_text

__all__ = [
    "Tracer", "chrome_trace", "emit_tick_spans", "tracer",
    "StepStats", "peak_flops_per_device",
    "ProfilerWindow", "dump_flight", "parse_profile_steps",
    "ObsServer", "PromText", "TrainStats", "server_metrics_text",
]
